"""On-chip verification driver for the fusion PR (see .claude/skills/verify).

Runs on the REAL TPU (JAX_PLATFORMS=axon preset): TPC-H q1 (the tentpole
scan->filter->project->dense-agg chain) and q3 (join chain + top-k) at
sf=0.05 (~300k lineitem rows, multiple tiles), fused vs unfused vs pandas,
plus the empty-input edge through a fused scalar aggregation, printing the
on-chip kernel-dispatch counts both ways.
"""

import time

import numpy as np
import jax

print("devices:", jax.devices())

from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpch
from cockroach_tpu.flow import dispatch
from cockroach_tpu.utils import settings

cat = tpch.gen_tpch(sf=0.05, seed=11)
print("lineitem rows:", cat.get("lineitem").num_rows)


def run(qname, fusion, **kw):
    settings.set("sql.distsql.fusion.enabled", fusion)
    try:
        rel = Q.QUERIES[qname](cat, **kw)
        t0 = time.perf_counter()
        rel.run()  # warm (compile)
        warm = time.perf_counter() - t0
        d0 = dispatch.total()
        t0 = time.perf_counter()
        res = rel.run()
        dt = time.perf_counter() - t0
        print(f"{qname} fusion={fusion}: warm {warm:.1f}s, steady "
              f"{dt*1e3:.0f}ms, dispatches {dispatch.total() - d0}")
        return res
    finally:
        settings.reset("sql.distsql.fusion.enabled")


def identical(a, b, tag):
    assert set(a) == set(b), tag
    for k in a:
        x, y = np.asarray(a[k]), np.asarray(b[k])
        assert x.shape == y.shape, (tag, k, x.shape, y.shape)
        if x.dtype == object or y.dtype == object:
            assert list(x) == list(y), (tag, k)
        else:
            np.testing.assert_array_equal(x, y, err_msg=f"{tag}:{k}")
    print(f"{tag}: fused == unfused ({len(next(iter(a.values())))} rows)")


# 1) q1: the acceptance-criterion chain, fused vs unfused bit-identical
f1, u1 = run("q1", True), run("q1", False)
identical(f1, u1, "q1")

# 2) q1 vs pandas oracle
li = tpch.to_pandas(cat, "lineitem")
cutoff = tpch.d("1998-12-01") - 90
f = li[li.l_shipdate <= cutoff].copy()
want = (
    f.groupby(["l_returnflag", "l_linestatus"])
    .agg(sum_qty=("l_quantity", "sum"), count_order=("l_quantity", "size"))
    .reset_index()
    .sort_values(["l_returnflag", "l_linestatus"])
)
np.testing.assert_array_equal(f1["l_returnflag"], want.l_returnflag)
np.testing.assert_allclose(np.asarray(f1["sum_qty"], dtype=np.float64),
                           want.sum_qty, rtol=1e-12)
np.testing.assert_array_equal(f1["count_order"], want.count_order)
print("q1: matches pandas oracle")

# 3) q3: join chain + top-k
identical(run("q3", True), run("q3", False), "q3")

# 4) empty input through a fused scalar aggregation (far-future date)
fe, ue = run("q6", True, date="2199-01-01"), run("q6", False,
                                                 date="2199-01-01")
identical(fe, ue, "q6-empty")

print("OK: on-chip fusion verification passed")
