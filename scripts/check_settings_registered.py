"""Static cluster-settings audit — every setting key must be both
registered and read.

Two failure classes, each a drift bug the type system can't catch:

- **unregistered use**: `settings.get("x")` (or `.set`) with a key no
  `register_*` call declares — a typo or a deleted setting; it raises
  KeyError only on the code path that reads it.
- **registered-but-unread**: a `register_*` key no code path in
  `cockroach_tpu/` gets or sets — dead surface area that documents a
  knob which controls nothing.

Pure text pass (regexes tolerant of calls split across lines), no import
of the package — so it runs without pulling in jax. Wired as a tier-1
test via tests/test_settings_registered.py; also runnable directly:

    python -m scripts.check_settings_registered
"""

from __future__ import annotations

import pathlib
import re
import sys

# matches settings.get("k") / _settings.set('k') with the open paren and
# the key possibly on different lines (\s* spans newlines)
_USE = re.compile(r"settings\.(?:get|set)\(\s*['\"]([^'\"]+)['\"]")
_REGISTER = re.compile(
    r"register_(?:bool|int|float|enum|string)\(\s*\n?\s*['\"]([^'\"]+)['\"]")


def _scan(root: pathlib.Path, rx: re.Pattern,
          skip: tuple[str, ...] = ()) -> dict[str, list[str]]:
    found: dict[str, list[str]] = {}
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root.parent).as_posix()
        if rel in skip:
            continue
        for m in rx.finditer(path.read_text()):
            found.setdefault(m.group(1), []).append(rel)
    return found


def check(repo_root: str | pathlib.Path | None = None) -> list[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    if repo_root is None:
        repo_root = pathlib.Path(__file__).resolve().parent.parent
    pkg = pathlib.Path(repo_root) / "cockroach_tpu"
    # the registry module's own get()/set() bodies aren't usages
    used = _scan(pkg, _USE, skip=("cockroach_tpu/utils/settings.py",))
    registered = _scan(pkg, _REGISTER)
    problems = []
    for key in sorted(set(used) - set(registered)):
        problems.append(
            f"unregistered setting {key!r} used in {', '.join(used[key])}")
    for key in sorted(set(registered) - set(used)):
        problems.append(
            f"setting {key!r} registered in {', '.join(registered[key])} "
            f"but never read (settings.get) or set anywhere in the package")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    if not problems:
        print("settings registry clean: every key registered and read")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
