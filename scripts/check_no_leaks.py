"""Thread/socket leak census — the leaktest.AfterTest(t) analog.

Reference: CockroachDB wraps every test in pkg/testutils/leaktest, which
snapshots goroutines before the test and fails if new ones survive it.
Here the census covers three resources: live threads
(threading.enumerate), open socket fds (/proc/self/fd symlinks pointing
at socket inodes), and memory-monitor drain failures — a query-level
BytesMonitor (flow/memory.py) that closed with bytes still reserved is a
leaked account, the mon.BytesMonitor "monitor closed with outstanding
bytes" assertion. The drain counter is monotonic, so the census compares
totals: any increase between snapshots means some query in between
failed to drain to zero.

Usage (chaos + dcn tests):

    from scripts.check_no_leaks import snapshot, assert_no_leaks

    before = snapshot()
    ... start servers, run queries, close servers ...
    assert_no_leaks(before)

`assert_no_leaks` retries for a grace period: closed sockets and joined
threads take a beat to disappear (TIME_WAIT is NOT counted — the census
reads this process's fds, not kernel conn state).

Also runnable standalone for a quick census of the current interpreter:
``python -m scripts.check_no_leaks``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class Census:
    threads: frozenset[str]
    n_threads: int
    socket_fds: int
    # cumulative count of query memory monitors that closed non-drained
    # (flow/memory.drain_failure_count); default keeps old snapshots valid
    mem_drain_failures: int = 0


def _drain_failure_count() -> int:
    """Query-monitor drain failures so far (0 when the memory plane has
    not been imported — the census must not force it in)."""
    import sys

    mod = sys.modules.get("cockroach_tpu.flow.memory")
    if mod is None:
        return 0
    return mod.drain_failure_count()


def _socket_fd_count() -> int:
    """Open socket fds of THIS process (anon_inode/pipe/file excluded)."""
    fd_dir = "/proc/self/fd"
    if not os.path.isdir(fd_dir):  # non-Linux: thread census only
        return 0
    n = 0
    for fd in os.listdir(fd_dir):
        try:
            if os.readlink(os.path.join(fd_dir, fd)).startswith("socket:"):
                n += 1
        except OSError:
            continue  # fd closed while listing
    return n


def snapshot() -> Census:
    threads = frozenset(
        f"{t.name}:{t.ident}" for t in threading.enumerate())
    return Census(threads, len(threads), _socket_fd_count(),
                  _drain_failure_count())


def leaks(before: Census) -> list[str]:
    """What exists now that did not exist at `before` (empty = clean)."""
    now = snapshot()
    out = []
    new_threads = [
        n for n in now.threads - before.threads
        # pytest's own machinery may spin a watcher thread mid-test
        if not n.startswith(("pytest", "MainThread"))
    ]
    if new_threads:
        out.append(f"threads leaked: {sorted(new_threads)}")
    if now.socket_fds > before.socket_fds:
        out.append(
            f"socket fds leaked: {before.socket_fds} -> {now.socket_fds}")
    if now.mem_drain_failures > before.mem_drain_failures:
        import sys

        mod = sys.modules.get("cockroach_tpu.flow.memory")
        recent = mod.drain_failures(last=3) if mod is not None else []
        out.append(
            "memory monitors closed non-drained: "
            f"{before.mem_drain_failures} -> {now.mem_drain_failures}"
            + (f" (recent: {recent})" if recent else ""))
    return out


def assert_no_leaks(before: Census, grace_s: float = 5.0) -> None:
    """Fail if threads/sockets born after `before` still exist. Retries
    within grace_s: daemon threads observe their stop event and fds close
    asynchronously with the test's teardown calls."""
    deadline = time.monotonic() + grace_s
    remaining = leaks(before)
    while remaining and time.monotonic() < deadline:
        time.sleep(0.05)
        remaining = leaks(before)
    assert not remaining, "; ".join(remaining)


if __name__ == "__main__":
    c = snapshot()
    print(f"threads={c.n_threads} socket_fds={c.socket_fds} "
          f"mem_drain_failures={c.mem_drain_failures}")
    for t in sorted(c.threads):
        print(f"  {t}")
