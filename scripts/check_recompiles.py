"""Zero-recompile serving-path guard (tier-1, sibling of
check_dispatch_budget.py).

Drives representative TPC-H queries through the prepared-plan cache
(sql/plancache.py) against flow/dispatch.py's compile accounting and
checks three properties:

- **cold budget**: the FIRST execution of each query compiles at most a
  recorded number of distinct kernels. Canonical tile shapes
  (catalog.SHAPE_BUCKETS) and the keyed kernel cache keep this small; a
  regression here is a shape or key leak (e.g. a per-table capacity
  sneaking back into kernel shapes).
- **bounded adaptation**: the SECOND execution (plan-cache hit, same
  literals) may re-specialize a handful of kernels once — join emission
  caps learn from run 1 (operators.post_run_update) — but within a small
  recorded budget. The background warmup thread runs each statement
  twice for exactly this reason.
- **zero-recompile serving**: the THIRD execution — same statement
  shape, DIFFERENT literals — must trigger 0 new XLA traces (the
  plan-cache hit rebinds literals as jit arguments, and learned
  capacities snap to the canonical shape ladder) and report a plan-cache
  hit. Its wall time is printed (the <100ms warm-serving target on real
  accelerators); only the compile count is asserted — CI machine speed
  varies.

Tier-1 runs the representative subset; ``--all`` sweeps every TPC-H
query. Runnable directly:

    python -m scripts.check_recompiles [--all]
"""

from __future__ import annotations

import os
import sys
import time

_SF = 0.001

# cold-compile budgets per query (distinct kernel specializations on a
# fresh process, fusion on, tile 1024, measured then padded ~50%): the
# fused pipeline + spool/consumer kernels + finalize/sort. Queries run in
# this order, so later queries already share earlier kernels (the
# process-global kernel cache) — budgets encode that sharing too.
BUDGETS = {
    "q1": 8,    # measured 4
    "q3": 18,   # measured 12
    "q6": 4,    # measured 2
    "q9": 21,   # measured 14
    "q18": 24,  # measured 16
}
# every query not listed above (the --all sweep) gets this generic cap
BUDGET_DEFAULT = 45
# run-2 adaptation: post_run_update switches join emission to compact
# mode at a learned cap, re-specializing once (measured ≤5 on the tier-1
# subset, ≤11 across the full sweep — q7's join tree)
BUDGET_ADAPT = 16

# literal overrides for the serving run: same statement shape, different
# values — the case the zero-recompile path exists for
_REBIND = {
    "q1": {"delta_days": 60},
    "q3": {"date": "1995-03-01"},
    "q6": {"date": "1995-01-01", "discount": 0.05},
    "q9": {},             # color is a string (host-prepared table): the
    "q18": {"quantity": 250},  # q9 serving run is a same-structure rerun
}


def check(all_queries: bool = False) -> list[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from cockroach_tpu.bench import queries as Q
    from cockroach_tpu.bench.tpch import gen_tpch
    from cockroach_tpu.flow import dispatch
    from cockroach_tpu.sql import plancache
    from cockroach_tpu.utils import settings

    problems: list[str] = []
    names = list(Q.QUERIES) if all_queries else list(BUDGETS)
    try:
        settings.set("sql.distsql.fusion.enabled", True)
        settings.set("sql.distsql.shape_buckets.enabled", True)
        settings.set("sql.distsql.tile_size", 1024)
        settings.set("sql.plan_cache.enabled", True)
        cat = gen_tpch(sf=_SF, seed=3)
        for name in names:
            c0 = dispatch.compiles()
            _, status = plancache.run_cached(Q.QUERIES[name](cat))
            cold = dispatch.compiles() - c0
            budget = BUDGETS.get(name, BUDGET_DEFAULT)
            if cold > budget:
                problems.append(
                    f"{name}: cold run compiled {cold} kernels, budget "
                    f"{budget} — a kernel-cache key or canonical-shape "
                    "regression is minting per-query specializations")
            c1 = dispatch.compiles()
            plancache.run_cached(Q.QUERIES[name](cat))
            adapt = dispatch.compiles() - c1
            if adapt > BUDGET_ADAPT:
                problems.append(
                    f"{name}: adaptation run re-specialized {adapt} "
                    f"kernels, budget {BUDGET_ADAPT} — learned capacities "
                    "are not converging in one run")
            kwargs = _REBIND.get(name, {})
            c2 = dispatch.compiles()
            t0 = time.perf_counter()
            _, status2 = plancache.run_cached(Q.QUERIES[name](cat, **kwargs))
            warm_ms = (time.perf_counter() - t0) * 1e3
            recompiles = dispatch.compiles() - c2
            if status2 != "hit":
                problems.append(
                    f"{name}: serving run reported plan-cache status "
                    f"{status2!r}, expected 'hit' — the statement no "
                    "longer parameterizes to a stable plan key")
            if recompiles:
                problems.append(
                    f"{name}: serving run with rebound literals "
                    f"{kwargs or '(none)'} triggered {recompiles} new XLA "
                    "compiles, expected 0 — the zero-recompile serving "
                    "path is broken")
            print(f"  {name}: cold {cold}/{budget} compiles, adapt "
                  f"{adapt}/{BUDGET_ADAPT}, serve {recompiles} compiles "
                  f"{warm_ms:.1f}ms [{status}->{status2}]")
    finally:
        settings.reset("sql.distsql.fusion.enabled")
        settings.reset("sql.distsql.shape_buckets.enabled")
        settings.reset("sql.distsql.tile_size")
        settings.reset("sql.plan_cache.enabled")
    return problems


def main() -> int:
    all_queries = "--all" in sys.argv[1:]
    problems = check(all_queries=all_queries)
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    if not problems:
        n = len(BUDGETS) if not all_queries else "all TPC-H"
        print(f"recompile guard clean ({n} queries): warmed repeats run "
              "with zero new XLA compiles within per-query cold budgets")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
