"""Seed-matrix chaos runner — sweep the chaos suite across fault seeds.

Every chaos test arms the fault registry with a FIXED per-test seed, so
one pytest run exercises one deterministic fault schedule. This driver
re-runs the whole suite N times with CHAOS_SEED_OFFSET=0..N-1 — the
registry adds the offset to every armed seed (utils/faults.arm), so each
pass fires a DIFFERENT deterministic schedule while staying replayable:
a failing offset reproduces with the same command.

Usage:
    python scripts/run_chaos_matrix.py [--seeds N] [--offset-base K]

Exit code is non-zero if ANY seed fails; the failing offsets print so
the exact schedule can be replayed with
    CHAOS_SEED_OFFSET=<off> pytest -m 'chaos and not slow'
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_matrix(offsets, extra_args=(), quiet: bool = False) -> list[int]:
    """Run the fast chaos suite once per seed offset; returns the list of
    offsets that FAILED (empty = the whole matrix converged)."""
    failed: list[int] = []
    for off in offsets:
        env = dict(os.environ,
                   CHAOS_SEED_OFFSET=str(off),
                   JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
        # 'and not slow' keeps the matrix off its own wrapper test —
        # recursing into the runner would fork-bomb the suite
        cmd = [sys.executable, "-m", "pytest", "-q",
               "-m", "chaos and not slow",
               "-p", "no:cacheprovider", *extra_args]
        proc = subprocess.run(
            cmd, cwd=_REPO_ROOT,
            stdout=subprocess.PIPE if quiet else None,
            stderr=subprocess.STDOUT if quiet else None)
        if proc.returncode != 0:
            failed.append(off)
            if quiet and proc.stdout:
                sys.stdout.write(proc.stdout.decode("utf-8", "replace"))
        print(f"[chaos-matrix] offset {off}: "
              f"{'FAIL' if proc.returncode else 'ok'}")
    return failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=4,
                    help="number of seed offsets to sweep (default 4)")
    ap.add_argument("--offset-base", type=int, default=0,
                    help="first CHAOS_SEED_OFFSET (default 0)")
    args = ap.parse_args(argv)
    offsets = range(args.offset_base, args.offset_base + args.seeds)
    failed = run_matrix(offsets)
    if failed:
        print(f"[chaos-matrix] FAILED offsets: {failed} — replay with "
              f"CHAOS_SEED_OFFSET=<off> pytest -m 'chaos and not slow'")
        return 1
    print(f"[chaos-matrix] all {args.seeds} seed offsets converged")
    return 0


if __name__ == "__main__":
    sys.exit(main())
