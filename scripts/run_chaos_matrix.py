"""Seed-matrix chaos runner — sweep the chaos suite across fault seeds.

Every chaos test arms the fault registry with a FIXED per-test seed, so
one pytest run exercises one deterministic fault schedule. This driver
re-runs the whole suite N times with CHAOS_SEED_OFFSET=0..N-1 — the
registry adds the offset to every armed seed (utils/faults.arm), so each
pass fires a DIFFERENT deterministic schedule while staying replayable:
a failing offset reproduces with the same command.

Before any seed runs, the driver closes the coverage loop with the
fault-coverage lint pass (cockroach_tpu/lint/faultcoverage.py): every
site registered in utils/faults.py SITES must be exercised by at least
one chaos-marked test, or the matrix REFUSES to run — sweeping seeds
over a suite that never reaches a registered failure path is false
confidence. ``--matrix`` prints the full site↔test mapping.

Every seed also runs sanitizer-armed: the chaos suite's autouse
fixtures (tests/test_chaos.py) switch on ``debug.lock_order.enabled``
AND ``debug.race_detector.enabled``, so an inverted lock acquisition or
a lockset-disjoint shared-state access anywhere under fault injection
fails the offset with a stack trace instead of a hang or a corruption.

Usage:
    python scripts/run_chaos_matrix.py [--seeds N] [--offset-base K]
                                       [--matrix]

Exit code is non-zero if coverage is incomplete or ANY seed fails; the
failing offsets print so the exact schedule can be replayed with
    CHAOS_SEED_OFFSET=<off> pytest -m 'chaos and not slow'
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def coverage_matrix() -> dict[str, list[str]]:
    """site -> chaos tests exercising it, from the fault-coverage pass
    (pure AST — nothing is imported, runs without jax)."""
    sys.path.insert(0, _REPO_ROOT)
    from cockroach_tpu.lint.core import load_files
    from cockroach_tpu.lint.faultcoverage import site_matrix

    files = load_files([os.path.join(_REPO_ROOT, "cockroach_tpu"),
                        os.path.join(_REPO_ROOT, "tests")])
    return site_matrix(files)


def check_coverage(verbose: bool = False) -> list[str]:
    """Returns the registered sites no chaos test exercises (empty =
    every failure mode in the registry is reachable by this matrix)."""
    matrix = coverage_matrix()
    uncovered = sorted(s for s, tests in matrix.items() if not tests)
    if verbose:
        width = max(len(s) for s in matrix) if matrix else 0
        for site in sorted(matrix):
            tests = matrix[site]
            status = f"{len(tests)} test(s)" if tests else "UNCOVERED"
            print(f"  {site:<{width}}  {status}")
            for t in tests:
                print(f"  {'':<{width}}    {t}")
    return uncovered


def run_matrix(offsets, extra_args=(), quiet: bool = False) -> list[int]:
    """Run the fast chaos suite once per seed offset; returns the list of
    offsets that FAILED (empty = the whole matrix converged)."""
    failed: list[int] = []
    for off in offsets:
        env = dict(os.environ,
                   CHAOS_SEED_OFFSET=str(off),
                   JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
        # 'and not slow' keeps the matrix off its own wrapper test —
        # recursing into the runner would fork-bomb the suite
        cmd = [sys.executable, "-m", "pytest", "-q",
               "-m", "chaos and not slow",
               "-p", "no:cacheprovider", *extra_args]
        proc = subprocess.run(
            cmd, cwd=_REPO_ROOT, env=env,
            stdout=subprocess.PIPE if quiet else None,
            stderr=subprocess.STDOUT if quiet else None)
        if proc.returncode != 0:
            failed.append(off)
            if quiet and proc.stdout:
                sys.stdout.write(proc.stdout.decode("utf-8", "replace"))
        print(f"[chaos-matrix] offset {off}: "
              f"{'FAIL' if proc.returncode else 'ok'}")
    return failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=4,
                    help="number of seed offsets to sweep (default 4)")
    ap.add_argument("--offset-base", type=int, default=0,
                    help="first CHAOS_SEED_OFFSET (default 0)")
    ap.add_argument("--matrix", action="store_true",
                    help="print the full site<->test coverage matrix")
    args = ap.parse_args(argv)
    if args.matrix:
        print("[chaos-matrix] site -> test coverage:")
    uncovered = check_coverage(verbose=args.matrix)
    if uncovered:
        print("[chaos-matrix] REFUSING to run: registered fault sites "
              "with no chaos test:", file=sys.stderr)
        for site in uncovered:
            print(f"  {site}", file=sys.stderr)
        print("  (add a chaos test naming each site, or unregister it "
              "in utils/faults.py SITES)", file=sys.stderr)
        return 1
    print(f"[chaos-matrix] coverage closed: every registered fault site "
          f"has a chaos test")
    offsets = range(args.offset_base, args.offset_base + args.seeds)
    failed = run_matrix(offsets)
    if failed:
        print(f"[chaos-matrix] FAILED offsets: {failed} — replay with "
              f"CHAOS_SEED_OFFSET=<off> pytest -m 'chaos and not slow'")
        return 1
    print(f"[chaos-matrix] all {args.seeds} seed offsets converged")
    return 0


if __name__ == "__main__":
    sys.exit(main())
