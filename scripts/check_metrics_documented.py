"""Static metrics audit — every metric must carry help text and be
documented in README's metrics table.

Two failure classes, both observability drift the type system can't catch:

- **undocumented metric**: a ``DEFAULT.counter/gauge/histogram/
  labeled_counter`` registration whose name is missing from the README
  metrics table, or whose help string is empty — an operator sees the
  series in /_status/vars with no way to learn what it measures.
- **stale table row**: a README row naming a metric no code registers —
  documentation for a series that will never appear.

Pure ast pass over ``cockroach_tpu/`` (no package import, so it runs
without pulling in jax). Wired as a tier-1 test via
tests/test_metrics_documented.py; also runnable directly:

    python -m scripts.check_metrics_documented
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

_KINDS = ("counter", "gauge", "histogram", "labeled_counter",
          "labeled_gauge")
# README metrics-table rows: | `metric_name` | ... |
_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|", re.MULTILINE)


def registrations(pkg: pathlib.Path) -> dict[str, dict]:
    """{metric name: {kind, help, where}} for every DEFAULT registry
    registration in the package."""
    regs: dict[str, dict] = {}
    for path in sorted(pkg.rglob("*.py")):
        rel = path.relative_to(pkg.parent).as_posix()
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _KINDS):
                continue
            base = node.func.value
            base_name = (base.attr if isinstance(base, ast.Attribute)
                         else base.id if isinstance(base, ast.Name)
                         else None)
            if base_name != "DEFAULT":
                continue  # per-test registries document themselves
            args = node.args
            if not args or not isinstance(args[0], ast.Constant):
                continue
            name = str(args[0].value)
            # labeled_counter(name, label, help); the rest (name, help)
            hi = 2 if node.func.attr == "labeled_counter" else 1
            help_txt = ""
            if len(args) > hi and isinstance(args[hi], ast.Constant):
                help_txt = str(args[hi].value)
            for kw in node.keywords:
                if kw.arg == "help" and isinstance(kw.value, ast.Constant):
                    help_txt = str(kw.value.value)
            regs[name] = {"kind": node.func.attr, "help": help_txt,
                          "where": f"{rel}:{node.lineno}"}
    return regs


def documented(readme: pathlib.Path) -> set[str]:
    return set(_ROW.findall(readme.read_text())) if readme.exists() else set()


def check(repo_root: str | pathlib.Path | None = None) -> list[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    if repo_root is None:
        repo_root = pathlib.Path(__file__).resolve().parent.parent
    root = pathlib.Path(repo_root)
    regs = registrations(root / "cockroach_tpu")
    rows = documented(root / "README.md")
    problems = []
    for name in sorted(regs):
        if not regs[name]["help"].strip():
            problems.append(
                f"metric {name!r} ({regs[name]['where']}) registered with "
                f"empty help text")
        if name not in rows:
            problems.append(
                f"metric {name!r} ({regs[name]['where']}) missing from the "
                f"README metrics table")
    for name in sorted(rows - set(regs)):
        problems.append(
            f"README metrics table documents {name!r} but no code "
            f"registers it")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    if not problems:
        print("metrics registry clean: every metric has help text and a "
              "README table row")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
