"""Kernel-dispatch budget regression guard (tier-1, same spirit as
check_settings_registered.py).

Runs ONE representative fused query — TPC-H q1, a scan -> filter ->
project -> group-by chain — at two tile sizes and checks two budgets
against flow/dispatch.py's per-call accounting:

- **steady total**: warm (post-adaptive-learning) dispatches for the whole
  query must stay at or under BUDGET_STEADY. A fusion regression (a chain
  member silently falling back to its own per-operator jit) roughly
  doubles this.
- **per tile**: halving the tile size doubles the input tile count; the
  dispatch increase per extra tile must stay at or under BUDGET_PER_TILE
  (the fused pipeline pays exactly ONE pre-aggregation dispatch per tile).

Budgets are recorded constants, not ratios, so a regression shows up as a
hard failure with the measured numbers in the message. Runnable directly:

    python -m scripts.check_dispatch_budget
"""

from __future__ import annotations

import os
import sys

# measured 8 with the fusion pass on (6 input tiles): 6 fused
# slice+filter+project+group+merge dispatches + finalize + sort. The
# unfused engine measures 31.
BUDGET_STEADY = 10
# the join-plane queries, same harness: q9's part|supplier|orders chain
# probes its build tables inside the fused per-tile step kernel (measured
# 20 warm at sf 0.001 / 6 lineitem tiles; an unfused chain pays one
# dispatch + readback per join per tile and blows well past this), and
# q18's ORDER BY ... LIMIT runs as a folded device top-k instead of a
# full sort spool (measured 23).
BUDGET_STEADY_Q9 = 24
BUDGET_STEADY_Q18 = 27
# ONE fused pre-aggregation kernel per extra input tile (acceptance
# criterion of the fusion work; measured exactly 1.0) — the accumulator
# merge rides inside the fold step kernel. The unfused engine pays 5.
BUDGET_PER_TILE = 1.25
# a distributed plan (partial agg -> all_to_all shuffle -> merge agg ->
# finalize over the 8-way mesh) is ONE SPMD program = ONE dispatch; the
# lower bound of 1 proves parallel/* kernels route through dispatch.jit
# and count at all (they used to call jax.jit directly and were invisible
# to this accounting).
BUDGET_SPMD = 2

_SF = 0.001
_TILE = 1024


def _steady_dispatches(cat, tile: int, qname: str = "q1") -> int:
    from cockroach_tpu.bench import queries as Q
    from cockroach_tpu.flow import dispatch
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.plan import builder as plan_builder
    from cockroach_tpu.utils import settings

    settings.set("sql.distsql.tile_size", tile)
    root = plan_builder.build(Q.QUERIES[qname](cat).optimized_plan(), cat)
    run_operator(root)  # warm: compile + adaptive capacity learning
    d0 = dispatch.total()
    run_operator(root)
    return dispatch.total() - d0


def _spmd_dispatches() -> int:
    """Warm dispatches for one distributed groupby over an 8-way mesh."""
    import numpy as np

    from cockroach_tpu import coldata as cd
    from cockroach_tpu.flow import dispatch
    from cockroach_tpu.ops import aggregation as agg
    from cockroach_tpu.parallel import dist, mesh as mesh_mod

    mesh = mesh_mod.make_mesh(8)
    schema = cd.Schema.of(g=cd.INT64, v=cd.INT64)
    rng = np.random.default_rng(11)
    n = 2000
    b = cd.from_host(
        schema,
        {"g": rng.integers(0, 32, n), "v": rng.integers(0, 100, n)},
        capacity=512 * 8,
    )
    b = dist.shard_batch(b, mesh)
    fn, _ = dist.make_distributed_groupby(
        mesh, schema, (0,),
        (agg.AggSpec("sum", 1, "s"), agg.AggSpec("count_rows", None, "n")),
        local_capacity=512,
    )
    fn(b)  # warm: compile
    d0 = dispatch.total()
    fn(b)
    return dispatch.total() - d0


def check() -> list[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from cockroach_tpu.bench.tpch import gen_tpch
    from cockroach_tpu.utils import settings

    import jax

    if len(jax.devices()) < 8:  # standalone run: conftest hasn't forced
        from cockroach_tpu.utils.backend import force_cpu_backend

        force_cpu_backend(8)  # the SPMD case needs the full virtual mesh
    problems = []
    try:
        settings.set("sql.distsql.fusion.enabled", True)
        cat = gen_tpch(sf=_SF, seed=3)
        tiles = -(-cat.get("lineitem").num_rows // _TILE)
        steady = _steady_dispatches(cat, _TILE)
        if steady > BUDGET_STEADY:
            problems.append(
                f"q1 steady-state kernel dispatches {steady} exceed the "
                f"recorded budget {BUDGET_STEADY} ({tiles} input tiles) — "
                "a pipeline member stopped fusing or a new per-tile "
                "dispatch crept into the pull loop")
        halved = _steady_dispatches(cat, _TILE // 2)
        per_tile = (halved - steady) / tiles
        if per_tile > BUDGET_PER_TILE:
            problems.append(
                f"marginal dispatches per extra input tile {per_tile:.2f} "
                f"({steady} -> {halved} when tiles double from {tiles}) "
                f"exceed the budget {BUDGET_PER_TILE} — the per-tile "
                "chain is no longer one fused kernel")
        for qname, budget in (("q9", BUDGET_STEADY_Q9),
                              ("q18", BUDGET_STEADY_Q18)):
            got = _steady_dispatches(cat, _TILE, qname)
            if got > budget:
                problems.append(
                    f"{qname} steady-state kernel dispatches {got} exceed "
                    f"the recorded budget {budget} — the multiway fused "
                    "probe (q9) or device top-k fold (q18) stopped "
                    "covering the join plane's per-tile work")
        spmd = _spmd_dispatches()
        if spmd < 1:
            problems.append(
                "distributed groupby registered 0 kernel dispatches — the "
                "SPMD plan no longer routes through flow/dispatch.jit and "
                "is invisible to dispatch accounting")
        elif spmd > BUDGET_SPMD:
            problems.append(
                f"distributed groupby dispatches {spmd} exceed the budget "
                f"{BUDGET_SPMD} — the partial-agg/shuffle/merge pipeline "
                "is no longer one SPMD program")
    finally:
        settings.reset("sql.distsql.tile_size")
        settings.reset("sql.distsql.fusion.enabled")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    if not problems:
        print("dispatch budget clean: fused pipeline within "
              f"{BUDGET_STEADY} steady / {BUDGET_PER_TILE}-per-tile, "
              f"q9 within {BUDGET_STEADY_Q9}, q18 within "
              f"{BUDGET_STEADY_Q18}, distributed plan within "
              f"{BUDGET_SPMD}")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
