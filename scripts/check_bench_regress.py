"""Bench-regression gate — compare a fresh BENCH JSON against the roofline.

The repo keeps one ``BENCH_r*.json`` per recorded bench run (wrapper dict
with the parsed one-line bench JSON under ``"parsed"``). This checker
takes a FRESH bench emission (a file holding ``python bench.py``'s one
JSON line, or ``-`` for stdin) and diffs its throughput surface against
the newest recorded baseline:

- every throughput series (any key ending ``_per_sec``, plus the
  top-level geomean ``value``) that dropped more than the threshold
  (default 20%) is flagged as a regression;
- every metric present in the baseline but ABSENT from the fresh run is
  flagged — a bench refactor that silently stops emitting a series must
  not pass as "no regressions".

Both runs must come from the same platform (a cpu-fallback run diffed
against a tpu baseline would flag everything); mismatches flag, they do
not silently pass.

Usage (tier-2, run_chaos_matrix.py-style — not part of the tier-1 pytest
sweep; run it after a bench session, before committing a BENCH file):

    python bench.py > /tmp/bench_fresh.json
    python scripts/check_bench_regress.py /tmp/bench_fresh.json
    python scripts/check_bench_regress.py --threshold 0.3 /tmp/fresh.json
    python bench.py | python scripts/check_bench_regress.py -

Exit code is non-zero if ANY regression or missing metric is flagged; the
flags print one per line so the offending series are greppable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def latest_baseline(repo_root: str = _REPO_ROOT,
                    host_class: str | None = None
                    ) -> tuple[str, dict] | None:
    """Newest BENCH_r*.json's parsed bench dict (path, parsed); None when
    no baseline has been recorded yet (first run is a free pass).

    With ``host_class``, only baselines of the SAME host class compare —
    a laptop run diffed against a TPU-pod baseline would flag every
    series. Baselines recorded before host_class stamping act as
    wildcards (they match any fresh host) rather than being skipped,
    so the gate keeps teeth across the transition."""
    paths = sorted(glob.glob(os.path.join(repo_root, "BENCH_r*.json")))
    for path in reversed(paths):
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        # recorded files wrap the bench line under "parsed"; accept a bare
        # bench dict too so old/raw captures also work as baselines
        parsed = doc.get("parsed", doc)
        bhost = parsed.get("host_class")
        if host_class is None or bhost is None or bhost == host_class:
            return path, parsed
    return None


def flatten_throughput(bench: dict) -> dict[str, float]:
    """{series name: value} for every throughput figure in a bench dict:
    the top-level geomean plus each detail entry's *_per_sec keys."""
    out: dict[str, float] = {}
    if isinstance(bench.get("value"), (int, float)):
        out["value"] = float(bench["value"])
    for dname, d in (bench.get("detail") or {}).items():
        if not isinstance(d, dict):
            continue
        for k, v in d.items():
            if k.endswith("_per_sec") and isinstance(v, (int, float)):
                out[f"{dname}.{k}"] = float(v)
    return out


def compare(fresh: dict, baseline: dict, threshold: float = 0.2
            ) -> list[str]:
    """Flags (empty = clean): >threshold throughput drops vs baseline and
    baseline series missing from the fresh run."""
    flags: list[str] = []
    # comparability gate: the metric name encodes scale factor + platform
    # (tpch_sf0.5_cpu_...), so differing names means the runs measured
    # different configurations — flag, don't diff apples to oranges
    bname, fname = baseline.get("metric", ""), fresh.get("metric", "")
    if bname and fname and bname != fname:
        flags.append(
            f"config mismatch: baseline {bname!r} vs fresh {fname!r} "
            "(not comparable)")
        return flags
    base_t = flatten_throughput(baseline)
    fresh_t = flatten_throughput(fresh)
    for name, bval in sorted(base_t.items()):
        fval = fresh_t.get(name)
        if fval is None:
            flags.append(f"missing metric: {name} (baseline {bval:g})")
            continue
        if bval > 0 and fval < bval * (1.0 - threshold):
            drop = 100.0 * (1.0 - fval / bval)
            flags.append(
                f"regression: {name} {bval:g} -> {fval:g} "
                f"(-{drop:.1f}% > {threshold:.0%} threshold)")
    flags.extend(overload_oracle_flags(fresh))
    flags.extend(fanout_oracle_flags(fresh))
    flags.extend(views_oracle_flags(fresh))
    flags.extend(coalesce_oracle_flags(fresh))
    flags.extend(warmup_oracle_flags(fresh))
    return flags


def overload_oracle_flags(fresh: dict) -> list[str]:
    """The multi-tenant overload oracle is pass/fail, not a trend: when
    the fresh run carries ``mixed_load.overload_*`` figures, a false
    oracle bool flags regardless of any throughput threshold (goodput
    collapsing past saturation, untyped errors, or a noisy neighbor
    breaking per-tenant p99 isolation are correctness failures)."""
    ml = (fresh.get("detail") or {}).get("mixed_load")
    if not isinstance(ml, dict) or "overload_oracle_ok" not in ml:
        return []
    flags = []
    for key, what in (
            ("overload_oracle_goodput_ok",
             "goodput fell below 80% of saturation past the knee"),
            ("overload_oracle_typed_ok",
             "untyped errors (or zero shed) under overload"),
            ("overload_oracle_isolation_ok",
             "noisy neighbor pushed well-behaved p99 queue-wait past "
             "2x its solo baseline"),
    ):
        if not ml.get(key, True):
            flags.append(f"overload oracle: {what} "
                         f"(mixed_load.{key} = false)")
    if not ml["overload_oracle_ok"] and not flags:
        flags.append("overload oracle: mixed_load.overload_oracle_ok = "
                     "false")
    return flags


def fanout_oracle_flags(fresh: dict) -> list[str]:
    """The changefeed fan-out oracle is pass/fail, not a trend: when the
    fresh run carries ``fanout.*`` figures, a false oracle bool flags
    regardless of any throughput threshold (a subscriber losing or
    duplicating a version after dedup, or buffer bytes leaking past hub
    close, are correctness failures)."""
    fo = (fresh.get("detail") or {}).get("fanout")
    if not isinstance(fo, dict) or "fanout_oracle_ok" not in fo:
        return []
    if not fo["fanout_oracle_ok"]:
        return ["fanout oracle: a subscriber lost or duplicated a version "
                "after (ts, key) dedup, or fan-out buffer bytes leaked "
                "past hub close (detail.fanout.fanout_oracle_ok = false)"]
    return []


def views_oracle_flags(fresh: dict) -> list[str]:
    """The matview oracle is pass/fail, not a trend: when the fresh run
    carries ``views.*`` figures, a false oracle bool flags regardless of
    any throughput threshold (a standing view drifting from its defining
    query's rescan, or per-view dispatches creeping back into the flush
    path, are correctness failures)."""
    vw = (fresh.get("detail") or {}).get("views")
    if not isinstance(vw, dict) or "views_oracle_ok" not in vw:
        return []
    flags = []
    if not vw["views_oracle_ok"]:
        flags.append("views oracle: a sampled materialized view was not "
                     "bit-identical to a fresh rescan of its defining "
                     "query (detail.views.views_oracle_ok = false)")
    if not vw.get("views_dispatch_ok", True):
        flags.append("views oracle: flush cost scaled with the view count "
                     "or fell back to base rescans on the steady path "
                     "(detail.views.views_dispatch_ok = false)")
    return flags


def coalesce_oracle_flags(fresh: dict) -> list[str]:
    """The batch-coalescing oracle is pass/fail, not a trend: when the
    fresh run carries ``mixed_load.coalesce_*`` figures, a false oracle
    bool flags regardless of any throughput threshold (a coalesced op
    returning different bytes than its solo execution, or typed per-key
    errors leaking across sessions in a merged train, are correctness
    failures)."""
    ml = (fresh.get("detail") or {}).get("mixed_load")
    if not isinstance(ml, dict) or "coalesce_oracle_ok" not in ml:
        return []
    flags = []
    if not ml["coalesce_oracle_ok"]:
        flags.append("coalesce oracle: coalesced execution was not "
                     "bit-identical to per-session solo batches "
                     "(detail.mixed_load.coalesce_oracle_ok = false)")
    if ml.get("coalesce_errors", 0):
        flags.append(f"coalesce oracle: {ml['coalesce_errors']} op(s) "
                     "errored during the coalesce A/B "
                     "(detail.mixed_load.coalesce_errors != 0)")
    return flags


def warmup_oracle_flags(fresh: dict) -> list[str]:
    """The warm-menu oracle is pass/fail, not a trend: when the fresh run
    carries ``warmup.*`` figures, a warmed kernel returning different
    bytes than a cold-compiled one, or the menu failing to pre-mint the
    ladder (serving-path compiles > 0 with the menu on), flags regardless
    of any throughput threshold."""
    wu = (fresh.get("detail") or {}).get("warmup")
    if not isinstance(wu, dict):
        return []
    flags = []
    if not wu.get("menu_oracle_ok", True):
        flags.append("warmup oracle: menu-warmed results were not "
                     "bit-identical to cold-compiled results "
                     "(detail.warmup.menu_oracle_ok = false)")
    if wu.get("serving_compiles_on", 0):
        flags.append(f"warmup oracle: {wu['serving_compiles_on']} "
                     "serving-path compile(s) with the menu on — the AOT "
                     "ladder missed shapes it promises to cover "
                     "(detail.warmup.serving_compiles_on != 0)")
    return flags


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="flag >threshold throughput regressions vs the newest "
                    "recorded BENCH_r*.json")
    ap.add_argument("fresh", help="fresh bench JSON file, or - for stdin")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="fractional drop that counts as a regression "
                         "(default 0.2 = 20%%)")
    ap.add_argument("--baseline", default=None,
                    help="explicit baseline file (default: newest "
                         "BENCH_r*.json in the repo root)")
    args = ap.parse_args(argv)

    raw = (sys.stdin.read() if args.fresh == "-"
           else open(args.fresh, encoding="utf-8").read())
    # bench.py's contract is ONE JSON line, but stderr passthrough means a
    # captured file may carry '#' progress lines — take the last JSON line
    fresh = None
    for line in raw.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            fresh = json.loads(line)
    if fresh is None:
        print("no JSON object found in fresh input", file=sys.stderr)
        return 2

    if args.baseline is not None:
        with open(args.baseline, encoding="utf-8") as f:
            doc = json.load(f)
        bpath, baseline = args.baseline, doc.get("parsed", doc)
    else:
        found = latest_baseline(host_class=fresh.get("host_class"))
        if found is None:
            print("no comparable BENCH_r*.json baseline recorded "
                  f"(host_class {fresh.get('host_class')!r}); nothing to "
                  "compare")
            return 0
        bpath, baseline = found

    flags = compare(fresh, baseline, args.threshold)
    if flags:
        print(f"bench regressions vs {os.path.basename(bpath)}:")
        for fl in flags:
            print(f"  {fl}")
        return 1
    n = len(flatten_throughput(baseline))
    print(f"ok: {n} throughput series within {args.threshold:.0%} of "
          f"{os.path.basename(bpath)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
