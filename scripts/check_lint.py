"""crlint tree gate — the static-analysis suite must be clean at HEAD.

Runs every crlint pass (cockroach_tpu/lint/: host-sync, raw-jit,
broad-except, unused-import, tracing-api, lock-order, shared-state,
mem-accounting, fault-coverage, untimed-wait, recompile-hazard,
race-coverage, unknown-pragma) over the package, the
scripts/ directory, the tests/ tree, and the repo-root entry points
(bench.py, __graft_entry__.py) and fails on any unsuppressed
finding. This is the
nogo/roachvet analog: the lint rules are only worth having if the tree
is kept at zero findings, so the gate rides in tier-1 next to the
settings and dispatch-budget audits. Pure AST pass — nothing is
imported, so it runs without pulling in jax.

Deliberate exceptions carry an inline pragma with a mandatory reason:

    # crlint: allow-<rule>(<why this site is exempt>)

(same line, the line above, or on a `def` line to cover the function).
Silent `except Exception: pass` handlers in kv/, flow/ and server/ are
hard errors the pragma cannot suppress. Wired as a tier-1 test via
tests/test_lint.py; also runnable directly:

    python -m scripts.check_lint
    python -m cockroach_tpu.lint --rule host-sync cockroach_tpu scripts
"""

from __future__ import annotations

import pathlib
import sys


def check(repo_root: str | pathlib.Path | None = None,
          timings: dict | None = None) -> list[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    from cockroach_tpu.lint import run_lint

    if repo_root is None:
        repo_root = pathlib.Path(__file__).resolve().parent.parent
    root = pathlib.Path(repo_root)
    paths = [root / "cockroach_tpu", root / "scripts", root / "tests"]
    # repo-root entry points ride along when present (fixture trees in
    # the lint tests call check() on trimmed copies without them)
    for entry in ("bench.py", "__graft_entry__.py"):
        if (root / entry).is_file():
            paths.append(root / entry)
    return [f.render() for f in run_lint(paths, timings=timings)]


def main() -> int:
    timings: dict = {}
    problems = check(timings=timings)
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    # per-pass wall time: the budget the shared TreeCache defends —
    # a regression in any single pass is attributable at a glance
    width = max((len(k) for k in timings), default=0)
    for name, secs in sorted(timings.items(), key=lambda kv: -kv[1]):
        print(f"  {name:<{width}}  {secs:7.3f}s", file=sys.stderr)
    print(f"  {'total':<{width}}  {sum(timings.values()):7.3f}s",
          file=sys.stderr)
    if not problems:
        print("crlint clean: all passes over cockroach_tpu/, scripts/, "
              "tests/ and the repo-root entry points")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
