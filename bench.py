"""Benchmark driver — the TPC-H north-star ladder on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

value: geomean over the query ladder (default q1,q3,q9,q18 — BASELINE.md's
north-star queries) of lineitem rows/sec through each full pipeline, each the
median of BENCH_RUNS timed runs after a compile warm-up. Per-query numbers
are in "detail".

vs_baseline: geomean ratio against a single-host pandas implementation of the
same queries measured in-process (the reference's 8-vCPU colexec baseline
cannot be executed in this image — no Go toolchain; pandas columnar eval is
the closest measurable stand-in and is itself vectorized C). Every engine
result is asserted equal to the pandas result before timing counts.

On any unrecoverable failure, still emits one JSON line with an "error" field.

Env knobs: TPCH_SF (default 1.0), BENCH_RUNS (default 3), BENCH_QUERY
(comma-separated, default "q1,q3,q18,q9" — q9's five-way
join compiles longest and runs last so a cold cache cannot starve the rest
of the ladder), BENCH_BACKEND_RETRIES,
BENCH_BACKEND_TIMEOUT (seconds for the subprocess backend probe).
"""

import faulthandler
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

# SIGUSR1 -> dump all thread stacks to stderr (diagnosing tunnel hangs:
# `kill -USR1 <pid>` shows whether the bench is wedged in compile, transfer,
# or host code without killing the run)
faulthandler.register(signal.SIGUSR1, all_threads=True)


_probe_diag: list[str] = []


def _probe_backend(timeout_s: float) -> str | None:
    """Initialize the default JAX backend in a THROWAWAY SUBPROCESS so that a
    hung accelerator tunnel (the round-1 failure mode: the injected TPU
    plugin blocked forever in jax.devices()) cannot take down the bench.
    Returns the platform name on success, else None; failures append an
    attributable line (timeout vs stderr tail) to _probe_diag, which lands
    in the emitted JSON when the whole window comes up dry."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, timeout=timeout_s, text=True,
        )
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"").decode(errors="replace").strip()
                .splitlines()[-2:])
        _probe_diag.append(
            f"probe timed out after {timeout_s:.0f}s"
            + (f" (stderr: {' | '.join(tail)})" if tail else "")
        )
        print(f"# backend probe timed out ({timeout_s:.0f}s)",
              file=sys.stderr, flush=True)
        return None
    if out.returncode == 0 and out.stdout.strip():
        return out.stdout.strip().splitlines()[-1]
    tail = (out.stderr or "").strip().splitlines()[-3:]
    _probe_diag.append(f"probe rc={out.returncode}: {' | '.join(tail)}")
    print(f"# backend probe failed rc={out.returncode}: {' | '.join(tail)}",
          file=sys.stderr, flush=True)
    return None


def _pandas_baseline(qname, cat, res) -> float:
    """Run the same query in pandas, assert the engine result matches, and
    return the elapsed seconds (the measured stand-in baseline)."""
    from cockroach_tpu.bench import tpch

    li = tpch.to_pandas(cat, "lineitem")
    if qname == "q1":
        t0 = time.time()
        cutoff = tpch.d("1998-12-01") - 90
        f = li[li.l_shipdate <= cutoff].copy()
        f["disc_price"] = f.l_extendedprice * (1 - f.l_discount)
        f["charge"] = f.disc_price * (1 + f.l_tax)
        base = (
            f.groupby(["l_returnflag", "l_linestatus"])
            .agg(
                sum_qty=("l_quantity", "sum"),
                sum_base_price=("l_extendedprice", "sum"),
                sum_disc_price=("disc_price", "sum"),
                sum_charge=("charge", "sum"),
                avg_qty=("l_quantity", "mean"),
                avg_price=("l_extendedprice", "mean"),
                avg_disc=("l_discount", "mean"),
                count_order=("l_quantity", "size"),
            )
            .sort_index()
        )
        el = time.time() - t0
        for col in ("sum_qty", "sum_base_price", "sum_disc_price",
                    "sum_charge", "avg_qty", "avg_price", "avg_disc",
                    "count_order"):
            np.testing.assert_allclose(
                np.asarray(res[col], dtype=np.float64),
                base[col].to_numpy().astype(np.float64), rtol=1e-9,
            )
        return el
    if qname == "q6":
        t0 = time.time()
        date = tpch.d("1994-01-01")
        f = li[(li.l_shipdate >= date) & (li.l_shipdate < date + 365)
               & (li.l_discount >= 0.05 - 1e-9) & (li.l_discount <= 0.07 + 1e-9)
               & (li.l_quantity < 24)]
        want = (f.l_extendedprice * f.l_discount).sum()
        el = time.time() - t0
        np.testing.assert_allclose(float(res["revenue"][0]), want, rtol=1e-9)
        return el
    if qname == "q3":
        o = tpch.to_pandas(cat, "orders")
        c = tpch.to_pandas(cat, "customer")
        t0 = time.time()
        date = tpch.d("1995-03-15")
        cb = c[c.c_mktsegment == "BUILDING"]
        ob = o[o.o_orderdate < date].merge(
            cb, left_on="o_custkey", right_on="c_custkey")
        lb = li[li.l_shipdate > date]
        j = lb.merge(ob, left_on="l_orderkey", right_on="o_orderkey")
        j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
        want = (
            j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
            .agg(revenue=("revenue", "sum")).reset_index()
            .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
            .head(10)
        )
        el = time.time() - t0
        np.testing.assert_allclose(
            np.asarray(res["revenue"], dtype=np.float64),
            want.revenue.to_numpy(), rtol=1e-9,
        )
        return el
    if qname == "q9":
        import pandas as pd

        o = tpch.to_pandas(cat, "orders")
        s = tpch.to_pandas(cat, "supplier")
        n = tpch.to_pandas(cat, "nation")
        p = tpch.to_pandas(cat, "part")
        ps = tpch.to_pandas(cat, "partsupp")
        t0 = time.time()
        pg = p[p.p_name.str.contains("green")]
        j = (
            li[li.l_partkey.isin(pg.p_partkey)]
            .merge(ps, left_on=["l_partkey", "l_suppkey"],
                   right_on=["ps_partkey", "ps_suppkey"])
            .merge(s, left_on="l_suppkey", right_on="s_suppkey")
            .merge(n, left_on="s_nationkey", right_on="n_nationkey")
            .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        )
        j["o_year"] = pd.to_datetime(
            j.o_orderdate, unit="D", origin="unix"
        ).dt.year
        j["amount"] = (
            j.l_extendedprice * (1 - j.l_discount)
            - j.ps_supplycost * j.l_quantity
        )
        want = (
            j.groupby(["n_name", "o_year"]).agg(sum_profit=("amount", "sum"))
            .reset_index()
            .sort_values(["n_name", "o_year"], ascending=[True, False])
        )
        el = time.time() - t0
        np.testing.assert_allclose(
            np.asarray(res["sum_profit"], dtype=np.float64),
            want.sum_profit.to_numpy(), rtol=1e-9,
        )
        return el
    if qname == "q18":
        o = tpch.to_pandas(cat, "orders")
        c = tpch.to_pandas(cat, "customer")
        t0 = time.time()
        qty = li.groupby("l_orderkey").l_quantity.sum()
        big = qty[qty > 300].index
        j = (
            o[o.o_orderkey.isin(big)]
            .merge(c, left_on="o_custkey", right_on="c_custkey")
            .merge(li, left_on="o_orderkey", right_on="l_orderkey")
        )
        want = (
            j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                       "o_totalprice"])
            .agg(sum_qty=("l_quantity", "sum")).reset_index()
            .sort_values(["o_totalprice", "o_orderdate"],
                         ascending=[False, True])
            .head(100)
        )
        el = time.time() - t0
        np.testing.assert_allclose(
            np.asarray(res["sum_qty"], dtype=np.float64),
            want.sum_qty.to_numpy(), rtol=1e-12,
        )
        return el
    raise ValueError(f"no pandas baseline for {qname}")


def _bench_query(qname, cat, nrows, runs):
    """Median engine time + pandas baseline time for one query.
    Returns (rows_per_sec, ratio_vs_pandas, cold_s, warmup_s)."""
    from cockroach_tpu.bench import queries as Q
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.plan import builder as plan_builder

    rel = Q.QUERIES[qname](cat)
    # one operator tree, re-initialized per run: its jitted kernels compile
    # during the warm-up runs and are reused by every timed run (compiles
    # also land in the persistent cache, so future processes skip them).
    # TWO warmups, timed separately: the FIRST (cold_s) pays the compile
    # wall and also LEARNS adaptive execution choices (join emission
    # capacities); the SECOND compiles the handful of kernels those
    # choices select. warmup_s is the total until steady state — the
    # number the plan/kernel cache hierarchy exists to drive to ~0 on
    # repeat statements (scripts/check_recompiles.py holds the repeat to
    # zero new compiles).
    root = plan_builder.build(rel.plan, cat)
    t0 = time.time()
    run_operator(root)
    cold_s = time.time() - t0
    run_operator(root)
    warmup_s = time.time() - t0
    print(f"# {qname} warmup: cold {cold_s:.1f}s (compile), "
          f"settle {warmup_s - cold_s:.1f}s (learn+respecialize)",
          file=sys.stderr, flush=True)

    times = []
    for _ in range(runs):
        t0 = time.time()
        res = run_operator(root)
        times.append(time.time() - t0)
    med = sorted(times)[len(times) // 2]
    rows_per_sec = nrows / med

    # pandas baseline of the same query (asserts engine result matches)
    pandas_s = _pandas_baseline(qname, cat, res)
    print(f"# {qname}: engine {med*1e3:.0f}ms "
          f"({rows_per_sec/1e6:.1f}M rows/s); pandas {pandas_s*1e3:.0f}ms",
          file=sys.stderr, flush=True)
    return rows_per_sec, pandas_s / med, cold_s, warmup_s


_partial = {"detail": {}, "errors": [], "sf": 1.0, "platform": "unknown"}
_emit_lock = __import__("threading").Lock()
_emitted = False


def _emit(final: bool) -> None:
    """Assemble and print the one-line JSON from whatever has completed.
    Guarded so the deadline timer and the main thread can never both print
    (the contract is exactly ONE JSON line)."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
    detail = _partial["detail"]
    errors = list(_partial["errors"])
    if not detail:
        print(json.dumps({
            "metric": "tpch_bench_failed", "value": 0, "unit": "rows/sec",
            "vs_baseline": 0.0,
            "error": "; ".join(errors) or "no queries ran",
        }), flush=True)
        return
    queries = [d for d in detail.values() if "vs_pandas" in d]
    if queries:
        vals = [d["rows_per_sec"] for d in queries]
        ratios = [d["vs_pandas"] for d in queries]
        geomean = float(np.exp(np.mean(np.log(vals))))
        geomean_ratio = float(np.exp(np.mean(np.log(ratios))))
    else:
        geomean, geomean_ratio = 0.0, 0.0
    # vs_colexec_est: the measured-denominator ratio (BASELINE.md "Measured
    # baseline"): 8-vCPU colexec est. = pandas_1core/8, so the north-star
    # ">=10x the 8-vCPU baseline" is vs_colexec_est >= 10 == vs_pandas >= 80
    for d in queries:
        d["vs_colexec_est"] = round(d["vs_pandas"] / 8.0, 4)
    out = {
        "metric": (f"tpch_sf{_partial['sf']:g}_{_partial['platform']}"
                   "_geomean_rows_per_sec"),
        "value": round(geomean),
        "unit": "rows/sec",
        "vs_baseline": round(geomean_ratio, 3),
        "vs_colexec_est": round(geomean_ratio / 8.0, 4),
        # host class stamps the run so regression checks compare like
        # with like: a cpu-fallback run regressing against a TPU
        # baseline (or an 8-vCPU box against a 96-vCPU one) is noise,
        # not a regression
        "host_class": (f"{sys.platform}-{os.cpu_count()}cpu-"
                       f"{_partial['platform']}"),
        "detail": detail,
    }
    # cold/warm split (compile wall vs steady serving): cold is the sum of
    # first-run times; warm is the sum of steady-state medians
    colds = [d["cold_s"] for d in queries if "cold_s" in d]
    warms = [d["warm_ms"] for d in queries if "warm_ms" in d]
    if colds:
        out["cold_total_s"] = round(sum(colds), 1)
    if warms:
        out["warm_total_ms"] = round(sum(warms), 1)
    if errors:
        out["error"] = "; ".join(errors)
    if not final:
        out["note"] = "partial: deadline hit before full ladder"
    print(json.dumps(out), flush=True)


def _worker(job: str) -> None:
    """Run ONE ladder item in THIS process (spawned by main with a hard
    timeout): init the backend, load cached data, run the query + pandas
    baseline, print one JSON result line on stdout. Isolation is the point —
    the r4 tunnel wedged *inside* q1's first compile (28 min, zero CPU, no
    exception to catch), so each item must be killable without losing the
    ladder, and each retry gets a fresh PJRT connection."""
    sf = float(os.environ.get("TPCH_SF", "1.0"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # JAX_PLATFORMS=cpu is NOT enough: the injected plugin dials the
        # hardware tunnel even then (and hangs when it's wedged) — the
        # factory must be dropped before any device touch
        from cockroach_tpu.utils.backend import force_cpu_backend

        force_cpu_backend()
    import jax  # noqa: F401  (backend chosen by env set in parent)

    if not job.startswith("warmup_"):
        # the warmup A/B measures the COLD wall: the persistent XLA cache
        # would let the off phase ride compiles minted by earlier jobs
        # (or the on phase ride the off phase's), hollowing out both sides
        from cockroach_tpu.utils.backend import enable_compile_cache

        enable_compile_cache()
    platform = jax.devices()[0].platform
    if job.startswith("warmup_"):
        # cold-start kill A/B: each phase is its own worker process, so
        # the process-global kernel cache starts empty both times
        from cockroach_tpu.bench.warmup import run_warmup_cold

        w = run_warmup_cold(
            menu=job.endswith("_on"),
            sf=float(os.environ.get("BENCH_WARMUP_SF", "0.05")),
        )
        print("RESULT " + json.dumps({
            "job": job, "platform": platform, **w,
        }), flush=True)
        return
    if job == "ycsb":
        from cockroach_tpu.bench.ycsb import run_ycsb_e

        y = run_ycsb_e(n_keys=1 << 20, ops=512, scan_len=64,
                       concurrency=128)
        print("RESULT " + json.dumps({
            "job": job, "platform": platform,
            "load_keys_per_sec": y["load_keys_per_sec"],
            "put_keys_per_sec": y["put_keys_per_sec"],
            "ingest_speedup": y["ingest_speedup"],
            "bit_identical": y["bit_identical"],
            "scan_rows_per_sec": round(y["rows_per_sec"]),
            "ops_per_sec": round(y["ops_per_sec"], 1),
            "point_ops_per_sec": y["point_ops_per_sec"],
            "blockcache_hit_rate": y["blockcache_hit_rate"],
            "bloom_skips": y["bloom_skips"],
            "compactions": y["compactions"],
        }), flush=True)
        return
    if job == "fanout":
        # changefeed fan-out plane: ~1k mixed subscribers (fast / slow /
        # flapping) against one hub — sustained delivery, end-to-end lag,
        # eviction counts, peak fan-out memory, exactly-once oracle
        from cockroach_tpu.bench.fanout import run_fanout

        f = run_fanout(
            subscribers=int(os.environ.get("BENCH_FANOUT_SUBS", "1000")),
            duration_s=float(os.environ.get("BENCH_FANOUT_S", "10")),
        )
        print("RESULT " + json.dumps({
            "job": job, "platform": platform, **f,
        }), flush=True)
        return
    if job == "views":
        # matview maintenance plane: ~1k standing views (one shape class)
        # against a mixed write stream — refresh lag p50/p99, fused
        # dispatches per flush (O(kernels), not O(views)), delta-vs-
        # rescan ratio, sampled bit-identity oracle
        from cockroach_tpu.bench.views import run_views

        v = run_views(
            views=int(os.environ.get("BENCH_VIEWS_N", "1000")),
            rounds=int(os.environ.get("BENCH_VIEWS_ROUNDS", "8")),
        )
        print("RESULT " + json.dumps({
            "job": job, "platform": platform, **v,
        }), flush=True)
        return
    if job == "load":
        # mixed-workload serving load (ROADMAP 3(c)): N concurrent sessions
        # x (YCSB point ops + TPC-H analytics) through the full SQL front
        # door, measuring throughput, admission queue-wait, and peak HBM
        from cockroach_tpu.bench.load import (run_coalesce_ab,
                                              run_mixed_load,
                                              run_tenant_overload)

        r = run_mixed_load(
            sessions=int(os.environ.get("BENCH_LOAD_SESSIONS", "8")),
            duration_s=float(os.environ.get("BENCH_LOAD_S", "10")),
            sf=float(os.environ.get("BENCH_LOAD_SF", "0.01")),
        )
        # multi-tenant overload oracle rides the same worker: well-behaved
        # vs noisy tenant past saturation — goodput must stay flat, every
        # refusal typed (53300), per-tenant p99 isolation must hold
        ovl = run_tenant_overload(
            duration_s=float(os.environ.get("BENCH_OVERLOAD_S", "8")),
        )
        # cross-session coalescing A/B (same worker: it is the other half
        # of the serving-path story): off vs on over a fsync WAL store,
        # interleaved rounds, plus the coalesced-vs-solo bit-identity
        # oracle check_bench_regress.py enforces
        ab = run_coalesce_ab(
            sessions=int(os.environ.get("BENCH_COALESCE_SESSIONS", "16")),
            duration_s=float(os.environ.get("BENCH_COALESCE_S", "2.0")),
        )
        print("RESULT " + json.dumps({
            "job": job, "platform": platform,
            "sessions": r["sessions"],
            "ops_per_sec": r["ops_per_sec"],
            "point_ops": r["point_ops"],
            "analytic_ops": r["analytic_ops"],
            "inserts": r["inserts"],
            "conflicts": r["conflicts"],
            "errors": r["errors"],
            "p50_queue_wait_ms": r["p50_queue_wait_ms"],
            "p99_queue_wait_ms": r["p99_queue_wait_ms"],
            "admission_waits": r["admission_waits"],
            "admission_timeouts": r["admission_timeouts"],
            "peak_hbm_bytes": r["peak_hbm_bytes"],
            "spills": r["spills"],
            "drain_failures": r["drain_failures"],
            "shed": r["shed"],
            **{f"overload_{k}": v for k, v in ovl.items()
               if k not in ("last_error", "rejections_by_reason")},
            **ab,
        }), flush=True)
        return
    from cockroach_tpu.bench import tpch

    t0 = time.time()
    cat = tpch.gen_tpch_cached(sf=sf)
    nrows = cat.get("lineitem").num_rows
    print(f"# gen/load sf={sf}: {nrows} lineitems in {time.time()-t0:.1f}s "
          f"on {platform}", file=sys.stderr, flush=True)
    rps, ratio, cold, warm = _bench_query(job, cat, nrows, runs)
    print("RESULT " + json.dumps({
        "job": job, "platform": platform,
        "rows_per_sec": round(rps),
        "vs_pandas": round(ratio, 3),
        "cold_s": round(cold, 1),
        "warmup_s": round(warm, 1),
        "warm_ms": round(nrows / rps * 1e3, 1),
    }), flush=True)


def _run_worker(job: str, timeout_s: float, env: dict) -> dict | None:
    """Spawn a worker for one ladder item; returns its parsed RESULT dict or
    None (error recorded in _partial). Worker stderr passes through."""
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", job],
            env=env, timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired as e:
        tail = (e.stderr or b"")
        tail = (tail.decode(errors="replace") if isinstance(tail, bytes)
                else tail).strip().splitlines()[-3:]
        _partial["errors"].append(
            f"{job}: worker timed out after {timeout_s:.0f}s"
            + (f" (last: {' | '.join(tail)})" if tail else "")
        )
        print(f"# {job} worker TIMED OUT ({timeout_s:.0f}s)",
              file=sys.stderr, flush=True)
        return None
    for line in (out.stderr or "").splitlines():
        print(line, file=sys.stderr, flush=True)
    for line in (out.stdout or "").splitlines():
        if line.startswith("RESULT "):
            print(f"# {job} done in {time.time()-t0:.0f}s",
                  file=sys.stderr, flush=True)
            return json.loads(line[len("RESULT "):])
    tail = (out.stderr or "").strip().splitlines()[-3:]
    _partial["errors"].append(
        f"{job}: worker rc={out.returncode}: {' | '.join(tail)}"
    )
    return None


def main(only_job: str | None = None) -> None:
    sf = float(os.environ.get("TPCH_SF", "1.0"))
    deadline_s = float(os.environ.get("BENCH_TOTAL_S", "2700"))
    # north-star ladder (BASELINE.md): Q3/Q9/Q18 + the Q1 single-table base
    qnames = [q.strip() for q in
              os.environ.get("BENCH_QUERY", "q1,q3,q18,q9").split(",")
              if q.strip()]
    _partial["sf"] = sf
    start = time.time()

    # probe (subprocess-isolated) but DO NOT init in this process: the
    # parent must stay off-device so a wedged tunnel can only ever stall a
    # killable worker, never the emitter of the final JSON line
    window_s = float(os.environ.get("BENCH_TPU_WINDOW_S", "900"))
    timeout_s = float(os.environ.get("BENCH_BACKEND_TIMEOUT", "120"))
    platform = None
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        platform = "cpu"
    else:
        t0 = time.time()
        attempt = 0
        while time.time() - t0 < window_s:
            attempt += 1
            remaining = window_s - (time.time() - t0)
            platform = _probe_backend(min(timeout_s, max(30.0, remaining)))
            if platform is not None:
                print(f"# backend probe ok on attempt {attempt}: {platform}",
                      file=sys.stderr, flush=True)
                break
            timeout_s = min(timeout_s * 1.5, 300.0)
            time.sleep(min(20.0, max(0.0, window_s - (time.time() - t0))))
        if platform is None:
            _partial["errors"].append(
                f"tpu unreachable for {window_s:.0f}s ({attempt} probes): "
                + "; ".join(_probe_diag[-3:])
            )
    env = dict(os.environ)
    if platform is None or platform == "cpu":
        platform = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_FORCE_CPU"] = "1"
        if "TPCH_SF" not in os.environ:
            # TPU unreachable: record a complete CPU ladder at a scale the
            # deadline can hold rather than a partial one at SF1. SF0.5
            # (not 0.2): per-query host dispatch overhead (~120ms across
            # a 15-operator pipeline) dominates at SF0.2 and pins q9 to
            # pandas parity, while at SF0.5+ the engine pulls ahead on
            # every ladder query (SF1 measured: q9 4.6x) — and the warm
            # ladder still finishes in well under half the deadline
            sf = 0.5
            print(f"# cpu fallback: dropping to sf={sf}", file=sys.stderr,
                  flush=True)
    env["TPCH_SF"] = f"{sf:g}"
    _partial["sf"] = sf
    _partial["platform"] = platform

    jobs = list(qnames)
    if os.environ.get("BENCH_YCSB", "1") != "0":
        jobs.append("ycsb")
    if os.environ.get("BENCH_LOAD", "1") != "0":
        jobs.append("load")
    if os.environ.get("BENCH_FANOUT", "1") != "0":
        jobs.append("fanout")
    if os.environ.get("BENCH_VIEWS", "1") != "0":
        jobs.append("views")
    if os.environ.get("BENCH_WARMUP", "1") != "0":
        # two phases, two processes: each side's kernel cache starts cold
        jobs.extend(["warmup_off", "warmup_on"])
    if only_job is not None:
        # --job <name>: run exactly that ladder item (e.g. `bench.py --job
        # load` for the mixed-workload serving run) with the same worker
        # isolation + RESULT protocol as the full ladder
        jobs = (["warmup_off", "warmup_on"] if only_job == "warmup"
                else [only_job])

    def record(res) -> None:
        _partial["platform"] = res.pop("platform", platform)
        job_name = res.pop("job")
        if job_name == "ycsb":
            _partial["detail"]["ycsb_e_1m"] = res
        elif job_name == "load":
            _partial["detail"]["mixed_load"] = res
        elif job_name.startswith("warmup_"):
            # pair the two phases into one A/B block once both land
            w = _partial["detail"].setdefault("warmup", {})
            w[job_name[len("warmup_"):]] = res
            if "off" in w and "on" in w:
                off_c = w["off"].get("cold_s", 0.0)
                on_c = w["on"].get("cold_s", 0.0)
                w["cold_menu_speedup"] = (round(off_c / on_c, 2)
                                          if on_c > 0 else 0.0)
                w["serving_compiles_on"] = w["on"].get(
                    "serving_compiles", -1)
                # bit-identity: a menu-warmed kernel must return exactly
                # what a cold-compiled one returns
                w["menu_oracle_ok"] = (
                    w["off"].get("checksums") == w["on"].get("checksums"))
        else:
            _partial["detail"][job_name] = res

    failed: list[str] = []
    for i, job in enumerate(jobs):
        remaining = deadline_s - (time.time() - start) - 30.0
        if remaining < 60.0:
            _partial["errors"].append(
                f"{job}: skipped (deadline: {remaining:.0f}s left)"
            )
            continue
        # even budget over what's left, floored so one slot can absorb a
        # long first compile; a wedged worker forfeits only its own slot
        budget = max(300.0, remaining / (len(jobs) - i))
        budget = min(budget, remaining)
        res = _run_worker(job, budget, env)
        if res is None:
            failed.append(job)
            continue
        record(res)
    # second pass: a worker that died mid-cold-compile left its finished
    # kernels in the persistent cache (.jax_cache), so a retry skips them
    # and usually fits easily in whatever deadline remains. Budget splits
    # across the remaining retries — one wedged retry must forfeit only
    # its own share, same as the first pass
    for i, job in enumerate(failed):
        remaining = deadline_s - (time.time() - start) - 30.0
        if remaining < 120.0:
            _partial["errors"].append(
                f"{job}: retry skipped (deadline: {remaining:.0f}s left)"
            )
            continue
        budget = max(120.0, remaining / (len(failed) - i))
        print(f"# retrying {job} (cache warmed by first attempt, "
              f"{budget:.0f}s)", file=sys.stderr, flush=True)
        res = _run_worker(job, budget, env)
        if res is not None:
            record(res)
    _emit(final=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        try:
            _worker(sys.argv[2])
        except BaseException as e:
            print(f"# worker {sys.argv[2]} failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
            sys.exit(1)
        sys.exit(0)
    _only = None
    if len(sys.argv) >= 3 and sys.argv[1] == "--job":
        _only = sys.argv[2]
    try:
        main(_only)
    except BaseException as e:  # ALWAYS emit one parseable JSON line
        print(json.dumps({
            "metric": "tpch_bench_failed",
            "value": 0,
            "unit": "rows/sec",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }), flush=True)
        if isinstance(e, KeyboardInterrupt):
            raise
        sys.exit(0)
