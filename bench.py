"""Benchmark driver — the TPC-H north-star ladder on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

value: geomean over the query ladder (default q1,q3,q9,q18 — BASELINE.md's
north-star queries) of lineitem rows/sec through each full pipeline, each the
median of BENCH_RUNS timed runs after a compile warm-up. Per-query numbers
are in "detail".

vs_baseline: geomean ratio against a single-host pandas implementation of the
same queries measured in-process (the reference's 8-vCPU colexec baseline
cannot be executed in this image — no Go toolchain; pandas columnar eval is
the closest measurable stand-in and is itself vectorized C). Every engine
result is asserted equal to the pandas result before timing counts.

On any unrecoverable failure, still emits one JSON line with an "error" field.

Env knobs: TPCH_SF (default 1.0), BENCH_RUNS (default 3), BENCH_QUERY
(comma-separated, default "q1,q3,q18,q9" — q9's five-way
join compiles longest and runs last so a cold cache cannot starve the rest
of the ladder), BENCH_BACKEND_RETRIES,
BENCH_BACKEND_TIMEOUT (seconds for the subprocess backend probe).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def _scrub_to_cpu() -> None:
    """Drop every non-CPU backend so a broken accelerator plugin cannot hang
    or crash the bench."""
    from cockroach_tpu.utils.backend import force_cpu_backend

    force_cpu_backend()


_probe_diag: list[str] = []


def _probe_backend(timeout_s: float) -> str | None:
    """Initialize the default JAX backend in a THROWAWAY SUBPROCESS so that a
    hung accelerator tunnel (the round-1 failure mode: the injected TPU
    plugin blocked forever in jax.devices()) cannot take down the bench.
    Returns the platform name on success, else None; failures append an
    attributable line (timeout vs stderr tail) to _probe_diag, which lands
    in the emitted JSON when the whole window comes up dry."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, timeout=timeout_s, text=True,
        )
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"").decode(errors="replace").strip()
                .splitlines()[-2:])
        _probe_diag.append(
            f"probe timed out after {timeout_s:.0f}s"
            + (f" (stderr: {' | '.join(tail)})" if tail else "")
        )
        print(f"# backend probe timed out ({timeout_s:.0f}s)",
              file=sys.stderr, flush=True)
        return None
    if out.returncode == 0 and out.stdout.strip():
        return out.stdout.strip().splitlines()[-1]
    tail = (out.stderr or "").strip().splitlines()[-3:]
    _probe_diag.append(f"probe rc={out.returncode}: {' | '.join(tail)}")
    print(f"# backend probe failed rc={out.returncode}: {' | '.join(tail)}",
          file=sys.stderr, flush=True)
    return None


def _init_backend():
    """Backend acquisition. The TPU number IS the deliverable (r1-r3 all
    fell back), so the probe window is wide: repeated subprocess probes with
    growing timeouts across ~BENCH_TPU_WINDOW_S (default 900s — the tunnel
    has been observed to recover server-side on minutes timescales), rather
    than two quick tries. Only after the window is exhausted does the bench
    scrub to CPU, carrying the probes' diagnostics into the emitted JSON so
    a CPU ladder is attributable to a dead tunnel, not a silent default.
    Returns (jax_module, platform_str)."""
    window_s = float(os.environ.get("BENCH_TPU_WINDOW_S", "900"))
    timeout_s = float(os.environ.get("BENCH_BACKEND_TIMEOUT", "120"))
    platform = None
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        platform = "cpu"
    else:
        t0 = time.time()
        attempt = 0
        while time.time() - t0 < window_s:
            attempt += 1
            remaining = window_s - (time.time() - t0)
            platform = _probe_backend(min(timeout_s, max(30.0, remaining)))
            if platform is not None:
                print(f"# backend probe ok on attempt {attempt}: {platform}",
                      file=sys.stderr, flush=True)
                break
            timeout_s = min(timeout_s * 1.5, 300.0)
            time.sleep(min(20.0, max(0.0, window_s - (time.time() - t0))))
        if platform is None:
            _partial["errors"].append(
                "tpu unreachable for "
                f"{window_s:.0f}s ({attempt} probes): "
                + "; ".join(_probe_diag[-3:])
            )
    if platform is None or platform == "cpu":
        _scrub_to_cpu()
    import jax

    # the probe only proves a THROWAWAY subprocess could init the backend;
    # the tunnel can still wedge the in-process init, which except can't
    # catch — a watchdog guarantees the one-JSON-line contract regardless
    watchdog = _start_watchdog(
        timeout_s * 1.5, "in-process backend init hung"
    )
    try:
        # the probe subprocess validated this backend; init in-process
        platform = jax.devices()[0].platform
    except Exception as e:
        # device vanished between probe and init — record a CPU number
        # rather than nothing
        print(f"# in-process backend init failed ({e}); falling back to cpu",
              file=sys.stderr, flush=True)
        _scrub_to_cpu()
        platform = jax.devices()[0].platform
    finally:
        watchdog.cancel()
    return jax, platform


def _start_watchdog(timeout_s: float, what: str):
    """If not cancelled within timeout_s, emit the error JSON line and hard-
    exit (a wedged PJRT init cannot be interrupted from Python)."""
    import threading

    def fire():
        print(json.dumps({
            "metric": "tpch_bench_failed", "value": 0, "unit": "rows/sec",
            "vs_baseline": 0.0, "error": f"watchdog: {what}",
        }), flush=True)
        os._exit(0)

    t = threading.Timer(timeout_s, fire)
    t.daemon = True
    t.start()
    return t


def _pandas_baseline(qname, cat, res) -> float:
    """Run the same query in pandas, assert the engine result matches, and
    return the elapsed seconds (the measured stand-in baseline)."""
    from cockroach_tpu.bench import tpch

    li = tpch.to_pandas(cat, "lineitem")
    if qname == "q1":
        t0 = time.time()
        cutoff = tpch.d("1998-12-01") - 90
        f = li[li.l_shipdate <= cutoff].copy()
        f["disc_price"] = f.l_extendedprice * (1 - f.l_discount)
        f["charge"] = f.disc_price * (1 + f.l_tax)
        base = (
            f.groupby(["l_returnflag", "l_linestatus"])
            .agg(
                sum_qty=("l_quantity", "sum"),
                sum_base_price=("l_extendedprice", "sum"),
                sum_disc_price=("disc_price", "sum"),
                sum_charge=("charge", "sum"),
                avg_qty=("l_quantity", "mean"),
                avg_price=("l_extendedprice", "mean"),
                avg_disc=("l_discount", "mean"),
                count_order=("l_quantity", "size"),
            )
            .sort_index()
        )
        el = time.time() - t0
        for col in ("sum_qty", "sum_base_price", "sum_disc_price",
                    "sum_charge", "avg_qty", "avg_price", "avg_disc",
                    "count_order"):
            np.testing.assert_allclose(
                np.asarray(res[col], dtype=np.float64),
                base[col].to_numpy().astype(np.float64), rtol=1e-9,
            )
        return el
    if qname == "q6":
        t0 = time.time()
        date = tpch.d("1994-01-01")
        f = li[(li.l_shipdate >= date) & (li.l_shipdate < date + 365)
               & (li.l_discount >= 0.05 - 1e-9) & (li.l_discount <= 0.07 + 1e-9)
               & (li.l_quantity < 24)]
        want = (f.l_extendedprice * f.l_discount).sum()
        el = time.time() - t0
        np.testing.assert_allclose(float(res["revenue"][0]), want, rtol=1e-9)
        return el
    if qname == "q3":
        o = tpch.to_pandas(cat, "orders")
        c = tpch.to_pandas(cat, "customer")
        t0 = time.time()
        date = tpch.d("1995-03-15")
        cb = c[c.c_mktsegment == "BUILDING"]
        ob = o[o.o_orderdate < date].merge(
            cb, left_on="o_custkey", right_on="c_custkey")
        lb = li[li.l_shipdate > date]
        j = lb.merge(ob, left_on="l_orderkey", right_on="o_orderkey")
        j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
        want = (
            j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
            .agg(revenue=("revenue", "sum")).reset_index()
            .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
            .head(10)
        )
        el = time.time() - t0
        np.testing.assert_allclose(
            np.asarray(res["revenue"], dtype=np.float64),
            want.revenue.to_numpy(), rtol=1e-9,
        )
        return el
    if qname == "q9":
        import pandas as pd

        o = tpch.to_pandas(cat, "orders")
        s = tpch.to_pandas(cat, "supplier")
        n = tpch.to_pandas(cat, "nation")
        p = tpch.to_pandas(cat, "part")
        ps = tpch.to_pandas(cat, "partsupp")
        t0 = time.time()
        pg = p[p.p_name.str.contains("green")]
        j = (
            li[li.l_partkey.isin(pg.p_partkey)]
            .merge(ps, left_on=["l_partkey", "l_suppkey"],
                   right_on=["ps_partkey", "ps_suppkey"])
            .merge(s, left_on="l_suppkey", right_on="s_suppkey")
            .merge(n, left_on="s_nationkey", right_on="n_nationkey")
            .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        )
        j["o_year"] = pd.to_datetime(
            j.o_orderdate, unit="D", origin="unix"
        ).dt.year
        j["amount"] = (
            j.l_extendedprice * (1 - j.l_discount)
            - j.ps_supplycost * j.l_quantity
        )
        want = (
            j.groupby(["n_name", "o_year"]).agg(sum_profit=("amount", "sum"))
            .reset_index()
            .sort_values(["n_name", "o_year"], ascending=[True, False])
        )
        el = time.time() - t0
        np.testing.assert_allclose(
            np.asarray(res["sum_profit"], dtype=np.float64),
            want.sum_profit.to_numpy(), rtol=1e-9,
        )
        return el
    if qname == "q18":
        o = tpch.to_pandas(cat, "orders")
        c = tpch.to_pandas(cat, "customer")
        t0 = time.time()
        qty = li.groupby("l_orderkey").l_quantity.sum()
        big = qty[qty > 300].index
        j = (
            o[o.o_orderkey.isin(big)]
            .merge(c, left_on="o_custkey", right_on="c_custkey")
            .merge(li, left_on="o_orderkey", right_on="l_orderkey")
        )
        want = (
            j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                       "o_totalprice"])
            .agg(sum_qty=("l_quantity", "sum")).reset_index()
            .sort_values(["o_totalprice", "o_orderdate"],
                         ascending=[False, True])
            .head(100)
        )
        el = time.time() - t0
        np.testing.assert_allclose(
            np.asarray(res["sum_qty"], dtype=np.float64),
            want.sum_qty.to_numpy(), rtol=1e-12,
        )
        return el
    raise ValueError(f"no pandas baseline for {qname}")


def _bench_query(qname, cat, nrows, runs):
    """Median engine time + pandas baseline time for one query.
    Returns (rows_per_sec, ratio_vs_pandas, warmup_s)."""
    from cockroach_tpu.bench import queries as Q
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.plan import builder as plan_builder

    rel = Q.QUERIES[qname](cat)
    # one operator tree, re-initialized per run: its jitted kernels compile
    # during the warm-up run and are reused by every timed run (compiles
    # also land in the persistent cache, so future processes skip them)
    root = plan_builder.build(rel.plan, cat)
    t0 = time.time()
    run_operator(root)
    warmup_s = time.time() - t0
    print(f"# {qname} warmup (compile+upload): {warmup_s:.1f}s",
          file=sys.stderr, flush=True)

    times = []
    for _ in range(runs):
        t0 = time.time()
        res = run_operator(root)
        times.append(time.time() - t0)
    med = sorted(times)[len(times) // 2]
    rows_per_sec = nrows / med

    # pandas baseline of the same query (asserts engine result matches)
    pandas_s = _pandas_baseline(qname, cat, res)
    print(f"# {qname}: engine {med*1e3:.0f}ms "
          f"({rows_per_sec/1e6:.1f}M rows/s); pandas {pandas_s*1e3:.0f}ms",
          file=sys.stderr, flush=True)
    return rows_per_sec, pandas_s / med, warmup_s


_partial = {"detail": {}, "errors": [], "sf": 1.0, "platform": "unknown"}
_emit_lock = __import__("threading").Lock()
_emitted = False


def _emit(final: bool) -> None:
    """Assemble and print the one-line JSON from whatever has completed.
    Guarded so the deadline timer and the main thread can never both print
    (the contract is exactly ONE JSON line)."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
    detail = _partial["detail"]
    errors = list(_partial["errors"])
    if not detail:
        print(json.dumps({
            "metric": "tpch_bench_failed", "value": 0, "unit": "rows/sec",
            "vs_baseline": 0.0,
            "error": "; ".join(errors) or "no queries ran",
        }), flush=True)
        return
    queries = [d for d in detail.values() if "vs_pandas" in d]
    vals = [d["rows_per_sec"] for d in queries]
    ratios = [d["vs_pandas"] for d in queries]
    geomean = float(np.exp(np.mean(np.log(vals))))
    geomean_ratio = float(np.exp(np.mean(np.log(ratios))))
    out = {
        "metric": (f"tpch_sf{_partial['sf']:g}_{_partial['platform']}"
                   "_geomean_rows_per_sec"),
        "value": round(geomean),
        "unit": "rows/sec",
        "vs_baseline": round(geomean_ratio, 3),
        "detail": detail,
    }
    if errors:
        out["error"] = "; ".join(errors)
    if not final:
        out["note"] = "partial: deadline hit before full ladder"
    print(json.dumps(out), flush=True)


def main() -> None:
    sf = float(os.environ.get("TPCH_SF", "1.0"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    deadline_s = float(os.environ.get("BENCH_TOTAL_S", "2700"))
    # north-star ladder (BASELINE.md): Q3/Q9/Q18 + the Q1 single-table base
    qnames = [q.strip() for q in
              os.environ.get("BENCH_QUERY", "q1,q3,q18,q9").split(",")
              if q.strip()]
    _partial["sf"] = sf
    start = time.time()

    jax, platform = _init_backend()
    _partial["platform"] = platform
    if platform == "cpu" and "TPCH_SF" not in os.environ:
        # TPU unreachable: record a complete CPU ladder at a scale the
        # deadline can hold rather than a partial one at SF1
        sf = 0.2
        _partial["sf"] = sf
        print(f"# cpu fallback: dropping to sf={sf}", file=sys.stderr,
              flush=True)

    from cockroach_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()

    from cockroach_tpu.bench import tpch

    t0 = time.time()
    cat = tpch.gen_tpch_cached(sf=sf)
    nrows = cat.get("lineitem").num_rows
    print(f"# gen sf={sf}: {nrows} lineitems in {time.time()-t0:.1f}s "
          f"on {platform}", file=sys.stderr, flush=True)

    # the deadline guarantees the one-JSON-line contract even if a compile
    # wedges: emit whatever completed, then hard-exit
    def fire():
        print("# deadline hit — emitting partial result",
              file=sys.stderr, flush=True)
        _emit(final=False)
        os._exit(0)

    import threading

    killer = threading.Timer(max(60.0, deadline_s - (time.time() - start)),
                             fire)
    killer.daemon = True
    killer.start()

    for qname in qnames:
        try:
            rps, ratio, warm = _bench_query(qname, cat, nrows, runs)
            _partial["detail"][qname] = {
                "rows_per_sec": round(rps),
                "vs_pandas": round(ratio, 3),
                "warmup_s": round(warm, 1),
            }
        except Exception as e:  # keep benching the rest of the ladder
            _partial["errors"].append(f"{qname}: {type(e).__name__}: {e}")
            print(f"# {qname} FAILED: {e}", file=sys.stderr, flush=True)

    # BASELINE config #5: YCSB-E at 1M keys (bulk ingest + scan-heavy ops)
    if os.environ.get("BENCH_YCSB", "1") != "0":
        try:
            from cockroach_tpu.bench.ycsb import run_ycsb_e

            y = run_ycsb_e(n_keys=1 << 20, ops=512, scan_len=64,
                           concurrency=128)
            _partial["detail"]["ycsb_e_1m"] = {
                "load_keys_per_sec": y["load_keys_per_sec"],
                "scan_rows_per_sec": round(y["rows_per_sec"]),
                "ops_per_sec": round(y["ops_per_sec"], 1),
                "compactions": y["compactions"],
            }
            print(f"# ycsb-e 1M keys: load {y['load_keys_per_sec']}/s, "
                  f"scans {y['rows_per_sec']:.0f} rows/s",
                  file=sys.stderr, flush=True)
        except Exception as e:
            _partial["errors"].append(f"ycsb: {type(e).__name__}: {e}")

    killer.cancel()
    if not _partial["detail"]:
        raise RuntimeError("; ".join(_partial["errors"]) or "no queries ran")
    _emit(final=True)


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # ALWAYS emit one parseable JSON line
        print(json.dumps({
            "metric": "tpch_bench_failed",
            "value": 0,
            "unit": "rows/sec",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }), flush=True)
        if isinstance(e, KeyboardInterrupt):
            raise
        sys.exit(0)
