"""Drive the storage layer on the real TPU: engine write/scan/compact at
multi-tile scale, diffed against a pure-python MVCC model."""

import time

import numpy as np

import jax

print("devices:", jax.devices())

from cockroach_tpu.storage import Engine, WriteIntentError

t0 = time.time()
eng = Engine(val_width=12, memtable_size=8192, l0_trigger=4)
model = {}  # key -> list[(ts, tomb, value)]

rng = np.random.default_rng(7)
N = 30000
keys = [f"user{int(i):05d}".encode() for i in range(4000)]
ts = 0
for step in range(N):
    ts += 1
    k = keys[rng.integers(len(keys))]
    if rng.random() < 0.9:
        v = f"v{step}".encode()
        eng.put(k, v, ts=ts)
        model.setdefault(k, []).append((ts, False, v))
    else:
        eng.delete(k, ts=ts)
        model.setdefault(k, []).append((ts, True, b""))
print(f"wrote {N} ops in {time.time()-t0:.1f}s "
      f"(flushes={eng.stats.flushes} compactions={eng.stats.compactions})")


def model_scan(read_ts, lo=None, hi=None):
    out = []
    for k in sorted(model):
        if lo is not None and k < lo:
            continue
        if hi is not None and k >= hi:
            continue
        vis = [x for x in model[k] if x[0] <= read_ts]
        if vis:
            newest = max(vis, key=lambda x: x[0])
            if not newest[1]:
                out.append((k, newest[2]))
    return out


eng.flush()  # quiesce: scans then reuse the cached runs view
print(f"flushed; runs={len(eng.runs)}", flush=True)
for read_ts in (N, N // 2, N // 10, 1):
    t0 = time.time()
    got = eng.scan(None, None, ts=read_ts)
    want = model_scan(read_ts)
    assert got == want, f"scan@{read_ts}: {len(got)} vs {len(want)} rows"
    print(f"scan@{read_ts}: {len(got)} rows OK in {time.time()-t0:.1f}s",
          flush=True)

# bounded scan + point gets
got = eng.scan(b"user01000", b"user02000", ts=N)
want = model_scan(N, b"user01000", b"user02000")
assert got == want
print(f"bounded scan: {len(got)} rows OK")
for k in (keys[0], keys[-1], b"userXXXXX"):
    vis = [x for x in model.get(k, []) if x[0] <= N]
    newest = max(vis, key=lambda x: x[0]) if vis else None
    want_v = None if newest is None or newest[1] else newest[2]
    assert eng.get(k, ts=N) == want_v
print("point gets OK")

# intents: conflict, own-read, commit, abort
eng.put(b"user00001", b"prov", ts=ts + 1, txn=99)
try:
    eng.scan(None, None, ts=ts + 2)
    raise SystemExit("expected WriteIntentError")
except WriteIntentError as e:
    assert b"user00001" in e.keys
assert eng.get(b"user00001", ts=ts + 2, txn=99) == b"prov"
eng.resolve_intents(txn=99, commit_ts=ts + 2, commit=True)
assert eng.get(b"user00001", ts=ts + 2) == b"prov"
print("intent flow OK")

# full compaction with GC threshold, then re-check latest snapshot
eng.gc_ts = N // 2
eng.compact()
got = eng.scan(None, None, ts=N + 2)
want = model_scan(N + 2)
want = [(k, v) for k, v in want]
# user00001 now has prov at ts+2
assert got == sorted(
    {**dict(want), b"user00001": b"prov"}.items()
), "post-GC scan diverged"
print(f"post-GC scan: {len(got)} rows OK; stats={eng.compute_stats()}")

# empty engine edge
e2 = Engine()
assert e2.scan(None, None, ts=5) == [] and e2.get(b"x", ts=5) is None
e2.compact()
print("empty engine OK")
print("ALL STORAGE DRIVES PASSED")
