"""OrderedSyncOp — streaming merge-ordered fan-in."""

import numpy as np

from cockroach_tpu.coldata.batch import from_host
from cockroach_tpu.coldata.types import FLOAT64, INT64, Schema
from cockroach_tpu.flow.operator import Operator
from cockroach_tpu.flow.operators import OrderedSyncOp
from cockroach_tpu.flow.runtime import run_operator
from cockroach_tpu.ops.sort import SortKey

SCHEMA = Schema.of(x=INT64, tag=INT64)


class _SortedSource(Operator):
    """Emits a pre-sorted int stream in tiles of `tile` rows."""

    def __init__(self, values, tag, tile=4, schema=SCHEMA):
        super().__init__()
        self.output_schema = schema
        self.dictionaries = {}
        self.col_stats = {}
        self.values = list(values)
        self.tag = tag
        self.tile = tile
        self.pulls = 0

    def init(self):
        self._i = 0
        self._initialized = True

    def _next(self):
        if self._i >= len(self.values):
            return None
        chunk = self.values[self._i:self._i + self.tile]
        self._i += len(chunk)
        self.pulls += 1
        return from_host(self.output_schema, {
            "x": np.array(chunk),
            "tag": np.full(len(chunk), self.tag),
        })


def _merge(sources, keys=None):
    op = OrderedSyncOp(tuple(sources),
                       keys or (SortKey(0),))
    return op, run_operator(op)


def test_merges_sorted_streams_in_order():
    a = _SortedSource([1, 4, 7, 10, 13, 16], tag=0)
    b = _SortedSource([2, 5, 8, 11], tag=1)
    c = _SortedSource([3, 6, 9, 12, 15, 18, 21], tag=2)
    _, out = _merge([a, b, c])
    assert list(out["x"]) == sorted(out["x"])
    assert sorted(out["x"]) == sorted(
        [1, 4, 7, 10, 13, 16, 2, 5, 8, 11, 3, 6, 9, 12, 15, 18, 21])


def test_streams_incrementally_not_spool_everything():
    """The first emitted tile must appear after ONE pull per input (the
    barrier releases rows <= the slowest input's first-tile max), not
    after any input is exhausted."""
    a = _SortedSource(list(range(0, 100, 2)), tag=0, tile=5)
    b = _SortedSource(list(range(1, 100, 2)), tag=1, tile=5)
    op = OrderedSyncOp((a, b), (SortKey(0),))
    op.init()
    assert op._streaming  # single-word int key
    first = op.next_batch()
    assert first is not None
    emitted = int(np.asarray(first.mask).sum())
    assert 0 < emitted <= 10  # roughly the two first tiles' overlap
    assert a.pulls <= 2 and b.pulls <= 2  # nowhere near exhausted
    # draining the rest still yields a globally sorted stream
    xs = list(np.asarray(first.cols[0].data)[np.asarray(first.mask)])
    while True:
        t = op.next_batch()
        if t is None:
            break
        xs.extend(np.asarray(t.cols[0].data)[np.asarray(t.mask)])
    assert xs == sorted(xs) and len(xs) == 100


def test_duplicates_and_uneven_lengths_and_empties():
    a = _SortedSource([5, 5, 5], tag=0)
    b = _SortedSource([], tag=1)
    c = _SortedSource([1, 5, 9, 9, 9, 9, 9], tag=2, tile=2)
    _, out = _merge([a, b, c])
    assert list(out["x"]) == [1, 5, 5, 5, 5, 9, 9, 9, 9, 9]


def test_desc_keys_and_fallback_path():
    # DESC single key still packs into one word -> streaming
    a = _SortedSource([9, 6, 3], tag=0)
    b = _SortedSource([8, 5, 2], tag=1)
    op, out = _merge([a, b], keys=(SortKey(0, desc=True),))
    assert list(out["x"]) == [9, 8, 6, 5, 3, 2]

    # float keys don't bit-pack -> fallback (full sort), same results
    fs = Schema.of(x=FLOAT64, tag=INT64)
    a = _SortedSource([0.5, 1.5, 2.5], tag=0, schema=fs)
    b = _SortedSource([1.0, 2.0], tag=1, schema=fs)
    op = OrderedSyncOp((a, b), (SortKey(0),))
    out = run_operator(op)
    assert not op._streaming
    assert list(out["x"]) == [0.5, 1.0, 1.5, 2.0, 2.5]
