"""AOT kernel menu tests (sql/warmmenu.py).

The PR-19 cold-wall acceptance sweep, sized to stay tier-1-fast: over a
one-rung catalog the menu is 4 ladder statements, so the whole module
compiles a handful of kernels once. Covers: a post-menu first execution
of a ladder-shaped query compiles 0 new kernels and counts as a menu
hit (including on the exact-text memo fast path), results are
bit-identical to cold-compiled ones, the vtable surfaces the rows, and
no warmup thread survives the build (the census/leak discipline)."""

import threading

import numpy as np
import pytest

from cockroach_tpu.catalog import Catalog, Table
from cockroach_tpu.coldata.types import FLOAT64, INT64, Schema
from cockroach_tpu.flow import dispatch
from cockroach_tpu.sql import warmmenu
from cockroach_tpu.sql.session import Session
from cockroach_tpu.utils import metric, settings


def _cat(n=96, seed=11) -> Catalog:
    rng = np.random.default_rng(seed)
    cat = Catalog()
    cat.add(Table(
        name="menu_t",
        schema=Schema(("m_key", "m_val"), (INT64, FLOAT64)),
        columns={
            "m_key": np.arange(n, dtype=np.int64),
            "m_val": rng.uniform(0.0, 5.0, n),
        },
    ))
    return cat


@pytest.fixture(scope="module")
def warmed():
    """One menu build shared by the module (compiles are the cost)."""
    warmmenu.reset()
    cat = _cat()
    boot = Session(catalog=cat)
    settings.set("sql.warmup.menu.enabled", True)
    try:
        run = warmmenu.build_menu(cat, boot.db, block=True)
        yield cat, boot, run
    finally:
        settings.reset("sql.warmup.menu.enabled")
        boot.close()
        warmmenu.reset()


def _menu_threads() -> list[str]:
    return [t.name for t in threading.enumerate()
            if t.name.startswith(("warm-menu", "plan-warmup"))]


def test_menu_compiles_ladder_and_joins_threads(warmed):
    cat, _boot, run = warmed
    rows = warmmenu.menu_rows()
    stmts = warmmenu._ladder_statements(cat)
    assert len(stmts) == 4  # one rung x four operator templates
    assert len(rows) == len(stmts)
    assert all(r["status"] == "compiled" for r in rows)
    assert sum(r["kernels"] for r in rows) > 0
    # block=True joined the pool: the census must be clean (the
    # stop-event + join-in-close discipline; a leaked warmup thread
    # would keep compiling into a node that already started serving)
    run.join(10)
    assert _menu_threads() == []


def test_post_menu_first_execution_compiles_nothing(warmed):
    """The acceptance criterion: menu-on first execution of every
    ladder-shaped statement is pure dispatch — 0 new kernels — and each
    counts as a serving-path menu hit."""
    cat, boot, _run = warmed
    serve = Session(catalog=cat, db=boot.db, bootstrap=False)
    try:
        stmts = warmmenu._ladder_statements(cat)
        hits0 = metric.SQL_WARMUP_MENU_HITS.value
        c0 = dispatch.compiles()
        for s in stmts:
            serve.execute(s)
        assert dispatch.compiles() - c0 == 0
        assert metric.SQL_WARMUP_MENU_HITS.value - hits0 == len(stmts)
        assert sum(r["hits"] for r in warmmenu.menu_rows()) >= len(stmts)
    finally:
        serve.close()


def test_memo_fast_path_counts_menu_hits(warmed):
    """Verbatim repeats take plancache's exact-text memo path; that is
    still a plan-cache hit and must count (the common serving shape —
    without it a warmed node reports zero menu value)."""
    cat, boot, _run = warmed
    serve = Session(catalog=cat, db=boot.db, bootstrap=False)
    try:
        stmt = warmmenu._ladder_statements(cat)[0]
        hits0 = metric.SQL_WARMUP_MENU_HITS.value
        serve.execute(stmt)
        serve.execute(stmt)
        assert metric.SQL_WARMUP_MENU_HITS.value - hits0 == 2
    finally:
        serve.close()


def test_menu_results_bit_identical_to_cold(warmed):
    """A warmed kernel must return byte-identical results to a
    cold-compiled one: rebuild the same catalog data fresh (no menu) and
    compare every ladder statement's columns."""
    cat, boot, _run = warmed
    serve = Session(catalog=cat, db=boot.db, bootstrap=False)
    cold_cat = _cat()
    cold = Session(catalog=cold_cat)
    try:
        for s in warmmenu._ladder_statements(cat):
            warm_out = serve.execute(s)
            cold_out = cold.execute(s)
            assert set(warm_out) == set(cold_out)
            for name in warm_out:
                np.testing.assert_array_equal(
                    np.asarray(warm_out[name]), np.asarray(cold_out[name]),
                    err_msg=f"{s}: {name}")
    finally:
        cold.close()
        serve.close()


def test_vtable_surfaces_menu_rows(warmed):
    cat, boot, _run = warmed
    serve = Session(catalog=cat, db=boot.db, bootstrap=False)
    try:
        out = serve.execute(
            "select fingerprint, status, kernels, hits "
            "from crdb_internal.node_warmup_menu")
        statuses = [str(s) for s in np.asarray(out["status"])]
        assert len(statuses) == 4
        assert all(s == "compiled" for s in statuses)
    finally:
        serve.close()


def test_disabled_menu_is_a_noop():
    cat = _cat(seed=12)
    boot = Session(catalog=cat)
    prev = settings.get("sql.warmup.menu.enabled")
    settings.set("sql.warmup.menu.enabled", False)
    try:
        rows0 = warmmenu.menu_rows()
        assert warmmenu.build_menu(cat, boot.db, block=True) is None
        assert warmmenu.menu_rows() == rows0
        assert _menu_threads() == []
    finally:
        settings.set("sql.warmup.menu.enabled", prev)
        boot.close()
