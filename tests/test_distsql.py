"""Plan-level distribution tests: sql()/Rel queries execute through the
Exchange/Broadcast/Gather SPMD path on the virtual 8-device mesh and must
match the single-device flow engine bit-for-bit (the reference's
local-vs-fakedist logictest config pairing: every query runs under both
configs and must agree)."""

import numpy as np
import pytest

from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpch
from cockroach_tpu.parallel import mesh as mesh_mod
from cockroach_tpu.sql import sql


@pytest.fixture(scope="module")
def cat():
    return tpch.gen_tpch(sf=0.01, seed=11)


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh(8)


def _assert_same(got: dict, want: dict):
    assert set(got.keys()) == set(want.keys())
    for k in want:
        g, w = np.asarray(got[k]), np.asarray(want[k])
        assert g.shape == w.shape, f"{k}: {g.shape} vs {w.shape}"
        if g.dtype.kind == "f" or w.dtype.kind == "f":
            np.testing.assert_allclose(
                g.astype(np.float64), w.astype(np.float64), rtol=1e-9,
                err_msg=k)
        else:
            np.testing.assert_array_equal(g, w, err_msg=k)


def _unordered(res: dict, keys: list[str]) -> dict:
    """Sort a result dict by key columns for order-insensitive compare."""
    order = np.lexsort([np.asarray(res[k]) for k in reversed(keys)])
    return {k: np.asarray(v)[order] for k, v in res.items()}


# ---------------------------------------------------------------------------
# north-star queries through the distributed planner


# minutes of XLA compile on the CPU-emulated 8-device mesh (q13's
# right-join + grouped-count plan); tier-1 skips it, `-m slow` covers it
_COMPILE_HEAVY = {"q13", "q2", "q18", "q21"}


@pytest.mark.parametrize("qname", [
    pytest.param(q, marks=pytest.mark.slow) if q in _COMPILE_HEAVY else q
    for q in sorted(Q.QUERIES)
])
def test_all_tpch_distributed(cat, mesh, qname):
    """22/22: every TPC-H query through distribute()+shard_map on the
    8-device mesh must match the single-device flow engine (the fakedist
    discipline, logictestbase.go:315)."""
    rel = Q.QUERIES[qname](cat)
    want = rel.run()
    got = rel.run_distributed(mesh)
    _assert_same(got, want)


@pytest.mark.parametrize("qname", ["q5", "q6", "q10"])
def test_more_queries_distributed(cat, mesh, qname):
    rel = Q.QUERIES[qname](cat)
    want = rel.run()
    got = rel.run_distributed(mesh)
    _assert_same(got, want)


# ---------------------------------------------------------------------------
# individual stage coverage


def test_distributed_groupby_exchange(cat, mesh):
    """Pure partial->exchange->final aggregation (no dense path: high
    cardinality keys)."""
    rel = sql(cat, """
        select l_orderkey, sum(l_quantity) as q, count(*) as n,
               avg(l_extendedprice) as p
        from lineitem group by l_orderkey
    """)
    txt = rel.explain_distributed()
    assert "exchange" in txt and "mode=partial" in txt and "mode=final" in txt
    got = _unordered(rel.run_distributed(mesh), ["l_orderkey"])
    want = _unordered(rel.run(), ["l_orderkey"])
    _assert_same(got, want)


def test_distributed_scalar_aggregate(cat, mesh):
    rel = sql(cat, """
        select sum(l_extendedprice) as s, min(l_shipdate) as lo,
               max(l_shipdate) as hi, count(*) as n, avg(l_discount) as d
        from lineitem where l_quantity < 25
    """)
    _assert_same(rel.run_distributed(mesh), rel.run())


def test_distributed_distinct(cat, mesh):
    rel = sql(cat, "select distinct l_shipmode from lineitem "
                   "order by l_shipmode")
    _assert_same(rel.run_distributed(mesh), rel.run())


def test_distributed_shuffle_join(cat, mesh):
    """Force the both-sides-exchange join path with broadcast_rows=0."""
    rel = sql(cat, """
        select o_orderpriority, count(*) as n
        from lineitem, orders
        where l_orderkey = o_orderkey and l_shipdate > date '1995-01-01'
        group by o_orderpriority order by o_orderpriority
    """)
    got = rel.run_distributed(mesh, broadcast_rows=0)
    _assert_same(got, rel.run())


def test_distributed_broadcast_join(cat, mesh):
    rel = sql(cat, """
        select n_name, count(*) as n
        from supplier, nation
        where s_nationkey = n_nationkey
        group by n_name order by n desc, n_name
    """)
    txt = rel.explain_distributed()
    assert "broadcast" in txt
    _assert_same(rel.run_distributed(mesh), rel.run())


def test_distributed_window_partition_exchange(cat, mesh):
    from cockroach_tpu.sql.rel import Rel

    rel = Rel.scan(cat, "lineitem",
                   ("l_orderkey", "l_linenumber", "l_quantity"))
    w = rel.window(["l_orderkey"], [("l_linenumber", False)],
                   [("rn", "row_number", None),
                    ("s", "sum", "l_quantity")])
    got = _unordered(w.run_distributed(mesh),
                     ["l_orderkey", "l_linenumber"])
    want = _unordered(w.run(), ["l_orderkey", "l_linenumber"])
    _assert_same(got, want)


def test_distributed_semi_anti_join(cat, mesh):
    rel = sql(cat, """
        select count(*) as n from customer
        where c_custkey not in (select o_custkey from orders)
    """)
    _assert_same(rel.run_distributed(mesh), rel.run())
    rel2 = sql(cat, """
        select count(*) as n from orders
        where o_orderkey in (select l_orderkey from lineitem
                             where l_quantity > 45)
    """)
    _assert_same(rel2.run_distributed(mesh), rel2.run())


def test_overflow_retry_loop(cat, mesh):
    """Maximally-skewed shuffle (every row hashes to ONE key, so one device
    receives the whole table): the first attempt's static buckets overflow,
    the host retry loop doubles capacities until the run is clean, and the
    result is still exact — the contract parallel/shuffle.py promises."""
    from cockroach_tpu.ops import expr as ex
    from cockroach_tpu.coldata.types import INT64
    from cockroach_tpu.parallel.planner import DistributedQuery
    from cockroach_tpu.sql.rel import Rel

    # a GROUP BY on the constant key would NOT overflow: partial aggregation
    # collapses the skew before the shuffle (the design's skew-killer). A
    # window function must ship raw rows, so a constant partition key funnels
    # the entire table onto one device and overflows the static buckets.
    rel = (
        Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
        .project([("k", ex.Const(7, INT64)),
                  ("o", ex.ColRef(0)),
                  ("q", ex.ColRef(1))])
        .window(["k"], [("o", False)], [("s", "sum", "q")])
    )
    q = DistributedQuery(rel.plan, cat, mesh)
    out = q.run()
    assert q.factor > 1, "skewed shuffle must have triggered >=1 retry"
    got_s = np.unique(np.asarray(out["s"]))
    want_s = np.unique(np.asarray(rel.run()["s"]))
    np.testing.assert_array_equal(got_s, want_s)  # whole-partition sum
    assert len(out["s"]) == len(rel.run()["s"])


def test_explain_distributed_stages(cat):
    rel = Q.QUERIES["q3"](cat)
    txt = rel.explain_distributed()
    # Q3 = 3-table join + group-by + sort: every stage class must appear
    assert "exchange" in txt or "broadcast" in txt
    assert "gather" in txt  # final ordered fan-in


@pytest.mark.slow
def test_distributed_topk_avoids_full_gather(cat, mesh):
    """ORDER BY + LIMIT distributes as per-device top-k + small gather +
    sorted merge — the sorttopk.go/OrderedSynchronizer pattern. The plan
    must NOT gather the full result, and results must match exactly."""
    for qname in ("q3", "q18"):
        rel = Q.QUERIES[qname](cat)
        txt = rel.explain_distributed()
        assert "gather" in txt.lower()
        # structural check: plan is Limit(Sort(Gather(Limit(Sort(...)))))
        # — the gather moves per-device top-k rows, not the full result
        from cockroach_tpu.plan import spec as S
        from cockroach_tpu.plan.distribute import distribute

        d = distribute(rel.plan, cat)
        assert isinstance(d, S.Limit) and isinstance(d.input, S.Sort)
        assert isinstance(d.input.input, S.Gather)
        inner = d.input.input.input
        assert isinstance(inner, S.Limit) and isinstance(inner.input, S.Sort)
        want = rel.run()
        got = rel.run_distributed(mesh)
        _assert_same(got, want)


def test_kv_backed_table_distributes(mesh):
    """A KV-engine-backed table participates in the distributed SPMD path:
    the direct-columnar-scan snapshot shards across the mesh like a host
    table (closing r2's 'KV-backed tables cannot distribute')."""
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu import coldata as cd
    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.kv.table import create_kv_table
    from cockroach_tpu.sql.rel import Rel
    from cockroach_tpu.storage import rowcodec
    from cockroach_tpu.storage.lsm import Engine

    schema = cd.Schema.of(id=cd.INT64, grp=cd.INT64, val=cd.DECIMAL(12, 2))
    db = DB(Engine(key_width=16, val_width=rowcodec.value_width(schema),
                   memtable_size=1 << 12), ManualClock())
    kcat = catalog_mod.Catalog()
    t = create_kv_table(kcat, db, "m", schema, pk="id")
    n = 3000
    t.bulk_load({
        "id": np.arange(n),
        "grp": np.arange(n) % 13,
        "val": (np.arange(n) * 7 + 1) % 1000,
    })

    rel = (Rel.scan(kcat, "m", ("grp", "val"))
           .groupby(["grp"], [("s", "sum", "val"), ("c", "count_rows",
                                                    None)])
           .sort([("grp", False)]))
    want = rel.run()
    got = rel.run_distributed(mesh)
    _assert_same(got, want)


def test_distributed_statistical_aggregates(cat, mesh):
    """var/stddev ride the partial (sum, sum_sq, count) staging across the
    Exchange: distributed == single-device to fp tolerance."""
    from cockroach_tpu.sql import sql

    rel = sql(cat, """
        select l_returnflag, stddev(l_quantity) as s,
               var_pop(l_extendedprice) as vp
        from lineitem group by l_returnflag order by l_returnflag
    """)
    want = rel.run()
    got = rel.run_distributed(mesh)
    assert list(got["l_returnflag"]) == list(want["l_returnflag"])
    # fp note: shard-order float summation + the sumsq - n*mean^2
    # cancellation bound the distributed/local agreement near 1e-7 relative
    # (the reference's float aggregates carry the same non-determinism
    # across plan placements)
    np.testing.assert_allclose(np.asarray(got["s"], np.float64),
                               np.asarray(want["s"], np.float64), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got["vp"], np.float64),
                               np.asarray(want["vp"], np.float64),
                               rtol=1e-6)
