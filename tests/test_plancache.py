"""Prepared-plan cache + canonical-shape tests (sql/plancache.py).

Covers the PR-6 acceptance sweep: shape bucketing must be bit-identical
to the unbucketed engine across the fusion matrix, the plan cache must
LRU-evict at its size cap, concurrent sessions must share one cache
safely, and DDL invalidation must never serve a stale plan (the
dropped-index case)."""

import threading

import numpy as np
import pytest

import cockroach_tpu.catalog as catalog_mod
from cockroach_tpu import coldata as cd
from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpcds, tpch
from cockroach_tpu.kv import DB, ManualClock
from cockroach_tpu.sql import Session, plancache
from cockroach_tpu.storage import rowcodec
from cockroach_tpu.storage.lsm import Engine
from cockroach_tpu.utils import settings

_FAST_TPCH = {"q1", "q3", "q6", "q9", "q18"}
_FAST_TPCDS = {"q3", "q42"}


# --------------------------------------------------------------------------
# shape bucketing on/off bit-identity across the fusion matrix


@pytest.fixture(scope="module")
def hcat():
    return tpch.gen_tpch(sf=0.005, seed=7)


@pytest.fixture(scope="module")
def dcat():
    return tpcds.gen_tpcds(sf=0.01)


def _run_bucketed(cat, rel, buckets: bool):
    # the padded device image is pinned per table (__cap__), so a toggle
    # needs the device cache dropped to take effect
    for t in cat.tables.values():
        t._device = None
    settings.set("sql.distsql.fusion.enabled", True)
    settings.set("sql.distsql.shape_buckets.enabled", buckets)
    try:
        return rel.run()
    finally:
        settings.reset("sql.distsql.fusion.enabled")
        settings.reset("sql.distsql.shape_buckets.enabled")
        for t in cat.tables.values():
            t._device = None


def _assert_identical(got, want):
    assert set(got) == set(want)
    for name in want:
        g, w = np.asarray(got[name]), np.asarray(want[name])
        assert g.shape == w.shape, name
        if g.dtype == object or w.dtype == object:
            assert list(g) == list(w), name
        else:
            # bit-identical, not allclose: padding must not leak into
            # results (masked rows only)
            np.testing.assert_array_equal(g, w, err_msg=name)


@pytest.mark.parametrize(
    "qname",
    [pytest.param(q, marks=() if q in _FAST_TPCH else (pytest.mark.slow,))
     for q in sorted(Q.QUERIES)],
)
def test_tpch_bucketing_equivalence(hcat, qname):
    rel = Q.QUERIES[qname](hcat)
    _assert_identical(_run_bucketed(hcat, rel, True),
                      _run_bucketed(hcat, rel, False))


@pytest.mark.parametrize(
    "qname",
    [pytest.param(q, marks=() if q in _FAST_TPCDS else (pytest.mark.slow,))
     for q in sorted(tpcds.QUERIES)],
)
def test_tpcds_bucketing_equivalence(dcat, qname):
    rel = tpcds.QUERIES[qname](dcat)
    _assert_identical(_run_bucketed(dcat, rel, True),
                      _run_bucketed(dcat, rel, False))


# --------------------------------------------------------------------------
# plan cache behavior through the Session


SCHEMA = cd.Schema.of(id=cd.INT64, qty=cd.INT64, grp=cd.INT64)


def _session(n=40):
    db = DB(
        Engine(key_width=24, val_width=rowcodec.value_width(SCHEMA) + 64,
               memtable_size=256),
        ManualClock(),
    )
    cat = catalog_mod.Catalog()
    s = Session(catalog=cat, db=db)
    s.execute("CREATE TABLE items (id INT PRIMARY KEY, qty INT, grp INT)")
    for i in range(n):
        s.execute(
            f"INSERT INTO items VALUES ({i}, {i % 7}, {i % 3})")
    return s


def _cache(sess):
    return plancache.cache_for(sess.catalog)


def test_plan_cache_hit_and_memo():
    s = _session()
    c = _cache(s)
    h0, m0 = c.hits, c.misses
    r1 = s.execute("SELECT qty FROM items WHERE id = 7")
    assert c.misses == m0 + 1
    # different literal, same fingerprint: plan-cache hit, rebind only
    r2 = s.execute("SELECT qty FROM items WHERE id = 8")
    assert c.hits == h0 + 1
    assert list(np.asarray(r1["qty"])) == [0]
    assert list(np.asarray(r2["qty"])) == [1]
    # verbatim repeat: the exact-text memo answers without a parse
    r3 = s.execute("SELECT qty FROM items WHERE id = 8")
    assert list(np.asarray(r3["qty"])) == list(np.asarray(r2["qty"]))


def test_plan_cache_lru_eviction():
    s = _session()
    c = _cache(s)
    c.clear()
    settings.set("sql.plan_cache.size", 2)
    try:
        s.execute("SELECT qty FROM items WHERE id = 1")
        s.execute("SELECT grp FROM items WHERE id = 1")
        assert len(c) == 2
        ev0 = c.evictions
        s.execute("SELECT qty, grp FROM items WHERE id = 1")
        assert len(c) == 2
        assert c.evictions == ev0 + 1
        # the first (least recently used) statement now misses again
        m0 = c.misses
        s.execute("SELECT qty FROM items WHERE id = 2")
        assert c.misses == m0 + 1
    finally:
        settings.reset("sql.plan_cache.size")


def test_plan_cache_concurrent_sessions():
    s1 = _session()
    s2 = Session(catalog=s1.catalog, db=s1.db, bootstrap=False)
    errs = []
    results = {}

    def work(name, sess):
        try:
            out = []
            for i in range(8):
                r = sess.execute(f"SELECT qty FROM items WHERE grp = {i % 3}")
                out.append(sorted(np.asarray(r["qty"]).tolist()))
            results[name] = out
        except Exception as e:  # pragma: no cover - surfaced via errs
            errs.append(e)

    ts = [threading.Thread(target=work, args=("a", s1)),
          threading.Thread(target=work, args=("b", s2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    assert results["a"] == results["b"]
    # both sessions share ONE cache on the catalog
    assert len(_cache(s1)) >= 1
    assert _cache(s1) is _cache(s2)


def test_plan_cache_sees_dml():
    """A cached plan must serve rows written AFTER it was cached (the
    operator tree re-snapshots the table on every run)."""
    s = _session(n=5)
    r1 = s.execute("SELECT qty FROM items WHERE grp = 0")
    n1 = len(np.asarray(r1["qty"]))
    s.execute("INSERT INTO items VALUES (100, 42, 0)")
    r2 = s.execute("SELECT qty FROM items WHERE grp = 0")
    got = sorted(np.asarray(r2["qty"]).tolist())
    assert len(got) == n1 + 1
    assert 42 in got


def test_plan_cache_invalidated_by_ddl_and_never_serves_dropped_index():
    s = _session()
    c = _cache(s)
    r_before = sorted(
        np.asarray(s.execute(
            "SELECT id FROM items WHERE qty = 3")["id"]).tolist())
    v0 = s.catalog.version
    s.execute("CREATE INDEX qty_idx ON items (qty)")
    assert s.catalog.version > v0
    assert len(c) == 0  # DDL evicts every cached plan
    # plan through the index, then drop it: the cached index-scan plan
    # must never serve again
    r_idx = sorted(
        np.asarray(s.execute(
            "SELECT id FROM items WHERE qty = 3")["id"]).tolist())
    assert r_idx == r_before
    s.execute("DROP INDEX qty_idx ON items")
    assert len(c) == 0
    # a row inserted after the drop is invisible to the dropped index's
    # frozen data — a stale plan would miss it
    s.execute("INSERT INTO items VALUES (200, 3, 1)")
    r_after = sorted(
        np.asarray(s.execute(
            "SELECT id FROM items WHERE qty = 3")["id"]).tolist())
    assert r_after == sorted(r_before + [200])


def test_plan_cache_disabled_setting():
    s = _session()
    c = _cache(s)
    c.clear()
    settings.set("sql.plan_cache.enabled", False)
    try:
        s.execute("SELECT qty FROM items WHERE id = 3")
        assert len(c) == 0
    finally:
        settings.reset("sql.plan_cache.enabled")


def test_warmup_thread_precompiles():
    s = _session()
    settings.set("sql.plan_cache.warmup.enabled", True)
    try:
        th = plancache.start_warmup(
            s, statements=["SELECT qty FROM items WHERE id = 5"])
        assert th is not None
        th.join(timeout=120)
        assert not th.is_alive()
        from cockroach_tpu.flow import dispatch

        c0 = dispatch.compiles()
        r = s.execute("SELECT qty FROM items WHERE id = 6")
        assert list(np.asarray(r["qty"])) == [6 % 7]
        assert dispatch.compiles() == c0  # warmed entirely off-path
    finally:
        settings.reset("sql.plan_cache.warmup.enabled")
