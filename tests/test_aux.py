"""Aux subsystem tests: settings registry, tracing, EXPLAIN (ANALYZE),
metamorphic tile-size randomization (SURVEY.md §5 parity: pkg/settings,
pkg/util/tracing, execstats, pkg/util/metamorphic)."""

import numpy as np
import pytest

from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpch
from cockroach_tpu.sql import explain
from cockroach_tpu.utils import settings, tracing


@pytest.fixture(scope="module")
def cat():
    return tpch.gen_tpch(sf=0.002, seed=11)


@pytest.fixture(autouse=True)
def _reset_settings():
    yield
    settings.reset()


def test_settings_registry():
    assert settings.get("sql.distsql.tile_size") == 1 << 20
    settings.set("sql.distsql.tile_size", 1024)
    assert settings.get("sql.distsql.tile_size") == 1024
    with pytest.raises(ValueError):
        settings.set("sql.distsql.tile_size", 1)  # below min
    with pytest.raises(TypeError):
        settings.set("sql.distsql.dense_agg.enabled", "sideways")
    settings.reset("sql.distsql.tile_size")
    assert settings.get("sql.distsql.tile_size") == 1 << 20
    assert "storage.l0_compaction_threshold" in settings.all_settings()


def test_tracing_spans():
    tr = tracing.Tracer()
    with tr.span("root", query="q1") as root:
        with tr.span("child"):
            pass
        with tr.span("child2") as c2:
            c2.record({"rows": 5})
    assert len(tr.finished) == 1
    s = tr.finished[0]
    assert s.name == "root" and len(s.children) == 2
    assert s.children[1].records == [{"rows": 5}]
    assert "root" in s.tree()


def test_explain_plan(cat):
    txt = Q.q3(cat).explain()
    assert "hash-join" in txt and "scan lineitem" in txt
    assert "limit 10" in txt and "group-by" in txt


def test_explain_analyze(cat):
    txt, res = Q.q1(cat).explain_analyze()
    assert "rows=" in txt and "self=" in txt
    # the scan line reports at least as many rows as the final output
    assert len(res["l_returnflag"]) > 0
    first = txt.splitlines()[0]
    assert "sort" in first


def test_explain_sql(cat):
    txt = explain(cat, "explain select count(*) as n from lineitem")
    assert "scalar-group-by" in txt and "scan lineitem" in txt
    txt = explain(
        cat, "explain analyze select count(*) as n from lineitem"
    )
    assert "rows=1" in txt


def test_metamorphic_tile_size(cat, rng):
    """q1 result must be invariant under randomized scan tile size — the
    coldata-batch-size metamorphic constant (coldata/batch.go:86)."""
    base = Q.q1(cat).run()
    chosen = settings.randomize_metamorphic(rng)
    assert "sql.distsql.tile_size" in chosen
    got = Q.q1(cat).run()
    for k in base:
        np.testing.assert_array_equal(np.asarray(base[k]), np.asarray(got[k]),
                                      err_msg=f"{k} under {chosen}")


def test_engine_uses_l0_setting():
    from cockroach_tpu.storage import Engine

    settings.set("storage.l0_compaction_threshold", 2)
    eng = Engine(val_width=8, memtable_size=2)
    assert eng.l0_trigger == 2


def test_explain_merge_join_children(cat):
    """EXPLAIN renders MergeJoin with both input subtrees (regression:
    _children treated it as a leaf), and explain_analyze carries stats."""
    from cockroach_tpu.sql.rel import Rel

    li = Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_totalprice"))
    j = li.merge_join(orders, ("l_orderkey", "o_orderkey"))
    txt = j.explain()
    assert "merge-join" in txt
    assert txt.count("scan") == 2  # both children rendered
    txt2, _ = j.explain_analyze()
    assert "merge-join" in txt2 and txt2.count("scan") == 2
    assert "rows=" in txt2


def test_streaming_scan_matches_resident(cat):
    """Tables over sql.distsql.scan_stream_rows stream host->device with
    double buffering instead of materializing in HBM; results are
    identical and the scan demonstrably ran multi-tile."""
    from cockroach_tpu.bench import queries as Q
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.plan import builder as plan_builder
    from cockroach_tpu.utils import settings

    rel = Q.q1(cat)
    want = rel.run()  # resident path

    settings.set("sql.distsql.scan_stream_rows", 1024)
    settings.set("sql.distsql.tile_size", 4096)
    try:
        root = plan_builder.build(rel.plan, cat)
        root.collect_stats(True)
        got = run_operator(root)
    finally:
        settings.reset("sql.distsql.scan_stream_rows")
        settings.reset("sql.distsql.tile_size")

    def find_scan(op):
        from cockroach_tpu.flow.operators import ScanOp

        if isinstance(op, ScanOp):
            return op
        for c in op.children():
            s = find_scan(c)
            if s is not None:
                return s
        return None

    scan = find_scan(root)
    assert scan is not None and scan.streaming
    assert scan.stats.batches > 1, "must have streamed multiple tiles"
    for k in want:
        g, w = np.asarray(got[k]), np.asarray(want[k])
        if g.dtype.kind in ("O", "U", "S"):
            np.testing.assert_array_equal(g, w, err_msg=k)
        else:
            np.testing.assert_allclose(
                g.astype(np.float64), w.astype(np.float64),
                rtol=1e-9, err_msg=k)


def test_query_error_boundary(cat):
    """Engine/kernel failures surface as typed QueryError at the flow
    boundary, never a raw backend traceback (colexecerror/error.go:45);
    expected domain errors pass through unwrapped."""

    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.plan import builder as plan_builder
    from cockroach_tpu.sql.rel import Rel
    from cockroach_tpu.utils.errors import QueryError

    rel = Rel.scan(cat, "lineitem", ("l_orderkey",))
    root = plan_builder.build(rel.plan, cat)

    class Broken:
        output_schema = root.output_schema
        dictionaries = {}
        col_stats = {}

        def init(self):
            pass

        def next_batch(self):
            raise AssertionError("kernel blew up")

        def close(self):
            pass

    with pytest.raises(QueryError) as ei:
        run_operator(Broken())
    assert "kernel blew up" in str(ei.value)
    assert isinstance(ei.value.__cause__, AssertionError)

    # distributed boundary: a plan over a KV table cannot distribute and
    # must surface as a clean QueryError (wrapping the TypeError)
    from cockroach_tpu.utils.errors import register_passthrough
    from cockroach_tpu.kv.txn import TransactionRetryError

    register_passthrough(TransactionRetryError)

    def raises_passthrough():
        raise TransactionRetryError()

    class Passthrough(Broken):
        def next_batch(self):
            raises_passthrough()

    with pytest.raises(TransactionRetryError):
        run_operator(Passthrough())


def test_memory_accounting_drives_spills(cat):
    """Byte budgets (colmem.Allocator analog) trigger the external operator
    swaps: sort spills to the range-partitioned external sort, hash join
    swaps to the Grace partitioner — results unchanged; EXPLAIN ANALYZE
    reports per-operator bytes."""
    from cockroach_tpu.bench import queries as Q
    from cockroach_tpu.flow import operators as flow_ops
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.plan import builder as plan_builder
    from cockroach_tpu.sql.rel import Rel

    rel = Q.q3(cat)
    want = rel.run()

    settings.set("sql.distsql.workmem_bytes", 1 << 16)
    try:
        root = plan_builder.build(rel.plan, cat)
        got = run_operator(root)

        def find(op, cls):
            if isinstance(op, cls):
                return op
            for c in op.children():
                r = find(c, cls)
                if r is not None:
                    return r
            return None

        jo = find(root, flow_ops.HashJoinOp)
        assert jo is not None and jo._grace is not None, \
            "byte budget must have swapped in the Grace hash join"
        # a sort whose input exceeds the byte budget spills externally
        li = Rel.scan(cat, "lineitem", ("l_orderkey", "l_extendedprice"))
        li = li.sort([("l_extendedprice", True)])
        sroot = plan_builder.build(li.plan, cat)
        sgot = run_operator(sroot)
        so = find(sroot, flow_ops.SortOp)
        assert so is not None and so._external is not None, \
            "byte budget must have spilled the sort"
    finally:
        settings.reset("sql.distsql.workmem_bytes")
    # spilled sort result matches the in-memory sort
    np.testing.assert_allclose(
        np.asarray(sgot["l_extendedprice"], np.float64),
        np.asarray(li.run()["l_extendedprice"], np.float64), rtol=0)
    for k in want:
        g, w = np.asarray(got[k]), np.asarray(want[k])
        if g.dtype.kind == "f" or w.dtype.kind == "f":
            np.testing.assert_allclose(g.astype(np.float64),
                                       w.astype(np.float64), rtol=1e-9)
        else:
            np.testing.assert_array_equal(g, w)

    # EXPLAIN ANALYZE surfaces byte accounting per operator
    txt, _ = Q.q1(cat).explain_analyze()
    assert "bytes=" in txt


def test_cli_execute_and_render():
    """The SQL shell (layer-1 CLI analog): statement execution, table
    rendering, errors as messages not tracebacks."""
    from cockroach_tpu import cli
    from cockroach_tpu.sql import Session

    sess = Session()
    out = cli.execute_and_render(sess, "create table t (a int primary key, "
                                       "b float)")
    assert "CREATE TABLE" in out
    out = cli.execute_and_render(sess, "insert into t values (1, 2.5), "
                                       "(2, null)")
    assert "2 row(s)" in out
    out = cli.execute_and_render(sess, "select a, b from t order by a")
    assert "NULL" in out and "(2 rows)" in out and "2.5" in out
    out = cli.execute_and_render(sess, "select nope from t")
    assert out.startswith("ERROR:")
    out = cli.execute_and_render(sess, "explain select a from t where a > 1")
    assert "-> " in out


def test_metrics_registry():
    """pkg/util/metric analog: engine/flow/txn producers feed the default
    registry; scrape() renders prometheus text exposition."""
    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.storage.lsm import Engine
    from cockroach_tpu.utils import metric

    w0 = metric.ENGINE_WRITES.value
    c0 = metric.TXN_COMMITS.value
    db = DB(Engine(key_width=16, val_width=16, memtable_size=8), ManualClock())
    db.txn(lambda t: [t.put(b"k%d" % i, b"v") for i in range(10)])
    assert metric.ENGINE_WRITES.value >= w0 + 10
    assert metric.TXN_COMMITS.value == c0 + 1
    assert len(db.scan(b"k", b"l")) == 10

    text = metric.DEFAULT.scrape()
    assert "# TYPE storage_writes counter" in text
    assert "# TYPE sql_query_seconds histogram" in text
    assert "storage_flushes" in text

    h = metric.Histogram("x_seconds")
    h.observe(0.002)
    h.observe(3.0)
    r = metric.Registry()
    r._metrics["x_seconds"] = h
    out = r.scrape()
    assert 'x_seconds_bucket{le="+Inf"} 2' in out
    assert "x_seconds_count 2" in out


def test_structured_logging(tmp_path):
    """pkg/util/log analog: channelized JSON lines, severity filter,
    redaction markers."""
    import json as _json

    from cockroach_tpu.utils import log

    path = str(tmp_path / "out.log")
    log.set_file(path)
    try:
        log.set_min_severity("INFO")
        log.debug(log.DEV, "dropped")
        log.info(log.STORAGE, "kept", runs=3)
        log._sink.redact = True
        log.warning(log.SENSITIVE_ACCESS, "auth",
                    user=log.Redactable("alice"))
    finally:
        log._sink.redact = False
        log.set_file(None)
    lines = [_json.loads(x) for x in open(path).read().splitlines()]
    assert [x["msg"] for x in lines] == ["kept", "auth"]
    assert lines[0]["ch"] == "STORAGE" and lines[0]["runs"] == 3
    assert lines[1]["user"] == "<redacted>"


def test_admission_work_queue_priorities():
    """util/admission reduction: slots grant strictly by priority order;
    releases hand slots to the highest-priority waiter."""
    import threading

    from cockroach_tpu.utils import admission

    q = admission.WorkQueue(slots=1)
    assert q.admit(admission.NORMAL)
    order = []
    done = []

    def worker(prio, tag):
        q.admit(prio)
        order.append(tag)
        q.release()
        done.append(tag)

    threads = [
        threading.Thread(target=worker, args=(admission.LOW, "low")),
        threading.Thread(target=worker, args=(admission.HIGH, "high")),
        threading.Thread(target=worker, args=(admission.NORMAL, "normal")),
    ]
    for t in threads:
        t.start()
    import time

    deadline = time.time() + 10
    while q.waited < 3 and time.time() < deadline:
        time.sleep(0.01)  # deterministic: wait until all three queued
    assert q.waited == 3
    q.release()
    for t in threads:
        t.join(timeout=5)
    assert order == ["high", "normal", "low"], order
    assert sorted(done) == ["high", "low", "normal"]


def test_admission_io_governor():
    """Write pacing follows L0 run count (io_load_listener shape)."""
    from cockroach_tpu.storage.lsm import Engine
    from cockroach_tpu.utils import admission

    eng = Engine(key_width=16, val_width=16, memtable_size=16,
                 l0_trigger=64)  # don't auto-compact during the test
    gov = admission.IOGovernor(eng, healthy_runs=2,
                               delay_per_run_s=0.0001)
    assert gov.write_delay_s() == 0
    for i in range(16 * 4):
        eng.put(b"k%04d" % i, b"v", ts=i + 1)
    eng.flush_mem_only()
    assert len(eng.runs) >= 3
    assert gov.write_delay_s() > 0
    gov.pace_write()
    assert gov.throttled == 1
    eng.compact(bottom=True)
    assert gov.write_delay_s() == 0


def test_timeseries_db():
    """pkg/ts reduction: metric snapshots persist in KV, query/downsample/
    prune over wall-clock ranges."""
    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.kv.tsdb import TimeSeriesDB
    from cockroach_tpu.storage.lsm import Engine
    from cockroach_tpu.utils import metric

    clock = ManualClock(start=1)
    db = DB(Engine(key_width=48, val_width=32, memtable_size=256), clock)
    ts = TimeSeriesDB(db)
    reg = metric.Registry()
    g = reg.gauge("lsm_runs")
    c = reg.counter("writes")

    for i in range(10):
        g.set(i)
        c.inc(5)
        ts.record(reg)
        clock.advance(1000)  # 1s per sample

    series = ts.query("writes")
    assert len(series) == 10
    assert [v for _, v in series] == [5.0 * (i + 1) for i in range(10)]

    # downsample 5s buckets, avg of gauge values 0..4 and 5..9
    ds = ts.downsample("lsm_runs", bucket_ms=5000, agg="avg")
    assert len(ds) in (2, 3)
    assert abs(ds[0][1] - np.mean(range(5))) < 2.0

    # retention: prune the first half
    half = series[5][0]
    dropped = ts.prune("writes", keep_after_ms=half)
    assert dropped == 5
    assert len(ts.query("writes")) == 5


def test_explain_distsql(cat):
    """EXPLAIN (DISTSQL) renders the distribution stages (Exchange /
    broadcast / gather placements) from SQL text."""
    from cockroach_tpu.sql import explain

    txt = explain(cat, "explain (distsql) "
                       "select l_returnflag, count(*) from lineitem "
                       "group by l_returnflag")
    assert "scan lineitem" in txt
    txt2 = explain(
        cat, "explain (distsql) "
             "select o_orderkey, count(*) as c from orders, lineitem "
             "where o_orderkey = l_orderkey group by o_orderkey "
             "order by c desc limit 5")
    assert "gather" in txt2.lower() or "exchange" in txt2.lower()
