"""Join kernel tests vs numpy oracle (reference analog:
pkg/sql/colexec/hashjoiner_test.go + columnar_operators_test.go oracle)."""

import numpy as np
import pytest

from cockroach_tpu import coldata as cd
from cockroach_tpu.ops import join as jn


def make_tables(rng, np_build=40, np_probe=100, key_range=30, null_frac=0.1):
    bschema = cd.Schema.of(bk=cd.INT64, bv=cd.INT64)
    pschema = cd.Schema.of(pk=cd.INT64, pv=cd.INT64)
    bk = rng.integers(0, key_range, np_build)
    pk = rng.integers(0, key_range, np_probe)
    bkv = rng.random(np_build) > null_frac
    pkv = rng.random(np_probe) > null_frac
    b = cd.from_host(
        bschema,
        {"bk": bk, "bv": np.arange(np_build) * 10},
        valids={"bk": bkv},
        capacity=64,
    )
    p = cd.from_host(
        pschema,
        {"pk": pk, "pv": np.arange(np_probe)},
        valids={"pk": pkv},
        capacity=128,
    )
    return (bschema, b, bk, bkv), (pschema, p, pk, pkv)


def oracle_pairs(pk, pkv, bk, bkv):
    """list of (probe_i, build_j) inner matches."""
    out = []
    for i in range(len(pk)):
        if not pkv[i]:
            continue
        for j in range(len(bk)):
            if bkv[j] and bk[j] == pk[i]:
                out.append((i, j))
    return out


def test_unique_inner_left_semi_anti(rng):
    # unique build keys
    bschema = cd.Schema.of(bk=cd.INT64, bv=cd.INT64)
    pschema = cd.Schema.of(pk=cd.INT64, pv=cd.INT64)
    bk = np.array([1, 3, 5, 7, 9])
    pk = np.array([1, 2, 3, 9, 9, 4, 7])
    pkv = np.array([True, True, True, True, False, True, True])
    b = cd.from_host(bschema, {"bk": bk, "bv": bk * 100}, capacity=8)
    p = cd.from_host(pschema, {"pk": pk, "pv": np.arange(7)}, valids={"pk": pkv}, capacity=16)

    out = jn.hash_join_unique(
        p, pschema, (0,), b, bschema, (0,), jn.JoinSpec("inner", True)
    )
    res = cd.to_host(out, pschema.concat(bschema))
    order = np.argsort(res["pv"])
    np.testing.assert_array_equal(np.asarray(res["pv"])[order], [0, 2, 3, 6])
    np.testing.assert_array_equal(np.asarray(res["bv"])[order], [100, 300, 900, 700])

    out = jn.hash_join_unique(
        p, pschema, (0,), b, bschema, (0,), jn.JoinSpec("left", True)
    )
    res = cd.to_host(out, pschema.concat(bschema))
    assert len(res["pv"]) == 7
    bv_by_pv = dict(zip(res["pv"], res["bv"]))
    assert bv_by_pv[1] is None and bv_by_pv[4] is None  # no match, NULL key
    assert bv_by_pv[0] == 100

    out = jn.hash_join_unique(
        p, pschema, (0,), b, bschema, (0,), jn.JoinSpec("semi", True)
    )
    res = cd.to_host(out, pschema)
    np.testing.assert_array_equal(sorted(res["pv"]), [0, 2, 3, 6])

    out = jn.hash_join_unique(
        p, pschema, (0,), b, bschema, (0,), jn.JoinSpec("anti", True)
    )
    res = cd.to_host(out, pschema)
    # NULL-key probe row 4 is kept by anti join (NOT EXISTS semantics)
    np.testing.assert_array_equal(sorted(res["pv"]), [1, 4, 5])


@pytest.mark.parametrize("join_type", ["inner", "left", "semi", "anti"])
def test_general_join_vs_oracle(rng, join_type):
    (bschema, b, bk, bkv), (pschema, p, pk, pkv) = make_tables(rng)
    out, total = jn.hash_join_general(
        p, pschema, (0,), b, bschema, (0,), jn.JoinSpec(join_type, False), 1024
    )
    pairs = oracle_pairs(pk, pkv, bk, bkv)
    if join_type == "inner":
        res = cd.to_host(out, pschema.concat(bschema))
        got = sorted(zip(res["pv"], res["bv"]))
        want = sorted((pv, bj * 10) for (pv, bj) in pairs)
        assert got == want
        assert int(total) == len(pairs)
    elif join_type == "left":
        res = cd.to_host(out, pschema.concat(bschema))
        matched_p = {i for i, _ in pairs}
        want = sorted((i, j * 10) for i, j in pairs) + sorted(
            (i, None) for i in range(len(pk)) if i not in matched_p
        )
        got = sorted(
            zip(res["pv"], res["bv"]),
            key=lambda t: (t[0], -1 if t[1] is None else t[1]),
        )
        want = sorted(want, key=lambda t: (t[0], -1 if t[1] is None else t[1]))
        assert got == want
    elif join_type == "semi":
        res = cd.to_host(out, pschema)
        assert sorted(res["pv"]) == sorted({i for i, _ in pairs})
    else:
        res = cd.to_host(out, pschema)
        matched_p = {i for i, _ in pairs}
        assert sorted(res["pv"]) == [i for i in range(len(pk)) if i not in matched_p]


def test_general_join_overflow_reports_total(rng):
    bschema = cd.Schema.of(bk=cd.INT64)
    pschema = cd.Schema.of(pk=cd.INT64)
    b = cd.from_host(bschema, {"bk": np.zeros(50, dtype=np.int64)}, capacity=64)
    p = cd.from_host(pschema, {"pk": np.zeros(50, dtype=np.int64)}, capacity=64)
    out, total = jn.hash_join_general(
        p, pschema, (0,), b, bschema, (0,), jn.JoinSpec("inner", False), 128
    )
    assert int(total) == 2500  # caller must rerun with >= 2500 capacity
    out, total = jn.hash_join_general(
        p, pschema, (0,), b, bschema, (0,), jn.JoinSpec("inner", False), 4096
    )
    assert int(out.length()) == 2500


def test_string_key_join_cross_dictionary(rng):
    d1 = cd.Dictionary(np.array(["a", "b", "c"], dtype=object))
    d2 = cd.Dictionary(np.array(["c", "a"], dtype=object))
    pschema = cd.Schema.of(s=cd.STRING, pv=cd.INT64)
    bschema = cd.Schema.of(t=cd.STRING, bv=cd.INT64)
    p = cd.from_host(
        pschema,
        {"s": np.array([0, 1, 2], dtype=np.int32), "pv": np.arange(3)},
        capacity=8,
    )
    b = cd.from_host(
        bschema,
        {"t": np.array([0, 1], dtype=np.int32), "bv": np.array([100, 200])},
        capacity=8,
    )
    out = jn.hash_join_unique(
        p,
        pschema,
        (0,),
        b,
        bschema,
        (0,),
        jn.JoinSpec("inner", True),
        probe_hash_tables={0: d1.hashes},
        build_hash_tables={0: d2.hashes},
        # plan-time remap: build codes -> probe dictionary codes
        build_code_remaps={0: np.array([d1.code_of(str(v)) for v in d2.values])},
    )
    res = cd.to_host(out, pschema.concat(bschema), dictionaries={0: d1})
    got = sorted(zip(res["s"], res["bv"]))
    assert got == [("a", 200), ("c", 100)]


# ---------------------------------------------------------------------------
# round 2: right/full outer, cross join, UNION ALL


@pytest.fixture(scope="module")
def outer_cat():
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, STRING, Schema

    c = catalog_mod.Catalog()
    c.add(catalog_mod.Table.from_strings(
        "l", Schema.of(lk=INT64, lv=INT64, ls=STRING),
        {"lk": np.array([1, 2, 2, 3, 5]),
         "lv": np.array([10, 20, 21, 30, 50]),
         "ls": np.array(["a", "b", "b", "c", "e"], dtype=object)},
    ))
    c.add(catalog_mod.Table.from_strings(
        "r", Schema.of(rk=INT64, rv=INT64, rs=STRING),
        {"rk": np.array([2, 3, 3, 4]),
         "rv": np.array([200, 300, 301, 400]),
         "rs": np.array(["x", "y", "y", "z"], dtype=object)},
    ))
    return c


def _pd(cat, name):
    from cockroach_tpu.coldata.batch import to_host
    import pandas as pd

    t = cat.get(name)
    b = t.device_batch()
    return pd.DataFrame(to_host(b, t.schema, t.dict_by_index()))


def test_right_outer_join(outer_cat):
    from cockroach_tpu.sql.rel import Rel

    l = Rel.scan(outer_cat, "l")
    r = Rel.scan(outer_cat, "r")
    res = l.join(r, on=[("lk", "rk")], how="right",
                 build_unique=False).run()
    want = _pd(outer_cat, "l").merge(
        _pd(outer_cat, "r"), left_on="lk", right_on="rk", how="right")
    assert len(res["rk"]) == len(want)
    got = sorted(zip(res["rv"], [x if x is not None else -1
                                 for x in res["lv"]]))
    exp = sorted(zip(want.rv, want.lv.fillna(-1).astype(int)))
    assert got == exp
    # null-extended probe STRING decodes to None
    nulls = [s for v, s in zip(res["lv"], res["ls"]) if v is None]
    assert nulls and all(s is None for s in nulls)


def test_full_outer_join(outer_cat):
    from cockroach_tpu.sql.rel import Rel

    l = Rel.scan(outer_cat, "l")
    r = Rel.scan(outer_cat, "r")
    res = l.join(r, on=[("lk", "rk")], how="full",
                 build_unique=False).run()
    want = _pd(outer_cat, "l").merge(
        _pd(outer_cat, "r"), left_on="lk", right_on="rk", how="outer")
    assert len(res["lk"]) == len(want)
    got = sorted(((-1 if a is None else a), (-1 if b is None else b))
                 for a, b in zip(res["lv"], res["rv"]))
    exp = sorted(zip(want.lv.fillna(-1).astype(int),
                     want.rv.fillna(-1).astype(int)))
    assert got == exp


def test_cross_join(outer_cat):
    from cockroach_tpu.sql.rel import Rel

    l = Rel.scan(outer_cat, "l", ("lk", "lv"))
    r = Rel.scan(outer_cat, "r", ("rk", "rs"))
    res = l.cross_join(r).run()
    assert len(res["lk"]) == 5 * 4
    got = sorted(zip(res["lv"], res["rk"]))
    exp = sorted((lv, rk) for lv in [10, 20, 21, 30, 50]
                 for rk in [2, 3, 3, 4])
    assert got == exp
    assert set(res["rs"]) == {"x", "y", "z"}  # dict decodes across product


def test_union_all(outer_cat):
    from cockroach_tpu.sql.rel import Rel

    from cockroach_tpu.ops import expr as ex

    l = Rel.scan(outer_cat, "l", ("lk", "lv"))
    u = l.union_all(l.filter(
        ex.Cmp("gt", l.c("lv"), l.c("lv"))))  # empty second arm
    res = u.run()
    assert sorted(res["lk"]) == [1, 2, 2, 3, 5]
    u2 = l.union_all(l)
    assert sorted(u2.run()["lv"]) == sorted([10, 20, 21, 30, 50] * 2)
    # arity mismatch rejected
    with pytest.raises(ValueError):
        l.union_all(Rel.scan(outer_cat, "r"))


def test_right_full_joins_distributed(outer_cat):
    from cockroach_tpu.parallel import mesh as mesh_mod
    from cockroach_tpu.sql.rel import Rel

    mesh = mesh_mod.make_mesh(8)
    l = Rel.scan(outer_cat, "l", ("lk", "lv"))
    r = Rel.scan(outer_cat, "r", ("rk", "rv"))
    for how in ("right", "full"):
        rel = l.join(r, on=[("lk", "rk")], how=how, build_unique=False)
        got = rel.run_distributed(mesh, broadcast_rows=0)
        want = rel.run()
        key = lambda d: sorted(
            ((-1 if a is None else a), (-1 if b is None else b))
            for a, b in zip(d["lv"], d["rv"]))
        assert key(got) == key(want), how


# ---------------------------------------------------------------------------
# dense direct-addressing paths


def _dense_catalog():
    """dim has an arange PK (dense analytic); child has fanout-2 clustering."""
    from cockroach_tpu.catalog import Catalog, Table

    cat = Catalog()
    n = 50
    cat.add(Table.from_strings(
        "dim", cd.Schema.of(dk=cd.INT64, dv=cd.INT64),
        {"dk": np.arange(1, n + 1), "dv": np.arange(1, n + 1) * 7},
    ))
    cat.add(Table.from_strings(
        "child", cd.Schema.of(ck=cd.INT64, sub=cd.INT64, cv=cd.INT64),
        {"ck": np.repeat(np.arange(1, n + 1), 2),
         "sub": np.tile(np.array([10, 20]), n),
         "cv": np.arange(2 * n)},
    ))
    return cat


def test_dense_key_info_detection():
    cat = _dense_catalog()
    assert cat.get("dim").dense_key_info()["dk"] == (1, 1)
    assert cat.get("child").dense_key_info()["ck"] == (1, 2)
    assert "dv" not in cat.get("dim").dense_key_info()
    assert "sub" not in cat.get("child").dense_key_info()


@pytest.mark.parametrize("jt", ["inner", "left", "semi", "anti"])
def test_analytic_join_vs_sorted(jt, rng):
    """HashJoinOp with an analytic dense build must equal the sorted-index
    fallback, including out-of-range probe keys and a filtered build."""
    from cockroach_tpu.catalog import Catalog, Table
    from cockroach_tpu.flow import operators as ops
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.ops import expr as ex
    from cockroach_tpu.ops.join import JoinSpec

    cat = _dense_catalog()
    # probe keys include 0 and n+5 (out of build range) and NULLs
    pk = rng.integers(-2, 58, 40)
    pschema = cd.Schema.of(fk=cd.INT64, pv=cd.INT64)
    pkv = rng.random(40) > 0.15
    cat.add(Table.from_strings(
        "probe", pschema,
        {"fk": pk, "pv": np.arange(40)}, valids={"fk": pkv},
    ))

    def build_tree():
        scan = ops.ScanOp(cat.get("dim"))
        # filter keeps dv < 200 — a mask-only chain over the table
        pred = ex.Cmp("lt", ex.ColRef(1), ex.lit(200))
        return ops.FilterOp(scan, pred)

    probe = ops.ScanOp(cat.get("probe"))
    j = ops.HashJoinOp(probe, build_tree(), (0,), (0,),
                       JoinSpec(join_type=jt, build_unique=True))
    j.init()
    assert j._analytic is not None, "analytic path must engage"
    got = run_operator(j)

    probe2 = ops.ScanOp(cat.get("probe"))
    j2 = ops.HashJoinOp(probe2, build_tree(), (0,), (0,),
                        JoinSpec(join_type=jt, build_unique=True))
    j2._plan_analytic = lambda: None  # force the sorted fallback
    j2.init()
    assert j2._analytic is None
    want = run_operator(j2)
    for c in want:
        np.testing.assert_array_equal(got[c], want[c]), c


def test_analytic_clustered_fanout(rng):
    """Composite-key join against the fanout-2 child table."""
    from cockroach_tpu.catalog import Catalog, Table
    from cockroach_tpu.flow import operators as ops
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.ops.join import JoinSpec

    cat = _dense_catalog()
    pk = rng.integers(0, 55, 64)
    sub = rng.choice(np.array([10, 20, 30]), 64)
    pschema = cd.Schema.of(fk=cd.INT64, fsub=cd.INT64, pv=cd.INT64)
    cat.add(Table.from_strings(
        "probe2", pschema,
        {"fk": pk, "fsub": sub, "pv": np.arange(64)},
    ))
    probe = ops.ScanOp(cat.get("probe2"))
    build = ops.ScanOp(cat.get("child"))
    j = ops.HashJoinOp(probe, build, (0, 1), (0, 1),
                       JoinSpec(join_type="inner", build_unique=True))
    j.init()
    assert j._analytic is not None and j._analytic.fanout == 2
    got = run_operator(j)
    # numpy oracle
    child_ck = np.repeat(np.arange(1, 51), 2)
    child_sub = np.tile(np.array([10, 20]), 50)
    child_cv = np.arange(100)
    rows = []
    for i in range(64):
        hit = np.nonzero((child_ck == pk[i]) & (child_sub == sub[i]))[0]
        for h in hit:
            rows.append((pk[i], sub[i], i, child_ck[h], child_sub[h],
                         child_cv[h]))
    want = np.array(sorted(rows))
    got_rows = np.array(sorted(zip(*[got[c] for c in
                                     ("fk", "fsub", "pv", "ck", "sub", "cv")])))
    np.testing.assert_array_equal(got_rows.astype(np.int64),
                                  want.astype(np.int64))
