"""Expression eval tests — selection/projection semantics incl. 3-valued logic
(reference analog: colexecsel/colexecproj generated kernel behavior)."""

import numpy as np

from cockroach_tpu import coldata as cd
from cockroach_tpu.ops import expr as ex


def setup_batch():
    schema = cd.Schema.of(
        a=cd.INT64, b=cd.FLOAT64, d=cd.DECIMAL(10, 2), dt=cd.DATE
    )
    arrays = {
        "a": np.array([1, 2, 3, 4, 5]),
        "b": np.array([0.5, 1.5, 2.5, 3.5, 4.5]),
        "d": np.array([100, 250, 399, 1000, 5]),  # 1.00 2.50 3.99 10.00 0.05
        "dt": np.array([0, 365, 10956, 10957, 19000], dtype=np.int32),
    }
    valids = {"a": np.array([True, True, False, True, True])}
    return schema, cd.from_host(schema, arrays, valids=valids, capacity=8)


def test_filter_cmp_with_nulls():
    schema, b = setup_batch()
    # a > 1 : rows 1,3,4 true; row 2 NULL (excluded); row 0 false
    m = ex.filter_mask(b, schema, ex.Cmp("gt", ex.ColRef(0), ex.lit(1)))
    np.testing.assert_array_equal(np.asarray(m)[:5], [False, True, False, True, True])


def test_decimal_compare_and_arith():
    schema, b = setup_batch()
    # d <= 3.99 -> rows 0,1,2,4
    pred = ex.Cmp("le", ex.ColRef(2), ex.Const(3.99, cd.DECIMAL(10, 2)))
    m = ex.filter_mask(b, schema, pred)
    np.testing.assert_array_equal(np.asarray(m)[:5], [True, True, True, False, True])
    # d * d has scale 4
    t = ex.expr_type(ex.BinOp("*", ex.ColRef(2), ex.ColRef(2)), schema)
    assert t.scale == 4
    d, v = ex.eval_expr(ex.BinOp("*", ex.ColRef(2), ex.ColRef(2)), b.cols, schema)
    assert int(np.asarray(d)[1]) == 62500  # 2.50^2 = 6.25 at scale 4


def test_kleene_and_or():
    schema = cd.Schema.of(x=cd.BOOL, y=cd.BOOL)
    xv = np.array([True, True, True, False, False, False, True, False, True])
    xn = np.array([True, True, True, True, True, True, False, False, False])
    yv = np.array([True, False, False, True, False, True, True, False, False])
    yn = np.array([True, True, False, True, True, False, True, True, False])
    b = cd.from_host(
        schema, {"x": xv, "y": yv}, valids={"x": xn, "y": yn}, capacity=16
    )
    d, v = ex.eval_expr(ex.and_(ex.ColRef(0), ex.ColRef(1)), b.cols, schema)
    d, v = np.asarray(d)[:9], np.asarray(v)[:9]
    # NULL AND false = false (known); NULL AND true = NULL
    assert v[7] and not d[7]  # x NULL, y false -> false
    assert not v[6]  # x NULL, y true -> NULL
    assert not v[8]  # NULL AND NULL -> NULL
    assert v[0] and d[0]
    assert v[1] and not d[1]
    do, vo = ex.eval_expr(ex.or_(ex.ColRef(0), ex.ColRef(1)), b.cols, schema)
    do, vo = np.asarray(do)[:9], np.asarray(vo)[:9]
    assert vo[6] and do[6]  # NULL OR true -> true
    assert not vo[7]  # NULL OR false -> NULL
    assert vo[0] and do[0]


def test_case_and_cast():
    schema, b = setup_batch()
    e = ex.Case(
        whens=((ex.Cmp("gt", ex.ColRef(0), ex.lit(3)), ex.lit(100)),),
        otherwise=ex.lit(0),
    )
    d, v = ex.eval_expr(e, b.cols, schema)
    np.testing.assert_array_equal(np.asarray(d)[:5], [0, 0, 0, 100, 100])
    c = ex.Cast(ex.ColRef(2), cd.FLOAT64)
    d, v = ex.eval_expr(c, b.cols, schema)
    np.testing.assert_allclose(np.asarray(d)[:5], [1.0, 2.5, 3.99, 10.0, 0.05])


def test_extract_year():
    schema, b = setup_batch()
    d, v = ex.eval_expr(ex.ExtractYear(ex.ColRef(3)), b.cols, schema)
    np.testing.assert_array_equal(np.asarray(d)[:5], [1970, 1971, 1999, 2000, 2022])
    # day 10956 = 1999-12-31, day 10957 = 2000-01-01 (7 leap days in 1970-1999)


def test_division_by_zero_is_null():
    schema = cd.Schema.of(x=cd.INT64, y=cd.INT64)
    b = cd.from_host(
        schema, {"x": np.array([10, 10]), "y": np.array([2, 0])}, capacity=4
    )
    d, v = ex.eval_expr(ex.BinOp("/", ex.ColRef(0), ex.ColRef(1)), b.cols, schema)
    assert np.asarray(d)[0] == 5.0
    assert not np.asarray(v)[1]


def test_code_lookup_string_predicate():
    # s LIKE '%an%' pre-evaluated per dictionary code on host
    dic = cd.Dictionary(np.array(["apple", "banana", "mango"], dtype=object))
    table = np.array(["an" in str(s) for s in dic.values])
    schema = cd.Schema.of(s=cd.STRING)
    b = cd.from_host(schema, {"s": np.array([0, 1, 2, 1], dtype=np.int32)}, capacity=8)
    m = ex.filter_mask(b, schema, ex.CodeLookup(col=0, table=table))
    np.testing.assert_array_equal(np.asarray(m)[:4], [False, True, True, True])


def test_scalar_builtins_sql():
    """sem/builtins surface: abs/ceil/floor/round/sign/sqrt/exp/ln,
    coalesce, length, upper/lower — oracle numpy/pandas."""
    import numpy as np

    from cockroach_tpu.bench import tpch
    from cockroach_tpu.sql import sql

    cat = tpch.gen_tpch(sf=0.002, seed=9)
    li = tpch.to_pandas(cat, "lineitem")

    got = sql(cat, """
        select abs(l_quantity - 25.0) as a, ceil(l_discount) as c,
               floor(l_tax) as f, round(l_extendedprice) as r,
               sqrt(l_quantity) as s,
               coalesce(l_quantity, 0) as co
        from lineitem order by l_orderkey, l_linenumber limit 50
    """).run()
    df = li.sort_values(["l_orderkey", "l_linenumber"]).head(50)
    np.testing.assert_allclose(np.asarray(got["a"], np.float64),
                               (df.l_quantity - 25.0).abs(), rtol=0, atol=1e-9)
    np.testing.assert_allclose(np.asarray(got["c"], np.float64),
                               np.ceil(df.l_discount), rtol=0)
    np.testing.assert_allclose(np.asarray(got["f"], np.float64),
                               np.floor(df.l_tax), rtol=0)
    np.testing.assert_allclose(np.asarray(got["r"], np.float64),
                               np.floor(df.l_extendedprice + 0.5), rtol=0)
    np.testing.assert_allclose(np.asarray(got["s"], np.float64),
                               np.sqrt(df.l_quantity), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(got["co"], np.float64),
                               df.l_quantity, rtol=0)

    got = sql(cat, """
        select length(l_shipmode) as n, upper(l_shipmode) as u,
               lower(l_shipmode) as lo
        from lineitem order by l_orderkey, l_linenumber limit 10
    """).run()
    df = li.sort_values(["l_orderkey", "l_linenumber"]).head(10)
    assert list(got["n"]) == [len(s) for s in df.l_shipmode]
    assert list(got["u"]) == [s.upper() for s in df.l_shipmode]
    assert list(got["lo"]) == [s.lower() for s in df.l_shipmode]


def test_random_expression_fuzz():
    """sqlsmith-lite: random arithmetic/comparison/boolean/CASE expressions
    over lineitem evaluated by the engine vs a numpy oracle interpreter —
    the vectorized-vs-row cross-check pattern of
    distsql/columnar_operators_test.go, aimed at expression lowering."""
    import numpy as np

    from cockroach_tpu.bench import tpch
    from cockroach_tpu.coldata.types import FLOAT64
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.ops import expr as ex
    from cockroach_tpu.plan import builder as plan_builder
    from cockroach_tpu.sql.rel import Rel

    cat = tpch.gen_tpch(sf=0.002, seed=21)
    base = Rel.scan(cat, "lineitem", (
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_linenumber",
    ))
    df = tpch.to_pandas(cat, "lineitem")
    cols = {
        0: df.l_quantity.to_numpy(dtype=np.float64),
        1: df.l_extendedprice.to_numpy(dtype=np.float64),
        2: df.l_discount.to_numpy(dtype=np.float64),
        3: df.l_tax.to_numpy(dtype=np.float64),
        4: df.l_linenumber.to_numpy(dtype=np.float64),
    }
    rng = np.random.default_rng(99)

    def gen_num(depth):
        r = rng.random()
        if depth >= 3 or r < 0.35:
            if rng.random() < 0.5:
                return ("col", int(rng.integers(0, 5)))
            return ("lit", float(np.round(rng.uniform(-5, 5), 2)))
        if r < 0.8:
            op = rng.choice(["+", "-", "*"])
            return ("bin", str(op), gen_num(depth + 1), gen_num(depth + 1))
        if r < 0.9:
            return ("func", str(rng.choice(["abs", "floor", "ceil"])),
                    gen_num(depth + 1))
        return ("case", gen_bool(depth + 1), gen_num(depth + 1),
                gen_num(depth + 1))

    def gen_bool(depth):
        if depth >= 3 or rng.random() < 0.6:
            op = rng.choice(["lt", "le", "gt", "ge", "eq", "ne"])
            return ("cmp", str(op), gen_num(depth + 1), gen_num(depth + 1))
        op = rng.choice(["and", "or"])
        return ("bool", str(op), gen_bool(depth + 1), gen_bool(depth + 1))

    def to_engine(t):
        k = t[0]
        if k == "col":
            # engine sees typed columns (decimal etc.); cast to float so
            # engine and oracle share one numeric domain
            return ex.Cast(ex.ColRef(t[1]), FLOAT64)
        if k == "lit":
            return ex.Const(t[1], FLOAT64)
        if k == "bin":
            return ex.BinOp(t[1], to_engine(t[2]), to_engine(t[3]))
        if k == "func":
            return ex.Func1(t[1], to_engine(t[2]))
        if k == "case":
            return ex.Case(((to_engine(t[1]), to_engine(t[2])),),
                           to_engine(t[3]))
        if k == "cmp":
            return ex.Cmp(t[1], to_engine(t[2]), to_engine(t[3]))
        if k == "bool":
            return ex.BoolOp(t[1], (to_engine(t[2]), to_engine(t[3])))
        raise AssertionError(k)

    def oracle(t):
        k = t[0]
        if k == "col":
            return cols[t[1]]
        if k == "lit":
            return np.full(len(cols[0]), t[1])
        if k == "bin":
            a, b = oracle(t[2]), oracle(t[3])
            return {"+": a + b, "-": a - b, "*": a * b}[t[1]]
        if k == "func":
            f = {"abs": np.abs, "floor": np.floor, "ceil": np.ceil}[t[1]]
            return f(oracle(t[2]))
        if k == "case":
            return np.where(oracle(t[1]), oracle(t[2]), oracle(t[3]))
        if k == "cmp":
            a, b = oracle(t[2]), oracle(t[3])
            return {"lt": a < b, "le": a <= b, "gt": a > b,
                    "ge": a >= b, "eq": a == b, "ne": a != b}[t[1]]
        if k == "bool":
            a, b = oracle(t[2]), oracle(t[3])
            return a & b if t[1] == "and" else a | b
        raise AssertionError(k)

    for trial in range(25):
        tree = gen_num(0)
        rel = base.project([("out", to_engine(tree))])
        got = run_operator(plan_builder.build(rel.plan, cat))["out"]
        want = oracle(tree)
        np.testing.assert_allclose(
            np.asarray(got, np.float64), want, rtol=1e-9, atol=1e-9,
            err_msg=f"trial {trial}: {tree}")


def test_cast_matrix():
    """colexecbase cast semantics: DECIMAL->INT rounds (Postgres), scale
    cuts round half away from zero, FLOAT->INT rounds, DATE<->TIMESTAMP,
    numeric->BOOL."""
    import numpy as np

    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu import coldata as cd
    from cockroach_tpu.sql import sql

    cat = catalog_mod.Catalog()
    schema = cd.Schema.of(i=cd.INT64, d=cd.DECIMAL(12, 2), f=cd.FLOAT64,
                          day=cd.DATE)
    cat.add(catalog_mod.Table.from_strings("t", schema, {
        "i": np.array([-3, 0, 7], dtype=np.int64),
        "d": np.array([-155, 0, 155], dtype=np.int64),  # -1.55, 0, 1.55
        "f": np.array([-2.5, 0.5, 2.49]),
        "day": np.array([0, 1, 10957], dtype=np.int32),  # 2000-01-01
    }))

    res = sql(cat, """
        select cast(d as int) as di, cast(f as int) as fi,
               cast(i as decimal) as idec, cast(d as float) as df,
               cast(i as bool) as ib, cast(day as timestamp) as ts
        from t order by i
    """).run()
    assert list(res["di"]) == [-2, 0, 2], "numeric->int rounds half away"
    assert list(res["fi"]) == [-2, 0, 2], "float->int rounds (banker's at .5)"
    np.testing.assert_allclose(np.asarray(res["idec"], np.float64),
                               [-3.0, 0.0, 7.0])
    np.testing.assert_allclose(np.asarray(res["df"], np.float64),
                               [-1.55, 0.0, 1.55])
    assert list(res["ib"]) == [True, False, True]
    assert int(res["ts"][2]) == 10957 * 86400 * 1000000
