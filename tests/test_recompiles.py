"""Tier-1 wiring for the zero-recompile serving-path guard
(scripts/check_recompiles.py): cold compiles stay within recorded
per-query budgets, adaptation settles in one run, and a warmed repeat
with different literals triggers zero new XLA traces."""

import pytest

from scripts.check_recompiles import check


@pytest.mark.slow
def test_recompiles():
    problems = check()
    assert not problems, "\n".join(problems)
