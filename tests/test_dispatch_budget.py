"""Tier-1 wiring for the kernel-dispatch budget guard
(scripts/check_dispatch_budget.py): one representative fused query must
stay within its recorded dispatch budget, and the marginal cost of an
extra input tile must stay one fused kernel."""

import pytest

from scripts.check_dispatch_budget import check


@pytest.mark.slow
def test_dispatch_budget():
    problems = check()
    assert not problems, "\n".join(problems)
