"""Per-statement KV-operation budgets — the pkg/bench/rttanalysis analog.

The reference asserts each SQL statement shape performs a bounded number
of KV round-trips (rttanalysis.RoundTripBenchTestCase); regressions that
add a lookup per row or an extra scan per statement fail CI. Here the
"round trips" are engine-level ops (storage_writes / storage_scans
counters): the same regression class — a DML path quietly degrading to
per-row scans — trips these budgets."""

import numpy as np
import pytest

from cockroach_tpu.sql.session import Session
from cockroach_tpu.utils import metric


class OpCounts:
    def __enter__(self):
        self.w0 = metric.ENGINE_WRITES.value
        self.s0 = metric.ENGINE_SCANS.value
        return self

    def __exit__(self, *exc):
        self.writes = metric.ENGINE_WRITES.value - self.w0
        self.scans = metric.ENGINE_SCANS.value - self.s0


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE kvt (k INT PRIMARY KEY, v INT, s STRING)")
    s.execute("INSERT INTO kvt VALUES " + ", ".join(
        f"({i}, {i * 10}, 'tag{i % 7}')" for i in range(200)
    ))
    return s


def test_multirow_insert_write_budget(sess):
    """One INSERT of 100 rows must not degrade to per-row engine writes
    beyond row count + constant overhead (txn record, dictionary)."""
    with OpCounts() as c:
        sess.execute("INSERT INTO kvt VALUES " + ", ".join(
            f"({i}, 1, 'x')" for i in range(1000, 1100)
        ))
    assert c.writes <= 100 + 20, c.writes
    assert c.scans <= 6, c.scans


def test_point_select_scan_budget(sess):
    with OpCounts() as c:
        out = sess.execute("SELECT v FROM kvt WHERE k = 42")
    assert list(np.asarray(out["v"])) == [420]
    assert c.scans <= 2, c.scans
    assert c.writes == 0, c.writes


def test_full_scan_budget(sess):
    """A full-table SELECT is one columnar scan, not per-row gets."""
    with OpCounts() as c:
        out = sess.execute("SELECT count(v) AS n FROM kvt")
    assert int(np.asarray(out["n"])[0]) == 200
    assert c.scans <= 2, c.scans


def test_update_budget(sess):
    """UPDATE of ~30 rows: bounded by one scan + one write per row +
    constant overhead."""
    with OpCounts() as c:
        sess.execute("UPDATE kvt SET v = v + 1 WHERE k < 30")
    assert c.scans <= 4, c.scans
    assert c.writes <= 30 + 10, c.writes


def test_delete_budget(sess):
    with OpCounts() as c:
        sess.execute("DELETE FROM kvt WHERE k >= 190")
    assert c.scans <= 4, c.scans
    assert c.writes <= 10 + 10, c.writes


def test_txn_block_budget(sess):
    """BEGIN; two point writes; COMMIT — constant op count (no hidden
    re-scans at commit)."""
    with OpCounts() as c:
        sess.execute("BEGIN")
        sess.execute("INSERT INTO kvt VALUES (5001, 1, 'a')")
        sess.execute("INSERT INTO kvt VALUES (5002, 2, 'b')")
        sess.execute("COMMIT")
    assert c.writes <= 2 + 12, c.writes
    assert c.scans <= 6, c.scans