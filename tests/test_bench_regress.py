"""Bench-regression gate (scripts/check_bench_regress.py) and the
histogram-quantile helper the load harness recovers queue-wait from."""

from __future__ import annotations

import json

from scripts.check_bench_regress import (compare, flatten_throughput,
                                         latest_baseline, main)

_BASE = {
    "metric": "tpch_sf0.5_cpu_geomean_rows_per_sec",
    "value": 1000,
    "detail": {
        "q1": {"rows_per_sec": 500, "vs_pandas": 2.0},
        "ycsb_e_1m": {"ops_per_sec": 2000.0, "compactions": 3},
        "mixed_load": {"ops_per_sec": 40.0, "p99_queue_wait_ms": 1.0},
    },
}


def test_flatten_throughput_picks_per_sec_series():
    flat = flatten_throughput(_BASE)
    assert flat == {
        "value": 1000.0,
        "q1.rows_per_sec": 500.0,
        "ycsb_e_1m.ops_per_sec": 2000.0,
        "mixed_load.ops_per_sec": 40.0,
    }  # vs_pandas / compactions / wait_ms are not throughput series


def test_compare_clean_within_threshold():
    fresh = json.loads(json.dumps(_BASE))
    fresh["detail"]["q1"]["rows_per_sec"] = 420  # -16%: under the bar
    assert compare(fresh, _BASE, threshold=0.2) == []


def test_compare_flags_regressions_and_missing_series():
    fresh = json.loads(json.dumps(_BASE))
    fresh["detail"]["q1"]["rows_per_sec"] = 300  # -40%
    del fresh["detail"]["ycsb_e_1m"]["ops_per_sec"]
    flags = compare(fresh, _BASE, threshold=0.2)
    assert any(f.startswith("regression: q1.rows_per_sec") for f in flags)
    assert any(f.startswith("missing metric: ycsb_e_1m.ops_per_sec")
               for f in flags)
    assert len(flags) == 2
    # a looser threshold forgives the drop but never the missing series
    flags = compare(fresh, _BASE, threshold=0.5)
    assert len(flags) == 1 and flags[0].startswith("missing metric")


def test_compare_refuses_config_mismatch():
    fresh = dict(_BASE, metric="tpch_sf1_tpu_geomean_rows_per_sec")
    flags = compare(fresh, _BASE)
    assert len(flags) == 1 and flags[0].startswith("config mismatch")


def test_main_against_recorded_baseline(tmp_path, capsys):
    """CLI shape: wrapper files ({"parsed": ...}) unwrap, '#' progress
    lines in the fresh capture are skipped, exit codes gate."""
    base = tmp_path / "BENCH_r01.json"
    base.write_text(json.dumps({"n": 1, "parsed": _BASE}))
    fresh = tmp_path / "fresh.json"
    fresh.write_text("# gen/load sf=0.5: ...\n" + json.dumps(_BASE) + "\n")
    assert main([str(fresh), "--baseline", str(base)]) == 0
    bad = json.loads(json.dumps(_BASE))
    bad["detail"]["q1"]["rows_per_sec"] = 1
    fresh.write_text(json.dumps(bad))
    assert main([str(fresh), "--baseline", str(base)]) == 1
    out = capsys.readouterr().out
    assert "regression: q1.rows_per_sec" in out


def test_latest_baseline_picks_newest(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": {"value": 1}}))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": {"value": 2}}))
    path, parsed = latest_baseline(str(tmp_path))
    assert path.endswith("BENCH_r02.json") and parsed["value"] == 2
    assert latest_baseline(str(tmp_path / "empty")) is None


def test_hist_quantile_from_bucket_deltas():
    from cockroach_tpu.bench.load import hist_quantile_from_deltas

    buckets = (0.001, 0.01, 0.1, 1.0)
    before = [0, 0, 0, 0, 0]
    # 90 observations <=1ms, 9 in (1ms,10ms], 1 in (10ms,100ms]
    after = [90, 9, 1, 0, 0]
    assert hist_quantile_from_deltas(buckets, before, after, 0.50) == 0.001
    assert hist_quantile_from_deltas(buckets, before, after, 0.95) == 0.01
    assert hist_quantile_from_deltas(buckets, before, after, 0.999) == 0.1
    # no traffic between snapshots -> 0, not a stale figure
    assert hist_quantile_from_deltas(buckets, after, after, 0.99) == 0.0
    # overflow bucket reports the last finite bound (a floor)
    assert hist_quantile_from_deltas(buckets, before,
                                     [0, 0, 0, 0, 5], 0.99) == 1.0


def test_compare_flags_failed_overload_oracle_regardless_of_threshold():
    """The multi-tenant overload oracle is pass/fail: a false oracle
    bool flags even when every throughput series is flat, and each
    failed sub-oracle names itself; a passing oracle adds nothing."""
    base = json.loads(json.dumps(_BASE))
    base["detail"]["mixed_load"].update({
        "overload_goodput_per_sec": 90.0, "overload_oracle_ok": True,
        "overload_oracle_goodput_ok": True, "overload_oracle_typed_ok": True,
        "overload_oracle_isolation_ok": True})
    fresh = json.loads(json.dumps(base))
    assert compare(fresh, base, threshold=0.2) == []
    fresh["detail"]["mixed_load"].update({
        "overload_oracle_ok": False,
        "overload_oracle_goodput_ok": False,
        "overload_oracle_isolation_ok": False})
    flags = compare(fresh, base, threshold=0.99)
    assert any("overload_oracle_goodput_ok" in f for f in flags)
    assert any("overload_oracle_isolation_ok" in f for f in flags)
    assert not any("typed" in f for f in flags)
    assert all(f.startswith("overload oracle:") for f in flags)
    # runs without overload figures (old baselines, --job q1) don't flag
    assert compare(_BASE, _BASE, threshold=0.2) == []
