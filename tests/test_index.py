"""Secondary indexes: CREATE INDEX backfill, maintenance, index scans.

Reference behaviors mirrored: index key encoding + maintenance
(pkg/sql/rowenc/index_encoding.go), index-backed constrained scans
(pkg/sql/opt/xform/select_funcs.go), index join / Streamer fetch
(pkg/sql/rowexec/joinreader.go, pkg/kv/kvclient/kvstreamer/streamer.go:517),
chunked checkpointed backfill (pkg/sql/backfill.go)."""

import numpy as np
import pytest

from cockroach_tpu import sql as sqlmod
from cockroach_tpu.kv import index as ixm
from cockroach_tpu.sql.session import Session


def _sess(n=60):
    sess = Session()
    sess.execute(
        "create table t (id int primary key, k int, v int, s string)")
    sess.execute("insert into t values " + ", ".join(
        f"({i}, {i % 9}, {i * 3}, 'g{i % 4}')" for i in range(n)))
    return sess


# -- codec ------------------------------------------------------------------


def test_entry_codec_roundtrip_and_order():
    ks = []
    for val, pk in [(-(1 << 62), 5), (-3, 1), (0, 0), (0, 7), (9, 2),
                    (1 << 62, 9)]:
        k = ixm.encode_entry(7, val, pk)
        assert ixm.decode_entry(k) == (val, pk)
        ks.append(k)
    assert ks == sorted(ks), "entry keys must sort by (value, pk)"


def test_value_span_covers_exactly():
    lo, hi = ixm.value_span(7, 10, 20)
    for val in (9, 10, 15, 20, 21):
        k = ixm.encode_entry(7, val, 123)
        inside = lo <= k < hi
        assert inside == (10 <= val <= 20), val


def test_encode_entries_matches_scalar():
    vals = np.array([-5, 0, 3, 1 << 40], dtype=np.int64)
    pks = np.array([1, 2, 3, 4], dtype=np.int64)
    batch = ixm.encode_entries(9, vals, pks)
    for i in range(4):
        assert batch[i].tobytes() == ixm.encode_entry(
            9, int(vals[i]), int(pks[i]))


# -- DDL + read path --------------------------------------------------------


def test_create_index_and_eq_scan():
    sess = _sess()
    out = sess.execute("create index ik on t (k)")
    assert "created_index" in out
    plan = sqlmod.explain(sess.catalog, "select id, v from t where k = 4")
    assert "index-scan t@ik" in plan, plan
    got = sess.execute("select id from t where k = 4 order by id")
    assert list(got["id"]) == [i for i in range(60) if i % 9 == 4]


def test_index_scan_matches_full_scan_results():
    sess = _sess()
    sess.execute("create index ik on t (k)")
    q = "select id, v from t where k = 7 and v > 30 order by id"
    with_index = sess.execute(q)
    from cockroach_tpu.utils import settings

    settings.set("sql.opt.index_scan.enabled", False)
    try:
        full = sess.execute(q)
    finally:
        settings.set("sql.opt.index_scan.enabled", True)
    assert list(with_index["id"]) == list(full["id"])
    assert list(with_index["v"]) == list(full["v"])


def test_range_scan_uses_index_when_selective():
    sess = _sess()
    sess.execute("create index iv on t (v)")
    sess.execute("analyze t")
    plan = sqlmod.explain(
        sess.catalog, "select id from t where v >= 30 and v <= 36")
    assert "index-scan t@iv [30, 36]" in plan, plan
    got = sess.execute(
        "select id from t where v >= 30 and v <= 36 order by id")
    assert list(got["id"]) == [10, 11, 12]


def test_unselective_range_keeps_full_scan():
    sess = _sess()
    sess.execute("create index iv on t (v)")
    sess.execute("analyze t")
    plan = sqlmod.explain(sess.catalog, "select id from t where v >= 0")
    assert "index-scan" not in plan, plan


def test_write_paths_maintain_index():
    sess = _sess()
    sess.execute("create index ik on t (k)")
    # INSERT after index creation
    sess.execute("insert into t values (100, 4, 1, 'x')")
    got = sess.execute("select id from t where k = 4 order by id")
    assert 100 in list(got["id"])
    # UPDATE moves the row between index buckets
    sess.execute("update t set k = 5 where id = 100")
    got = sess.execute("select id from t where k = 4 order by id")
    assert 100 not in list(got["id"])
    got = sess.execute("select id from t where k = 5 order by id")
    assert 100 in list(got["id"])
    # DELETE removes the entry
    sess.execute("delete from t where id = 100")
    got = sess.execute("select id from t where k = 5 order by id")
    assert 100 not in list(got["id"])


def test_index_inside_txn_sees_own_writes():
    sess = _sess()
    sess.execute("create index ik on t (k)")
    sess.execute("begin")
    sess.execute("insert into t values (200, 4, 2, 'y')")
    got = sess.execute("select id from t where k = 4 order by id")
    assert 200 in list(got["id"])
    sess.execute("rollback")
    got = sess.execute("select id from t where k = 4 order by id")
    assert 200 not in list(got["id"])


def test_drop_index_reverts_plan():
    sess = _sess()
    sess.execute("create index ik on t (k)")
    sess.execute("drop index ik on t")
    plan = sqlmod.explain(sess.catalog, "select id from t where k = 4")
    assert "index-scan" not in plan
    got = sess.execute("select id from t where k = 4 order by id")
    assert list(got["id"]) == [i for i in range(60) if i % 9 == 4]


def test_string_index_eq_via_dictionary_code():
    sess = _sess()
    sess.execute("create index istr on t (s)")
    got = sess.execute("select id from t where s = 'g2' order by id")
    assert list(got["id"]) == [i for i in range(60) if i % 4 == 2]


def test_index_persists_across_restart():
    from cockroach_tpu.catalog import Catalog
    from cockroach_tpu.kv.table import load_catalog_from_engine

    sess = _sess()
    sess.execute("create index ik on t (k)")
    cat = Catalog()
    load_catalog_from_engine(cat, sess.db)
    t2 = cat.tables["t"]
    assert [ix.name for ix in t2.indexes] == ["ik"]
    pks = ixm.scan_pks(t2, t2.indexes[0], 4, 4)
    assert sorted(pks.tolist()) == [i for i in range(60) if i % 9 == 4]


def test_float_index_rejected():
    sess = Session()
    sess.execute("create table f (id int primary key, x float)")
    with pytest.raises(Exception, match="FLOAT"):
        sess.execute("create index ix on f (x)")


def test_streamer_fetch_shapes_by_request():
    sess = _sess()
    t = sess.catalog.tables["t"]
    st = ixm.Streamer(t)
    b = st.fetch(np.array([3, 5, 57], dtype=np.int64), ("id", "v"))
    assert b.capacity == 128  # request-sized, not table-sized
    ids = np.asarray(b.cols[0].data)[np.asarray(b.mask)]
    assert sorted(ids.tolist()) == [3, 5, 57]
    vs = np.asarray(b.cols[1].data)[np.asarray(b.mask)]
    assert sorted(vs.tolist()) == [9, 15, 171]


def test_streamer_missing_pks_masked_off():
    sess = _sess()
    t = sess.catalog.tables["t"]
    b = ixm.Streamer(t).fetch(
        np.array([1, 999, 2], dtype=np.int64), ("id",))
    ids = np.asarray(b.cols[0].data)[np.asarray(b.mask)]
    assert sorted(ids.tolist()) == [1, 2]


def test_vectorized_upsert_tombstones_stale_entries():
    """Multi-row INSERT VALUES over an existing pk with a changed indexed
    value must tombstone the old index entry (the old row is read BEFORE
    the put lands, or the txn's own intent would hide it)."""
    sess = _sess()
    sess.execute("create index ik on t (k)")
    # pk 3 currently has k=3; the multi-row VALUES path rewrites it to k=8
    sess.execute("insert into t values (3, 8, 1, 'x'), (300, 8, 2, 'y')")
    got = sess.execute("select id from t where k = 3 order by id")
    assert 3 not in list(got["id"])
    got = sess.execute("select id from t where k = 8 order by id")
    assert {3, 300} <= set(int(x) for x in got["id"])
    # the stale (k=3, pk=3) entry is physically gone, not just filtered
    t = sess.catalog.tables["t"]
    pks = ixm.scan_pks(t, t.indexes[0], 3, 3)
    assert 3 not in pks.tolist()
