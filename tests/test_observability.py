"""End-to-end observability: distributed tracing, crdb_internal virtual
tables, statement diagnostics bundles, and the admin HTTP payloads.

The invariants pinned here:

- concurrent sessions grow DISJOINT span trees (the contextvar tracer's
  whole point — no shared stack to interleave);
- span context propagates across the KV RPC and DCN flow seams and the
  remote recording grafts back into the caller's tree, surviving chaos
  drops/retries and typed-error paths (spans always close);
- EXPLAIN ANALYZE (DEBUG) captures a bundle whose trace covers
  SQL -> flow -> operators with per-operator times summing to the query
  span within 10% (warm run);
- crdb_internal tables answer plain SQL, including over pgwire;
- the AdminServer payload methods and the debug-zip collector snapshot
  the same registries without sockets.
"""

import threading
import time

import numpy as np
import pytest

from cockroach_tpu.catalog import Catalog, Table
from cockroach_tpu.coldata import types as T
from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.rpc import BatchClient, BatchServer
from cockroach_tpu.sql import Session, diagnostics, explain
from cockroach_tpu.storage.lsm import Engine, WriteIntentError
from cockroach_tpu.utils import faults, settings, tracing
from cockroach_tpu.utils.faults import FaultSpec


def _session():
    s = Session(Catalog())
    s.execute("create table t (id int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    return s


# ------------------------------------------------------------ span trees

def test_concurrent_sessions_disjoint_span_trees():
    """N threads, each its own Session: every sql.execute root holds spans
    of exactly one trace id, and no two threads share a trace."""
    barrier = threading.Barrier(3)
    roots_by_thread: dict[int, list] = {}

    def work(idx):
        s = _session()
        barrier.wait()
        for _ in range(4):
            s.execute("select count(*) from t where id > 1")
        # roots are captured from the thread's own statements via the
        # finished ring below; record the trace ids this thread minted
        s.close()

    # the finished registry is a bounded ring that trims from the head, so
    # a high-water mark taken mid-suite can be sliced away — start empty
    tracing.DEFAULT.finished.clear()
    threads = [threading.Thread(target=work, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    roots = [s for s in tracing.DEFAULT.finished
             if s.name == "sql.execute"
             and s.tags.get("stmt", "").startswith("select count")]
    assert len(roots) == 12
    seen_spans: set[int] = set()
    for r in roots:
        ids = {s.trace_id for s in r.walk()}
        assert ids == {r.trace_id}, "foreign trace id inside a tree"
        for s in r.walk():
            assert s.span_id not in seen_spans, "span shared between trees"
            seen_spans.add(s.span_id)
        assert r.duration is not None and r.duration >= 0
    # each statement minted a fresh trace — no cross-thread interleaving
    assert len({r.trace_id for r in roots}) == 12
    assert not [s for s in tracing.inflight()
                if s.name == "sql.execute"], "unclosed session spans"


def test_session_spans_cover_the_sql_seams():
    s = _session()
    tracing.DEFAULT.finished.clear()  # bounded ring: start from empty
    s.execute("select v from t where id = 2")
    roots = [r for r in tracing.DEFAULT.finished
             if r.tags.get("stmt") == "select v from t where id = 2"]
    assert len(roots) == 1, "seam spans must nest, not mint extra roots"
    root = roots[0]
    assert root.name == "sql.execute"
    names = [c.name for c in root.walk()]
    assert "sql.parse" in names
    assert "sql.bind" in names
    assert "sql.plancache.lookup" in names
    assert "query" in names
    q = next(c for c in root.walk() if c.name == "query")
    assert q.tags.get("cache") in ("hit", "miss")
    s.close()


# --------------------------------------------------- KV trace propagation

def test_kv_trace_propagates_and_grafts_under_chaos(tmp_path):
    """Two-node shape (client + RPC server over a WAL engine): span
    context rides the envelope, the server recording grafts back — on
    retries and on typed-error paths alike — and every span closes."""
    db = DB(Engine(key_width=16, val_width=64, memtable_size=256,
                   wal_path=str(tmp_path / "kv.wal")), Clock())
    srv = BatchServer(db)
    client = BatchClient(srv.addr, deadline_s=2.0, max_retries=8)
    faults.arm(11, {
        "kv.rpc.client.batch": FaultSpec(kind="drop", p=0.3, max_fires=3),
        "kv.rpc.server.eval": FaultSpec(kind="drop", p=0.3, max_fires=3),
    })
    try:
        with tracing.span("test.kv") as sp:
            for i in range(20):
                client.put(b"k%03d" % i, b"v%d" % i)
            assert client.get(b"k003") == b"v3"
    finally:
        faults.disarm()
    kvs = [c for c in sp.children if c.name == "kv/batch"]
    assert len(kvs) == 21
    assert all(c.duration is not None for c in kvs), "unclosed client span"
    assert any(c.tags.get("attempts", 1) > 1 for c in kvs), \
        "chaos injected no retries — the retry-path graft went untested"
    # every batch carries the grafted server-side recording, same trace
    for c in kvs:
        remote = [g for g in c.children if g.remote]
        assert [g.name for g in remote] == ["kv/server.batch"]
        assert remote[0].trace_id == sp.trace_id
    # the put batches show the storage seam under the server span
    wal = [g.name for c in kvs for g in c.children if g.remote
           for g in g.walk()]
    assert "storage/wal.append" in wal

    # typed-error path: the client span closes WITH the server recording
    t = db.new_txn()
    t.put(b"locked", b"x")
    with tracing.span("test.kv.err") as esp:
        with pytest.raises(WriteIntentError):
            client.get(b"locked")
    kb = next(c for c in esp.children if c.name == "kv/batch")
    assert kb.duration is not None
    assert kb.error and "WriteIntent" in kb.error
    assert [g.name for g in kb.children if g.remote] == ["kv/server.batch"]
    t.commit()
    assert not [s for s in tracing.inflight() if s.name.startswith("kv/")]
    client.close()
    srv.close()


# -------------------------------------------------- DCN trace propagation

def test_dcn_flow_trace_grafts_across_the_stream():
    """Remote flow: the handshake carries the span context, the server's
    flow/outbox recording rides the post-EOS trailer and grafts into the
    setup-time parent span with the caller's trace id."""
    from cockroach_tpu.flow import dcn
    from cockroach_tpu.flow.operators import ScanOp
    from cockroach_tpu.flow.runtime import run_operator

    tbl = Table.from_strings("nums", T.Schema(("x",), (T.INT64,)),
                             {"x": np.arange(100, dtype=np.int64)})
    srv = dcn.FlowServer({"nums": lambda: ScanOp(tbl)}).serve_background()
    try:
        with tracing.span("test.flow") as sp:
            inbox = dcn.setup_remote_flow(srv.addr, "nums", tbl.schema)
            got = run_operator(inbox)
        assert len(got["x"]) == 100
        deadline = time.time() + 5
        while time.time() < deadline:  # trailer graft is post-EOS async
            remote = [c for c in sp.walk() if c.remote]
            if remote:
                break
            time.sleep(0.02)
        assert [c.name for c in remote] == ["flow/outbox"]
        assert remote[0].trace_id == sp.trace_id
        assert remote[0].tags.get("batches") == 1

        # legacy plain-name handshake (no active span) still works
        inbox2 = dcn.setup_remote_flow(srv.addr, "nums", tbl.schema)
        assert len(run_operator(inbox2)["x"]) == 100
    finally:
        srv.close()


# --------------------------------- EXPLAIN ANALYZE (DEBUG) + bundle times

def test_explain_analyze_debug_bundle_time_sum():
    from cockroach_tpu.bench import tpch

    cat = tpch.gen_tpch(sf=0.01, seed=7)
    q = ("select c_nationkey, count(*) as n from orders, customer "
         "where o_custkey = c_custkey group by c_nationkey")
    explain(cat, "explain analyze " + q)  # warm kernels + plan
    out = explain(cat, "explain analyze (debug) " + q)
    assert out.splitlines()[0].startswith("->"), "plan root must stay line 1"
    assert "trace:" in out and "operator/" in out
    bid = int(out.rsplit("diagnostics bundle:", 1)[1].strip())
    bundle = diagnostics.get(bid)
    assert bundle is not None
    assert bundle["trigger"] == "explain_analyze_debug"
    assert bundle["plan"] and "group-by" in bundle["plan"]
    assert bundle["counters"]["kernelDispatches"] > 0
    tr = bundle["trace"]
    assert tr["name"] == "query"
    # per-operator wall times (inclusive roots directly under the query
    # span) sum to the measured latency within 10% on a warm run
    ops = [c for c in tr["children"] if c["name"].startswith("operator/")]
    assert ops, "no operator spans folded into the trace"
    op_ms = sum(c["durationMs"] for c in ops)
    assert abs(op_ms - tr["durationMs"]) <= 0.10 * tr["durationMs"], \
        f"operator spans {op_ms}ms vs query span {tr['durationMs']}ms"


def test_slow_query_log_captures_bundle_and_never_raises():
    s = _session()
    settings.set("sql.log.slow_query.latency_threshold", 1e-9)
    try:
        s.execute("select count(*) from t")
        listing = diagnostics.bundles()
        assert listing and listing[0]["trigger"] == "slow_query"
        full = diagnostics.get(listing[0]["id"])
        assert full["trace"]["name"] == "sql.execute"
        assert full["planCacheStatus"] in ("hit", "miss", "disabled",
                                           "uncacheable")
        # the error path also lands a bundle (error=True) — and capture
        # inside the exception-in-flight finally must not mask the error
        with pytest.raises(Exception, match="nope"):
            s.execute("select nope from t")
        assert any(b["error"] for b in diagnostics.bundles())
    finally:
        settings.reset("sql.log.slow_query.latency_threshold")
        s.close()


def test_diagnostics_ring_is_bounded(tmp_path):
    import os

    settings.set("sql.diagnostics.dir", str(tmp_path))
    settings.set("sql.diagnostics.ring_size", 3)
    s = _session()
    settings.set("sql.log.slow_query.latency_threshold", 1e-9)
    try:
        for i in range(6):
            s.execute(f"select count(*) from t where id > {i}")
        listing = diagnostics.bundles()
        assert len(listing) == 3
        files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert len(files) == 3  # evicted bundles are unlinked
        # newest first, and the oldest three are gone
        ids = [b["id"] for b in listing]
        assert ids == sorted(ids, reverse=True)
    finally:
        settings.reset("sql.log.slow_query.latency_threshold")
        settings.reset("sql.diagnostics.ring_size")
        settings.reset("sql.diagnostics.dir")
        s.close()


# ----------------------------------------------------------- crdb_internal

def test_crdb_internal_tables_answer_sql():
    s = _session()
    s.execute("select count(*) from t")
    res = s.execute(
        "select count(*) from crdb_internal.node_statement_statistics")
    assert int(res["count"][0]) >= 1
    res = s.execute(
        "select fingerprint, count from "
        "crdb_internal.node_statement_statistics")
    fps = [str(f) for f in res["fingerprint"]]
    assert any("select count" in f for f in fps)
    # the running query sees ITSELF in cluster_queries
    res = s.execute("select query, phase from crdb_internal.cluster_queries")
    assert any("cluster_queries" in str(q) for q in res["query"])
    res = s.execute(
        "select session_id, active_queries from "
        "crdb_internal.cluster_sessions")
    assert len(res["session_id"]) >= 1
    res = s.execute("select name, value from crdb_internal.node_metrics")
    names = [str(n) for n in res["name"]]
    assert "sql_queries" in names
    assert "sql_query_seconds_count" in names  # histogram expansion
    res = s.execute(
        "select count(*) from crdb_internal.node_inflight_trace_spans")
    assert int(res["count"][0]) >= 1  # at least this statement's root
    s.close()


def test_crdb_internal_plans_bypass_the_plan_cache():
    from cockroach_tpu.sql import plancache
    from cockroach_tpu.sql.binder import sql as bind_sql

    s = _session()
    q = "select count(*) from crdb_internal.cluster_sessions"
    assert plancache.probe(bind_sql(s.catalog, q)) == "uncacheable"
    # repeated reads re-materialize: a session registered between reads
    # is visible (a cached plan would pin the old snapshot)
    n0 = int(s.execute(q)["count"][0])
    s2 = Session(s.catalog)
    n1 = int(s.execute(q)["count"][0])
    assert n1 == n0 + 1
    s2.close()
    s.close()


def test_crdb_internal_over_pgwire():
    from test_pgwire import MiniPg

    from cockroach_tpu.server.pgwire import PgServer

    sess = Session()
    srv = PgServer(catalog=sess.catalog, db=sess.db).serve_background()
    try:
        c = MiniPg(srv.addr)
        c.query("create table pt (a int primary key)")
        c.query("insert into pt values (1), (2)")
        c.query("select count(*) from pt")
        rows, names, tag, err = c.query(
            "select count(*) from crdb_internal.node_statement_statistics")
        assert err is None
        assert names == ["count"]
        assert int(rows[0][0]) >= 1
        assert tag == "SELECT 1"
        c.close()
    finally:
        srv.close()


# --------------------------------------------- http payloads + debug zip

def test_admin_payload_methods_without_sockets():
    from cockroach_tpu.server.http import AdminServer
    from cockroach_tpu.server.node import Node

    # generous ttl: a cold engine put compiles kernels, which can take
    # longer than the default 1s ttl — the record would expire mid-write
    node = Node(node_id=9, heartbeat_interval_s=0.1,
                metrics_interval_s=0.1, ttl_ms=30000)
    admin = AdminServer(node)  # payload methods need no listener
    node.liveness.heartbeat()
    h = admin.health()
    assert h["nodeId"] == 9 and h["isLive"]
    assert "# TYPE sql_queries counter" in admin.vars()
    stmts = admin.statements()["statements"]
    if stmts:  # earlier tests populated the registry
        assert {"fingerprint", "count", "meanMs", "rows", "errors",
                "p50Ms", "p99Ms"} <= set(stmts[0])
    assert isinstance(admin.contention()["events"], list)
    assert isinstance(admin.diagnostics()["bundles"], list)
    assert admin.diagnostics_bundle(999999) is None
    with tracing.span("test.http"):
        spans = admin.spans()["spans"]
    assert any(s["operation"] == "test.http" for s in spans)
    from cockroach_tpu.utils import metric

    node.tsdb.record(metric.DEFAULT)  # no poller running; record directly
    pts = admin.ts_query("sql_queries", 0, 1 << 62)["datapoints"]
    assert pts and all(len(p) == 2 for p in pts)


def test_tsdb_prune_all_bounds_retention():
    from cockroach_tpu.kv.tsdb import TimeSeriesDB
    from cockroach_tpu.utils import metric

    db = DB(Engine(key_width=64, val_width=128), Clock())
    ts = TimeSeriesDB(db)
    ts.record(metric.DEFAULT)
    time.sleep(0.01)
    ts.record(metric.DEFAULT)
    before = len(ts.query("sql_queries"))
    assert before >= 2
    # cutoff between the two sample batches drops only the older ones
    walls = [w for w, _ in ts.query("sql_queries")]
    dropped = ts.prune_all(walls[-1])
    assert dropped >= 1
    kept = ts.query("sql_queries")
    assert len(kept) >= 1 and all(w >= walls[-1] for w, _ in kept)


def test_debug_zip_in_process_snapshot(tmp_path):
    from cockroach_tpu.server import debugzip

    s = _session()
    settings.set("sql.log.slow_query.latency_threshold", 1e-9)
    try:
        s.execute("select count(*) from t")
    finally:
        settings.reset("sql.log.slow_query.latency_threshold")
    files = debugzip.collect()
    assert {"metrics.txt", "settings.json", "statements.json",
            "spans.json", "diagnostics.json"} <= set(files)
    assert any(n.startswith("diagnostics/bundle_") for n in files)
    out = debugzip.write_zip(str(tmp_path / "debug.zip"), files)
    import zipfile

    with zipfile.ZipFile(out) as z:
        assert "debug/metrics.txt" in z.namelist()
        assert "sql_queries" in z.read("debug/metrics.txt").decode()
    s.close()
