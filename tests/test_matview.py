"""Materialized views: changefeed-fed standing aggregates (sql/matview.py
+ flow/viewmaint.py). Deterministic contracts — the injected-fault side
lives in test_matview_chaos.py:

- bit-identity: a q1-shaped view equals a fresh full rescan of its
  defining query after ANY interleaving of inserts, updates, deletes and
  commits (the delta algebra is exact: DECIMAL sums are scaled-int64,
  avg finalizes through the same code path as the scan pipeline);
- restart: tearing the plane down and re-registering the view resumes
  from the resolved frontier, bit-identical to the incremental state;
- retractions: count/sum/avg retract natively; min/max falls back to a
  per-view rescan ONLY when a retraction hits the group extremum
  (counted in matview_minmax_rescans);
- steady path does delta work only: one fused dispatch per shape class,
  never a base-table rescan;
- concurrent reads during flush serve a consistent frontier snapshot
  (never a torn mix of column generations);
- planner rewrite + EXPLAIN note + vtable introspection surfaces.
"""

import threading

import numpy as np
import pytest

from cockroach_tpu.flow import dispatch
from cockroach_tpu.sql import Session, explain, matview
from cockroach_tpu.utils import metric, settings

# the canonical q1 shape: grouped sum/avg/count over a date-filtered scan
Q = ("SELECT flag, sum(qty) AS sq, avg(price) AS ap, count(*) AS n "
     "FROM t WHERE d <= DATE '1998-06-15' GROUP BY flag ORDER BY flag")


def _mk_session():
    s = Session(val_width=160)
    s.execute("CREATE TABLE t (k INT PRIMARY KEY, flag STRING, "
              "qty DECIMAL(12,2), price DECIMAL(12,2), d DATE)")
    return s


def _seed_rows(s, n=40):
    for i in range(n):
        s.execute(
            f"INSERT INTO t VALUES ({i}, '{'ABC'[i % 3]}', {i}.25, "
            f"{i * 2}.50, DATE '1998-0{1 + i % 8}-0{1 + i % 9}')")


@pytest.fixture
def sess():
    s = _mk_session()
    yield s
    matview.close_all(s.catalog)


def _rows(res):
    return {k: np.asarray(v) for k, v in res.items()}


def _assert_same(a, b, ctx=""):
    a, b = _rows(a), _rows(b)
    assert list(a) == list(b), (ctx, list(a), list(b))
    for k in a:
        assert np.array_equal(a[k], b[k]), (ctx, k, a[k], b[k])


def _oracle(s, q=Q):
    """Fresh full-rescan reference with the planner rewrite OFF, so the
    oracle can never itself be served from the view under test."""
    prev = settings.get("sql.matview.rewrite.enabled")
    settings.set("sql.matview.rewrite.enabled", False)
    try:
        return s.execute(q)
    finally:
        settings.set("sql.matview.rewrite.enabled", prev)


def test_create_matches_rescan(sess):
    _seed_rows(sess)
    base = _oracle(sess)
    out = sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    assert out["created_view"] == "mv"
    _assert_same(base, sess.execute("SELECT * FROM mv ORDER BY flag"))


def test_mixed_dml_bit_identity(sess, rng):
    """The oracle: arbitrary insert/update/delete interleavings, view ==
    fresh rescan after every round (including rows outside the filter
    and deletes of never-matching rows)."""
    _seed_rows(sess)
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    live = set(range(40))
    next_k = 100
    for rnd in range(6):
        for _ in range(int(rng.integers(1, 5))):  # inserts (some filtered)
            f = "ABC"[int(rng.integers(0, 3))]
            mo = 1 + int(rng.integers(0, 12) % 9) % 8
            sess.execute(
                f"INSERT INTO t VALUES ({next_k}, '{f}', "
                f"{int(rng.integers(0, 50))}.75, "
                f"{int(rng.integers(0, 99))}.25, "
                f"DATE '1998-0{mo}-11')")
            live.add(next_k)
            next_k += 1
        for _ in range(int(rng.integers(1, 4))):  # updates
            k = int(rng.choice(sorted(live)))
            sess.execute(f"UPDATE t SET qty = {int(rng.integers(0, 80))}.50,"
                         f" price = {int(rng.integers(0, 80))}.00"
                         f" WHERE k = {k}")
        if rnd % 2 == 1:
            k = int(rng.choice(sorted(live)))
            sess.execute(f"DELETE FROM t WHERE k = {k}")
            live.discard(k)
        _assert_same(_oracle(sess),
                     sess.execute("SELECT * FROM mv ORDER BY flag"),
                     ctx=f"round {rnd}")


def test_restart_resume_from_frontier(sess):
    """Tear the matview plane down (crash analog) and re-register the
    view: the rebuild rescans at the resolved frontier and must be
    bit-identical to the incremental state it replaces."""
    _seed_rows(sess)
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    sess.execute("INSERT INTO t VALUES (200, 'B', 9.00, 1.50, "
                 "DATE '1998-01-02')")
    sess.execute("DELETE FROM t WHERE k = 4")
    r_inc = sess.execute("SELECT * FROM mv ORDER BY flag")
    # restart: the registry, maintainers and hub die with the node;
    # the base table (KV) and its changefeed history survive
    matview.close_all(sess.catalog)
    sess.catalog.tables.pop("mv", None)
    sess.catalog.bump_version()
    sess._invalidate_plans()
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    r_back = sess.execute("SELECT * FROM mv ORDER BY flag")
    _assert_same(r_inc, r_back, ctx="restart")
    _assert_same(_oracle(sess), r_back, ctx="restart-vs-rescan")


def test_retraction_per_aggregate_kind(sess):
    """count/sum/avg retract natively; min/max retracts natively UNLESS
    the retraction hits the group's current extremum — that one case
    re-scans the view (matview_minmax_rescans)."""
    q2 = ("SELECT flag, count(*) AS n, count(qty) AS nq, sum(qty) AS sq, "
          "avg(price) AS ap, min(qty) AS mn, max(qty) AS mx "
          "FROM t WHERE d <= DATE '1999-01-01' GROUP BY flag ORDER BY flag")
    _seed_rows(sess)
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {q2}")
    reg = matview.registry_for(sess.catalog)

    def rescans():
        (row,) = reg.rows()
        return row["minmax_rescans"]

    # 1) delete strictly inside the extremes: native retraction, no rescan
    sess.execute("DELETE FROM t WHERE k = 3")  # qty 3.25 in (0.25, 39.25)
    _assert_same(_oracle(sess, q2),
                 sess.execute("SELECT * FROM mv ORDER BY flag"),
                 ctx="interior delete")
    assert rescans() == 0
    # 2) update raising a group's max: pure insert-side extremum move
    sess.execute("UPDATE t SET qty = 99.99 WHERE k = 12")
    _assert_same(_oracle(sess, q2),
                 sess.execute("SELECT * FROM mv ORDER BY flag"),
                 ctx="raise max")
    assert rescans() == 0
    # 3) delete the row holding the max: the non-retractable case
    sess.execute("DELETE FROM t WHERE k = 12")
    _assert_same(_oracle(sess, q2),
                 sess.execute("SELECT * FROM mv ORDER BY flag"),
                 ctx="delete extremum")
    assert rescans() >= 1
    # 4) delete the row holding a group's min
    before = rescans()
    sess.execute("DELETE FROM t WHERE k = 0")  # qty 0.25 = min of 'A'
    _assert_same(_oracle(sess, q2),
                 sess.execute("SELECT * FROM mv ORDER BY flag"),
                 ctx="delete min")
    assert rescans() > before


def test_steady_path_is_delta_only(sess):
    """1 flush refreshing N same-shaped views = 1 fused dispatch (per
    shape class, not per view), and zero base-table rescans."""
    _seed_rows(sess)
    for i, d in enumerate(("1998-03-15", "1998-06-15", "1998-08-15")):
        sess.execute(
            f"CREATE MATERIALIZED VIEW mv{i} AS "
            + Q.replace("1998-06-15", d))
    reg = matview.registry_for(sess.catalog)
    m = reg.maintainers["t"]
    assert len(m.classes) == 1  # same parameterized shape -> one class
    for i in range(6):
        sess.execute(f"INSERT INTO t VALUES ({300 + i}, 'A', 1.25, 2.50, "
                     f"DATE '1998-0{2 + i}-03')")
    sess.execute("DELETE FROM t WHERE k = 7")
    m.pump()
    assert m.pending()
    d0 = dispatch.total()
    full0 = metric.MATVIEW_FULL_RESCANS.value
    mm0 = metric.MATVIEW_MINMAX_RESCANS.value
    fr0 = [v.frontier for v in m.views()]
    assert m.flush()
    assert dispatch.total() - d0 <= len(m.classes)  # O(kernels), not O(views)
    assert metric.MATVIEW_FULL_RESCANS.value == full0  # no base rescan
    assert metric.MATVIEW_MINMAX_RESCANS.value == mm0
    assert all(v.frontier > f for v, f in zip(m.views(), fr0))
    for i, d in enumerate(("1998-03-15", "1998-06-15", "1998-08-15")):
        _assert_same(_oracle(sess, Q.replace("1998-06-15", d)),
                     sess.execute(f"SELECT * FROM mv{i} ORDER BY flag"),
                     ctx=f"view {d}")


def test_concurrent_reads_during_flush(sess):
    """Readers racing the maintainer's flush/re-host must always see one
    consistent frontier snapshot: with every row's qty fixed at 2.00,
    sum(qty) == 2 * count(*) holds at EVERY frontier — a torn mix of
    column generations would break it."""
    qc = ("SELECT flag, count(*) AS n, sum(qty) AS sq FROM t "
          "WHERE d <= DATE '1999-01-01' GROUP BY flag ORDER BY flag")
    for i in range(20):
        sess.execute(f"INSERT INTO t VALUES ({i}, '{'AB'[i % 2]}', 2.00, "
                     f"4.00, DATE '1998-01-0{1 + i % 9}')")
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {qc}")
    reader = Session(catalog=sess.catalog, db=sess.db, bootstrap=False)
    stop = threading.Event()
    errors = []

    def read_loop():
        while not stop.is_set():
            try:
                res = reader.execute("SELECT * FROM mv ORDER BY flag")
                n = np.asarray(res["n"], dtype=np.float64)
                sq = np.asarray(res["sq"], dtype=np.float64)
                if not np.array_equal(sq, 2.0 * n):
                    errors.append(("torn", sq.tolist(), n.tolist()))
            except Exception as e:  # noqa: BLE001 - surface in main thread
                errors.append(("raise", repr(e)))

    th = threading.Thread(target=read_loop, daemon=True)
    th.start()
    try:
        for i in range(40):
            sess.execute(f"INSERT INTO t VALUES ({100 + i}, "
                         f"'{'AB'[i % 2]}', 2.00, 4.00, DATE '1998-02-01')")
            if i % 5 == 0:
                sess.execute("REFRESH MATERIALIZED VIEW mv")
    finally:
        stop.set()
        th.join(timeout=30)
    assert not th.is_alive()
    assert not errors, errors[:3]
    _assert_same(_oracle(sess, qc),
                 sess.execute("SELECT * FROM mv ORDER BY flag"))


def test_rewrite_serves_from_view(sess):
    _seed_rows(sess)
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    hits0 = metric.MATVIEW_REWRITE_HITS.value
    # different text, same bound shape AND literals -> served from state
    res = sess.execute(Q.replace("SELECT", "select"))
    assert metric.MATVIEW_REWRITE_HITS.value > hits0
    _assert_same(res, sess.execute("SELECT * FROM mv ORDER BY flag"))
    # different literal -> no match, fresh scan (and it must be correct)
    other = Q.replace("1998-06-15", "1998-04-15")
    hits1 = metric.MATVIEW_REWRITE_HITS.value
    _assert_same(_oracle(sess, other), sess.execute(other))
    assert metric.MATVIEW_REWRITE_HITS.value == hits1
    # setting gate
    prev = settings.get("sql.matview.rewrite.enabled")
    settings.set("sql.matview.rewrite.enabled", False)
    try:
        hits2 = metric.MATVIEW_REWRITE_HITS.value
        sess.execute(Q)
        assert metric.MATVIEW_REWRITE_HITS.value == hits2
    finally:
        settings.set("sql.matview.rewrite.enabled", prev)


def test_explain_notes_view(sess):
    _seed_rows(sess)
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    direct = explain(sess.catalog, "EXPLAIN SELECT * FROM mv")
    assert "served from materialized view mv" in direct
    rewritten = explain(sess.catalog, "EXPLAIN " + Q)
    assert "served from materialized view mv" in rewritten
    assert "rewrite" in rewritten
    untouched = explain(
        sess.catalog, "EXPLAIN " + Q.replace("1998-06-15", "1998-04-15"))
    assert "materialized view" not in untouched


def test_vtable_reports_views(sess):
    _seed_rows(sess)
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    res = sess.execute(
        "SELECT view, base_table, groups, full_rescans FROM "
        "crdb_internal.node_materialized_views")
    assert _rows(res)["groups"].tolist() == [3]  # flags A, B, C
    assert _rows(res)["full_rescans"].tolist() == [1]  # initial population
    rows = matview.registry_for(sess.catalog).rows()
    assert [r["view"] for r in rows] == ["mv"]
    assert rows[0]["base_table"] == "t"
    assert rows[0]["frontier"] > 0


def test_oob_group_key_rebuilds(sess):
    """A group-key dictionary code minted after CREATE lands outside the
    view's dense layout: the maintainer falls back to a rebuild (counted
    in full_rescans) and the new group appears."""
    _seed_rows(sess)
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    reg = matview.registry_for(sess.catalog)
    (row,) = reg.rows()
    full0 = row["full_rescans"]
    sess.execute("INSERT INTO t VALUES (500, 'ZED', 1.00, 2.00, "
                 "DATE '1998-01-05')")
    _assert_same(_oracle(sess),
                 sess.execute("SELECT * FROM mv ORDER BY flag"),
                 ctx="new dict value")
    (row,) = reg.rows()
    assert row["full_rescans"] > full0
    assert row["groups"] == 4


def test_ddl_lifecycle_and_gates(sess):
    _seed_rows(sess, n=6)
    prev = settings.get("sql.matview.enabled")
    settings.set("sql.matview.enabled", False)
    try:
        with pytest.raises(Exception, match="disabled"):
            sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    finally:
        settings.set("sql.matview.enabled", prev)
    # non-aggregate defining query is refused with a typed error
    with pytest.raises(Exception, match="grouped aggregate"):
        sess.execute("CREATE MATERIALIZED VIEW mv AS SELECT k FROM t")
    sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    with pytest.raises(Exception, match="already exists"):
        sess.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    out = sess.execute("REFRESH MATERIALIZED VIEW mv")
    assert out["refreshed"] == "mv"
    assert metric.MATVIEW_VIEWS.value == 1
    sess.execute("DROP MATERIALIZED VIEW mv")
    assert metric.MATVIEW_VIEWS.value == 0
    assert "mv" not in sess.catalog.tables
    with pytest.raises(Exception, match="unknown materialized view"):
        sess.execute("DROP MATERIALIZED VIEW mv")
