"""Columnar substrate tests (reference analog: pkg/col/coldata tests)."""

import jax.numpy as jnp
import numpy as np

from cockroach_tpu import coldata as cd


def make_batch(n=10, cap=16):
    schema = cd.Schema.of(a=cd.INT64, b=cd.FLOAT64, s=cd.STRING)
    arrays = {
        "a": np.arange(n, dtype=np.int64),
        "b": np.arange(n, dtype=np.float64) * 0.5,
        "s": np.arange(n, dtype=np.int32) % 3,
    }
    return schema, cd.from_host(schema, arrays, capacity=cap)


def test_from_host_roundtrip():
    schema, b = make_batch()
    assert b.capacity == 16
    assert int(b.length()) == 10
    out = cd.to_host(b, schema)
    np.testing.assert_array_equal(out["a"], np.arange(10))
    np.testing.assert_allclose(out["b"], np.arange(10) * 0.5)


def test_mask_and_compact():
    schema, b = make_batch()
    keep = jnp.asarray(np.arange(16) % 2 == 0) & b.mask
    b2 = b.with_mask(keep)
    assert int(b2.length()) == 5
    c = cd.compact(b2)
    m = np.asarray(c.mask)
    assert m[:5].all() and not m[5:].any()
    out = cd.to_host(c, schema)
    np.testing.assert_array_equal(out["a"], [0, 2, 4, 6, 8])


def test_compact_shrink_capacity():
    schema, b = make_batch(n=4, cap=64)
    c = cd.compact(b, capacity=8)
    assert c.capacity == 8
    out = cd.to_host(c, schema)
    np.testing.assert_array_equal(out["a"], np.arange(4))


def test_nulls_roundtrip():
    schema = cd.Schema.of(x=cd.INT64)
    v = np.array([True, False, True])
    b = cd.from_host(schema, {"x": np.array([1, 2, 3])}, valids={"x": v}, capacity=8)
    out = cd.to_host(b, schema)
    assert out["x"][0] == 1 and out["x"][1] is None and out["x"][2] == 3


def test_concat():
    schema, b1 = make_batch(n=3, cap=8)
    _, b2 = make_batch(n=4, cap=8)
    c = cd.concat([b1, b2], capacity=16)
    assert int(c.length()) == 7
    out = cd.to_host(c, schema)
    np.testing.assert_array_equal(out["a"], [0, 1, 2, 0, 1, 2, 3])


def test_dictionary():
    d = cd.Dictionary(np.array(["cherry", "apple", "banana"], dtype=object))
    assert d.code_of("apple") == 1
    assert d.code_of("missing") == -1
    # ranks reflect sorted byte order
    assert d.ranks[1] < d.ranks[2] < d.ranks[0]
    dec = d.decode(np.array([2, 0, -1]))
    assert list(dec[:2]) == ["banana", "cherry"] and dec[2] is None


def test_dictionary_hash_cross_table():
    d1 = cd.Dictionary(np.array(["x", "y"], dtype=object))
    d2 = cd.Dictionary(np.array(["y", "x"], dtype=object))
    assert d1.hashes[0] == d2.hashes[1]
    assert d1.hashes[1] == d2.hashes[0]
    assert d1.hashes[0] != d1.hashes[1]
