"""KV txn layer tests — isolation, conflicts, retries, and a kvnemesis-style
randomized serializability check (reference: pkg/kv tests + kvnemesis)."""

import numpy as np
import pytest

from cockroach_tpu.kv import (
    DB, ManualClock, TransactionAbortedError, TransactionRetryError,
)
from cockroach_tpu.storage import Engine


def mkdb():
    return DB(Engine(val_width=16), ManualClock())


def test_hlc_monotone():
    from cockroach_tpu.kv import hlc

    c = ManualClock()
    a, b = c.now(), c.now()
    assert b > a  # same wall time -> logical bump
    c.advance(10)
    d = c.now()
    assert d > b
    wall, logical = hlc.unpack(d)
    assert wall == 11 and logical == 0
    e = c.update(hlc.pack(99, 5))
    assert e > hlc.pack(99, 5)


def test_db_basic():
    db = mkdb()
    ts1 = db.put(b"a", b"1")
    db.put(b"a", b"2")
    assert db.get(b"a") == b"2"
    assert db.get(b"a", ts=ts1) == b"1"
    db.delete(b"a")
    assert db.get(b"a") is None
    db.put(b"b", b"x")
    db.put(b"c", b"y")
    assert db.scan(b"a", b"z") == [(b"b", b"x"), (b"c", b"y")]


def test_txn_commit_visibility():
    db = mkdb()
    db.put(b"k", b"base")
    t = db.new_txn()
    t.put(b"k", b"txn")
    # uncommitted write invisible to non-transactional reads below intent ts,
    # and a conflict at-or-above it
    assert db.get(b"k", ts=t.read_ts - 1) == b"base"
    assert t.get(b"k") == b"txn"  # own write visible
    t.commit()
    assert db.get(b"k") == b"txn"


def test_txn_rollback():
    db = mkdb()
    db.put(b"k", b"base")
    t = db.new_txn()
    t.put(b"k", b"gone")
    t.rollback()
    assert db.get(b"k") == b"base"
    with pytest.raises(TransactionAbortedError):
        t.put(b"k", b"zombie")


def test_txn_write_write_conflict():
    db = mkdb()
    t1 = db.new_txn()
    t2 = db.new_txn()
    t1.put(b"k", b"one")
    with pytest.raises(TransactionRetryError):
        t2.put(b"k", b"two")
    t1.commit()


def test_txn_write_too_old():
    db = mkdb()
    t1 = db.new_txn()
    db.put(b"k", b"newer")  # commits above t1.read_ts
    with pytest.raises(TransactionRetryError):
        t1.put(b"k", b"stale")


def test_txn_read_refresh_invalidation():
    db = mkdb()
    db.put(b"k", b"v0")
    t = db.new_txn()
    assert t.get(b"k") == b"v0"
    db.put(b"k", b"v1")  # invalidates t's read before commit
    t.put(b"other", b"x")
    with pytest.raises(TransactionRetryError):
        t.commit()
    assert db.get(b"other") is None  # rolled back


def test_txn_closure_retries():
    db = mkdb()
    db.put(b"counter", b"0")
    calls = {"n": 0}

    def incr(t):
        calls["n"] += 1
        v = int(t.get(b"counter") or b"0")
        if calls["n"] == 1:
            # sneak in a conflicting commit mid-txn on first attempt
            db.put(b"counter", str(v + 10).encode())
        t.put(b"counter", str(v + 1).encode())

    db.txn(incr)
    # first attempt fails refresh (or write-too-old) and retries cleanly
    assert calls["n"] >= 2
    assert db.get(b"counter") == b"11"


def test_txn_rewrite_last_write_wins():
    """A txn rewriting its own key sees and commits the latest write —
    intent sequence numbers (enginepb.TxnSeq analog)."""
    db = mkdb()

    def rw(t):
        t.put(b"rw", b"first")
        assert t.get(b"rw") == b"first"
        t.put(b"rw", b"second")
        assert t.get(b"rw") == b"second"
        t.delete(b"rw")
        assert t.get(b"rw") is None
        t.put(b"rw", b"final")

    db.txn(rw)
    assert db.get(b"rw") == b"final"


@pytest.mark.slow
def test_bank_transfer_invariant():
    """Total balance is conserved across random transfer txns."""
    db = mkdb()
    rng = np.random.default_rng(3)
    n = 10
    for i in range(n):
        db.put(f"acct{i}".encode(), b"100")
    for _ in range(60):
        a, b = rng.integers(0, n, 2)
        if a == b:
            continue
        amt = int(rng.integers(1, 20))

        def xfer(t, a=a, b=b, amt=amt):
            va = int(t.get(f"acct{a}".encode()))
            vb = int(t.get(f"acct{b}".encode()))
            t.put(f"acct{a}".encode(), str(va - amt).encode())
            t.put(f"acct{b}".encode(), str(vb + amt).encode())

        db.txn(xfer)
    total = sum(int(v) for _, v in db.scan(None, None))
    assert total == n * 100


@pytest.mark.slow
def test_kvnemesis_lite():
    """Randomized serial-equivalence: run sequential txns doing random
    read-modify-writes over a small keyspace against a python dict model."""
    db = mkdb()
    rng = np.random.default_rng(5)
    model: dict[bytes, bytes] = {}
    ctr_keys = [f"c{i}".encode() for i in range(6)]  # int-valued RMW keys
    str_keys = [f"k{i}".encode() for i in range(6)]  # blind put/del keys
    for step in range(120):
        kind = rng.random()
        if kind < 0.5:
            k1 = ctr_keys[rng.integers(len(ctr_keys))]
            k2 = ctr_keys[rng.integers(len(ctr_keys))]
        else:
            k1 = str_keys[rng.integers(len(str_keys))]
            k2 = k1

        def op(t, k1=k1, k2=k2, kind=kind, step=step):
            if kind < 0.5:  # transfer-style RMW over two keys
                a = int(t.get(k1) or b"0")
                b = int(t.get(k2) or b"0")
                t.put(k1, str(a + 1).encode())
                t.put(k2, str(b + 2).encode())
                return ("rmw",)
            if kind < 0.75:
                t.put(k1, f"s{step}".encode())
                return ("put",)
            t.delete(k1)
            return ("del",)

        res = db.txn(op)
        # apply the same op to the model (sequentially — txns are serial here)
        if res[0] == "rmw":
            a = int(model.get(k1, b"0"))
            b = int(model.get(k2, b"0"))
            model[k1] = str(a + 1).encode()
            model[k2] = str(b + 2).encode()
        elif res[0] == "put":
            model[k1] = f"s{step}".encode()
        else:
            model.pop(k1, None)
    got = dict(db.scan(None, None))
    assert got == model


def test_interleaved_serializability():
    """Two interleaved txns cannot both commit if they cross-read/write the
    same keys (write skew prevented by the refresh check)."""
    db = mkdb()
    db.put(b"x", b"0")
    db.put(b"y", b"0")
    t1 = db.new_txn()
    t2 = db.new_txn()
    # t1 reads x writes y; t2 reads y writes x — classic write skew
    assert t1.get(b"x") == b"0"
    assert t2.get(b"y") == b"0"
    t1.put(b"y", b"1")
    t2.put(b"x", b"1")  # allowed: x carries no intent and no newer commit
    t1.commit()         # commits y=1
    with pytest.raises(TransactionRetryError):
        t2.commit()     # must fail: its read of y was invalidated
    assert db.get(b"y") == b"1"
    assert db.get(b"x") == b"0"  # t2 rolled back


def test_non_txn_write_respects_intents():
    """Non-txn DB.put/delete sequence through the lock check: writing under
    another txn's intent raises WriteIntentError instead of silently laying
    a committed version beneath the intent."""
    from cockroach_tpu.kv import DB, WriteIntentError

    db = DB()
    t = db.new_txn()
    t.put("k", "txnval")
    with pytest.raises(WriteIntentError):
        db.put("k", "sneaky")
    with pytest.raises(WriteIntentError):
        db.delete("k")
    t.commit()
    assert db.get("k") == b"txnval"
    db.put("k", "after")  # lock released by commit
    assert db.get("k") == b"after"


def test_node_liveness_epochs():
    """liveness.go analog: heartbeats extend expiration under an epoch;
    expired records can be fenced by an epoch increment; live ones can't."""
    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.kv.liveness import NodeLiveness, StillLiveError
    from cockroach_tpu.storage.lsm import Engine

    clock = ManualClock(start=1)
    db = DB(Engine(key_width=16, val_width=32, memtable_size=256), clock)
    n1 = NodeLiveness(db, 1, ttl_ms=1000)
    n2 = NodeLiveness(db, 2, ttl_ms=1000)

    r1 = n1.heartbeat()
    n2.heartbeat()
    assert r1.epoch == 1
    assert n2.is_live(1) and n1.is_live(2)
    assert {r.node_id for r in n1.livenesses()} == {1, 2}

    # node 1 keeps heartbeating: epoch stays, expiration extends
    clock.advance(500)
    r1b = n1.heartbeat()
    assert r1b.epoch == 1 and r1b.expiration > r1.expiration

    # fencing a LIVE node is refused
    with pytest.raises(StillLiveError):
        n2.increment_epoch(1)

    # after expiry, node 2 declares node 1 dead by bumping its epoch
    clock.advance(5000)
    assert not n2.is_live(1)
    fenced = n2.increment_epoch(1)
    assert fenced.epoch == 2

    # node 1's next heartbeat detects the fence (its old epoch is gone)
    from cockroach_tpu.kv.liveness import EpochFencedError

    with pytest.raises(EpochFencedError):
        n1.heartbeat()


def test_jobs_resume_from_checkpoint():
    """pkg/jobs analog: a job killed mid-run re-adopts and RESUMES from its
    persisted progress instead of restarting (the backup-checkpoint
    discipline, manifest_handling.go:1401)."""
    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.kv.jobs import Registry
    from cockroach_tpu.storage.lsm import Engine

    db = DB(Engine(key_width=16, val_width=256, memtable_size=256),
            ManualClock())
    reg = Registry(db)
    work_log: list[int] = []
    crash_at = {"n": 3}

    def resume(registry, job):
        done = job.progress.get("done", 0)
        total = job.payload["total"]
        for i in range(done, total):
            if i == crash_at["n"]:
                crash_at["n"] = -1  # only crash once
                raise RuntimeError("simulated crash")
            work_log.append(i)
            job.progress["done"] = i + 1
            registry.checkpoint(job)
        return {"rows": total}

    reg.register("backfill", resume)
    job = reg.create("backfill", {"total": 6})
    assert reg.load(job.job_id).state == "pending"

    with pytest.raises(RuntimeError):
        reg.adopt_and_resume(job.job_id)
    assert reg.load(job.job_id).state == "failed"
    assert work_log == [0, 1, 2], "crashed at unit 3"

    # "restart": a fresh registry over the same engine re-adopts; the
    # failed record still holds progress, so work resumes at unit 3
    reg2 = Registry(db)
    reg2.register("backfill", resume)
    j = reg2.load(job.job_id)
    j.state = "pending"  # operator-retry (RESUME JOB)
    reg2.checkpoint(j)
    out = reg2.adopt_and_resume(job.job_id)
    assert out.state == "succeeded" and out.progress["rows"] == 6
    assert work_log == [0, 1, 2, 3, 4, 5], "no unit re-ran"


def test_backup_as_a_job(tmp_path):
    """BACKUP rides the jobs frame: durable record, engine checkpoint,
    restore from the produced artifact."""
    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.kv.jobs import Registry, register_builtin_jobs
    from cockroach_tpu.storage.lsm import Engine

    db = DB(Engine(key_width=16, val_width=256, memtable_size=64),
            ManualClock())
    db.txn(lambda t: [t.put(b"k%03d" % i, b"v%03d" % i) for i in range(50)])
    reg = Registry(db)
    register_builtin_jobs(reg)
    path = str(tmp_path / "bk")
    job = reg.create("backup", {"path": path})
    done = reg.adopt_and_resume(job.job_id)
    assert done.state == "succeeded" and done.progress["path"] == path

    restored = Engine.open_checkpoint(path)
    got = restored.scan(b"k", b"l", ts=db.clock.now())
    assert len(got) == 50 and got[0] == (b"k000", b"v000")


def test_changefeed_exactly_once_resume(tmp_path):
    """CDC reduction: the feed emits each committed version once, resumes
    from the checkpointed resolved frontier after a crash, and surfaces
    deletes as NULL values (the changefeedccl envelope)."""
    import json as _json

    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.kv.changefeed import register_changefeed_job
    from cockroach_tpu.kv.jobs import Registry
    from cockroach_tpu.storage.lsm import Engine

    db = DB(Engine(key_width=16, val_width=256, memtable_size=64),
            ManualClock())
    reg = Registry(db)
    register_changefeed_job(reg)
    sink = str(tmp_path / "feed.ndjson")

    db.txn(lambda t: [t.put(b"u001", b"alice"), t.put(b"u002", b"bob")])
    job = reg.create("changefeed", {"sink": sink, "start": "u",
                                    "end": "v", "polls": 1})
    reg.adopt_and_resume(job.job_id)
    lines = [_json.loads(x) for x in open(sink).read().splitlines()]
    assert [(e["key"], e["value"]) for e in lines] == [
        ("u001", "alice"), ("u002", "bob")]

    # more writes + a delete; resume the feed (operator RESUME after crash)
    db.txn(lambda t: (t.put(b"u001", b"alice2"), t.delete(b"u002")))
    j = reg.load(job.job_id)
    j.state = "pending"
    reg.checkpoint(j)
    reg.adopt_and_resume(job.job_id)
    lines = [_json.loads(x) for x in open(sink).read().splitlines()]
    assert len(lines) == 4, "exactly once per version, no re-emission"
    assert (lines[2]["key"], lines[2]["value"]) == ("u001", "alice2")
    assert (lines[3]["key"], lines[3]["value"]) == ("u002", None)


def test_kvnemesis_with_ingest_and_limited_scans():
    """kvnemesis extension over the round-3 paths: bulk INGEST runs
    interleave with transactional RMWs and LIMITED scans (iterator seeks +
    pagination boundaries); every read must match a sequential dict model."""

    db = DB(Engine(key_width=16, val_width=16, memtable_size=32),
            ManualClock())
    rng = np.random.default_rng(11)
    model: dict[bytes, bytes] = {}

    def key(i: int) -> bytes:
        return b"n%05d" % i

    for step in range(80):
        kind = rng.random()
        if kind < 0.25:
            # bulk ingest a contiguous strip (AddSSTable path)
            lo = int(rng.integers(0, 400))
            width = int(rng.integers(1, 40))
            idx = np.arange(lo, lo + width)
            keys = np.zeros((width, 16), dtype=np.uint8)
            for j, i in enumerate(idx):
                kb = key(int(i))
                keys[j, :len(kb)] = np.frombuffer(kb, dtype=np.uint8)
            vals = np.zeros((width, 16), dtype=np.uint8)
            payload = b"g%03d" % step
            vals[:, :len(payload)] = np.frombuffer(payload, dtype=np.uint8)
            db.engine.ingest(keys, vals, ts=db.clock.now(),
                             vlens=np.full(width, len(payload)))
            for i in idx:
                model[key(int(i))] = payload
        elif kind < 0.6:
            # transactional RMW
            k = key(int(rng.integers(0, 400)))

            def op(t, k=k, step=step):
                cur = t.get(k) or b""
                t.put(k, b"t%03d" % step)
                return cur

            db.txn(op)
            model[k] = b"t%03d" % step
        elif kind < 0.75:
            k = key(int(rng.integers(0, 400)))
            db.delete(k)
            model.pop(k, None)
        else:
            # limited scan from a random start: must equal the model's
            # first `limit` keys at/after start (pagination correctness)
            start = key(int(rng.integers(0, 400)))
            limit = int(rng.integers(1, 25))
            got = db.scan(start, None, max_keys=limit)
            want = sorted(
                (k, v) for k, v in model.items() if k >= start
            )[:limit]
            assert got == want, f"step {step}: scan from {start!r}"
    got = dict(db.scan(None, None))
    assert got == model


def test_rangefeed_push_subscription():
    """MuxRangeFeed reduction: a subscriber receives committed versions as
    events plus resolved checkpoints, across writes made AFTER subscribing
    (push, not poll-from-client)."""
    from cockroach_tpu.kv.changefeed import (
        RangefeedServer, subscribe_rangefeed,
    )

    db = DB(Engine(key_width=16, val_width=64, memtable_size=64),
            ManualClock())
    db.txn(lambda t: t.put(b"w1", b"before"))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        sock, frames = subscribe_rangefeed(srv.addr, start=b"w", end=b"x")
        sock.settimeout(15)  # a stalled server fails the test, not hangs it
        got = []
        resolved = 0
        import time as _time

        deadline = _time.time() + 10
        wrote = False
        for f in frames:
            if "resolved" in f:
                resolved = f["resolved"]
                if not wrote:
                    db.txn(lambda t: (t.put(b"w2", b"after"),
                                      t.delete(b"w1")))
                    wrote = True
            else:
                got.append((f["key"], f["value"]))
            if len(got) >= 3 or _time.time() > deadline:
                break
        sock.close()
        assert ("w1", "before") in got, "catch-up scan event"
        assert ("w2", "after") in got, "post-subscribe write pushed"
        assert ("w1", None) in got, "delete surfaces as NULL"
        assert resolved > 0
    finally:
        srv.close()


def test_commit_heavy_intent_resolution_bounds_runs():
    """resolve_intents rewrites every run AND mints a new one per commit
    (the per-commit memtable flush); its end-of-resolution compaction
    hook must keep the run count bounded under a commit-heavy loop —
    without it, N commits leave ~N runs and every cold merged-view
    rebuild pays for all of them."""
    from cockroach_tpu.utils import settings

    db = mkdb()
    prev = settings.get("storage.compaction.pacing.enabled")
    settings.set("storage.compaction.pacing.enabled", False)
    try:
        n = 40
        for i in range(n):
            t = db.new_txn()
            t.put(b"k%d" % (i % 8), b"v%d" % i)
            t.commit()
        eng = db.engine
        assert len(eng.runs) <= eng.l0_trigger + 1, (
            f"{len(eng.runs)} runs after {n} commits "
            f"(trigger {eng.l0_trigger})")
        for j in range(8):
            assert db.get(b"k%d" % j) is not None
    finally:
        settings.set("storage.compaction.pacing.enabled", prev)
