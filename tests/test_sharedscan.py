"""Shared tile stream tests (flow/sharedscan.py).

Concurrent resident scans of one table must ride one slice-dispatch
stream bit-identically: a subscriber attaching mid-stream produces or
consumes exactly the tiles a solo scan would slice (mask included), a
detach mid-stream leaves the other subscriber's results untouched, and
the stream dies with its last subscriber (no registry or staging
leak)."""

import numpy as np
import pytest

from cockroach_tpu.catalog import Catalog, Table
from cockroach_tpu.coldata.types import FLOAT64, INT64, Schema
from cockroach_tpu.flow import sharedscan
from cockroach_tpu.flow.operators import ScanOp
from cockroach_tpu.utils import metric, settings


@pytest.fixture(autouse=True)
def _gate():
    """Shared streams on for the test body; registry always drained."""
    settings.set("sql.distsql.sharedscan.enabled", True)
    yield
    settings.reset("sql.distsql.sharedscan.enabled")
    settings.reset("sql.distsql.sharedscan.window")
    sharedscan.reset()


def _cat(n=512, seed=3) -> Catalog:
    rng = np.random.default_rng(seed)
    cat = Catalog()
    cat.add(Table(
        name="fact",
        schema=Schema(("f_key", "f_val"), (INT64, FLOAT64)),
        columns={
            "f_key": np.arange(n, dtype=np.int64),
            "f_val": rng.uniform(0.0, 10.0, n),
        },
    ))
    return cat


def _rows(tiles) -> list[tuple]:
    """Live rows of a tile sequence (mask applied) — the bit-identity
    surface: a wrong shared mask shows up here as phantom/lost rows."""
    out = []
    for t in tiles:
        mask = np.asarray(t.mask)
        cols = [np.asarray(c.data) for c in t.cols]
        for i in np.nonzero(mask)[0]:
            out.append(tuple(c[i] for c in cols))
    return out


def _drain(op) -> list:
    tiles = []
    while True:
        t = op._next()
        if t is None:
            return tiles
        tiles.append(t)


def test_two_scans_share_one_stream_bit_identical():
    cat = _cat()
    table = cat.get("fact")
    tile = 128

    # solo oracle: gate off, one scan slices its own tiles
    settings.set("sql.distsql.sharedscan.enabled", False)
    solo = ScanOp(table, tile=tile)
    solo.init()
    want = _rows(_drain(solo))
    solo.close()
    settings.set("sql.distsql.sharedscan.enabled", True)

    a = ScanOp(table, tile=tile)
    b = ScanOp(table, tile=tile)
    attached0 = metric.SQL_SHARED_SCAN_ATTACHED.value
    saved0 = metric.SQL_SHARED_SCAN_DISPATCHES_SAVED.value
    a.init()
    b.init()
    assert a._shared is not None and a._shared is b._shared
    # the second attach to a live stream counts
    assert metric.SQL_SHARED_SCAN_ATTACHED.value == attached0 + 1

    # interleave: a produces each tile, b consumes it for free
    rows_a, rows_b = [], []
    while True:
        ta = a._next()
        tb = b._next()
        assert (ta is None) == (tb is None)
        if ta is None:
            break
        rows_a.extend(_rows([ta]))
        rows_b.extend(_rows([tb]))
    assert rows_a == want
    assert rows_b == want
    assert metric.SQL_SHARED_SCAN_DISPATCHES_SAVED.value > saved0

    a.close()
    b.close()
    # stream died with its last subscriber
    assert not sharedscan._streams


def test_attach_mid_stream_and_detach_mid_stream():
    """b attaches after a consumed half the table and a detaches before
    the end — both must still see every row exactly once."""
    cat = _cat()
    table = cat.get("fact")
    tile = 64

    settings.set("sql.distsql.sharedscan.enabled", False)
    solo = ScanOp(table, tile=tile)
    solo.init()
    want = _rows(_drain(solo))
    solo.close()
    settings.set("sql.distsql.sharedscan.enabled", True)

    a = ScanOp(table, tile=tile)
    a.init()
    n_tiles = a._batch.capacity // tile
    tiles_a = [a._next() for _ in range(n_tiles // 2)]

    b = ScanOp(table, tile=tile)
    b.init()  # mid-stream attach: same stream, own cursor from tile 0
    assert b._shared is a._shared

    # a finishes and detaches while b is mid-stream
    tiles_a.extend(_drain(a))
    a.close()
    assert sharedscan._streams  # b still holds the stream open

    tiles_b = _drain(b)
    b.close()
    assert not sharedscan._streams

    assert _rows(tiles_a) == want
    # b started from tile 0 after the window may have trimmed early
    # tiles: those slice solo (catch-up) and must still be identical
    assert _rows(tiles_b) == want


def test_lagging_subscriber_catches_up_solo():
    """A subscriber further behind than the window slices its own tiles
    and still sees every row (the stream never waits for laggards)."""
    cat = _cat(n=512)
    table = cat.get("fact")
    tile = 64
    settings.set("sql.distsql.sharedscan.window", 1)

    settings.set("sql.distsql.sharedscan.enabled", False)
    solo = ScanOp(table, tile=tile)
    solo.init()
    want = _rows(_drain(solo))
    solo.close()
    settings.set("sql.distsql.sharedscan.enabled", True)

    a = ScanOp(table, tile=tile)
    b = ScanOp(table, tile=tile)
    a.init()
    b.init()
    tiles_a = _drain(a)  # sprints ahead; window keeps only the last tile
    tiles_b = _drain(b)  # every earlier tile is gone: solo catch-up
    a.close()
    b.close()
    assert _rows(tiles_a) == want
    assert _rows(tiles_b) == want


def test_sharding_and_gate_off_run_solo():
    cat = _cat()
    table = cat.get("fact")
    sharded = ScanOp(table, tile=128, shard=(0, 2))
    sharded.init()
    assert sharded._shared is None  # sharded scans never share
    sharded.close()

    settings.set("sql.distsql.sharedscan.enabled", False)
    plain = ScanOp(table, tile=128)
    plain.init()
    assert plain._shared is None
    plain.close()
