"""Device top-k pushdown (plan/topkopt.py + flow/operators.TopKOp).

ORDER BY ... LIMIT plans as a per-tile k-selection instead of a full
sort + truncate (sorttopk.go's K-row heap). The contract under test is
bit-identity: the TopK plan must return exactly the rows — in exactly
the order — of the stable full sort it replaces, including when
duplicate sort keys straddle the k boundary, under OFFSET, with k > n,
with composite keys, and with pipeline fusion on or off.
"""

import numpy as np
import pytest

import cockroach_tpu.plan.topkopt  # crlint: allow-unused-import(registers sql.opt.topk.* settings before tests set them)
from cockroach_tpu import catalog as catalog_mod
from cockroach_tpu.coldata.types import INT64, Schema
from cockroach_tpu.sql.rel import Rel
from cockroach_tpu.utils import settings


@pytest.fixture(scope="module")
def cat():
    rng = np.random.default_rng(42)
    n = 5000
    c = catalog_mod.Catalog()
    c.add(catalog_mod.Table.from_strings(
        "t", Schema.of(a=INT64, b=INT64, row=INT64),
        # ~125 duplicates per value of a: any small k cuts mid-tie-run
        {"a": rng.integers(0, 40, n).astype(np.int64),
         "b": rng.integers(0, 1000, n).astype(np.int64),
         "row": np.arange(n, dtype=np.int64)}))
    return c


def _run(cat, keys, k, offset, topk, fusion=True):
    settings.set("sql.opt.topk.enabled", topk)
    settings.set("sql.distsql.fusion.enabled", fusion)
    try:
        return Rel.scan(cat, "t").sort(keys).limit(k, offset=offset).run()
    finally:
        settings.reset("sql.opt.topk.enabled")
        settings.reset("sql.distsql.fusion.enabled")


def _assert_identical(got, want):
    assert sorted(got) == sorted(want)
    for name in want:
        np.testing.assert_array_equal(
            np.asarray(got[name]), np.asarray(want[name]), err_msg=name)


CASES = [
    ([("a", False)], 50, 0),        # ascending, cut inside a tie run
    ([("a", True)], 64, 10),        # descending + OFFSET
    ([("a", False), ("b", True)], 100, 0),  # composite asc/desc
    ([("b", False)], 1, 0),         # k = 1
    ([("a", False)], 10000, 0),     # k > n: whole table
    ([("a", False)], 100, 4990),    # offset reaches past most of k
]


@pytest.mark.parametrize("keys,k,offset", CASES)
def test_topk_bit_identical_to_full_sort(cat, keys, k, offset):
    want = _run(cat, keys, k, offset, topk=False)
    got = _run(cat, keys, k, offset, topk=True)
    _assert_identical(got, want)


@pytest.mark.parametrize("fusion", [True, False])
def test_topk_fusion_on_off(cat, fusion):
    keys, k = [("a", False), ("b", True)], 77
    want = _run(cat, keys, k, 0, topk=False, fusion=False)
    got = _run(cat, keys, k, 0, topk=True, fusion=fusion)
    _assert_identical(got, want)


def test_topk_values_against_numpy(cat):
    """Independent oracle: the sort-key values of the top-k rows equal the
    numpy-sorted prefix (tie order aside, the selected multiset of keys
    is forced)."""
    k = 123
    res = _run(cat, [("a", False), ("b", False)], k, 0, topk=True)
    tbl = cat.get("t")
    a = np.asarray(tbl.columns["a"])
    b = np.asarray(tbl.columns["b"])
    order = np.lexsort((b, a))[:k]
    np.testing.assert_array_equal(np.asarray(res["a"]), a[order])
    np.testing.assert_array_equal(np.asarray(res["b"]), b[order])


def test_topk_plan_label_and_gates(cat):
    rel = Rel.scan(cat, "t").sort([("a", False)]).limit(20)
    settings.set("sql.opt.topk.enabled", True)
    try:
        assert "top-k" in rel.explain()
        settings.set("sql.opt.topk.max_k", 10)
        assert "top-k" not in rel.explain()  # k over the cap: keep the sort
        settings.reset("sql.opt.topk.max_k")
        settings.set("sql.opt.topk.enabled", False)
        assert "top-k" not in rel.explain()
    finally:
        settings.reset("sql.opt.topk.enabled")
        settings.reset("sql.opt.topk.max_k")
