"""Per-tenant admission control: token buckets, stride-scheduled fair
share, queue-depth backpressure, the shed ladder, and typed rejections
end to end through the SQL front door (utils/admission.py grown by the
overload-survival plane; the pgwire 53300 mapping is in test_pgwire.py,
chaos sites in test_chaos.py)."""

import threading
import time

import pytest

from cockroach_tpu.sql import Session
from cockroach_tpu.utils import admission, settings
from cockroach_tpu.utils.admission import (
    HIGH,
    LANE_ANALYTICAL,
    LANE_INTERACTIVE,
    LOW,
    NORMAL,
    TokenBucket,
    WorkQueue,
)
from cockroach_tpu.utils.errors import AdmissionRejectedError


# -- classification ---------------------------------------------------------


def test_classify_statement_lanes():
    assert admission.classify_statement("SELECT v FROM t WHERE k = 1") \
        == NORMAL
    assert admission.classify_statement("INSERT INTO t VALUES (1)") \
        == NORMAL
    assert admission.classify_statement("SET statement_timeout = 5") \
        == NORMAL
    # scans with joins/aggregation ride the analytical (shed-first) lane
    assert admission.classify_statement(
        "select a, sum(b) from t group by a") == LOW
    assert admission.classify_statement(
        "SELECT * FROM a JOIN b ON a.x = b.x") == LOW
    assert admission.classify_statement(
        "explain analyze select count(*) from t") == LOW
    # txn control winds down in-flight work: shed dead last
    assert admission.classify_statement("COMMIT") == HIGH
    assert admission.classify_statement("  rollback") == HIGH
    assert admission.lane_for(LOW) == LANE_ANALYTICAL
    assert admission.lane_for(NORMAL) == LANE_INTERACTIVE
    assert admission.lane_for(HIGH) == LANE_INTERACTIVE


# -- token bucket -----------------------------------------------------------


def test_token_bucket_refill_and_retry_hint():
    b = TokenBucket(rate=10.0, burst=2)
    t0 = b._t_last  # the bucket's own epoch: elapsed-time math is exact
    assert b.take(t0) == 0.0
    assert b.take(t0) == 0.0
    retry = b.take(t0)  # burst spent, no elapsed time: must hint, not 0
    assert 0.0 < retry <= 0.1
    # a bit over a tenth of a second refills one token at rate 10
    assert b.take(t0 + 0.11) == 0.0
    # refill never exceeds burst
    assert b.take(t0 + 100.0) == 0.0
    assert b.take(t0 + 100.0) == 0.0
    assert b.take(t0 + 100.0) > 0.0
    assert b.retry_after_s() > 0.0


def test_token_bucket_rate_zero_is_unlimited():
    b = TokenBucket(rate=0.0, burst=1)
    t0 = time.monotonic()
    for _ in range(1000):
        assert b.take(t0) == 0.0
    assert b.retry_after_s() == 0.0


def test_tenant_rate_limit_rejects_with_retry_hint():
    q = WorkQueue(slots=2)
    q.configure_tenant(5, rate=1.0, burst=1)
    assert q.admit(tenant_id=5)
    q.release()
    with pytest.raises(AdmissionRejectedError) as ei:
        q.admit(tenant_id=5)
    assert "rate limit" in str(ei.value)
    assert 0.0 < ei.value.retry_after_s <= 1.0
    assert ei.value.tenant_id == 5
    row = next(r for r in q.tenant_rows() if r["tenant_id"] == 5)
    assert row["admitted"] == 1 and row["rejected"] == 1
    assert q.in_use == 0


# -- queue-depth backpressure ----------------------------------------------


def test_queue_bound_rejects_typed_busy():
    q = WorkQueue(slots=1, max_queue_depth=1)
    assert q.admit(tenant_id=1)  # hold the only slot
    waiter_done = []

    def waiter():
        waiter_done.append(q.admit(tenant_id=2, timeout=10.0))
        q.release()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = time.time() + 5.0
    while q.queue_depth < 1 and time.time() < deadline:
        time.sleep(0.001)
    assert q.queue_depth == 1
    # depth is at the bound: the next arrival fails fast, typed
    with pytest.raises(AdmissionRejectedError) as ei:
        q.admit(tenant_id=3)
    assert "queue full" in str(ei.value)
    assert ei.value.retry_after_s > 0.0
    assert q.rejections_by_reason
    q.release()  # grant rides to the queued waiter
    t.join(timeout=10.0)
    assert waiter_done == [True]
    assert q.in_use == 0 and q.queue_depth == 0


# -- stride fair share ------------------------------------------------------


def _grant_order(q, arrivals, hold_release):
    """Enqueue ``arrivals`` = [(name, tenant_id), ...] while the slot is
    held, then release repeatedly; each granted thread records its name
    and hands the slot on. Returns the recorded grant order."""
    order = []
    lock = threading.Lock()

    def worker(name, tid):
        assert q.admit(tenant_id=tid, timeout=30.0)
        with lock:
            order.append(name)
        q.release()

    threads = []
    for name, tid in arrivals:
        t = threading.Thread(target=worker, args=(name, tid), daemon=True)
        t.start()
        deadline = time.time() + 5.0
        while q.queue_depth < len(threads) + 1 and time.time() < deadline:
            time.sleep(0.001)
        threads.append(t)
    hold_release()
    for t in threads:
        t.join(timeout=30.0)
    return order


def test_fair_share_well_behaved_not_starved_by_flood():
    """A tenant that has been hammering the queue carries a higher
    virtual time; an idle tenant's arrival clamps to the scheduler floor
    and wins the next grant past the whole queued backlog."""
    q = WorkQueue(slots=1)
    noisy, well = 2, 3
    for _ in range(4):  # noisy builds vtime lag through real grants
        assert q.admit(tenant_id=noisy)
        q.release()
    assert q.admit(tenant_id=1)  # park the slot so arrivals queue
    order = _grant_order(
        q,
        [(f"n{i}", noisy) for i in range(4)] + [("well", well)],
        q.release)
    assert order[0] == "well", order
    assert q.in_use == 0 and q.queue_depth == 0


def test_configure_tenant_weight_scales_vtime():
    """Weighted stride: each grant advances vtime by 1/weight, so a
    weight-2 tenant accumulates half the virtual time for the same
    number of grants (twice the fair share under contention)."""
    q = WorkQueue(slots=1)
    q.configure_tenant(7, weight=2.0)
    q.configure_tenant(8, weight=1.0)
    assert q.admit(tenant_id=1)
    order = _grant_order(
        q,
        [("a0", 7), ("b0", 8), ("a1", 7), ("b1", 8)],
        q.release)
    assert sorted(order) == ["a0", "a1", "b0", "b1"]
    rows = {r["tenant_id"]: r for r in q.tenant_rows()}
    assert rows[7]["weight"] == 2.0
    assert rows[7]["vtime"] == pytest.approx(rows[7]["admitted"] * 0.5)
    assert rows[8]["vtime"] == pytest.approx(float(rows[8]["admitted"]))


def test_lane_depth_gauges_track_queue():
    q = WorkQueue(slots=1)
    assert q.admit(tenant_id=1)
    done = []

    def low_waiter():
        done.append(q.admit(priority=LOW, tenant_id=2, timeout=10.0))
        q.release()

    t = threading.Thread(target=low_waiter, daemon=True)
    t.start()
    deadline = time.time() + 5.0
    while q.lane_depths()[LANE_ANALYTICAL] < 1 and time.time() < deadline:
        time.sleep(0.001)
    assert q.lane_depths() == {LANE_INTERACTIVE: 0, LANE_ANALYTICAL: 1}
    q.release()
    t.join(timeout=10.0)
    assert done == [True]
    assert q.lane_depths() == {LANE_INTERACTIVE: 0, LANE_ANALYTICAL: 0}


# -- graceful shedding ------------------------------------------------------


def test_shed_ladder_from_io_health():
    try:
        assert admission.shed_floor() == LOW  # healthy: everything lands
        admission.set_io_health_provider(lambda: 1.0)
        assert admission.shed_floor() == NORMAL
        q = WorkQueue(slots=4)
        with pytest.raises(AdmissionRejectedError) as ei:
            q.admit(priority=LOW, tenant_id=2)
        assert "shedding analytical" in str(ei.value)
        assert q.admit(priority=NORMAL, tenant_id=2)
        q.release()
        admission.set_io_health_provider(lambda: 2.0)
        assert admission.shed_floor() == HIGH
        with pytest.raises(AdmissionRejectedError):
            q.admit(priority=NORMAL, tenant_id=2)
        assert q.admit(priority=HIGH, tenant_id=2)  # COMMIT still lands
        q.release()
        # a broken provider reads healthy, never takes admission down
        admission.set_io_health_provider(lambda: 1 / 0)
        assert admission.shed_floor() == LOW
    finally:
        admission.set_io_health_provider(None)
    assert admission.shed_floor() == LOW


def test_shed_ladder_from_memory_pressure():
    lo = settings.get("admission.shed.mem_low")
    hi = settings.get("admission.shed.mem_high")
    try:
        settings.set("admission.shed.mem_low", 0.0)
        assert admission.shed_floor() == NORMAL
        settings.set("admission.shed.mem_high", 0.0)
        assert admission.shed_floor() == HIGH
    finally:
        settings.set("admission.shed.mem_low", lo)
        settings.set("admission.shed.mem_high", hi)
    assert admission.shed_floor() == LOW


# -- sql_slot: typed rejections, statement deadline -------------------------


def test_sql_slot_raises_typed_on_timeout_instead_of_running_slotless():
    """The old bug: sql_slot discarded admit()'s verdict and ran WITHOUT
    a slot when the wait timed out. Now the timeout surfaces as the
    typed 53300-shaped rejection and no slot is held."""
    saved = admission._SQL_QUEUE
    q = WorkQueue(slots=1)
    admission._SQL_QUEUE = q
    try:
        assert q.admit(tenant_id=1)  # park the only slot
        t0 = time.perf_counter()
        with pytest.raises(AdmissionRejectedError) as ei:
            with admission.sql_slot(
                    deadline=time.monotonic() + 0.05):
                pytest.fail("must not run without a slot")
        assert "deadline" in str(ei.value)
        assert time.perf_counter() - t0 < 5.0
        # an already-expired deadline rejects before queuing at all
        with pytest.raises(AdmissionRejectedError) as ei:
            with admission.sql_slot(deadline=time.monotonic() - 1.0):
                pytest.fail("must not run without a slot")
        assert "before admission" in str(ei.value)
        q.release()
        assert q.in_use == 0 and q.queue_depth == 0
    finally:
        admission._SQL_QUEUE = saved


def test_statement_timeout_counts_queue_wait_through_session():
    sess = Session()
    saved = admission._SQL_QUEUE
    q = WorkQueue(slots=1)
    admission._SQL_QUEUE = q
    try:
        sess.execute("SET statement_timeout = 80")
        assert q.admit(tenant_id=1)  # saturate: the statement must queue
        with pytest.raises(AdmissionRejectedError):
            sess.execute("SELECT 1")
        q.release()
        # deadline cleared: same statement admits and runs
        sess.execute("SET statement_timeout = 0")
        assert sess.execute("SELECT 1 AS x") is not None
        assert q.in_use == 0 and q.queue_depth == 0
    finally:
        admission._SQL_QUEUE = saved
        sess.close()


# -- observability ----------------------------------------------------------


def test_crdb_internal_node_tenant_admission():
    sess = Session()
    try:
        res = sess.execute(
            "SELECT tenant_id, admitted, rejected, shed_floor "
            "FROM crdb_internal.node_tenant_admission")
        tids = [int(x) for x in res["tenant_id"]]
        # the session's own statements run as the system tenant
        assert admission.SYSTEM_TENANT_ID in tids
        i = tids.index(admission.SYSTEM_TENANT_ID)
        assert int(res["admitted"][i]) >= 1
        assert int(res["shed_floor"][i]) == admission.shed_floor()
    finally:
        sess.close()


def test_explain_analyze_shows_admission_line():
    from cockroach_tpu import sql as sqlmod
    from cockroach_tpu.bench.tpch import gen_tpch_cached

    cat = gen_tpch_cached(0.005)
    txt = sqlmod.explain(
        cat, "explain analyze select l_orderkey from lineitem "
             "where l_orderkey = 1")
    assert "admission:" in txt
    assert "lane=interactive" in txt
    assert "shed_floor=" in txt


def test_tenant_admission_caps_bind_at_session_create():
    """A tenant carrying admission_* capabilities gets its bucket/weight
    configured on the shared queue when a session binds as it."""
    from cockroach_tpu.kv.tenant import TenantRegistry

    boot = Session()
    saved = admission._SQL_QUEUE
    q = WorkQueue(slots=4)
    admission._SQL_QUEUE = q
    try:
        reg = TenantRegistry(boot.db)
        reg.bootstrap()
        rec = reg.create("capped", caps={
            "admission_rate": 7.0, "admission_burst": 3,
            "admission_weight": 2.0})
        tsess = Session(catalog=boot.catalog, db=boot.db,
                        bootstrap=False, tenant="capped")
        row = next(r for r in q.tenant_rows()
                   if r["tenant_id"] == rec.tenant_id)
        assert row["rate"] == 7.0
        assert row["burst"] == 3.0
        assert row["weight"] == 2.0
        tsess.close()
    finally:
        admission._SQL_QUEUE = saved
        boot.close()
