"""DistSender / RangeCache / multi-Store routing (kvclient reduction).

Each test asserts behavior that disappears if the wiring is removed:
cross-range scans reassemble in key order; a stale cache is detected at
the store and retried after eviction (not served wrong); transactions
spanning ranges stay atomic; move_range relocates data without losing
MVCC history or intents."""

import numpy as np

from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.dist import (
    DistSender,
    Meta,
    RangeKeyMismatchError,
    Store,
)
from cockroach_tpu.storage.lsm import WriteIntentError


def _mk(n_stores=2, **kw):
    meta = Meta(first_store=1)
    kw.setdefault("key_width", 16)
    kw.setdefault("val_width", 16)
    kw.setdefault("memtable_size", 64)
    stores = [Store(i + 1, meta, **kw) for i in range(n_stores)]
    return meta, stores, DistSender(stores, meta)


def test_split_routes_and_cross_range_scan():
    meta, stores, ds = _mk()
    db = DB(ds, Clock())
    for i in range(40):
        db.put(b"k%04d" % i, b"v%04d" % i)
    # split and move the upper half to store 2
    ds.split_at(b"k0020")
    right = meta.lookup(b"k0020")
    ds.move_range(right.range_id, to_store=2)
    # point reads route to both stores
    assert db.get(b"k0005") == b"v0005"
    assert db.get(b"k0030") == b"v0030"
    # the moved range's data actually lives in store 2's engine now
    now = db.clock.now()
    assert stores[1].engine.scan(b"k", b"l", ts=now)
    assert not stores[0].engine.scan(b"k0020", b"l", ts=now)
    # cross-range scan reassembles in key order
    rows = db.scan(b"k0010", b"k0030")
    assert [k for k, _ in rows] == [b"k%04d" % i for i in range(10, 30)]
    # max_keys stops at the limit across the boundary
    rows = db.scan(b"k0015", None, max_keys=10)
    assert [k for k, _ in rows] == [b"k%04d" % i for i in range(15, 25)]


def test_stale_cache_detected_and_refreshed():
    meta, stores, ds = _mk()
    db = DB(ds, Clock())
    for i in range(20):
        db.put(b"k%04d" % i, b"old%d" % i)
    _ = db.get(b"k0010")  # warm ds.cache with the full-keyspace descriptor
    cached = ds.cache.lookup(b"k0010")
    # another admin path splits + moves behind this sender's cache
    other = DistSender(list(ds.stores.values()), meta)
    other.split_at(b"k0010")
    right = meta.lookup(b"k0010")
    other.move_range(right.range_id, to_store=2)
    # the store bounds-check must reject the stale descriptor...
    try:
        ds.stores[cached.store_id].check(cached, b"k0015", None)
        raise AssertionError("stale descriptor passed the bounds check")
    except RangeKeyMismatchError:
        pass
    # ...and the sender transparently retries: correct data, cache evicted
    ev0 = ds.cache.evictions
    assert db.get(b"k0015") == b"old15"
    assert ds.cache.evictions > ev0


def test_txn_atomic_across_ranges():
    meta, stores, ds = _mk()
    db = DB(ds, Clock())
    ds.split_at(b"m")
    right = meta.lookup(b"m")
    ds.move_range(right.range_id, to_store=2)

    def op(t):
        t.put(b"a1", b"left")
        t.put(b"z1", b"right")

    db.txn(op)
    assert db.get(b"a1") == b"left" and db.get(b"z1") == b"right"

    # a failing txn leaves NO intents on either store
    class Boom(Exception):
        pass

    def bad(t):
        t.put(b"a2", b"x")
        t.put(b"z2", b"y")
        raise Boom

    try:
        db.txn(bad)
        raise AssertionError("txn should have raised")
    except Boom:
        pass
    assert db.get(b"a2") is None and db.get(b"z2") is None
    for s in stores:
        assert not s.engine._locks  # no orphaned intents on either store


def test_move_range_preserves_history_and_intents():
    meta, stores, ds = _mk()
    db = DB(ds, Clock())
    ts1 = db.put(b"h1", b"v1")
    db.put(b"h1", b"v2")
    db.delete(b"h2")  # tombstone history
    db.put(b"h2", b"v3")
    t = db.new_txn()
    t.put(b"h3", b"pending")
    ds.split_at(b"h")
    ds.split_at(b"i")
    mid = meta.lookup(b"h")
    moved = ds.move_range(mid.range_id, to_store=2)
    assert moved >= 4  # both h1 versions + h2 tombstone + h2 + h3 intent
    # old versions still visible at their timestamps
    assert db.get(b"h1", ts=ts1) == b"v1"
    assert db.get(b"h1") == b"v2"
    assert db.get(b"h2") == b"v3"
    # the intent moved too: reads conflict until the txn resolves
    try:
        db.get(b"h3")
        raise AssertionError("expected WriteIntentError on moved intent")
    except WriteIntentError:
        pass
    t.commit()
    assert db.get(b"h3") == b"pending"


def test_scan_batch_groups_by_store():
    meta, stores, ds = _mk()
    db = DB(ds, Clock())
    for i in range(64):
        db.put(b"k%04d" % i, b"v%04d" % i)
    ds.split_at(b"k0032")
    ds.move_range(meta.lookup(b"k0032").range_id, to_store=2)
    starts = [b"k0000", b"k0030", b"k0040", b"k0010"]
    got = ds.scan_batch(starts, ts=db.clock.now(), max_keys=8)
    for s, rows in zip(starts, got):
        lo = int(s[1:5])
        want = [b"k%04d" % i for i in range(lo, min(lo + 8, 64))]
        assert [k for k, _ in rows] == want, (s, rows[:3])
    # the boundary-crossing scan (k0030) spans both stores
    assert got[1][0][0] == b"k0030" and got[1][-1][0] == b"k0037"


def test_move_range_durable_across_crash(tmp_path):
    """The relocation primitives are WAL-logged: after move_range, killing
    and reopening BOTH stores from their WALs keeps the moved data on the
    destination and does NOT resurrect it on the source."""
    from cockroach_tpu.storage.lsm import Engine

    meta = Meta(first_store=1)
    kw = dict(key_width=16, val_width=16, memtable_size=64)
    stores = [
        Store(1, meta, wal_path=str(tmp_path / "s1.wal"), **kw),
        Store(2, meta, wal_path=str(tmp_path / "s2.wal"), **kw),
    ]
    ds = DistSender(stores, meta)
    db = DB(ds, Clock())
    for i in range(30):
        db.put(b"d%04d" % i, b"v%04d" % i)
    ds.split_at(b"d0015")
    right = meta.lookup(b"d0015")
    ds.move_range(right.range_id, to_store=2)
    now = db.clock.now()

    # "crash": reopen both engines from their WALs (no checkpoint taken)
    stores[0].engine.close()
    stores[1].engine.close()
    e1 = Engine(wal_path=str(tmp_path / "s1.wal"), **kw)
    e2 = Engine(wal_path=str(tmp_path / "s2.wal"), **kw)
    # destination kept the moved rows
    got2 = e2.scan(b"d0015", b"e", ts=now)
    assert [k for k, _ in got2] == [b"d%04d" % i for i in range(15, 30)]
    # source did NOT resurrect them; its own half is intact
    assert not e1.scan(b"d0015", b"e", ts=now)
    got1 = e1.scan(b"d0000", b"d0015", ts=now)
    assert [k for k, _ in got1] == [b"d%04d" % i for i in range(15)]


def test_sql_over_multi_range_keyspace():
    """SQL runs over a DB whose sender routes a SPLIT keyspace across two
    stores: the columnar scan path reads the cross-store merged view, DML
    routes writes by range, and results match a single-store run."""
    from cockroach_tpu.sql.session import Session

    meta = Meta(first_store=1)
    kw = dict(key_width=16, val_width=128, memtable_size=256)
    stores = [Store(1, meta, **kw), Store(2, meta, **kw)]
    ds = DistSender(stores, meta)
    sess = Session(db=DB(ds, Clock()))
    sess.execute("create table kvt (id int primary key, g int, x int)")
    sess.execute(
        "insert into kvt values " + ", ".join(
            f"({i}, {i % 5}, {i * 3})" for i in range(200))
    )
    # split the keyspace INSIDE the table's span and rebalance
    from cockroach_tpu.storage import rowcodec

    t = sess.catalog.get("kvt")
    start, end = rowcodec.table_span(t.table_id)
    now0 = sess.db.clock.now()
    all_keys = [k for k, _ in ds.scan(start, end, ts=now0)]
    assert len(all_keys) >= 200
    ds.split_at(all_keys[100])  # split at the 100th row's actual key
    descs = meta.snapshot()
    ds.move_range(descs[-1].range_id, to_store=2)
    # both stores now hold table rows
    now = sess.db.clock.now()
    assert stores[0].engine.scan(start, None, ts=now, max_keys=1)
    assert stores[1].engine.scan(start, None, ts=now, max_keys=1)
    # full scan + aggregate see every row across both stores
    res = sess.execute("select count(*) as n, sum(x) as sx from kvt")
    assert int(res["n"][0]) == 200
    assert int(res["sx"][0]) == sum(i * 3 for i in range(200))
    # post-split DML routes by range: update a row on each side
    sess.execute("update kvt set x = -1 where id = 10")
    sess.execute("update kvt set x = -2 where id = 150")
    res = sess.execute("select x from kvt where id in (10, 150) order by x")
    assert list(res["x"]) == [-2, -1]
    res = sess.execute("select g, count(*) as c from kvt group by g order by g")
    assert list(res["c"]) == [40] * 5


def test_kvnemesis_with_splits_and_moves():
    """kvnemesis over a MULTI-RANGE keyspace: random txn RMWs, blind
    writes, deletes and scans interleave with admin SPLITs and range
    MOVES between stores. Every read must match a sequential dict model —
    a lost write, a resurrected cleared key, or a scan that drops a
    boundary row fails loudly (the reference's kvnemesis runs exactly
    this shape with real splits/merges, pkg/kv/kvnemesis/doc.go)."""
    meta, stores, ds = _mk(n_stores=3, memtable_size=32)
    db = DB(ds, Clock())
    rng = np.random.default_rng(23)
    model: dict[bytes, bytes] = {}

    def key(i: int) -> bytes:
        return b"q%05d" % i

    for step in range(160):
        kind = rng.random()
        if kind < 0.08:
            # admin split at a random key (metadata only)
            at = key(int(rng.integers(1, 300)))
            ds.split_at(at)
            continue
        if kind < 0.16 and len(meta.snapshot()) > 1:
            # relocate a random range to a random store
            descs = meta.snapshot()
            d = descs[int(rng.integers(len(descs)))]
            to = int(rng.integers(1, 4))
            ds.move_range(d.range_id, to)
            continue
        if kind < 0.55:
            # txn RMW over two COUNTER keys (possibly in different
            # ranges; counters use the low half of the keyspace, blind
            # string writes the high half)
            k1 = key(int(rng.integers(0, 150)))
            k2 = key(int(rng.integers(0, 150)))

            def op(t, k1=k1, k2=k2):
                a = int(t.get(k1) or b"0")
                b = int(t.get(k2) or b"0")
                t.put(k1, str(a + 1).encode())
                if k2 != k1:
                    t.put(k2, str(b + 2).encode())

            db.txn(op)
            a = int(model.get(k1, b"0"))
            b = int(model.get(k2, b"0"))
            model[k1] = str(a + 1).encode()
            if k2 != k1:
                model[k2] = str(b + 2).encode()
        elif kind < 0.7:
            k = key(int(rng.integers(150, 300)))
            v = b"s%04d" % step
            db.put(k, v)
            model[k] = v
        elif kind < 0.8:
            k = key(int(rng.integers(150, 300)))
            db.delete(k)
            model.pop(k, None)
        elif kind < 0.9:
            # point reads across the split keyspace
            for _ in range(4):
                k = key(int(rng.integers(0, 300)))
                assert db.get(k) == model.get(k), (step, k)
        else:
            # bounded scan, possibly crossing range boundaries
            lo = int(rng.integers(0, 280))
            hi = lo + int(rng.integers(1, 40))
            got = db.scan(key(lo), key(hi), max_keys=16)
            want = sorted(
                (k, v) for k, v in model.items()
                if key(lo) <= k < key(hi)
            )[:16]
            assert got == want, (step, lo, hi, got[:3], want[:3])

    # final full sweep: every key, every store, exactly the model
    got = dict(db.scan(key(0), key(99999)))
    assert got == model
    assert len(meta.snapshot()) > 3  # splits actually happened
    moved = {d.store_id for d in meta.snapshot()}
    assert len(moved) > 1  # ranges actually live on multiple stores


def test_show_ranges_through_sql():
    """SHOW RANGES reflects the Meta descriptor table on a DistSender-
    backed session (and a synthetic whole-keyspace range otherwise)."""
    from cockroach_tpu.sql.session import Session

    meta = Meta(first_store=1)
    kw = dict(key_width=16, val_width=128, memtable_size=256)
    stores = [Store(1, meta, **kw), Store(2, meta, **kw)]
    ds = DistSender(stores, meta)
    sess = Session(db=DB(ds, Clock()))
    sess.execute("create table rr (id int primary key)")
    sess.execute("insert into rr values (1), (2)")
    ds.split_at(b"\x05")
    ds.move_range(meta.lookup(b"\x05").range_id, 2)
    res = sess.execute("show ranges")
    assert list(res["range_id"]) == [1, 2]
    assert list(res["store_id"]) == [1, 2]

    plain = Session()
    res = plain.execute("show ranges")
    assert list(res["range_id"]) == [1]


def test_lease_guard_stamps_every_piece_across_autosplit():
    """The ROADMAP open item, closed: range-addressed lease stamping on
    the DistSender path survives an auto-split. The guard checks the
    (holder, epoch) pair per ROUTED PIECE, so after a split + lease
    carry a multi-range op validates BOTH children — and once the
    holder's epoch is fenced, every piece (including the child range
    minted after wiring) refuses with a typed error."""
    import threading

    import pytest

    from cockroach_tpu.kv import liveness as lv
    from cockroach_tpu.kv.liveness import (EpochFencedError, LeaseManager,
                                           NodeLiveness)

    meta, stores, ds = _mk()
    db = DB(ds, Clock())
    nl = NodeLiveness(db, 1, ttl_ms=600_000)
    nl.heartbeat()
    lm = LeaseManager(nl)
    lm.acquire(1)
    checked = []
    local = threading.local()

    def guard(rid):  # the Node._dist_lease_check shape, instrumented
        if getattr(local, "busy", False):
            return
        local.busy = True
        try:
            checked.append(rid)
            rec = lm.holder(rid)
            if rec is not None and rec.node_id == 1:
                lm.check(rid)
        finally:
            local.busy = False

    ds.lease_check = guard
    for i in range(20):
        db.put(b"u%04d" % i, b"v%d" % i)
    # auto-split shape: boundary appears, lease carries to the child
    left, right = meta.split_at(b"u0010")
    assert lm.carry(left.range_id, right.range_id) is not None
    assert (lm.holder(right.range_id).epoch
            == lm.holder(left.range_id).epoch)
    ds.move_range(right.range_id, to_store=2)
    # a span crossing the boundary routes two pieces; the guard saw the
    # child's id too (per-piece stamping, not per-batch)
    checked.clear()
    rows = db.scan(b"u0005", b"u0015")
    assert [k for k, _ in rows] == [b"u%04d" % i for i in range(5, 15)]
    assert {left.range_id, right.range_id} <= set(checked)
    # fence the holder: bump its liveness epoch behind its back
    raw = db.get(NodeLiveness._key(1))
    epoch, exp, nid = lv._REC.unpack(raw)
    db.put(NodeLiveness._key(1), lv._REC.pack(epoch + 1, exp, nid))
    # every piece now fails the epoch equality — parent AND child
    with pytest.raises(EpochFencedError):
        db.put(b"u0002", b"stale")
    with pytest.raises(EpochFencedError):
        db.put(b"u0012", b"stale")
    with pytest.raises(EpochFencedError):
        db.scan(b"u0005", b"u0015")


def test_range_cache_single_flight_coalesces_meta_lookups():
    """Concurrent cache misses for the same key coalesce into ONE meta
    lookup (the singleflight discipline): followers block on the
    leader's in-flight event instead of stampeding the meta range."""
    import threading
    import time as _time

    from cockroach_tpu.kv.dist import RangeCache

    meta = Meta(first_store=1)
    Store(1, meta, key_width=16, val_width=16)

    class SlowMeta:
        """Meta proxy whose lookup is slow enough that every thread is
        in flight together."""

        def __init__(self, inner):
            self.inner = inner
            self.lookups = 0

        def lookup(self, key):
            self.lookups += 1
            _time.sleep(0.05)
            return self.inner.lookup(key)

    slow = SlowMeta(meta)
    cache = RangeCache(slow)
    got, errs = [], []
    start = threading.Barrier(8)

    def worker():
        try:
            start.wait()
            got.append(cache.lookup(b"sf-key"))
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(got) == 8 and len({d.range_id for d in got}) == 1
    assert slow.lookups == 1, "lookup stampede: single-flight broken"
    assert cache.coalesced >= 7
    # hits after install never touch meta
    cache.lookup(b"sf-key")
    assert slow.lookups == 1
