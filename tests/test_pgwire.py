"""pgwire protocol tests — a minimal hand-rolled v3 client (no Postgres
driver ships in this image; the reference likewise tests conn.go at the
message level, pkg/sql/pgwire/conn_test.go). Covers startup, simple
queries with text results, NULLs, DML tags, transaction status in
ReadyForQuery, error/recovery, and two concurrent sessions."""

import socket
import struct

import pytest

from cockroach_tpu.server.pgwire import PgServer
from cockroach_tpu.sql import Session


class MiniPg:
    """Just enough of the v3 protocol to drive the server."""

    def __init__(self, addr):
        self.sock = socket.create_connection(addr, timeout=30)
        body = struct.pack("!I", 196608) + b"user\x00t\x00\x00"
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self.txn_status = None
        self._drain_until_ready()

    def _recv_exact(self, n):
        buf = bytearray()
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            assert c, "server closed"
            buf.extend(c)
        return bytes(buf)

    def _msg(self):
        tag = self._recv_exact(1)
        n = struct.unpack("!I", self._recv_exact(4))[0]
        return tag, self._recv_exact(n - 4)

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, body = self._msg()
            msgs.append((tag, body))
            if tag == b"Z":
                self.txn_status = body
                return msgs

    def query(self, sql):
        """-> (rows as lists of str|None, command_tag, error|None)"""
        body = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        rows, names, tag_line, err = [], None, None, None
        for tag, body in self._drain_until_ready():
            if tag == b"T":
                ncols = struct.unpack("!H", body[:2])[0]
                names = []
                off = 2
                for _ in range(ncols):
                    end = body.index(b"\x00", off)
                    names.append(body[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                ncols = struct.unpack("!H", body[:2])[0]
                off = 2
                row = []
                for _ in range(ncols):
                    ln = struct.unpack("!i", body[off:off + 4])[0]
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(body[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif tag == b"C":
                tag_line = body.rstrip(b"\x00").decode()
            elif tag == b"E":
                err = body.decode(errors="replace")
        return rows, names, tag_line, err

    def close(self):
        self.sock.sendall(b"X" + struct.pack("!I", 4))
        self.sock.close()


@pytest.fixture
def server():
    sess = Session()
    srv = PgServer(catalog=sess.catalog, db=sess.db).serve_background()
    yield srv
    srv.close()


def test_pgwire_end_to_end(server):
    c = MiniPg(server.addr)
    assert c.txn_status == b"I"
    _, _, tag, err = c.query(
        "create table t (a int primary key, b int, s string)")
    assert err is None and tag == "CREATE TABLE"
    _, _, tag, err = c.query(
        "insert into t values (1, 10, 'x'), (2, null, 'y')")
    assert err is None and tag == "INSERT 0 2"
    rows, names, tag, err = c.query("select a, b, s from t order by a")
    assert err is None
    assert names == ["a", "b", "s"]
    assert rows == [["1", "10", "x"], ["2", None, "y"]]
    assert tag == "SELECT 2"
    c.close()


def test_pgwire_txn_status_and_errors(server):
    c = MiniPg(server.addr)
    c.query("create table u (a int primary key)")
    c.query("begin")
    assert c.txn_status == b"T"  # in a block
    c.query("insert into u values (1)")
    # an error aborts the block: status E, statements rejected
    _, _, _, err = c.query("select nope from u")
    assert err is not None
    assert c.txn_status == b"E"
    _, _, _, err = c.query("insert into u values (2)")
    assert err is not None and "aborted" in err
    c.query("rollback")
    assert c.txn_status == b"I"
    rows, _, _, err = c.query("select count(*) from u")
    assert err is None and rows == [["0"]]
    # errors outside a block recover to idle
    _, _, _, err = c.query("select broken syntax here")
    assert err is not None
    assert c.txn_status == b"I"
    c.close()


def test_pgwire_two_concurrent_sessions(server):
    a = MiniPg(server.addr)
    b = MiniPg(server.addr)
    a.query("create table shared (k int primary key, v int)")
    a.query("insert into shared values (1, 100)")
    # session A opens a txn and writes; B (its own session) stays idle
    a.query("begin")
    a.query("update shared set v = 200 where k = 1")
    assert a.txn_status == b"T"
    assert b.txn_status == b"I"
    # B's read hits A's intent -> serialization failure with SQLSTATE 40001
    _, _, _, err = b.query("select v from shared")
    assert err is not None and "40001" in err
    a.query("commit")
    rows, _, _, err = b.query("select v from shared")
    assert err is None and rows == [["200"]]
    a.close()
    b.close()


def test_pgwire_through_node_lifecycle():
    from cockroach_tpu.server.node import Node

    node = Node(node_id=4, heartbeat_interval_s=0.1)
    node.start(gossip_port=None, pg_port=0)
    try:
        c = MiniPg(node.pg.addr)
        c.query("create table nt (a int primary key)")
        c.query("insert into nt values (7)")
        rows, _, _, err = c.query("select a from nt")
        assert err is None and rows == [["7"]]
        c.close()
    finally:
        node.stop()
