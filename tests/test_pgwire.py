"""pgwire protocol tests — a minimal hand-rolled v3 client (no Postgres
driver ships in this image; the reference likewise tests conn.go at the
message level, pkg/sql/pgwire/conn_test.go). Covers startup, simple
queries with text results, NULLs, DML tags, transaction status in
ReadyForQuery, error/recovery, and two concurrent sessions."""

import socket
import struct

import pytest

from cockroach_tpu.server.pgwire import PgServer
from cockroach_tpu.sql import Session


class MiniPg:
    """Just enough of the v3 protocol to drive the server."""

    def __init__(self, addr):
        self.sock = socket.create_connection(addr, timeout=30)
        body = struct.pack("!I", 196608) + b"user\x00t\x00\x00"
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self.txn_status = None
        self._drain_until_ready()

    def _recv_exact(self, n):
        buf = bytearray()
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            assert c, "server closed"
            buf.extend(c)
        return bytes(buf)

    def _msg(self):
        tag = self._recv_exact(1)
        n = struct.unpack("!I", self._recv_exact(4))[0]
        return tag, self._recv_exact(n - 4)

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, body = self._msg()
            msgs.append((tag, body))
            if tag == b"Z":
                self.txn_status = body
                return msgs

    def query(self, sql):
        """-> (rows as lists of str|None, command_tag, error|None)"""
        body = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        rows, names, tag_line, err = [], None, None, None
        for tag, body in self._drain_until_ready():
            if tag == b"T":
                ncols = struct.unpack("!H", body[:2])[0]
                names = []
                off = 2
                for _ in range(ncols):
                    end = body.index(b"\x00", off)
                    names.append(body[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                ncols = struct.unpack("!H", body[:2])[0]
                off = 2
                row = []
                for _ in range(ncols):
                    ln = struct.unpack("!i", body[off:off + 4])[0]
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(body[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif tag == b"C":
                tag_line = body.rstrip(b"\x00").decode()
            elif tag == b"E":
                err = body.decode(errors="replace")
        return rows, names, tag_line, err

    def close(self):
        self.sock.sendall(b"X" + struct.pack("!I", 4))
        self.sock.close()


@pytest.fixture
def server():
    sess = Session()
    srv = PgServer(catalog=sess.catalog, db=sess.db).serve_background()
    yield srv
    srv.close()


def test_pgwire_end_to_end(server):
    c = MiniPg(server.addr)
    assert c.txn_status == b"I"
    _, _, tag, err = c.query(
        "create table t (a int primary key, b int, s string)")
    assert err is None and tag == "CREATE TABLE"
    _, _, tag, err = c.query(
        "insert into t values (1, 10, 'x'), (2, null, 'y')")
    assert err is None and tag == "INSERT 0 2"
    rows, names, tag, err = c.query("select a, b, s from t order by a")
    assert err is None
    assert names == ["a", "b", "s"]
    assert rows == [["1", "10", "x"], ["2", None, "y"]]
    assert tag == "SELECT 2"
    c.close()


def test_pgwire_txn_status_and_errors(server):
    c = MiniPg(server.addr)
    c.query("create table u (a int primary key)")
    c.query("begin")
    assert c.txn_status == b"T"  # in a block
    c.query("insert into u values (1)")
    # an error aborts the block: status E, statements rejected
    _, _, _, err = c.query("select nope from u")
    assert err is not None
    assert c.txn_status == b"E"
    _, _, _, err = c.query("insert into u values (2)")
    assert err is not None and "aborted" in err
    c.query("rollback")
    assert c.txn_status == b"I"
    rows, _, _, err = c.query("select count(*) from u")
    assert err is None and rows == [["0"]]
    # errors outside a block recover to idle
    _, _, _, err = c.query("select broken syntax here")
    assert err is not None
    assert c.txn_status == b"I"
    c.close()


def test_pgwire_two_concurrent_sessions(server):
    a = MiniPg(server.addr)
    b = MiniPg(server.addr)
    a.query("create table shared (k int primary key, v int)")
    a.query("insert into shared values (1, 100)")
    # session A opens a txn and writes; B (its own session) stays idle
    a.query("begin")
    a.query("update shared set v = 200 where k = 1")
    assert a.txn_status == b"T"
    assert b.txn_status == b"I"
    # B's read hits A's intent -> serialization failure with SQLSTATE 40001
    _, _, _, err = b.query("select v from shared")
    assert err is not None and "40001" in err
    a.query("commit")
    rows, _, _, err = b.query("select v from shared")
    assert err is None and rows == [["200"]]
    a.close()
    b.close()


def test_pgwire_through_node_lifecycle():
    from cockroach_tpu.server.node import Node

    node = Node(node_id=4, heartbeat_interval_s=0.1)
    node.start(gossip_port=None, pg_port=0)
    try:
        c = MiniPg(node.pg.addr)
        c.query("create table nt (a int primary key)")
        c.query("insert into nt values (7)")
        rows, _, _, err = c.query("select a from nt")
        assert err is None and rows == [["7"]]
        c.close()
    finally:
        node.stop()


class MiniPgExt(MiniPg):
    """Extended-protocol messages (Parse/Bind/Describe/Execute/Sync)."""

    def _send_msg(self, tag: bytes, body: bytes):
        self.sock.sendall(tag + struct.pack("!I", len(body) + 4) + body)

    def prepare(self, name: str, sql: str):
        self._send_msg(b"P", name.encode() + b"\x00" + sql.encode()
                       + b"\x00" + struct.pack("!H", 0))

    def bind(self, portal: str, stmt: str, params: list):
        body = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
        body += struct.pack("!H", 1) + struct.pack("!H", 0)  # all text
        body += struct.pack("!H", len(params))
        for p in params:
            if p is None:
                body += struct.pack("!i", -1)
            else:
                pb = str(p).encode()
                body += struct.pack("!i", len(pb)) + pb
        body += struct.pack("!H", 0)  # result formats: default text
        self._send_msg(b"B", body)

    def describe_portal(self, portal: str):
        self._send_msg(b"D", b"P" + portal.encode() + b"\x00")

    def execute(self, portal: str):
        self._send_msg(b"E", portal.encode() + b"\x00"
                       + struct.pack("!i", 0))

    def sync(self):
        self._send_msg(b"S", b"")
        return self._drain_until_ready()


def test_pgwire_extended_protocol(server):
    c = MiniPgExt(server.addr)
    try:
        c.query("create table ep (id int primary key, v int, s string)")
        c.query("insert into ep values (1, 10, 'a'), (2, 20, 'b'),"
                " (3, 30, 'it''s')")
        # Parse/Bind/Describe/Execute with int + string parameters
        c.prepare("sel", "select id, v, s from ep where v > $1 and s <> $2"
                         " order by id")
        c.bind("", "sel", ["15", "zzz"])
        c.describe_portal("")
        c.execute("")
        msgs = c.sync()
        tags = [t for t, _ in msgs]
        assert b"1" in tags and b"2" in tags  # Parse/BindComplete
        assert b"T" in tags  # RowDescription from Describe
        drows = [b for t, b in msgs if t == b"D"]
        assert len(drows) == 2  # v in (20, 30)
        assert b"E" not in tags
        # RowDescription came ONLY from Describe, before the DataRows
        assert tags.index(b"T") < tags.index(b"D")

        # rebind same statement with different params (incl. quote escape)
        c.bind("", "sel", ["0", "it's"])
        c.execute("")
        msgs = c.sync()
        drows = [b for t, b in msgs if t == b"D"]
        assert len(drows) == 2  # id 1 and 2 (id 3's s matches $2)

        # NULL parameter: v > NULL matches nothing
        c.bind("", "sel", [None, "zzz"])
        c.execute("")
        msgs = c.sync()
        assert [b for t, b in msgs if t == b"D"] == []

        # DML through the extended path + NoData describe
        c.prepare("ins", "insert into ep values ($1, $2, $3)")
        c.bind("", "ins", ["4", "40", "d"])
        c.describe_portal("")
        c.execute("")
        msgs = c.sync()
        tags = [t for t, _ in msgs]
        assert b"n" in tags  # NoData
        assert any(t == b"C" and b"INSERT" in b for t, b in msgs)
        rows, _, _, _ = c.query("select count(*) as n from ep")
        assert rows == [["4"]]

        # error recovery: unknown portal fails ONCE, Sync recovers
        c.execute("nope")
        c.execute("nope")  # discarded (post-error, pre-Sync)
        msgs = c.sync()
        errs = [b for t, b in msgs if t == b"E"]
        assert len(errs) == 1
        rows, _, _, err = c.query(
            "select count(*) as one from ep where id = 1")
        assert err is None and rows == [["1"]]
    finally:
        c.close()


def test_pgwire_describe_statement_and_param_edge_cases(server):
    c = MiniPgExt(server.addr)
    try:
        c.query("create table dx (id int primary key, s string)")
        c.query("insert into dx values (1, 'a')")
        # Describe STATEMENT: ParameterDescription then RowDescription
        c.prepare("ds", "select id, s from dx where id = $1")
        c._send_msg(b"D", b"Sds\x00")
        msgs = c.sync()
        tags = [t for t, _ in msgs]
        assert b"t" in tags and b"T" in tags
        tbody = next(b for t, b in msgs if t == b"t")
        assert struct.unpack("!H", tbody[:2])[0] == 1  # one placeholder
        # a param VALUE containing '$1' must not be re-substituted
        c.prepare("p2", "select id from dx where s <> $1 and s <> $2")
        c.bind("", "p2", ["x", "$1"])
        c.execute("")
        msgs = c.sync()
        assert len([b for t, b in msgs if t == b"D"]) == 1
        assert not any(t == b"E" for t, _ in msgs)
        # binary result format is rejected, not silently mis-encoded
        body = (b"\x00" + b"p2\x00" + struct.pack("!H", 0)
                + struct.pack("!H", 2)
                + struct.pack("!i", 1) + b"x"
                + struct.pack("!i", 1) + b"y"
                + struct.pack("!HH", 1, 1))  # result format: binary
        c._send_msg(b"B", body)
        msgs = c.sync()
        assert any(t == b"E" and b"binary result" in b for t, b in msgs)
    finally:
        c.close()


def test_placeholder_inside_string_literal_is_text(server):
    c = MiniPgExt(server.addr)
    try:
        c.query("create table lt (id int primary key, s string)")
        c.query("insert into lt values (1, 'a$1b'), (2, 'x')")
        # '$1' inside the prepared SQL's literal is TEXT, not a param
        c.prepare("q", "select id from lt where s = 'a$1b' and id = $1")
        c.bind("", "q", ["1"])
        c.execute("")
        msgs = c.sync()
        assert len([b for t, b in msgs if t == b"D"]) == 1
        assert not any(t == b"E" for t, _ in msgs)
    finally:
        c.close()


def test_pgwire_extended_rebind_rides_plan_cache():
    """Parse-once/Bind-many through the extended protocol must hit the
    prepared-plan cache on every rebind: the inlined literals reach
    Session.execute, sql/plancache.py re-parameterizes them back out, and
    the repeat serves with zero new XLA compiles."""
    from cockroach_tpu.flow import dispatch
    from cockroach_tpu.sql import plancache

    sess = Session()
    srv = PgServer(catalog=sess.catalog, db=sess.db).serve_background()
    try:
        c = MiniPgExt(srv.addr)
        c.query("create table pc (id int primary key, v int)")
        c.query("insert into pc values (1, 10), (2, 20), (3, 30)")
        c.prepare("sel", "select v from pc where id = $1")
        c.bind("", "sel", ["1"])
        c.execute("")
        c.sync()
        cache = plancache.cache_for(sess.catalog)
        h0, c0 = cache.hits, dispatch.compiles()
        c.bind("", "sel", ["2"])
        c.execute("")
        msgs = c.sync()
        rows = [b for t, b in msgs if t == b"D"]
        assert len(rows) == 1 and rows[0].endswith(b"20")
        assert cache.hits == h0 + 1
        assert dispatch.compiles() == c0  # zero-recompile serving path
        c.close()
    finally:
        srv.close()


def test_pgwire_overload_sheds_typed_53300_not_hang_or_drop():
    """Overload at the wire: with one slot held and a depth-1 queue, a
    first client queues (not dropped) and a second is refused with
    SQLSTATE 53300 on an open, still-usable connection (never a hang,
    never a connection teardown). Once the slot frees, the queued
    statement completes and the refused client's retry succeeds."""
    import time

    from cockroach_tpu.utils import admission

    sess = Session()
    srv = PgServer(catalog=sess.catalog, db=sess.db).serve_background()
    saved = admission._SQL_QUEUE
    q = admission.WorkQueue(slots=1, max_queue_depth=1)
    admission._SQL_QUEUE = q
    c1 = c2 = None
    try:
        assert q.admit(tenant_id=1)  # the test parks the only slot
        c1 = MiniPg(srv.addr)
        c2 = MiniPg(srv.addr)
        # c1 issues a statement but we don't read the reply yet: its
        # server thread must be sitting in the admission queue
        body = b"select 1\x00"
        c1.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        deadline = time.time() + 10.0
        while q.queue_depth < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert q.queue_depth == 1, "first statement never queued"
        # queue at its bound: c2 gets the typed busy, 53300 on the wire
        _, _, _, err = c2.query("select 1")
        assert err is not None and "53300" in err
        assert "admission" in err or "busy" in err or "full" in err
        # the refusal did not tear down c2: protocol still in sync
        assert c2.txn_status == b"I"
        # free the slot: the queued c1 statement is granted and completes
        q.release()
        msgs = c1._drain_until_ready()
        assert any(t == b"D" for t, _ in msgs), "queued stmt lost"
        assert not any(t == b"E" for t, _ in msgs)
        # and c2's retry now admits normally
        rows, _, _, err = c2.query("select 1")
        assert err is None and rows == [["1"]]
        assert q.in_use == 0 and q.queue_depth == 0
    finally:
        if c1 is not None:
            c1.close()
        if c2 is not None:
            c2.close()
        admission._SQL_QUEUE = saved
        srv.close()
        sess.close()
