"""Tier-1 wiring for the static cluster-settings audit
(scripts/check_settings_registered.py): every settings key used in the
package must be registered, and every registered key must be read."""

from scripts.check_settings_registered import check


def test_every_setting_registered_and_read():
    problems = check()
    assert not problems, "\n".join(problems)


def test_checker_catches_unregistered_and_unread(tmp_path):
    # the audit itself must flag both drift classes, including calls
    # split across lines (the real codebase has such call sites)
    pkg = tmp_path / "cockroach_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'register_bool(\n    "x.registered.unread", True, "d")\n'
        'settings.get(\n    "x.used.unregistered")\n'
        'settings.set("x.both", 1)\nregister_int("x.both", 0, "d")\n'
    )
    problems = check(tmp_path)
    assert any("x.used.unregistered" in p for p in problems)
    assert any("x.registered.unread" in p for p in problems)
    assert not any("x.both" in p for p in problems)
