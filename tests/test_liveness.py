"""Node liveness epoch state machine + epoch leases (kv/liveness.py).

Every scenario runs on a ManualClock so expiry is deterministic: no
sleeps, no wall-clock flakes. Multiple NodeLiveness instances sharing
one DB model nodes sharing the liveness range."""

import pytest

from cockroach_tpu.kv import DB
from cockroach_tpu.kv.hlc import ManualClock
from cockroach_tpu.kv.liveness import (
    EpochFencedError,
    LeaseManager,
    NodeLiveness,
    NotLeaseHolderError,
    StillLiveError,
)
from cockroach_tpu.storage.lsm import Engine


def _db(clock=None):
    return DB(Engine(key_width=16, val_width=32, memtable_size=64),
              clock or ManualClock(start=1_000))


def _node(db, node_id, ttl_ms=100):
    return NodeLiveness(db, node_id, heartbeat_interval_ms=ttl_ms // 2,
                        ttl_ms=ttl_ms)


# -- heartbeat ---------------------------------------------------------------


def test_first_heartbeat_creates_epoch_one():
    db = _db()
    n1 = _node(db, 1)
    rec = n1.heartbeat()
    assert rec.epoch == 1
    assert rec.node_id == 1
    assert rec.live_at(db.clock.now())
    assert n1.is_live(1)


def test_heartbeat_renews_expiration_same_epoch():
    db = _db()
    n1 = _node(db, 1, ttl_ms=100)
    first = n1.heartbeat()
    db.clock.advance(60)  # past half the ttl, still live
    second = n1.heartbeat()
    assert second.epoch == first.epoch == 1
    assert second.expiration > first.expiration


def test_record_expires_without_heartbeat():
    db = _db()
    n1 = _node(db, 1, ttl_ms=100)
    n1.heartbeat()
    db.clock.advance(200)  # well past the ttl
    assert not n1.is_live(1)
    n2 = _node(db, 2)
    assert not n2.is_live(1)  # peers agree: shared records, shared clock


def test_is_live_unknown_node_false():
    db = _db()
    assert not _node(db, 1).is_live(99)


# -- epoch increment (fencing) ----------------------------------------------


def test_increment_epoch_refused_while_live():
    db = _db()
    n1, n2 = _node(db, 1), _node(db, 2)
    n1.heartbeat()
    with pytest.raises(StillLiveError):
        n2.increment_epoch(1)


def test_increment_epoch_after_expiry_bumps():
    db = _db()
    n1, n2 = _node(db, 1, ttl_ms=100), _node(db, 2)
    n1.heartbeat()
    db.clock.advance(200)
    rec = n2.increment_epoch(1)
    assert rec.epoch == 2
    assert rec.node_id == 1


def test_increment_epoch_unknown_node_errors():
    db = _db()
    with pytest.raises(ValueError):
        _node(db, 1).increment_epoch(42)


def test_fenced_node_heartbeat_raises_epoch_fenced():
    """The node was declared dead while dark; its next heartbeat must NOT
    resurrect the old epoch — it surfaces EpochFencedError instead."""
    db = _db()
    n1, n2 = _node(db, 1, ttl_ms=100), _node(db, 2)
    n1.heartbeat()
    db.clock.advance(200)
    n2.increment_epoch(1)  # the fencing write
    with pytest.raises(EpochFencedError):
        n1.heartbeat()


def test_resurrect_after_fence_adopts_new_epoch():
    """A FRESH NodeLiveness instance (process restart: no remembered
    epoch) heartbeats under the bumped epoch and is live again — restart
    recovers, stale in-memory epoch state does not."""
    db = _db()
    n1, n2 = _node(db, 1, ttl_ms=100), _node(db, 2)
    n1.heartbeat()
    db.clock.advance(200)
    n2.increment_epoch(1)
    n1b = _node(db, 1)  # restarted process: _my_epoch is None
    rec = n1b.heartbeat()
    assert rec.epoch == 2  # adopted the bumped epoch, didn't invent one
    assert n2.is_live(1)
    # and the OLD instance still cannot heartbeat its stale epoch back
    with pytest.raises(EpochFencedError):
        n1.heartbeat()


def test_livenesses_lists_all_records():
    db = _db()
    _node(db, 3).heartbeat()
    _node(db, 1).heartbeat()
    n = _node(db, 2)
    n.heartbeat()
    recs = {r.node_id: r for r in n.livenesses()}
    assert sorted(recs) == [1, 2, 3]
    assert all(r.epoch == 1 for r in recs.values())


# -- epoch leases ------------------------------------------------------------


def test_acquire_vacant_and_renew():
    db = _db()
    lm = LeaseManager(_node(db, 1))
    rec = lm.acquire(7)
    assert (rec.range_id, rec.node_id, rec.epoch) == (7, 1, 1)
    again = lm.acquire(7)  # renew: same holder, same epoch
    assert (again.node_id, again.epoch) == (1, 1)
    held = lm.holder(7)
    assert held is not None and held.node_id == 1
    lm.check(7)  # serve guard passes for the holder


def test_acquire_against_live_holder_reroutes():
    db = _db()
    lm1 = LeaseManager(_node(db, 1))
    lm2 = LeaseManager(_node(db, 2))
    lm1.acquire(7)
    lm2.liveness.heartbeat()
    with pytest.raises(NotLeaseHolderError) as ei:
        lm2.acquire(7)
    assert ei.value.holder == 1  # reroute hint carried


def test_failover_fences_dead_holder_and_takes_lease():
    db = _db()
    n1 = _node(db, 1, ttl_ms=100)
    lm1 = LeaseManager(n1)
    lm2 = LeaseManager(_node(db, 2))
    lm1.acquire(7)
    lm2.liveness.heartbeat()
    db.clock.advance(200)  # n1 dark; n2's record would expire too, so:
    lm2.liveness.heartbeat()  # n2 stays live
    rec = lm2.acquire(7)  # fences n1 (epoch 1->2), takes the lease
    assert rec.node_id == 2
    # the fencing write really landed on n1's liveness record
    assert lm2.liveness._read(1).epoch == 2
    # old holder's serve guard now fails with the FENCED error, not a
    # mere not-leaseholder: its epoch no longer matches anything
    with pytest.raises((EpochFencedError, NotLeaseHolderError)):
        lm1.check(7)
    with pytest.raises(EpochFencedError):
        n1.heartbeat()


def test_check_not_holder_carries_hint():
    db = _db()
    lm1 = LeaseManager(_node(db, 1))
    lm2 = LeaseManager(_node(db, 2))
    lm1.acquire(7)
    with pytest.raises(NotLeaseHolderError) as ei:
        lm2.check(7)
    assert ei.value.holder == 1


def test_check_vacant_range_not_holder():
    db = _db()
    with pytest.raises(NotLeaseHolderError):
        LeaseManager(_node(db, 1)).check(99)


def test_check_epoch_fenced_after_bump():
    """The holder's liveness epoch moved past the lease's epoch: check()
    raises EpochFencedError even though the lease record still names the
    node — the epoch-equality invariant, no wall-clock involved."""
    db = _db()
    n1 = _node(db, 1, ttl_ms=100)
    lm1 = LeaseManager(n1)
    lm1.acquire(7)
    db.clock.advance(200)
    _node(db, 2).increment_epoch(1)
    with pytest.raises(EpochFencedError):
        lm1.check(7)
