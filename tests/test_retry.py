"""Retry/backoff/deadline discipline + the dialer breaker state machine
(util/retry.Options and rpc/peer.go reductions)."""

import random
import socket
import time

import pytest

from cockroach_tpu.kv.dialer import BreakerOpenError, _Breaker
from cockroach_tpu.storage.lsm import WriteIntentError
from cockroach_tpu.utils import retry
from cockroach_tpu.utils.faults import InjectedFault


def test_backoff_attempt_count_and_determinism():
    b = retry.Backoff(max_attempts=4, initial_s=0.0, jitter=0.0)
    assert list(b.attempts()) == [0, 1, 2, 3]
    # jitter draws come from the injected rng: same seed, same schedule
    draws = [retry.Backoff(max_attempts=3, initial_s=0.001,
                           rng=random.Random(5)).rng.random()
             for _ in range(2)]
    assert draws[0] == draws[1]


def test_backoff_respects_overall_deadline():
    b = retry.Backoff(max_attempts=50, initial_s=0.02, multiplier=1.0,
                      jitter=0.0, deadline_s=0.1)
    t0 = time.monotonic()
    n = sum(1 for _ in b.attempts())
    assert time.monotonic() - t0 < 1.0
    assert n < 50  # the deadline cut the attempt budget


def test_call_retries_transient_until_success():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("transient")
        return "ok"

    assert retry.call(flaky, retry.Backoff(max_attempts=5,
                                           initial_s=0.0)) == "ok"
    assert calls["n"] == 3


def test_call_hard_error_surfaces_immediately():
    calls = {"n": 0}

    def hard():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry.call(hard, retry.Backoff(max_attempts=5, initial_s=0.0))
    assert calls["n"] == 1


def test_call_exhaustion_reraises_last_transient():
    def always():
        raise ConnectionError("down forever")

    with pytest.raises(ConnectionError):
        retry.call(always, retry.Backoff(max_attempts=3, initial_s=0.0))


def test_retryable_classification():
    assert retry.is_retryable(WriteIntentError([b"k"], [1]))
    assert retry.is_retryable(socket.timeout())
    assert retry.is_retryable(TimeoutError())
    assert retry.is_retryable(retry.RPCDeadlineError("deadline"))
    assert retry.is_retryable(ConnectionResetError())
    assert retry.is_retryable(OSError("connection refused"))
    # an injected drop classifies exactly like a real one
    assert retry.is_retryable(InjectedFault("kv.rpc.client.batch", "drop"))
    # breaker-open is retryable-after-cooldown: the backoff outlasts the
    # cooldown so a later attempt is admitted as the half-open probe
    assert retry.is_retryable(BreakerOpenError("open"))
    assert not retry.is_retryable(ValueError("planning bug"))
    assert not retry.is_retryable(KeyError("missing"))


def test_breaker_trips_at_threshold():
    b = _Breaker(trip_threshold=3, cooldown_s=10.0)
    b.fail()
    b.fail()
    b.admit()  # two failures: still closed
    b.fail()
    with pytest.raises(BreakerOpenError):
        b.admit()


def test_breaker_half_open_single_probe_then_reset():
    b = _Breaker(trip_threshold=1, cooldown_s=0.05)
    b.fail()
    with pytest.raises(BreakerOpenError):
        b.admit()
    time.sleep(0.06)
    b.admit()  # this caller IS the half-open probe
    # a second caller during the probe is NOT admitted
    with pytest.raises(BreakerOpenError):
        b.admit()
    b.ok()  # probe's RPC succeeded: breaker closes fully
    b.admit()
    b.admit()  # closed: everyone is admitted


def test_breaker_probe_failure_reopens():
    b = _Breaker(trip_threshold=1, cooldown_s=0.05)
    b.fail()
    time.sleep(0.06)
    b.admit()  # probe admitted
    b.fail()  # probe's RPC failed: open again, cooldown restarts
    with pytest.raises(BreakerOpenError):
        b.admit()


def test_breaker_aborted_probe_frees_slot():
    b = _Breaker(trip_threshold=1, cooldown_s=0.05)
    b.fail()
    time.sleep(0.06)
    b.admit()
    b.probe_aborted()  # the dial itself failed; slot frees immediately
    b.admit()  # next caller becomes the probe without waiting 2x cooldown


def test_retry_through_breaker_cooldown():
    """The integration the classification exists for: a retry loop whose
    backoff spans the cooldown gets admitted as the half-open probe and
    succeeds once the peer is back."""
    b = _Breaker(trip_threshold=1, cooldown_s=0.08)
    b.fail()  # tripped

    def guarded():
        b.admit()
        return "through"

    got = retry.call(
        guarded,
        retry.Backoff(max_attempts=8, initial_s=0.04, multiplier=1.5,
                      jitter=0.0),
        retryable=lambda e: isinstance(e, BreakerOpenError),
    )
    assert got == "through"


# -- per-range retry budgets -------------------------------------------------


def test_range_retry_budget_exhausts_then_refills():
    from cockroach_tpu.utils import metric

    b = retry.RangeRetryBudget(budget=3, refill_per_s=200.0)
    exhausted_before = metric.RPC_RETRY_BUDGET_EXHAUSTED.value
    by_range_before = metric.RPC_RETRIES_BY_RANGE.value(7)
    for _ in range(3):
        b.spend(7)
    assert metric.RPC_RETRIES_BY_RANGE.value(7) == by_range_before + 3
    with pytest.raises(retry.RetryBudgetExhausted) as ei:
        b.spend(7)
    assert ei.value.range_id == 7
    assert metric.RPC_RETRY_BUDGET_EXHAUSTED.value > exhausted_before
    time.sleep(0.02)  # 200 tokens/s: at least one token back
    b.spend(7)  # flows again after the refill


def test_range_retry_budget_isolates_ranges():
    """One flapping range cannot starve another range's retries — the
    whole point of moving the budget off the client."""
    b = retry.RangeRetryBudget(budget=1, refill_per_s=0.0)
    b.spend(1)
    with pytest.raises(retry.RetryBudgetExhausted):
        b.spend(1)
    b.spend(2)  # untouched range: full budget


def test_range_retry_budget_exhaustion_is_not_retryable():
    """RetryBudgetExhausted is a hard stop: the shared classification
    must never feed it back into a retry loop."""
    b = retry.RangeRetryBudget(budget=0.5, refill_per_s=0.0)
    try:
        b.spend(9)
        raise AssertionError("expected exhaustion")
    except retry.RetryBudgetExhausted as e:
        assert not retry.is_retryable(e)
        assert not isinstance(e, ConnectionError)
