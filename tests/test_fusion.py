"""Fusion-equivalence sweep + pull-loop readback tests.

`sql.distsql.fusion.enabled=off` degrades the engine to classic
one-jit-per-operator pulls (both the plan-build pass in flow/fuse.py and
the consumer-driven spool fusion in flow/operators.py) — the oracle every
fused run must match bit-for-bit, including the speculative-capacity retry
path and both readback-overlap modes.

A representative subset runs tier-1; the full TPC-H + TPC-DS corpus is
marked slow (compile-bound: each fused chain jits per query)."""

import numpy as np
import pytest

from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpcds, tpch
from cockroach_tpu.utils import settings

# tier-1 representatives: dense group-by (q1), join chain + top-k (q3),
# scalar agg (q6), 5-way join + expr group (q9), semi-join style agg (q18)
_FAST_TPCH = {"q1", "q3", "q6", "q9", "q18"}
_FAST_TPCDS = {"q3", "q42"}


@pytest.fixture(scope="module")
def hcat():
    return tpch.gen_tpch(sf=0.005, seed=7)


@pytest.fixture(scope="module")
def dcat():
    return tpcds.gen_tpcds(sf=0.01)


def _run(rel, fusion: bool, overlap: bool = True):
    settings.set("sql.distsql.fusion.enabled", fusion)
    settings.set("sql.distsql.readback_overlap", overlap)
    try:
        return rel.run()
    finally:
        settings.reset("sql.distsql.fusion.enabled")
        settings.reset("sql.distsql.readback_overlap")


def _assert_identical(got, want):
    assert set(got) == set(want)
    for name in want:
        g, w = np.asarray(got[name]), np.asarray(want[name])
        assert g.shape == w.shape, name
        if g.dtype == object or w.dtype == object:
            assert list(g) == list(w), name
        else:
            # bit-identical, not allclose: fusion must not reassociate
            np.testing.assert_array_equal(g, w, err_msg=name)


@pytest.mark.parametrize(
    "qname",
    [pytest.param(q, marks=() if q in _FAST_TPCH else (pytest.mark.slow,))
     for q in sorted(Q.QUERIES)],
)
def test_tpch_fusion_equivalence(hcat, qname):
    rel = Q.QUERIES[qname](hcat)
    _assert_identical(_run(rel, fusion=True), _run(rel, fusion=False))


@pytest.mark.parametrize(
    "qname",
    [pytest.param(q, marks=() if q in _FAST_TPCDS else (pytest.mark.slow,))
     for q in sorted(tpcds.QUERIES)],
)
def test_tpcds_fusion_equivalence(dcat, qname):
    rel = tpcds.QUERIES[qname](dcat)
    _assert_identical(_run(rel, fusion=True), _run(rel, fusion=False))


def test_readback_overlap_equivalence(hcat):
    rel = Q.QUERIES["q3"](hcat)
    _assert_identical(_run(rel, fusion=True, overlap=True),
                      _run(rel, fusion=True, overlap=False))


def test_retry_path_equivalence(hcat):
    """Speculative-capacity overflow under fusion: shrinking a learned
    join emission capacity must trigger the post_run_update -> re-run path
    and still produce the unfused oracle's exact results."""
    from cockroach_tpu.flow import runtime
    from cockroach_tpu.flow.operators import HashJoinOp
    from cockroach_tpu.plan import builder as plan_builder

    rel = Q.QUERIES["q3"](hcat)
    oracle = _run(rel, fusion=False)
    settings.set("sql.distsql.fusion.enabled", True)
    try:
        root = plan_builder.build(rel.optimized_plan(), rel.catalog)
        runtime.run_operator(root)  # learn emission capacities

        joins = []

        def walk(op):
            if isinstance(op, HashJoinOp):
                joins.append(op)
            for c in op.children():
                walk(c)

        walk(root)
        assert joins, "q3 plan lost its hash joins"
        for j in joins:
            j._emit_mode = "compact"
            j._emit_cap = 16  # guaranteed overflow at sf 0.005

        inits = 0
        orig_init = root.init

        def counting_init():
            nonlocal inits
            inits += 1
            orig_init()

        root.init = counting_init
        res = runtime.run_operator(root)
        assert inits >= 2, "overflow did not trigger the re-run path"
    finally:
        settings.reset("sql.distsql.fusion.enabled")
    _assert_identical(res, oracle)


def test_readback_shrink_overflow_patch():
    """_ReadbackShrink speculation: a large tile compacts to capacity/64
    with NO host sync; when the deferred count shows the compaction
    truncated live rows, finish() re-materializes from the retained
    original — no rows lost."""
    from cockroach_tpu.coldata.batch import from_host, to_host
    from cockroach_tpu.coldata.types import INT64, Schema
    from cockroach_tpu.flow.runtime import _ReadbackShrink

    schema = Schema(("v",), (INT64,))
    cap = _ReadbackShrink.MIN_CAP  # 64k tile
    live = cap // 2  # far more live rows than the cap/64 shrink target
    b = from_host(schema, {"v": np.arange(live, dtype=np.int64)},
                  capacity=cap)

    shrink = _ReadbackShrink()
    small = shrink.shrink(b)
    assert small.capacity == cap >> 6  # speculation actually engaged
    outs = [to_host(small, schema, {})]
    assert len(outs[0]["v"]) < live  # truncated pre-patch
    shrink.finish(outs, schema, {})
    np.testing.assert_array_equal(outs[0]["v"],
                                  np.arange(live, dtype=np.int64))

    # small tiles pass through untouched (no compact dispatch to pay)
    tiny = from_host(schema, {"v": np.arange(10, dtype=np.int64)},
                     capacity=1024)
    assert shrink.shrink(tiny) is tiny


def test_explain_shows_pipeline_groups(hcat):
    rel = Q.QUERIES["q1"](hcat)
    settings.set("sql.distsql.fusion.enabled", True)
    try:
        fused = rel.explain()
    finally:
        settings.reset("sql.distsql.fusion.enabled")
    assert "[pipeline" in fused
    settings.set("sql.distsql.fusion.enabled", False)
    try:
        plain = rel.explain()
    finally:
        settings.reset("sql.distsql.fusion.enabled")
    assert "[pipeline" not in plain


def test_explain_analyze_reports_dispatches(hcat):
    rel = Q.QUERIES["q1"](hcat)
    settings.set("sql.distsql.fusion.enabled", True)
    try:
        text, res = rel.explain_analyze()
    finally:
        settings.reset("sql.distsql.fusion.enabled")
    dispatches, compiles = text.splitlines()[-2:]
    assert dispatches.startswith("kernel dispatches: ")
    assert int(dispatches.split(": ")[1]) > 0
    assert compiles.startswith("kernel compiles: ")
    assert "[pipeline" in text
    assert len(res["l_returnflag"]) > 0


def test_general_probe_fusion_equivalence(hcat):
    """Non-unique (fan-out) inner probes fuse as speculative streaming
    emitters under sql.distsql.fusion.general_probe; the gated-off run —
    the probe breaking the chain like pre-fusion engines — is the oracle."""
    from cockroach_tpu.sql.rel import Rel

    rel = (Rel.scan(hcat, "orders")
           .join(Rel.scan(hcat, "lineitem"),
                 on=[("o_orderkey", "l_orderkey")], how="inner",
                 build_unique=False)
           .groupby(["o_orderkey"], [("n", "count_rows", None)]))
    settings.set("sql.distsql.fusion.general_probe", False)
    try:
        want = _run(rel, fusion=True)
    finally:
        settings.reset("sql.distsql.fusion.general_probe")
    _assert_identical(_run(rel, fusion=True), want)


@pytest.mark.parametrize("qname", ["q9", "q18"])
def test_spill_and_skew_forced_tpch_equivalence(hcat, qname):
    """The join-plane escape hatches must not change a single bit: q9/q18
    re-run with workmem forced down (Grace spill + hybrid partition
    degrade) and the skew sampler armed aggressively, against the
    in-memory fused oracle."""
    from cockroach_tpu.utils import metric

    rel = Q.QUERIES[qname](hcat)
    want = _run(rel, fusion=True)
    spills0 = metric.GRACE_JOIN_SPILLS.value
    settings.set("sql.distsql.workmem_bytes", 1 << 16)
    settings.set("sql.distsql.grace_skew_frac", 0.02)
    try:
        got = _run(rel, fusion=True)
    finally:
        settings.reset("sql.distsql.workmem_bytes")
        settings.reset("sql.distsql.grace_skew_frac")
    assert metric.GRACE_JOIN_SPILLS.value > spills0, "never spilled"
    _assert_identical(got, want)
