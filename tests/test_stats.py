"""ANALYZE + statistics-driven planning (pkg/sql/stats +
statistics_builder reduction): collection, persistence, and the three
planner consumers — join order, broadcast threshold, exact-key layouts —
each shown to CHANGE PLANS when the statistics are perturbed."""

import numpy as np

import cockroach_tpu.catalog as catalog_mod
from cockroach_tpu.coldata.types import INT64, Schema
from cockroach_tpu.sql import Session, sql
from cockroach_tpu.sql import stats as stats_mod


def _cat():
    c = catalog_mod.Catalog()
    c.add(catalog_mod.Table.from_strings(
        "big", Schema.of(bk=INT64, bv=INT64),
        {"bk": np.arange(1, 1001), "bv": np.arange(1001, 2001)},
    ))
    c.add(catalog_mod.Table.from_strings(
        "small", Schema.of(sk=INT64, sv=INT64),
        {"sk": np.arange(1, 51), "sv": np.arange(51, 101)},
    ))
    return c


def test_analyze_collects_and_shows():
    sess = Session()
    sess.execute("create table t (a int primary key, b int, s string)")
    sess.execute(
        "insert into t values (1, 10, 'x'), (2, 10, 'y'), (3, null, 'x')")
    r = sess.execute("analyze t")
    assert r == {"analyzed": "t", "rows": 3}
    r = sess.execute("show statistics for table t")
    by = dict(zip(r["column_name"], zip(r["distinct_count"],
                                        r["null_count"])))
    assert by["a"] == (3, 0)
    assert by["b"] == (1, 1)  # {10}, one NULL
    assert by["s"] == (2, 0)
    # (lo, hi) bounds feed the kernel layer through col_stats
    t = sess.catalog.tables["t"]
    assert t.col_stats()["b"] == (10, 10)


def test_analyze_persists_across_restart():
    sess = Session()
    sess.execute("create table p (a int primary key, b int)")
    sess.execute("insert into p values (1, 5), (2, 6)")
    sess.execute("analyze p")
    sess2 = Session(db=sess.db)  # fresh catalog over the same store
    t2 = sess2.catalog.tables["p"]
    assert t2.estimated_rows() == 2
    assert t2.col_stats()["b"] == (5, 6)


def test_perturbed_rowcount_flips_join_order():
    """The binder starts its greedy join order at the LARGEST estimated
    source; inflating `small`'s row count must flip probe/build sides."""
    from cockroach_tpu.plan import spec as S

    def probe_table(cat):
        rel = sql(cat, "select bv, sv from big, small where bk = sk")
        # find the HashJoin node and identify which side is the probe
        node = rel.plan
        while not isinstance(node, S.HashJoin):
            node = node.input
        side = node.probe
        while not isinstance(side, S.TableScan):
            side = side.input
        return side.table

    cat = _cat()
    for name in ("big", "small"):
        cat.get(name).set_stats(stats_mod.analyze_table(cat.get(name)))
    assert probe_table(cat) == "big"  # truthful stats: big probes
    # perturb: claim `small` has a million rows — the plan must flip,
    # with the data unchanged
    fake = stats_mod.analyze_table(cat.get("small"))
    fake.row_count = 1_000_000
    cat.get("small").set_stats(fake)
    assert probe_table(cat) == "small"


def test_perturbed_rowcount_changes_broadcast_decision():
    """distribute() broadcasts builds below the row threshold; inflating
    the build side's statistics must replace Broadcast with Exchange."""
    from cockroach_tpu.plan import distribute as D
    from cockroach_tpu.plan import spec as S

    def has_broadcast(plan):
        if isinstance(plan, S.Broadcast):
            return True
        return any(
            has_broadcast(getattr(plan, f))
            for f in ("input", "probe", "build")
            if getattr(plan, f, None) is not None
        )

    cat = _cat()
    rel = sql(cat, "select bv, sv from big, small where bk = sk")
    assert has_broadcast(D.distribute(rel.plan, cat))
    # inflate BOTH sides so the join order keeps big as the probe but the
    # build side (small) crosses the broadcast threshold: the distribute
    # planner must switch from replicating the build to hash-shuffling
    fake_small = stats_mod.analyze_table(cat.get("small"))
    fake_small.row_count = 1 << 18  # over BROADCAST_ROWS_DEFAULT (1 << 17)
    cat.get("small").set_stats(fake_small)
    fake_big = stats_mod.analyze_table(cat.get("big"))
    fake_big.row_count = 1 << 20
    cat.get("big").set_stats(fake_big)
    rel2 = sql(cat, "select bv, sv from big, small where bk = sk")
    assert not has_broadcast(D.distribute(rel2.plan, cat))


def test_perturbed_bounds_change_exact_key_layout():
    """plan_exact_key derives packed-key bit widths from (lo, hi): widening
    the analyzed bounds must widen the layout; dropping them must disable
    the exact-key path entirely."""
    from cockroach_tpu.flow import operators as ops
    from cockroach_tpu.ops.join import JoinSpec

    cat = _cat()
    for name in ("big", "small"):
        cat.get(name).set_stats(stats_mod.analyze_table(cat.get(name)))

    def layout_bits():
        j = ops.HashJoinOp(
            ops.ScanOp(cat.get("big")), ops.ScanOp(cat.get("small")),
            (0,), (0,), JoinSpec("inner", True),
        )
        return None if j.exact_layout is None else j.exact_layout.total_bits

    tight = layout_bits()
    assert tight is not None and tight <= 10  # keys 1..1000
    wide = stats_mod.analyze_table(cat.get("big"))
    wide.cols["bk"].hi = 1 << 40
    cat.get("big").set_stats(wide)
    assert layout_bits() >= 40  # the layout followed the (perturbed) stats


def test_cost_based_join_order_matches_heuristic():
    """`sql.opt.join_order = cost` swaps the greedy heuristic for the
    Selinger left-deep DP (binder._dp_join_order). On a three-table chain
    the DP must produce the same rows as the heuristic and must never
    insert a cartesian product when equi-edges connect the sources."""
    from cockroach_tpu.plan import spec as S
    from cockroach_tpu.utils import settings

    c = _cat()
    c.add(catalog_mod.Table.from_strings(
        "mid", Schema.of(mk=INT64, mv=INT64),
        {"mk": np.arange(1, 201), "mv": np.arange(201, 401)},
    ))
    for name in ("big", "mid", "small"):
        c.get(name).set_stats(stats_mod.analyze_table(c.get(name)))
    q = ("select bv, mv, sv from big, mid, small "
         "where bk = mk and mk = sk order by bv")

    def rows(res):
        return sorted(zip(*(res[k].tolist() for k in ("bv", "mv", "sv"))))

    def count_cross(node):
        # a cartesian join lowers through Rel.cross_join, which stamps a
        # constant "__k" join-key column into a Project on both sides
        n = 1 if (isinstance(node, S.Project) and "__k" in node.names) else 0
        for f in ("input", "probe", "build"):
            child = getattr(node, f, None)
            if isinstance(child, S.PlanNode):
                n += count_cross(child)
        return n

    heur = sql(c, q)
    want = rows(heur.run())
    assert want  # the chain join is non-empty
    settings.set("sql.opt.join_order", "cost")
    try:
        cost = sql(c, q)
        assert rows(cost.run()) == want
        assert count_cross(cost.plan) == 0
    finally:
        settings.set("sql.opt.join_order", "heuristic")
