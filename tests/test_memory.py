"""Resource observability plane: the mon.BytesMonitor tree, budget-driven
spills, admission timeout/grant racing, and the serving-load surfaces.

Reference shapes under test: pkg/util/mon (hierarchical byte accounting,
"monitor closed with outstanding bytes" drain discipline), colexecdisk's
disk_spiller (budget exceeded -> external variant, bit-identical results),
and admission's WorkQueue (a grant racing a timeout withdrawal must never
leak the slot).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from cockroach_tpu.catalog import Catalog
from cockroach_tpu.flow import memory
from cockroach_tpu.sql import Session
from cockroach_tpu.utils import admission, settings


# ------------------------------------------------------------ monitor tree

def test_monitor_tree_charges_ancestors():
    root = memory.BytesMonitor("test-root", level="root")
    sess = root.child("sess", level="session")
    query = sess.child("query", level="query")
    op = query.child("op", level="operator")

    op.reserve(1000)
    assert (op.used, query.used, sess.used, root.used) == (1000,) * 4
    op.reserve(500)
    assert root.used == 1500 and root.high_water == 1500
    op.release(600)
    assert (op.used, root.used) == (900, 900)
    assert root.high_water == 1500  # peak survives the release

    # close() force-releases the remainder up the chain and reports it
    leaked = op.close()
    assert leaked == 900
    assert query.used == 0 and root.used == 0
    assert op.closed and op.close() == 0  # idempotent


def test_budget_refusal_leaves_chain_untouched():
    root = memory.BytesMonitor("test-root", level="root")
    op = root.child("op", budget=4096)
    op.reserve(4000)
    assert op.would_exceed(100)
    with pytest.raises(memory.BudgetExceededError):
        op.reserve(100)
    # the refused reservation charged NOTHING anywhere
    assert op.used == 4000 and root.used == 4000
    # an ancestor budget refuses too, before any charge lands
    mid = root.child("mid", budget=8192)
    leaf = mid.child("leaf")  # unlimited at this level
    leaf.reserve(8000)
    with pytest.raises(memory.BudgetExceededError):
        leaf.reserve(200)
    assert leaf.used == 8000 and mid.used == 8000
    # force=True skips the check (host-side state that cannot spill) but
    # still accounts the bytes truthfully
    op.reserve(100, force=True)
    assert op.used == 4100 and op.high_water == 4100
    op.close()
    leaf.close()
    mid.close()
    assert root.used == 0


def test_query_scope_joins_and_counts_drain_failures():
    before = memory.drain_failure_count()
    root_used0 = memory.ROOT.used
    with memory.query_scope() as qm:
        # a nested scope (diagnostics re-run shape) JOINS the outer monitor
        with memory.query_scope() as inner:
            assert inner is qm
        # a deliberately leaked operator account: never closed
        alloc = memory.Allocator("leaky op")
        alloc.reserve(2048)
        assert memory.current_query() is qm
        assert qm.used == 2048
    # scope exit force-closed the child, so the node gauge is clean...
    assert memory.ROOT.used == root_used0
    assert memory.current_query() is None
    # ...and the leak was censused with the monitor named
    assert memory.drain_failure_count() == before + 1
    name, leaked = memory.drain_failures(last=1)[0]
    assert leaked == 2048 and name.startswith("query-")
    # undo the deliberate failure so the per-test drain census (conftest
    # autouse fixture) doesn't flag this test — the one place the counter
    # may be rolled back, because the leak was the assertion target
    memory._DRAIN_TOTAL -= 1
    memory._DRAIN_FAILURES.pop()


def test_query_scope_drains_cleanly_when_accounts_close():
    with memory.query_scope() as qm:
        alloc = memory.Allocator("tidy op")
        alloc.reserve(4096)
        alloc.close()
        assert qm.used == 0
    assert qm.high_water == 4096  # peak recorded even after the drain


# ------------------------------------- budget exceeded -> external variant

_SPILL_Q = ("select l_orderkey, sum(l_quantity) as sq from lineitem "
            "group by l_orderkey order by l_orderkey")


def _tpch_session():
    from cockroach_tpu.bench.tpch import gen_tpch_cached

    return Session(catalog=gen_tpch_cached(0.005))


def test_spill_bit_identity_and_query_attribution():
    """disk_spiller contract under the monitor tree: lowering workmem to
    its floor forces the agg/sort spools past budget and into the external
    variants; the result must be BIT-IDENTICAL to the in-memory run, and
    the spill must be attributed to the owning query's fingerprint
    (non-zero spills + peak-memory percentiles in sqlstats)."""
    s = _tpch_session()
    ref = s.execute(_SPILL_Q)  # in-memory reference (default workmem)

    spills_before = memory.ROOT.spills
    settings.set("sql.distsql.workmem_bytes", 65536)
    try:
        got = s.execute(_SPILL_Q)
    finally:
        settings.reset("sql.distsql.workmem_bytes")
    assert memory.ROOT.spills > spills_before  # the budget actually bit
    assert sorted(ref.keys()) == sorted(got.keys())
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k])

    # attribution: the fingerprint's sqlstats row carries the spill count
    # and non-zero peak-memory percentiles next to its latency figures
    res = s.execute(
        "select fingerprint, spills, max_mem_mb, mem_p50_mb, mem_p99_mb "
        "from crdb_internal.node_statement_statistics")
    rows = {str(f): i for i, f in enumerate(res["fingerprint"])}
    key = next(f for f in rows if "group by l_orderkey" in f)
    i = rows[key]
    assert int(res["spills"][i]) >= 1
    assert float(res["max_mem_mb"][i]) > 0
    assert float(res["mem_p99_mb"][i]) > 0
    s.close()


def test_explain_analyze_prints_memory_and_spill_lines():
    """Acceptance shape: EXPLAIN ANALYZE on a spilling query prints a per-
    operator max-memory figure, marks the spilled operators, and footers
    the query's peak before the kernel-dispatch lines."""
    from cockroach_tpu import sql as sqlmod
    from cockroach_tpu.bench.tpch import gen_tpch_cached

    cat = gen_tpch_cached(0.005)
    settings.set("sql.distsql.workmem_bytes", 65536)
    try:
        txt = sqlmod.explain(cat, "explain analyze " + _SPILL_Q)
    finally:
        settings.reset("sql.distsql.workmem_bytes")
    assert "max mem=" in txt
    assert "spilled" in txt
    lines = txt.splitlines()
    (peak_line,) = [ln for ln in lines if "query peak memory:" in ln]
    assert "(spills:" in peak_line
    # footer ordering: peak memory BEFORE the kernel dispatch/compile pair
    assert lines.index(peak_line) < lines.index(
        next(ln for ln in lines if ln.startswith("kernel dispatches:")))


# --------------------------------------------------- crdb_internal surface

def test_crdb_internal_memory_monitor_and_load_tables():
    s = Session(Catalog())
    s.execute("create table t (id int primary key, v int)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    s.execute("select id, v from t order by v")  # reserves a sort spool

    res = s.execute(
        "select name, level, depth, used_bytes, peak_bytes, budget_bytes "
        "from crdb_internal.node_memory_monitors")
    names = [str(n) for n in res["name"]]
    levels = [str(lv) for lv in res["level"]]
    assert names[0] == "root" and int(res["depth"][0]) == 0
    assert "session" in levels  # this session's own monitor is live
    # the statement reading the table sees ITSELF as the open query monitor
    assert "query" in levels
    assert int(res["used_bytes"][0]) >= 0

    res = s.execute(
        "select active_sessions, admission_slots, admission_admitted, "
        "sql_mem_peak_bytes, queries_total from crdb_internal.cluster_load")
    assert len(res["admission_slots"]) == 1
    assert int(res["active_sessions"][0]) >= 1
    assert int(res["admission_slots"][0]) >= 1
    assert int(res["admission_admitted"][0]) >= 1
    assert int(res["sql_mem_peak_bytes"][0]) > 0  # the sort spool peak
    s.close()


# ------------------------------------------------- admission race hammer

def test_admission_timeout_grant_race_hammer():
    """Regression for the admit timeout/grant race: a waiter whose grant
    lands concurrently with its timeout withdrawal must HAND THE SLOT BACK
    instead of leaking it. Hammer with timeouts at the same scale as the
    hold times so the race window is hit constantly; afterwards the queue
    must be fully drained and every slot grantable again."""
    q = admission.WorkQueue(slots=2)
    deadline = time.time() + 2.0
    granted = [0] * 8

    def worker(i: int) -> None:
        rng = np.random.default_rng(i)
        while time.time() < deadline:
            if q.admit(timeout=float(rng.uniform(0.0, 0.002))):
                granted[i] += 1
                if rng.random() < 0.5:
                    time.sleep(float(rng.uniform(0.0, 0.001)))
                q.release()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads)

    # the storm must have actually exercised both outcomes
    assert sum(granted) > 0 and q.timeouts > 0
    # post-storm invariants: nothing waiting, nothing held...
    assert q.queue_depth == 0
    assert q.in_use == 0
    assert not q._waiters or all(w.withdrawn for _, _, w in q._waiters)
    # ...and BOTH slots immediately grantable (a leaked slot would make
    # the second of these time out)
    assert q.admit(timeout=1.0)
    assert q.admit(timeout=1.0)
    q.release()
    q.release()
    assert q.in_use == 0


def test_admission_timeout_holds_nothing():
    q = admission.WorkQueue(slots=1)
    assert q.admit()
    t0 = time.perf_counter()
    assert q.admit(timeout=0.05) is False  # queue full: pure timeout
    assert time.perf_counter() - t0 < 5.0
    assert q.timeouts == 1 and q.queue_depth == 0
    q.release()  # the ORIGINAL holder's release must find a free queue
    assert q.in_use == 0
    assert q.admit(timeout=0.5)
    q.release()


def test_sql_slot_is_reentrant_per_thread():
    """A nested statement (internal executor / diagnostics re-run) must
    not deadlock on its own session's slot even at slots=1."""
    saved = admission._SQL_QUEUE
    admission._SQL_QUEUE = admission.WorkQueue(slots=1)
    try:
        with admission.sql_slot() as w0:
            with admission.sql_slot() as w1:  # nested: free pass
                assert w1 == 0.0
            assert w0 >= 0.0
        assert admission._SQL_QUEUE.in_use == 0
    finally:
        admission._SQL_QUEUE = saved


# ------------------------------------------------------- mixed-load harness

@pytest.mark.slow
def test_mixed_load_harness_smoke():
    """bench/load.py end-to-end at toy scale: the BENCH JSON fields exist,
    ops completed, and the run leaves the memory plane drained."""
    from cockroach_tpu.bench.load import run_mixed_load

    r = run_mixed_load(sessions=2, duration_s=1.5, sf=0.005, n_keys=64)
    assert r["ops"] > 0 and r["ops_per_sec"] > 0
    assert r["errors"] == 0, r["last_error"]
    assert r["peak_hbm_bytes"] > 0
    assert r["p99_queue_wait_ms"] >= 0.0
    assert r["admission_waits"] >= r["ops"]  # every admit observes the wait
