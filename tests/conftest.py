"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is `fakedist`
— pkg/sql/physicalplan/fake_span_resolver.go — which fakes multi-node
distribution inside one process). Real-TPU runs happen only via bench.py.

The environment injects a TPU PJRT plugin via a PYTHONPATH sitecustomize, and
that plugin opens a hardware tunnel even under JAX_PLATFORMS=cpu — making CPU
tests hostage to tunnel health. Backend init is lazy, so at conftest time we
can still drop the plugin's backend factory before anything initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# sitecustomize imports jax before conftest, freezing jax_platforms at the
# env value ("axon") — override the live config, not just the env var.
jax.config.update("jax_platforms", "cpu")

try:
    from jax._src import xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu",):
            _xb._backend_factories.pop(_name, None)
except Exception:  # pragma: no cover - defensive: jax internals moved
    pass

assert jax.devices()[0].platform == "cpu"
assert len(jax.devices()) == 8, "virtual 8-device CPU mesh required"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
