"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is `fakedist`
— pkg/sql/physicalplan/fake_span_resolver.go — which fakes multi-node
distribution inside one process). Real-TPU runs happen only via bench.py.

Must set env before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
