"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is `fakedist`
— pkg/sql/physicalplan/fake_span_resolver.go — which fakes multi-node
distribution inside one process). Real-TPU runs happen only via bench.py.

The environment injects a TPU PJRT plugin via a PYTHONPATH sitecustomize, and
that plugin opens a hardware tunnel even under JAX_PLATFORMS=cpu — making CPU
tests hostage to tunnel health. Backend init is lazy, so at conftest time we
can still drop the plugin's backend factory before anything initializes.
"""

from cockroach_tpu.utils.backend import force_cpu_backend

force_cpu_backend(8)

import jax  # noqa: E402

assert jax.devices()[0].platform == "cpu"
assert len(jax.devices()) == 8, "virtual 8-device CPU mesh required"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers",
        "chaos: deterministic seeded fault-injection tests (fast seeds "
        "run in tier-1; exclude with -m 'not chaos')")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _memory_drain_census():
    """leaktest analog for the memory-monitor tree: every query-level
    monitor must drain to zero by the time its query scope closes. The
    drain-failure counter (flow/memory.py) is monotonic, so any increase
    across a test means that test leaked reserved bytes — fail it, with
    the offending monitors named (scripts/check_no_leaks.py carries the
    same census for standalone harnesses)."""
    from scripts.check_no_leaks import _drain_failure_count

    before = _drain_failure_count()
    yield
    after = _drain_failure_count()
    # the node-wide block cache (storage/blockcache.py) outlives any one
    # engine: drain it between tests so cached windows from a dead test's
    # runs can't pin root-monitor bytes or leak hit-rate state across
    # tests (every test starts cold, like a fresh node)
    from cockroach_tpu.storage import blockcache

    blockcache.node_cache().clear()
    if after > before:
        from cockroach_tpu.flow import memory

        raise AssertionError(
            f"query memory monitors closed non-drained ({before} -> "
            f"{after}): {memory.drain_failures(last=after - before)}")
