"""Range lifecycle subsystem: load stats, replica queues, and the
split/merge/rebalance allocator (kv/loadstats.py, kv/queues.py,
kv/allocator.py).

Every test asserts behavior that disappears if the wiring is removed:
decayed counters actually decay; the reservoir names a load-balancing
split point; purgatory retries typed errors instead of dropping them; a
hot-key workload fires a load split + lease transfer AUTOMATICALLY and
the post-lifecycle reads equal a no-split oracle; cold ranges re-merge
once the load decays away."""

import threading
import time
import types

import pytest

from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.allocator import RangeLifecycle, StoreCapacity, StorePool
from cockroach_tpu.kv.dist import DistSender, Meta, Store
from cockroach_tpu.kv.loadstats import DecayingCounter, RangeLoadStats
from cockroach_tpu.kv.queues import ReplicaQueue
from cockroach_tpu.utils import metric, settings


def _mk(n_stores=2, **kw):
    meta = Meta(first_store=1)
    kw.setdefault("key_width", 16)
    kw.setdefault("val_width", 16)
    kw.setdefault("memtable_size", 64)
    stores = [Store(i + 1, meta, **kw) for i in range(n_stores)]
    return meta, stores, DistSender(stores, meta)


class _ManualClock:
    """Injectable monotonic clock stepped by tests."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- load stats --------------------------------------------------------------


def test_decaying_counter_half_life():
    clk = _ManualClock()
    c = DecayingCounter(half_life_s=10.0, clock=clk)
    for _ in range(100):
        c.record()
    r0 = c.rate()
    assert r0 > 0
    clk.advance(10.0)
    # one half-life: the decayed count (and hence the rate) halves
    assert c.rate() == pytest.approx(r0 / 2.0, rel=1e-6)
    clk.advance(200.0)
    assert c.rate() < r0 / 1000.0  # idle range goes cold without a timer


def test_reservoir_split_key_is_interior_median():
    clk = _ManualClock()
    ls = RangeLoadStats(half_life_s=30.0, sample_size=16, seed=1, clock=clk)
    for i in range(100):
        ls.record_write(1, b"k%03d" % i, 16)
    key = ls.split_key(1, b"", None)
    assert key is not None
    # the median of a uniform keyspace lands near the middle — a split
    # there balances the observed load
    assert b"k020" < key < b"k080"
    # bounds are strict: a point no sample exceeds yields no split key
    assert ls.split_key(1, b"k099", None) is None
    # single hot key at the range start: nothing strictly interior
    ls2 = RangeLoadStats(sample_size=8, seed=1, clock=clk)
    for _ in range(50):
        ls2.record_read(7, b"hot")
    assert ls2.split_key(7, b"hot", None) is None
    assert ls2.split_key(9, b"", None) is None  # unknown range


def test_note_split_partitions_samples_and_halves_rates():
    clk = _ManualClock()
    ls = RangeLoadStats(half_life_s=30.0, sample_size=32, seed=2, clock=clk)
    for i in range(32):
        ls.record_write(1, b"k%03d" % i, 8)
    q_before = ls.qps(1)
    w_before = ls.write_bytes_rate(1)
    ls.note_split(1, 2, b"k016")
    # both sides keep half the history: neither looks newborn-cold
    assert ls.qps(1) == pytest.approx(q_before / 2, rel=1e-6)
    assert ls.qps(2) == pytest.approx(q_before / 2, rel=1e-6)
    assert ls.write_bytes_rate(2) == pytest.approx(w_before / 2, rel=1e-6)
    # samples partition by the split key
    assert all(k < b"k016" for k in ls._ranges[1].samples)
    assert all(k >= b"k016" for k in ls._ranges[2].samples)
    # merge folds the child back in and forgets it
    ls.note_merge(1, 2)
    assert ls.qps(1) == pytest.approx(q_before, rel=1e-6)
    assert ls.qps(2) == 0.0
    assert 2 not in ls._ranges


# -- replica queues ----------------------------------------------------------


def test_queue_priority_order_and_dedup():
    reg = metric.Registry()
    done = []
    q = ReplicaQueue("t-prio", done.append, registry=reg)
    assert q.maybe_add("a", 1.0)
    assert q.maybe_add("b", 5.0)
    assert not q.maybe_add("a", 0.5)   # lower priority: dedup keeps 1.0
    assert q.maybe_add("a", 3.0)       # higher priority wins
    assert len(q) == 2
    q.drain()
    assert done == ["b", "a"]          # highest priority first, a once
    assert q.processed.value == 2


def test_queue_purgatory_backoff_and_recovery():
    reg = metric.Registry()
    clk = _ManualClock()
    boom = {"on": True}

    def process(item):
        if boom["on"]:
            raise ConnectionError("transient")

    q = ReplicaQueue("t-purg", process, purgatory_errors=(ConnectionError,),
                     purgatory_interval_s=5.0, max_backoff_s=60.0,
                     registry=reg, clock=clk)
    q.maybe_add("r1", 1.0)
    q.drain()
    assert q.purgatory_len() == 1 and len(q) == 0
    assert q.failures.value == 0       # purgatory != dropped
    # purgatory owns retries: re-adding is refused
    assert not q.maybe_add("r1", 99.0)
    # before the backoff deadline nothing retries...
    assert q.drain() == 0
    # ...after it, the retry happens (and fails again: backoff doubles)
    clk.advance(5.0)
    assert q.drain() == 1
    assert q.purgatory_len() == 1
    clk.advance(5.0)                   # second try backs off 10s, not 5
    assert q.drain() == 0
    # the world gets better: a forced drain converges
    boom["on"] = False
    assert q.drain(force_purgatory=True) == 1
    assert q.purgatory_len() == 0
    assert q.processed.value == 1


def test_queue_unexpected_error_drops_item_not_queue():
    reg = metric.Registry()
    calls = []

    def process(item):
        calls.append(item)
        if item == "bad":
            raise ValueError("poison range")

    q = ReplicaQueue("t-drop", process, purgatory_errors=(ConnectionError,),
                     registry=reg)
    q.maybe_add("bad", 9.0)
    q.maybe_add("good", 1.0)
    q.drain()
    # the poison item is counted and dropped; the queue keeps serving
    assert calls == ["bad", "good"]
    assert q.failures.value == 1 and q.processed.value == 1
    assert q.purgatory_len() == 0 and len(q) == 0


def test_queue_start_stop_joins_thread():
    reg = metric.Registry()
    done = threading.Event()
    q = ReplicaQueue("t-loop", lambda item: done.set(), interval_s=0.01,
                     registry=reg)
    q.start()
    try:
        q.maybe_add("x", 1.0)
        assert done.wait(timeout=5.0), "background loop never processed"
    finally:
        q.stop()
    assert q._thread is None


# -- store pool --------------------------------------------------------------


def test_store_pool_thresholds_and_gossip_roundtrip():
    pool = StorePool()
    pool.note(StoreCapacity(1, 1, ranges=4, qps=90.0, logical_bytes=100))
    pool.note(StoreCapacity(2, 2, ranges=0, qps=10.0, logical_bytes=0))
    assert pool.mean_qps() == pytest.approx(50.0)
    assert [c.store_id for c in pool.overfull()] == [1]
    assert pool.least_loaded(exclude_store=1).store_id == 2
    assert pool.least_loaded(exclude_store=2).store_id == 1
    # advertisement round-trips through the gossip info encoding
    cap = StoreCapacity(3, 9, ranges=7, qps=1.5, logical_bytes=4096)
    assert StoreCapacity.from_info(cap.to_info()) == cap


# -- the tentpole: hot-key workload drives split + transfer + re-merge -------


def _settings_guard():
    """try/finally helper: snapshot the lifecycle settings, reset after."""
    return ("kv.range.split_qps_threshold", "kv.range.max_bytes",
            "kv.range.merge_enabled", "kv.allocator.enabled")


def test_hot_key_workload_splits_transfers_then_remerges():
    """The end-to-end story on a 2-store cluster: a skewed (YCSB-style
    hot-range) workload pushes one range over the QPS threshold; the
    split queue cuts it at the sampled median and the lease carries to
    the child; the rebalancer moves load onto the idle store and
    transfers the lease to that store's node; reads stay identical to a
    no-split dict oracle throughout; and once the load decays away the
    merge queue folds the keyspace back together."""
    from cockroach_tpu.kv.liveness import LeaseManager, NodeLiveness

    import random

    clk = _ManualClock()
    meta, stores, ds = _mk(n_stores=2)
    db = DB(ds, Clock())
    load = RangeLoadStats(half_life_s=5.0, sample_size=32, seed=3, clock=clk)
    ds.load = load
    # two "nodes" sharing the liveness range, one per store; node 1
    # drives the lifecycle and holds the initial lease
    nl1 = NodeLiveness(db, 1, ttl_ms=120_000)
    nl2 = NodeLiveness(db, 2, ttl_ms=120_000)
    nl1.heartbeat()
    nl2.heartbeat()
    lm = LeaseManager(nl1)
    lm.acquire(1)
    life = RangeLifecycle(ds, load=load, leases=lm, node_id=1,
                          store_nodes={1: 1, 2: 2}, clock=clk)
    settings.set("kv.range.split_qps_threshold", 5.0)
    try:
        splits0 = metric.KV_RANGE_SPLITS.value
        transfers0 = metric.KV_LEASE_TRANSFERS.value
        merges0 = metric.KV_RANGE_MERGES.value
        rng = random.Random(7)
        model = {}
        # skewed workload: 80% of ops hit the first fifth of the keyspace
        for _ in range(400):
            i = rng.randrange(40) if rng.random() < 0.8 \
                else 40 + rng.randrange(160)
            k = b"y%05d" % i
            v = b"v%05d" % rng.randrange(10_000)
            db.put(k, v)
            model[k] = v
        for _ in range(4):
            life.tick()
        assert metric.KV_RANGE_SPLITS.value > splits0, \
            "hot range never load-split"
        descs = meta.snapshot()
        assert len(descs) > 1
        # the split landed inside the keyspace (reservoir median), not at
        # an edge, and every child got a lease carried from the parent
        for d in descs:
            rec = lm.holder(d.range_id)
            assert rec is not None, f"r{d.range_id} lease vacant after split"
        # rebalance: the idle store took load and its node took the lease
        assert metric.KV_LEASE_TRANSFERS.value > transfers0, \
            "overfull store never shed a lease"
        assert {d.store_id for d in descs} == {1, 2}
        moved = [d for d in descs if d.store_id == 2]
        assert any(lm.holder(d.range_id).node_id == 2 for d in moved)
        # correctness oracle: identical to the unsplit dict model
        for k, v in model.items():
            assert db.get(k) == v
        got = {k: v for k, v in db.scan(b"y", b"z")}
        assert got == model
        # /hot_ranges payload: every range, hottest first, leaseholders on
        report = life.hot_ranges()["hotRanges"]
        assert len(report) == len(descs)
        assert [r["qps"] for r in report] == sorted(
            (r["qps"] for r in report), reverse=True)
        assert all(r["leaseholder"] in (1, 2) for r in report)
        assert all(r["sizeBytes"] > 0 for r in report)
        # the load goes away; everything decays cold and re-merges
        clk.advance(3600.0)
        for _ in range(10):
            life.tick()
            if len(meta.snapshot()) == 1:
                break
        assert metric.KV_RANGE_MERGES.value > merges0
        assert len(meta.snapshot()) == 1, "cold ranges never re-merged"
        # absorbed ranges' leases were released; data still intact
        live_ids = {d.range_id for d in meta.snapshot()}
        for d in descs:
            if d.range_id not in live_ids:
                assert lm.holder(d.range_id) is None
        assert {k: v for k, v in db.scan(b"y", b"z")} == model
    finally:
        for name in _settings_guard():
            settings.reset(name)


def test_split_disabled_below_threshold_and_merge_respects_setting():
    clk = _ManualClock()
    meta, stores, ds = _mk(n_stores=1)
    db = DB(ds, Clock())
    load = RangeLoadStats(half_life_s=5.0, sample_size=16, seed=4, clock=clk)
    ds.load = load
    life = RangeLifecycle(ds, load=load, clock=clk)
    try:
        # default thresholds: a light workload never trips the decider
        for i in range(50):
            db.put(b"q%04d" % i, b"v")
        life.tick()
        assert len(meta.snapshot()) == 1
        # admin-split a cold keyspace, but with merges disabled the
        # boundary stays put
        settings.set("kv.range.merge_enabled", False)
        ds.split_at(b"q0025")
        life.tick()
        assert len(meta.snapshot()) == 2
        settings.set("kv.range.merge_enabled", True)
        for _ in range(3):
            life.tick()
        assert len(meta.snapshot()) == 1
    finally:
        for name in _settings_guard():
            settings.reset(name)


def test_post_split_throughput_not_degraded():
    """Acceptance gate: after the lifecycle splits the hot range, the
    same workload's throughput is not materially worse than pre-split.
    The DistSender serializes on one process-wide lock, so a strict >=
    would flake on scheduler noise; 0.5x is the regression tripwire
    (a broken split path — e.g. routing retries on every op — lands far
    below it), and both numbers are reported on failure."""
    meta, stores, ds = _mk(n_stores=2)
    db = DB(ds, Clock())
    load = RangeLoadStats(half_life_s=5.0, sample_size=32, seed=5)
    ds.load = load
    life = RangeLifecycle(ds, load=load)
    settings.set("kv.range.split_qps_threshold", 5.0)
    try:
        import random

        rng = random.Random(11)

        def burst(n=300):
            t0 = time.perf_counter()
            for _ in range(n):
                i = rng.randrange(200)
                db.put(b"t%05d" % i, b"v%05d" % i)
                db.get(b"t%05d" % rng.randrange(200))
            return n / (time.perf_counter() - t0)

        pre = burst()  # also warms every JIT path
        splits0 = metric.KV_RANGE_SPLITS.value
        life.tick()
        assert metric.KV_RANGE_SPLITS.value > splits0, \
            "workload never tripped the split queue"
        post = burst()
        assert post >= pre * 0.5, \
            f"post-split throughput collapsed: {pre:.0f} -> {post:.0f} ops/s"
    finally:
        for name in _settings_guard():
            settings.reset(name)


# -- /hot_ranges surfaces ----------------------------------------------------


def test_admin_hot_ranges_payload_and_degraded_fallbacks():
    from cockroach_tpu.server.http import AdminServer

    meta, stores, ds = _mk(n_stores=1)
    db = DB(ds, Clock())
    load = RangeLoadStats(half_life_s=5.0, seed=6)
    ds.load = load
    db.put(b"hr-a", b"1")
    life = RangeLifecycle(ds, load=load)
    # with a ranger: the full lifecycle report
    node = types.SimpleNamespace(node_id=1, db=db, ranger=life)
    rows = AdminServer(node).hot_ranges()["hotRanges"]
    assert len(rows) == 1 and rows[0]["qps"] > 0
    assert rows[0]["sizeBytes"] > 0 and rows[0]["leaseholder"] is None
    # without a ranger but with a meta: bare descriptor table
    node2 = types.SimpleNamespace(node_id=1, db=db, ranger=None)
    rows2 = AdminServer(node2).hot_ranges()["hotRanges"]
    assert len(rows2) == 1 and rows2[0]["qps"] == 0.0
    # single-engine node (no meta at all): empty, never an error
    from cockroach_tpu.storage.lsm import Engine

    node3 = types.SimpleNamespace(
        node_id=1, db=types.SimpleNamespace(engine=Engine(
            key_width=16, val_width=16)), ranger=None)
    assert AdminServer(node3).hot_ranges() == {"hotRanges": []}


@pytest.mark.slow
def test_node_runs_lifecycle_and_serves_hot_ranges_http(capsys):
    """Full integration: a Node over a 2-store DistSender runs the
    lifecycle in the BACKGROUND (no synchronous ticks) — the seeded
    hot-key workload alone fires the split queue; /hot_ranges serves the
    distribution over real HTTP and the `hot-ranges` CLI verb renders
    it. close() joins every lifecycle thread (leak census)."""
    import json
    import random
    from urllib.request import urlopen

    from scripts.check_no_leaks import assert_no_leaks, snapshot

    from cockroach_tpu import cli
    from cockroach_tpu.server.node import Node

    before = snapshot()
    meta, stores, ds = _mk(n_stores=2)
    db = DB(ds, Clock())
    settings.set("kv.range.split_qps_threshold", 2.0)
    node = None
    try:
        node = Node(1, db=db, heartbeat_interval_s=0.05,
                    ttl_ms=60_000).start(gossip_port=0, http_port=0)
        assert node.ranger is not None, "allocator not wired on start"
        splits0 = metric.KV_RANGE_SPLITS.value
        rng = random.Random(13)
        deadline = time.monotonic() + 20.0
        while (metric.KV_RANGE_SPLITS.value == splits0
               and time.monotonic() < deadline):
            for _ in range(50):
                i = rng.randrange(40) if rng.random() < 0.8 \
                    else 40 + rng.randrange(160)
                db.put(b"n%05d" % i, b"v%05d" % i)
        assert metric.KV_RANGE_SPLITS.value > splits0, \
            "background lifecycle never split the hot range"
        url = f"http://127.0.0.1:{node.admin.port}/hot_ranges"
        with urlopen(url, timeout=5) as r:
            payload = json.load(r)
        assert len(payload["hotRanges"]) >= 2
        assert any(row["qps"] > 0 for row in payload["hotRanges"])
        # the CLI verb renders the same payload psql-style
        rc = cli.main(["hot-ranges",
                       "--url", f"http://127.0.0.1:{node.admin.port}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "rangeId" in out and "qps" in out
    finally:
        if node is not None:
            node.close()
        for name in _settings_guard():
            settings.reset(name)
    assert_no_leaks(before)
