"""Storage layer tests — MVCC scan filter, merge, LSM engine.

Mirrors the reference's storage test strategy (SURVEY.md §4): unit tests,
datadriven MVCC-history scripts (pkg/storage/mvcc_history_test.go), and a
randomized oracle diffing the engine against a pure-python MVCC model
(pkg/storage/metamorphic).
"""

import numpy as np
import pytest

from cockroach_tpu.storage import Engine, WriteIntentError
from cockroach_tpu.storage import keys as K
from cockroach_tpu.storage import mvcc

import jax.numpy as jnp


# ---------------------------------------------------------------------------
# key encoding


def test_key_words_order():
    keys = [b"a", b"ab", b"b", b"", b"zzz", b"a\x01", b"aa"]
    enc = K.encode_keys(keys, 16)
    words = np.asarray(K.key_words(jnp.asarray(enc)))
    order = sorted(range(len(keys)), key=lambda i: tuple(words[i]))
    assert [keys[i] for i in order] == sorted(keys)


def test_key_roundtrip():
    keys = [b"hello", b"", b"x" * 24]
    enc = K.encode_keys(keys, 24)
    assert K.decode_keys(enc) == keys


# ---------------------------------------------------------------------------
# MVCC scan filter kernel


def _block(rows, cap=None, kw=16, vw=8):
    """rows: list of (key, ts, txn, tomb, value)."""
    keys = K.encode_keys([r[0] for r in rows], kw)
    vals = np.zeros((len(rows), vw), dtype=np.uint8)
    vlen = np.zeros((len(rows),), dtype=np.int32)
    for i, r in enumerate(rows):
        v = r[4]
        vals[i, : len(v)] = np.frombuffer(v, dtype=np.uint8)
        vlen[i] = len(v)
    b = mvcc.block_from_host(
        keys,
        np.array([r[1] for r in rows]),
        np.array([r[2] for r in rows]),
        np.array([r[3] for r in rows]),
        vals,
        vlen,
        cap=cap or len(rows),
    )
    return mvcc.sort_block(b)


def _selected_keys(block, sel):
    idx = np.nonzero(np.asarray(sel))[0]
    ks = K.decode_keys(np.asarray(block.key)[idx])
    vs = [
        bytes(np.asarray(block.value)[i][: int(np.asarray(block.vlen)[i])])
        for i in idx
    ]
    return list(zip(ks, vs))


def test_scan_filter_newest_visible():
    b = _block([
        (b"a", 5, 0, False, b"a5"),
        (b"a", 3, 0, False, b"a3"),
        (b"b", 9, 0, False, b"b9"),
        (b"b", 2, 0, False, b"b2"),
    ])
    sel, conflict = mvcc.mvcc_scan_filter(b, jnp.int64(4), jnp.int64(0))
    assert not np.asarray(conflict).any()
    assert _selected_keys(b, sel) == [(b"a", b"a3"), (b"b", b"b2")]
    sel, _ = mvcc.mvcc_scan_filter(b, jnp.int64(100), jnp.int64(0))
    assert _selected_keys(b, sel) == [(b"a", b"a5"), (b"b", b"b9")]
    sel, _ = mvcc.mvcc_scan_filter(b, jnp.int64(1), jnp.int64(0))
    assert _selected_keys(b, sel) == []


def test_scan_filter_tombstone():
    b = _block([
        (b"a", 5, 0, True, b""),
        (b"a", 3, 0, False, b"a3"),
    ])
    sel, _ = mvcc.mvcc_scan_filter(b, jnp.int64(10), jnp.int64(0))
    assert _selected_keys(b, sel) == []  # deleted at ts 5
    sel, _ = mvcc.mvcc_scan_filter(b, jnp.int64(4), jnp.int64(0))
    assert _selected_keys(b, sel) == [(b"a", b"a3")]  # before the delete


def test_scan_filter_intents():
    b = _block([
        (b"a", 7, 42, False, b"a7i"),  # intent of txn 42
        (b"a", 3, 0, False, b"a3"),
    ])
    # txn 42 sees its own intent
    sel, conflict = mvcc.mvcc_scan_filter(b, jnp.int64(10), jnp.int64(42))
    assert not np.asarray(conflict).any()
    assert _selected_keys(b, sel) == [(b"a", b"a7i")]
    # another reader below the intent ts sees the committed version
    sel, conflict = mvcc.mvcc_scan_filter(b, jnp.int64(5), jnp.int64(0))
    assert not np.asarray(conflict).any()
    assert _selected_keys(b, sel) == [(b"a", b"a3")]
    # a reader at/above the intent ts conflicts (WriteIntentError)
    _, conflict = mvcc.mvcc_scan_filter(b, jnp.int64(8), jnp.int64(0))
    assert np.asarray(conflict).any()


def test_scan_filter_bounds():
    b = _block([
        (b"a", 1, 0, False, b"va"),
        (b"b", 1, 0, False, b"vb"),
        (b"c", 1, 0, False, b"vc"),
    ])
    sw = jnp.asarray(K.encode_bound(b"b", 16))
    ew = jnp.asarray(K.encode_bound(b"c", 16))
    sel, _ = mvcc.mvcc_scan_filter(b, jnp.int64(5), jnp.int64(0), sw, ew)
    assert _selected_keys(b, sel) == [(b"b", b"vb")]


def test_merge_blocks_sorted():
    b1 = _block([(b"a", 1, 0, False, b"1"), (b"c", 1, 0, False, b"1")])
    b2 = _block([(b"b", 2, 0, False, b"2"), (b"a", 3, 0, False, b"3")])
    m = mvcc.merge_blocks((b1, b2), cap=8)
    mask = np.asarray(m.mask)
    ks = K.decode_keys(np.asarray(m.key)[mask])
    ts = np.asarray(m.ts)[mask]
    assert ks == [b"a", b"a", b"b", b"c"]
    assert list(ts) == [3, 1, 2, 1]  # ts desc within key


def test_gc_filter():
    b = _block([
        (b"a", 9, 0, False, b"a9"),
        (b"a", 5, 0, False, b"a5"),
        (b"a", 2, 0, False, b"a2"),
        (b"b", 4, 0, True, b""),
        (b"b", 2, 0, False, b"b2"),
    ])
    keep = mvcc.mvcc_gc_filter(b, jnp.int64(6), bottom=True)
    kept = _selected_keys(b, np.asarray(keep))
    # a9 survives (> gc_ts), a5 survives (newest <= gc_ts), a2 dropped;
    # b@4 tombstone is newest <= gc_ts but b2 below it is dropped -> the
    # tombstone itself elides at the bottom level
    assert (b"a", b"a9") in kept and (b"a", b"a5") in kept
    assert (b"a", b"a2") not in kept
    assert all(k != b"b" for k, _ in kept)


# ---------------------------------------------------------------------------
# LSM engine


def test_engine_basic():
    eng = Engine(val_width=8, memtable_size=4)
    eng.put(b"a", b"1", ts=1)
    eng.put(b"b", b"2", ts=2)
    assert eng.get(b"a", ts=5) == b"1"
    assert eng.get(b"a", ts=0) is None
    eng.put(b"a", b"1b", ts=3)
    assert eng.get(b"a", ts=5) == b"1b"
    assert eng.get(b"a", ts=2) == b"1"
    eng.delete(b"b", ts=4)
    assert eng.get(b"b", ts=5) is None
    assert eng.get(b"b", ts=3) == b"2"
    assert eng.scan(None, None, ts=10) == [(b"a", b"1b")]


def test_engine_flush_compact():
    eng = Engine(val_width=8, memtable_size=2, l0_trigger=2)
    for i in range(20):
        eng.put(f"k{i:03d}".encode(), str(i % 7).encode(), ts=i + 1)
    res = eng.scan(None, None, ts=100)
    assert len(res) == 20
    assert res[0] == (b"k000", b"0")
    assert eng.stats.compactions > 0
    st = eng.compute_stats()
    assert st.live_count == 20 and st.key_count == 20


def test_engine_intent_flow():
    eng = Engine(val_width=8)
    eng.put(b"a", b"base", ts=1)
    eng.put(b"a", b"prov", ts=5, txn=7)
    with pytest.raises(WriteIntentError):
        eng.scan(None, None, ts=6)
    assert eng.get(b"a", ts=6, txn=7) == b"prov"
    eng.resolve_intents(txn=7, commit_ts=6, commit=True)
    assert eng.get(b"a", ts=6) == b"prov"
    assert eng.get(b"a", ts=5) == b"base"  # commit moved the version to ts 6


def test_engine_intent_abort():
    eng = Engine(val_width=8)
    eng.put(b"a", b"base", ts=1)
    eng.put(b"a", b"prov", ts=5, txn=7)
    eng.resolve_intents(txn=7, commit_ts=0, commit=False)
    assert eng.get(b"a", ts=10) == b"base"
    assert eng.intent_keys(7) == []


def test_engine_checkpoint(tmp_path):
    eng = Engine(val_width=8, memtable_size=3)
    for i in range(10):
        eng.put(f"k{i}".encode(), str(i).encode(), ts=i + 1)
    eng.checkpoint(str(tmp_path / "ckpt"))
    eng2 = Engine.open_checkpoint(str(tmp_path / "ckpt"))
    assert eng2.scan(None, None, ts=100) == eng.scan(None, None, ts=100)


# ---------------------------------------------------------------------------
# datadriven MVCC history scripts (mvcc_history_test.go style)

HISTORY_CASES = [
    (
        """
        put k=a v=v1 ts=1
        put k=a v=v2 ts=3
        del k=a ts=5
        put k=b v=v3 ts=2
        scan ts=4
        """,
        [(b"a", b"v2"), (b"b", b"v3")],
    ),
    (
        """
        put k=a v=v1 ts=1
        del k=a ts=2
        put k=a v=v4 ts=4
        scan ts=9
        """,
        [(b"a", b"v4")],
    ),
    (
        """
        put k=x v=p ts=4 txn=9
        put k=y v=q ts=1
        resolve txn=9 ts=6 commit=true
        scan ts=7
        """,
        [(b"x", b"p"), (b"y", b"q")],
    ),
]


@pytest.mark.parametrize("script,expected", HISTORY_CASES)
def test_mvcc_history(script, expected):
    eng = Engine(val_width=8)
    result = None
    for line in script.strip().splitlines():
        parts = line.split()
        cmd, kv = parts[0], dict(p.split("=") for p in parts[1:])
        if cmd == "put":
            eng.put(kv["k"], kv["v"], ts=int(kv["ts"]), txn=int(kv.get("txn", 0)))
        elif cmd == "del":
            eng.delete(kv["k"], ts=int(kv["ts"]), txn=int(kv.get("txn", 0)))
        elif cmd == "resolve":
            eng.resolve_intents(
                int(kv["txn"]), int(kv["ts"]), kv["commit"] == "true"
            )
        elif cmd == "scan":
            result = eng.scan(None, None, ts=int(kv["ts"]))
    assert result == expected


# ---------------------------------------------------------------------------
# randomized oracle vs a pure-python MVCC model (metamorphic style)


class _Model:
    def __init__(self):
        self.versions = {}  # key -> list of (ts, tomb, value)

    def put(self, k, v, ts):
        self.versions.setdefault(k, []).append((ts, False, v))

    def delete(self, k, ts):
        self.versions.setdefault(k, []).append((ts, True, b""))

    def scan(self, ts):
        out = []
        for k in sorted(self.versions):
            vis = [x for x in self.versions[k] if x[0] <= ts]
            if not vis:
                continue
            newest = max(vis, key=lambda x: x[0])
            if not newest[1]:
                out.append((k, newest[2]))
        return out


def test_engine_random_oracle(rng):
    eng = Engine(val_width=8, memtable_size=16, l0_trigger=3)
    model = _Model()
    keyspace = [f"k{i:02d}".encode() for i in range(24)]
    ts = 0
    for step in range(300):
        ts += 1
        k = keyspace[rng.integers(len(keyspace))]
        r = rng.random()
        if r < 0.6:
            v = f"v{step}".encode()
            eng.put(k, v, ts=ts)
            model.put(k, v, ts)
        elif r < 0.8:
            eng.delete(k, ts=ts)
            model.delete(k, ts)
        else:
            read_ts = int(rng.integers(1, ts + 1))
            assert eng.scan(None, None, ts=read_ts) == model.scan(read_ts), (
                f"divergence at step {step} read_ts {read_ts}"
            )
    assert eng.scan(None, None, ts=ts) == model.scan(ts)


def test_engine_rejects_nul_keys():
    """Zero-padded fixed-width key encoding cannot represent keys containing
    0x00 (b"a" == b"a\\x00" after padding) — the engine must reject them."""
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine()
    with pytest.raises(ValueError, match="0x00"):
        eng.put(b"a\x00b", b"v", ts=1)
    eng.put(b"ab", b"v", ts=1)  # NUL-free keys still fine
    assert eng.get(b"ab", ts=2) == b"v"


def test_wal_crash_recovery(tmp_path):
    """Writes since the last checkpoint survive a crash via WAL replay
    (pebble WAL semantics: write-ahead, truncate at checkpoint)."""
    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "wal.log")
    eng = Engine(val_width=8, wal_path=wal, memtable_size=4)
    for i in range(10):
        eng.put(b"k%02d" % i, b"v%d" % i, ts=i + 1)
    eng.delete(b"k03", ts=100)
    # crash: no checkpoint, engine dropped with a dirty memtable
    eng.close()
    del eng

    eng2 = Engine(val_width=8, wal_path=wal)
    assert eng2.get(b"k07", ts=200) == b"v7"
    assert eng2.get(b"k03", ts=200) is None  # tombstone replayed
    got = eng2.scan(None, None, ts=200)
    assert len(got) == 9
    eng2.close()


def test_wal_truncated_by_checkpoint(tmp_path):
    import os

    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "wal.log")
    ckpt = str(tmp_path / "ckpt")
    eng = Engine(val_width=8, wal_path=wal)
    eng.put(b"a", b"1", ts=1)
    eng.checkpoint(ckpt)
    assert os.path.getsize(wal) == 4  # just the magic: records truncated
    eng.put(b"b", b"2", ts=2)  # post-checkpoint write, only in WAL
    eng.close()

    eng2 = Engine.open_checkpoint(ckpt, wal_path=wal)
    assert eng2.get(b"a", ts=10) == b"1"
    assert eng2.get(b"b", ts=10) == b"2"  # replayed over the checkpoint
    eng2.close()


def test_tiered_compaction_partial_merge():
    """Incremental compaction merges only the smallest runs; the run set
    stays leveled instead of collapsing to one on every trigger, and reads
    stay correct across partially-merged runs."""
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine(val_width=8, memtable_size=8, l0_trigger=3,
                 compact_width=2)
    for i in range(80):
        eng.put(b"k%03d" % (i % 20), b"v%03d" % i, ts=i + 1)
    eng.flush()
    assert eng.stats.compactions >= 1
    assert len(eng.runs) >= 2, "tiered compaction must keep multiple runs"
    # correctness across the leveled runs: newest version per key wins
    for k in range(20):
        want = b"v%03d" % (60 + k)  # last write of key k
        assert eng.get(b"k%03d" % k, ts=1000) == want
    # a full bottom compaction still collapses everything
    eng.compact(bottom=True)
    assert len(eng.runs) == 1


def test_reads_do_not_mutate_runs():
    """get/scan must not flush the memtable or rewrite the run set (the
    round-1 engine re-merged the world on every read after a write)."""
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine(val_width=8, memtable_size=1024, l0_trigger=10)
    eng.put(b"a", b"1", ts=1)
    eng.flush()
    eng.put(b"b", b"2", ts=2)  # sits in the memtable
    runs_before = len(eng.runs)
    gen_before = eng._gen
    assert eng.get(b"a", ts=10) == b"1"
    assert eng.get(b"b", ts=10) == b"2"
    assert eng.scan(None, None, ts=10) == [(b"a", b"1"), (b"b", b"2")]
    assert len(eng.runs) == runs_before and eng._gen == gen_before
    assert len(eng.mem) == 1, "memtable must survive reads unflushed"


def test_wal_replay_preserves_committed_txns(tmp_path):
    """Intent resolutions are WAL-logged: without them, crash replay would
    resurrect an acknowledged commit's writes as unresolved intents
    (regression found in review, reproduced live)."""
    from cockroach_tpu.storage.lsm import Engine, WriteIntentError

    wal = str(tmp_path / "wal.log")
    eng = Engine(val_width=8, wal_path=wal)
    eng.put(b"a", b"1", ts=5, txn=7)
    eng.resolve_intents(7, commit_ts=5, commit=True)
    eng.put(b"b", b"2", ts=6, txn=9)
    eng.resolve_intents(9, commit_ts=0, commit=False)  # aborted
    eng.put(b"c", b"3", ts=7, txn=11)  # still open at crash time
    assert eng.get(b"a", ts=10) == b"1"
    eng.close()
    del eng

    eng2 = Engine(val_width=8, wal_path=wal)
    assert eng2.get(b"a", ts=10) == b"1"  # commit survived, no intent error
    assert eng2.get(b"b", ts=10) is None  # abort survived
    with pytest.raises(WriteIntentError):
        eng2.get(b"c", ts=10)  # open txn's intent correctly still blocks
    assert eng2.other_intent(b"c", 0) == 11  # lock table rebuilt from replay
    eng2.close()


def test_wal_torn_header(tmp_path):
    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "wal.log")
    with open(wal, "wb") as f:
        f.write(b"CT")  # crash mid-write of the magic
    eng = Engine(val_width=8, wal_path=wal)  # must not refuse to open
    eng.put(b"a", b"1", ts=1)
    eng.close()
    eng2 = Engine(val_width=8, wal_path=wal)
    assert eng2.get(b"a", ts=5) == b"1"
    eng2.close()


def test_wal_torn_tail_truncated_before_append(tmp_path):
    """Torn tail bytes are truncated before new appends; without that, new
    records land after garbage and later replays misparse them."""
    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "wal.log")
    eng = Engine(val_width=8, wal_path=wal)
    eng.put(b"a", b"1", ts=1)
    eng.close()
    with open(wal, "ab") as f:
        f.write(b"\x00" * 7)  # crash mid-record: torn header bytes

    eng2 = Engine(val_width=8, wal_path=wal)
    assert eng2.get(b"a", ts=5) == b"1"
    eng2.put(b"b", b"2", ts=2)  # appended after the truncation point
    eng2.close()

    eng3 = Engine(val_width=8, wal_path=wal)
    assert eng3.get(b"a", ts=5) == b"1"
    assert eng3.get(b"b", ts=5) == b"2"  # survived a second replay intact
    eng3.close()


def test_ingest_survives_crash_via_wal(tmp_path):
    """Bulk-ingested runs are durable: the run lands in a fsynced side file
    plus a WAL link record BEFORE the ingest is acknowledged, so WAL replay
    restores it alongside transactional writes (the AddSSTable durability
    contract — reference pkg/kvserver/batcheval/cmd_add_sstable.go)."""
    import numpy as np

    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "wal.log")
    eng = Engine(val_width=8, wal_path=wal)
    eng.put(b"w1", b"tx1", ts=1)
    keys = np.zeros((4, eng.key_width), dtype=np.uint8)
    vals = np.zeros((4, 4), dtype=np.uint8)
    for i in range(4):
        kb = b"ing%d" % i
        keys[i, : len(kb)] = np.frombuffer(kb, np.uint8)
        vb = b"v%03d" % i
        vals[i] = np.frombuffer(vb, np.uint8)
    eng.ingest(keys, vals, ts=5)
    eng.put(b"w2", b"tx2", ts=7)  # post-ingest write replays in order
    eng.close()
    del eng

    eng2 = Engine(val_width=8, wal_path=wal)
    assert eng2.get(b"w1", ts=100) == b"tx1"
    assert eng2.get(b"w2", ts=100) == b"tx2"
    for i in range(4):
        assert eng2.get(b"ing%d" % i, ts=100) == b"v%03d" % i
    # a second crash+replay is idempotent (seq high-water guards relinks)
    eng2.close()
    eng3 = Engine(val_width=8, wal_path=wal)
    assert eng3.get(b"ing2", ts=100) == b"v002"
    assert len(eng3.scan(None, None, ts=100)) == 6
    eng3.close()


def test_ingest_side_files_cleaned_by_checkpoint(tmp_path):
    """Checkpoint folds ingested runs into its .npz set and truncates the
    WAL; the now-unreferenced ingest side files are removed."""
    import glob
    import numpy as np

    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "wal.log")
    eng = Engine(val_width=8, wal_path=wal)
    keys = np.zeros((2, eng.key_width), dtype=np.uint8)
    keys[0, :2] = np.frombuffer(b"aa", np.uint8)
    keys[1, :2] = np.frombuffer(b"bb", np.uint8)
    vals = np.full((2, 2), ord("x"), dtype=np.uint8)
    eng.ingest(keys, vals, ts=3)
    assert glob.glob(wal + ".ingest*.npz")
    ckpt = str(tmp_path / "ckpt")
    eng.checkpoint(ckpt)
    assert not glob.glob(wal + ".ingest*.npz")
    eng.close()

    eng2 = Engine.open_checkpoint(ckpt, wal_path=wal)
    assert eng2.get(b"aa", ts=100) == b"xx"
    assert eng2.get(b"bb", ts=100) == b"xx"
    eng2.close()


# ---------------------------------------------------------------------------
# batched multi-scan (kv Streamer analog)


def test_scan_batch_matches_serial():
    eng = Engine(key_width=16, val_width=8, memtable_size=1 << 20)
    n = 500
    for i in range(n):
        eng.put(b"k%08d" % i, b"v%d" % (i % 97), ts=5)
    # overwrite some keys at a later ts + tombstone a few
    for i in range(0, n, 7):
        eng.put(b"k%08d" % i, b"w%d" % i, ts=9)
    for i in range(0, n, 31):
        eng.delete(b"k%08d" % i, ts=10)
    eng.flush()
    starts = [b"k%08d" % s for s in (0, 3, 77, 250, 444, 499, 900)]
    batched = eng.scan_batch(starts, ts=11, max_keys=17)
    for s, got in zip(starts, batched):
        want = eng.scan(s, None, ts=11, max_keys=17)
        assert got == want, f"start={s!r}"


def test_scan_batch_grows_window():
    eng = Engine(key_width=16, val_width=8, memtable_size=1 << 20)
    # many versions per key force the initial window to truncate
    for i in range(64):
        for ts in range(1, 12):
            eng.put(b"q%06d" % i, b"v%d" % ts, ts=ts)
    eng.flush()
    got = eng.scan_batch([b"q%06d" % 0], ts=20, max_keys=48)[0]
    want = eng.scan(b"q%06d" % 0, None, ts=20, max_keys=48)
    assert got == want
    assert len(got) == 48


def test_scan_batch_sees_memtable_and_intents():
    eng = Engine(key_width=16, val_width=8, memtable_size=1 << 20)
    for i in range(100):
        eng.put(b"m%06d" % i, b"v", ts=3)
    eng.flush()
    eng.put(b"m%06d" % 50, b"mem", ts=6)  # stays in memtable
    got = eng.scan_batch([b"m%06d" % 49], ts=7, max_keys=3)[0]
    assert got[1] == (b"m%06d" % 50, b"mem")
    # an intent from another txn inside one scan's range -> conflict
    eng.put(b"m%06d" % 60, b"i", ts=8, txn=42)
    with pytest.raises(WriteIntentError):
        eng.scan_batch([b"m%06d" % 58, b"m%06d" % 0], ts=9, max_keys=5)
    # the intent owner reads its own write
    got = eng.scan_batch([b"m%06d" % 60], ts=9, txn=42, max_keys=1)[0]
    assert got[0] == (b"m%06d" % 60, b"i")


def test_scan_batch_pages_past_tombstone_runs():
    # a truncated window whose rows are ALL tombstoned must page forward,
    # not return short (regression: growth keyed on selected-row
    # incompleteness only)
    eng = Engine(key_width=16, val_width=8, memtable_size=1 << 20)
    for i in range(500):
        eng.put(b"k%08d" % i, b"v%d" % i, ts=5)
    for i in range(400):
        eng.delete(b"k%08d" % i, ts=10)
    eng.flush()
    got = eng.scan_batch([b"k%08d" % 0], ts=11, max_keys=17)[0]
    want = eng.scan(b"k%08d" % 0, None, ts=11, max_keys=17)
    assert got == want
    assert len(got) == 17


def test_wal_torn_ingest_side_file(tmp_path):
    # a torn .ingest*.npz (crash mid-write) must not make the store
    # unopenable — the run is dropped with a warning, everything else replays
    wal = str(tmp_path / "w.wal")
    eng = Engine(key_width=16, val_width=8, wal_path=wal)
    eng.put(b"keep", b"x", ts=3)
    keys = np.zeros((4, 16), dtype=np.uint8)
    for i in range(4):
        keys[i, :6] = np.frombuffer(b"ing%03d" % i, dtype=np.uint8)
    eng.ingest(keys, np.full((4, 8), ord("v"), np.uint8), ts=5)
    import glob, os
    side = glob.glob(str(tmp_path / "*.ingest*.npz"))[0]
    with open(side, "r+b") as f:  # tear it: truncate mid-zip
        f.truncate(os.path.getsize(side) // 2)
    eng2 = Engine(key_width=16, val_width=8, wal_path=wal)
    assert eng2.get(b"keep", ts=10) == b"x"  # store opens; put survives
    assert eng2.get(b"ing000", ts=10) is None  # torn run dropped


def test_metamorphic_op_sequence_across_configs():
    """The pkg/storage/metamorphic discipline: ONE random op sequence
    (puts, deletes, ingests, scans, gets, flushes, compactions,
    intent lay/resolve) runs against engines with DIFFERENT tuning
    (memtable size, L0 trigger, compaction width) — every read result
    must be identical across configs; tuning may change performance,
    never answers."""
    import numpy as np

    from cockroach_tpu.storage.lsm import Engine, WriteIntentError

    configs = [
        dict(memtable_size=4, l0_trigger=2, compact_width=2),
        dict(memtable_size=64, l0_trigger=8, compact_width=4),
        dict(memtable_size=1024, l0_trigger=64, compact_width=8),
    ]
    engines = [Engine(key_width=16, val_width=16, **c) for c in configs]
    rng = np.random.default_rng(77)

    def key(i: int) -> bytes:
        return b"m%05d" % i

    ts = 0
    for step in range(140):
        kind = rng.random()
        ts += 1
        if kind < 0.3:
            k, v = key(int(rng.integers(0, 60))), b"v%04d" % step
            if rng.random() < 0.3:
                # var-width: overflow-heap values interleave with inline
                v = v * int(rng.integers(4, 40))
            for e in engines:
                e.put(k, v, ts=ts)
        elif kind < 0.4:
            k = key(int(rng.integers(0, 60)))
            for e in engines:
                e.delete(k, ts=ts)
        elif kind < 0.5:
            lo = int(rng.integers(0, 50))
            width = int(rng.integers(1, 12))
            keys = np.zeros((width, 16), np.uint8)
            for j in range(width):
                kb = key(lo + j)
                keys[j, :len(kb)] = np.frombuffer(kb, np.uint8)
            vals = np.zeros((width, 16), np.uint8)
            pay = b"g%04d" % step
            vals[:, :len(pay)] = np.frombuffer(pay, np.uint8)
            for e in engines:
                e.ingest(keys.copy(), vals.copy(), ts=ts)
        elif kind < 0.56:
            txn = 1000 + step
            k = key(int(rng.integers(0, 60)))
            commit = rng.random() < 0.5
            for e in engines:
                e.put(k, b"i%04d" % step, ts=ts, txn=txn)
                e.resolve_intents(txn, ts, commit=commit)
        elif kind < 0.62:
            for e in engines:
                e.flush()
        elif kind < 0.66:
            for e in engines:
                e.compact(bottom=bool(rng.random() < 0.3))
        elif kind < 0.85:
            lo = int(rng.integers(0, 55))
            hi = lo + int(rng.integers(1, 20))
            mk = (int(rng.integers(1, 8))
                  if rng.random() < 0.5 else None)
            results = [
                e.scan(key(lo), key(hi), ts=ts, max_keys=mk)
                for e in engines
            ]
            assert results[0] == results[1] == results[2], (
                step, lo, hi, mk,
                [r[:3] for r in results],
            )
        else:
            k = key(int(rng.integers(0, 60)))
            # historical read at a random past timestamp
            at = int(rng.integers(1, ts + 1))
            got = [e.get(k, ts=at) for e in engines]
            assert got[0] == got[1] == got[2], (step, k, at, got)

    # final: full sweeps and stats-visible state agree
    sweeps = [dict(e.scan(key(0), key(99999), ts=ts + 1)) for e in engines]
    assert sweeps[0] == sweeps[1] == sweeps[2]
    # run counts legitimately DIFFER (that's the point of the tuning);
    # the data cannot
    assert len({e.stats.runs for e in engines}) >= 1


def test_bloom_filters_prune_point_reads():
    """Per-run bloom filters (pebble table-filter role): point gets skip
    runs that definitely lack the key; answers never change."""
    from cockroach_tpu.storage.lsm import Engine
    from cockroach_tpu.utils import metric

    eng = Engine(key_width=16, val_width=16, memtable_size=4,
                 l0_trigger=64)
    # several disjoint runs (tiny memtable flushes constantly)
    for i in range(40):
        eng.put(b"b%05d" % i, b"v%05d" % i, ts=i + 1)
    eng.flush()
    assert len(eng.runs) >= 4
    # present keys: correct values
    for i in (0, 17, 39):
        assert eng.get(b"b%05d" % i, ts=100) == b"v%05d" % i
    # absent keys: bloom pruning engages (counter moves) and stays correct
    before = metric.BLOOM_SKIPS.value
    for i in range(200, 240):
        assert eng.get(b"b%05d" % i, ts=100) is None
    assert metric.BLOOM_SKIPS.value > before
    # a present key still found after more churn + compaction
    eng.compact(bottom=True)
    assert eng.get(b"b%05d" % 17, ts=100) == b"v%05d" % 17


# -- variable-width values (the overflow heap; pebble value-separation /
# coldata/bytes.go offsets+payload role) ------------------------------------


def test_varwidth_put_get_roundtrip():
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine(val_width=16)
    small = b"tiny"
    big = bytes(range(256)) * 5  # 1280 bytes, 80x the inline width
    eng.put(b"a", small, ts=1)
    eng.put(b"b", big, ts=1)
    assert eng.get(b"a", ts=2) == small
    assert eng.get(b"b", ts=2) == big
    # scan resolves overflow pointers too
    assert eng.scan(None, None, ts=2) == [(b"a", small), (b"b", big)]


def test_varwidth_survives_flush_and_compaction():
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine(val_width=16, memtable_size=4, l0_trigger=3)
    vals = {b"k%02d" % i: (b"x%03d" % i) * (i + 1) for i in range(20)}
    for i, (k, v) in enumerate(sorted(vals.items())):
        eng.put(k, v, ts=i + 1)
    eng.flush()
    eng.compact(bottom=True)
    for k, v in vals.items():
        assert eng.get(k, ts=100) == v


def test_varwidth_wal_replay(tmp_path):
    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "wal.bin")
    eng = Engine(val_width=16, wal_path=wal)
    big = b"payload-" * 50
    eng.put(b"k1", big, ts=1)
    eng.put(b"k2", b"small", ts=2)
    eng.put(b"k3", big[::-1], ts=3)
    # crash: reopen from the WAL alone
    eng2 = Engine(val_width=16, wal_path=wal)
    assert eng2.get(b"k1", ts=10) == big
    assert eng2.get(b"k2", ts=10) == b"small"
    assert eng2.get(b"k3", ts=10) == big[::-1]


def test_varwidth_checkpoint_roundtrip(tmp_path):
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine(val_width=16)
    big = b"0123456789abcdef" * 9
    eng.put(b"k1", big, ts=1)
    eng.put(b"k2", b"inline", ts=2)
    ck = str(tmp_path / "ck")
    eng.checkpoint(ck)
    eng2 = Engine.open_checkpoint(ck)
    assert eng2.get(b"k1", ts=10) == big
    assert eng2.get(b"k2", ts=10) == b"inline"


def test_varwidth_export_import_rehomes_blobs():
    from cockroach_tpu.storage.lsm import Engine

    src = Engine(val_width=16)
    big1 = b"A" * 100
    big2 = b"B" * 333
    src.put(b"k1", big1, ts=1)
    src.put(b"k2", b"sm", ts=2)
    src.put(b"k3", big2, ts=3)
    rows = src.export_span(None, None)
    dst = Engine(val_width=16)
    # pollute the destination heap so offsets cannot accidentally line up
    dst.put(b"zzz", b"C" * 77, ts=1)
    dst.import_rows(rows)
    assert dst.get(b"k1", ts=10) == big1
    assert dst.get(b"k2", ts=10) == b"sm"
    assert dst.get(b"k3", ts=10) == big2
    assert dst.get(b"zzz", ts=10) == b"C" * 77


def test_varwidth_scan_batch():
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine(val_width=16)
    big = b"Z" * 64
    for i in range(8):
        eng.put(b"s%02d" % i, big if i % 2 else b"s", ts=1)
    out = eng.scan_batch([b"s00", b"s04"], ts=2, max_keys=4)
    assert out[0] == [(b"s%02d" % i, big if i % 2 else b"s")
                      for i in range(4)]
    assert out[1] == [(b"s%02d" % i, big if i % 2 else b"s")
                      for i in range(4, 8)]


def test_varwidth_kv_table_long_strings():
    """>16-byte strings flow through KV tables without width errors (the
    dictionary entry lands in the overflow heap)."""
    from cockroach_tpu.sql.session import Session

    long_s = "the quick brown fox jumps over the lazy dog " * 4
    sess = Session()
    sess.execute("create table ls (id int primary key, s string)")
    sess.execute(f"insert into ls values (1, '{long_s}'), (2, 'short')")
    got = sess.execute("select s from ls where id = 1")
    assert list(got["s"]) == [long_s]
    # restart path: dictionary reloads from the engine
    from cockroach_tpu.catalog import Catalog
    from cockroach_tpu.kv.table import load_catalog_from_engine

    cat = Catalog()
    load_catalog_from_engine(cat, sess.db)
    row = cat.tables["ls"].get_row(1)
    assert row["s"] == long_s

# -- bulk ingest (storage/ingest.py RunBuilder) ------------------------------


def test_bulk_ingest_bit_identity_with_mvcc_ops():
    """The AddSSTable contract: rows landed through the RunBuilder (device
    sort/merge/dedup, memtable bypass) must be indistinguishable from
    per-key puts under EVERY later MVCC operation — tombstones, intents,
    resolution, compaction — not just an initial scan."""
    from cockroach_tpu.storage import ingest as bulk
    from cockroach_tpu.storage.lsm import Engine

    n = 300

    def key(i: int) -> bytes:
        return b"bi%06d" % i

    keys = np.zeros((n, 16), np.uint8)
    vals = np.zeros((n, 16), np.uint8)
    for i in range(n):
        kb, vb = key(i), b"v%06d" % i
        keys[i, : len(kb)] = np.frombuffer(kb, np.uint8)
        vals[i, : len(vb)] = np.frombuffer(vb, np.uint8)
    vals2 = vals.copy()
    vals2[:, 0] = ord("w")  # second version of the first 50 keys

    # duplicates within one flush dedup device-side, later batch winning
    e_dup = Engine(key_width=16, val_width=16, memtable_size=64)
    rb = bulk.RunBuilder(e_dup, ts=5, target_rows=1 << 16)
    rb.add(keys[:50], vals[:50])
    rb.add(keys[:50], vals2[:50])
    assert rb.finish() == {"rows": 50, "runs": 1}
    assert e_dup.get(key(3), ts=6) == bytes(vals2[3])

    e_ing = Engine(key_width=16, val_width=16, memtable_size=64)
    rb = bulk.RunBuilder(e_ing, ts=5, target_rows=128)  # forces >1 run
    rb.add(keys[:200], vals[:200])
    rb.add(keys[200:], vals[200:])
    rb.add(keys[:50], vals2[:50])  # cross-run overlap: seq order wins
    got = rb.finish()
    assert got["runs"] >= 2 and got["rows"] >= n

    e_put = Engine(key_width=16, val_width=16, memtable_size=64)
    for i in range(n):
        e_put.put(bytes(keys[i]).rstrip(b"\0"), bytes(vals[i]), ts=5)
    for i in range(50):  # same overwrite, same ts: higher seq wins
        e_put.put(bytes(keys[i]).rstrip(b"\0"), bytes(vals2[i]), ts=5)

    engines = (e_ing, e_put)
    assert e_ing.scan(key(0), key(n), ts=6) == e_put.scan(
        key(0), key(n), ts=6)

    # identical MVCC op sequence on both
    for e in engines:
        for i in range(0, n, 7):
            e.delete(key(i), ts=8)
        e.put(key(33), b"intent-c", ts=9, txn=42)
        e.put(key(34), b"intent-a", ts=9, txn=43)
    for e in engines:
        with pytest.raises(WriteIntentError):
            e.scan(key(30), key(40), ts=10)
        own = e.scan(key(30), key(34), ts=10, txn=42)
        assert (key(33), b"intent-c") in own
        e.resolve_intents(42, commit_ts=9, commit=True)
        e.resolve_intents(43, commit_ts=0, commit=False)

    # divergent physical maintenance must not create logical divergence
    e_ing.compact(bottom=True)
    e_put.flush()
    assert e_ing.scan(key(0), key(n), ts=20) == e_put.scan(
        key(0), key(n), ts=20)
    for i in (0, 7, 33, 34, 49, 50, 299):
        assert e_ing.get(key(i), ts=20) == e_put.get(key(i), ts=20)
    # historical reads below the ops agree too
    assert e_ing.scan(key(0), key(n), ts=6) == e_put.scan(
        key(0), key(n), ts=6)


def test_wal_torn_ingest_link_record_replay(tmp_path):
    """A crash that tears the _REC_INGEST link record itself (side file
    durable, WAL record half-written): replay must drop the torn link —
    the run stays invisible — while everything before it survives, and a
    fresh ingest afterwards lands cleanly."""
    import os

    from cockroach_tpu.storage.lsm import Engine

    wal = str(tmp_path / "w.wal")
    eng = Engine(key_width=16, val_width=8, wal_path=wal)
    eng.put(b"keep", b"x", ts=1)
    eng.close()
    size0 = os.path.getsize(wal)

    eng = Engine(key_width=16, val_width=8, wal_path=wal)
    keys = np.zeros((4, 16), np.uint8)
    for i in range(4):
        keys[i, :6] = np.frombuffer(b"ing%03d" % i, np.uint8)
    eng.ingest(keys, np.full((4, 8), ord("v"), np.uint8), ts=5)
    eng.close()
    size1 = os.path.getsize(wal)
    assert size1 > size0
    with open(wal, "r+b") as f:  # tear the link record in half
        f.truncate(size0 + (size1 - size0) // 2)

    eng2 = Engine(key_width=16, val_width=8, wal_path=wal)
    assert eng2.get(b"keep", ts=10) == b"x"
    assert eng2.get(b"ing000", ts=10) is None  # torn link never replays
    eng2.ingest(keys, np.full((4, 8), ord("v"), np.uint8), ts=6)  # retry
    eng2.close()

    eng3 = Engine(key_width=16, val_width=8, wal_path=wal)
    assert eng3.get(b"keep", ts=10) == b"x"
    for i in range(4):
        assert eng3.get(b"ing%03d" % i, ts=10) == b"v" * 8
    assert len(eng3.scan(None, None, ts=10)) == 5
    eng3.close()


# -- compaction pacing (utils/admission.IOGovernor) --------------------------


def test_compaction_pacing_defers_then_debt_bypasses():
    """With a minimum inter-compaction interval set, small debt defers
    (counted + histogram-recorded when the compaction finally runs) but
    debt past max_debt_runs compacts immediately — pacing may trade
    latency, never unbounded read amplification."""
    import time as _time

    from cockroach_tpu.storage.lsm import Engine
    from cockroach_tpu.utils import metric, settings

    settings.set("storage.compaction.pacing.min_interval_ms", 60_000)
    settings.set("storage.compaction.pacing.max_debt_runs", 4)
    try:
        eng = Engine(key_width=16, val_width=8, memtable_size=4,
                     l0_trigger=2, compact_width=2)
        # pretend a compaction just ran, so the interval gate is active
        eng.governor._last_compaction_t = _time.monotonic()
        deferred0 = eng.governor.compactions_deferred
        hist_n0 = metric.COMPACTION_PACING_DELAY.n
        for i in range(16):  # tiny memtable: flushes pile up runs
            eng.put(b"p%05d" % i, b"v", ts=i + 1)
        eng.flush()
        # debt is in the paced band: deferrals observed, nothing compacted
        assert eng.governor.compactions_deferred > deferred0
        assert eng.stats.compactions == 0
        assert 0 < eng.governor.compaction_debt() <= 4
        for i in range(16, 48):  # push debt past max_debt_runs
            eng.put(b"p%05d" % i, b"v", ts=i + 1)
        eng.flush()
        assert eng.stats.compactions >= 1, "max debt must bypass pacing"
        # the bypassing run recorded how long pacing had held things back
        assert metric.COMPACTION_PACING_DELAY.n > hist_n0
        # answers unaffected by the deferral games
        for i in (0, 15, 47):
            assert eng.get(b"p%05d" % i, ts=100) == b"v"
        # disabled pacing -> compact on every trigger (seed behavior)
        settings.set("storage.compaction.pacing.enabled", False)
        before = eng.stats.compactions
        for i in range(48, 80):
            eng.put(b"p%05d" % i, b"v", ts=i + 1)
        eng.flush()
        assert eng.stats.compactions > before
    finally:
        settings.reset("storage.compaction.pacing.min_interval_ms")
        settings.reset("storage.compaction.pacing.max_debt_runs")
        settings.reset("storage.compaction.pacing.enabled")
