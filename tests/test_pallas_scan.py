"""Pallas MVCC scan-filter parity vs the jnp filter (interpret mode on
CPU; the real-chip run happens in bench.py's YCSB phase on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from cockroach_tpu.storage import mvcc
from cockroach_tpu.storage.pallas_scan import pallas_scan_filter


def _window_block(rng, B=4, window=256, nkeys=40, read_ts=50):
    """Random MVCC windows in the multi_scan layout: each row holds sorted
    (key asc, ts desc, seq desc) entries with dead tails."""
    rows = []
    for b in range(B):
        entries = []
        for _ in range(rng.integers(5, nkeys)):
            key = b"k%06d" % rng.integers(0, 30)
            for _ in range(rng.integers(1, 4)):
                entries.append((
                    key,
                    int(rng.integers(1, 100)),        # ts
                    int(rng.integers(0, 3)),          # txn (0 committed)
                    bool(rng.random() < 0.2),         # tombstone
                ))
        entries.sort(key=lambda e: (e[0], -e[1]))
        entries = entries[:window]
        rows.append(entries)
    n = B * window
    keys = np.zeros((n, 16), np.uint8)
    ts = np.zeros(n, np.int64)
    txn = np.zeros(n, np.int64)
    tomb = np.zeros(n, bool)
    mask = np.zeros(n, bool)
    for b, entries in enumerate(rows):
        for i, (key, t, x, tb) in enumerate(entries):
            j = b * window + i
            keys[j, :len(key)] = np.frombuffer(key, np.uint8)
            ts[j], txn[j], tomb[j], mask[j] = t, x, tb, True
    blk = mvcc.KVBlock(
        key=jnp.asarray(keys), ts=jnp.asarray(ts),
        seq=jnp.zeros(n, jnp.int64), txn=jnp.asarray(txn),
        tomb=jnp.asarray(tomb), value=jnp.zeros((n, 8), jnp.uint8),
        vlen=jnp.zeros(n, jnp.int32), mask=jnp.asarray(mask),
    )
    return blk


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_pallas_filter_matches_jnp(seed):
    rng = np.random.default_rng(seed)
    blk = _window_block(rng)
    for read_ts, reader in ((50, 0), (10, 0), (50, 1), (200, 2)):
        want_sel, want_conf = mvcc.mvcc_scan_filter(
            blk, jnp.int64(read_ts), jnp.int64(reader), window=256)
        got_sel, got_conf = pallas_scan_filter(
            blk, jnp.int64(read_ts), jnp.int64(reader), window=256,
            interpret=True)
        np.testing.assert_array_equal(
            np.asarray(got_sel), np.asarray(want_sel),
            err_msg=f"selected mismatch at {(read_ts, reader)}")
        np.testing.assert_array_equal(
            np.asarray(got_conf), np.asarray(want_conf),
            err_msg=f"conflict mismatch at {(read_ts, reader)}")


def test_pallas_filter_edge_windows():
    # empty windows, all-tombstone windows, single huge key run
    window = 128
    n = 3 * window
    keys = np.zeros((n, 16), np.uint8)
    ts = np.zeros(n, np.int64)
    tomb = np.zeros(n, bool)
    mask = np.zeros(n, bool)
    # window 0: empty. window 1: one key, all versions tombstoned
    for i in range(20):
        j = window + i
        keys[j, :4] = np.frombuffer(b"aaaa", np.uint8)
        ts[j] = 100 - i
        tomb[j] = True
        mask[j] = True
    # window 2: one key run spanning the whole window
    for i in range(window):
        j = 2 * window + i
        keys[j, :4] = np.frombuffer(b"bbbb", np.uint8)
        ts[j] = 10_000 - i
        mask[j] = True
    blk = mvcc.KVBlock(
        key=jnp.asarray(keys), ts=jnp.asarray(ts),
        seq=jnp.zeros(n, jnp.int64), txn=jnp.zeros(n, jnp.int64),
        tomb=jnp.asarray(tomb), value=jnp.zeros((n, 8), jnp.uint8),
        vlen=jnp.zeros(n, jnp.int32), mask=jnp.asarray(mask),
    )
    want = mvcc.mvcc_scan_filter(blk, jnp.int64(50_000), jnp.int64(0),
                                 window=window)
    got = pallas_scan_filter(blk, jnp.int64(50_000), jnp.int64(0),
                             window=window, interpret=True)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))


def test_scan_batch_through_pallas_filter():
    """End-to-end batched scans with the Pallas filter forced on
    (interpret mode on CPU) must equal the jnp-filtered results."""
    from cockroach_tpu.storage.lsm import Engine
    from cockroach_tpu.utils import settings

    def build():
        eng = Engine(key_width=16, val_width=8, memtable_size=1 << 20)
        for i in range(400):
            eng.put(b"k%08d" % i, b"v%d" % i, ts=5)
        for i in range(0, 400, 7):
            eng.put(b"k%08d" % i, b"w%d" % i, ts=9)
        for i in range(0, 400, 31):
            eng.delete(b"k%08d" % i, ts=10)
        eng.flush()
        return eng

    eng = build()
    starts = [b"k%08d" % s for s in (0, 13, 100, 399)]
    settings.set("storage.pallas_filter", "off")
    try:
        want = eng.scan_batch(starts, ts=11, max_keys=20)
        settings.set("storage.pallas_filter", "on")
        got = eng.scan_batch(starts, ts=11, max_keys=20)
    finally:
        settings.reset("storage.pallas_filter")
    assert got == want
