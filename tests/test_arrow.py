"""Arrow interchange golden tests vs pyarrow (colserde parity:
pkg/col/colserde/arrowbatchconverter_test.go round-trip strategy)."""

import numpy as np
import pyarrow as pa
import pytest

from cockroach_tpu import coldata as cd
from cockroach_tpu.coldata import arrow as A
from cockroach_tpu.coldata.batch import Dictionary, from_host, to_host


def test_fixed_width_roundtrip_zero_copy():
    ints = np.arange(1000, dtype=np.int64) - 500
    arr = A.column_to_arrow(ints, np.ones(1000, bool), cd.INT64)
    assert arr.type == pa.int64() and arr.null_count == 0
    back, valid, d = A.column_from_arrow(arr)
    np.testing.assert_array_equal(back, ints)
    assert valid.all() and d is None
    assert np.shares_memory(back, np.asarray(arr))  # zero-copy return


def test_nulls_roundtrip():
    vals = np.array([1.5, 2.5, 3.5, 4.5])
    valid = np.array([True, False, True, False])
    arr = A.column_to_arrow(vals, valid, cd.FLOAT64)
    assert arr.null_count == 2
    assert arr.to_pylist() == [1.5, None, 3.5, None]
    back, v2, _ = A.column_from_arrow(arr)
    np.testing.assert_array_equal(v2, valid)
    np.testing.assert_array_equal(back[valid], vals[valid])


def test_decimal_exact_roundtrip():
    scaled = np.array([123456, -999, 0, 2**53 + 1], dtype=np.int64)
    t = cd.DECIMAL(18, 2)
    arr = A.column_to_arrow(scaled, np.ones(4, bool), t)
    assert arr.type == pa.decimal128(38, 2)
    # golden: pyarrow sees the true decimal values
    import decimal

    assert arr[0].as_py() == decimal.Decimal("1234.56")
    assert arr[1].as_py() == decimal.Decimal("-9.99")
    back, _, _ = A.column_from_arrow(arr)
    np.testing.assert_array_equal(back, scaled)  # bit-exact, no float trip


def test_decimal_overflow_detected():
    big = pa.array([10**25], type=pa.decimal128(38, 2))
    with pytest.raises(OverflowError):
        A.column_from_arrow(big)


def test_string_dictionary_roundtrip():
    values = np.array(["apple", "banana", "apple", "cherry"], dtype=object)
    d = Dictionary(np.array(["apple", "banana", "cherry"], dtype=object))
    codes = np.array([0, 1, 0, 2], dtype=np.int32)
    arr = A.column_to_arrow(codes, np.ones(4, bool), cd.STRING, d)
    assert pa.types.is_dictionary(arr.type)
    assert arr.to_pylist() == list(values)
    back, _, d2 = A.column_from_arrow(arr)
    assert [str(d2.values[c]) for c in back] == list(values)
    # plain utf8 also ingests (dictionary-encode on the way in)
    plain = pa.array(["x", "y", "x"], type=pa.utf8())
    codes3, valid3, d3 = A.column_from_arrow(plain)
    assert [str(d3.values[c]) for c in codes3] == ["x", "y", "x"]


def test_bytes_roundtrip():
    data = np.zeros((3, 4), dtype=np.uint8)
    data[0, :2] = [65, 66]
    data[1] = [1, 2, 3, 4]
    arr = A.column_to_arrow(data, np.array([True, True, False]),
                            cd.BYTES(4))
    assert arr.type == pa.binary(4)
    assert arr[0].as_py() == b"AB\x00\x00" and arr[2].as_py() is None
    back, valid, _ = A.column_from_arrow(arr)
    np.testing.assert_array_equal(back[:2], data[:2])
    assert list(valid) == [True, True, False]


def test_batch_roundtrip_with_ipc():
    """Device batch -> Arrow -> IPC bytes -> Arrow -> device batch: the
    full Outbox/Inbox serialization path."""
    schema = cd.Schema.of(a=cd.INT64, b=cd.DECIMAL(12, 2), s=cd.STRING)
    d = Dictionary(np.array(["p", "q"], dtype=object))
    b = from_host(
        schema,
        {"a": np.arange(6), "b": np.arange(6) * 100,
         "s": np.array([0, 1, 0, 1, 0, 1], np.int32)},
        valids={"a": np.array([1, 1, 1, 0, 1, 1], bool)},
        capacity=8,
    )
    rb = A.batch_to_arrow(b, schema, {2: d})
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    rb2 = pa.ipc.open_stream(sink.getvalue()).read_next_batch()
    b2, schema2, dicts2 = A.batch_from_arrow(rb2)
    got = to_host(b2, schema2, dicts2)
    want = to_host(b, schema, {2: d})
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])


def test_tpch_loads_through_arrow():
    from cockroach_tpu.bench import tpch
    from cockroach_tpu.sql import sql

    cat_a = tpch.gen_tpch(sf=0.002, seed=3, via_arrow=True)
    cat_d = tpch.gen_tpch(sf=0.002, seed=3, via_arrow=False)
    q = "select l_returnflag, sum(l_extendedprice) as s from lineitem " \
        "group by l_returnflag order by l_returnflag"
    ra, rd = sql(cat_a, q).run(), sql(cat_d, q).run()
    np.testing.assert_array_equal(ra["l_returnflag"], rd["l_returnflag"])
    np.testing.assert_allclose(
        np.asarray(ra["s"], np.float64), np.asarray(rd["s"], np.float64),
        rtol=1e-12)
