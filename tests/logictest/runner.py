"""Mini SQL logic-test driver — the pkg/sql/logictest discipline.

Reference: logic.go:4355 RunLogicTest executes datadriven .test files under
multiple cluster configs (local, fakedist, ...); each `query` directive
carries a type signature, expected rows, and optional sort mode. This
runner keeps the same file shape, reduced to the directives the engine
needs today:

    statement ok
    CREATE TABLE t (...)

    query IRT nosort|rowsort|valuesort
    SELECT ...
    ----
    expected cell per line (row-major)

    query error <substring>
    SELECT ...

Type letters: I int, R real (compared at 1e-9), T text, B bool. Every
query runs TWICE — single-device and distributed over the mesh — and both
must match the expectation (the local/fakedist config pairing).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class Case:
    kind: str  # statement | query
    sql: str
    types: str = ""
    sort: str = "nosort"
    expected: list[str] = field(default_factory=list)
    error: str | None = None
    line: int = 0


def parse_file(path: str) -> list[Case]:
    cases: list[Case] = []
    with open(path) as f:
        lines = f.read().splitlines()
    i = 0
    while i < len(lines):
        ln = lines[i].strip()
        if not ln or ln.startswith("#"):
            i += 1
            continue
        head = ln.split()
        if head[0] == "statement":
            ok = head[1] == "ok"
            err = None if ok else " ".join(head[2:]) or head[1]
            i += 1
            sql_lines = []
            while i < len(lines) and lines[i].strip():
                sql_lines.append(lines[i])
                i += 1
            cases.append(Case("statement", "\n".join(sql_lines),
                              error=None if ok else err, line=i))
        elif head[0] == "query":
            if head[1] == "error":
                err = " ".join(head[2:])
                i += 1
                sql_lines = []
                while i < len(lines) and lines[i].strip():
                    sql_lines.append(lines[i])
                    i += 1
                cases.append(Case("query", "\n".join(sql_lines), error=err,
                                  line=i))
                continue
            types = head[1]
            sort = head[2] if len(head) > 2 else "nosort"
            i += 1
            sql_lines = []
            while i < len(lines) and lines[i].strip() != "----":
                sql_lines.append(lines[i])
                i += 1
            i += 1  # skip ----
            expected = []
            while i < len(lines) and lines[i].strip():
                expected.append(lines[i].strip())
                i += 1
            cases.append(Case("query", "\n".join(sql_lines), types=types,
                              sort=sort, expected=expected, line=i))
        else:
            raise ValueError(f"{path}:{i}: unknown directive {ln!r}")
        i += 1
    return cases


def _render(val, t: str) -> str:
    if val is None:
        return "NULL"
    if t == "I":
        return str(int(val))
    if t == "R":
        f = float(val)
        return f"{f:.6g}"
    if t == "B":
        return "true" if bool(val) else "false"
    s = str(val)
    # the reference's logictest renders the empty string as "·"
    # (logic.go) — expected-cell parsing strips lines, so a bare empty
    # cell would otherwise terminate the block
    return s if s else "·"


def _cells(res: dict, types: str, sort: str) -> list[str]:

    names = list(res.keys())
    assert len(names) == len(types), (
        f"query returns {len(names)} columns, signature has {len(types)}"
    )
    ncols = len(names)
    nrows = len(res[names[0]]) if ncols else 0
    rows = []
    for r in range(nrows):
        rows.append(tuple(
            _render(res[names[c]][r], types[c]) for c in range(ncols)
        ))
    if sort == "rowsort":
        rows.sort()
    cells = [c for row in rows for c in row]
    if sort == "valuesort":
        cells.sort()
    return cells


def _compare(got: list[str], want: list[str], types: str, line: int,
             config: str):
    assert len(got) == len(want), (
        f"line {line} [{config}]: {len(got)} cells, expected {len(want)}\n"
        f"got:  {got}\nwant: {want}"
    )
    ncols = max(1, len(types))
    for i, (g, w) in enumerate(zip(got, want)):
        t = types[i % ncols] if types else "T"
        if t == "R" and g != "NULL" and w != "NULL":
            assert abs(float(g) - float(w)) <= 1e-9 * max(
                1.0, abs(float(w))
            ), f"line {line} [{config}] cell {i}: {g} != {w}"
        else:
            assert g == w, f"line {line} [{config}] cell {i}: {g!r} != {w!r}"


def run_logic_file(path: str, session, mesh=None) -> int:
    """Execute one .test file through a Session. Queries over static host
    tables additionally run distributed over `mesh` (fakedist pairing).
    Returns the number of directives executed."""
    from cockroach_tpu.sql import BindError, sql as sql_bind
    from cockroach_tpu.utils.errors import QueryError

    n = 0
    for case in parse_file(path):
        n += 1
        if case.error is not None:
            try:
                session.execute(case.sql)
            except (BindError, QueryError, ValueError, SyntaxError) as e:
                assert case.error.lower() in str(e).lower(), (
                    f"line {case.line}: error {e!r} missing "
                    f"{case.error!r}"
                )
            else:
                raise AssertionError(
                    f"line {case.line}: expected error {case.error!r}"
                )
            continue
        res = session.execute(case.sql)
        if case.kind == "statement":
            continue
        got = _cells(res, case.types, case.sort)
        _compare(got, case.expected, case.types, case.line, "local")
        in_txn = getattr(session, "_txn", None) is not None
        if mesh is not None and not in_txn:
            # the distributed re-run binds fresh (outside any session txn —
            # an in-txn query's snapshot/intents are session state)
            try:
                rel = sql_bind(session.catalog, case.sql)
                dres = rel.run_distributed(mesh)
            except (BindError, TypeError, QueryError):
                continue  # KV-backed scans don't distribute yet
            dgot = _cells(dres, case.types, case.sort)
            _compare(dgot, case.expected, case.types, case.line, "fakedist")
    return n


def logic_files() -> list[str]:
    d = os.path.join(os.path.dirname(__file__), "testdata")
    return sorted(
        os.path.join(d, f) for f in os.listdir(d) if f.endswith(".test")
    )
