"""Logictest corpus generator — sqlite3 as the independent oracle.

The reference's corpus (pkg/sql/logictest/testdata/logic_test, 447 files)
encodes SQL behavior as datadriven files. This generator produces ORIGINAL
files for this engine's dialect subset: each query's expected cells come
from sqlite (stdlib, a fully independent SQL implementation), rendered with
the runner's own formatting rules. Dialect divergences (CAST rounding,
case-insensitive LIKE, int division) are simply not generated here —
they're covered by handwritten files encoding THIS engine's documented
semantics.

Run:  python tests/logictest/gen_corpus.py [--verify]
  --verify also executes every generated file through a Session and reports
  failures (used before checking generated files in).
"""

from __future__ import annotations

import os
import sqlite3
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "testdata")

# shared fixture tables (lowercase strings only: LIKE stays case-exact)
NUMS = """
create table nums (a int primary key, b int, f float, s string)
""", """
insert into nums values
  (1, 10, 1.5, 'apple'), (2, null, -2.25, 'banana'), (3, 30, null, 'cherry'),
  (4, null, null, null), (5, 10, 0.5, 'apple'), (6, -7, 3.25, 'date'),
  (7, 30, -0.5, 'banana'), (8, 0, 7.125, 'elder'), (9, 10, 2.5, null),
  (10, -7, 1.25, 'fig')
"""

PAIR = """
create table pl (id int primary key, k int, v int, m int)
""", """
insert into pl values (1, 1, 100, 10), (2, 1, 200, 11), (3, 2, 300, 12),
                      (4, null, 400, 13), (5, 3, 500, 14), (6, 2, 600, 15)
""", """
create table pr (id int primary key, k int, w int, tag string)
""", """
insert into pr values (10, 1, 7, 'x'), (11, 1, 8, 'y'), (12, 3, 9, 'x'),
                      (13, null, 5, 'z'), (14, 4, 6, 'y')
"""

# (filename, setup statements, [(types, sort, sql), ...])
AREAS: list[tuple[str, tuple[str, ...], list[tuple[str, str, str]]]] = []

AREAS.append(("agg_grouping", NUMS, [
    ("II", "rowsort", "select b, count(*) from nums group by b"),
    ("II", "rowsort", "select b, count(f) from nums group by b"),
    ("IR", "rowsort", "select b, sum(f) from nums group by b"),
    ("IR", "rowsort", "select b, avg(a) from nums group by b"),
    ("II", "rowsort", "select b, min(a) from nums group by b"),
    ("II", "rowsort", "select b, max(a) from nums group by b"),
    ("TI", "rowsort", "select s, count(*) from nums group by s"),
    ("TR", "rowsort", "select s, sum(f) from nums group by s"),
    ("I", "nosort", "select count(*) from nums"),
    ("I", "nosort", "select count(b) from nums"),
    ("I", "nosort", "select count(*) from nums where b is null"),
    ("R", "nosort", "select sum(f) from nums"),
    ("R", "nosort", "select avg(b) from nums"),
    ("I", "nosort", "select min(b) from nums"),
    ("I", "nosort", "select max(b) from nums"),
    ("R", "nosort", "select sum(f) from nums where a > 100"),
    ("I", "nosort", "select count(*) from nums where a > 100"),
    ("II", "rowsort",
     "select b, count(*) from nums group by b having count(*) > 1"),
    ("IR", "rowsort",
     "select b, sum(f) from nums group by b having sum(f) > 1.0"),
    ("II", "rowsort",
     "select b, max(a) from nums where f is not null group by b"),
    ("ITI", "rowsort",
     "select b, s, count(*) from nums group by b, s"),
    ("II", "rowsort",
     "select b * 2, count(*) from nums where a < 9 group by b * 2"),
]))

AREAS.append(("distinct_limit", NUMS, [
    ("I", "rowsort", "select distinct b from nums"),
    ("T", "rowsort", "select distinct s from nums"),
    ("II", "rowsort", "select distinct b, b from nums"),
    ("IT", "rowsort", "select distinct b, s from nums where a <= 5"),
    ("I", "nosort", "select a from nums order by a limit 3"),
    ("I", "nosort", "select a from nums order by a desc limit 4"),
    ("I", "nosort", "select a from nums order by a limit 3 offset 2"),
    ("I", "nosort", "select a from nums order by a limit 20 offset 8"),
    ("I", "nosort", "select a from nums order by a limit 2 offset 20"),
    ("I", "nosort", "select distinct b from nums order by b limit 2"),
    ("II", "nosort",
     "select a, b from nums order by b, a limit 5"),
    ("I", "nosort", "select count(*) from (select distinct b from nums)"),
]))

AREAS.append(("order_nulls", NUMS, [
    ("I", "nosort", "select b from nums order by b"),
    ("I", "nosort", "select b from nums order by b desc"),
    ("R", "nosort", "select f from nums order by f"),
    ("R", "nosort", "select f from nums order by f desc"),
    ("T", "nosort", "select s from nums order by s"),
    ("T", "nosort", "select s from nums order by s desc"),
    ("II", "nosort", "select b, a from nums order by b, a"),
    ("II", "nosort", "select b, a from nums order by b desc, a desc"),
    ("IRT", "nosort",
     "select b, f, s from nums order by b, f desc, s"),
    ("IT", "nosort", "select a, s from nums order by s, a limit 6"),
]))

AREAS.append(("join_edges", PAIR, [
    ("III", "rowsort", "select pl.id, pr.id, w from pl, pr where pl.k = pr.k"),
    ("II", "rowsort", "select pl.id, w from pl left join pr on pl.k = pr.k"),
    ("I", "rowsort",
     "select pl.id from pl, pr where pl.k = pr.k and w > 7"),
    ("IT", "rowsort",
     "select v, tag from pl, pr where pl.k = pr.k and pl.v >= 300"),
    ("II", "rowsort",
     "select a.id, b.id from pl as a, pl as b where a.k = b.k"),
    ("I", "nosort", "select count(*) from pl, pr"),
    ("I", "nosort", "select count(*) from pl, pr where pl.k = pr.k"),
    ("II", "rowsort",
     "select k, n from (select pl.k as k, count(*) as n "
     "from pl, pr where pl.k = pr.k group by pl.k)"),
    ("TI", "rowsort",
     "select tag, sum(v) from pl, pr where pl.k = pr.k group by tag"),
    ("I", "rowsort",
     "select pl.id from pl left join pr on pl.k = pr.k where w is null"),
]))

AREAS.append(("subqueries", PAIR, [
    ("I", "rowsort", "select id from pl where k in (select k from pr)"),
    ("I", "rowsort",
     "select id from pl where k not in (select k from pr where k is not null)"),
    ("I", "rowsort",
     "select id from pl where exists (select * from pr where pr.k = pl.k)"),
    ("I", "rowsort",
     "select id from pl where not exists "
     "(select * from pr where pr.k = pl.k)"),
    ("I", "rowsort",
     "select id from pl where v > (select min(w) from pr) * 40"),
    ("I", "rowsort",
     "select id from pr where w = (select max(w) from pr)"),
    ("I", "rowsort",
     "select id from pl where k in (select k from pr where tag = 'x')"),
    ("I", "nosort",
     "select count(*) from pl where k not in (select k from pr)"),
]))

AREAS.append(("scalar_functions", NUMS, [
    ("I", "rowsort", "select abs(b) from nums where b is not null"),
    ("R", "rowsort", "select abs(f) from nums where f is not null"),
    ("R", "rowsort", "select floor(f) from nums where f is not null"),
    ("R", "rowsort", "select ceil(f) from nums where f is not null"),
    ("R", "rowsort", "select f + 1.5 from nums where f is not null"),
    ("R", "rowsort", "select f * -2.0 from nums where f > 0"),
    ("I", "rowsort", "select length(s) from nums where s is not null"),
    ("T", "rowsort", "select upper(s) from nums where s is not null"),
    ("T", "rowsort",
     "select substring(s, 1, 3) from nums where s is not null"),
    ("I", "rowsort", "select coalesce(b, -1) from nums"),
    ("R", "rowsort", "select coalesce(f, 0.0) from nums"),
    ("I", "rowsort", "select coalesce(b, a) from nums"),
    ("R", "rowsort", "select sqrt(a) from nums where a in (1, 4, 9)"),
    ("I", "rowsort", "select a + b * 2 from nums where b is not null"),
    ("I", "rowsort", "select -(a) from nums where a < 4"),
]))

AREAS.append(("between_like_union", NUMS + PAIR, [
    ("I", "rowsort", "select a from nums where b between 0 and 20"),
    ("I", "rowsort", "select a from nums where a between 3 and 6"),
    ("I", "rowsort", "select a from nums where f between -1.0 and 2.0"),
    ("I", "rowsort", "select a from nums where s like 'a%'"),
    ("I", "rowsort", "select a from nums where s like '%an%'"),
    ("I", "rowsort", "select a from nums where s like '_a%'"),
    ("I", "rowsort", "select a from nums where s not like '%a%'"),
    ("I", "rowsort",
     "select b from nums union select k from pl"),
    ("I", "rowsort",
     "select b from nums union all select k from pl"),
    ("I", "rowsort",
     "select a from nums where b = 10 union select id from pr where w < 7"),
]))

AREAS.append(("where_3vl", NUMS, [
    ("I", "rowsort", "select a from nums where b > 0 or f > 0"),
    ("I", "rowsort", "select a from nums where b > 0 and f > 0"),
    ("I", "rowsort", "select a from nums where not (b > 0)"),
    ("I", "rowsort", "select a from nums where b is null and f is null"),
    ("I", "rowsort", "select a from nums where b is null or f is null"),
    ("I", "rowsort", "select a from nums where b = b"),
    ("I", "rowsort", "select a from nums where b <> 10"),
    ("I", "rowsort", "select a from nums where coalesce(b, 0) >= 0"),
    ("I", "rowsort", "select a from nums where (b > 0) = (f > 0)"),
    ("I", "rowsort", "select a from nums where b in (10, -7)"),
    ("I", "rowsort", "select a from nums where b not in (10, 30)"),
]))


# -- matrix areas: systematic (aggregate x predicate x grouping) sweeps ------
# every generated directive is still independently oracle-checked by sqlite


def _agg_matrix() -> list[tuple[str, str, str]]:
    aggs = [("count(*)", "I"), ("count(b)", "I"), ("sum(b)", "I"),
            ("min(b)", "I"), ("max(b)", "I"), ("avg(b)", "R"),
            ("sum(f)", "R"), ("min(f)", "R"), ("max(f)", "R"),
            ("count(s)", "I")]
    preds = ["", "where a <= 7", "where b is not null", "where f > 0",
             "where s like '%a%'"]
    groups = [("", ""), ("group by b", "I"), ("group by s", "T")]
    out = []
    for agg, at in aggs:
        for pred in preds:
            for grp, gt in groups:
                if grp:
                    gcol = grp.split()[-1]
                    sql = f"select {gcol}, {agg} from nums {pred} {grp}"
                    out.append((gt + at, "rowsort", " ".join(sql.split())))
                else:
                    sql = f"select {agg} from nums {pred}"
                    out.append((at, "nosort", " ".join(sql.split())))
    return out


def _cmp_matrix() -> list[tuple[str, str, str]]:
    out = []
    for col, lit in (("b", "10"), ("b", "0"), ("f", "1.25"), ("a", "5"),
                     ("s", "'banana'"), ("f", "-0.5"), ("b", "-7")):
        for op in ("<", "<=", ">", ">=", "=", "<>"):
            out.append(("I", "rowsort",
                        f"select a from nums where {col} {op} {lit}"))
    for col in ("b", "f", "s"):
        out.append(("I", "rowsort",
                    f"select a from nums where {col} is null"))
        out.append(("I", "rowsort",
                    f"select a from nums where {col} is not null"))
    for lo, hi in (("0", "20"), ("-10", "0"), ("30", "30")):
        out.append(("I", "rowsort",
                    f"select a from nums where b between {lo} and {hi}"))
        out.append(("I", "rowsort",
                    f"select a from nums where b not between {lo} and {hi}"))
    return out


def _order_limit_matrix() -> list[tuple[str, str, str]]:
    out = []
    for col in ("b", "f", "s", "a"):
        for d in ("", " desc"):
            for tail in ("", " limit 4", " limit 3 offset 3"):
                out.append(("I", "nosort",
                            f"select a from nums order by {col}{d}, a{tail}"))
    return out


def _join_matrix() -> list[tuple[str, str, str]]:
    out = []
    for how in ("", " left"):
        for pred in ("", " where v >= 300", " where v + w > 305"):
            joined = (f"select pl.id, v from pl{how} join pr on pl.k = pr.k"
                      f"{pred}") if how else (
                      f"select pl.id, v from pl, pr where pl.k = pr.k"
                      + pred.replace("where", "and"))
            out.append(("II", "rowsort", joined))
    for agg in ("count(*)", "sum(v)", "min(w)"):
        out.append(("I", "nosort",
                    f"select {agg} from pl, pr where pl.k = pr.k"))
    return out


def _expr_matrix() -> list[tuple[str, str, str]]:
    """Arithmetic/function expressions in SELECT and in WHERE."""
    out = []
    exprs_i = ["a + 1", "a - 3", "a * 2", "-(a)", "abs(a - 5)",
               "coalesce(b, 0) + a", "a + coalesce(b, -(a))"]
    for e in exprs_i:
        out.append(("I", "rowsort", f"select {e} from nums"))
        out.append(("I", "rowsort", f"select a from nums where {e} > 4"))
    exprs_r = ["f * 2.0", "f + 0.25", "-(f)", "abs(f)", "floor(f) + 0.5",
               "ceil(f) - 1.0", "coalesce(f, -9.0)"]
    for e in exprs_r:
        out.append(("R", "rowsort",
                    f"select {e} from nums where f is not null"))
        out.append(("I", "rowsort", f"select a from nums where {e} < 2.0"))
    for pred in ("a + coalesce(b, 0) > 12", "abs(coalesce(f, -5.0)) > 2.0",
                 "a * 2 between 4 and 12", "not (a > 5)",
                 "a in (1, 3, 5, 7) and b is not null"):
        out.append(("I", "rowsort", f"select a from nums where {pred}"))
    for sel in ("a > 5", "b is null", "f > 0.0"):
        out.append(("B", "rowsort", f"select {sel} from nums"))
    for func in ("abs", "floor", "ceil", "sqrt"):
        out.append(("R", "rowsort",
                    f"select {func}(f) from nums where f > 0"))
    for func in ("length", "upper", "lower"):
        t = "I" if func == "length" else "T"
        out.append((t, "rowsort",
                    f"select {func}(s) from nums where s is not null"))
    out.append(("I", "rowsort",
                "select a from nums where length(s) = 5"))
    out.append(("T", "rowsort",
                "select substring(s, 2, 2) from nums where s is not null"))
    return out


AREAS.append(("matrix_expr", NUMS, _expr_matrix()))
AREAS.append(("matrix_agg", NUMS, _agg_matrix()))
AREAS.append(("matrix_cmp", NUMS, _cmp_matrix()))
AREAS.append(("matrix_order_limit", NUMS, _order_limit_matrix()))
AREAS.append(("matrix_join", PAIR, _join_matrix()))

def _window_matrix() -> list[tuple[str, str, str]]:
    """OVER-clause matrix: functions x partitions x frames, ordered by
    the unique pk inside OVER so sqlite's RANGE default and this engine's
    ROWS default agree (they differ only on ORDER BY ties)."""
    out: list[tuple[str, str, str]] = []
    for fn, types in [("row_number()", "II"), ("rank()", "II"),
                      ("dense_rank()", "II")]:
        out.append((types, "",
                    f"select a, {fn} over (partition by b order by a) "
                    "from nums order by a"))
        out.append((types, "",
                    f"select a, {fn} over (order by a) from nums "
                    "order by a"))
    for agg, types in [("sum(a)", "II"), ("count(a)", "II"),
                       ("min(a)", "II"), ("max(a)", "II"),
                       ("avg(a)", "IR"), ("sum(f)", "IR"),
                       ("count(f)", "II")]:
        out.append((types, "",
                    f"select a, {agg} over (partition by b order by a) "
                    "from nums order by a"))
        out.append((types, "",
                    f"select a, {agg} over (partition by b) from nums "
                    "order by a"))
        out.append((types, "",
                    f"select a, {agg} over (order by a rows between 2 "
                    "preceding and current row) from nums order by a"))
        out.append((types, "",
                    f"select a, {agg} over (order by a rows between 1 "
                    "preceding and 1 following) from nums order by a"))
    for fn in ["lag(a)", "lead(a)", "lag(a, 2)", "lead(a, 2)"]:
        out.append(("II", "",
                    f"select a, {fn} over (partition by b order by a) "
                    "from nums order by a"))
    out.append(("II", "",
                "select a, first_value(a) over (partition by b order by a)"
                " from nums order by a"))
    out.append(("II", "",
                "select a, last_value(a) over (partition by b order by a "
                "rows between unbounded preceding and unbounded following)"
                " from nums order by a"))
    # RANGE frames: value-offset windows over a TIED order key (b repeats)
    # — exercises peer-inclusive semantics sqlite and this engine share,
    # including NULL order keys framing to their own peer group
    for agg, types in [("sum(a)", "II"), ("count(a)", "II"),
                       ("min(a)", "II"), ("avg(a)", "IR")]:
        out.append((types, "",
                    f"select a, {agg} over (order by b range between 10 "
                    "preceding and 10 following) from nums order by a"))
        out.append((types, "",
                    f"select a, {agg} over (order by b range between 5 "
                    "preceding and current row) from nums order by a"))
        out.append((types, "",
                    f"select a, {agg} over (partition by s order by b "
                    "range between 20 preceding and 0 following) "
                    "from nums order by a"))
        # default frame over a tied key: RANGE peer-inclusive cumulative
        out.append((types, "",
                    f"select a, {agg} over (order by b) from nums "
                    "order by a"))
    out.append(("II", "",
                "select a, sum(a) over (order by b desc range between 10 "
                "preceding and 10 following) from nums order by a"))
    out.append(("II", "",
                "select a, sum(a) over (order by b range between "
                "unbounded preceding and 0 following) from nums "
                "order by a"))
    # GROUPS frames: peer-group offsets (any key shape; NULL group counts)
    for agg, types in [("sum(a)", "II"), ("count(a)", "II"),
                       ("max(a)", "II")]:
        out.append((types, "",
                    f"select a, {agg} over (order by b groups between 1 "
                    "preceding and current row) from nums order by a"))
        out.append((types, "",
                    f"select a, {agg} over (order by b groups between 1 "
                    "preceding and 1 following) from nums order by a"))
        out.append((types, "",
                    f"select a, {agg} over (partition by s order by b "
                    "groups between unbounded preceding and 0 following) "
                    "from nums order by a"))
    out.append(("II", "",
                "select a, sum(a) over (order by s groups between 1 "
                "preceding and 1 following) from nums order by a"))
    # EXCLUDE clause across all three frame modes
    for excl in ("exclude current row", "exclude group", "exclude ties"):
        out.append(("II", "",
                    "select a, sum(a) over (order by b rows between 2 "
                    f"preceding and 2 following {excl}) from nums "
                    "order by a"))
        out.append(("II", "",
                    "select a, count(a) over (order by b range between 10 "
                    f"preceding and 10 following {excl}) from nums "
                    "order by a"))
        out.append(("II", "",
                    "select a, min(a) over (order by b groups between 1 "
                    f"preceding and 1 following {excl}) from nums "
                    "order by a"))
    out.append(("II", "",
                "select a, first_value(a) over (order by b rows between "
                "1 preceding and 1 following exclude current row) "
                "from nums order by a"))
    out.append(("II", "",
                "select a, last_value(a) over (order by b rows between "
                "1 preceding and 1 following exclude group) from nums "
                "order by a"))
    return out


AREAS.append(("matrix_window", NUMS, _window_matrix()))

AREAS.append(("case_cast_cte", NUMS, [
    ("I", "rowsort",
     "select case when b > 9 then 1 when b is null then -1 else 0 end "
     "from nums"),
    ("I", "rowsort",
     "select case when f > 1.0 then a else -(a) end from nums "
     "where f is not null"),
    ("II", "rowsort",
     "select b, case when b = 10 then 100 else b end from nums "
     "where b is not null"),
    ("I", "rowsort",
     "select a from nums where case when b is null then 0 else b end > 5"),
    ("R", "rowsort", "select cast(a as float) from nums where a < 4"),
    ("R", "rowsort", "select cast(b as float) from nums where b > 0"),
    ("I", "nosort",
     "with big as (select a from nums where b > 5) "
     "select count(*) from big"),
    ("II", "rowsort",
     "with m as (select max(b) as mb from nums) "
     "select a, mb from nums, m where b = mb"),
    ("I", "rowsort",
     "with pos as (select a, f from nums where f > 0) "
     "select a from pos where f < 3.0"),
    ("I", "nosort", "select count(distinct b) from nums"),
    ("I", "nosort", "select count(distinct s) from nums"),
    ("I", "nosort",
     "select count(distinct b) from nums where a > 2"),
]))

AREAS.append(("select_list_subqueries", NUMS + PAIR, [
    # bare-column subquery over a UNIQUE correlation key (multi-row
    # inners diverge: this engine takes max(), sqlite the first row,
    # postgres errors — not a generatable directive)
    ("II", "rowsort",
     "select id, (select w from pr where pr.id = pl.m) from pl"),
    ("II", "rowsort",
     "select id, (select sum(w) from pr where pr.k = pl.k) from pl"),
    ("II", "rowsort",
     "select id, coalesce((select max(w) from pr where pr.k = pl.k), -1) "
     "from pl"),
    ("II", "rowsort",
     "select a, (select count(*) from pl where pl.k = nums.a) from nums"),
]))

AREAS.append(("scalar_subqueries", NUMS, [
    ("I", "rowsort", "select a from nums where b = (select max(b) from nums)"),
    ("I", "rowsort",
     "select a from nums where f > (select avg(f) from nums where f > 0)"),
    ("I", "nosort", "select count(*) from nums "
     "where a > (select min(a) from nums)"),
    ("I", "rowsort",
     "select a from nums where b > (select avg(b) from nums)"),
    ("R", "rowsort",
     "select f from nums where f > (select min(f) from nums) + 3.0"),
    ("I", "rowsort",
     "select a from nums where (select count(*) from nums) = 10"),
]))


AREAS.append(("setops_filter_distinctfrom", NUMS + PAIR, [
    ("I", "rowsort",
     "select a from nums intersect select v - 99 from pl"),
    ("I", "rowsort", "select a from nums except select k from pr"),
    ("I", "rowsort",
     "select b from nums intersect select b from nums where a > 5"),
    ("I", "rowsort",
     "select b from nums except select b from nums where b > 5"),
    ("I", "nosort",
     "select count(*) filter (where b > 5) from nums"),
    ("II", "rowsort",
     "select b, count(*) filter (where f > 0) from nums group by b"),
    ("IR", "rowsort",
     "select b, sum(f) filter (where f > 0) from nums group by b"),
    ("II", "rowsort",
     "select b, min(a) filter (where a > 2) from nums group by b"),
    ("I", "rowsort", "select a from nums where b is distinct from 10"),
    ("I", "rowsort",
     "select a from nums where b is not distinct from null"),
    ("I", "rowsort",
     "select a from nums where f is distinct from null"),
]))

# NOTE: mixed-operator chains (union ... intersect ...) are NOT generated
# here: sqlite evaluates all set ops left-to-right at equal precedence,
# while this dialect follows the standard (INTERSECT binds tighter) —
# covered by the handwritten setop_precedence.test instead
AREAS.append(("setop_chains", NUMS + PAIR, [
    ("I", "rowsort",
     "select b from nums intersect select b from nums"),
    ("I", "nosort",
     "select a from nums where a < 4 union select k from pr "
     "where k is not null order by 1 limit 4"),
    ("I", "rowsort",
     "select a from nums where a < 5 union select a from nums where a > 7 "
     "except select a from nums where a = 2"),
    ("I", "rowsort",
     "select a from nums where a <= 3 union all select a from nums "
     "where a <= 2 except select 1 from nums where a = 1"),
]))

AREAS.append(("math_builtins", NUMS, [
    ("II", "rowsort", "select a, mod(b, 3) from nums where b is not null"),
    ("II", "rowsort", "select a, mod(b, -4) from nums where b is not null"),
    ("IR", "rowsort", "select a, pow(f, 2) from nums where f is not null"),
    # round ties excluded: this dialect rounds floats half-to-even
    # (CockroachDB/IEEE), sqlite half-away — a documented divergence
    ("IR", "rowsort",
     "select a, round(f, 1) from nums where f is not null "
     "and a <> 2 and a <> 6 and a <> 10"),
    ("IR", "rowsort", "select a, trunc(f) from nums where f is not null"),
    ("II", "rowsort", "select a, sign(f) from nums where f is not null"),
    ("IR", "rowsort",
     "select a, atan2(f, 2.0) from nums where f is not null"),
    ("IR", "rowsort",
     "select a, log(f) from nums where f > 0"),
    ("IR", "rowsort", "select a, ln(f) from nums where f > 0"),
    ("IR", "rowsort", "select a, sqrt(f) from nums where f > 0"),
    ("IR", "rowsort",
     "select a, degrees(f) from nums where f is not null"),
    ("IR", "rowsort",
     "select a, radians(f) from nums where f is not null"),
    ("IR", "rowsort", "select a, sin(f) + cos(f) from nums "
     "where f is not null"),
    ("IR", "rowsort", "select a, atan(f) from nums where f is not null"),
    ("IR", "rowsort", "select a, exp(b) from nums where b = 0"),
    ("II", "rowsort",
     "select a, greatest(b, 5) from nums where b is not null"),
    ("II", "rowsort",
     "select a, least(b, 5) from nums where b is not null"),
    ("II", "rowsort", "select a, coalesce(nullif(b, 10), -99) from nums "
     "where b is not null"),
]))


def _render(val, t: str) -> str:
    if val is None:
        return "NULL"
    if t == "I":
        return str(int(val))
    if t == "R":
        return f"{float(val):.6g}"
    if t == "B":
        return "true" if val else "false"
    s = str(val)
    return s if s else "·"  # runner's empty-string cell convention


def _sqlite_dialect(sql: str) -> str:
    # sqlite's log() is also base-10, matching this dialect (builtins.go)
    return (sql.replace("substring(", "substr(")
            .replace("strpos(", "instr(")
            .replace("greatest(", "max(")
            .replace("least(", "min("))


def generate() -> list[str]:
    paths = []
    for fname, setup, queries in AREAS:
        conn = sqlite3.connect(":memory:")
        for s in setup:
            conn.execute(_sqlite_dialect(s))
        out = [
            f"# {fname}: generated by gen_corpus.py — expected rows computed",
            "# by sqlite3 (independent oracle); regenerate, don't hand-edit.",
            "",
        ]
        for s in setup:
            out.append("statement ok")
            out.append(s.strip())
            out.append("")
        for types, sort, sql in queries:
            rows = conn.execute(_sqlite_dialect(sql)).fetchall()
            cells = []
            rendered = [
                tuple(_render(v, types[c]) for c, v in enumerate(row))
                for row in rows
            ]
            if sort == "rowsort":
                rendered.sort()
            cells = [c for row in rendered for c in row]
            if sort == "valuesort":
                cells.sort()
            out.append(f"query {types} {sort}")
            out.append(sql)
            out.append("----")
            out.extend(cells)
            out.append("")
        path = os.path.join(OUT, f"{fname}.test")
        with open(path, "w") as f:
            f.write("\n".join(out))
        paths.append(path)
        conn.close()
    return paths


def verify(paths: list[str]) -> int:
    from cockroach_tpu.sql import Session

    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "logictest_runner", os.path.join(HERE, "runner.py"))
    runner = importlib.util.module_from_spec(spec)
    sys.modules["logictest_runner"] = runner  # dataclasses need the module
    spec.loader.exec_module(runner)
    failures = 0
    for p in paths:
        try:
            n = runner.run_logic_file(p, Session())
            print(f"ok {os.path.basename(p)}: {n} directives")
        except Exception as e:
            failures += 1
            print(f"FAIL {os.path.basename(p)}: {e}")
    return failures


if __name__ == "__main__":
    ps = generate()
    total = sum(
        open(p).read().count("query ") + open(p).read().count("statement ")
        for p in ps
    )
    print(f"generated {len(ps)} files, ~{total} directives")
    if "--verify" in sys.argv:
        from cockroach_tpu.utils.backend import force_cpu_backend

        force_cpu_backend()
        sys.exit(1 if verify(ps) else 0)
