"""Block cache + split-block bloom tests (storage/blockcache.py).

The pebble read-stack properties: blooms may lie positive, never
negative; the cache obeys its monitor budget under pressure; the engine
seek path serves repeat windows from cache and compaction invalidates
exactly its input runs' entries.
"""

import numpy as np

from cockroach_tpu.storage import blockcache
from cockroach_tpu.utils import settings


def _void(arr_u8: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(arr_u8).view(
        np.dtype((np.void, arr_u8.shape[1]))).reshape(-1)


def _rand_keys(rng, n: int, tag: int) -> np.ndarray:
    out = np.zeros((n, 16), dtype=np.uint8)
    out[:, 0] = tag  # disjoint keyspaces per tag
    out[:, 1:] = rng.integers(0, 256, size=(n, 15), dtype=np.uint8)
    return out


def test_bloom_fp_bound_and_zero_false_negatives(rng):
    """Membership is exact-negative: every inserted key answers True
    (zero FN — the correctness property) and the false-positive rate over
    disjoint probe keys stays under 3% (10 bits/key theoretical ~1.2%)."""
    members = _void(_rand_keys(rng, 4096, tag=1))
    probes = _void(_rand_keys(rng, 4096, tag=2))
    filt = blockcache.SplitBloom.build(members)

    mh1, mh2 = blockcache.bloom_hashes(members)
    assert all(filt.might_contain(int(mh1[i]), int(mh2[i]))
               for i in range(len(members))), "false negative"

    ph1, ph2 = blockcache.bloom_hashes(probes)
    fp = sum(filt.might_contain(int(ph1[i]), int(ph2[i]))
             for i in range(len(probes)))
    assert fp / len(probes) < 0.03, f"FP rate {fp / len(probes):.3f}"


def test_bloom_empty_and_single_key(rng):
    empty = blockcache.SplitBloom.build(_void(_rand_keys(rng, 1, 1))[:0])
    h1, h2 = blockcache.bloom_hashes(_void(_rand_keys(rng, 8, 3)))
    assert not any(empty.might_contain(int(h1[i]), int(h2[i]))
                   for i in range(8))
    one = _void(_rand_keys(rng, 1, 4))
    filt = blockcache.SplitBloom.build(one)
    oh1, oh2 = blockcache.bloom_hashes(one)
    assert filt.might_contain(int(oh1[0]), int(oh2[0]))


def test_cache_eviction_under_budget_pressure():
    """The clock sweep keeps residency under storage.block_cache.size_bytes
    and releases evicted bytes back to the monitor tree; referenced
    entries survive one sweep (second chance), cold ones go first."""
    settings.set("storage.block_cache.size_bytes", 4096)
    cache = blockcache.BlockCache(name="test/block-cache")
    try:
        blk = lambda: np.zeros(1024, dtype=np.uint8)  # noqa: E731
        for pos in range(4):
            cache.put(1, pos, 8, blk())
        assert cache.stats()["entries"] == 4
        assert cache.used_bytes() == 4096
        # touch (1, 0, 8): its ref bit survives the next sweep
        assert cache.get(1, 0, 8) is not None
        cache.put(2, 0, 8, blk())  # forces one eviction
        s = cache.stats()
        assert s["evictions"] >= 1
        assert cache.used_bytes() <= 4096
        assert cache.get(1, 0, 8) is not None, "referenced entry evicted"
        assert cache.get(1, 1, 8) is None, "cold entry should have gone"
        # oversized windows never cache (would evict the whole world)
        cache.put(3, 0, 99, np.zeros(8192, dtype=np.uint8))
        assert cache.stats()["entries"] <= 4
        # budget 0 disables caching outright
        settings.set("storage.block_cache.size_bytes", 0)
        cache.put(4, 0, 8, blk())
        assert cache.get(4, 0, 8) is None
    finally:
        cache.close()
        settings.reset("storage.block_cache.size_bytes")


def test_cache_invalidate_run_is_surgical():
    cache = blockcache.BlockCache(name="test/block-cache-2")
    try:
        for tok in (7, 8):
            for pos in range(3):
                cache.put(tok, pos, 4, np.zeros(64, dtype=np.uint8))
        cache.invalidate_run(7)
        assert all(cache.get(7, p, 4) is None for p in range(3))
        assert all(cache.get(8, p, 4) is not None for p in range(3))
    finally:
        cache.close()


def test_engine_seek_path_hits_cache_and_compaction_invalidates():
    """Repeat point reads over a flushed run serve their seek windows
    from the node cache; compacting runs away drops exactly their
    entries (fresh tokens, so no aliasing with the merged output)."""
    from cockroach_tpu.storage.lsm import Engine

    eng = Engine(key_width=16, val_width=16, memtable_size=4,
                 l0_trigger=64)
    for i in range(48):
        eng.put(b"c%05d" % i, b"v%05d" % i, ts=i + 1)
    eng.flush()
    assert len(eng.runs) >= 2
    cache = blockcache.node_cache()

    assert eng.get(b"c%05d" % 7, ts=100) == b"v%05d" % 7  # populate
    s0 = cache.stats()
    assert eng.get(b"c%05d" % 7, ts=100) == b"v%05d" % 7  # repeat
    s1 = cache.stats()
    assert s1["hits"] > s0["hits"], "repeat read missed the cache"

    old_tokens = {eng._meta_for(r).token for r in eng.runs}
    eng.compact(bottom=True)
    assert not any(k[0] in old_tokens for k in cache._entries), \
        "compaction left dead runs' windows cached"
    # reads after the invalidation are still correct and re-cacheable
    assert eng.get(b"c%05d" % 7, ts=100) == b"v%05d" % 7
    assert eng.get(b"c%05d" % 7, ts=100) == b"v%05d" % 7
