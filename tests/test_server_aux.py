"""Disk health monitor + ballast + cluster version/upgrades."""

import os

from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.upgrade import (
    Migration,
    active_version,
    is_active,
    run_upgrades,
)
from cockroach_tpu.storage.disk import (
    DiskMonitor,
    create_ballast,
    release_ballast,
)
from cockroach_tpu.storage.lsm import Engine
from cockroach_tpu.utils import settings


def test_disk_monitor_flags_slow_and_recovers(tmp_path):
    mon = DiskMonitor(str(tmp_path), window=16)
    for _ in range(8):
        mon.observe(0.001)  # 1ms: healthy
    assert not mon.is_slow()
    p99_healthy = mon.p99_ms()
    assert 0 < p99_healthy < 5
    # a stall pushes p99 past the threshold
    for _ in range(16):
        mon.observe(1.0)
    assert mon.is_slow()
    # recovery: fresh fast samples displace the stall window
    for _ in range(16):
        mon.observe(0.001)
    assert not mon.is_slow()
    # probe writes a real marker file and records its latency
    ms = mon.probe()
    assert ms >= 0 and os.path.exists(tmp_path / ".disk_probe")


def test_wal_appends_feed_disk_monitor(tmp_path):
    eng = Engine(key_width=16, val_width=16,
                 wal_path=str(tmp_path / "w.wal"))
    mon = DiskMonitor(str(tmp_path))
    eng.disk_monitor = mon
    for i in range(10):
        eng.put(b"k%05d" % i, b"v", ts=i + 1)
    assert len(mon.samples) >= 10  # every WAL append was observed


def test_ballast_reserve_and_release(tmp_path):
    p = create_ballast(str(tmp_path), size_bytes=1 << 20)
    assert os.path.getsize(p) == 1 << 20
    # idempotent
    assert create_ballast(str(tmp_path), size_bytes=1 << 20) == p
    assert release_ballast(str(tmp_path)) is True
    assert not os.path.exists(p)
    assert release_ballast(str(tmp_path)) is False


def test_upgrades_run_in_order_and_persist(tmp_path):
    db = DB(Engine(key_width=16, val_width=64), Clock())
    # fresh store bootstraps at the target with no migrations run
    ran = run_upgrades(db, to_version=(4, 2), migrations=[])
    assert ran == [] and active_version(db) == (4, 2)

    # an OLD store (simulate by rewinding the version key) runs pending
    # migrations in order, bumping the version after each
    import struct

    db.put(b"\x01ver", struct.pack("<ii", 4, 0))
    order = []
    migs = [
        Migration((4, 1), "add-index-x", lambda d: order.append("x")),
        Migration((4, 2), "rewrite-desc", lambda d: order.append("d")),
        Migration((4, 0), "too-old", lambda d: order.append("OLD")),
    ]
    migs.sort(key=lambda m: m.version)
    ran = run_upgrades(db, to_version=(4, 2), migrations=migs)
    assert ran == ["add-index-x", "rewrite-desc"]
    assert order == ["x", "d"]  # (4,0) already active: skipped
    assert active_version(db) == (4, 2)
    assert is_active(db, (4, 1)) and not is_active(db, (4, 3))

    # idempotent: nothing pending on a second pass
    assert run_upgrades(db, to_version=(4, 2), migrations=migs) == []


def test_crash_between_migrations_resumes_at_failure():
    db = DB(Engine(key_width=16, val_width=64), Clock())
    import struct

    db.put(b"\x01ver", struct.pack("<ii", 1, 0))
    order = []

    def boom(d):
        order.append("m2")
        raise RuntimeError("mid-upgrade crash")

    migs = [
        Migration((1, 1), "m1", lambda d: order.append("m1")),
        Migration((1, 2), "m2-crashes", boom),
    ]
    try:
        run_upgrades(db, to_version=(1, 2), migrations=migs)
        raise AssertionError("expected the migration to raise")
    except RuntimeError:
        pass
    # m1's bump persisted; the retry re-runs ONLY m2
    assert active_version(db) == (1, 1)
    migs[1] = Migration((1, 2), "m2-fixed", lambda d: order.append("m2ok"))
    ran = run_upgrades(db, to_version=(1, 2), migrations=migs)
    assert ran == ["m2-fixed"] and order == ["m1", "m2", "m2ok"]
    assert active_version(db) == (1, 2)


def test_health_endpoint_reports_disk(tmp_path):
    import json
    import urllib.request

    from cockroach_tpu.server.node import Node

    eng = Engine(key_width=64, val_width=128,
                 wal_path=str(tmp_path / "n.wal"))
    node = Node(node_id=3, engine=eng, heartbeat_interval_s=0.1,
                ttl_ms=30000)
    node.start(gossip_port=None, http_port=0)
    try:
        assert node.disk is not None
        with urllib.request.urlopen(
            f"http://127.0.0.1:{node.admin.port}/health", timeout=5
        ) as r:
            h = json.loads(r.read())
        assert "diskSlow" in h and h["diskSlow"] is False
        # slow-disk flag surfaces through the endpoint
        thr = settings.get("storage.disk.slow_threshold_ms")
        for _ in range(300):
            node.disk.observe(thr / 1e3 * 5)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{node.admin.port}/health", timeout=5
        ) as r:
            h = json.loads(r.read())
        assert h["diskSlow"] is True
    finally:
        node.stop()


def test_http_handler_carries_request_timeout(tmp_path):
    """Admin handler threads bound their request reads: a client that
    connects and never sends a request line releases its thread at the
    handler timeout instead of parking in recv forever (the untimed-wait
    regression) — and meanwhile real requests keep being served."""
    import json
    import socket
    import urllib.request

    from cockroach_tpu.server.node import Node

    eng = Engine(key_width=64, val_width=128,
                 wal_path=str(tmp_path / "t.wal"))
    node = Node(node_id=4, engine=eng, heartbeat_interval_s=0.1,
                ttl_ms=30000)
    node.start(gossip_port=None, http_port=0)
    try:
        handler_cls = node.admin._httpd.RequestHandlerClass
        assert handler_cls.timeout is not None
        assert 0 < handler_cls.timeout <= 60
        # a silent client holds a connection open while a real request
        # is served — per-connection threads plus the read deadline keep
        # the admin plane responsive
        silent = socket.create_connection(
            ("127.0.0.1", node.admin.port))
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{node.admin.port}/health", timeout=5
            ) as r:
                assert "diskSlow" in json.loads(r.read())
        finally:
            silent.close()
    finally:
        node.stop()
