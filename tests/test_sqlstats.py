"""SQL statement statistics (pkg/sql/sqlstats reduction)."""

from cockroach_tpu.sql import sqlstats
from cockroach_tpu.sql.session import Session
from cockroach_tpu.sql.sqlstats import fingerprint


def test_fingerprint_strips_literals():
    assert fingerprint("SELECT * FROM t WHERE a = 5") == \
        fingerprint("select *  from t where a = 99")
    assert fingerprint("select 'x' || s from t") == \
        fingerprint("select 'other''quoted' || s from t")
    # differing VALUES row counts share one fingerprint
    assert fingerprint("insert into t values (1, 2)") == \
        fingerprint("insert into t values (3, 4), (5, 6)")
    assert fingerprint("select a from t") != fingerprint("select b from t")


def test_session_accumulates_statement_stats():
    sqlstats.DEFAULT.clear()
    try:
        sess = Session()
        sess.execute("create table st (id int primary key, v int)")
        for i in range(5):
            sess.execute(f"insert into st values ({i}, {i * 2})")
        for _ in range(3):
            sess.execute("select v from st where id = 2")
        try:
            sess.execute("select nope from st")
        except Exception:  # noqa: BLE001
            pass
        by_fp = {s.fingerprint: s for s in sqlstats.DEFAULT.all()}
        ins = by_fp[fingerprint("insert into st values (0, 0)")]
        assert ins.count == 5 and ins.errors == 0
        sel = by_fp[fingerprint("select v from st where id = 1")]
        assert sel.count == 3 and sel.rows == 3  # one row x 3 runs
        assert sel.mean_s > 0 and sel.max_s >= sel.min_s
        bad = by_fp[fingerprint("select nope from st")]
        assert bad.errors == 1

        # SHOW STATEMENTS surfaces them through SQL
        res = sess.execute("show statements")
        fps = list(res["fingerprint"])
        assert fingerprint("select v from st where id = 1") in fps
    finally:
        sqlstats.DEFAULT.clear()


def test_statements_served_over_admin_http():
    import json
    import urllib.request

    from cockroach_tpu.server.node import Node

    sqlstats.DEFAULT.clear()
    node = Node(node_id=4, heartbeat_interval_s=0.1, ttl_ms=30000)
    node.start(gossip_port=None, http_port=0, pg_port=0)
    try:
        sess = Session(catalog=node._sql_catalog, db=node.db,
                       bootstrap=False)
        sess.execute("create table ht (id int primary key)")
        sess.execute("insert into ht values (1)")
        sess.execute("select * from ht")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{node.admin.port}/_status/statements",
            timeout=5,
        ) as r:
            sts = json.loads(r.read())["statements"]
        fps = [s["fingerprint"] for s in sts]
        assert "select * from ht" in fps
    finally:
        node.stop()
        sqlstats.DEFAULT.clear()


def test_registry_caps_fingerprints():
    r = sqlstats.StatsRegistry(max_fingerprints=10)
    for i in range(25):
        r.record(f"select col{i} from t", 0.001 * (i + 1), 1)
    assert len(r.all()) <= 10
    assert r.evicted > 0
    # the most expensive fingerprints survived eviction
    fps = [s.fingerprint for s in r.all()]
    assert fingerprint("select col24 from t") in fps


def test_dml_rows_counted():
    sqlstats.DEFAULT.clear()
    try:
        sess = Session()
        sess.execute("create table dr (id int primary key, v int)")
        sess.execute("insert into dr values (1, 1), (2, 2), (3, 3)")
        sess.execute("update dr set v = 9 where id < 3")
        by_fp = {s.fingerprint: s for s in sqlstats.DEFAULT.all()}
        ins = by_fp[fingerprint("insert into dr values (1, 1)")]
        assert ins.rows == 3
        upd = by_fp[fingerprint("update dr set v = 9 where id < 3")]
        assert upd.rows == 2
    finally:
        sqlstats.DEFAULT.clear()


def test_contention_events_recorded_and_surfaced():
    """pkg/sql/contention reduction: intent conflicts land in the
    registry with the real key and holder txn, visible via SHOW
    CONTENTION."""
    from cockroach_tpu.kv import DB, Clock
    from cockroach_tpu.kv.contention import DEFAULT as cont
    from cockroach_tpu.kv.txn import TransactionRetryError
    from cockroach_tpu.storage.lsm import Engine

    cont.clear()
    try:
        db = DB(Engine(key_width=16, val_width=16), Clock())
        holder = db.new_txn()
        holder.put(b"hot", b"x")
        waiter = db.new_txn()
        try:
            waiter.get(b"hot")
            raise AssertionError("expected conflict")
        except TransactionRetryError:
            pass
        waiter2 = db.new_txn()
        try:
            waiter2.put(b"hot", b"y")
            raise AssertionError("expected conflict")
        except TransactionRetryError:
            pass
        rows = cont.rows_payload()
        assert rows and rows[0]["key"] == "hot"
        assert rows[0]["count"] == 2
        assert rows[0]["lastHolderTxn"] == holder.txn_id
        assert rows[0]["numWaiters"] == 2
        holder.rollback()
        waiter.rollback()
        waiter2.rollback()

        sess = Session(db=db)
        res = sess.execute("show contention")
        assert "hot" in list(res["key"])
    finally:
        cont.clear()


def test_session_variables_set_show():
    """sessiondata vars (vars.go role): driver startup SETs succeed,
    SHOW answers defaults and stored values, unknown SHOW errors."""
    sess = Session()
    assert sess.execute("set extra_float_digits = 3") == {
        "set": "extra_float_digits"}
    assert sess.execute("SET application_name TO 'myapp'") == {
        "set": "application_name"}
    assert list(sess.execute("show application_name")[
        "application_name"]) == ["myapp"]
    assert list(sess.execute("show timezone")["timezone"]) == ["UTC"]
    # tolerant SET of an unknown var (drivers send dialect-specific ones)
    sess.execute("set random_driver_knob = 'x'")
    assert list(sess.execute("show random_driver_knob")[
        "random_driver_knob"]) == ["x"]
    try:
        sess.execute("show never_set_unknown")
        raise AssertionError("expected unknown-parameter error")
    except Exception as e:  # noqa: BLE001
        assert "unrecognized" in str(e)
    # cluster settings still route to their own handler
    out = sess.execute("show cluster setting sql.distsql.max_fused_joins")
    assert list(out["variable"]) == ["sql.distsql.max_fused_joins"]
