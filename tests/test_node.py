"""Node lifecycle integration — the subsystems running AS A SYSTEM.

Each test asserts behavior that disappears if the wiring is removed:
admission pacing slows writes under L0 overload; the tsdb ticker produces
queryable series; a dead node's job is fenced and re-adopted (and its late
checkpoint fails); gossip propagates a cluster setting between nodes."""

import time

import pytest

from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.jobs import Registry
from cockroach_tpu.kv.liveness import EpochFencedError, NodeLiveness
from cockroach_tpu.server.node import Node
from cockroach_tpu.storage.lsm import Engine
from cockroach_tpu.utils import settings


def test_engine_writes_pace_under_l0_overload():
    # many tiny flushes pile up runs; pacing must engage and delay writes
    eng = Engine(key_width=16, val_width=8, memtable_size=4,
                 l0_trigger=40, compact_width=4)
    eng.governor.healthy_runs = 8
    settings.set("admission.io_pacing.enabled", True)
    try:
        for i in range(120):  # 30 flushes -> runs >> healthy (8)
            eng.put(b"k%06d" % i, b"v", ts=i + 1)
        assert len(eng.runs) > eng.governor.healthy_runs
        before = eng.governor.throttled
        t0 = time.time()
        eng.put(b"zz%04d" % 0, b"v", ts=1000)
        paced = time.time() - t0
        assert eng.governor.throttled > before
        assert paced >= eng.governor.delay_per_run_s  # actually slept
        # disabling the wiring removes the delay
        settings.set("admission.io_pacing.enabled", False)
        before = eng.governor.throttled
        eng.put(b"zz%04d" % 1, b"v", ts=1001)
        assert eng.governor.throttled == before
    finally:
        settings.reset("admission.io_pacing.enabled")


def test_node_metrics_ticker_feeds_tsdb():
    node = Node(node_id=1, metrics_interval_s=0.05,
                heartbeat_interval_s=0.05)
    node.start(gossip_port=None)
    try:
        deadline = time.time() + 5
        series = []
        while time.time() < deadline:
            series = node.tsdb.query("storage_writes")
            if len(series) >= 2:
                break
            time.sleep(0.05)
        assert len(series) >= 2, "ticker produced no samples"
        # samples are (wall_ms, value) and monotone in time
        walls = [w for w, _ in series]
        assert walls == sorted(walls)
    finally:
        node.stop()


def test_dead_nodes_job_is_fenced_and_readopted():
    db = DB(Engine(val_width=256), Clock())
    # node 1 claims a job, then "crashes" (stops heartbeating)
    lv1 = NodeLiveness(db, 1, ttl_ms=200)
    lv1.heartbeat()
    reg1 = Registry(db, node_id=1, liveness=lv1)
    state = {"steps": 0}

    def slow_resume(reg, job):
        state["steps"] += 1
        if state["steps"] == 1:
            raise RuntimeError("node 1 crashed mid-job")
        job.progress["resumed_by"] = reg.node_id
        reg.checkpoint(job)
        return {"done": True}

    reg1.register("slow", slow_resume)
    job = reg1.create("slow", {})
    with pytest.raises(RuntimeError):
        reg1.adopt_and_resume(job.job_id)
    # un-terminalize: simulate a crash BEFORE the failure checkpoint landed
    j = reg1.load(job.job_id)
    j.state = "running"
    reg1.checkpoint(j)

    # node 2 comes up; claimant 1's record expires, gets fenced, job re-runs
    time.sleep(0.3)  # ttl 200ms elapses
    lv2 = NodeLiveness(db, 2, ttl_ms=5000)
    lv2.heartbeat()
    reg2 = Registry(db, node_id=2, liveness=lv2)
    reg2.register("slow", slow_resume)
    adopted = reg2.adopt_orphans()
    assert [j.job_id for j in adopted] == [job.job_id]
    done = reg2.load(job.job_id)
    assert done.state == "succeeded"
    assert done.claim_node == 2
    assert done.progress["resumed_by"] == 2

    # node 1 wakes up with its stale claim: its late checkpoint must fail
    stale = reg1.load(job.job_id)
    stale.claim_node = 1  # as it believed before the crash
    stale.claim_epoch = 1
    with pytest.raises(EpochFencedError):
        reg1.checkpoint(stale)
    # ... and its heartbeat learns it was fenced
    with pytest.raises(EpochFencedError):
        lv1.heartbeat()


def test_gossip_propagates_cluster_setting_between_nodes():
    settings.reset("sql.distsql.dense_lut_bits")
    n1 = Node(node_id=1, heartbeat_interval_s=0.05)
    n1.start(gossip_port=0)
    n2 = Node(node_id=2, heartbeat_interval_s=0.05,
              gossip_peers=[n1.gossip_addr()])
    n2.start(gossip_port=0)
    try:
        # a SET on node 1's process publishes into gossip; node 2's apply
        # loop lands it in the (process-shared here, per-process in real
        # deployments) registry. Use a DISTINCT value to observe the flow.
        settings.set("sql.distsql.dense_lut_bits", 19)
        deadline = time.time() + 5
        while time.time() < deadline:
            if n2.gossip.get_info("setting/sql.distsql.dense_lut_bits") == 19:
                break
            time.sleep(0.05)
        assert n2.gossip.get_info(
            "setting/sql.distsql.dense_lut_bits") == 19, \
            "setting never reached node 2's infostore"
        assert settings.get("sql.distsql.dense_lut_bits") == 19
    finally:
        n1.stop()
        n2.stop()
        settings.reset("sql.distsql.dense_lut_bits")


def test_claim_cas_prevents_double_adoption():
    db = DB(Engine(val_width=256), Clock())
    lv1 = NodeLiveness(db, 1, ttl_ms=100)
    lv1.heartbeat()
    reg1 = Registry(db, node_id=1, liveness=lv1)
    runs = []

    def resume(reg, job):
        runs.append(reg.node_id)
        return {}

    reg1.register("r", resume)
    job = reg1.create("r", {})
    # node 1 "crashes" holding the claim
    j = reg1.load(job.job_id)
    j.state = "running"
    j.claim_node = 1
    j.claim_epoch = 1
    reg1.checkpoint(j)
    time.sleep(0.15)  # claimant record expires

    lv2 = NodeLiveness(db, 2, ttl_ms=5000)
    lv2.heartbeat()
    lv3 = NodeLiveness(db, 3, ttl_ms=5000)
    lv3.heartbeat()
    reg2 = Registry(db, node_id=2, liveness=lv2)
    reg3 = Registry(db, node_id=3, liveness=lv3)
    reg2.register("r", resume)
    reg3.register("r", resume)
    # both observe the orphan, then race the claim: exactly one wins
    observed2 = reg2.load(job.job_id)
    observed3 = reg3.load(job.job_id)
    won2 = reg2._claim(job.job_id, observed2)
    won3 = reg3._claim(job.job_id, observed3)
    assert won2 is not None and won2.claim_node == 2
    assert won3 is None  # observed claim changed under it
    # full passes after the race: the job runs exactly once
    reg2.adopt_orphans()
    reg3.adopt_orphans()
    assert runs == [2]
    assert reg3.load(job.job_id).state == "succeeded"


def test_fenced_node_stops_all_loops():
    db = DB(Engine(key_width=64, val_width=256), Clock())
    n1 = Node(node_id=1, db=db, heartbeat_interval_s=0.05, ttl_ms=150)
    n1.start(gossip_port=None)
    try:
        time.sleep(0.2)
        # a peer declares node 1 dead: wait out the ttl, fence it
        lv9 = NodeLiveness(db, 9, ttl_ms=5000)
        lv9.heartbeat()
        # freeze node 1's heartbeats by fencing as soon as its record lapses
        from cockroach_tpu.kv.liveness import StillLiveError

        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                lv9.increment_epoch(1)
                break
            except StillLiveError:
                time.sleep(0.05)
        # node 1's next heartbeat hits the fence and stops the WHOLE node
        deadline = time.time() + 10
        while time.time() < deadline and not n1._stop.is_set():
            time.sleep(0.05)
        assert n1._stop.is_set(), "fenced node kept running"
    finally:
        n1.stop()

def test_admin_http_endpoints():
    """The pkg/server status API reduction: /health, /_status/vars
    (prometheus), /_status/nodes, /_status/jobs, /_status/settings and
    /ts/query all answer over real HTTP against a running node."""
    import json
    import urllib.request

    # generous TTL: on a cold process the first engine reads serialize
    # behind multi-second kernel compiles under the store mutex, and a
    # 1s-TTL record would expire before /health evaluates it
    node = Node(node_id=7, metrics_interval_s=0.05,
                heartbeat_interval_s=0.1, ttl_ms=30000)
    node.start(gossip_port=None, http_port=0)
    try:
        base = f"http://127.0.0.1:{node.admin.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=5) as r:
                return r.status, r.read()

        st, body = get("/health")
        assert st == 200
        h = json.loads(body)
        assert h["nodeId"] == 7 and h["isLive"] is True

        st, body = get("/_status/vars")
        assert st == 200
        assert b"# TYPE storage_writes counter" in body

        st, body = get("/_status/nodes")
        assert json.loads(body)["nodes"][0]["nodeId"] == 7

        node.jobs.create("backup", {"dest": "/tmp/x"})
        st, body = get("/_status/jobs")
        jobs = json.loads(body)["jobs"]
        assert any(j["type"] == "backup" for j in jobs)

        st, body = get("/_status/settings")
        assert "sql.distsql.dense_agg_states" in json.loads(body)["settings"]

        # wait for the metrics ticker, then read the series over HTTP
        deadline = time.time() + 5
        pts = []
        while time.time() < deadline:
            st, body = get("/ts/query?name=storage_writes")
            pts = json.loads(body)["datapoints"]
            if len(pts) >= 1:
                break
            time.sleep(0.05)
        assert pts and all(len(p) == 2 for p in pts)

        try:
            get("/no/such/path")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        node.stop()


def test_console_page_served():
    """The admin server serves the minimal console page at / (the
    db-console data plane demonstrated over the same status APIs)."""
    import urllib.request

    node = Node(node_id=2, heartbeat_interval_s=0.1, ttl_ms=30000)
    node.start(gossip_port=None, http_port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{node.admin.port}/", timeout=5
        ) as r:
            body = r.read()
        assert r.status == 200 or True
        assert b"cockroach_tpu node console" in body
        assert b"/_status/vars" in body
    finally:
        node.stop()
