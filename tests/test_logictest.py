"""Datadriven SQL logic tests (the pkg/sql/logictest reduction): each .test
file in tests/logictest/testdata runs its statements through a Session and
every query under BOTH the local flow engine and (where the plan
distributes) the 8-device mesh — the local/fakedist config pairing of
logictestbase.go."""

import pytest

from cockroach_tpu.parallel import mesh as mesh_mod
from cockroach_tpu.sql import Session

import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "logictest_runner",
    os.path.join(os.path.dirname(__file__), "logictest", "runner.py"),
)
runner = importlib.util.module_from_spec(_spec)
import sys

sys.modules["logictest_runner"] = runner
_spec.loader.exec_module(runner)


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh(8)


@pytest.mark.parametrize(
    "path", runner.logic_files(),
    ids=lambda p: p.rsplit("/", 1)[-1].removesuffix(".test"),
)
def test_logic_file(path, mesh):
    n = runner.run_logic_file(path, Session(), mesh=mesh)
    assert n > 0, "file had no directives"
