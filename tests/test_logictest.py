"""Datadriven SQL logic tests (the pkg/sql/logictest reduction): each .test
file in tests/logictest/testdata runs its statements through a Session and
every query under BOTH the local flow engine and (where the plan
distributes) the 8-device mesh — the local/fakedist config pairing of
logictestbase.go."""

import pytest

from cockroach_tpu.parallel import mesh as mesh_mod
from cockroach_tpu.sql import Session

import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "logictest_runner",
    os.path.join(os.path.dirname(__file__), "logictest", "runner.py"),
)
runner = importlib.util.module_from_spec(_spec)
import sys

sys.modules["logictest_runner"] = runner
_spec.loader.exec_module(runner)


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh(8)


# files whose directive matrices take 20s-4min of XLA compile each on the
# CPU-emulated 8-device mesh; tier-1 skips them, `-m slow` covers them
_COMPILE_HEAVY = {
    "matrix_window", "matrix_agg", "setop_precedence",
    "setops_filter_distinctfrom", "join_edges", "matrix_order_limit",
    "setop_chains", "agg_grouping",
    "matrix_join", "joins_subqueries", "window", "distinct_limit",
    "subqueries", "select_list_subqueries", "case_cast_cte",
}


def _logic_id(p: str) -> str:
    return p.rsplit("/", 1)[-1].removesuffix(".test")


@pytest.mark.parametrize(
    "path", [
        pytest.param(p, marks=pytest.mark.slow)
        if _logic_id(p) in _COMPILE_HEAVY else p
        for p in runner.logic_files()
    ],
    ids=_logic_id,
)
def test_logic_file(path, mesh):
    n = runner.run_logic_file(path, Session(), mesh=mesh)
    assert n > 0, "file had no directives"
