"""Distributed shuffle/groupby/join tests on the virtual 8-device CPU mesh
(fakedist analog: full shuffle + partial/final aggregation machinery in one
process — reference: fake_span_resolver-based logictest configs)."""

import jax
import numpy as np
import pytest

from cockroach_tpu import coldata as cd
from cockroach_tpu.ops import aggregation as agg
from cockroach_tpu.ops import join as jn
from cockroach_tpu.parallel import dist, mesh as mesh_mod, shuffle as shuf


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh(8)


def make_sharded(mesh, schema, arrays, cap_per_device=512, valids=None):
    n = len(next(iter(arrays.values())))
    total = cap_per_device * 8
    assert n <= total
    b = cd.from_host(schema, arrays, valids=valids, capacity=total)
    return dist.shard_batch(b, mesh)


def test_shuffle_coherence(mesh, rng):
    # after shuffling by key, all rows with equal key are on one device
    schema = cd.Schema.of(k=cd.INT64, v=cd.INT64)
    n = 3000
    k = rng.integers(0, 100, n)
    b = make_sharded(mesh, schema, {"k": k, "v": np.arange(n)})
    # rows are front-packed onto 6 of 8 devices, so per-bucket load can
    # exceed 2x fair share; 4x absorbs it (overflow retry tested below)
    fn = shuf.make_shuffle(mesh, schema, (0,), local_capacity=512,
                           send_factor=4.0, out_capacity=1024)
    out, overflow = fn(b)
    assert int(np.asarray(overflow).sum()) == 0
    # inspect per-device shards
    key_to_dev = {}
    rows = 0
    for d in range(8):
        shard = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[d * 1024:(d + 1) * 1024], out)
        m = shard.mask
        ks = shard.cols[0].data[m]
        rows += m.sum()
        for key in np.unique(ks):
            assert key_to_dev.setdefault(key, d) == d, "key split across devices"
    assert rows == n


def test_distributed_groupby_vs_oracle(mesh, rng):
    schema = cd.Schema.of(g=cd.INT64, v=cd.INT64)
    n = 4000
    g = rng.integers(0, 50, n)
    v = rng.integers(-1000, 1000, n)
    b = make_sharded(mesh, schema, {"g": g, "v": v})
    fn, out_schema = dist.make_distributed_groupby(
        mesh, schema, (0,),
        (agg.AggSpec("sum", 1, "s"), agg.AggSpec("avg", 1, "a"),
         agg.AggSpec("count_rows", None, "n")),
        local_capacity=512,
    )
    out, overflow = fn(b)
    assert int(np.asarray(overflow).sum()) == 0
    res = cd.to_host(out, out_schema)
    assert len(res["g"]) == len(np.unique(g))
    bykey = {res["g"][i]: (res["s"][i], res["a"][i], res["n"][i])
             for i in range(len(res["g"]))}
    for key in np.unique(g):
        sel = g == key
        s, a, cnt = bykey[key]
        assert s == v[sel].sum()
        np.testing.assert_allclose(a, v[sel].mean())
        assert cnt == sel.sum()


def test_distributed_join_vs_oracle(mesh, rng):
    pschema = cd.Schema.of(pk=cd.INT64, pv=cd.INT64)
    bschema = cd.Schema.of(bk=cd.INT64, bv=cd.INT64)
    npr, nb = 3000, 800
    pk = rng.integers(0, 1000, npr)
    bk = rng.permutation(1000)[:nb]  # unique build keys
    p = make_sharded(mesh, pschema, {"pk": pk, "pv": np.arange(npr)})
    b = make_sharded(mesh, bschema, {"bk": bk, "bv": bk * 7}, cap_per_device=128)
    fn, out_schema = dist.make_distributed_join(
        mesh, pschema, (0,), bschema, (0,), jn.JoinSpec("inner", True),
        probe_capacity=512, build_capacity=128,
    )
    out, overflow = fn(p, b)
    assert int(np.asarray(overflow).sum()) == 0
    res = cd.to_host(out, out_schema)
    bset = set(bk)
    want = sorted((i, pk[i] * 7) for i in range(npr) if pk[i] in bset)
    got = sorted(zip(res["pv"], res["bv"]))
    assert got == want


def test_shuffle_overflow_reported(mesh):
    # all rows to one key -> one device receives everything -> overflow
    schema = cd.Schema.of(k=cd.INT64)
    n = 4000
    b = make_sharded(mesh, schema, {"k": np.zeros(n, dtype=np.int64)})
    fn = shuf.make_shuffle(mesh, schema, (0,), local_capacity=512,
                           send_factor=1.0)
    out, overflow = fn(b)
    assert int(np.asarray(overflow).sum()) > 0  # host must retry bigger
