"""Aggregation and sort kernel tests, verified against numpy oracles
(the oracle pattern from pkg/sql/distsql/columnar_operators_test.go:
engine result must equal a trusted host computation)."""

import numpy as np
import pytest

from cockroach_tpu import coldata as cd
from cockroach_tpu.ops import aggregation as agg
from cockroach_tpu.ops import sort as srt


def groupby_oracle(keys, vals, valid):
    """dict: key-tuple -> (sum_valid, count_valid, min, max, n_rows)"""
    out = {}
    for i in range(len(vals)):
        k = tuple(keys[j][i] for j in range(len(keys)))
        s = out.setdefault(k, [0, 0, None, None, 0])
        s[4] += 1
        if valid[i]:
            s[0] += vals[i]
            s[1] += 1
            s[2] = vals[i] if s[2] is None else min(s[2], vals[i])
            s[3] = vals[i] if s[3] is None else max(s[3], vals[i])
    return out


@pytest.mark.parametrize("n,cap", [(50, 64), (1000, 1024)])
def test_sort_groupby_vs_oracle(rng, n, cap):
    schema = cd.Schema.of(g=cd.INT64, h=cd.INT32, v=cd.INT64)
    g = rng.integers(0, 7, n)
    h = rng.integers(0, 3, n).astype(np.int32)
    v = rng.integers(-100, 100, n)
    vv = rng.random(n) > 0.2
    b = cd.from_host(
        schema, {"g": g, "h": h, "v": v}, valids={"v": vv}, capacity=cap
    )
    specs = (
        agg.AggSpec("sum", 2, "s"),
        agg.AggSpec("count", 2, "c"),
        agg.AggSpec("min", 2, "mn"),
        agg.AggSpec("max", 2, "mx"),
        agg.AggSpec("count_rows", None, "n"),
    )
    out, ng = agg.sort_groupby(b, schema, (0, 1), specs)
    out_schema = agg.groupby_output_schema(schema, (0, 1), specs)
    res = cd.to_host(out, out_schema)
    oracle = groupby_oracle([g, h], v, vv)
    assert len(res["g"]) == len(oracle)
    got = {}
    for i in range(len(res["g"])):
        got[(res["g"][i], res["h"][i])] = (
            res["s"][i],
            res["c"][i],
            res["mn"][i],
            res["mx"][i],
            res["n"][i],
        )
    for k, (s, c, mn, mx, nr) in oracle.items():
        gs, gc, gmn, gmx, gn = got[k]
        assert gc == c and gn == nr
        if c > 0:
            assert gs == s and gmn == mn and gmx == mx
        else:
            assert gs is None and gmn is None and gmx is None


def test_groupby_null_keys_form_group(rng):
    schema = cd.Schema.of(g=cd.INT64, v=cd.INT64)
    g = np.array([1, 1, 2, 0, 0])
    gv = np.array([True, True, True, False, False])
    v = np.arange(5)
    b = cd.from_host(schema, {"g": g, "v": v}, valids={"g": gv}, capacity=8)
    out, ng = agg.sort_groupby(b, schema, (0,), (agg.AggSpec("sum", 1, "s"),))
    out_schema = agg.groupby_output_schema(schema, (0,), (agg.AggSpec("sum", 1, "s"),))
    res = cd.to_host(out, out_schema)
    assert int(ng) == 3 and len(res["g"]) == 3  # groups: 1, 2, NULL
    bykey = {}
    for i in range(3):
        bykey[res["g"][i]] = res["s"][i]
    assert bykey[1] == 1 and bykey[2] == 2 and bykey[None] == 7


def test_smallgroup_operator_vs_general(rng):
    """Dense-state aggregation must agree with the general sort path,
    including NULL group keys (each NULL combination its own group)."""
    from cockroach_tpu.flow.operators import AggregateOp, SmallGroupAggregateOp
    from cockroach_tpu.flow.operator import SourceOperator

    class OneShot(SourceOperator):
        def __init__(self, batch, schema, dicts=None):
            super().__init__()
            self.output_schema = schema
            self.dictionaries = dicts or {}
            self._batch = batch

        def _next(self):
            b, self._batch = self._batch, None
            return b

    schema = cd.Schema.of(a=cd.STRING, b=cd.STRING, v=cd.INT64)
    n = 300
    a = rng.integers(0, 3, n).astype(np.int32)
    b_ = rng.integers(0, 2, n).astype(np.int32)
    v = rng.integers(-50, 50, n)
    av = rng.random(n) > 0.15  # NULL keys present
    bv = rng.random(n) > 0.15
    vv = rng.random(n) > 0.2
    mk = lambda: cd.from_host(
        schema, {"a": a, "b": b_, "v": v},
        valids={"a": av, "b": bv, "v": vv}, capacity=512,
    )
    specs = (
        agg.AggSpec("sum", 2, "s"),
        agg.AggSpec("avg", 2, "m"),
        agg.AggSpec("count_rows", None, "n"),
    )
    dense = SmallGroupAggregateOp(OneShot(mk(), schema), (0, 1), specs, (3, 2))
    general = AggregateOp(OneShot(mk(), schema), (0, 1), specs)
    out_d = dense.next_batch()
    out_g = general.next_batch()
    rd = cd.to_host(out_d, dense.output_schema)
    rg = cd.to_host(out_g, general.output_schema)
    assert len(rd["s"]) == len(rg["s"])

    def keyed(r):
        return {
            (r["a"][i], r["b"][i]): (r["s"][i], r["m"][i], r["n"][i])
            for i in range(len(r["s"]))
        }

    kd, kg = keyed(rd), keyed(rg)
    assert set(kd) == set(kg)
    for k in kd:
        sd, md, nd = kd[k]
        sg, mg, ng = kg[k]
        assert sd == sg and nd == ng
        if md is None:
            assert mg is None
        else:
            np.testing.assert_allclose(md, mg)


def test_sort_multi_key_desc_nulls(rng):
    schema = cd.Schema.of(a=cd.INT64, b=cd.FLOAT64)
    a = np.array([3, 1, 2, 1, 3, 0])
    av = np.array([True, True, True, True, True, False])
    bv = np.array([0.5, 2.5, -1.5, 1.0, -0.5, 9.9])
    b = cd.from_host(schema, {"a": a, "b": bv}, valids={"a": av}, capacity=8)
    out = srt.sort_batch(
        b, schema, (srt.SortKey(0, desc=False), srt.SortKey(1, desc=True))
    )
    res = cd.to_host(out, schema)
    # NULL first (asc), then 1 (b desc: 2.5 then 1.0), 2, 3 (0.5 then -0.5)
    assert res["a"][0] is None
    np.testing.assert_array_equal(list(res["a"][1:]), [1, 1, 2, 3, 3])
    np.testing.assert_allclose(list(res["b"][1:]), [2.5, 1.0, -1.5, 0.5, -0.5])


def test_sort_string_ranks():
    dic = cd.Dictionary(np.array(["pear", "apple", "mango"], dtype=object))
    schema = cd.Schema.of(s=cd.STRING)
    b = cd.from_host(schema, {"s": np.array([0, 1, 2], dtype=np.int32)}, capacity=4)
    out = srt.sort_batch(
        b, schema, (srt.SortKey(0),), rank_tables={0: dic.ranks}
    )
    res = cd.to_host(out, schema, dictionaries={0: dic})
    np.testing.assert_array_equal(list(res["s"]), ["apple", "mango", "pear"])


def test_limit_offset():
    schema = cd.Schema.of(x=cd.INT64)
    b = cd.from_host(schema, {"x": np.arange(10)}, capacity=16)
    out = srt.limit_mask(b, limit=3, offset=2)
    res = cd.to_host(out, schema)
    np.testing.assert_array_equal(res["x"], [2, 3, 4])


def test_float_sort_total_order(rng):
    schema = cd.Schema.of(f=cd.FLOAT64)
    f = np.array([0.0, -0.0, 1.5, -1.5, np.inf, -np.inf])
    b = cd.from_host(schema, {"f": f}, capacity=8)
    out = srt.sort_batch(b, schema, (srt.SortKey(0),))
    res = cd.to_host(out, schema)
    np.testing.assert_array_equal(
        res["f"], [-np.inf, -1.5, -0.0, 0.0, 1.5, np.inf]
    )


def test_null_group_ignores_underlying_data():
    # NULL keys with differing garbage data beneath must form ONE group
    schema = cd.Schema.of(g=cd.INT64, v=cd.INT64)
    b = cd.from_host(
        schema,
        {"g": np.array([1, 5, 7]), "v": np.array([10, 20, 30])},
        valids={"g": np.array([True, False, False])},
        capacity=8,
    )
    out, ng = agg.sort_groupby(b, schema, (0,), (agg.AggSpec("sum", 1, "s"),))
    assert int(ng) == 2
    res = cd.to_host(out, agg.groupby_output_schema(schema, (0,), (agg.AggSpec("sum", 1, "s"),)))
    bykey = dict(zip(res["g"], res["s"]))
    assert bykey[1] == 10 and bykey[None] == 50


def test_groupby_overflow_reports_count(rng):
    schema = cd.Schema.of(g=cd.INT64, v=cd.INT64)
    b = cd.from_host(schema, {"g": np.arange(5), "v": np.ones(5, dtype=np.int64)}, capacity=8)
    out, ng = agg.sort_groupby(b, schema, (0,), (agg.AggSpec("sum", 1, "s"),), out_capacity=4)
    assert int(ng) == 5  # caller must re-bucket: only 4 groups fit


def test_external_sort_multiword_bytes(rng):
    """External (spilled) sort over a BYTES column wider than 8: range
    partitioning must follow full lexicographic order (regression:
    _primary_u64 treated every non-final sort-key operand as a 1-bit band,
    scrambling multi-word BYTES partitions)."""
    from cockroach_tpu.flow.operator import SourceOperator
    from cockroach_tpu.flow.operators import SortOp
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.ops.sort import SortKey
    from cockroach_tpu.utils import settings

    class Tiles(SourceOperator):
        def __init__(self, batches, schema):
            super().__init__()
            self.output_schema = schema
            self.dictionaries = {}
            self._batches = list(batches)
            self._i = 0

        def init(self):
            super().init()
            self._i = 0

        def _next(self):
            if self._i >= len(self._batches):
                return None
            b = self._batches[self._i]
            self._i += 1
            return b

    width = 12  # two uint64 words
    schema = cd.Schema.of(k=cd.BYTES(width), v=cd.INT64)
    n_tiles, tile = 6, 1024
    tiles, host_keys, host_vals = [], [], []
    base = rng.integers(65, 68, size=(3,))  # few leading bytes -> heavy
    for ti in range(n_tiles):               # word0 ties across partitions
        raw = rng.integers(65, 91, size=(tile, width), dtype=np.uint8)
        raw[:, 0] = base[ti % 3]  # force equal leading bytes across tiles
        raw[:, 1] = 65
        v = rng.integers(0, 1 << 40, tile)
        tiles.append(cd.from_host(schema, {"k": raw, "v": v}, capacity=tile))
        host_keys.append(raw)
        host_vals.append(v)
    keys = np.concatenate(host_keys)
    vals = np.concatenate(host_vals)

    settings.set("sql.distsql.workmem_rows", 2048)  # force the spill
    try:
        root = SortOp(Tiles(tiles, schema), (SortKey(0),))
        res = run_operator(root)
    finally:
        settings.reset("sql.distsql.workmem_rows")

    order = sorted(range(len(vals)), key=lambda i: bytes(keys[i]))
    np.testing.assert_array_equal(
        np.stack([np.frombuffer(bytes(x), dtype=np.uint8)
                  for x in res["k"]]) if res["k"].dtype == object
        else res["k"],
        keys[order],
    )


def test_external_sort_bool_key(rng):
    """Spilled sort with a BOOL primary key: the partition key must keep the
    bool's ordering bit (regression: the band/payload split zeroed it,
    collapsing range partitioning to one bucket — defeating the memory
    bound the spill exists to respect)."""
    from cockroach_tpu.flow.external import _primary_u64
    from cockroach_tpu.flow.operator import SourceOperator
    from cockroach_tpu.flow.operators import SortOp
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.ops.sort import SortKey
    from cockroach_tpu.utils import settings

    schema = cd.Schema.of(b=cd.BOOL, v=cd.INT64)
    n = 1024
    bv = rng.integers(0, 2, n).astype(bool)
    batch = cd.from_host(schema, {"b": bv, "v": np.arange(n)}, capacity=n)
    u = np.asarray(_primary_u64(batch, schema, SortKey(0)))
    assert len(np.unique(u)) == 2, "bool ordering bit must survive packing"
    assert u[bv].min() > u[~bv].max()  # False < True in SQL order


def test_stddev_variance_aggregates():
    """var/stddev (sample + population) via (sum, sum_sq, count) states —
    grouped, scalar, and merged across tiles; oracle numpy."""
    import numpy as np

    from cockroach_tpu.bench import tpch
    from cockroach_tpu.sql import sql

    cat = tpch.gen_tpch(sf=0.005, seed=3)
    li = tpch.to_pandas(cat, "lineitem")

    got = sql(cat, """
        select l_returnflag, variance(l_quantity) as v,
               stddev(l_quantity) as s,
               var_pop(l_quantity) as vp, stddev_pop(l_quantity) as sp
        from lineitem group by l_returnflag order by l_returnflag
    """).run()
    g = li.groupby("l_returnflag").l_quantity
    np.testing.assert_allclose(np.asarray(got["v"], np.float64),
                               g.var(ddof=1).to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(got["s"], np.float64),
                               g.std(ddof=1).to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(got["vp"], np.float64),
                               g.var(ddof=0).to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(got["sp"], np.float64),
                               g.std(ddof=0).to_numpy(), rtol=1e-9)

    got = sql(cat, "select stddev(l_extendedprice) as s from lineitem").run()
    np.testing.assert_allclose(float(got["s"][0]),
                               li.l_extendedprice.std(ddof=1), rtol=1e-9)


def test_external_grace_aggregation_and_distinct():
    """When the group count itself exceeds workmem, aggregation spills to
    group-disjoint Grace partitions and streams per-partition results —
    identical answers to the in-memory path (external_hash_aggregator /
    external distinct roles)."""
    import numpy as np

    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, Schema
    from cockroach_tpu.sql.rel import Rel
    from cockroach_tpu.utils import metric, settings

    rng = np.random.default_rng(9)
    n = 60_000
    cat = catalog_mod.Catalog()
    cat.add(catalog_mod.Table.from_strings(
        "big", Schema.of(g=INT64, x=INT64),
        {"g": rng.integers(0, 40_000, n), "x": rng.integers(0, 100, n)},
    ))
    q = lambda: Rel.scan(cat, "big").groupby(  # noqa: E731
        ["g"], [("n", "count_rows", None), ("sx", "sum", "x")])
    # BOTH baselines compute with the default budget (in-memory path)
    want = q().run()
    d_want = Rel.scan(cat, "big").distinct().run()

    spills0 = metric.EXTERNAL_AGG_SPILLS.value
    settings.set("sql.distsql.workmem_rows", 4096)
    try:
        got = q().run()
        d_got = Rel.scan(cat, "big").distinct().run()
    finally:
        settings.reset("sql.distsql.workmem_rows")
    assert metric.EXTERNAL_AGG_SPILLS.value > spills0  # actually spilled

    def sorted_by_g(res):
        order = np.argsort(np.asarray(res["g"]))
        return {k: np.asarray(v)[order] for k, v in res.items()}

    a, b = sorted_by_g(want), sorted_by_g(got)
    assert len(a["g"]) == len(b["g"])
    np.testing.assert_array_equal(a["g"], b["g"])
    np.testing.assert_array_equal(a["n"], b["n"])
    np.testing.assert_array_equal(a["sx"], b["sx"])

    dw = sorted(zip(d_want["g"], d_want["x"]))
    dg = sorted(zip(d_got["g"], d_got["x"]))
    assert dw == dg
