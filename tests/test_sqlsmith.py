"""sqlsmith (reduced) — random SELECT generation vs the sqlite oracle.

Reference: pkg/internal/sqlsmith generates random SQL and cross-checks
engines; pkg/sql/tests runs TLP mutations. This reduction generates
random single- and two-table SELECTs over seeded fixtures within the
dialect's supported grammar (projections with arithmetic/builtins,
WHERE with 3VL predicates, GROUP BY + HAVING, ORDER BY + LIMIT, inner
joins) and asserts cell-level equality against sqlite3 — an independent
SQL implementation — under the logictest runner's rendering rules.

Every query is deterministic per seed: a failure reproduces by seed."""

import sqlite3

import numpy as np
import pytest

from cockroach_tpu.sql.session import Session

_SETUP = [
    "create table nums (a int primary key, b int, f float, s string)",
    "insert into nums values "
    "(1, 10, 1.5, 'apple'), (2, null, -2.25, 'banana'), (3, 30, null, "
    "'cherry'), (4, null, null, null), (5, 10, 0.5, 'apple'), "
    "(6, -7, 3.25, 'date'), (7, 30, -0.5, 'banana'), (8, 0, 7.125, "
    "'elder'), (9, 10, 2.5, null), (10, -7, 1.25, 'fig')",
    "create table pr (id int primary key, k int, w int)",
    "insert into pr values (10, 1, 7), (11, 1, 8), (12, 3, 9), "
    "(13, null, 5), (14, 4, 6), (15, 10, 2), (16, 30, 3)",
]

# deliberately excluded (documented dialect divergences vs sqlite):
# greatest/least (sqlite's scalar max/min propagate NULL; ours ignore it),
# round ties (half-to-even vs half-away), int division (/ promotes here)
_NUM_EXPRS = [
    "a", "b", "a + b", "a - b", "a * 2", "abs(b)", "b + 1",
    "coalesce(b, 0)", "case when b > 5 then 1 else 0 end", "mod(a, 3)",
]
_PREDS = [
    "b > 5", "b is null", "b is not null", "f > 0", "a between 2 and 8",
    "b in (10, 30)", "b not in (10, 30)", "s = 'apple'", "s is null",
    "b > 5 and f > 0", "b > 5 or f < 0", "not (b > 5)",
]


def _gen_query(rng) -> str:
    kind = rng.integers(0, 4)
    if kind == 0:  # projection + filter + order
        cols = ", ".join(
            f"{e} as c{i}" for i, e in enumerate(
                rng.choice(_NUM_EXPRS, size=rng.integers(1, 4),
                           replace=False))
        )
        q = f"select a, {cols} from nums"
        if rng.random() < 0.7:
            q += f" where {rng.choice(_PREDS)}"
        q += " order by a"
        if rng.random() < 0.4:
            q += f" limit {int(rng.integers(1, 8))}"
        return q
    if kind == 1:  # aggregation
        aggs = ", ".join(
            f"{f}({c}) as g{i}" for i, (f, c) in enumerate(
                [(str(rng.choice(["sum", "count", "min", "max", "avg"])),
                  str(rng.choice(["a", "b", "f"])))
                 for _ in range(int(rng.integers(1, 4)))])
        )
        q = f"select b, {aggs} from nums"
        if rng.random() < 0.5:
            q += f" where {rng.choice(_PREDS)}"
        q += " group by b"
        if rng.random() < 0.4:
            q += " having count(*) > 1"
        q += " order by b"
        return q
    if kind == 2:  # scalar aggregate
        f = str(rng.choice(["sum", "count", "min", "max", "avg"]))
        c = str(rng.choice(["a", "b", "f"]))
        q = f"select {f}({c}) as g from nums"
        if rng.random() < 0.6:
            q += f" where {rng.choice(_PREDS)}"
        return q
    # join
    q = ("select nums.a, pr.id, pr.w from nums "
         "join pr on nums.b = pr.k")
    if rng.random() < 0.5:
        q += f" where {rng.choice(_PREDS)}"
    q += " order by nums.a, pr.id"
    if rng.random() < 0.3:
        q += f" limit {int(rng.integers(1, 10))}"
    return q


def _cell(v):
    if v is None:
        return "NULL"
    if isinstance(v, float):
        if v != v:
            return "NULL"
        return f"{v:.6g}"
    return str(v)


@pytest.fixture(scope="module")
def engines():
    s = Session()
    lite = sqlite3.connect(":memory:")
    try:
        lite.execute("select mod(7, 3)")
    except sqlite3.OperationalError:
        # sqlite < 3.35 (or built without SQLITE_ENABLE_MATH_FUNCTIONS)
        # lacks mod(); supply the same truncate-toward-zero semantics
        import math

        lite.create_function(
            "mod", 2,
            lambda x, y: None if x is None or y is None
            else math.fmod(x, y))
    for stmt in _SETUP:
        s.execute(stmt)
        lite.execute(stmt)
    return s, lite


@pytest.mark.parametrize("seed", range(40))
def test_random_query_matches_sqlite(engines, seed):
    s, lite = engines
    rng = np.random.default_rng(seed)
    q = _gen_query(rng)
    want_rows = lite.execute(q).fetchall()
    got = s.execute(q)
    names = list(got.keys())
    n = len(got[names[0]]) if names else 0
    got_rows = []
    for r in range(n):
        got_rows.append(tuple(_cell(_py(got[c][r])) for c in names))
    want_rendered = [tuple(_cell(v) for v in row) for row in want_rows]
    # ORDER BY keys may admit ties: compare as multisets of rendered rows
    assert sorted(got_rows) == sorted(want_rendered), (
        f"seed {seed}: {q}\ngot:  {got_rows}\nwant: {want_rendered}"
    )


def _py(v):
    """numpy scalar / masked None -> python value."""
    if v is None:
        return None
    if isinstance(v, (np.floating,)):
        f = float(v)
        return None if f != f else f
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    return v