"""Window function + merge join tests vs pandas oracles
(colexecwindow / mergejoiner analogs)."""

import numpy as np
import pandas as pd
import pytest

from cockroach_tpu.bench import tpch
from cockroach_tpu.sql.rel import Rel


@pytest.fixture(scope="module")
def cat():
    return tpch.gen_tpch(sf=0.002, seed=13)


@pytest.fixture(scope="module")
def li(cat):
    return tpch.to_pandas(cat, "lineitem")


def _window_rel(cat, funcs, running=False):
    r = Rel.scan(cat, "lineitem",
                 ("l_orderkey", "l_linenumber", "l_quantity", "l_partkey"))
    return r.window(["l_orderkey"], [("l_linenumber", False)], funcs,
                    running=running).run()


def test_row_number_rank(cat, li):
    res = _window_rel(cat, [("rn", "row_number", None)])
    df = pd.DataFrame({k: res[k] for k in
                       ("l_orderkey", "l_linenumber", "rn")})
    want = (
        li.sort_values(["l_orderkey", "l_linenumber"])
        .groupby("l_orderkey").cumcount() + 1
    )
    got = df.sort_values(["l_orderkey", "l_linenumber"]).rn
    np.testing.assert_array_equal(got.to_numpy(), want.to_numpy())


def test_rank_with_ties(cat):
    # rank over l_quantity (has ties within an order)
    r = Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
    res = r.window(["l_orderkey"], [("l_quantity", False)],
                   [("rk", "rank", None), ("drk", "dense_rank", None)]).run()
    df = pd.DataFrame({k: res[k] for k in ("l_orderkey", "l_quantity",
                                           "rk", "drk")})
    g = df.sort_values(["l_orderkey", "l_quantity"])
    want_rk = (
        g.groupby("l_orderkey").l_quantity.rank(method="min").astype(int)
    )
    want_drk = (
        g.groupby("l_orderkey").l_quantity.rank(method="dense").astype(int)
    )
    np.testing.assert_array_equal(g.rk.to_numpy(), want_rk.to_numpy())
    np.testing.assert_array_equal(g.drk.to_numpy(), want_drk.to_numpy())


def test_lag_lead(cat, li):
    res = _window_rel(cat, [("prev_q", "lag", "l_quantity"),
                            ("next_q", "lead", "l_quantity")])
    df = pd.DataFrame({
        "l_orderkey": res["l_orderkey"],
        "l_linenumber": res["l_linenumber"],
        "prev_q": res["prev_q"], "next_q": res["next_q"],
    }).sort_values(["l_orderkey", "l_linenumber"])
    s = li.sort_values(["l_orderkey", "l_linenumber"])
    want_prev = s.groupby("l_orderkey").l_quantity.shift(1)
    want_next = s.groupby("l_orderkey").l_quantity.shift(-1)
    got_prev = pd.to_numeric(df.prev_q, errors="coerce") / 1  # None -> NaN
    got_next = pd.to_numeric(df.next_q, errors="coerce")
    np.testing.assert_allclose(
        np.where(np.isnan(got_prev), -1, got_prev),
        np.where(want_prev.isna(), -1, want_prev), rtol=1e-12)
    np.testing.assert_allclose(
        np.where(np.isnan(got_next), -1, got_next),
        np.where(want_next.isna(), -1, want_next), rtol=1e-12)


def test_window_partition_sum_and_running(cat, li):
    res = _window_rel(cat, [("tot", "sum", "l_quantity"),
                            ("cnt", "count", "l_quantity")])
    df = pd.DataFrame({
        "l_orderkey": res["l_orderkey"],
        "l_linenumber": res["l_linenumber"],
        "tot": np.asarray(res["tot"], dtype=np.float64),
        "cnt": res["cnt"],
    }).sort_values(["l_orderkey", "l_linenumber"])
    s = li.sort_values(["l_orderkey", "l_linenumber"])
    want_tot = s.groupby("l_orderkey").l_quantity.transform("sum")
    want_cnt = s.groupby("l_orderkey").l_quantity.transform("count")
    np.testing.assert_allclose(df.tot.to_numpy(), want_tot, rtol=1e-12)
    np.testing.assert_array_equal(df.cnt.to_numpy(), want_cnt)

    run = _window_rel(cat, [("rsum", "sum", "l_quantity")], running=True)
    df2 = pd.DataFrame({
        "l_orderkey": run["l_orderkey"],
        "l_linenumber": run["l_linenumber"],
        "rsum": np.asarray(run["rsum"], dtype=np.float64),
    }).sort_values(["l_orderkey", "l_linenumber"])
    want_rsum = s.groupby("l_orderkey").l_quantity.cumsum()
    np.testing.assert_allclose(df2.rsum.to_numpy(), want_rsum, rtol=1e-12)


def test_window_min_max_first_last(cat, li):
    res = _window_rel(cat, [
        ("mn", "min", "l_quantity"), ("mx", "max", "l_quantity"),
        ("fv", "first_value", "l_quantity"),
        ("lv", "last_value", "l_quantity"),
    ])
    df = pd.DataFrame({
        "l_orderkey": res["l_orderkey"],
        "l_linenumber": res["l_linenumber"],
        "mn": np.asarray(res["mn"], np.float64),
        "mx": np.asarray(res["mx"], np.float64),
        "fv": np.asarray(res["fv"], np.float64),
        "lv": np.asarray(res["lv"], np.float64),
    }).sort_values(["l_orderkey", "l_linenumber"])
    s = li.sort_values(["l_orderkey", "l_linenumber"])
    g = s.groupby("l_orderkey").l_quantity
    np.testing.assert_allclose(df.mn.to_numpy(), g.transform("min"), rtol=1e-12)
    np.testing.assert_allclose(df.mx.to_numpy(), g.transform("max"), rtol=1e-12)
    np.testing.assert_allclose(df.fv.to_numpy(), g.transform("first"), rtol=1e-12)
    np.testing.assert_allclose(df.lv.to_numpy(), g.transform("last"), rtol=1e-12)


def test_window_string_partition_and_minmax(cat):
    """PARTITION BY a STRING column; min/max over a STRING column must
    reduce byte order (ranks), not dictionary codes."""
    r = Rel.scan(cat, "lineitem", ("l_returnflag", "l_shipmode",
                                   "l_quantity"))
    res = r.window(["l_returnflag"], [("l_quantity", False)], [
        ("n", "count", None),
        ("min_mode", "min", "l_shipmode"),
        ("first_mode", "first_value", "l_shipmode"),
    ]).run()
    df = pd.DataFrame({k: res[k] for k in ("l_returnflag", "min_mode", "n")})
    li2 = tpch.to_pandas(cat, "lineitem")
    want_min = li2.groupby("l_returnflag").l_shipmode.min()
    for rf, grp in df.groupby("l_returnflag"):
        assert set(grp.min_mode) == {want_min[rf]}, rf
        assert set(grp.n) == {int((li2.l_returnflag == rf).sum())}
    # string outputs decode to strings, not codes
    assert isinstance(res["first_mode"][0], str)


def test_window_running_min(cat):
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, Schema

    c2 = catalog_mod.Catalog()
    c2.add(catalog_mod.Table.from_strings(
        "t", Schema.of(g=INT64, o=INT64, v=INT64),
        {"g": np.array([1, 1, 1, 2, 2]), "o": np.arange(5),
         "v": np.array([3, 1, 2, 5, 4])},
    ))
    res = Rel.scan(c2, "t").window(
        ["g"], [("o", False)], [("rm", "min", "v")], running=True
    ).run()
    df = pd.DataFrame(res).sort_values(["g", "o"])
    np.testing.assert_array_equal(df.rm, [3, 1, 1, 5, 4])


# ---------------------------------------------------------------------------
# merge join


def test_merge_join_matches_hash_join(cat):
    li = Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_totalprice"))
    mj = li.merge_join(orders, ("l_orderkey", "o_orderkey")).run()
    hj = li.join(orders, on=[("l_orderkey", "o_orderkey")],
                 build_unique=False).run()
    for k in mj:
        a = np.sort(np.asarray(mj[k], dtype=np.float64))
        b = np.sort(np.asarray(hj[k], dtype=np.float64))
        np.testing.assert_allclose(a, b, rtol=1e-12, err_msg=k)


def test_merge_join_duplicates_and_types():
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, Schema

    cat = catalog_mod.Catalog()
    cat.add(catalog_mod.Table.from_strings(
        "t1", Schema.of(a=INT64, x=INT64),
        {"a": np.array([1, 2, 2, 3, 9]), "x": np.arange(5)},
    ))
    cat.add(catalog_mod.Table.from_strings(
        "t2", Schema.of(b=INT64, y=INT64),
        {"b": np.array([2, 2, 3, 4]), "y": np.arange(4) * 10},
    ))
    t1 = Rel.scan(cat, "t1")
    t2 = Rel.scan(cat, "t2")
    res = t1.merge_join(t2, ("a", "b")).run()
    df = pd.DataFrame(res).sort_values(["a", "x", "y"]).reset_index(drop=True)
    p1 = pd.DataFrame({"a": [1, 2, 2, 3, 9], "x": np.arange(5)})
    p2 = pd.DataFrame({"b": [2, 2, 3, 4], "y": np.arange(4) * 10})
    want = p1.merge(p2, left_on="a", right_on="b").sort_values(
        ["a", "x", "y"]).reset_index(drop=True)
    assert len(df) == len(want) == 5  # 2x2 dup matches + one single
    np.testing.assert_array_equal(df.a, want.a)
    np.testing.assert_array_equal(df.y, want.y)
    # semi / anti
    semi = t1.merge_join(t2, ("a", "b"), how="semi").run()
    assert sorted(semi["a"]) == [2, 2, 3]
    anti = t1.merge_join(t2, ("a", "b"), how="anti").run()
    assert sorted(anti["a"]) == [1, 9]
    # left join null-extends
    left = t1.merge_join(t2, ("a", "b"), how="left").run()
    assert len(left["a"]) == 7
    assert sum(1 for v in left["y"] if v is None) == 2


def test_merge_join_int64_extremes():
    """Keys at int64 max must not collide with the NULL/dead sentinel."""
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, Schema

    mx = np.iinfo(np.int64).max
    cat = catalog_mod.Catalog()
    cat.add(catalog_mod.Table.from_strings(
        "t1", Schema.of(a=INT64, x=INT64),
        {"a": np.array([mx, 5]), "x": np.array([1, 2])},
    ))
    cat.add(catalog_mod.Table.from_strings(
        "t2", Schema.of(b=INT64, y=INT64),
        {"b": np.array([mx, 7]), "y": np.array([10, 20])},
    ))
    res = Rel.scan(cat, "t1").merge_join(
        Rel.scan(cat, "t2"), ("a", "b")).run()
    assert list(res["a"]) == [mx] and list(res["y"]) == [10]


def test_window_order_by_bytes_column():
    """ORDER BY over a BYTES (2-D) column: peers must compare all lanes
    (regression: _order_peers lacked the 2-D branch and crashed)."""

    from cockroach_tpu.coldata import batch as cb
    from cockroach_tpu.coldata.types import BYTES, INT64, Schema
    from cockroach_tpu.ops import window as W
    from cockroach_tpu.ops.sort import SortKey

    schema = Schema.of(g=INT64, k=BYTES(4), v=INT64)
    keys = np.zeros((6, 4), dtype=np.uint8)
    for i, s in enumerate([b"aa", b"ab", b"ab", b"ba", b"ba", b"bb"]):
        keys[i, : len(s)] = np.frombuffer(s, dtype=np.uint8)
    b = cb.from_host(
        schema,
        {"g": np.array([1, 1, 1, 1, 1, 1]), "k": keys,
         "v": np.arange(6)},
        capacity=8,
    )
    out = W.compute_windows(
        b, schema, (0,), (SortKey(1),),
        (W.WindowSpec("rank", None, "rk"),
         W.WindowSpec("dense_rank", None, "drk")),
    )
    mask = np.asarray(out.mask)
    rk = np.asarray(out.cols[3].data)[mask]
    drk = np.asarray(out.cols[4].data)[mask]
    # ties on "ab" and "ba" share ranks
    np.testing.assert_array_equal(np.sort(rk), [1, 2, 2, 4, 4, 6])
    np.testing.assert_array_equal(np.sort(drk), [1, 2, 2, 3, 3, 4])


def test_ntile_percent_rank_cume_dist(cat):
    """ntile / percent_rank / cume_dist against a pandas oracle (ties
    included: peers share percent_rank and cume_dist)."""
    import pandas as pd

    from cockroach_tpu.sql.rel import Rel

    li = Rel.scan(cat, "orders", ("o_custkey", "o_totalprice", "o_orderkey"))
    w = li.window(
        ["o_custkey"], [("o_totalprice", False), ("o_orderkey", False)],
        [("nt", "ntile", None), ("pr", "percent_rank", None),
         ("cd", "cume_dist", None)],
    )
    # ntile bucket count rides WindowSpec.offset
    node = w.plan
    specs = tuple(
        sp if sp.func != "ntile" else type(sp)(
            sp.func, sp.col, sp.name, offset=4, running=sp.running)
        for sp in node.specs
    )
    import dataclasses

    w = Rel(w.catalog, dataclasses.replace(node, specs=specs), w.schema,
            dict(w.dicts))
    got = w.run()

    df = tpch.to_pandas(cat, "orders")[
        ["o_custkey", "o_totalprice", "o_orderkey"]]
    df = df.sort_values(["o_custkey", "o_totalprice", "o_orderkey"])
    g = df.groupby("o_custkey")
    df["nt"] = g.cumcount()
    nsz = g.o_orderkey.transform("size")
    k = 4
    q, r = nsz // k, nsz % k
    big = r * (q + 1)
    df["nt"] = np.where(
        q == 0, df["nt"] + 1,
        np.where(df["nt"] < big, df["nt"] // np.maximum(q + 1, 1) + 1,
                 r + (df["nt"] - big) // np.maximum(q, 1) + 1),
    )
    # ties: orderkey is unique so rank==cumcount+1 here
    df["pr"] = np.where(nsz > 1, g.cumcount() / np.maximum(nsz - 1, 1), 0.0)
    df["cd"] = (g.cumcount() + 1) / nsz

    order = np.lexsort([np.asarray(got["o_orderkey"]),
                        np.asarray(got["o_custkey"])])
    df = df.sort_values(["o_custkey", "o_orderkey"])
    np.testing.assert_array_equal(
        np.asarray(got["nt"])[order], df["nt"].to_numpy())
    np.testing.assert_allclose(
        np.asarray(got["pr"], np.float64)[order], df["pr"].to_numpy(),
        rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(got["cd"], np.float64)[order], df["cd"].to_numpy(),
        rtol=1e-12)

def test_merge_join_composite_keys():
    """Composite ordered keys compare lexicographically (the generated
    mergejoiner's multi-column cursor, reference mergejoiner.go) —
    including duplicates, NULLs in either key column, and a STRING
    component with different dictionaries on the two sides."""
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, STRING, Schema

    cat = catalog_mod.Catalog()
    cat.add(catalog_mod.Table.from_strings(
        "t1", Schema.of(a=INT64, s=STRING, x=INT64),
        {"a": np.array([1, 1, 2, 2, 3, 0]),
         "s": np.array(["u", "v", "u", "u", "u", "u"], dtype=object),
         "x": np.arange(6)},
        valids={"a": np.array([1, 1, 1, 1, 1, 0], dtype=bool),
                "s": np.array([1, 1, 1, 1, 0, 1], dtype=bool)},
    ))
    cat.add(catalog_mod.Table.from_strings(
        "t2", Schema.of(b=INT64, t=STRING, y=INT64),
        {"b": np.array([1, 2, 2, 3, 0]),
         "t": np.array(["v", "u", "u", "w", "u"], dtype=object),
         "y": np.arange(5) * 10},
        valids={"b": np.array([1, 1, 1, 1, 0], dtype=bool)},
    ))
    t1 = Rel.scan(cat, "t1")
    t2 = Rel.scan(cat, "t2")
    res = t1.merge_join(t2, [("a", "b"), ("s", "t")]).run()
    df = pd.DataFrame(res).sort_values(["a", "x", "y"]).reset_index(drop=True)
    # expected: (1,v)x1 match, (2,u)x2 rows x 2 dups = 4, NULLs never match
    # pandas merge treats NaN keys as equal; SQL does not — drop the NULL
    # rows from the oracle input (they can never match)
    p1 = pd.DataFrame({"a": [1, 1, 2, 2],
                       "s": ["u", "v", "u", "u"],
                       "x": np.arange(4)})
    p2 = pd.DataFrame({"b": [1, 2, 2, 3],
                       "t": ["v", "u", "u", "w"],
                       "y": np.arange(4) * 10})
    want = p1.merge(p2, left_on=["a", "s"], right_on=["b", "t"]).sort_values(
        ["a", "x", "y"]).reset_index(drop=True)
    assert len(df) == len(want) == 5
    np.testing.assert_array_equal(df.a, want.a)
    np.testing.assert_array_equal(df.y, want.y)
    # semi/anti with composite keys
    semi = t1.merge_join(t2, [("a", "b"), ("s", "t")], how="semi").run()
    assert sorted(semi["x"]) == [1, 2, 3]
    anti = t1.merge_join(t2, [("a", "b"), ("s", "t")], how="anti").run()
    assert sorted(anti["x"]) == [0, 4, 5]
    # matches the hash join on the same composite key
    hj = t1.join(t2, on=[("a", "b"), ("s", "t")], build_unique=False).run()
    assert sorted(zip(df.a, df.y)) == sorted(
        zip(hj["a"], hj["y"]))


def test_rows_between_frames_match_pandas_rolling():
    """General ROWS BETWEEN frames (colexecwindow framer role): sliding
    sums/avgs/counts via prefix difference, min/max via the RMQ sparse
    table, first/last at the frame edges — all against pandas rolling."""
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, Schema

    rng = np.random.default_rng(3)
    n = 300
    g = rng.integers(0, 4, n)
    o = np.arange(n)
    x = rng.integers(-50, 50, n)
    cat = catalog_mod.Catalog()
    cat.add(catalog_mod.Table.from_strings(
        "w", Schema.of(g=INT64, o=INT64, x=INT64),
        {"g": g, "o": o, "x": x},
    ))
    rel = Rel.scan(cat, "w")
    out = rel.window(
        ["g"], [("o", False)],
        [("s", "sum", "x"), ("mn", "min", "x"), ("mx", "max", "x"),
         ("c", "count", "x"), ("fv", "first_value", "x"),
         ("lv", "last_value", "x")],
        frame=(2, 1),  # ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING
    ).run()
    df = pd.DataFrame(out).sort_values(["g", "o"]).reset_index(drop=True)
    pdf = pd.DataFrame({"g": g, "o": o, "x": x}).sort_values(
        ["g", "o"]).reset_index(drop=True)
    grp = pdf.groupby("g").x
    # pandas rolling(4) centered at [i-2, i+1]
    roll = grp.rolling(4, min_periods=1)
    want_s = roll.sum().shift(-1).values
    want_mn = roll.min().shift(-1).values
    want_mx = roll.max().shift(-1).values
    # shift(-1) crosses group boundaries; recompute per group honestly
    for name, colname in [("sum", "s"), ("min", "mn"), ("max", "mx"),
                          ("count", "c"), ("first", "fv"), ("last", "lv")]:
        for gi in range(4):
            sub = pdf[pdf.g == gi].reset_index(drop=True)
            got = df[df.g == gi].reset_index(drop=True)
            for i in range(len(sub)):
                lo = max(0, i - 2)
                hi = min(len(sub) - 1, i + 1)
                wnd = sub.x.iloc[lo:hi + 1]
                if name == "sum":
                    assert int(got.s[i]) == int(wnd.sum()), (gi, i)
                elif name == "min":
                    assert int(got.mn[i]) == int(wnd.min()), (gi, i)
                elif name == "max":
                    assert int(got.mx[i]) == int(wnd.max()), (gi, i)
                elif name == "count":
                    assert int(got.c[i]) == len(wnd), (gi, i)
                elif name == "first":
                    assert int(got.fv[i]) == int(wnd.iloc[0]), (gi, i)
                else:
                    assert int(got.lv[i]) == int(wnd.iloc[-1]), (gi, i)


def test_frames_unbounded_and_edge_cases():
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, Schema

    cat = catalog_mod.Catalog()
    cat.add(catalog_mod.Table.from_strings(
        "w2", Schema.of(g=INT64, o=INT64, x=INT64),
        {"g": np.array([1, 1, 1, 2, 2]),
         "o": np.array([1, 2, 3, 1, 2]),
         "x": np.array([10, 20, 30, 5, 7])},
    ))
    rel = Rel.scan(cat, "w2")
    # (None, 0) == running sum
    out = rel.window(["g"], [("o", False)], [("rs", "sum", "x")],
                     frame=(None, 0)).run()
    df = pd.DataFrame(out).sort_values(["g", "o"])
    assert list(df.rs) == [10, 30, 60, 5, 12]
    # unbounded both ways == whole partition
    out = rel.window(["g"], [("o", False)], [("ws", "sum", "x")],
                     frame=(None, None)).run()
    df = pd.DataFrame(out).sort_values(["g", "o"])
    assert list(df.ws) == [60, 60, 60, 12, 12]
