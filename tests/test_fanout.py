"""Changefeed fan-out plane: subscriber tree, backpressure ladder,
reconnect-from-frontier, liveness reaping and introspection surfaces.

The chaos-side counterparts (injected faults at the three
changefeed.* sites, racesan schedule) live in test_chaos.py; this file
covers the deterministic contracts:

- demux: each subscriber sees exactly its span's events, bit-identical
  (after (ts, key) dedup) to a direct changes_between scan;
- reconnect: a client killed mid-stream resumes with since=<last
  checkpoint> and the deduped union equals the full-history oracle;
- the ladder: coalesce → shed → typed SlowConsumerError eviction, with
  the staging monitor draining to zero;
- liveness: a dead socket is reaped within heartbeat + deadline and the
  no-leak census stays clean;
- bounded tree: past max_subscribers the newcomer gets a typed
  subscriber_limit frame, existing registrations keep streaming;
- vtable + admin endpoint snapshots.
"""

import socket
import time

import pytest

from scripts.check_no_leaks import assert_no_leaks, snapshot

from cockroach_tpu.kv import DB
from cockroach_tpu.kv import fanout
from cockroach_tpu.kv.changefeed import (
    RangefeedServer,
    changes_between,
    subscribe_rangefeed,
)
from cockroach_tpu.kv.hlc import ManualClock
from cockroach_tpu.storage.lsm import Engine
from cockroach_tpu.utils import settings
from cockroach_tpu.utils.errors import SlowConsumerError
from cockroach_tpu.flow import memory as flowmem


def _db():
    return DB(Engine(key_width=16, val_width=64, memtable_size=64),
              ManualClock())


def _oracle(db, start=None, end=None):
    """(ts, key) -> value map from a direct catch-up scan — the
    bit-identity reference every stream must dedup to."""
    events, _resolved = changes_between(db, 0, db.clock.now(), start, end)
    return {(e["ts"], e["key"]): e["value"] for e in events}


def _drain(sock, frames, until_resolved, deadline_s=15):
    """Collect event frames (deduped by (ts, key)) until the resolved
    frontier reaches `until_resolved`, an error frame arrives, or the
    stream ends. Returns (events, resolved, error_frame)."""
    sock.settimeout(deadline_s)
    events, resolved = {}, 0
    deadline = time.time() + deadline_s
    for f in frames:
        if "error" in f:
            return events, resolved, f
        if "resolved" in f:
            resolved = max(resolved, f["resolved"])
            if resolved >= until_resolved:
                break
        else:
            events[(f["ts"], f["key"])] = f["value"]
        if time.time() > deadline:
            break
    return events, resolved, None


@pytest.fixture
def _fast_knobs():
    """Tight liveness knobs so reap/eviction paths run in test time."""
    prev = {k: settings.get(k) for k in (
        "changefeed.fanout.heartbeat_s",
        "changefeed.fanout.send_deadline_s")}
    settings.set("changefeed.fanout.heartbeat_s", 0.05)
    settings.set("changefeed.fanout.send_deadline_s", 1.0)
    yield
    for k, v in prev.items():
        settings.set(k, v)


# -- demux ------------------------------------------------------------------


def test_fanout_demux_spans_bit_identity():
    """Two span subscribers + one full subscriber on the same hub: each
    receives exactly its span's committed versions — equal, after
    (ts, key) dedup, to a direct changes_between scan."""
    db = _db()
    db.txn(lambda t: (t.put(b"a1", b"v1"), t.put(b"b1", b"v2")))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        subs = [subscribe_rangefeed(srv.addr, start=b"a", end=b"b"),
                subscribe_rangefeed(srv.addr, start=b"b", end=b"c"),
                subscribe_rangefeed(srv.addr)]
        db.txn(lambda t: (t.put(b"a2", b"v3"), t.delete(b"b1")))
        hi = db.clock.now()
        got = [_drain(s, fr, hi) for s, fr in subs]
        for s, _fr in subs:
            s.close()
        spans = [(b"a", b"b"), (b"b", b"c"), (None, None)]
        for (events, resolved, err), (lo, hi_k) in zip(got, spans):
            assert err is None
            assert resolved >= hi
            assert events == _oracle(db, lo, hi_k)
    finally:
        srv.close()


# -- reconnect-from-frontier ------------------------------------------------


def test_reconnect_from_frontier_bit_identity():
    """Kill the client mid-stream, reconnect with since=<last observed
    checkpoint>: the deduped union of both connections equals the full
    changes_between history — no loss, duplicates collapse."""
    db = _db()
    for i in range(5):
        db.txn(lambda t, i=i: t.put(b"k%d" % i, b"v%d" % i))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        mid = db.clock.now()
        sock, frames = subscribe_rangefeed(srv.addr)
        first, ckpt, err = _drain(sock, frames, mid)
        assert err is None and ckpt >= mid
        # torn disconnect: no goodbye, no unsubscribe
        sock.close()
        for i in range(5, 10):
            db.txn(lambda t, i=i: t.put(b"k%d" % i, b"v%d" % i))
        hi = db.clock.now()
        sock2, frames2 = subscribe_rangefeed(srv.addr, since=ckpt)
        second, ckpt2, err2 = _drain(sock2, frames2, hi)
        sock2.close()
        assert err2 is None and ckpt2 >= hi
        merged = dict(first)
        merged.update(second)
        assert merged == _oracle(db), \
            "reconnect-from-frontier lost or duplicated a version"
        # the frontier contract: nothing below the checkpoint re-streams
        assert all(ts > ckpt for ts, _k in second), \
            "second connection re-sent versions below its since frontier"
    finally:
        srv.close()


# -- the backpressure ladder ------------------------------------------------


def _ladder_hub(db):
    """Hub with an undrained registration (test seam: no sender thread)
    forced LIVE so _enqueue_locked exercises the ladder deterministically.
    The poller is parked (huge interval) — the test drives every rung."""
    hub = fanout.FanoutHub(db, poll_interval_s=3600)
    a, b = socket.socketpair()
    sub = hub.add_subscriber(a, start_sender=False)
    with hub._mu:
        sub.state = fanout.LIVE
    return hub, sub, a, b


def _batch(n_keys, nbytes, versions=1, key_prefix=b"lad"):
    out = []
    ts = 1
    for v in range(versions):
        for i in range(n_keys):
            out.append((ts, b"%s%04d" % (key_prefix, i), b"x" * nbytes,
                        nbytes, time.monotonic()))
            ts += 1
    return out


def test_ladder_rung_one_coalesces_duplicate_keys():
    db = _db()
    prev = {k: settings.get(k) for k in (
        "changefeed.fanout.buffer_bytes",
        "changefeed.fanout.highwater_frac")}
    settings.set("changefeed.fanout.buffer_bytes", 4096)
    settings.set("changefeed.fanout.highwater_frac", 0.1)
    hub, sub, a, b = _ladder_hub(db)
    try:
        # 3 versions of 2 keys, 100 B each = 600 B > high water (409 B):
        # the queue coalesces to newest-version-per-key
        with hub._mu:
            hub._enqueue_locked(sub, _batch(2, 100, versions=3))
        assert sub.state == fanout.LIVE
        assert sub.coalesced == 4 and len(sub.buf) == 2
        assert sub.queued_bytes == 200
        # the survivors are the NEWEST version of each key
        assert sorted(e[0] for e in sub.buf) == [5, 6]
        assert sub.mon.used == 200, "coalesce must rebase the reservation"
    finally:
        hub.close()
        a.close()
        b.close()
        for k, v in prev.items():
            settings.set(k, v)


def test_ladder_rung_two_sheds_to_catchup():
    db = _db()
    prev = {k: settings.get(k) for k in (
        "changefeed.fanout.buffer_bytes",
        "changefeed.fanout.highwater_frac")}
    settings.set("changefeed.fanout.buffer_bytes", 4096)
    settings.set("changefeed.fanout.highwater_frac", 0.1)
    hub, sub, a, b = _ladder_hub(db)
    try:
        # 60 DISTINCT keys x 100 B: coalescing drops nothing, the queue
        # blows the 4096 B budget, the ladder sheds to catch-up
        with hub._mu:
            hub._enqueue_locked(sub, _batch(60, 100))
        assert sub.state == fanout.CATCHUP
        assert sub.sheds == 1 and sub.sheds_run == 1
        assert sub.buf == [] and sub.queued_bytes == 0
        assert sub.mon.used == 0, "shed must release every buffered byte"
    finally:
        hub.close()
        a.close()
        b.close()
        for k, v in prev.items():
            settings.set(k, v)


def test_ladder_terminal_rung_typed_eviction():
    db = _db()
    prev = {k: settings.get(k) for k in (
        "changefeed.fanout.buffer_bytes",
        "changefeed.fanout.highwater_frac",
        "changefeed.fanout.max_consecutive_sheds")}
    settings.set("changefeed.fanout.buffer_bytes", 4096)
    settings.set("changefeed.fanout.highwater_frac", 0.1)
    settings.set("changefeed.fanout.max_consecutive_sheds", 2)
    hub, sub, a, b = _ladder_hub(db)
    try:
        for _round in range(2):  # two sheds without ever draining
            with hub._mu:
                hub._enqueue_locked(sub, _batch(60, 100))
                sub.state = fanout.LIVE  # simulate the rescan completing
        assert sub.sheds_run == 2
        with hub._mu:
            hub._enqueue_locked(sub, _batch(60, 100))
        assert sub.state == fanout.EVICTED
        err = sub.evict_error
        assert isinstance(err, SlowConsumerError)
        assert err.subscriber_id == sub.id
        assert err.frontier == sub.frontier, \
            "the typed error must carry the exact reconnect point"
        assert "shed" in err.reason
        assert sub.mon.used == 0
    finally:
        hub.close()
        a.close()
        b.close()
        for k, v in prev.items():
            settings.set(k, v)
    assert flowmem.staging_monitor("changefeed").used == 0, \
        "fan-out staging account retained bytes after hub close"


def test_eviction_never_blocks_peers():
    """The ladder runs entirely under the hub lock without touching the
    evicted subscriber's socket: a sibling registration keeps streaming
    while one member of the tree is being evicted."""
    db = _db()
    db.txn(lambda t: t.put(b"p1", b"v1"))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        hub = srv.hub
        # healthy real client alongside a doomed seam registration
        sock, frames = subscribe_rangefeed(srv.addr)
        x, y = socket.socketpair()
        doomed = hub.add_subscriber(x, start_sender=False)
        with hub._mu:
            hub._evict_locked(doomed, "test: forced eviction")
        assert doomed.state == fanout.EVICTED
        db.txn(lambda t: t.put(b"p2", b"v2"))
        hi = db.clock.now()
        events, resolved, err = _drain(sock, frames, hi)
        sock.close()
        assert err is None and resolved >= hi
        assert events == _oracle(db), "peer stream degraded by eviction"
        x.close()
        y.close()
    finally:
        srv.close()


# -- liveness (the old per-connection _tail had no send bound) --------------


def test_dead_socket_reaped_and_census_clean(_fast_knobs):
    """A client that vanishes without a goodbye: the heartbeat checkpoint
    hits the dead socket (or the reaper's send deadline trips) and the
    registration + its sender thread are reaped while the server keeps
    running — then the full census (threads, socket fds, monitor drains)
    returns to the pre-server baseline."""
    before = snapshot()
    db = _db()
    db.txn(lambda t: t.put(b"d1", b"v1"))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        sock, frames = subscribe_rangefeed(srv.addr)
        sock.settimeout(10)
        assert next(frames) is not None  # established and streaming
        sock.close()  # torn: no unsubscribe, no FIN handshake with server
        deadline = time.time() + 10
        while time.time() < deadline:
            with srv.hub._mu:
                if not srv.hub._subs:
                    break
            time.sleep(0.02)
        with srv.hub._mu:
            assert not srv.hub._subs, \
                "dead subscriber not reaped within heartbeat + deadline"
    finally:
        srv.close()
    assert flowmem.staging_monitor("changefeed").used == 0
    assert_no_leaks(before)


# -- bounded subscriber tree ------------------------------------------------


def test_subscriber_limit_typed_refusal():
    db = _db()
    db.txn(lambda t: t.put(b"l1", b"v1"))
    prev = settings.get("changefeed.fanout.max_subscribers")
    settings.set("changefeed.fanout.max_subscribers", 1)
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        sock1, frames1 = subscribe_rangefeed(srv.addr)
        sock1.settimeout(10)
        assert next(frames1) is not None  # first registration streams
        sock2, frames2 = subscribe_rangefeed(srv.addr)
        sock2.settimeout(10)
        refusal = next(frames2)
        assert refusal == {"error": "subscriber_limit"}
        assert next(frames2, None) is None, "refused conn must close"
        sock2.close()
        # the tree itself is unaffected: the first stream still resolves
        hi = db.clock.now()
        events, resolved, err = _drain(sock1, frames1, hi)
        assert err is None and resolved >= hi
        sock1.close()
    finally:
        srv.close()
        settings.set("changefeed.fanout.max_subscribers", prev)


# -- introspection ----------------------------------------------------------


def test_vtable_and_status_endpoint_snapshot():
    from cockroach_tpu.server.http import AdminServer
    from cockroach_tpu.sql import crdb_internal

    db = _db()
    db.txn(lambda t: t.put(b"s1", b"v1"))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        sock, frames = subscribe_rangefeed(srv.addr, start=b"s", end=b"t")
        hi = db.clock.now()
        _events, resolved, err = _drain(sock, frames, hi)
        assert err is None and resolved >= hi
        tab = crdb_internal.build(
            object(), "crdb_internal.node_changefeed_subscribers")
        rows = {name: tab.columns[name] for name in tab.schema.names}

        def col_str(name):  # STRING columns are dictionary-encoded
            return str(tab.dictionaries[name].values[int(rows[name][0])])

        assert len(rows["subscriber_id"]) == 1
        assert col_str("state") == fanout.LIVE
        assert col_str("span_start") == "s"
        assert col_str("span_end") == "t"
        assert int(rows["frontier"][0]) >= hi
        assert int(rows["sent_events"][0]) >= 1
        # the admin payload method wraps the same snapshot (self unused:
        # payload methods need no listener)
        payload = AdminServer.changefeeds(None)
        assert len(payload["subscribers"]) == 1
        assert payload["subscribers"][0]["state"] == fanout.LIVE
        assert payload["buffer_bytes"] >= 0
        sock.close()
    finally:
        srv.close()


def test_hub_close_idempotent_and_registry_drops():
    db = _db()
    hub = fanout.FanoutHub(db, poll_interval_s=3600)
    assert hub in fanout.hubs()
    hub.close()
    hub.close()  # second close is a no-op, not a crash
    assert hub not in fanout.hubs()
    assert fanout.subscriber_rows() == [] or all(
        r["hub"] != hub.name for r in fanout.subscriber_rows())


# -- deadline discipline ------------------------------------------------------


def test_subscribe_dial_arms_read_deadline():
    """subscribe_rangefeed's connect timeout persists as the per-frame
    read deadline (the untimed-wait regression: a silent server used to
    park the consumer in recv forever — now it reads as end-of-feed and
    the consumer re-subscribes from its last checkpoint)."""
    db = _db()
    srv = RangefeedServer(db, poll_interval_s=0.02)
    try:
        sock, frames = subscribe_rangefeed(srv.addr)
        assert sock.gettimeout() == settings.get("flow.dcn.io_timeout_s")
        sock.close()
    finally:
        srv.close()


def test_silent_subscription_ends_feed_not_hangs():
    """Against a peer that accepts and never answers, the frame iterator
    terminates within the io deadline instead of blocking forever."""
    import socket

    prev = settings.get("flow.dcn.io_timeout_s")
    settings.set("flow.dcn.io_timeout_s", 0.3)
    lsn = socket.create_server(("127.0.0.1", 0))  # accepts, never serves
    try:
        sock, frames = subscribe_rangefeed(lsn.getsockname())
        t0 = time.time()
        assert list(frames) == []  # timeout reads as end-of-feed
        assert time.time() - t0 < 5.0
        sock.close()
    finally:
        settings.set("flow.dcn.io_timeout_s", prev)
        lsn.close()
