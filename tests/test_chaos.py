"""Chaos harness: distributed join + KV RPC suites under seeded injected
faults (utils/faults.py). Every test asserts results equal the no-fault
oracle AND that no threads/sockets/flow-registry entries leak — the
leaktest.AfterTest + TestingKnobs discipline combined.

Fast seeds only: everything here is deterministic (one seeded RNG drives
all firing decisions) and finishes in seconds, so the suite runs inside
tier-1. Exclude with -m 'not chaos'."""

import threading
import time

import numpy as np
import pytest

from scripts.check_no_leaks import assert_no_leaks, snapshot

from cockroach_tpu.catalog import Catalog, Table
from cockroach_tpu.coldata.types import FLOAT64, INT64, Schema
from cockroach_tpu.flow.disthost import (
    HostFlowServer,
    cancel_flow,
    run_distributed_hosts,
    run_distributed_join,
    setup_flow,
)
from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.rpc import BatchClient, BatchServer
from cockroach_tpu.ops.aggregation import AggSpec
from cockroach_tpu.plan import builder as plan_builder
from cockroach_tpu.plan import spec as S
from cockroach_tpu.flow.runtime import run_operator
from cockroach_tpu.storage.lsm import Engine
from cockroach_tpu.utils import faults, locks, metric, racesan, settings
from cockroach_tpu.utils.faults import FaultSpec, InjectedFault

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


@pytest.fixture(autouse=True)
def _lock_order_detector():
    """Run every chaos scenario with the runtime deadlock detector armed:
    an inverted acquisition anywhere under fault injection raises
    LockOrderError instead of hanging the suite (the deadlock-build-tag
    discipline; see utils/locks.py)."""
    locks.reset()
    prev = settings.get("debug.lock_order.enabled")
    settings.set("debug.lock_order.enabled", True)
    yield
    settings.set("debug.lock_order.enabled", prev)
    locks.reset()


@pytest.fixture(autouse=True)
def _race_sanitizer():
    """...and with the runtime data-race sanitizer armed: every tracked
    control-plane field (utils/racesan.py note_read/note_write sites) runs
    the Eraser lockset algorithm while faults push threads down rarely
    taken paths — a lockset-disjoint access raises DataRaceError at the
    access instead of corrupting state (the make-testrace discipline)."""
    racesan.reset()
    prev = settings.get("debug.race_detector.enabled")
    settings.set("debug.race_detector.enabled", True)
    yield
    settings.set("debug.race_detector.enabled", prev)
    racesan.reset()


def _mini_catalog(n=600, c=16, seed=7) -> Catalog:
    """Small deterministic two-table catalog (fast chaos iterations; the
    tpch generator would dominate runtime)."""
    rng = np.random.default_rng(seed)
    cat = Catalog()
    cat.add(Table(
        name="orders",
        schema=Schema(("o_key", "o_cust", "o_val"),
                      (INT64, INT64, FLOAT64)),
        columns={
            "o_key": np.arange(n, dtype=np.int64),
            "o_cust": rng.integers(0, c, n, dtype=np.int64),
            "o_val": rng.uniform(1.0, 100.0, n),
        },
    ))
    cat.add(Table(
        name="cust",
        schema=Schema(("c_key", "c_grp"), (INT64, INT64)),
        columns={
            "c_key": np.arange(c, dtype=np.int64),
            "c_grp": np.arange(c, dtype=np.int64) % 4,
        },
    ))
    return cat


def _agg_plan(cat: Catalog) -> S.PlanNode:
    sch = cat.get("orders").schema
    return S.Aggregate(
        S.TableScan("orders"),
        group_cols=(sch.index("o_cust"),),
        aggs=(AggSpec("count_rows", None, "n"),
              AggSpec("sum", sch.index("o_val"), "total")),
        mode="complete",
    )


def _join_plan() -> S.HashJoin:
    return S.HashJoin(
        probe=S.TableScan("orders", ("o_key", "o_cust")),
        build=S.TableScan("cust", ("c_key", "c_grp")),
        probe_keys=(1,),
        build_keys=(0,),
    )


def _canon(res: dict) -> np.ndarray:
    rows = np.stack([np.asarray(res[k], dtype=np.float64)
                     for k in sorted(res.keys())], axis=1)
    return rows[np.lexsort(rows.T[::-1])]


def _assert_equal(got: dict, want: dict) -> None:
    assert sorted(got.keys()) == sorted(want.keys())
    np.testing.assert_allclose(_canon(got), _canon(want), rtol=1e-9)


# -- determinism ------------------------------------------------------------


def test_fault_registry_deterministic_replay():
    """Same seed, same specs => the exact same fault sequence (the whole
    point of seeding: a chaos failure replays)."""
    spec = {"site.a": FaultSpec(kind="error", p=0.5, max_fires=3),
            "site.b": FaultSpec(kind="delay", p=0.5, delay_s=0.0)}
    runs = []
    for _ in range(2):
        faults.arm(1234, {k: FaultSpec(**{
            "kind": v.kind, "p": v.p, "delay_s": v.delay_s,
            "max_fires": v.max_fires}) for k, v in spec.items()})
        for _ in range(30):
            for site in ("site.a", "site.b"):
                try:
                    faults.fire(site)
                except InjectedFault:
                    pass
        runs.append(faults.fired())
        faults.disarm()
    assert runs[0] == runs[1]
    assert any(s == "site.a" for s, _ in runs[0])  # it actually fired


def test_disarmed_sites_are_free():
    faults.disarm()
    faults.fire("kv.rpc.client.batch")  # no-op, no exception
    assert faults.partial_fraction("storage.wal.append") is None


# -- KV RPC under drops -----------------------------------------------------


def test_kv_rpc_drops_retry_to_oracle():
    """Client-wire drops AND server-eval drops: the retry layer re-dials
    and re-sends until the (max_fires-bounded) faults exhaust; every
    read then equals the no-fault oracle."""
    before = snapshot()
    db = DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())
    srv = BatchServer(db)
    client = BatchClient(srv.addr, deadline_s=2.0, max_retries=8)
    retries_before = metric.RPC_RETRIES.value
    faults.arm(11, {
        "kv.rpc.client.batch": FaultSpec(kind="drop", p=0.25, max_fires=4),
        "kv.rpc.server.eval": FaultSpec(kind="drop", p=0.25, max_fires=4),
    })
    try:
        oracle = {}
        for i in range(30):
            k = b"k%03d" % i
            v = b"v%03d" % (i * 7)
            client.put(k, v)
            oracle[k] = v
        for k, v in oracle.items():
            assert client.get(k) == v
        assert faults.fired(), "chaos run injected nothing"
        assert metric.RPC_RETRIES.value > retries_before
    finally:
        faults.disarm()
        client.close()
        srv.close()
    assert_no_leaks(before)


def test_batch_server_restart_same_port_and_idempotent_close():
    """Back-to-back start/stop on the SAME port never raises; close() is
    idempotent and leaves no thread or socket behind."""
    before = snapshot()
    db = DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())
    port = None
    for round_no in range(3):
        srv = BatchServer(db, port=port or 0)
        port = srv.addr[1]
        c = BatchClient(srv.addr)
        c.put(b"r%d" % round_no, b"x")
        c.close()
        srv.close()
        srv.close()  # idempotent
    assert_no_leaks(before)


def test_host_flow_server_restart_same_port_and_idempotent_close():
    before = snapshot()
    cat = _mini_catalog()
    port = None
    for _ in range(3):
        srv = HostFlowServer(cat, port=port or 0).serve_background()
        port = srv.addr[1]
        srv.close()
        srv.close()  # idempotent
    assert_no_leaks(before)


# -- distributed plane under chaos ------------------------------------------


def test_distributed_join_under_rpc_drops_equals_oracle():
    """Setup/stream RPC drops (bounded) against both hosts: retries — and,
    if they exhaust, degradation — still produce the oracle result, and
    no flow-registry entry outlives the query."""
    before = snapshot()
    cat = _mini_catalog()
    plan = _join_plan()
    want = run_operator(plan_builder.build(plan, cat))
    srvs = [HostFlowServer(cat).serve_background() for _ in range(2)]
    faults.arm(29, {
        "flow.host.setup": FaultSpec(kind="drop", p=0.4, max_fires=2),
        "flow.host.stream": FaultSpec(kind="error", p=0.4, max_fires=2),
    })
    try:
        got = run_distributed_join(plan, cat, [s.addr for s in srvs])
        _assert_equal(got, want)
        assert faults.fired(), "chaos run injected nothing"
        faults.disarm()
        for s in srvs:
            assert s.registry_size() == 0, "leaked flow-registry entries"
    finally:
        faults.disarm()
        for s in srvs:
            s.close()
    assert_no_leaks(before)


def test_distributed_agg_host_killed_mid_flow_degrades():
    """One host dies while its stream is still being established: the
    gateway cancels the flow everywhere, probes survivors, re-plans onto
    them, and still returns the oracle result (surfaced via the
    distsql_degraded_queries metric)."""
    before = snapshot()
    cat = _mini_catalog()
    plan = _agg_plan(cat)
    want = run_operator(plan_builder.build(plan, cat))
    srv_a = HostFlowServer(cat).serve_background()
    srv_b = HostFlowServer(cat).serve_background()
    degraded_before = metric.DIST_DEGRADED.value
    # every stream handshake stalls 0.4s; host B dies at 0.15s — so B is
    # guaranteed to go down after setup registered its fragment but
    # before its stream delivers (the "killed mid-flow" window)
    faults.arm(23, {
        "flow.host.stream": FaultSpec(kind="delay", p=1.0, delay_s=0.4),
    })
    killer = threading.Timer(0.15, srv_b.close)
    killer.start()
    try:
        got = run_distributed_hosts(plan, cat, [srv_a.addr, srv_b.addr])
        _assert_equal(got, want)
        assert metric.DIST_DEGRADED.value > degraded_before
        faults.disarm()
        assert srv_a.registry_size() == 0, "leaked flow-registry entries"
    finally:
        killer.cancel()
        faults.disarm()
        srv_a.close()
        srv_b.close()
    assert_no_leaks(before)


def test_distributed_agg_all_hosts_dead_falls_back_local():
    """No host reachable at all: the gateway degrades to single-host
    local execution rather than erroring."""
    cat = _mini_catalog()
    plan = _agg_plan(cat)
    want = run_operator(plan_builder.build(plan, cat))
    srv = HostFlowServer(cat).serve_background()
    dead_addr = srv.addr
    srv.close()  # nothing listens here anymore
    degraded_before = metric.DIST_DEGRADED.value
    got = run_distributed_hosts(plan, cat, [dead_addr])
    _assert_equal(got, want)
    assert metric.DIST_DEGRADED.value > degraded_before


def test_cancel_flow_purges_registry_and_poisons_late_arrivals():
    """cancel_flow removes every registered entry of the flow and fails
    late setups/stream-waits for it (no TTL-long lingering)."""
    cat = _mini_catalog()
    srv = HostFlowServer(cat, stream_wait_s=0.5).serve_background()
    try:
        frag = S.TableScan("orders")
        setup_flow(srv.addr, "doomed", {0: frag, 1: frag})
        assert srv.registry_size() == 2
        removed = cancel_flow(srv.addr, "doomed")
        assert removed == 2
        assert srv.registry_size() == 0
        # a late setup for the cancelled flow is rejected outright
        with pytest.raises(RuntimeError):
            setup_flow(srv.addr, "doomed", {2: frag})
        assert srv.registry_size() == 0
    finally:
        srv.close()


# -- WAL chaos --------------------------------------------------------------


def test_wal_torn_append_recovers_on_reopen(tmp_path):
    """A partial fault tears an append mid-record (the crash-mid-write
    shape): reopening truncates the torn tail and replays everything
    before it; the store keeps working."""
    wal = str(tmp_path / "w.wal")
    eng = Engine(key_width=16, val_width=8, wal_path=wal)
    eng.put(b"a", b"1", ts=3)
    faults.arm(31, {
        "storage.wal.append": FaultSpec(kind="partial", p=1.0, max_fires=1),
    })
    with pytest.raises(InjectedFault):
        eng.put(b"b", b"2", ts=4)
    faults.disarm()
    # crash: reopen from the WAL alone
    eng2 = Engine(key_width=16, val_width=8, wal_path=wal)
    assert eng2.get(b"a", ts=10) == b"1"
    assert eng2.get(b"b", ts=10) is None  # torn record truncated away
    eng2.put(b"c", b"3", ts=5)  # appending after truncation works
    assert eng2.get(b"c", ts=10) == b"3"


# -- seed matrix (tier-2) ----------------------------------------------------


@pytest.mark.slow
def test_chaos_matrix_sweeps_seed_offsets():
    """Tier-2: the whole fast chaos suite re-runs under shifted fault
    seeds (scripts/run_chaos_matrix.py) — different deterministic fault
    schedules, same convergence. Two offsets here keep it bounded; the
    CLI sweeps wider."""
    from scripts.run_chaos_matrix import run_matrix

    failed = run_matrix([0, 1], quiet=True)
    assert failed == [], f"chaos matrix failed at seed offsets {failed}"


# -- exactly-once KV writes -------------------------------------------------


def _put_req(k: bytes, v: bytes) -> dict:
    from cockroach_tpu.kv.rpc import _b64

    return {"op": "put", "key": _b64(k), "value": _b64(v)}


def _version_count(db, key: bytes) -> int:
    """Committed MVCC versions of `key` — the double-apply oracle: an
    exactly-once write leaves exactly one."""
    from cockroach_tpu.kv.changefeed import changes_between

    events, _ = changes_between(db, 0, db.clock.now())
    want = key.decode("utf-8", "replace")
    return sum(1 for e in events if e["key"] == want)


def test_exactly_once_response_dropped_retry_hits_replay_cache():
    """The server applies a mutation batch, then the response is dropped
    (the classic ambiguous window): the client's transport retry re-sends
    the SAME (cid, seq) stamp and the server answers from the replay
    cache — one version lands, never two."""
    before = snapshot()
    db = DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())
    srv = BatchServer(db)
    client = BatchClient(srv.addr, deadline_s=2.0, max_retries=4)
    hits_before = metric.REPLAY_CACHE_HITS.value
    faults.arm(43, {
        "kv.rpc.server.respond": FaultSpec(kind="drop", p=1.0, max_fires=1),
    })
    try:
        ts = client.put(b"eo-a", b"once")
        assert isinstance(ts, int)
        assert metric.REPLAY_CACHE_HITS.value > hits_before
        assert client.get(b"eo-a") == b"once"
        assert _version_count(db, b"eo-a") == 1, "double-applied!"
    finally:
        faults.disarm()
        client.close()
        srv.close()
    assert_no_leaks(before)


def test_exactly_once_across_server_crash_and_wal_restart(tmp_path):
    """Node killed mid-mutation-batch: the batch applies, the response is
    lost, the whole server AND engine go down. A fresh engine reopens
    from the WAL, a new server binds, and the client's retry (same
    stamp) dedups against the recovered replay cache — byte-exact
    convergence with zero double-applies."""
    import json as _json
    import socket as _socket

    from cockroach_tpu.flow.dcn import _recv_msg, _send_msg
    from cockroach_tpu.kv.rpc import AmbiguousResultError

    before = snapshot()
    wal = str(tmp_path / "eo.wal")
    eng = Engine(key_width=16, val_width=32, memtable_size=64, wal_path=wal)
    db = DB(eng, Clock())
    srv = BatchServer(db)
    # one attempt only: the dropped response surfaces as a typed
    # AmbiguousResultError carrying the stamp instead of a silent retry
    client = BatchClient(srv.addr, deadline_s=1.0, max_retries=1)
    ambiguous_before = metric.AMBIGUOUS_RESULTS.value
    hits_before = metric.REPLAY_CACHE_HITS.value
    faults.arm(53, {
        "kv.rpc.server.respond": FaultSpec(kind="drop", p=1.0, max_fires=1),
    })
    try:
        with pytest.raises(AmbiguousResultError) as ei:
            client.put(b"eo-b", b"exactly-once")
        faults.disarm()
        assert metric.AMBIGUOUS_RESULTS.value > ambiguous_before
        stamp = (ei.value.cid, ei.value.seq)
        assert stamp[0] == client.cid and stamp[1] is not None
        # crash: server down, engine down
        client.close()
        srv.close()
        eng.close()
        # restart: recover from the WAL alone; the applied batch AND its
        # dedup entry come back together (one atomic _REC_BATCH record)
        eng2 = Engine(key_width=16, val_width=32, memtable_size=64,
                      wal_path=wal)
        db2 = DB(eng2, Clock())
        srv2 = BatchServer(db2)
        try:
            # the application-level retry: re-send the SAME stamped
            # envelope (what BatchClient's transport retry does on the
            # wire) against the restarted server
            envelope = {"requests": [_put_req(b"eo-b", b"exactly-once")],
                        "cid": stamp[0], "seq": stamp[1]}
            s = _socket.create_connection(srv2.addr, timeout=5.0)
            try:
                _send_msg(s, _json.dumps(envelope).encode("utf-8"))
                resp = _json.loads(_recv_msg(s).decode("utf-8"))
            finally:
                s.close()
            assert "responses" in resp, resp
            assert metric.REPLAY_CACHE_HITS.value > hits_before
            assert db2.get(b"eo-b") == b"exactly-once"
            assert _version_count(db2, b"eo-b") == 1, "double-applied!"
        finally:
            srv2.close()
    finally:
        faults.disarm()
    assert_no_leaks(before)


def test_wal_torn_mid_batch_record_is_all_or_nothing(tmp_path):
    """A crash tears the WAL mid-_REC_BATCH: reopening recovers NEITHER
    the ops NOR the dedup entry (they live in one record), so the retry
    applies cleanly — exactly once, no half-applied batch."""
    wal = str(tmp_path / "torn.wal")
    eng = Engine(key_width=16, val_width=32, wal_path=wal)
    muts = [(b"tb-a", b"1", 5, 0, False), (b"tb-b", b"2", 6, 0, False)]
    resp = {"responses": [{"ts": 5}, {"ts": 6}]}
    faults.arm(59, {
        "storage.wal.append": FaultSpec(kind="partial", p=1.0, max_fires=1),
    })
    with pytest.raises(InjectedFault):
        eng.apply_rpc_batch("cl-torn", 1, muts, resp)
    faults.disarm()
    # crash + reopen: the torn batch record truncated away entirely
    eng2 = Engine(key_width=16, val_width=32, wal_path=wal)
    assert eng2.get(b"tb-a", ts=10) is None
    assert eng2.get(b"tb-b", ts=10) is None
    assert eng2.replay_cache_get("cl-torn", 1) is None
    # the retry (same stamp) applies exactly once
    eng2.apply_rpc_batch("cl-torn", 1, muts, resp)
    assert eng2.get(b"tb-a", ts=10) == b"1"
    assert eng2.get(b"tb-b", ts=10) == b"2"
    assert eng2.replay_cache_get("cl-torn", 1) == resp
    # and survives ANOTHER restart
    eng2.close()
    eng3 = Engine(key_width=16, val_width=32, wal_path=wal)
    assert eng3.replay_cache_get("cl-torn", 1) == resp
    assert eng3.get(b"tb-b", ts=10) == b"2"


# -- lease failover under heartbeat blackhole --------------------------------


def _wait_until(cond, timeout_s: float = 10.0, msg: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_heartbeat_blackhole_fences_node_and_reroutes_leases():
    """Node 1 holds range 1's epoch lease; its heartbeats get blackholed
    (scoped fault — peers keep renewing). Node 2 watches the record
    expire, bumps node 1's epoch (the fencing write), takes the lease,
    and gossip-advertises itself; the LeaseRouter reroutes writes. The
    dark node refuses range-addressed mutations with a typed error, and
    once the blackhole lifts its own heartbeat observes the fence and
    stops the whole node — resurrect-under-old-epoch is impossible."""
    from cockroach_tpu.kv.dist import LeaseRouter
    from cockroach_tpu.kv.liveness import (EpochFencedError,
                                           NotLeaseHolderError)
    from cockroach_tpu.server.node import Node

    before = snapshot()
    shared = DB(Engine(key_width=64, val_width=128), Clock())
    failovers_before = metric.LEASE_FAILOVERS.value
    # ttl >> heartbeat interval: a scheduler stall must not expire a
    # HEALTHY node's record mid-test (that would be a real — but
    # unscripted — failover and the assertions below would race it)
    n1 = Node(1, db=shared, heartbeat_interval_s=0.05, ttl_ms=1200,
              lease_ranges=[1]).start(gossip_port=0, kv_port=0)
    n2 = None
    try:
        _wait_until(
            lambda: str(n1.gossip.get_info("lease/1") or "").startswith("1:"),
            msg="n1 to acquire + advertise the lease")
        n2 = Node(2, db=shared, heartbeat_interval_s=0.05, ttl_ms=1200,
                  lease_ranges=[1], gossip_peers=[n1.gossip_addr()],
                  ).start(gossip_port=0, kv_port=0)
        router = LeaseRouter(n2.gossip, n2.dialer)
        _wait_until(
            lambda: str(n2.gossip.get_info("lease/1") or "").startswith("1:"),
            msg="n2 to learn the lease through gossip")
        router.batch(1, [_put_req(b"fo-a", b"from-n1")])
        # blackhole ONLY node 1's heartbeats (scoped site): its record
        # silently ages toward expiry while node 2 keeps renewing
        faults.arm(47, {
            "liveness.heartbeat.n1": FaultSpec(kind="error", p=1.0),
        })
        _wait_until(
            lambda: str(n2.gossip.get_info("lease/1") or "").startswith("2:"),
            msg="n2 to fence n1 and take the lease")
        assert metric.LEASE_FAILOVERS.value > failovers_before
        # the fenced holder cannot serve range-addressed mutations: its
        # lease guard answers a typed refusal, never a silent write
        stale = BatchClient(n1.kv_rpc.addr, deadline_s=2.0, max_retries=1)
        try:
            with pytest.raises((EpochFencedError, NotLeaseHolderError)):
                stale.batch([_put_req(b"fo-stale", b"zombie")], range_id=1)
        finally:
            stale.close()
        assert shared.get(b"fo-stale") is None, "fenced node served a write"
        # the router re-resolves to the new holder and the write lands
        router.batch(1, [_put_req(b"fo-b", b"from-n2")])
        faults.disarm()
        # blackhole lifts: n1's next heartbeat sees the bumped epoch and
        # stops the node — it never heartbeats the old epoch back to life
        _wait_until(lambda: n1._stop.is_set(),
                    msg="fenced n1 to stop itself")
        assert shared.get(b"fo-a") == b"from-n1"
        assert shared.get(b"fo-b") == b"from-n2"
    finally:
        faults.disarm()
        if n2 is not None:
            n2.stop()
        n1.stop()
    assert_no_leaks(before)


def test_wal_fsync_and_delay_faults(tmp_path):
    """fsync error-injection surfaces (WALFailover trigger shape); delay
    injection slows appends without corrupting them."""
    wal = str(tmp_path / "f.wal")
    eng = Engine(key_width=16, val_width=8, wal_path=wal, wal_fsync=True)
    faults.arm(37, {
        "storage.wal.fsync": FaultSpec(kind="error", p=1.0, max_fires=1),
    })
    with pytest.raises(InjectedFault):
        eng.put(b"x", b"1", ts=3)
    faults.disarm()
    faults.arm(41, {
        "storage.wal.append": FaultSpec(kind="delay", p=1.0,
                                        delay_s=0.01, max_fires=2),
    })
    t0 = time.monotonic()
    eng.put(b"y", b"2", ts=4)
    assert time.monotonic() - t0 >= 0.01
    faults.disarm()
    assert eng.get(b"y", ts=10) == b"2"


# -- range lifecycle chaos ----------------------------------------------------


def _ranger_cluster(load_seed=3):
    """2-store DistSender cluster with routing-path load stats installed
    (no background threads: chaos drives the queues synchronously)."""
    from cockroach_tpu.kv.dist import DistSender, Meta, Store
    from cockroach_tpu.kv.loadstats import RangeLoadStats

    meta = Meta(first_store=1)
    stores = [Store(i + 1, meta, key_width=16, val_width=16,
                    memtable_size=64) for i in range(2)]
    ds = DistSender(stores, meta)
    db = DB(ds, Clock())
    load = RangeLoadStats(half_life_s=5.0, sample_size=32, seed=load_seed)
    ds.load = load
    return meta, ds, db, load


def test_ranger_split_crash_between_meta_write_and_bookkeeping():
    """ranger.split.apply fires AFTER Meta.split_at but BEFORE the lease
    carry / cache repair / load handoff — the classic torn-split window.
    The item parks in purgatory; the retry finds the boundary already
    present, recovers both sides, and finishes the bookkeeping. Reads
    converge to the no-fault oracle with zero leaks."""
    from cockroach_tpu.kv.allocator import RangeLifecycle
    from cockroach_tpu.utils import settings

    before = snapshot()
    meta, ds, db, load = _ranger_cluster()
    life = RangeLifecycle(ds, load=load)
    settings.set("kv.range.split_qps_threshold", 5.0)
    try:
        model = {}
        for i in range(200):
            k, v = b"s%04d" % i, b"v%04d" % (i * 3)
            db.put(k, v)
            model[k] = v
        splits0 = metric.KV_RANGE_SPLITS.value
        faults.arm(61, {
            "ranger.split.apply": FaultSpec(kind="error", p=1.0,
                                            max_fires=1),
        })
        life.scan_once()
        life.split_queue.drain()
        assert faults.fired(), "chaos run injected nothing"
        # torn state: the meta write landed, the bookkeeping did not
        assert len(meta.snapshot()) == 2
        assert metric.KV_RANGE_SPLITS.value == splits0
        assert life.split_queue.purgatory_len() == 1
        faults.disarm()
        # retry from purgatory: idempotent recovery completes the split
        life.split_queue.drain(force_purgatory=True)
        assert life.split_queue.purgatory_len() == 0
        assert metric.KV_RANGE_SPLITS.value > splits0
        # both children carry load history (neither looks newborn-cold)
        assert all(load.qps(d.range_id) > 0 for d in meta.snapshot())
        for k, v in model.items():
            assert db.get(k) == v
        assert dict(db.scan(b"s", b"t")) == model
    finally:
        faults.disarm()
        settings.reset()
    assert_no_leaks(before)


def test_ranger_merge_crash_after_meta_write_converges():
    """ranger.merge.apply fires after Meta.merge_at removed the boundary
    but before the load fold / cache eviction. The retry sees the
    boundary gone, repairs the cache with the current owner, and
    converges — stale-descriptor routing self-heals, data intact."""
    from cockroach_tpu.kv.allocator import RangeLifecycle

    before = snapshot()
    meta, ds, db, load = _ranger_cluster()
    life = RangeLifecycle(ds, load=load)
    model = {}
    for i in range(120):
        k, v = b"c%04d" % i, b"w%04d" % i
        db.put(k, v)
        model[k] = v
    # admin-split a keyspace that is cold against the DEFAULT threshold,
    # and strand the right side on the other store (the merge must
    # colocate before it can fold the boundary)
    ds.split_at(b"c0060")
    right = meta.lookup(b"c0060")
    ds.move_range(right.range_id, to_store=2)
    merges0 = metric.KV_RANGE_MERGES.value
    faults.arm(67, {
        "ranger.merge.apply": FaultSpec(kind="error", p=1.0, max_fires=1),
    })
    try:
        life.scan_once()
        life.merge_queue.drain()
        assert faults.fired(), "chaos run injected nothing"
        # torn state: boundary gone from meta, bookkeeping lost
        assert len(meta.snapshot()) == 1
        assert metric.KV_RANGE_MERGES.value == merges0
        assert life.merge_queue.purgatory_len() == 1
        faults.disarm()
        life.merge_queue.drain(force_purgatory=True)
        assert life.merge_queue.purgatory_len() == 0
        # converged: one range, every key served, scans cross cleanly
        for k, v in model.items():
            assert db.get(k) == v
        assert dict(db.scan(b"c", b"d")) == model
        assert db.get(b"c0060") == model[b"c0060"]
    finally:
        faults.disarm()
    assert_no_leaks(before)


def test_ranger_lease_transfer_dropped_completes_on_retry():
    """ranger.lease.transfer fires after the data move but before the
    lease write lands (the dropped-transfer window): the range lives on
    the target store while the lease still names the old node. The
    purgatory retry detects the mismatch and completes the handoff —
    exactly once, converging holder == target node."""
    from cockroach_tpu.kv.allocator import RangeLifecycle
    from cockroach_tpu.kv.liveness import LeaseManager, NodeLiveness
    from cockroach_tpu.utils import settings

    before = snapshot()
    meta, ds, db, load = _ranger_cluster()
    nl1 = NodeLiveness(db, 1, ttl_ms=600_000)
    nl2 = NodeLiveness(db, 2, ttl_ms=600_000)
    nl1.heartbeat()
    nl2.heartbeat()
    lm = LeaseManager(nl1)
    lm.acquire(1)
    life = RangeLifecycle(ds, load=load, leases=lm, node_id=1,
                          store_nodes={1: 1, 2: 2})
    settings.set("kv.range.split_qps_threshold", 5.0)
    try:
        import random

        rng = random.Random(17)
        model = {}
        for _ in range(300):
            i = rng.randrange(40) if rng.random() < 0.8 \
                else 40 + rng.randrange(160)
            k, v = b"x%05d" % i, b"v%05d" % rng.randrange(10_000)
            db.put(k, v)
            model[k] = v
        # first: a clean load split so the rebalancer has something it
        # can move WITHOUT flipping the whole imbalance (a store's only
        # range never rebalances — the improvement guard)
        life.scan_once()
        life.split_queue.drain()
        assert len(meta.snapshot()) >= 2
        transfers0 = metric.KV_LEASE_TRANSFERS.value
        faults.arm(71, {
            "ranger.lease.transfer": FaultSpec(kind="error", p=1.0,
                                               max_fires=1),
        })
        life.scan_once()
        life.rebalance_queue.drain()
        assert faults.fired(), "chaos run injected nothing"
        # torn state: data moved, lease write lost
        moved = [d for d in meta.snapshot() if d.store_id == 2]
        assert moved, "rebalance never moved the hot range"
        assert all(lm.holder(d.range_id).node_id == 1 for d in moved)
        assert metric.KV_LEASE_TRANSFERS.value == transfers0
        assert life.rebalance_queue.purgatory_len() == 1
        faults.disarm()
        life.rebalance_queue.drain(force_purgatory=True)
        assert life.rebalance_queue.purgatory_len() == 0
        assert metric.KV_LEASE_TRANSFERS.value == transfers0 + 1
        # converged: the moved range's lease names the target's node
        moved = [d for d in meta.snapshot() if d.store_id == 2]
        assert any(lm.holder(d.range_id).node_id == 2 for d in moved)
        for k, v in model.items():
            assert db.get(k) == v
    finally:
        faults.disarm()
        settings.reset()
    assert_no_leaks(before)


# -- storage read/ingest plane ----------------------------------------------


def test_bulk_ingest_link_crash_atomic_abort_then_retry(tmp_path):
    """Crash in the AddSSTable link window (side file durable, WAL link
    record not yet written): the ingest aborts atomically — the run is
    invisible to the live engine AND to replay — and a retry lands it
    cleanly; exactly one copy of every row survives the crash cycle."""
    wal = str(tmp_path / "w.wal")
    eng = Engine(key_width=16, val_width=8, wal_path=wal)
    eng.put(b"keep", b"x", ts=1)
    keys = np.zeros((4, 16), np.uint8)
    for i in range(4):
        keys[i, :6] = np.frombuffer(b"ing%03d" % i, np.uint8)
    vals = np.full((4, 8), ord("v"), np.uint8)
    faults.arm(73, {
        "storage.ingest.link": FaultSpec(kind="error", p=1.0, max_fires=1),
    })
    try:
        with pytest.raises(InjectedFault):
            eng.ingest(keys, vals, ts=5)
        # atomic abort: nothing of the run is visible on the live engine
        assert eng.get(b"ing000", ts=10) is None
        assert len(eng.scan(None, None, ts=10)) == 1
        eng.ingest(keys, vals, ts=6)  # retry (fault budget exhausted)
    finally:
        faults.disarm()
    assert eng.get(b"ing002", ts=10) == b"v" * 8
    eng.close()
    # crash replay: the aborted attempt's orphan side file must not
    # resurrect — exactly one version of each row
    eng2 = Engine(key_width=16, val_width=8, wal_path=wal)
    assert eng2.get(b"keep", ts=10) == b"x"
    assert len(eng2.scan(None, None, ts=10)) == 5
    ckpt = str(tmp_path / "ckpt")
    eng2.checkpoint(ckpt)  # orphan cleanup path still works post-chaos
    import glob

    assert not glob.glob(wal + ".ingest*.npz")
    eng2.close()


def test_compaction_swap_crash_still_invalidates_cache():
    """Crash between a compaction's run-set swap and its bookkeeping: the
    replaced runs' block-cache windows MUST be invalidated anyway (the
    finally path) or reads could serve stale cached data for dead runs."""
    from cockroach_tpu.storage import blockcache

    eng = Engine(key_width=16, val_width=16, memtable_size=4,
                 l0_trigger=64)
    for i in range(48):
        eng.put(b"s%05d" % i, b"v%05d" % i, ts=i + 1)
    eng.flush()
    assert len(eng.runs) >= 2
    # warm the cache with seek windows from the soon-dead runs
    for i in (3, 17, 40):
        assert eng.get(b"s%05d" % i, ts=100) == b"v%05d" % i
        assert eng.get(b"s%05d" % i, ts=100) == b"v%05d" % i
    old_tokens = {eng._meta_for(r).token for r in eng.runs}
    faults.arm(79, {
        "storage.compaction.swap": FaultSpec(kind="error", p=1.0,
                                             max_fires=1),
    })
    try:
        with pytest.raises(InjectedFault):
            eng.compact(bottom=True)
    finally:
        faults.disarm()
    cache = blockcache.node_cache()
    assert not any(k[0] in old_tokens for k in cache._entries), \
        "dead runs' windows survived the crashed compaction"
    # the swap itself landed: reads stay correct and re-cacheable
    for i in (3, 17, 40, 47):
        assert eng.get(b"s%05d" % i, ts=100) == b"v%05d" % i


def test_bloom_corruption_detected_zero_false_negatives():
    """Silent bloom bit corruption after the build checksum: the lazy CRC
    verify on a first negative must detect it and disable the filter —
    reads stay correct (no row is ever lost to a corrupt filter), the
    corruption is counted, and absent keys still answer None."""
    faults.arm(83, {
        "storage.bloom.build": FaultSpec(kind="partial", p=1.0),
    })
    try:
        eng = Engine(key_width=16, val_width=16, memtable_size=4,
                     l0_trigger=64)
        for i in range(40):  # tiny memtable: several corrupt-filter runs
            eng.put(b"g%05d" % i, b"v%05d" % i, ts=i + 1)
        eng.flush()
        assert len(eng.runs) >= 4
    finally:
        faults.disarm()
    before = metric.BLOOM_CORRUPTIONS.value
    # zero false negatives: every present key is found despite corruption
    for i in range(40):
        assert eng.get(b"g%05d" % i, ts=100) == b"v%05d" % i
    # absent keys probe negatives -> corruption detected, answers correct
    for i in range(500, 540):
        assert eng.get(b"g%05d" % i, ts=100) is None
    assert metric.BLOOM_CORRUPTIONS.value > before
    # disabled filters keep serving (as "maybe") after detection
    assert eng.get(b"g%05d" % 7, ts=100) == b"v%05d" % 7


# -- control-plane fault sites (dialer / liveness / gossip / rangefeed) ------


def test_dialer_injected_connect_failure_then_retry_succeeds():
    """A transient connect failure at the nodedialer site: the dial raises
    through (an injected drop classifies exactly like a real one), the
    half-open probe slot is released, and the immediate retry lands a
    working connection — the breaker must NOT have tripped on a single
    unreported failure."""
    from cockroach_tpu.flow.gossip import Gossip
    from cockroach_tpu.kv.dialer import NodeDialer, advertise

    db = DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())
    srv = BatchServer(db)
    g = Gossip(99)
    advertise(g, 7, srv.addr)
    dialer = NodeDialer(g, trip_threshold=2, cooldown_s=0.4)
    faults.arm(61, {
        "kv.dialer.dial": FaultSpec(kind="error", p=1.0, max_fires=1),
    })
    try:
        with pytest.raises(InjectedFault):
            dialer.dial(7)
        c = dialer.dial(7)  # fault exhausted: the retry connects
        c.put(b"dk", b"dv")
        assert c.get(b"dk") == b"dv"
        dialer.report_ok(7)
    finally:
        faults.disarm()
        dialer.close()
        srv.close()


def test_epoch_bump_injected_cput_failure_then_retry_fences():
    """The fencer's IncrementEpoch write fails in flight (node-scoped to
    the node DOING the bump); the retry must complete the fence: the dead
    node's epoch bumps and its eventual heartbeat is fenced."""
    from cockroach_tpu.kv.hlc import ManualClock
    from cockroach_tpu.kv.liveness import EpochFencedError, NodeLiveness

    db = DB(Engine(key_width=16, val_width=32, memtable_size=64),
            ManualClock(start=1_000))
    n1 = NodeLiveness(db, 1, heartbeat_interval_ms=50, ttl_ms=100)
    n2 = NodeLiveness(db, 2, heartbeat_interval_ms=50, ttl_ms=100)
    n1.heartbeat()
    db.clock.advance(200)  # node 1's record expires
    faults.arm(67, {
        "liveness.epoch_bump.n2": FaultSpec(kind="error", p=1.0,
                                            max_fires=1),
    })
    try:
        with pytest.raises(InjectedFault):
            n2.increment_epoch(1)
        rec = n2.increment_epoch(1)  # retry lands the fencing write
        assert rec.epoch == 2
        assert rec.node_id == 1
        with pytest.raises(EpochFencedError):
            n1.heartbeat()  # the old epoch is dead for good
    finally:
        faults.disarm()


def test_gossip_injected_broadcast_failure_then_retry_converges():
    """A partitioned gossip link (node-scoped to the pushing node): the
    exchange raises, the next round retries and the peer's infos still
    propagate — run_background survives exactly this way."""
    from cockroach_tpu.flow.gossip import Gossip

    g2 = Gossip(node_id=2)
    g2.add_info("node:2:addr", "hostB:26257")
    addr = g2.serve()
    g1 = Gossip(node_id=1)
    g1.add_info("node:1:addr", "hostA:26257")
    faults.arm(71, {
        "gossip.broadcast.n1": FaultSpec(kind="error", p=1.0, max_fires=1),
    })
    try:
        with pytest.raises(InjectedFault):
            g1.exchange(addr)
        assert g1.get_info("node:2:addr") is None  # nothing leaked through
        learned = g1.exchange(addr)  # next round: the partition healed
        assert learned >= 1
        assert g1.get_info("node:2:addr") == "hostB:26257"
    finally:
        faults.disarm()
        g1.close()
        g2.close()


def test_rangefeed_injected_subscribe_failure_then_retry_streams():
    """A failed (re)subscription — the restart path every rangefeed
    consumer must retry through: the first subscribe raises before any
    socket exists, the retry connects and replays the catch-up scan."""
    from cockroach_tpu.kv.changefeed import (
        RangefeedServer, subscribe_rangefeed,
    )
    from cockroach_tpu.kv.hlc import ManualClock

    db = DB(Engine(key_width=16, val_width=64, memtable_size=64),
            ManualClock())
    db.txn(lambda t: t.put(b"rf1", b"before"))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    faults.arm(73, {
        "kv.rangefeed.subscribe": FaultSpec(kind="error", p=1.0,
                                            max_fires=1),
    })
    try:
        with pytest.raises(InjectedFault):
            subscribe_rangefeed(srv.addr, start=b"r", end=b"s")
        sock, frames = subscribe_rangefeed(srv.addr, start=b"r", end=b"s")
        sock.settimeout(15)
        got = None
        for f in frames:
            if "key" in f:
                got = f
                break
            if "resolved" in f and f["resolved"] > 0 and got is None:
                break  # checkpoint past the put without the event: fail
        assert got is not None and got["key"] == "rf1", \
            "catch-up scan lost the pre-subscribe write"
        sock.close()
    finally:
        faults.disarm()
        srv.close()


# -- runtime race sanitizer (utils/racesan.py) -------------------------------


class _SharedBox:
    """A stand-in control-plane object with one tracked field."""


def test_race_sanitizer_flags_lockset_disjoint_writes():
    """The seeded two-thread race: main writes under lock A, a second
    thread writes under lock B, main writes again under A — the candidate
    lockset refines to empty on a write/write and DataRaceError fires
    deterministically (no die-roll, no timing window)."""
    o = _SharedBox()
    la = locks.lock("chaos.race.a")
    lb = locks.lock("chaos.race.b")
    transfer_errs = []

    with la:
        racesan.note_write(o, "field")  # exclusive(main): quiet

    def writer_b():
        try:
            with lb:
                racesan.note_write(o, "field")
        except racesan.DataRaceError as e:  # pragma: no cover
            transfer_errs.append(e)

    t = threading.Thread(target=writer_b, name="chaos-writer-b")
    t.start()
    t.join(5)
    assert not t.is_alive()
    # the transfer access seeds C = {B}: not yet provably racy
    assert not transfer_errs
    # main's next write refines C to {B} ∩ {A} = ∅ — write/write with no
    # common lock, the sanitizer raises AT the access
    with pytest.raises(racesan.DataRaceError, match="field"):
        with la:
            racesan.note_write(o, "field")


def test_race_sanitizer_flags_unlocked_read_of_written_field():
    """write/read race: a second thread reads a written field holding no
    locks at all — the transfer seeds an empty candidate set on a
    write-involved field and raises immediately."""
    o = _SharedBox()
    lk = locks.lock("chaos.race.w")
    with lk:
        racesan.note_write(o, "field")
    errs = []

    def reader():
        try:
            racesan.note_read(o, "field")
        except racesan.DataRaceError as e:
            errs.append(e)

    t = threading.Thread(target=reader, name="chaos-reader")
    t.start()
    t.join(5)
    assert len(errs) == 1
    assert "no common lock" in str(errs[0])


def test_race_sanitizer_common_lock_stays_quiet():
    """The discipline the detector enforces, working: two threads
    hammering the same field under ONE shared lock never report."""
    o = _SharedBox()
    lk = locks.lock("chaos.race.common")
    errs = []

    def worker():
        try:
            for _ in range(50):
                with lk:
                    racesan.note_write(o, "field")
                    racesan.note_read(o, "field")
        except racesan.DataRaceError as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=worker) for _ in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert not errs


def test_race_sanitizer_single_thread_unlocked_is_quiet():
    """Single-threaded init without locks is the NORMAL pattern
    (constructors fill fields before any thread exists) — the exclusive
    state never reports, whatever the lockset."""
    o = _SharedBox()
    for _ in range(5):
        racesan.note_write(o, "field")
        racesan.note_read(o, "field")


# -- spill-join fault sites -------------------------------------------------


def _spill_join_catalog(seed=23) -> Catalog:
    """Two tables big enough that the join Grace-partitions under a tiny
    workmem AND at least one partition's build side alone exceeds it
    (forcing the merge-probe run path)."""
    rng = np.random.default_rng(seed)
    cat = Catalog()
    cat.add(Table(
        name="probe",
        schema=Schema(("k", "w"), (INT64, INT64)),
        columns={"k": rng.integers(0, 1200, 4000, dtype=np.int64),
                 "w": rng.integers(0, 100, 4000, dtype=np.int64)},
    ))
    cat.add(Table(
        name="build",
        schema=Schema(("bk", "v"), (INT64, INT64)),
        columns={"bk": rng.integers(0, 1500, 36000, dtype=np.int64),
                 "v": rng.integers(0, 100, 36000, dtype=np.int64)},
    ))
    return cat


def _run_spill_join(cat: Catalog, workmem: int) -> dict:
    from cockroach_tpu.sql.rel import Rel

    prev = settings.get("sql.distsql.workmem_bytes")
    settings.set("sql.distsql.workmem_bytes", workmem)
    try:
        return (Rel.scan(cat, "probe")
                .join(Rel.scan(cat, "build"), on=[("k", "bk")],
                      how="inner", build_unique=False)
                .groupby(["k"], [("n", "count_rows", None),
                                 ("sv", "sum", "v")])
                .run())
    finally:
        settings.set("sql.distsql.workmem_bytes", prev)


def test_spill_partition_write_fault_surfaces_then_clean_rerun():
    """A host spill-partition write failure mid-staging surfaces as a
    typed QueryError carrying the injected fault (not silent row loss),
    every staging reservation drains (fire precedes the account), and a
    clean re-run equals the no-fault oracle."""
    from cockroach_tpu.utils.errors import QueryError

    cat = _spill_join_catalog()
    want = _run_spill_join(cat, workmem=2 << 30)
    faults.arm(31, {"flow.spill.partition_write":
                    FaultSpec(kind="error", p=1.0, max_fires=1)})
    try:
        with pytest.raises(QueryError) as ei:
            _run_spill_join(cat, workmem=1 << 16)
        assert isinstance(ei.value.__cause__, InjectedFault)
        assert faults.fired(), "spill staging never hit the fault site"
    finally:
        faults.disarm()
    _assert_equal(_run_spill_join(cat, workmem=1 << 16), want)


def test_spill_merge_probe_fault_surfaces_then_clean_rerun():
    """An oversized-partition merge-probe run failure surfaces mid-query
    (as a typed QueryError) after partial output may already have
    streamed; monitors drain and a clean re-run is exact."""
    from cockroach_tpu.utils.errors import QueryError

    cat = _spill_join_catalog()
    want = _run_spill_join(cat, workmem=2 << 30)
    merge0 = metric.GRACE_JOIN_MERGE_PARTS.value
    faults.arm(37, {"flow.spill.merge_probe":
                    FaultSpec(kind="error", p=1.0, max_fires=1)})
    try:
        with pytest.raises(QueryError) as ei:
            _run_spill_join(cat, workmem=1 << 16)
        assert isinstance(ei.value.__cause__, InjectedFault)
        assert faults.fired(), "join never reached the merge-probe path"
    finally:
        faults.disarm()
    got = _run_spill_join(cat, workmem=1 << 16)
    assert metric.GRACE_JOIN_MERGE_PARTS.value > merge0
    _assert_equal(got, want)


# -- admission chaos (admission.grant.stall / admission.bucket.refill) ------


def test_admission_grant_lost_withdraws_waiter_and_leaks_no_slot():
    """Error-kind admission.grant.stall: a queued waiter's grant is lost.
    The waiter must withdraw cleanly (typed busy, cause = the injected
    fault), lane depth returns to zero, no slot leaks, and a clean rerun
    admits — the grant/withdraw race discipline under injected failure."""
    from cockroach_tpu.utils import admission
    from cockroach_tpu.utils.errors import AdmissionRejectedError

    q = admission.WorkQueue(slots=1, max_queue_depth=8)
    assert q.admit(tenant_id=2)  # park the slot so the next admit queues
    faults.arm(79, {"admission.grant.stall":
                    FaultSpec(kind="error", p=1.0, max_fires=1)})
    try:
        with pytest.raises(AdmissionRejectedError) as ei:
            q.admit(tenant_id=3, timeout=5.0)
        assert isinstance(ei.value.__cause__, InjectedFault)
        assert ei.value.retry_after_s > 0.0
        assert faults.fired(), "admit never reached the queued-grant path"
    finally:
        faults.disarm()
    assert q.queue_depth == 0
    assert q.lane_depths() == {admission.LANE_INTERACTIVE: 0,
                               admission.LANE_ANALYTICAL: 0}
    q.release()
    assert q.admit(tenant_id=3, timeout=5.0)  # clean rerun admits
    q.release()
    assert q.in_use == 0


def test_admission_grant_stall_delay_still_lands_grant():
    """Delay-kind admission.grant.stall only holds the stalled waiter's
    thread — the grant itself (decided under the queue lock by the
    releasing thread) still lands, and the slot accounting stays exact."""
    from cockroach_tpu.utils import admission

    q = admission.WorkQueue(slots=1)
    assert q.admit(tenant_id=2)
    faults.arm(83, {"admission.grant.stall":
                    FaultSpec(kind="delay", p=1.0, delay_s=0.2,
                              max_fires=1)})
    got = []

    def waiter():
        got.append(q.admit(tenant_id=3, timeout=10.0))
        q.release()

    t = threading.Thread(target=waiter, daemon=True)
    try:
        t.start()
        deadline = time.time() + 5.0
        while not faults.fired() and time.time() < deadline:
            time.sleep(0.005)
        assert faults.fired(), "waiter never queued into the stall site"
        q.release()  # grant races the stalled waiter: must land anyway
        t.join(timeout=10.0)
        assert got == [True]
    finally:
        faults.disarm()
    assert q.in_use == 0 and q.queue_depth == 0


def test_admission_bucket_refill_failure_is_typed_busy():
    """admission.bucket.refill error-kind: the tenant's token refill
    fails — the admit surfaces the typed 53300-shaped busy (cause = the
    injected fault, retry-after hint attached), the tenant's rejection
    counter moves, and the very next admit (fault spent) succeeds."""
    from cockroach_tpu.utils import admission
    from cockroach_tpu.utils.errors import AdmissionRejectedError

    q = admission.WorkQueue(slots=2)
    q.configure_tenant(5, rate=1000.0, burst=4)
    faults.arm(89, {"admission.bucket.refill":
                    FaultSpec(kind="error", p=1.0, max_fires=1)})
    try:
        with pytest.raises(AdmissionRejectedError) as ei:
            q.admit(tenant_id=5)
        assert isinstance(ei.value.__cause__, InjectedFault)
        assert "refill" in str(ei.value)
        assert faults.fired()
    finally:
        faults.disarm()
    row = next(r for r in q.tenant_rows() if r["tenant_id"] == 5)
    assert row["rejected"] == 1
    assert q.admit(tenant_id=5)  # clean rerun admits
    q.release()
    assert q.in_use == 0


def test_admission_grant_stall_under_concurrent_load_converges():
    """Probabilistic stall/loss sweep under real contention: N threads ×
    M admits against 2 slots with admission.grant.stall armed at p=0.3.
    Every admit either holds-then-releases or surfaces the typed busy;
    afterwards zero slots are in use and the queue is empty (no grant is
    ever both counted and lost — the sanitizer-armed shared-state check
    rides the autouse fixtures)."""
    from cockroach_tpu.utils import admission
    from cockroach_tpu.utils.errors import AdmissionRejectedError

    q = admission.WorkQueue(slots=2, max_queue_depth=64)
    ok = []
    shed = []
    lock = threading.Lock()

    def worker(tid):
        for _ in range(12):
            try:
                if q.admit(tenant_id=tid, timeout=10.0):
                    time.sleep(0.001)
                    q.release()
                    with lock:
                        ok.append(tid)
            except AdmissionRejectedError:
                with lock:
                    shed.append(tid)

    faults.arm(97, {"admission.grant.stall":
                    FaultSpec(kind="error", p=0.3, max_fires=8)})
    try:
        threads = [threading.Thread(target=worker, args=(tid,),
                                    daemon=True) for tid in (2, 3, 4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
    finally:
        faults.disarm()
    assert len(ok) + len(shed) == 36
    assert q.in_use == 0 and q.queue_depth == 0
    assert q.lane_depths() == {admission.LANE_INTERACTIVE: 0,
                               admission.LANE_ANALYTICAL: 0}


# -- changefeed fan-out plane under injected faults --------------------------


def _feed_db():
    from cockroach_tpu.kv.hlc import ManualClock

    return DB(Engine(key_width=16, val_width=64, memtable_size=64),
              ManualClock())


def _feed_oracle(db):
    """(ts, key) -> value of the full committed history — the exactly-once
    reference every faulted stream must dedup to."""
    from cockroach_tpu.kv.changefeed import changes_between

    events, _resolved = changes_between(db, 0, db.clock.now())
    return {(e["ts"], e["key"]): e["value"] for e in events}


def _feed_drain(sock, frames, until_resolved, deadline_s=15):
    """Deduped event frames until the frontier reaches `until_resolved`,
    an error frame, or end-of-stream. Returns (events, resolved, err)."""
    sock.settimeout(deadline_s)
    events, resolved = {}, 0
    deadline = time.time() + deadline_s
    for f in frames:
        if "error" in f:
            return events, resolved, f
        if "resolved" in f:
            resolved = max(resolved, f["resolved"])
            if resolved >= until_resolved:
                break
        else:
            events[(f["ts"], f["key"])] = f["value"]
        if time.time() > deadline:
            break
    return events, resolved, None


def test_fanout_injected_send_fault_evicts_then_reconnect_exactly_once():
    """Site ``changefeed.subscriber.send``: the sender's first
    transmission dies mid-stream. The subscriber is evicted with a typed
    slow_consumer frame carrying its frontier, the emit loop survives,
    and a reconnect from that frontier replays the feed so the deduped
    union is bit-identical to the no-fault catch-up scan — exactly once
    per version."""
    from cockroach_tpu.kv.changefeed import (
        RangefeedServer, subscribe_rangefeed,
    )

    db = _feed_db()
    for i in range(6):
        db.txn(lambda t, i=i: t.put(b"sf%d" % i, b"v%d" % i))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    faults.arm(79, {
        "changefeed.subscriber.send": FaultSpec(kind="drop", p=1.0,
                                                max_fires=1),
    })
    try:
        sock, frames = subscribe_rangefeed(srv.addr)
        first, _ckpt, err = _feed_drain(sock, frames, db.clock.now())
        sock.close()
        assert err is not None and err["error"] == "slow_consumer", \
            "faulted send must evict with a typed goodbye"
        assert "frontier" in err
        assert metric.CHANGEFEED_EVICTIONS.value >= 1
        # the fault fired before any frame hit the wire: nothing was
        # checkpointed, so the carried frontier is the join point
        since = err["frontier"]
        assert since == 0
        sock2, frames2 = subscribe_rangefeed(srv.addr, since=since)
        hi = db.clock.now()
        second, ckpt2, err2 = _feed_drain(sock2, frames2, hi)
        sock2.close()
        assert err2 is None and ckpt2 >= hi, "emit loop wedged by fault"
        merged = dict(first)
        merged.update(second)
        assert merged == _feed_oracle(db), \
            "reconnect after injected send fault lost/duplicated a version"
    finally:
        faults.disarm()
        srv.close()


def test_fanout_injected_enqueue_fault_converges_without_buffer_leak():
    """Site ``changefeed.fanout.enqueue``: every other buffer append dies
    under a write stream. Each hit sheds the subscriber to catch-up (the
    engine re-feeds from the frontier, dedup by (ts, key)), so the stream
    still converges to the full history — and the changefeed staging
    account drains to zero after close: no leaked buffer bytes."""
    from cockroach_tpu.flow import memory as flowmem
    from cockroach_tpu.kv.changefeed import (
        RangefeedServer, subscribe_rangefeed,
    )

    db = _feed_db()
    # poll SLOWER than one cold overlay rebuild (~0.4s with dozens of
    # runs): each commit rewrites the run set, so a poller that fires
    # faster than it can rebuild serializes the writer to one txn per
    # rebuild under the store mutex and the test crawls
    srv = RangefeedServer(db, poll_interval_s=0.25)
    sheds0 = metric.CHANGEFEED_SHEDS.value
    # this test pins the SHED rung: transient fault (max_fires — the
    # retrying-caller knob) and a shed ceiling high enough that back-to-
    # back sheds during one slow rescan can't escalate to eviction (the
    # terminal rung has its own tests)
    prev_sheds = settings.get("changefeed.fanout.max_consecutive_sheds")
    settings.set("changefeed.fanout.max_consecutive_sheds", 100)
    faults.arm(83, {
        "changefeed.fanout.enqueue": FaultSpec(kind="error", p=0.5,
                                               max_fires=6),
    })
    try:
        sock, frames = subscribe_rangefeed(srv.addr)
        # spread the writes over a few poll intervals so enqueue runs
        # (and coin-flips) repeatedly while the consumer is live; after
        # each injected shed the sender rescans and returns LIVE, so the
        # next batch coin-flips again. A warm module can land a whole
        # batch inside one poll interval (one enqueue, ONE coin flip),
        # so keep writing rounds until the coin lands — each round is at
        # least one fresh flip, so 15 rounds at p=0.5 can't all miss
        i = 0
        for _round in range(15):
            for _ in range(12):
                db.txn(lambda t, i=i: t.put(b"eq%02d" % (i % 12),
                                            b"w%02d" % i))
                i += 1
                time.sleep(0.002)
            if metric.CHANGEFEED_SHEDS.value > sheds0:
                break
            time.sleep(0.3)  # let the poller batch + coin-flip this round
        hi = db.clock.now()
        events, resolved, err = _feed_drain(sock, frames, hi)
        sock.close()
        assert err is None, f"enqueue fault must shed, not evict: {err}"
        assert resolved >= hi, "frontier stalled under injected sheds"
        assert events == _feed_oracle(db), \
            "shed/rescan under enqueue faults lost or duplicated a version"
        assert metric.CHANGEFEED_SHEDS.value > sheds0, \
            "seed 83 at p=0.5 over ~dozens of enqueues must shed"
    finally:
        faults.disarm()
        srv.close()
        settings.set("changefeed.fanout.max_consecutive_sheds",
                     prev_sheds)
    assert flowmem.staging_monitor("changefeed").used == 0, \
        "fan-out buffer bytes leaked past hub close"


def test_fanout_injected_checkpoint_fault_resume_never_skips():
    """Site ``changefeed.frontier.checkpoint``: the first checkpoint
    write dies AFTER events reached the wire. The frontier must not
    advance past the failed checkpoint — the typed eviction carries the
    pre-fault frontier, and reconnecting from it re-delivers (dedup)
    rather than skips: resolved never runs ahead of delivery."""
    from cockroach_tpu.kv.changefeed import (
        RangefeedServer, subscribe_rangefeed,
    )

    db = _feed_db()
    for i in range(4):
        db.txn(lambda t, i=i: t.put(b"cp%d" % i, b"v%d" % i))
    srv = RangefeedServer(db, poll_interval_s=0.02)
    faults.arm(89, {
        "changefeed.frontier.checkpoint": FaultSpec(kind="error", p=1.0,
                                                    max_fires=1),
    })
    try:
        sock, frames = subscribe_rangefeed(srv.addr)
        first, ckpt, err = _feed_drain(sock, frames, db.clock.now())
        sock.close()
        assert err is not None and err["error"] == "slow_consumer"
        assert ckpt == 0, "a checkpoint frame arrived despite the fault"
        assert err["frontier"] == 0, \
            "frontier advanced past a checkpoint that never hit the wire"
        sock2, frames2 = subscribe_rangefeed(srv.addr,
                                             since=err["frontier"])
        hi = db.clock.now()
        second, ckpt2, err2 = _feed_drain(sock2, frames2, hi)
        sock2.close()
        assert err2 is None and ckpt2 >= hi
        merged = dict(first)
        merged.update(second)
        assert merged == _feed_oracle(db), \
            "resume after failed checkpoint skipped a version"
    finally:
        faults.disarm()
        srv.close()


def test_race_sanitizer_guards_fanout_frontier():
    """The fan-out plane's shared state is racesan-tracked: a subscriber
    frontier write under some OTHER lock (not the hub's
    ``kv.fanout.state`` lock every product access holds) refines the
    candidate lockset to empty and raises deterministically — the seeded
    two-thread schedule for the new subscriber tree."""
    import socket as _socket

    from cockroach_tpu.kv import fanout

    db = _feed_db()
    hub = fanout.FanoutHub(db, poll_interval_s=3600)
    a, b = _socket.socketpair()
    try:
        sub = hub.add_subscriber(a, start_sender=False)
        with hub._mu:
            racesan.note_write(sub, "frontier")  # product-path lockset
        rogue = locks.lock("chaos.race.fanout")
        transfer_errs = []

        def writer_rogue():
            try:
                with rogue:
                    racesan.note_write(sub, "frontier")
            except racesan.DataRaceError as e:  # pragma: no cover
                transfer_errs.append(e)

        t = threading.Thread(target=writer_rogue,
                             name="chaos-fanout-rogue")
        t.start()
        t.join(5)
        assert not t.is_alive()
        assert not transfer_errs  # transfer access only seeds C = {rogue}
        # the next product-path write proves disjointness: {mu} ∩ {rogue}
        with pytest.raises(racesan.DataRaceError, match="frontier"):
            with hub._mu:
                racesan.note_write(sub, "frontier")
    finally:
        hub.close()
        a.close()
        b.close()


# -- PR 19 serving-path sites: coalesce, sharedscan attach, warmup compile ---


def _coalesce_tape(tid: int, n: int = 40):
    """Deterministic per-thread mixed-DML tape over private keys."""
    ops = []
    for i in range(n):
        k = f"cz{tid}-{i % 6}"
        if i % 5 == 4:
            ops.append(("delete", k, None))
        elif i % 3 == 2:
            ops.append(("get", k, None))
        else:
            ops.append(("put", k, f"v{tid}.{i}"))
    return ops


def _play_tape(db, tape, out):
    for kind, k, v in tape:
        if kind == "put":
            out.append(db.put(k, v))
        elif kind == "delete":
            out.append(db.delete(k))
        else:
            out.append(db.get(k))


def test_coalesce_fault_degrades_to_solo_bit_identical():
    """A fault mid-coalesce ("kv.batch.coalesce") degrades every rider of
    that train to its own per-session solo batch: nothing errors, nothing
    applies twice, and the surviving state is bit-identical to the same
    tapes run uncoalesced (p=0.5 under the harness seed: degraded and
    merged trains interleave within one run)."""
    from cockroach_tpu.kv import coalesce

    tapes = [_coalesce_tape(t) for t in range(6)]

    # solo oracle first, before any injection
    solo = DB(Engine())
    solo_outs = [[] for _ in tapes]
    for t, tape in enumerate(tapes):
        _play_tape(solo, tape, solo_outs[t])
    want = dict(solo.scan(None, None))

    db = DB(Engine())
    settings.set("kv.batch.coalesce.enabled", True)
    faults.arm(1229, {"kv.batch.coalesce": FaultSpec(kind="error", p=0.5)})
    outs = [[] for _ in tapes]
    errs = []
    try:
        def worker(t):
            try:
                _play_tape(db, tapes[t], outs[t])
            except Exception as e:  # pragma: no cover - fail loudly below
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(len(tapes))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
    finally:
        faults.disarm()
        settings.reset("kv.batch.coalesce.enabled")
        coalesce.reset_db(db)
    assert not errs, errs
    assert dict(db.scan(None, None)) == want
    # get results are deterministic (thread-private keys): bit-identical
    # to the solo oracle even across degraded trains
    for t, tape in enumerate(tapes):
        for (kind, _k, _v), got, exp in zip(tape, outs[t], solo_outs[t]):
            if kind == "get":
                assert got == exp


def test_coalesce_fault_every_train_still_serves():
    """p=1.0: EVERY train degrades — the coalescer must transparently
    become the solo path, and the fault log must show the site fired."""
    from cockroach_tpu.kv import coalesce

    db = DB(Engine())
    settings.set("kv.batch.coalesce.enabled", True)
    faults.arm(7, {"kv.batch.coalesce": FaultSpec(kind="error", p=1.0)})
    try:
        ts1 = db.put("deg-a", "1")
        ts2 = db.put("deg-b", "2")
        assert isinstance(ts1, int) and isinstance(ts2, int)
        assert db.get("deg-a") == b"1"
        assert db.delete("deg-a") > ts1
        assert db.get("deg-a") is None
        assert ("kv.batch.coalesce", "error") in faults.fired()
    finally:
        faults.disarm()
        settings.reset("kv.batch.coalesce.enabled")
        coalesce.reset_db(db)


def test_sharedscan_attach_fault_runs_solo_identical():
    """An injected fault at "flow.sharedscan.attach" degrades that scan
    to slicing its own tiles — identical rows, no stream joined. With
    max_fires=1 the SECOND scan attaches normally, so one run covers
    both the degraded and the shared path over the same table."""
    from cockroach_tpu.flow import sharedscan
    from cockroach_tpu.flow.operators import ScanOp

    cat = _mini_catalog()
    table = cat.get("orders")

    def rows(op):
        out = []
        while True:
            t = op._next()
            if t is None:
                return out
            mask = np.asarray(t.mask)
            cols = [np.asarray(c.data) for c in t.cols]
            out.extend(tuple(c[i] for c in cols)
                       for i in np.nonzero(mask)[0])

    solo = ScanOp(table, tile=128)
    solo.init()
    want = rows(solo)
    solo.close()

    settings.set("sql.distsql.sharedscan.enabled", True)
    faults.arm(31, {"flow.sharedscan.attach":
                    FaultSpec(kind="error", p=1.0, max_fires=1)})
    try:
        a = ScanOp(table, tile=128)
        a.init()
        assert a._shared is None  # fault: degraded to solo slicing
        b = ScanOp(table, tile=128)
        b.init()
        assert b._shared is not None  # max_fires spent: normal attach
        got_a, got_b = rows(a), rows(b)
        a.close()
        b.close()
        assert got_a == want
        assert got_b == want
        assert not sharedscan._streams
        assert ("flow.sharedscan.attach", "error") in faults.fired()
    finally:
        faults.disarm()
        settings.reset("sql.distsql.sharedscan.enabled")
        sharedscan.reset()


def test_warmup_compile_fault_records_failed_serves_cold():
    """A fault at "sql.warmup.compile" marks that menu item failed; the
    build still completes inside its budget, readiness is never blocked,
    and the statement serves correctly on first use (compile-on-first-use
    degrade) — warmup is best-effort by contract."""
    from cockroach_tpu.sql import warmmenu
    from cockroach_tpu.sql.session import Session

    warmmenu.reset()
    cat = _mini_catalog(n=300, seed=23)
    boot = Session(catalog=cat)
    settings.set("sql.warmup.menu.enabled", True)
    faults.arm(47, {"sql.warmup.compile":
                    FaultSpec(kind="error", p=1.0, max_fires=2)})
    try:
        run = warmmenu.build_menu(cat, boot.db, block=True)
        assert run is not None
        run.join(10)
        rows = warmmenu.menu_rows()
        statuses = [r["status"] for r in rows]
        assert statuses.count("failed") == 2
        assert "compiled" in statuses
        # no warmup thread survives the blocking build
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("warm-menu")]
        # failed items still serve (cold) — and their results match a
        # fault-free session over the same data
        faults.disarm()
        serve = Session(catalog=cat, db=boot.db, bootstrap=False)
        oracle = Session(catalog=_mini_catalog(n=300, seed=23))
        try:
            for s in warmmenu._ladder_statements(cat):
                got, exp = serve.execute(s), oracle.execute(s)
                assert set(got) == set(exp)
                for name in exp:
                    np.testing.assert_array_equal(
                        np.asarray(got[name]), np.asarray(exp[name]),
                        err_msg=f"{s}: {name}")
        finally:
            oracle.close()
            serve.close()
    finally:
        faults.disarm()
        settings.reset("sql.warmup.menu.enabled")
        boot.close()
        warmmenu.reset()


def test_race_sanitizer_tracks_coalescer_pending():
    """The coalescer's cross-session meeting point (``_pending``) is
    racesan-tracked: a rogue thread touching it under the WRONG lock
    refines the candidate lockset to empty against the product path's
    ``kv.coalesce`` lock and the next product access raises — the seeded
    two-thread schedule for the commit train."""
    from cockroach_tpu.kv import coalesce

    db = DB(Engine())
    settings.set("kv.batch.coalesce.enabled", True)
    try:
        db.put("rs-seed", "1")  # product path: note under kv.coalesce
        co = db._coalescer
        rogue = locks.lock("chaos.race.coalesce")
        transfer_errs = []

        def writer_rogue():
            try:
                with rogue:
                    racesan.note_write(co, "_pending")
            except racesan.DataRaceError as e:  # pragma: no cover
                transfer_errs.append(e)

        t = threading.Thread(target=writer_rogue,
                             name="chaos-coalesce-rogue")
        t.start()
        t.join(5)
        assert not t.is_alive()
        assert not transfer_errs  # transfer only seeds C = {rogue}
        # next product-path boarding proves disjointness and raises
        with pytest.raises(racesan.DataRaceError, match="_pending"):
            db.put("rs-seed2", "2")
    finally:
        settings.reset("kv.batch.coalesce.enabled")
        coalesce.reset_db(db)


def test_race_sanitizer_tracks_sharedscan_subs():
    """Same seeded schedule for the shared stream's subscriber map: a
    rogue-locked ``_subs`` write races the product path's
    ``flow.sharedscan`` lock and detach raises at the access."""
    from cockroach_tpu.flow import sharedscan
    from cockroach_tpu.flow.operators import ScanOp

    cat = _mini_catalog()
    table = cat.get("orders")
    settings.set("sql.distsql.sharedscan.enabled", True)
    try:
        op = ScanOp(table, tile=128)
        op.init()  # product path: _subs write under flow.sharedscan
        stream = op._shared
        assert stream is not None
        rogue = locks.lock("chaos.race.sharedscan")
        transfer_errs = []

        def writer_rogue():
            try:
                with rogue:
                    racesan.note_write(stream, "_subs")
            except racesan.DataRaceError as e:  # pragma: no cover
                transfer_errs.append(e)

        t = threading.Thread(target=writer_rogue,
                             name="chaos-sharedscan-rogue")
        t.start()
        t.join(5)
        assert not t.is_alive()
        assert not transfer_errs
        with pytest.raises(racesan.DataRaceError, match="_subs"):
            op.close()  # detach: product-path _subs write
    finally:
        settings.reset("sql.distsql.sharedscan.enabled")
        sharedscan.reset()
