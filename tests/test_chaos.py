"""Chaos harness: distributed join + KV RPC suites under seeded injected
faults (utils/faults.py). Every test asserts results equal the no-fault
oracle AND that no threads/sockets/flow-registry entries leak — the
leaktest.AfterTest + TestingKnobs discipline combined.

Fast seeds only: everything here is deterministic (one seeded RNG drives
all firing decisions) and finishes in seconds, so the suite runs inside
tier-1. Exclude with -m 'not chaos'."""

import threading
import time

import numpy as np
import pytest

from scripts.check_no_leaks import assert_no_leaks, snapshot

from cockroach_tpu.catalog import Catalog, Table
from cockroach_tpu.coldata.types import FLOAT64, INT64, Schema
from cockroach_tpu.flow.disthost import (
    HostFlowServer,
    cancel_flow,
    run_distributed_hosts,
    run_distributed_join,
    setup_flow,
)
from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.rpc import BatchClient, BatchServer
from cockroach_tpu.ops.aggregation import AggSpec
from cockroach_tpu.plan import builder as plan_builder
from cockroach_tpu.plan import spec as S
from cockroach_tpu.flow.runtime import run_operator
from cockroach_tpu.storage.lsm import Engine
from cockroach_tpu.utils import faults, metric
from cockroach_tpu.utils.faults import FaultSpec, InjectedFault

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


def _mini_catalog(n=600, c=16, seed=7) -> Catalog:
    """Small deterministic two-table catalog (fast chaos iterations; the
    tpch generator would dominate runtime)."""
    rng = np.random.default_rng(seed)
    cat = Catalog()
    cat.add(Table(
        name="orders",
        schema=Schema(("o_key", "o_cust", "o_val"),
                      (INT64, INT64, FLOAT64)),
        columns={
            "o_key": np.arange(n, dtype=np.int64),
            "o_cust": rng.integers(0, c, n, dtype=np.int64),
            "o_val": rng.uniform(1.0, 100.0, n),
        },
    ))
    cat.add(Table(
        name="cust",
        schema=Schema(("c_key", "c_grp"), (INT64, INT64)),
        columns={
            "c_key": np.arange(c, dtype=np.int64),
            "c_grp": np.arange(c, dtype=np.int64) % 4,
        },
    ))
    return cat


def _agg_plan(cat: Catalog) -> S.PlanNode:
    sch = cat.get("orders").schema
    return S.Aggregate(
        S.TableScan("orders"),
        group_cols=(sch.index("o_cust"),),
        aggs=(AggSpec("count_rows", None, "n"),
              AggSpec("sum", sch.index("o_val"), "total")),
        mode="complete",
    )


def _join_plan() -> S.HashJoin:
    return S.HashJoin(
        probe=S.TableScan("orders", ("o_key", "o_cust")),
        build=S.TableScan("cust", ("c_key", "c_grp")),
        probe_keys=(1,),
        build_keys=(0,),
    )


def _canon(res: dict) -> np.ndarray:
    rows = np.stack([np.asarray(res[k], dtype=np.float64)
                     for k in sorted(res.keys())], axis=1)
    return rows[np.lexsort(rows.T[::-1])]


def _assert_equal(got: dict, want: dict) -> None:
    assert sorted(got.keys()) == sorted(want.keys())
    np.testing.assert_allclose(_canon(got), _canon(want), rtol=1e-9)


# -- determinism ------------------------------------------------------------


def test_fault_registry_deterministic_replay():
    """Same seed, same specs => the exact same fault sequence (the whole
    point of seeding: a chaos failure replays)."""
    spec = {"site.a": FaultSpec(kind="error", p=0.5, max_fires=3),
            "site.b": FaultSpec(kind="delay", p=0.5, delay_s=0.0)}
    runs = []
    for _ in range(2):
        faults.arm(1234, {k: FaultSpec(**{
            "kind": v.kind, "p": v.p, "delay_s": v.delay_s,
            "max_fires": v.max_fires}) for k, v in spec.items()})
        for _ in range(30):
            for site in ("site.a", "site.b"):
                try:
                    faults.fire(site)
                except InjectedFault:
                    pass
        runs.append(faults.fired())
        faults.disarm()
    assert runs[0] == runs[1]
    assert any(s == "site.a" for s, _ in runs[0])  # it actually fired


def test_disarmed_sites_are_free():
    faults.disarm()
    faults.fire("kv.rpc.client.batch")  # no-op, no exception
    assert faults.partial_fraction("storage.wal.append") is None


# -- KV RPC under drops -----------------------------------------------------


def test_kv_rpc_drops_retry_to_oracle():
    """Client-wire drops AND server-eval drops: the retry layer re-dials
    and re-sends until the (max_fires-bounded) faults exhaust; every
    read then equals the no-fault oracle."""
    before = snapshot()
    db = DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())
    srv = BatchServer(db)
    client = BatchClient(srv.addr, deadline_s=2.0, max_retries=8)
    retries_before = metric.RPC_RETRIES.value
    faults.arm(11, {
        "kv.rpc.client.batch": FaultSpec(kind="drop", p=0.25, max_fires=4),
        "kv.rpc.server.eval": FaultSpec(kind="drop", p=0.25, max_fires=4),
    })
    try:
        oracle = {}
        for i in range(30):
            k = b"k%03d" % i
            v = b"v%03d" % (i * 7)
            client.put(k, v)
            oracle[k] = v
        for k, v in oracle.items():
            assert client.get(k) == v
        assert faults.fired(), "chaos run injected nothing"
        assert metric.RPC_RETRIES.value > retries_before
    finally:
        faults.disarm()
        client.close()
        srv.close()
    assert_no_leaks(before)


def test_batch_server_restart_same_port_and_idempotent_close():
    """Back-to-back start/stop on the SAME port never raises; close() is
    idempotent and leaves no thread or socket behind."""
    before = snapshot()
    db = DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())
    port = None
    for round_no in range(3):
        srv = BatchServer(db, port=port or 0)
        port = srv.addr[1]
        c = BatchClient(srv.addr)
        c.put(b"r%d" % round_no, b"x")
        c.close()
        srv.close()
        srv.close()  # idempotent
    assert_no_leaks(before)


def test_host_flow_server_restart_same_port_and_idempotent_close():
    before = snapshot()
    cat = _mini_catalog()
    port = None
    for _ in range(3):
        srv = HostFlowServer(cat, port=port or 0).serve_background()
        port = srv.addr[1]
        srv.close()
        srv.close()  # idempotent
    assert_no_leaks(before)


# -- distributed plane under chaos ------------------------------------------


def test_distributed_join_under_rpc_drops_equals_oracle():
    """Setup/stream RPC drops (bounded) against both hosts: retries — and,
    if they exhaust, degradation — still produce the oracle result, and
    no flow-registry entry outlives the query."""
    before = snapshot()
    cat = _mini_catalog()
    plan = _join_plan()
    want = run_operator(plan_builder.build(plan, cat))
    srvs = [HostFlowServer(cat).serve_background() for _ in range(2)]
    faults.arm(29, {
        "flow.host.setup": FaultSpec(kind="drop", p=0.4, max_fires=2),
        "flow.host.stream": FaultSpec(kind="error", p=0.4, max_fires=2),
    })
    try:
        got = run_distributed_join(plan, cat, [s.addr for s in srvs])
        _assert_equal(got, want)
        assert faults.fired(), "chaos run injected nothing"
        faults.disarm()
        for s in srvs:
            assert s.registry_size() == 0, "leaked flow-registry entries"
    finally:
        faults.disarm()
        for s in srvs:
            s.close()
    assert_no_leaks(before)


def test_distributed_agg_host_killed_mid_flow_degrades():
    """One host dies while its stream is still being established: the
    gateway cancels the flow everywhere, probes survivors, re-plans onto
    them, and still returns the oracle result (surfaced via the
    distsql_degraded_queries metric)."""
    before = snapshot()
    cat = _mini_catalog()
    plan = _agg_plan(cat)
    want = run_operator(plan_builder.build(plan, cat))
    srv_a = HostFlowServer(cat).serve_background()
    srv_b = HostFlowServer(cat).serve_background()
    degraded_before = metric.DIST_DEGRADED.value
    # every stream handshake stalls 0.4s; host B dies at 0.15s — so B is
    # guaranteed to go down after setup registered its fragment but
    # before its stream delivers (the "killed mid-flow" window)
    faults.arm(23, {
        "flow.host.stream": FaultSpec(kind="delay", p=1.0, delay_s=0.4),
    })
    killer = threading.Timer(0.15, srv_b.close)
    killer.start()
    try:
        got = run_distributed_hosts(plan, cat, [srv_a.addr, srv_b.addr])
        _assert_equal(got, want)
        assert metric.DIST_DEGRADED.value > degraded_before
        faults.disarm()
        assert srv_a.registry_size() == 0, "leaked flow-registry entries"
    finally:
        killer.cancel()
        faults.disarm()
        srv_a.close()
        srv_b.close()
    assert_no_leaks(before)


def test_distributed_agg_all_hosts_dead_falls_back_local():
    """No host reachable at all: the gateway degrades to single-host
    local execution rather than erroring."""
    cat = _mini_catalog()
    plan = _agg_plan(cat)
    want = run_operator(plan_builder.build(plan, cat))
    srv = HostFlowServer(cat).serve_background()
    dead_addr = srv.addr
    srv.close()  # nothing listens here anymore
    degraded_before = metric.DIST_DEGRADED.value
    got = run_distributed_hosts(plan, cat, [dead_addr])
    _assert_equal(got, want)
    assert metric.DIST_DEGRADED.value > degraded_before


def test_cancel_flow_purges_registry_and_poisons_late_arrivals():
    """cancel_flow removes every registered entry of the flow and fails
    late setups/stream-waits for it (no TTL-long lingering)."""
    cat = _mini_catalog()
    srv = HostFlowServer(cat, stream_wait_s=0.5).serve_background()
    try:
        frag = S.TableScan("orders")
        setup_flow(srv.addr, "doomed", {0: frag, 1: frag})
        assert srv.registry_size() == 2
        removed = cancel_flow(srv.addr, "doomed")
        assert removed == 2
        assert srv.registry_size() == 0
        # a late setup for the cancelled flow is rejected outright
        with pytest.raises(RuntimeError):
            setup_flow(srv.addr, "doomed", {2: frag})
        assert srv.registry_size() == 0
    finally:
        srv.close()


# -- WAL chaos --------------------------------------------------------------


def test_wal_torn_append_recovers_on_reopen(tmp_path):
    """A partial fault tears an append mid-record (the crash-mid-write
    shape): reopening truncates the torn tail and replays everything
    before it; the store keeps working."""
    wal = str(tmp_path / "w.wal")
    eng = Engine(key_width=16, val_width=8, wal_path=wal)
    eng.put(b"a", b"1", ts=3)
    faults.arm(31, {
        "storage.wal.append": FaultSpec(kind="partial", p=1.0, max_fires=1),
    })
    with pytest.raises(InjectedFault):
        eng.put(b"b", b"2", ts=4)
    faults.disarm()
    # crash: reopen from the WAL alone
    eng2 = Engine(key_width=16, val_width=8, wal_path=wal)
    assert eng2.get(b"a", ts=10) == b"1"
    assert eng2.get(b"b", ts=10) is None  # torn record truncated away
    eng2.put(b"c", b"3", ts=5)  # appending after truncation works
    assert eng2.get(b"c", ts=10) == b"3"


def test_wal_fsync_and_delay_faults(tmp_path):
    """fsync error-injection surfaces (WALFailover trigger shape); delay
    injection slows appends without corrupting them."""
    wal = str(tmp_path / "f.wal")
    eng = Engine(key_width=16, val_width=8, wal_path=wal, wal_fsync=True)
    faults.arm(37, {
        "storage.wal.fsync": FaultSpec(kind="error", p=1.0, max_fires=1),
    })
    with pytest.raises(InjectedFault):
        eng.put(b"x", b"1", ts=3)
    faults.disarm()
    faults.arm(41, {
        "storage.wal.append": FaultSpec(kind="delay", p=1.0,
                                        delay_s=0.01, max_fires=2),
    })
    t0 = time.monotonic()
    eng.put(b"y", b"2", ts=4)
    assert time.monotonic() - t0 >= 0.01
    faults.disarm()
    assert eng.get(b"y", ts=10) == b"2"
