"""SQL DDL/DML over the KV layer (Session): CREATE TABLE / INSERT / UPDATE /
DELETE round-trips through the MVCC engine and back out via SELECT.

Reference parity points: pkg/sql/conn_executor.go statement dispatch,
pkg/sql/insert.go KV-encoded writes, pkg/sql/parser/sql.y DML grammar."""

import numpy as np
import pytest

from cockroach_tpu.sql import BindError, Session


@pytest.fixture
def sess():
    return Session()


def _setup_accounts(sess, n=20):
    sess.execute("""
        create table accounts (
            id int primary key,
            balance decimal(12, 2),
            opened date,
            score float,
            active bool
        )
    """)
    rows = ", ".join(
        f"({i}, {100 + i}.50, date '2020-01-01', {i} * 0.5, "
        f"{'true' if i % 2 == 0 else 'false'})"
        for i in range(n)
    )
    r = sess.execute(f"insert into accounts values {rows}")
    assert r["rows_affected"] == n
    return n


def test_create_insert_select_roundtrip(sess):
    n = _setup_accounts(sess)
    res = sess.execute("select id, balance, active from accounts "
                       "where id < 5 order by id")
    assert list(res["id"]) == [0, 1, 2, 3, 4]
    np.testing.assert_allclose(
        np.asarray(res["balance"], dtype=np.float64),
        [100.5, 101.5, 102.5, 103.5, 104.5],
    )
    # aggregates run through the same engine
    res = sess.execute("select count(*) as n, sum(balance) as s "
                       "from accounts")
    assert int(res["n"][0]) == n


def test_insert_column_list_and_nulls(sess):
    sess.execute("create table t (id int primary key, x int, y float)")
    sess.execute("insert into t (id, x, y) values (1, null, 2.5), "
                 "(2, 7, null)")
    res = sess.execute("select id, x, y from t order by id")
    assert res["x"][0] is None and int(res["x"][1]) == 7
    assert float(res["y"][0]) == 2.5 and res["y"][1] is None
    # NULL never satisfies a comparison
    res = sess.execute("select id from t where x > 0")
    assert list(res["id"]) == [2]


def test_update_where(sess):
    _setup_accounts(sess)
    r = sess.execute(
        "update accounts set balance = balance + 10.00, score = 0.0 "
        "where id >= 15")
    assert r["rows_affected"] == 5
    res = sess.execute("select balance, score from accounts "
                       "where id = 17")
    np.testing.assert_allclose(float(res["balance"][0]), 117.5 + 10.0)
    assert float(res["score"][0]) == 0.0
    # untouched rows keep their versions
    res = sess.execute("select balance from accounts where id = 3")
    np.testing.assert_allclose(float(res["balance"][0]), 103.5)


def test_delete_where(sess):
    n = _setup_accounts(sess)
    r = sess.execute("delete from accounts where active = false")
    assert r["rows_affected"] == n // 2
    res = sess.execute("select count(*) as n from accounts")
    assert int(res["n"][0]) == n - n // 2
    # MVCC: deleted rows are tombstoned, not gone from history
    r = sess.execute("delete from accounts")
    res = sess.execute("select count(*) as n from accounts")
    assert int(res["n"][0]) == 0


def test_insert_select(sess):
    _setup_accounts(sess, n=10)
    sess.execute("create table rich (id int primary key, "
                 "balance decimal(12, 2))")
    r = sess.execute("insert into rich (id, balance) "
                     "select id, balance from accounts where balance > 105")
    assert r["rows_affected"] == 5
    res = sess.execute("select count(*) as n from rich")
    assert int(res["n"][0]) == 5


def test_ddl_errors(sess):
    with pytest.raises(BindError):
        sess.execute("create table t (a int, b int)")  # no pk
    sess.execute("create table t (a int primary key, b int)")
    with pytest.raises(BindError):
        sess.execute("create table t (a int primary key)")  # duplicate
    with pytest.raises(BindError):
        sess.execute("insert into t values (1)")  # arity
    with pytest.raises(BindError):
        sess.execute("update t set a = 5")  # pk update
    with pytest.raises(BindError):
        sess.execute("insert into missing values (1)")


def test_update_is_transactional(sess):
    """All-or-nothing: a failing write mid-transaction rolls back."""
    sess.execute("create table t (a int primary key, b int)")
    sess.execute("insert into t values (1, 10), (2, 20)")
    t = sess.catalog.tables["t"]
    orig = t.insert
    calls = {"n": 0}

    def flaky(txn, row):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("boom")
        return orig(txn, row)

    t.insert = flaky
    with pytest.raises(RuntimeError):
        sess.execute("update t set b = 0")
    t.insert = orig
    res = sess.execute("select b from t order by a")
    assert [int(v) for v in res["b"]] == [10, 20], "rollback must undo all"


def test_string_columns_roundtrip(sess):
    """STRING columns in KV tables: dictionary codes in the row payload,
    dictionary persisted in a companion key space of the same engine."""
    sess.execute("create table users (id int primary key, name string, "
                 "city text)")
    sess.execute("insert into users values (1, 'ada', 'london'), "
                 "(2, 'grace', 'nyc'), (3, 'ada', null)")
    res = sess.execute("select id, name, city from users order by id")
    assert list(res["name"]) == ["ada", "grace", "ada"]
    assert res["city"][2] is None
    # string predicates ride the dictionary machinery
    res = sess.execute("select id from users where name = 'ada' order by id")
    assert list(res["id"]) == [1, 3]
    res = sess.execute("select id from users where name like 'gr%'")
    assert list(res["id"]) == [2]
    # group by a string column
    res = sess.execute("select name, count(*) as n from users "
                       "group by name order by name")
    assert list(res["name"]) == ["ada", "grace"]
    assert [int(v) for v in res["n"]] == [2, 1]
    # update through the string path
    sess.execute("update users set city = 'paris' where id = 2")
    res = sess.execute("select city from users where id = 2")
    assert res["city"][0] == "paris"


def test_string_dictionary_survives_restore(sess):
    """The dictionary is data: rebuilding the KVTable over the same engine
    recovers codes from the companion span."""
    from cockroach_tpu.kv.table import KVTable

    sess.execute("create table t (id int primary key, tag string)")
    sess.execute("insert into t values (1, 'x'), (2, 'y'), (3, 'x')")
    old = sess.catalog.tables["t"]
    reopened = KVTable(sess.db, "t", old.schema, old.pk, old.table_id,
                       old.dict_table_id)
    assert reopened._dicts[1].values == ["x", "y"]
    assert reopened.get_row(3)["tag"] == "x"


def test_string_dictionary_rolls_back_with_txn(sess):
    """A txn that aborts must not leave the in-memory dictionary ahead of
    the engine's persistent companion span (codes are assigned pending and
    promoted only on commit)."""
    from cockroach_tpu.kv.table import KVTable

    sess.execute("create table t (id int primary key, tag string)")
    sess.execute("insert into t values (1, 'kept')")
    t = sess.catalog.tables["t"]

    def failing(txn):
        t.insert(txn, {"id": 2, "tag": "doomed"})
        raise RuntimeError("abort")

    with pytest.raises(RuntimeError):
        sess.db.txn(failing)
    # in-memory dictionary did NOT keep the aborted code
    assert t._dicts[1].values == ["kept"]
    # and a new insert re-assigns the code consistently with persistence
    sess.execute("insert into t values (3, 'doomed')")
    reopened = KVTable(sess.db, "t", t.schema, t.pk, t.table_id,
                       t.dict_table_id)
    assert reopened._dicts[1].values == ["kept", "doomed"]
    assert reopened.get_row(3)["tag"] == "doomed"


def test_cluster_settings_sql_surface(sess):
    """SET/SHOW CLUSTER SETTING (pkg/settings SQL surface)."""
    from cockroach_tpu.utils import settings

    try:
        r = sess.execute("set cluster setting sql.distsql.tile_size = 8192")
        assert r == {"set": "sql.distsql.tile_size"}
        assert settings.get("sql.distsql.tile_size") == 8192
        r = sess.execute("show cluster setting sql.distsql.tile_size")
        assert list(r["value"]) == ["8192"]
        r = sess.execute("show cluster settings")
        assert "sql.distsql.workmem_bytes" in list(r["variable"])
        with pytest.raises(BindError):
            sess.execute("set cluster setting nope.nope = 1")
    finally:
        settings.reset("sql.distsql.tile_size")


def test_backup_restore_sql_surface(tmp_path):
    """BACKUP TO / RESTORE FROM / SHOW JOBS through the session: state
    written after the backup disappears on restore (engine-checkpoint
    semantics), string dictionaries reload from the restored spans."""
    sess = Session(val_width=256)
    sess.execute("create table t (a int primary key, tag string)")
    sess.execute("insert into t values (1, 'keep'), (2, 'keep2')")
    path = str(tmp_path / "bk")
    r = sess.execute(f"backup to '{path}'")
    assert r["state"] == "succeeded"
    sess.execute("insert into t values (3, 'lost-after-restore')")
    assert int(sess.execute("select count(*) as n from t")["n"][0]) == 3

    r = sess.execute(f"restore from '{path}'")
    assert r["restored"] == path
    res = sess.execute("select a, tag from t order by a")
    assert list(res["a"]) == [1, 2]
    assert list(res["tag"]) == ["keep", "keep2"]

    jobs = sess.execute("show jobs")
    # the backup job record itself was part of the backed-up state
    assert "backup" in list(jobs["job_type"])


def test_catalog_descriptors_survive_restart(tmp_path):
    """Schemas are data: a FRESH session over the same engine (or a restored
    checkpoint) rediscovers tables from persisted descriptors — the
    system.descriptor / catalog-bootstrap discipline."""
    sess = Session(val_width=256)
    sess.execute("create table t (a int primary key, tag string)")
    sess.execute("insert into t values (1, 'x'), (2, 'y')")

    # restart: new Session over the same DB, empty catalog
    sess2 = Session(db=sess.db)
    res = sess2.execute("select a, tag from t order by a")
    assert list(res["a"]) == [1, 2] and list(res["tag"]) == ["x", "y"]
    sess2.execute("insert into t values (3, 'z')")

    # backup in one session, restore into a COMPLETELY fresh one
    path = str(tmp_path / "bk")
    sess2.execute(f"backup to '{path}'")
    fresh = Session(val_width=256)
    fresh.execute(f"restore from '{path}'")
    res = fresh.execute("select count(*) as n from t")
    assert int(res["n"][0]) == 3


def test_show_tables_and_columns(sess):
    sess.execute("create table t (a int primary key, b decimal(10, 2))")
    r = sess.execute("show tables")
    assert "t" in list(r["table_name"])
    r = sess.execute("show columns from t")
    assert list(r["column_name"]) == ["a", "b"]
    assert list(r["data_type"]) == ["INT64", "DECIMAL(10,2)"]
    with pytest.raises(BindError):
        sess.execute("show columns from nope")


# ---------------------------------------------------------------------------
# explicit transactions (BEGIN/COMMIT/ROLLBACK — the conn_executor txn FSM)


def test_txn_commit_makes_writes_visible(sess):
    sess.execute("CREATE TABLE a (k INT PRIMARY KEY, v INT)")
    sess.execute("BEGIN")
    sess.execute("INSERT INTO a VALUES (1, 10)")
    sess.execute("INSERT INTO a VALUES (2, 20)")
    # in-txn read sees own uncommitted writes
    r = sess.execute("SELECT v FROM a ORDER BY k")
    assert list(r["v"]) == [10, 20]
    # a second session hits the open txn's intents (reduced semantics:
    # conflict error rather than txn-push; the reference would block)
    from cockroach_tpu.storage import WriteIntentError

    other = Session(catalog=sess.catalog, db=sess.db)
    with pytest.raises(WriteIntentError):
        other.execute("SELECT v FROM a")
    assert sess.execute("COMMIT") == {"commit": True}
    assert list(other.execute("SELECT v FROM a ORDER BY k")["v"]) == [10, 20]


def test_txn_rollback_discards_writes(sess):
    sess.execute("CREATE TABLE b (k INT PRIMARY KEY, v INT)")
    sess.execute("INSERT INTO b VALUES (1, 1)")
    sess.execute("BEGIN")
    sess.execute("UPDATE b SET v = 99 WHERE k = 1")
    sess.execute("INSERT INTO b VALUES (2, 2)")
    assert sess.execute("ROLLBACK") == {"rollback": True}
    r = sess.execute("SELECT k, v FROM b")
    assert list(r["k"]) == [1] and list(r["v"]) == [1]


def test_txn_multi_statement_atomicity_over_conflict(sess):
    from cockroach_tpu.kv.txn import TransactionRetryError

    sess.execute("CREATE TABLE c (k INT PRIMARY KEY, v INT)")
    sess.execute("INSERT INTO c VALUES (1, 1)")
    sess.execute("BEGIN")
    sess.execute("UPDATE c SET v = 2 WHERE k = 1")
    # another session's write conflicts with the open txn's intent and
    # surfaces as the RETRYABLE error (the 40001 contract clients loop on)
    other = Session(catalog=sess.catalog, db=sess.db)
    with pytest.raises(TransactionRetryError):
        other.execute("UPDATE c SET v = 3 WHERE k = 1")
    # our txn still commits its atomic block
    assert sess.execute("COMMIT") == {"commit": True}
    assert list(sess.execute("SELECT v FROM c")["v"]) == [2]


def test_txn_aborted_state_discipline(sess):
    sess.execute("CREATE TABLE d (k INT PRIMARY KEY, v INT)")
    sess.execute("BEGIN")
    sess._txn_aborted = True  # simulate a mid-block retryable failure
    with pytest.raises(BindError, match="aborted"):
        sess.execute("SELECT * FROM d")
    # COMMIT of an aborted block rolls back
    assert sess.execute("COMMIT") == {"rollback": True}
    # session is usable again
    sess.execute("INSERT INTO d VALUES (1, 1)")
    assert list(sess.execute("SELECT v FROM d")["v"]) == [1]


def test_txn_begin_nesting_and_stray_end(sess):
    assert "warning" in sess.execute("COMMIT")
    assert "warning" in sess.execute("ROLLBACK")
    sess.execute("BEGIN")
    with pytest.raises(BindError, match="already a transaction"):
        sess.execute("BEGIN")
    sess.execute("ROLLBACK")


def test_txn_snapshot_isolation_for_reads(sess):
    sess.execute("CREATE TABLE e (k INT PRIMARY KEY, v INT)")
    sess.execute("INSERT INTO e VALUES (1, 1)")
    sess.execute("BEGIN")
    assert list(sess.execute("SELECT v FROM e")["v"]) == [1]
    # a concurrent committed write lands ABOVE our snapshot: not visible
    other = Session(catalog=sess.catalog, db=sess.db)
    other.execute("INSERT INTO e VALUES (2, 2)")
    assert list(sess.execute("SELECT v FROM e")["v"]) == [1]
    # the concurrent commit invalidated our read span: COMMIT surfaces the
    # retryable error (the client restarts the block)
    from cockroach_tpu.kv.txn import TransactionRetryError

    with pytest.raises(TransactionRetryError):
        sess.execute("COMMIT")
    assert sess._txn is None  # back to NoTxn either way
