"""SQL DDL/DML over the KV layer (Session): CREATE TABLE / INSERT / UPDATE /
DELETE round-trips through the MVCC engine and back out via SELECT.

Reference parity points: pkg/sql/conn_executor.go statement dispatch,
pkg/sql/insert.go KV-encoded writes, pkg/sql/parser/sql.y DML grammar."""

import numpy as np
import pytest

from cockroach_tpu.sql import BindError, Session


@pytest.fixture
def sess():
    return Session()


def _setup_accounts(sess, n=20):
    sess.execute("""
        create table accounts (
            id int primary key,
            balance decimal(12, 2),
            opened date,
            score float,
            active bool
        )
    """)
    rows = ", ".join(
        f"({i}, {100 + i}.50, date '2020-01-01', {i} * 0.5, "
        f"{'true' if i % 2 == 0 else 'false'})"
        for i in range(n)
    )
    r = sess.execute(f"insert into accounts values {rows}")
    assert r["rows_affected"] == n
    return n


def test_create_insert_select_roundtrip(sess):
    n = _setup_accounts(sess)
    res = sess.execute("select id, balance, active from accounts "
                       "where id < 5 order by id")
    assert list(res["id"]) == [0, 1, 2, 3, 4]
    np.testing.assert_allclose(
        np.asarray(res["balance"], dtype=np.float64),
        [100.5, 101.5, 102.5, 103.5, 104.5],
    )
    # aggregates run through the same engine
    res = sess.execute("select count(*) as n, sum(balance) as s "
                       "from accounts")
    assert int(res["n"][0]) == n


def test_insert_column_list_and_nulls(sess):
    sess.execute("create table t (id int primary key, x int, y float)")
    sess.execute("insert into t (id, x, y) values (1, null, 2.5), "
                 "(2, 7, null)")
    res = sess.execute("select id, x, y from t order by id")
    assert res["x"][0] is None and int(res["x"][1]) == 7
    assert float(res["y"][0]) == 2.5 and res["y"][1] is None
    # NULL never satisfies a comparison
    res = sess.execute("select id from t where x > 0")
    assert list(res["id"]) == [2]


def test_update_where(sess):
    _setup_accounts(sess)
    r = sess.execute(
        "update accounts set balance = balance + 10.00, score = 0.0 "
        "where id >= 15")
    assert r["rows_affected"] == 5
    res = sess.execute("select balance, score from accounts "
                       "where id = 17")
    np.testing.assert_allclose(float(res["balance"][0]), 117.5 + 10.0)
    assert float(res["score"][0]) == 0.0
    # untouched rows keep their versions
    res = sess.execute("select balance from accounts where id = 3")
    np.testing.assert_allclose(float(res["balance"][0]), 103.5)


def test_delete_where(sess):
    n = _setup_accounts(sess)
    r = sess.execute("delete from accounts where active = false")
    assert r["rows_affected"] == n // 2
    res = sess.execute("select count(*) as n from accounts")
    assert int(res["n"][0]) == n - n // 2
    # MVCC: deleted rows are tombstoned, not gone from history
    r = sess.execute("delete from accounts")
    res = sess.execute("select count(*) as n from accounts")
    assert int(res["n"][0]) == 0


def test_insert_select(sess):
    _setup_accounts(sess, n=10)
    sess.execute("create table rich (id int primary key, "
                 "balance decimal(12, 2))")
    r = sess.execute("insert into rich (id, balance) "
                     "select id, balance from accounts where balance > 105")
    assert r["rows_affected"] == 5
    res = sess.execute("select count(*) as n from rich")
    assert int(res["n"][0]) == 5


def test_ddl_errors(sess):
    with pytest.raises(BindError):
        sess.execute("create table t (a int, b int)")  # no pk
    sess.execute("create table t (a int primary key, b int)")
    with pytest.raises(BindError):
        sess.execute("create table t (a int primary key)")  # duplicate
    with pytest.raises(BindError):
        sess.execute("insert into t values (1)")  # arity
    with pytest.raises(BindError):
        sess.execute("update t set a = 5")  # pk update
    with pytest.raises(BindError):
        sess.execute("insert into missing values (1)")


def test_update_is_transactional(sess):
    """All-or-nothing: a failing write mid-transaction rolls back."""
    sess.execute("create table t (a int primary key, b int)")
    sess.execute("insert into t values (1, 10), (2, 20)")
    t = sess.catalog.tables["t"]
    orig = t.insert
    calls = {"n": 0}

    def flaky(txn, row):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("boom")
        return orig(txn, row)

    t.insert = flaky
    with pytest.raises(RuntimeError):
        sess.execute("update t set b = 0")
    t.insert = orig
    res = sess.execute("select b from t order by a")
    assert [int(v) for v in res["b"]] == [10, 20], "rollback must undo all"
