"""ALTER TABLE schema changes: backfill job, checkpointed resume, swap."""


from cockroach_tpu.sql.session import Session


def _mk(n=50):
    sess = Session()
    sess.execute("create table sc (id int primary key, a int, s string)")
    sess.execute("insert into sc values " + ", ".join(
        f"({i}, {i * 2}, 's{i % 3}')" for i in range(n)))
    return sess


def test_add_column_with_default_backfills():
    sess = _mk()
    res = sess.execute("alter table sc add column b int default 7")
    assert "altered" in res
    got = sess.execute("select count(*) as n, sum(b) as sb from sc")
    assert int(got["n"][0]) == 50 and int(got["sb"][0]) == 350
    # new writes fill the new layout; selects mix old+new rows fine
    sess.execute("insert into sc values (100, 1, 'x', 9)")
    got = sess.execute("select b from sc where id = 100")
    assert list(got["b"]) == [9]
    got = sess.execute("select b from sc where id = 3")
    assert list(got["b"]) == [7]


def test_add_column_null_default():
    sess = _mk()
    sess.execute("alter table sc add column c float")
    got = sess.execute("select count(c) as n from sc")
    assert int(got["n"][0]) == 0  # all NULL
    sess.execute("update sc set c = 1.5 where id < 10")
    got = sess.execute("select count(c) as n from sc")
    assert int(got["n"][0]) == 10


def test_drop_column():
    sess = _mk()
    sess.execute("alter table sc drop column a")
    cols = sess.execute("show columns from sc")
    assert list(cols["column_name"]) == ["id", "s"]
    got = sess.execute("select s, count(*) as n from sc group by s order by s")
    assert list(got["n"]) == [17, 17, 16]
    # the dropped column is gone from SELECT *
    star = sess.execute("select * from sc where id = 1")
    assert set(star.keys()) == {"id", "s"}


def test_alter_errors():
    sess = _mk()
    for stmt, frag in [
        ("alter table sc drop column id", "PRIMARY KEY"),
        ("alter table sc add column a int", "already exists"),
        ("alter table sc drop column nope", "unknown column"),
        ("alter table nope add column x int", "unknown table"),
        ("alter table sc add column y int not null", "DEFAULT"),
    ]:
        try:
            sess.execute(stmt)
            raise AssertionError(f"expected error for {stmt}")
        except Exception as e:  # noqa: BLE001
            assert frag in str(e), (stmt, e)


def test_backfill_resumes_from_checkpoint():
    """Kill the backfill mid-run (fault injection on the registry
    checkpoint); a fresh resume completes from the checkpoint without
    double-applying, and the descriptor swaps only at the end."""
    from cockroach_tpu.sql import schemachange as sc_mod
    from cockroach_tpu.sql.schemachange import register_schema_change_job

    sess = _mk(n=900)  # > CHUNK_ROWS so several chunks run
    reg = sess._jobs_registry()
    register_schema_change_job(reg, sess.catalog)
    payload = sc_mod.plan_alter(
        sess.catalog, sess.db,
        __import__("cockroach_tpu.sql.parser", fromlist=["x"])
        .parse_statement("alter table sc add column b int default 5"),
    )
    job = reg.create("schema_change", payload)

    class Boom(Exception):
        pass

    real_checkpoint = reg.checkpoint
    calls = {"n": 0}

    def crashing_checkpoint(j):
        real_checkpoint(j)
        calls["n"] += 1
        if calls["n"] == 1:
            raise Boom("crash after first chunk checkpoint")

    # phase 1: a PROCESS CRASH mid-backfill — drive the resumer directly
    # so the exception escapes without the registry's failure markup
    # (adopt_and_resume would mark a raising resumer as failed, which is
    # the crash-free error path, not a crash)
    claimed = reg._claim(job.job_id, reg.load(job.job_id))
    assert claimed is not None and claimed.state == "running"
    reg.checkpoint = crashing_checkpoint
    try:
        sc_mod.backfill(reg, claimed, sess.catalog)
        raise AssertionError("expected the injected crash")
    except Boom:
        pass
    reg.checkpoint = real_checkpoint
    # mid-change: catalog still serves the OLD schema
    assert "b" not in sess.catalog.tables["sc"].schema.names
    saved = reg.load(job.job_id)
    assert saved.progress.get("last_pk") is not None
    # resume completes (idempotently re-scanning the boundary chunk)
    done = reg.adopt_and_resume(job.job_id)
    assert done.state == "succeeded"
    assert "b" in sess.catalog.tables["sc"].schema.names
    got = sess.execute("select count(*) as n, sum(b) as sb from sc")
    assert int(got["n"][0]) == 900 and int(got["sb"][0]) == 4500


def test_add_string_column_with_default():
    """The default string is dictionary-encoded (code 0 in the new
    column's span), old rows backfill to it, and new inserts share the
    dictionary."""
    sess = Session()
    sess.execute("create table st (id int primary key, a int)")
    sess.execute("insert into st values (1, 10), (2, 20)")
    sess.execute("alter table st add column tag string default 'blue'")
    got = sess.execute("select tag, count(*) as n from st group by tag")
    assert list(got["tag"]) == ["blue"] and list(got["n"]) == [2]
    sess.execute("insert into st values (3, 30, 'red')")
    got = sess.execute(
        "select tag, count(*) as n from st group by tag order by tag")
    assert list(got["tag"]) == ["blue", "red"]
    assert list(got["n"]) == [2, 1]
    # nullable string add without default: NULLs
    sess.execute("alter table st add column note string")
    got = sess.execute("select count(note) as n from st")
    assert int(got["n"][0]) == 0


def test_drop_resume_does_not_corrupt_surviving_string_dict():
    """Crash between the dict-span remap and the descriptor swap: the
    resume must NOT re-run the remap (it would delete the already-moved
    entries of the surviving string column)."""
    from cockroach_tpu.sql import schemachange as sc_mod
    from cockroach_tpu.sql.parser import parse_statement
    from cockroach_tpu.sql.schemachange import register_schema_change_job

    sess = Session()
    sess.execute(
        "create table dm (id int primary key, a string, b string)")
    sess.execute("insert into dm values (1, 'x', 'p'), (2, 'y', 'q')")
    reg = sess._jobs_registry()
    register_schema_change_job(reg, sess.catalog)
    payload = sc_mod.plan_alter(
        sess.catalog, sess.db,
        parse_statement("alter table dm drop column a"))
    job = reg.create("schema_change", payload)
    claimed = reg._claim(job.job_id, reg.load(job.job_id))

    # crash INSIDE _swap_descriptor right after the remap txn committed
    class Boom(Exception):
        pass

    import cockroach_tpu.kv.table as table_mod

    real_wd = table_mod.write_descriptor
    try:
        def crashing_wd(db, t, writer=None):
            raise Boom

        table_mod.write_descriptor = crashing_wd
        try:
            sc_mod.backfill(reg, claimed, sess.catalog)
            raise AssertionError("expected injected crash")
        except Boom:
            pass
    finally:
        table_mod.write_descriptor = real_wd
    # remap committed + flagged; resume completes without re-remapping
    assert reg.load(job.job_id).progress.get("dict_remapped") is True
    done = reg.adopt_and_resume(job.job_id)
    assert done.state == "succeeded"
    got = sess.execute("select b from dm order by id")
    assert list(got["b"]) == ["p", "q"]  # survivor's dictionary intact


def test_string_agg_downstream_guards():
    """Consumers whose plan depends on a string_agg output's dictionary
    refuse loudly instead of silently sorting/grouping garbage."""
    sess = Session()
    sess.execute("create table gg (id int primary key, g int, s string)")
    sess.execute("insert into gg values (1, 1, 'a'), (2, 1, 'b'), (3, 2, 'c')")
    # plain string_agg works
    got = sess.execute(
        "select g, string_agg(s, ',') as x from gg group by g")
    assert sorted(got["x"]) == ["a,b", "c"]
    try:
        sess.execute(
            "select g, string_agg(s, ',') as x from gg group by g "
            "order by x")
        raise AssertionError("expected ORDER BY string_agg to be refused")
    except Exception as e:  # noqa: BLE001
        assert "string_agg" in str(e)
