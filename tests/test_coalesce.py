"""Inter-query KV batching tests (kv/coalesce.py).

The PR-19 acceptance sweep for the commit train: concurrent mixed DML
through the coalescer must be bit-identical to the solo per-session
path (values, tombstones, typed errors — everything except the
timestamps a different interleaving necessarily stamps), a single-op
train must take the solo engine path, lock conflicts must demux to
exactly the conflicting session, and the group-commit WAL pipeline
(apply with fsync deferred, sync outside the engine mutex) must
survive a restart with every acked write present."""

import threading

import pytest

from cockroach_tpu.kv import DB
from cockroach_tpu.storage.lsm import Engine, WriteIntentError
from cockroach_tpu.utils import metric, settings


@pytest.fixture
def _gate():
    """Coalescing on for the test body, always restored."""
    settings.set("kv.batch.coalesce.enabled", True)
    yield
    settings.reset("kv.batch.coalesce.enabled")


def _fresh_db(tmp_path=None, name="wal.log") -> DB:
    if tmp_path is None:
        return DB(Engine())
    return DB(Engine(wal_path=str(tmp_path / name), wal_fsync=True))


def _thread_script(tid: int, n: int):
    """Deterministic per-thread op tape over a thread-private keyspace
    (disjoint keys: the interleaving cannot change any thread's view)."""
    ops = []
    for i in range(n):
        k = f"t{tid}-k{i % 8}"
        if i % 5 == 4:
            ops.append(("delete", k, None))
        elif i % 3 == 2:
            ops.append(("get", k, None))
        else:
            ops.append(("put", k, f"v{tid}.{i}"))
    return ops


def _run_script(db: DB, ops, outcomes: list) -> None:
    for kind, k, v in ops:
        if kind == "put":
            outcomes.append(("put", k, db.put(k, v)))
        elif kind == "delete":
            outcomes.append(("delete", k, db.delete(k)))
        else:
            outcomes.append(("get", k, db.get(k)))


def _state(db: DB) -> dict:
    return {k: v for k, v in db.scan(None, None)}


def test_concurrent_mixed_dml_bit_identical(_gate):
    """8 sessions of mixed put/delete/get through the coalescer leave the
    SAME visible state and per-thread get values as the same tapes run
    solo — merging must be invisible to every rider."""
    threads = 8
    scripts = [_thread_script(t, 60) for t in range(threads)]

    db = _fresh_db()
    outs = [[] for _ in range(threads)]
    errs = []

    def worker(t):
        try:
            _run_script(db, scripts[t], outs[t])
        except Exception as e:  # pragma: no cover - fail loudly below
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errs, errs

    # oracle: the same tapes, solo path, no concurrency
    settings.reset("kv.batch.coalesce.enabled")
    solo = _fresh_db()
    solo_outs = [[] for _ in range(threads)]
    for t in range(threads):
        _run_script(solo, scripts[t], solo_outs[t])

    assert _state(db) == _state(solo)
    for t in range(threads):
        for (k1, key1, r1), (k2, key2, r2) in zip(outs[t], solo_outs[t]):
            assert (k1, key1) == (k2, key2)
            if k1 == "get":
                # keys are thread-private, so get values are deterministic
                assert r1 == r2, (key1, r1, r2)
            else:
                # write timestamps come from clock.now() under the engine
                # mutex in both modes; values differ across runs but the
                # type/shape contract must not
                assert isinstance(r1, int) and isinstance(r2, int)


def test_metric_counts_merged_ops(_gate):
    """kv_batch_coalesced counts riders only when a train actually merged
    (a sequential caller is a train of one and never counts)."""
    db = _fresh_db()
    before = metric.KV_BATCH_COALESCED.value
    db.put("seq-a", "1")
    db.put("seq-b", "2")
    assert metric.KV_BATCH_COALESCED.value == before

    # deterministic merge: hold the engine mutex so the first submitter
    # leads a train that blocks mid-flush; everyone arriving meanwhile
    # boards the NEXT train, which is guaranteed to merge
    import time as _time

    def worker(i):
        db.put(f"m{i}", "x")

    with db.engine.mu:
        leader = threading.Thread(target=worker, args=(0,))
        leader.start()
        _time.sleep(0.1)  # leader is parked on engine.mu inside its flush
        riders = [threading.Thread(target=worker, args=(i,))
                  for i in range(1, 4)]
        for t in riders:
            t.start()
        _time.sleep(0.1)  # riders boarded behind the in-flight train
    leader.join(30)
    for t in riders:
        t.join(30)
    # the rider train merged 3 ops; each merged train increments by its
    # full rider count
    assert metric.KV_BATCH_COALESCED.value >= before + 3
    for i in range(4):
        assert db.get(f"m{i}") == b"x"


def test_write_intent_demuxes_to_conflicting_session_only(_gate):
    """A coalesced train carrying one locked key raises WriteIntentError
    in exactly that session; innocent riders of the same train commit."""
    db = _fresh_db()
    # lay a foreign intent the way a live txn would (lock table entry)
    with db.engine.mu:
        db.engine.put(b"locked", b"i", ts=db.clock.now(), txn=42)

    results = {}
    barrier = threading.Barrier(2)

    def conflicting():
        barrier.wait()
        try:
            db.put("locked", "v")
            results["conflict"] = "committed"
        except WriteIntentError:
            results["conflict"] = "typed"

    def innocent():
        barrier.wait()
        results["innocent"] = db.put("innocent", "v")

    ts = [threading.Thread(target=conflicting),
          threading.Thread(target=innocent)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert results["conflict"] == "typed"
    assert isinstance(results["innocent"], int)
    assert db.get("innocent") == b"v"


def test_max_ops_chunking_still_applies_everything(_gate):
    """Trains past kv.batch.coalesce.max_ops chunk into more batches,
    never drop or error."""
    settings.set("kv.batch.coalesce.max_ops", 2)
    try:
        db = _fresh_db()
        n, barrier = 6, threading.Barrier(6)
        errs = []

        def worker(i):
            barrier.wait()
            try:
                for j in range(10):
                    db.put(f"c{i}-{j}", f"{i}.{j}")
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs
        assert len(_state(db)) == 60
    finally:
        settings.reset("kv.batch.coalesce.max_ops")


def test_group_commit_wal_replay_has_every_acked_write(tmp_path, _gate):
    """The pipelined path (apply under mu with sync=False, fsync outside)
    must leave a WAL that replays every acked write after a restart —
    the durability contract is exactly the solo path's."""
    db = _fresh_db(tmp_path)
    n, barrier = 6, threading.Barrier(6)
    acked = []
    mu = threading.Lock()

    def worker(i):
        barrier.wait()
        got = []
        for j in range(15):
            k = f"w{i}-{j}"
            db.put(k, f"{i}.{j}")
            got.append(k)
        with mu:
            acked.extend(got)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert len(acked) == 90
    db.engine.close()

    # restart: a fresh engine over the same WAL
    reopened = DB(Engine(wal_path=str(tmp_path / "wal.log"),
                         wal_fsync=True))
    state = _state(reopened)
    for i in range(n):
        for j in range(15):
            assert state.get(f"w{i}-{j}".encode()) == f"{i}.{j}".encode()
    reopened.engine.close()


def test_gate_off_never_attaches():
    """With the gate off the DB takes the solo path and no coalescer is
    ever attached (zero overhead for existing deployments)."""
    db = _fresh_db()
    db.put("a", "1")
    assert db.get("a") == b"1"
    assert getattr(db, "_coalescer", None) is None
