"""KV Batch RPC service (Internal.Batch reduction)."""

import threading

from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.rpc import BatchClient, BatchServer
from cockroach_tpu.storage.lsm import Engine, WriteIntentError


def _srv():
    db = DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())
    return db, BatchServer(db)


def test_batch_roundtrip_and_ordering():
    db, srv = _srv()
    try:
        c = BatchClient(srv.addr)
        # one batch, ordered evaluation: put then read-your-write
        resp = c.batch([
            {"op": "put", "key": _e(b"a"), "value": _e(b"1")},
            {"op": "put", "key": _e(b"b"), "value": _e(b"\x00\xff")},
            {"op": "get", "key": _e(b"a")},
        ])
        assert _d(resp[2]["value"]) == b"1"
        assert c.get(b"b") == b"\x00\xff"  # byte-exact
        # server-side data is the same DB
        assert db.get(b"a") == b"1"
        # scans with limits
        c.put(b"c", b"3")
        assert c.scan(b"a", b"z", max_keys=2) == [(b"a", b"1"),
                                                  (b"b", b"\x00\xff")]
        # historical read at the put's timestamp
        ts1 = c.put(b"h", b"old")
        c.put(b"h", b"new")
        assert c.get(b"h", ts=ts1) == b"old"
        assert c.get(b"h") == b"new"
        # delete
        c.delete(b"a")
        assert c.get(b"a") is None
        c.close()
    finally:
        srv.close()


def test_typed_errors_and_concurrent_clients():
    db, srv = _srv()
    try:
        # a live intent surfaces as WriteIntentError (retryable), not a
        # generic failure, and does not kill the connection
        t = db.new_txn()
        t.put(b"locked", b"x")
        c = BatchClient(srv.addr)
        try:
            c.get(b"locked")
            raise AssertionError("expected WriteIntentError")
        except WriteIntentError:
            pass
        t.commit()
        assert c.get(b"locked") == b"x"  # same connection still works

        # unknown op: typed Internal error, connection survives
        try:
            c.batch([{"op": "nope"}])
            raise AssertionError("expected error")
        except RuntimeError as e:
            assert "unknown batch op" in str(e)
        assert c.get(b"locked") == b"x"

        # concurrent clients hammer the same server
        errs = []

        def worker(i):
            try:
                cc = BatchClient(srv.addr)
                for j in range(20):
                    cc.put(b"w%d-%02d" % (i, j), b"v%d" % j)
                got = cc.scan(b"w%d-" % i, b"w%d~" % i)
                assert len(got) == 20, got
                cc.close()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ths = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        assert not errs, errs[:2]
        c.close()
    finally:
        srv.close()


def _e(b: bytes) -> str:
    import base64

    return base64.b64encode(b).decode()


def _d(s: str) -> bytes:
    import base64

    return base64.b64decode(s)


def test_node_serves_kv_rpc():
    from cockroach_tpu.server.node import Node

    node = Node(node_id=9, heartbeat_interval_s=0.1, ttl_ms=30000)
    node.start(gossip_port=None, kv_port=0)
    try:
        c = BatchClient(node.kv_rpc.addr)
        c.put(b"nk", b"nv")
        assert c.get(b"nk") == b"nv"
        assert node.db.get(b"nk") == b"nv"
        c.close()
    finally:
        node.stop()


def test_close_severs_established_connections():
    db, srv = _srv()
    c = BatchClient(srv.addr)
    c.put(b"x", b"1")
    srv.close()
    try:
        c.put(b"y", b"2")
        raise AssertionError("expected the severed connection to fail")
    except (ConnectionError, OSError, RuntimeError):
        pass
    assert db.get(b"y") is None  # nothing landed after close


def test_intent_error_carries_real_keys():
    db, srv = _srv()
    try:
        t = db.new_txn()
        t.put(b"contended", b"x")
        c = BatchClient(srv.addr)
        try:
            c.get(b"contended")
            raise AssertionError("expected WriteIntentError")
        except WriteIntentError as e:
            assert e.keys == [b"contended"]
            assert e.txns and e.txns[0] != 0
        t.rollback()
        c.close()
    finally:
        srv.close()


def test_node_dialer_resolves_through_gossip():
    """nodedialer role: two nodes gossip their KV endpoints; each dials
    the other BY NODE ID and reads/writes its store; a restart with a new
    port re-advertises and the dialer reconnects."""
    import time

    from cockroach_tpu.server.node import Node

    n1 = Node(node_id=1, heartbeat_interval_s=0.1, ttl_ms=30000)
    n1.start(gossip_port=0, kv_port=0)
    n2 = Node(node_id=2, heartbeat_interval_s=0.1, ttl_ms=30000,
              gossip_peers=[n1.gossip_addr()])
    n2.start(gossip_port=0, kv_port=0)
    try:
        # wait for address propagation both ways
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                n1.dialer.resolve(2)
                n2.dialer.resolve(1)
                break
            except KeyError:
                time.sleep(0.05)
        c12 = n1.dialer.dial(2)
        c12.put(b"from1", b"hello2")
        assert n2.db.get(b"from1") == b"hello2"
        c21 = n2.dialer.dial(1)
        c21.put(b"from2", b"hello1")
        assert n1.db.get(b"from2") == b"hello1"
        # cached: same client object on re-dial
        assert n1.dialer.dial(2) is c12

        # node 2's endpoint "restarts" on a new port and re-advertises
        from cockroach_tpu.kv.dialer import advertise
        from cockroach_tpu.kv.rpc import BatchServer

        old = n2.kv_rpc
        n2.kv_rpc = BatchServer(n2.db, port=0)
        old.close()
        advertise(n2.gossip, 2, n2.kv_rpc.addr)
        deadline = time.time() + 5
        while time.time() < deadline:
            if tuple(n1.gossip.get_info("node/2/kv") or ()) == tuple(
                n2.kv_rpc.addr
            ):
                break
            time.sleep(0.05)
        c12b = n1.dialer.dial(2)  # address changed: fresh connection
        assert c12b is not c12
        c12b.put(b"after", b"restart")
        assert n2.db.get(b"after") == b"restart"
    finally:
        n1.stop()
        n2.stop()


def test_circuit_breaker_trips_fastfails_and_recovers():
    """Per-peer breaker (rpc peer-tracking reduction): consecutive dial
    failures trip it, an open breaker fast-fails without touching the
    network, and the post-cooldown half-open probe closes it when the
    peer returns."""
    import time as _time

    from cockroach_tpu.flow.gossip import Gossip
    from cockroach_tpu.kv.dialer import (
        BreakerOpenError,
        NodeDialer,
        advertise,
    )

    g = Gossip(99)
    db, srv = _srv()
    advertise(g, 7, srv.addr)
    dialer = NodeDialer(g, trip_threshold=2, cooldown_s=0.4)
    # healthy: dial works
    c = dialer.dial(7)
    c.put(b"cb", b"1")
    dialer.report_ok(7)
    # peer dies: REPORTED RPC failures trip the breaker (connect alone
    # can neither trip nor reset it — a wedged peer may accept connects)
    srv.close()
    dialer.forget(7)
    for _ in range(2):
        failed = False
        try:
            cc = dialer.dial(7)
            cc.put(b"x", b"y")  # conn to a closed server fails here
        except BreakerOpenError:
            raise AssertionError("breaker tripped too early")
        except (ConnectionError, OSError, RuntimeError):
            failed = True
            dialer.report_failure(7)
        assert failed, "expected failure against dead peer"
    assert dialer.breaker_open(7)
    # open: fast-fail, no network attempt
    try:
        dialer.dial(7)
        raise AssertionError("expected BreakerOpenError")
    except BreakerOpenError:
        pass
    # peer returns on a new port; after the cooldown the half-open probe
    # succeeds and the breaker closes
    from cockroach_tpu.kv.rpc import BatchServer

    srv2 = BatchServer(db, port=0)
    advertise(g, 7, srv2.addr)
    _time.sleep(0.45)
    c2 = dialer.dial(7)  # the probe
    c2.put(b"cb2", b"2")
    dialer.report_ok(7)
    assert not dialer.breaker_open(7)
    assert db.get(b"cb2") == b"2"
    srv2.close()
    g.close()
