"""TPC-C over the transactional KV layer: the full five-transaction spec
mix (NewOrder/Payment/OrderStatus/Delivery/StockLevel) as multi-statement
SQL transactions with the 3.3.2-style consistency invariants
(reference: pkg/workload/tpcc + roachtest's tpcc check)."""

import pytest

from cockroach_tpu.bench import tpcc
from cockroach_tpu.sql import Session


@pytest.fixture
def sess():
    s = Session(val_width=256)
    tpcc.load(s, warehouses=2, districts=4, customers=6, items=20)
    return s


@pytest.mark.slow
def test_new_order_allocates_sequential_ids(sess):
    ids = [tpcc.new_order(sess, 1, 2, 3, ol_cnt=5, entry_day=20000 + i,
                          items=20)
           for i in range(4)]
    assert ids == [1, 2, 3, 4], "district cursor must allocate sequentially"
    # another district's cursor is independent
    assert tpcc.new_order(sess, 2, 1, 1, 5, 20010, items=20) == 1
    tpcc.check_consistency(sess, warehouses=2, districts=4)


def test_payment_maintains_w_ytd_invariant(sess):
    for i in range(6):
        tpcc.payment(sess, 1 + i % 2, 1 + i % 4, 1 + i % 6,
                     amount_cents=1000 * (i + 1))
    tpcc.check_consistency(sess, warehouses=2, districts=4)
    # the customer leg: initial sum (48 x 10.00) + injected payments
    # (1000..6000 cents = 210.00 dollars), exactly
    res = sess.execute(
        "select sum(c_ytd_payment) as s from customer")
    assert abs(float(res["s"][0]) - (2 * 4 * 6 * 10.0 + 210.0)) < 1e-6


@pytest.mark.slow
def test_delivery_pops_oldest_and_credits_customer(sess):
    # three orders in district (1,1) for customer 2; one in (1,2)
    for i in range(3):
        tpcc.new_order(sess, 1, 1, 2, ol_cnt=4, entry_day=20000 + i,
                       items=20)
    tpcc.new_order(sess, 1, 2, 5, ol_cnt=3, entry_day=20010, items=20)
    bal0 = float(sess.execute(
        "select c_balance from customer where c_pk = 1010002"
    )["c_balance"][0])
    n = tpcc.delivery(sess, 1, carrier_id=7, delivery_day=20020,
                      districts=4)
    assert n == 2, "one delivery per non-empty district queue"
    # oldest order of (1,1) delivered: carrier stamped, queue popped
    o1 = 101 * 1000000 + 1
    row = sess.execute(
        f"select o_carrier_id, o_total from orders where o_pk = {o1}")
    assert int(row["o_carrier_id"][0]) == 7
    left = sess.execute(
        "select count(*) as n from new_order where no_w_id = 1 "
        "and no_d_id = 1")
    assert int(left["n"][0]) == 2, "two undelivered orders remain"
    # customer credited exactly the order total
    bal1 = float(sess.execute(
        "select c_balance from customer where c_pk = 1010002"
    )["c_balance"][0])
    assert abs((bal1 - bal0) - float(row["o_total"][0])) < 1e-6
    # order lines stamped with the delivery day
    lr = sess.execute(
        f"select min(ol_delivery_d) as lo, max(ol_delivery_d) as hi "
        f"from order_line where ol_o_pk = {o1}")
    assert int(lr["lo"][0]) == 20020 and int(lr["hi"][0]) == 20020
    tpcc.check_consistency(sess, warehouses=2, districts=4)


@pytest.mark.slow
def test_stock_level_counts_low_stock_items(sess):
    for i in range(5):
        tpcc.new_order(sess, 1, 3, 1, ol_cnt=8, entry_day=20000 + i,
                       items=20)
    # threshold above the start quantity counts every ordered item;
    # threshold 0 counts none
    n_all = tpcc.stock_level(sess, 1, 3, threshold=tpcc.STOCK_START + 100)
    n_none = tpcc.stock_level(sess, 1, 3, threshold=0)
    assert n_none == 0
    distinct = sess.execute(
        "select count(*) as n from "
        "(select distinct ol_i_id from order_line where ol_d_id = 3)")
    assert n_all == int(distinct["n"][0]) > 0


def test_order_status_reads_latest_order(sess):
    tpcc.new_order(sess, 2, 2, 4, ol_cnt=6, entry_day=20000, items=20)
    tpcc.new_order(sess, 2, 2, 4, ol_cnt=9, entry_day=20001, items=20)
    st = tpcc.order_status(sess, 2, 2, 4)
    assert st["latest_o_id"] == 2 and st["latest_lines"] == 9


@pytest.mark.slow
def test_full_mix_and_invariants(sess):
    out = tpcc.run_mix(sess, txns=30, warehouses=2, districts=4,
                       customers=6, items=20)
    assert out["new_orders"] > 0 and out["txns"] == 30
    assert out["tpmC"] > 0
    # all five transaction types exercised across the mix (seeded)
    assert sum(out["counts"].values()) == 30 - out["give_ups"]
    tpcc.check_consistency(sess, warehouses=2, districts=4)
    # order totals queryable through SQL
    res = sess.execute(
        "select count(*) as n, sum(o_total) as s from orders")
    assert int(res["n"][0]) == out["new_orders"]
