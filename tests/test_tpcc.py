"""TPC-C (reduced) over the transactional KV layer: NewOrder/Payment as
multi-statement transactions with the 3.3.2-style consistency invariants
(reference: pkg/workload/tpcc + roachtest's tpcc check)."""

import numpy as np
import pytest

from cockroach_tpu.bench import tpcc
from cockroach_tpu.sql import Session


@pytest.fixture
def sess():
    s = Session(val_width=256)
    tpcc.load(s, warehouses=2, districts=4, customers=6)
    return s


def test_new_order_allocates_sequential_ids(sess):
    ids = [tpcc.new_order(sess, 1, 2, 3, ol_cnt=5, entry_day=20000 + i)
           for i in range(4)]
    assert ids == [1, 2, 3, 4], "district cursor must allocate sequentially"
    # another district's cursor is independent
    assert tpcc.new_order(sess, 2, 1, 1, 5, 20010) == 1
    tpcc.check_consistency(sess, warehouses=2, districts=4)


def test_payment_maintains_w_ytd_invariant(sess):
    for i in range(6):
        tpcc.payment(sess, 1 + i % 2, 1 + i % 4, 1 + i % 6,
                     amount_cents=1000 * (i + 1))
    tpcc.check_consistency(sess, warehouses=2, districts=4)
    # the customer leg: initial sum (48 x 10.00) + injected payments
    # (1000..6000 cents = 210.00 dollars), exactly
    res = sess.execute(
        "select sum(c_ytd_payment) as s from customer")
    assert abs(float(res["s"][0]) - (2 * 4 * 6 * 10.0 + 210.0)) < 1e-6


def test_mix_and_invariants(sess):
    out = tpcc.run_mix(sess, txns=30, warehouses=2, districts=4,
                       customers=6)
    assert out["new_orders"] > 0 and out["txns"] == 30
    tpcc.check_consistency(sess, warehouses=2, districts=4)
    # order totals queryable through SQL
    res = sess.execute(
        "select count(*) as n, sum(o_total) as s from orders")
    assert int(res["n"][0]) == out["new_orders"]
