"""Ordered (sort-free) aggregation over clustered scans — the colexec
orderedAggregator specialization (reference: pkg/sql/colexec/
ordered_aggregator.go). Parity vs the general sort path and the plan-level
clustering detection."""

import numpy as np
import pytest

from cockroach_tpu.catalog import Catalog, Table
from cockroach_tpu.coldata.types import INT64, STRING, Schema
from cockroach_tpu.plan import builder as plan_builder
from cockroach_tpu.sql.rel import Rel


def _clustered_cat(rng, n=5000, groups=700, with_null=True):
    """A fact table clustered by grp (equal keys adjacent, like TPC-H
    lineitem by l_orderkey), with NULLs in the value column. Group ids
    are SPARSE over a huge range so the planner's dense-scatter path
    (bounded key spaces) stays out and the general aggregate — where the
    ordered specialization lives — is what's under test."""
    sizes = rng.integers(1, 12, groups)
    grp = np.repeat(
        rng.permutation(groups).astype(np.int64) * 12_345_678 + 10, sizes
    )[:n]
    n = len(grp)
    val = rng.integers(-50, 50, n).astype(np.int64)
    valid = rng.random(n) > 0.1 if with_null else np.ones(n, bool)
    cat = Catalog()
    cat.add(Table.from_strings(
        "fact",
        Schema.of(grp=INT64, val=INT64, tag=STRING),
        {
            "grp": grp,
            "val": val,
            "tag": np.array(["abcdef"[int(x) % 6] for x in grp],
                            dtype=object),
        },
        valids={"val": valid},
        ordering=("grp",),
    ))
    return cat, grp, val, valid


@pytest.mark.parametrize("seed", [0, 1])
def test_ordered_agg_matches_oracle(rng, seed):
    rng = np.random.default_rng(seed)
    cat, grp, val, valid = _clustered_cat(rng)
    r = Rel.scan(cat, "fact", ("grp", "val"))
    g = r.groupby(["grp"], [("s", "sum", "val"), ("c", "count", "val"),
                            ("mn", "min", "val"), ("mx", "max", "val")])
    # detection: pure scan chain -> ordered AND prefix-live
    op = plan_builder.build(g.plan, cat)
    assert getattr(op, "ordered", False), type(op).__name__
    assert getattr(op, "prefix_live", False)
    got = g.sort([("grp", False)]).run()

    import pandas as pd

    df = pd.DataFrame({"grp": grp, "val": np.where(valid, val, np.nan)})
    g = df.groupby("grp").val
    # SQL semantics: sum/min/max over an all-NULL group are NULL (pandas
    # sum would say 0 — min_count=1 restores the SQL answer)
    want = pd.DataFrame({
        "s": g.sum(min_count=1), "c": g.count(),
        "mn": g.min(), "mx": g.max(),
    }).reset_index().sort_values("grp")

    def col(series):
        return [None if pd.isna(x) else int(x) for x in series]

    np.testing.assert_array_equal(np.asarray(got["grp"]), want.grp)
    for name in ("s", "c", "mn", "mx"):
        a = [None if x is None else int(x) for x in got[name]]
        assert a == col(want[name]), name


def test_ordered_agg_with_filter_compacts(rng):
    """A filter below the aggregate interleaves dead rows: the ordered path
    must still group correctly (compaction sort) and detection must report
    prefix_live=False."""
    cat, grp, val, valid = _clustered_cat(rng, with_null=False)
    r = Rel.scan(cat, "fact", ("grp", "val"))
    from cockroach_tpu.ops import expr as ex

    f = r.filter(ex.Cmp("gt", r.c("val"), ex.lit(0)))
    g = f.groupby(["grp"], [("s", "sum", "val")])
    op = plan_builder.build(g.plan, cat)
    assert getattr(op, "ordered", False)
    assert not getattr(op, "prefix_live", True)
    got = g.sort([("grp", False)]).run()

    import pandas as pd

    df = pd.DataFrame({"grp": grp, "val": val})
    df = df[df.val > 0]
    want = df.groupby("grp").val.sum().reset_index().sort_values("grp")
    np.testing.assert_array_equal(np.asarray(got["grp"]), want.grp)
    np.testing.assert_array_equal(np.asarray(got["s"]), want.val)


def test_detection_negative_cases(rng):
    """Grouping by a non-prefix (or through a join) must NOT claim order."""
    cat, *_ = _clustered_cat(rng)
    r = Rel.scan(cat, "fact")
    g = r.groupby(["val"], [("c", "count_rows", None)])
    op = plan_builder.build(g.plan, cat)
    assert not getattr(op, "ordered", False)
    # group by (grp, val): grp is an ordering prefix but val breaks
    # adjacency within a run
    g2 = r.groupby(["grp", "val"], [("c", "count_rows", None)])
    op2 = plan_builder.build(g2.plan, cat)
    assert not getattr(op2, "ordered", False)


def test_ordered_agg_distributed_matches_local(rng):
    cat, *_ = _clustered_cat(rng)
    r = Rel.scan(cat, "fact", ("grp", "val"))
    g = r.groupby(["grp"], [("s", "sum", "val")]).sort([("grp", False)])
    local = g.run()
    dist = Rel.scan(cat, "fact", ("grp", "val")).groupby(
        ["grp"], [("s", "sum", "val")]).sort([("grp", False)]
                                             ).run_distributed()
    np.testing.assert_array_equal(np.asarray(local["grp"]),
                                  np.asarray(dist["grp"]))
    np.testing.assert_array_equal(np.asarray(local["s"]),
                                  np.asarray(dist["s"]))
