"""Storage<->SQL bridge tests: rows written through kv.Txn are readable by
the SQL engine via the direct-columnar-scan path (the cFetcher/col_mvcc
parity point — pkg/sql/colfetcher/cfetcher.go:230,
pkg/storage/col_mvcc.go:25-90)."""

import numpy as np
import pytest

import cockroach_tpu.catalog as catalog_mod
from cockroach_tpu import coldata as cd
from cockroach_tpu.kv import DB, ManualClock, WriteIntentError
from cockroach_tpu.kv.table import create_kv_table
from cockroach_tpu.sql import sql
from cockroach_tpu.storage import rowcodec
from cockroach_tpu.storage.lsm import Engine


SCHEMA = cd.Schema.of(
    id=cd.INT64, qty=cd.INT64, price=cd.DECIMAL(12, 2), day=cd.DATE,
    ratio=cd.FLOAT64, ok=cd.BOOL,
)


def _db():
    return DB(
        Engine(key_width=16, val_width=rowcodec.value_width(SCHEMA),
               memtable_size=64),
        ManualClock(),
    )


def _setup(n=50):
    db = _db()
    cat = catalog_mod.Catalog()
    t = create_kv_table(cat, db, "items", SCHEMA, pk="id")
    rng = np.random.default_rng(3)
    rows = []
    for i in range(n):
        rows.append({
            "id": i, "qty": int(rng.integers(1, 100)),
            "price": int(rng.integers(100, 10000)),
            "day": int(rng.integers(8000, 9000)),
            "ratio": float(rng.random()),
            "ok": bool(rng.integers(0, 2)),
        })

    def ins(txn):
        for r in rows:
            t.insert(txn, r)

    db.txn(ins)
    return db, cat, t, rows


def test_rowcodec_roundtrip():
    row = {"id": -5, "qty": 7, "price": 123456, "day": 8123,
           "ratio": -2.75, "ok": True}
    enc = rowcodec.encode_row(SCHEMA, row)
    dec = rowcodec.decode_row(SCHEMA, enc)
    assert dec["id"] == -5 and dec["qty"] == 7 and dec["price"] == 123456
    assert dec["day"] == 8123 and dec["ratio"] == -2.75 and dec["ok"] is True
    # NULLs
    enc2 = rowcodec.encode_row(SCHEMA, {"id": 1})
    dec2 = rowcodec.decode_row(SCHEMA, enc2)
    assert dec2["qty"] is None and dec2["ratio"] is None


def test_pk_encoding_order_and_nul_free():
    vals = [-(1 << 63), -12345, -1, 0, 1, 77, 1 << 40, (1 << 63) - 1]
    keys = [rowcodec.encode_pk(3, v) for v in vals]
    assert keys == sorted(keys), "key order must follow pk order"
    for k, v in zip(keys, vals):
        assert b"\x00" not in k
        assert rowcodec.decode_pk(k) == v


def test_sql_over_kv_table():
    """Rows written via transactions are visible to SQL aggregates through
    the engine (no preloaded host table anywhere)."""
    db, cat, t, rows = _setup()
    res = sql(cat, """
        select count(*) as n, sum(qty) as s, min(day) as lo, max(day) as hi
        from items where qty > 50
    """).run()
    want = [r for r in rows if r["qty"] > 50]
    assert int(res["n"][0]) == len(want)
    assert int(res["s"][0]) == sum(r["qty"] for r in want)
    assert int(res["lo"][0]) == min(r["day"] for r in want)
    assert int(res["hi"][0]) == max(r["day"] for r in want)
    # decimal + float columns decode correctly through the device path
    res2 = sql(cat, "select sum(price) as p, avg(ratio) as r from items").run()
    np.testing.assert_allclose(
        float(res2["p"][0]), sum(r["price"] for r in rows) / 100.0, rtol=1e-12
    )
    np.testing.assert_allclose(
        float(res2["r"][0]), np.mean([r["ratio"] for r in rows]), rtol=1e-12
    )


def test_kv_table_mvcc_snapshot():
    """read_ts pins a snapshot: updates after the snapshot are invisible."""
    db, cat, t, rows = _setup(10)
    ts0 = db.clock.now()

    def upd(txn):
        t.insert(txn, {**rows[0], "qty": 10_000})

    db.txn(upd)
    res = sql(cat, "select max(qty) as m from items").run()
    assert int(res["m"][0]) == 10_000
    t.read_ts = ts0
    try:
        res0 = sql(cat, "select max(qty) as m from items").run()
        assert int(res0["m"][0]) == max(r["qty"] for r in rows)
    finally:
        t.read_ts = None


def test_kv_table_abort_and_delete():
    db, cat, t, rows = _setup(10)

    class Boom(Exception):
        pass

    def bad(txn):
        t.insert(txn, {"id": 999, "qty": 1, "price": 1, "day": 1,
                       "ratio": 0.0, "ok": False})
        raise Boom()

    with pytest.raises(Boom):
        db.txn(bad)
    db.txn(lambda txn: t.delete_pk(txn, rows[0]["id"]))
    res = sql(cat, "select count(*) as n from items").run()
    assert int(res["n"][0]) == len(rows) - 1  # no aborted row, one deleted


def test_kv_table_null_columns():
    db = _db()
    cat = catalog_mod.Catalog()
    t = create_kv_table(cat, db, "items", SCHEMA, pk="id")

    def ins(txn):
        t.insert(txn, {"id": 1, "qty": 5})
        t.insert(txn, {"id": 2, "price": 300})

    db.txn(ins)
    res = sql(cat, "select count(qty) as cq, count(price) as cp, "
                   "count(*) as n from items").run()
    assert int(res["cq"][0]) == 1 and int(res["cp"][0]) == 1
    assert int(res["n"][0]) == 2


def test_kv_scan_hits_intent_conflict():
    db, cat, t, rows = _setup(5)
    open_txn = db.new_txn()
    t.insert(open_txn, {**rows[2], "qty": 1})
    with pytest.raises(WriteIntentError):
        sql(cat, "select count(*) as n from items").run()
    open_txn.rollback()
    res = sql(cat, "select count(*) as n from items").run()
    assert int(res["n"][0]) == 5


def test_ycsb_e_microbench():
    from cockroach_tpu.bench.ycsb import run_ycsb_e

    out = run_ycsb_e(n_keys=512, ops=8, scan_len=16)
    assert out["ops_per_sec"] > 0
    assert out["rows_scanned"] >= 5 * 16  # scans dominate the mix (a scan
    # starting near the end of the keyspace legitimately returns fewer rows)


def test_q1_over_kv_backed_lineitem():
    """TPC-H Q1 end-to-end over a lineitem that LIVES IN THE ENGINE —
    strings included (VERDICT: the kv/table.py fixed-width restriction is
    gone). Oracle: the same query over the host-resident catalog table."""
    from cockroach_tpu.bench import queries as Q
    from cockroach_tpu.bench import tpch

    host_cat = tpch.gen_tpch(sf=0.002, seed=5)
    want = Q.q1(host_cat).run()

    li = host_cat.get("lineitem")
    db = DB(
        Engine(key_width=16, val_width=rowcodec.value_width(li.schema),
               memtable_size=1 << 14),
        ManualClock(),
    )
    kv_cat = catalog_mod.Catalog()
    kvt = create_kv_table(kv_cat, db, "lineitem", li.schema, pk="l_rowid"
                          if "l_rowid" in li.schema.names else
                          li.schema.names[0])
    # lineitem has no single-column pk; use a synthetic rowid as the key
    n = li.num_rows

    def ins(txn):
        for r in range(n):
            row = {}
            for cname in li.schema.names:
                v = li.columns[cname][r]
                if cname in li.dictionaries:
                    v = li.dictionaries[cname].values[int(v)]
                row[cname] = v
            # key by row index: l_orderkey repeats, so the first column
            # cannot key the row; overwrite the pk encoding input
            row[kvt.pk] = r
            kvt.insert(txn, row)

    db.txn(ins)
    assert kvt.num_rows == n

    got = Q.q1(kv_cat).run()
    assert list(got["l_returnflag"]) == list(want["l_returnflag"])
    assert list(got["l_linestatus"]) == list(want["l_linestatus"])
    for col in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
                "avg_qty", "avg_price", "avg_disc", "count_order"):
        np.testing.assert_allclose(
            np.asarray(got[col], dtype=np.float64),
            np.asarray(want[col], dtype=np.float64), rtol=1e-9,
        )


def test_bulk_load_and_import_job(tmp_path):
    """IMPORT path: vectorized key/value encoding lands CSV data as sorted
    engine runs (AddSSTable discipline); strings dictionary-encode
    vectorized; results query identically to row-at-a-time inserts."""
    import csv

    from cockroach_tpu.kv import DB, ManualClock
    from cockroach_tpu.kv.jobs import Registry, register_import_job
    from cockroach_tpu.sql import sql

    db = DB(Engine(key_width=16, val_width=256, memtable_size=256),
            ManualClock())
    cat = catalog_mod.Catalog()
    schema = cd.Schema.of(id=cd.INT64, qty=cd.INT64,
                          price=cd.DECIMAL(12, 2), tag=cd.STRING)
    t = create_kv_table(cat, db, "items", schema, pk="id")

    path = str(tmp_path / "items.csv")
    n = 5000
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["id", "qty", "price", "tag"])
        w.writeheader()
        for i in range(n):
            w.writerow({"id": i, "qty": i % 97,
                        "price": f"{(i % 1000) + 0.25:.2f}",
                        "tag": f"t{i % 7}"})

    reg = Registry(db)
    register_import_job(reg, cat)
    job = reg.create("import", {"table": "items", "path": path})
    done = reg.adopt_and_resume(job.job_id)
    assert done.state == "succeeded" and done.progress["rows"] == n
    assert t.num_rows == n

    res = sql(cat, "select count(*) as n, sum(qty) as q from items").run()
    assert int(res["n"][0]) == n
    assert int(res["q"][0]) == sum(i % 97 for i in range(n))
    res = sql(cat, "select tag, count(*) as c from items group by tag "
                   "order by tag").run()
    assert list(res["tag"]) == [f"t{i}" for i in range(7)]
    res = sql(cat, "select price from items where id = 1234").run()
    np.testing.assert_allclose(float(res["price"][0]), 234 + 0.25)
    # NULL handling: a row with a missing value
    with open(path, "a", newline="") as f:
        f.write(f"{n},,,t0\n")
    job2 = reg.create("import", {"table": "items", "path": path})
    # re-import at a higher ts: idempotent for existing pks (MVCC versions)
    done2 = reg.adopt_and_resume(job2.job_id)
    assert done2.progress["rows"] == n + 1
    res = sql(cat, f"select qty from items where id = {n}").run()
    assert res["qty"][0] is None


@pytest.mark.slow
def test_sharded_scan_covers_kv_tables():
    """Shard masks select by LIVE-ROW RANK: a KVTable's live rows sit at
    scattered merged-view positions (often past num_rows), so positional
    sharding would silently drop rows (regression)."""
    import numpy as np

    from cockroach_tpu.flow.operators import ScanOp, UnionOp
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.sql import Session

    sess = Session()
    # two tables interleave their keys in the one engine, and updates leave
    # old MVCC versions around — live rows are NOT a position prefix
    sess.execute("create table a (k int primary key, v int)")
    sess.execute("create table b (k int primary key, v int)")
    for i in range(50):
        sess.execute(f"insert into a values ({i}, {i})")
        sess.execute(f"insert into b values ({i}, {1000 + i})")
    sess.execute("update b set v = v + 1 where k < 25")

    tbl = sess.catalog.tables["b"]
    full = run_operator(ScanOp(tbl))
    parts = UnionOp(tuple(
        ScanOp(tbl, shard=(i, 3)) for i in range(3)
    ))
    got = run_operator(parts)
    assert len(got["k"]) == len(full["k"]) == 50
    np.testing.assert_array_equal(np.sort(got["k"]), np.sort(full["k"]))
    np.testing.assert_array_equal(np.sort(got["v"]), np.sort(full["v"]))


def test_sharded_scan_covers_snapshot_beyond_num_rows():
    """The last shard is rank-unbounded: a snapshot can hold MORE live rows
    than num_rows reports at now() (e.g. a snapshot taken before deletes);
    those trailing ranks must not vanish from a sharded scan (regression)."""
    import numpy as np

    from cockroach_tpu.flow.operators import ScanOp, UnionOp
    from cockroach_tpu.flow.runtime import run_operator
    from cockroach_tpu.sql import Session

    sess = Session()
    sess.execute("create table s (k int primary key, v int)")
    for i in range(60):
        sess.execute(f"insert into s values ({i}, {i})")
    tbl = sess.catalog.tables["s"]
    snap_ts = sess.db.clock.now()
    sess.execute("delete from s where k >= 50")
    assert tbl.num_rows == 50  # newest-visible count
    tbl.read_ts = snap_ts  # scan AT the pre-delete snapshot
    try:
        got = run_operator(UnionOp(tuple(
            ScanOp(tbl, shard=(i, 3)) for i in range(3)
        )))
        assert len(got["k"]) == 60, "sharded snapshot scan dropped rows"
        np.testing.assert_array_equal(np.sort(got["k"]), np.arange(60))
    finally:
        tbl.read_ts = None


def test_distributed_kv_scan_sizes_from_snapshot():
    """The SPMD planner sizes shard capacity from snapshot_live_rows: a
    pre-delete snapshot holding more rows than num_rows must distribute
    completely (regression: sizing from num_rows dropped the tail)."""
    import numpy as np

    from cockroach_tpu.parallel import mesh as mesh_mod
    from cockroach_tpu.sql import Session, sql

    sess = Session()
    sess.execute("create table ds (k int primary key, v int)")
    rows = ", ".join(f"({i}, {i * 2})" for i in range(1200))
    sess.execute(f"insert into ds values {rows}")
    tbl = sess.catalog.tables["ds"]
    snap_ts = sess.db.clock.now()
    sess.execute("delete from ds where k >= 600")
    assert tbl.num_rows == 600
    tbl.read_ts = snap_ts
    try:
        assert tbl.snapshot_live_rows() == 1200
        rel = sql(sess.catalog, "select count(*) as n, sum(v) as s from ds")
        got = rel.run_distributed(mesh_mod.make_mesh(8))
        assert int(got["n"][0]) == 1200
        assert int(got["s"][0]) == sum(i * 2 for i in range(1200))
    finally:
        tbl.read_ts = None
