"""Multi-tenancy (reduced): shared-KV tenants, keyspace isolation by
table-id range, capability gating (reference: pkg/multitenant,
tenantcapabilities; see kv/tenant.py)."""

import numpy as np
import pytest

from cockroach_tpu.kv.tenant import (CapabilityError, TenantError,
                                     TenantRegistry)
from cockroach_tpu.sql.session import Session


def _shared_db():
    s = Session()  # owns a fresh engine
    return s.db


def test_registry_create_list_drop():
    db = _shared_db()
    reg = TenantRegistry(db)
    reg.bootstrap()
    a = reg.create("acme")
    b = reg.create("bravo")
    assert a.tenant_id == 2 and b.tenant_id == 3
    # disjoint id ranges
    assert a.id_hi < b.id_lo
    names = {r.name for r in reg.list()}
    assert names == {"system", "acme", "bravo"}
    with pytest.raises(TenantError):
        reg.create("acme")
    reg.drop("bravo")
    assert {r.name for r in reg.list()} == {"system", "acme"}
    with pytest.raises(TenantError):
        reg.drop("system")


def test_tenant_keyspace_isolation():
    """Same table name in two tenants: different spans, different data,
    and neither session can see the other's tables."""
    db = _shared_db()
    sys_s = Session(db=db)
    sys_s.execute("CREATE TENANT acme")
    sys_s.execute("CREATE TENANT bravo")

    sa = Session(db=db, tenant="acme")
    sb = Session(db=db, tenant="bravo")
    sa.execute("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
    sb.execute("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
    sa.execute("INSERT INTO t VALUES (1, 100)")
    sb.execute("INSERT INTO t VALUES (1, 200)")

    va = sa.execute("SELECT v FROM t")["v"]
    vb = sb.execute("SELECT v FROM t")["v"]
    assert list(np.asarray(va)) == [100]
    assert list(np.asarray(vb)) == [200]

    # disjoint physical spans
    ta = sa.catalog.tables["t"]
    tb = sb.catalog.tables["t"]
    assert ta.table_id != tb.table_id

    # a FRESH session per tenant rediscovers only its own table
    sa2 = Session(db=db, tenant="acme")
    assert list(np.asarray(sa2.execute("SELECT v FROM t")["v"])) == [100]
    # and the system-tenant records are invisible to the scoped catalog
    assert set(sa2.catalog.tables) == {"t"}


def test_capability_gating():
    db = _shared_db()
    sys_s = Session(db=db)
    sys_s.execute("CREATE TENANT acme")
    sa = Session(db=db, tenant="acme")
    # backups are denied by default
    with pytest.raises(CapabilityError):
        sa.execute("BACKUP TO 'nodelocal://1/b1'")
    sys_s.execute("ALTER TENANT acme GRANT CAPABILITY can_backup")
    # the capability is read at execute time by a fresh session
    sa2 = Session(db=db, tenant="acme")
    sa2.execute("CREATE TABLE t (k INT PRIMARY KEY)")
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        out = sa2.execute(f"BACKUP TO '{os.path.join(d, 'b')}'")
        assert out["state"] == "succeeded"

    sys_s.execute("ALTER TENANT acme REVOKE CAPABILITY can_create_table")
    sa3 = Session(db=db, tenant="acme")
    with pytest.raises(CapabilityError):
        sa3.execute("CREATE TABLE t2 (k INT PRIMARY KEY)")


def test_max_tables_and_range_exhaustion():
    db = _shared_db()
    Session(db=db).execute("CREATE TENANT tiny")
    s = Session(db=db, tenant="tiny")
    cap = int(s.tenant.caps["max_tables"])
    for i in range(cap):
        s.execute(f"CREATE TABLE t{i} (k INT PRIMARY KEY)")
    with pytest.raises(CapabilityError):
        s.execute(f"CREATE TABLE t{cap} (k INT PRIMARY KEY)")


def test_tenant_ddl_requires_system():
    db = _shared_db()
    Session(db=db).execute("CREATE TENANT acme")
    sa = Session(db=db, tenant="acme")
    with pytest.raises(TenantError):
        sa.execute("CREATE TENANT evil")
    with pytest.raises(TenantError):
        sa.execute("SHOW TENANTS")


def test_show_tenants():
    db = _shared_db()
    s = Session(db=db)
    s.execute("CREATE TENANT acme")
    out = s.execute("SHOW TENANTS")
    assert list(out["name"]) == ["system", "acme"]
    assert "can_backup=False" in out["capabilities"][1]