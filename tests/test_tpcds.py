"""TPC-DS (reduced) — star-schema reporting queries vs pandas oracles,
local AND distributed."""

import numpy as np
import pandas as pd
import pytest

from cockroach_tpu.bench import tpcds


@pytest.fixture(scope="module")
def cat():
    return tpcds.gen_tpcds(sf=0.01)


def _pd(cat, name):
    t = cat.get(name)
    out = {}
    for cname, typ in zip(t.schema.names, t.schema.types):
        col = t.columns[cname]
        if cname in t.dictionaries:
            out[cname] = t.dictionaries[cname].values[col]
        elif typ.family.name == "DECIMAL":
            out[cname] = col / 10.0**typ.scale
        else:
            out[cname] = col
    return pd.DataFrame(out)


def _oracle(cat, qname):
    ss = _pd(cat, "store_sales")
    dd = _pd(cat, "date_dim")
    it = _pd(cat, "item")
    if qname == "q3":
        j = (ss.merge(dd[dd.d_moy == 12], left_on="ss_sold_date_sk",
                      right_on="d_date_sk")
             .merge(it[it.i_manufact_id == 5], left_on="ss_item_sk",
                    right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_brand_id", "i_brand"])
             .ss_ext_sales_price.sum().reset_index(name="sum_agg"))
        return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                             ascending=[True, False, True]).head(100)
    if qname == "q42":
        j = (ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_category"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["rev", "d_year", "i_category"],
                             ascending=[False, True, True]).head(100)
    if qname == "q52":
        j = (ss.merge(dd[(dd.d_moy == 12) & (dd.d_year == 1999)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_brand_id", "i_brand"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["d_year", "rev", "i_brand_id"],
                             ascending=[True, False, True]).head(100)
    if qname == "q55":
        j = (ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 2001)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 3], left_on="ss_item_sk",
                    right_on="i_item_sk"))
        g = (j.groupby(["i_brand_id", "i_brand"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["rev", "i_brand_id"],
                             ascending=[False, True]).head(100)
    if qname == "q59_lite":
        st = _pd(cat, "store")
        j = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
        g = (j.groupby(["s_store_name", "d_year", "d_moy"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["s_store_name", "d_year", "d_moy"]).head(500)
    raise KeyError(qname)


@pytest.mark.parametrize("qname", sorted(tpcds.QUERIES))
def test_query_matches_pandas(cat, qname):
    got = tpcds.QUERIES[qname](cat).run()
    want = _oracle(cat, qname)
    assert len(next(iter(got.values()))) == len(want) > 0, qname
    val = "sum_agg" if qname == "q3" else "rev"
    np.testing.assert_allclose(
        np.asarray(got[val], np.float64), want[val].to_numpy(),
        rtol=1e-9, err_msg=qname,
    )
    for k in want.columns:
        if k == val:
            continue
        a, b = got[k], want[k].to_numpy()
        if a.dtype.kind in "OU":
            assert list(a) == list(b), (qname, k)
        else:
            np.testing.assert_array_equal(a, b, err_msg=f"{qname}.{k}")


@pytest.mark.parametrize("qname", ["q3", "q55"])
def test_query_distributed_matches_local(cat, qname):
    local = tpcds.QUERIES[qname](cat).run()
    dist = tpcds.QUERIES[qname](cat).run_distributed()
    for k in local:
        a, b = local[k], dist[k]
        if a.dtype.kind in "OU":
            assert list(a) == list(b), (qname, k)
        else:
            np.testing.assert_allclose(
                np.asarray(a, np.float64), np.asarray(b, np.float64),
                rtol=1e-9, err_msg=f"{qname}.{k}")
