"""TPC-DS (reduced) — star-schema reporting queries vs pandas oracles,
local AND distributed."""

import numpy as np
import pandas as pd
import pytest

from cockroach_tpu.bench import tpcds


@pytest.fixture(scope="module")
def cat():
    return tpcds.gen_tpcds(sf=0.01)


def _pd(cat, name):
    t = cat.get(name)
    out = {}
    for cname, typ in zip(t.schema.names, t.schema.types):
        col = t.columns[cname]
        if cname in t.dictionaries:
            out[cname] = t.dictionaries[cname].values[col]
        elif typ.family.name == "DECIMAL":
            out[cname] = col / 10.0**typ.scale
        else:
            out[cname] = col
    return pd.DataFrame(out)


def _oracle(cat, qname):
    ss = _pd(cat, "store_sales")
    dd = _pd(cat, "date_dim")
    it = _pd(cat, "item")
    if qname == "q3":
        j = (ss.merge(dd[dd.d_moy == 12], left_on="ss_sold_date_sk",
                      right_on="d_date_sk")
             .merge(it[it.i_manufact_id == 5], left_on="ss_item_sk",
                    right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_brand_id", "i_brand"])
             .ss_ext_sales_price.sum().reset_index(name="sum_agg"))
        return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                             ascending=[True, False, True]).head(100)
    if qname == "q42":
        j = (ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_category"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["rev", "d_year", "i_category"],
                             ascending=[False, True, True]).head(100)
    if qname == "q52":
        j = (ss.merge(dd[(dd.d_moy == 12) & (dd.d_year == 1999)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_brand_id", "i_brand"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["d_year", "rev", "i_brand_id"],
                             ascending=[True, False, True]).head(100)
    if qname == "q55":
        j = (ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 2001)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 3], left_on="ss_item_sk",
                    right_on="i_item_sk"))
        g = (j.groupby(["i_brand_id", "i_brand"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["rev", "i_brand_id"],
                             ascending=[False, True]).head(100)
    if qname == "q59_lite":
        st = _pd(cat, "store")
        j = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
        g = (j.groupby(["s_store_name", "d_year", "d_moy"])
             .ss_ext_sales_price.sum().reset_index(name="rev"))
        return g.sort_values(["s_store_name", "d_year", "d_moy"]).head(500)
    if qname == "q7":
        cd = _pd(cat, "customer_demographics")
        pr = _pd(cat, "promotion")
        cdf = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                 & (cd.cd_education_status == "College")]
        prf = pr[pr.p_channel_email == "N"]
        j = (ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                      right_on="d_date_sk")
             .merge(cdf, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
             .merge(prf, left_on="ss_promo_sk", right_on="p_promo_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby("i_brand_id")
             .agg(agg1=("ss_quantity", "mean"),
                  agg2=("ss_list_price", "mean"),
                  agg3=("ss_coupon_amt", "mean"),
                  agg4=("ss_ext_sales_price", "mean"))
             .reset_index())
        return g.sort_values("i_brand_id").head(100)
    if qname == "q19_lite":
        j = (ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 7], left_on="ss_item_sk",
                    right_on="i_item_sk"))
        g = (j.groupby(["i_brand_id", "i_brand", "i_manufact_id"])
             .ss_ext_sales_price.sum().reset_index(name="ext_price"))
        return g.sort_values(["ext_price", "i_brand_id", "i_manufact_id"],
                             ascending=[False, True, True]).head(100)
    if qname == "q53_lite":
        j = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["i_manufact_id", "d_year", "d_moy"])
             .ss_ext_sales_price.sum().reset_index(name="sum_sales"))
        g["avg_monthly"] = g.groupby("i_manufact_id"
                                     ).sum_sales.transform("mean")
        dev = g[(g.sum_sales - g.avg_monthly).abs()
                > 0.1 * g.avg_monthly]
        return dev.sort_values(["i_manufact_id", "d_year", "d_moy"]
                               ).head(200)
    if qname == "q65_lite":
        st = _pd(cat, "store")
        per_item = (ss.groupby(["ss_store_sk", "ss_item_sk"])
                    .ss_ext_sales_price.sum().reset_index(name="revenue"))
        per_store = (per_item.groupby("ss_store_sk")
                     .revenue.mean().reset_index(name="ave"))
        j = per_item.merge(per_store, on="ss_store_sk")
        low = j[j.revenue <= 0.95 * j.ave]
        out = low.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        return out.sort_values(["s_store_name", "ss_item_sk"]).head(200)
    raise KeyError(qname)


# value columns compared with float tolerance; everything else exactly
_VALS = {
    "q3": ("sum_agg",), "q42": ("rev",), "q52": ("rev",), "q55": ("rev",),
    "q59_lite": ("rev",), "q7": ("agg1", "agg2", "agg3", "agg4"),
    "q19_lite": ("ext_price",), "q53_lite": ("sum_sales", "avg_monthly"),
    "q65_lite": ("revenue", "ave"),
}


@pytest.mark.parametrize("qname", sorted(tpcds.QUERIES))
def test_query_matches_pandas(cat, qname):
    got = tpcds.QUERIES[qname](cat).run()
    want = _oracle(cat, qname)
    assert len(next(iter(got.values()))) == len(want) > 0, qname
    vals = _VALS[qname]
    for val in vals:
        np.testing.assert_allclose(
            np.asarray(got[val], np.float64), want[val].to_numpy(),
            rtol=1e-9, err_msg=f"{qname}.{val}",
        )
    for k in want.columns:
        if k in vals:
            continue
        # every oracle column must exist in the engine output — a silent
        # skip would let a dropped group-key column pass unnoticed
        assert k in got, f"{qname}: missing output column {k}"
        a, b = got[k], want[k].to_numpy()
        if a.dtype.kind in "OU":
            assert list(a) == list(b), (qname, k)
        else:
            np.testing.assert_array_equal(a, b, err_msg=f"{qname}.{k}")


@pytest.mark.parametrize("qname", ["q3", "q55", "q19_lite"])
def test_query_distributed_matches_local(cat, qname):
    local = tpcds.QUERIES[qname](cat).run()
    dist = tpcds.QUERIES[qname](cat).run_distributed()
    for k in local:
        a, b = local[k], dist[k]
        if a.dtype.kind in "OU":
            assert list(a) == list(b), (qname, k)
        else:
            np.testing.assert_allclose(
                np.asarray(a, np.float64), np.asarray(b, np.float64),
                rtol=1e-9, err_msg=f"{qname}.{k}")
