"""Tier-1 wiring for the static metrics audit
(scripts/check_metrics_documented.py): every DEFAULT-registry metric must
carry help text and a README metrics-table row, and every table row must
name a registered metric."""

from scripts.check_metrics_documented import check, registrations


def test_every_metric_documented():
    problems = check()
    assert not problems, "\n".join(problems)


def test_registration_scan_sees_the_real_tree():
    import pathlib

    regs = registrations(pathlib.Path("cockroach_tpu"))
    # the scan must index registrations in BOTH homes: the registry module
    # itself and subsystem modules registering on metric.DEFAULT
    assert "sql_kernel_dispatches" in regs      # utils/metric.py
    assert "storage_disk_write_p99_ms" in regs  # storage/disk.py
    assert regs["sql_kernel_dispatches"]["help"]
    assert regs["rpc_retries_by_range"]["kind"] == "labeled_counter"


def test_checker_catches_both_drift_classes(tmp_path):
    pkg = tmp_path / "cockroach_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'X = DEFAULT.counter(\n    "x_documented", "help here")\n'
        'Y = metric.DEFAULT.gauge("x_undocumented", "help")\n'
        'Z = DEFAULT.counter("x_no_help", "")\n'
    )
    (tmp_path / "README.md").write_text(
        "| metric | type | what |\n|---|---|---|\n"
        "| `x_documented` | counter | help here |\n"
        "| `x_no_help` | counter | row present, help missing |\n"
        "| `x_stale_row` | counter | registered nowhere |\n"
    )
    problems = check(tmp_path)
    assert any("x_undocumented" in p and "missing" in p for p in problems)
    assert any("x_no_help" in p and "empty help" in p for p in problems)
    assert any("x_stale_row" in p for p in problems)
    assert not any("'x_documented'" in p for p in problems)
