"""Hybrid spill join + heavy-hitter skew routing (flow/external.py).

The Grace hash join's two escape hatches, each pinned against the
in-memory oracle bit-for-bit:

- hybrid degrade: partitions whose build side exceeds workmem reload as
  budget-sized sorted runs and merge-probe (ops.merge_join) instead of
  one oversized hash table — every join type, with the memory-monitor
  drain census (conftest autouse) proving the spill path releases all
  reservations;
- heavy-hitter routing: build-side reservoir sampling detects hot keys,
  pins their build rows resident, and streams their probe rows through a
  hot lane — plus the SPMD half: the shuffle plane's keep-local routing
  for hot hashes (parallel/shuffle.py).
"""

import jax
import numpy as np
import pytest

from cockroach_tpu import catalog as catalog_mod
from cockroach_tpu import coldata as cd
from cockroach_tpu.coldata.types import INT64, Schema
from cockroach_tpu.ops.hashing import hash_columns
from cockroach_tpu.parallel import dist, mesh as mesh_mod, shuffle as shuf
from cockroach_tpu.sql.rel import Rel
from cockroach_tpu.utils import metric, settings


def _catalog(seed, np_rows, nb_rows, nkeys, hot_key=None, hot_build=0,
             hot_probe=0):
    rng = np.random.default_rng(seed)
    pk = rng.integers(0, nkeys, np_rows).astype(np.int64)
    bk = rng.integers(0, int(nkeys * 1.25), nb_rows).astype(np.int64)
    if hot_key is not None:
        pk[:hot_probe] = hot_key
        bk[:hot_build] = hot_key
        rng.shuffle(pk)
        rng.shuffle(bk)
    cat = catalog_mod.Catalog()
    cat.add(catalog_mod.Table.from_strings(
        "p", Schema.of(k=INT64, w=INT64),
        {"k": pk, "w": rng.integers(0, 100, np_rows).astype(np.int64)}))
    cat.add(catalog_mod.Table.from_strings(
        "b", Schema.of(bk=INT64, v=INT64),
        {"bk": bk, "v": rng.integers(0, 100, nb_rows).astype(np.int64)}))
    return cat


def _run_join(cat, how, workmem, tile=2048, skew_frac=None):
    prev = {n: settings.get(n) for n in (
        "sql.distsql.workmem_bytes", "sql.distsql.tile_size",
        "sql.distsql.grace_skew_frac")}
    settings.set("sql.distsql.workmem_bytes", workmem)
    settings.set("sql.distsql.tile_size", tile)
    if skew_frac is not None:
        settings.set("sql.distsql.grace_skew_frac", skew_frac)
    try:
        r = (Rel.scan(cat, "p")
             .join(Rel.scan(cat, "b"), on=[("k", "bk")], how=how,
                   build_unique=False))
        return r.run()
    finally:
        for n, val in prev.items():
            settings.set(n, val)


def _canon(res):
    names = sorted(res.keys())
    recs = list(zip(*[np.asarray(res[n]).tolist() for n in names]))
    return sorted(recs, key=lambda t: tuple((x is None, x) for x in t))


@pytest.mark.parametrize("how", [
    "inner", pytest.param("left", marks=pytest.mark.slow), "semi",
    pytest.param("anti", marks=pytest.mark.slow)])
def test_hybrid_spill_merge_runs_match_oracle(how):
    """Forced spill with partitions past workmem: the build side reloads
    as sorted runs and merge-probes; output equals the in-memory join."""
    cat = _catalog(11, 8000, 30000, nkeys=1500)
    oracle = _run_join(cat, how, workmem=2 << 30)
    spills0 = metric.GRACE_JOIN_SPILLS.value
    merge0 = metric.GRACE_JOIN_MERGE_PARTS.value
    got = _run_join(cat, how, workmem=1 << 16)
    assert metric.GRACE_JOIN_SPILLS.value > spills0, "join never spilled"
    assert metric.GRACE_JOIN_MERGE_PARTS.value > merge0, \
        "no partition degraded to merge runs (raise build size?)"
    assert _canon(got) == _canon(oracle)


@pytest.mark.parametrize("how", [
    "inner", pytest.param("left", marks=pytest.mark.slow), "semi",
    pytest.param("anti", marks=pytest.mark.slow)])
def test_skew_hot_lane_matches_oracle(how):
    """Heavy-hitter probe rows route through the resident hot build table;
    results stay identical and the routed-row metric moves."""
    cat = _catalog(13, 8000, 12000, nkeys=4000,
                   hot_key=77, hot_build=200, hot_probe=800)
    oracle = _run_join(cat, how, workmem=2 << 30, skew_frac=0.0)
    routed0 = metric.GRACE_JOIN_SKEW_ROUTED.value
    got = _run_join(cat, how, workmem=1 << 16, skew_frac=0.01)
    assert metric.GRACE_JOIN_SKEW_ROUTED.value > routed0, \
        "no probe rows took the hot lane"
    assert _canon(got) == _canon(oracle)


def test_skew_detection_skipped_when_hot_side_oversized():
    """When the hot keys' build rows would not fit the residency budget,
    the skew path stands down and the hybrid runs still bound memory."""
    cat = _catalog(17, 6000, 20000, nkeys=50,
                   hot_key=7, hot_build=12000, hot_probe=3000)
    oracle = _run_join(cat, "semi", workmem=2 << 30, skew_frac=0.0)
    routed0 = metric.GRACE_JOIN_SKEW_ROUTED.value
    got = _run_join(cat, "semi", workmem=1 << 16, skew_frac=0.05)
    assert metric.GRACE_JOIN_SKEW_ROUTED.value == routed0
    assert _canon(got) == _canon(oracle)


# -- SPMD half: hot hashes keep their rows local in the shuffle plane ------


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh(8)


def _key_hash(schema, key_value):
    one = cd.from_host(
        schema, {"k": np.array([key_value], dtype=np.int64),
                 "v": np.array([0], dtype=np.int64)}, capacity=1)
    return np.asarray(
        hash_columns([one.cols[0]], [schema.types[0]], None))[:1]


def test_shuffle_hot_hashes_stay_local(mesh):
    """A 60%-skewed key overflows the plain hash router; with its hash in
    hot_hashes the rows never leave their device, the shuffle carries only
    the cold tail, and non-hot keys still coalesce one-device-each."""
    schema = cd.Schema.of(k=cd.INT64, v=cd.INT64)
    n, D, local = 4000, 8, 512
    rng = np.random.default_rng(3)
    k = np.where(rng.random(n) < 0.6, 0,
                 rng.integers(1, 50, n)).astype(np.int64)
    b = cd.from_host(schema, {"k": k, "v": np.arange(n, dtype=np.int64)},
                     capacity=local * D)
    sb = dist.shard_batch(b, mesh)
    hot_h = _key_hash(schema, 0)

    fn0 = shuf.make_shuffle(mesh, schema, (0,), local_capacity=local,
                            send_factor=1.0)
    _, ovf0 = fn0(sb)
    assert int(np.asarray(ovf0).sum()) > 0  # skew breaks the plain router

    fn1 = shuf.make_shuffle(mesh, schema, (0,), local_capacity=local,
                            send_factor=1.0, out_capacity=2 * local,
                            hot_hashes=hot_h)
    out, ovf1 = fn1(sb)
    assert int(np.asarray(ovf1).sum()) == 0

    rows, key_to_dev = 0, {}
    for d in range(D):
        shard_in = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[d * local:(d + 1) * local], sb)
        hot_in = int(((shard_in.cols[0].data == 0) & shard_in.mask).sum())
        shard = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[d * 2 * local:(d + 1) * 2 * local], out)
        ks = shard.cols[0].data[shard.mask]
        rows += int(shard.mask.sum())
        assert int((ks == 0).sum()) == hot_in, "hot rows moved devices"
        for key in np.unique(ks[ks != 0]):
            assert key_to_dev.setdefault(key, d) == d, "non-hot key split"
    assert rows == n


def test_shuffle_hot_routing_with_replicated_build_is_exact(mesh):
    """The routing contract end to end: non-hot build rows live only on
    their hash-owner device, hot build rows are replicated everywhere;
    joining each post-shuffle shard against its device's build slice
    reproduces the full join exactly."""
    schema = cd.Schema.of(k=cd.INT64, v=cd.INT64)
    n, D, local = 3000, 8, 512
    rng = np.random.default_rng(5)
    k = np.where(rng.random(n) < 0.5, 7,
                 rng.integers(8, 60, n)).astype(np.int64)
    v = np.arange(n, dtype=np.int64)
    sb = dist.shard_batch(
        cd.from_host(schema, {"k": k, "v": v}, capacity=local * D), mesh)
    bk = np.arange(0, 60, dtype=np.int64)
    hot_h = _key_hash(schema, 7)

    fn = shuf.make_shuffle(mesh, schema, (0,), local_capacity=local,
                           send_factor=2.0, out_capacity=2 * local,
                           hot_hashes=hot_h)
    out, ovf = fn(sb)
    assert int(np.asarray(ovf).sum()) == 0

    # per-device build slice: owned non-hot keys + replicated hot key
    bh = np.concatenate([_key_hash(schema, int(key)) for key in bk])
    owner = (bh % np.uint64(D)).astype(np.int64)
    got = []
    for d in range(D):
        dev_keys = set(bk[(owner == d) & (bk != 7)].tolist()) | {7}
        bmap = {int(key): int(key) * 100 for key in dev_keys}
        shard = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[d * 2 * local:(d + 1) * 2 * local], out)
        m = shard.mask
        for key, val in zip(shard.cols[0].data[m], shard.cols[1].data[m]):
            assert int(key) in bmap, "row on a device missing its build rows"
            got.append((int(val), bmap[int(key)]))
    want = sorted((int(vv), int(kk) * 100) for vv, kk in zip(v, k))
    assert sorted(got) == want
