"""End-to-end TPC-H query tests vs pandas oracles at tiny scale — the analog
of pkg/workload/tpch expected-row validation + the vec-vs-row oracle pattern
(pkg/sql/distsql/columnar_operators_test.go)."""

import numpy as np
import pandas as pd
import pytest

from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpch


@pytest.fixture(scope="module")
def cat():
    return tpch.gen_tpch(sf=0.005, seed=7)


@pytest.fixture(scope="module")
def dfs(cat):
    return {
        n: tpch.to_pandas(cat, n)
        for n in ("lineitem", "orders", "customer", "nation", "region",
                  "supplier", "part", "partsupp")
    }


def test_q1(cat, dfs):
    res = Q.q1(cat).run()
    li = dfs["lineitem"]
    cutoff = tpch.d("1998-12-01") - 90
    f = li[li.l_shipdate <= cutoff].copy()
    f["disc_price"] = (f.l_extendedprice * (1 - f.l_discount)).round(10)
    f["charge"] = (f.disc_price * (1 + f.l_tax)).round(10)
    want = (
        f.groupby(["l_returnflag", "l_linestatus"])
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size"),
        )
        .reset_index()
        .sort_values(["l_returnflag", "l_linestatus"])
    )
    assert len(res["l_returnflag"]) == len(want)
    np.testing.assert_array_equal(res["l_returnflag"], want.l_returnflag)
    np.testing.assert_array_equal(res["l_linestatus"], want.l_linestatus)
    np.testing.assert_allclose(res["sum_qty"], want.sum_qty, rtol=1e-12)
    np.testing.assert_allclose(res["sum_base_price"], want.sum_base_price, rtol=1e-12)
    np.testing.assert_allclose(res["sum_disc_price"], want.sum_disc_price, rtol=1e-9)
    np.testing.assert_allclose(res["sum_charge"], want.sum_charge, rtol=1e-9)
    np.testing.assert_allclose(res["avg_qty"], want.avg_qty, rtol=1e-12)
    np.testing.assert_allclose(res["avg_disc"], want.avg_disc, rtol=1e-12)
    np.testing.assert_array_equal(res["count_order"], want.count_order)


def test_q3(cat, dfs):
    res = Q.q3(cat).run()
    li, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    date = tpch.d("1995-03-15")
    cb = c[c.c_mktsegment == "BUILDING"]
    ob = o[o.o_orderdate < date].merge(cb, left_on="o_custkey", right_on="c_custkey")
    lb = li[li.l_shipdate > date]
    j = lb.merge(ob, left_on="l_orderkey", right_on="o_orderkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    want = (
        j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
        .agg(revenue=("revenue", "sum"))
        .reset_index()
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
        .head(10)
    )
    assert len(res["l_orderkey"]) == len(want)
    np.testing.assert_array_equal(res["l_orderkey"], want.l_orderkey)
    np.testing.assert_allclose(res["revenue"], want.revenue, rtol=1e-9)


def test_q6(cat, dfs):
    res = Q.q6(cat).run()
    li = dfs["lineitem"]
    date = tpch.d("1994-01-01")
    f = li[
        (li.l_shipdate >= date)
        & (li.l_shipdate < date + 365)
        & (li.l_discount >= 0.05 - 1e-9)
        & (li.l_discount <= 0.07 + 1e-9)
        & (li.l_quantity < 24)
    ]
    want = (f.l_extendedprice * f.l_discount).sum()
    np.testing.assert_allclose(res["revenue"][0], want, rtol=1e-9)


def test_q4(cat, dfs):
    res = Q.q4(cat).run()
    li, o = dfs["lineitem"], dfs["orders"]
    date = tpch.d("1993-07-01")
    of = o[(o.o_orderdate >= date) & (o.o_orderdate < date + 92)]
    late = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    j = of[of.o_orderkey.isin(late)]
    want = (
        j.groupby("o_orderpriority").size().reset_index(name="order_count")
        .sort_values("o_orderpriority")
    )
    np.testing.assert_array_equal(res["o_orderpriority"], want.o_orderpriority)
    np.testing.assert_array_equal(res["order_count"], want.order_count)


def test_q9(cat, dfs):
    res = Q.q9(cat).run()
    li, o, s = dfs["lineitem"], dfs["orders"], dfs["supplier"]
    n, p, ps = dfs["nation"], dfs["part"], dfs["partsupp"]
    pg = p[p.p_name.str.contains("green")]
    j = (
        li[li.l_partkey.isin(pg.p_partkey)]
        .merge(ps, left_on=["l_partkey", "l_suppkey"],
               right_on=["ps_partkey", "ps_suppkey"])
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
    )
    j["o_year"] = (
        pd.to_datetime(j.o_orderdate, unit="D", origin="unix").dt.year
    )
    j["amount"] = (
        (j.l_extendedprice * (1 - j.l_discount)).round(4)
        - (j.ps_supplycost * j.l_quantity).round(4)
    )
    want = (
        j.groupby(["n_name", "o_year"]).agg(sum_profit=("amount", "sum"))
        .reset_index().sort_values(["n_name", "o_year"],
                                   ascending=[True, False])
    )
    assert len(res["nation"]) == len(want)
    np.testing.assert_array_equal(res["nation"], want.n_name)
    np.testing.assert_array_equal(res["o_year"], want.o_year)
    np.testing.assert_allclose(res["sum_profit"], want.sum_profit, rtol=1e-9)


def test_q10(cat, dfs):
    res = Q.q10(cat).run()
    li, o, c, n = dfs["lineitem"], dfs["orders"], dfs["customer"], dfs["nation"]
    date = tpch.d("1993-10-01")
    of = o[(o.o_orderdate >= date) & (o.o_orderdate < date + 92)]
    j = (
        li[li.l_returnflag == "R"]
        .merge(of, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    )
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    want = (
        j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                   "c_address", "c_comment"])
        .agg(revenue=("rev", "sum")).reset_index()
        .sort_values(["revenue", "c_custkey"], ascending=[False, True])
        .head(20)
    )
    assert len(res["c_custkey"]) == len(want)
    np.testing.assert_array_equal(res["c_custkey"], want.c_custkey)
    np.testing.assert_allclose(res["revenue"], want.revenue, rtol=1e-9)


def test_q12(cat, dfs):
    res = Q.q12(cat).run()
    li, o = dfs["lineitem"], dfs["orders"]
    date = tpch.d("1994-01-01")
    f = li[
        li.l_shipmode.isin(["MAIL", "SHIP"])
        & (li.l_commitdate < li.l_receiptdate)
        & (li.l_shipdate < li.l_commitdate)
        & (li.l_receiptdate >= date)
        & (li.l_receiptdate < date + 365)
    ].merge(o, left_on="l_orderkey", right_on="o_orderkey")
    f["high"] = f.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    f["low"] = 1 - f.high
    want = (
        f.groupby("l_shipmode").agg(
            high_line_count=("high", "sum"), low_line_count=("low", "sum")
        ).reset_index().sort_values("l_shipmode")
    )
    np.testing.assert_array_equal(res["l_shipmode"], want.l_shipmode)
    np.testing.assert_array_equal(res["high_line_count"], want.high_line_count)
    np.testing.assert_array_equal(res["low_line_count"], want.low_line_count)


def test_q14(cat, dfs):
    res = Q.q14(cat).run()
    li, p = dfs["lineitem"], dfs["part"]
    date = tpch.d("1995-09-01")
    f = li[(li.l_shipdate >= date) & (li.l_shipdate < date + 30)].merge(
        p, left_on="l_partkey", right_on="p_partkey"
    )
    f["rev"] = f.l_extendedprice * (1 - f.l_discount)
    promo = f[f.p_type.str.startswith("PROMO")].rev.sum()
    want = 100.0 * promo / f.rev.sum()
    np.testing.assert_allclose(res["promo_revenue"][0], want, rtol=1e-9)


def test_q18(cat, dfs):
    res = Q.q18(cat).run()
    li, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    qty = li.groupby("l_orderkey").l_quantity.sum()
    big = qty[qty > 300].index
    j = (
        o[o.o_orderkey.isin(big)]
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
    )
    want = (
        j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"])
        .agg(sum_qty=("l_quantity", "sum")).reset_index()
        .sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True])
        .head(100)
    )
    assert len(res["o_orderkey"]) == len(want)
    np.testing.assert_array_equal(res["o_orderkey"], want.o_orderkey)
    np.testing.assert_allclose(res["sum_qty"], want.sum_qty, rtol=1e-12)


def test_q5(cat, dfs):
    res = Q.q5(cat).run()
    li, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    s, n, r = dfs["supplier"], dfs["nation"], dfs["region"]
    date = tpch.d("1994-01-01")
    nr = n.merge(r[r.r_name == "ASIA"], left_on="n_regionkey",
                 right_on="r_regionkey")
    of = o[(o.o_orderdate >= date) & (o.o_orderdate < date + 365)]
    j = (
        li.merge(of, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
    )
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(nr, left_on="s_nationkey", right_on="n_nationkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    want = (
        j.groupby("n_name").agg(revenue=("revenue", "sum")).reset_index()
        .sort_values("revenue", ascending=False)
    )
    assert len(res["n_name"]) == len(want)
    np.testing.assert_array_equal(res["n_name"], want.n_name)
    np.testing.assert_allclose(res["revenue"], want.revenue, rtol=1e-9)


# ---------------------------------------------------------------------------
# round 2: the remaining 12 queries


def test_q2(cat, dfs):
    res = Q.q2(cat).run()
    p, s, ps, n, r = (dfs["part"], dfs["supplier"], dfs["partsupp"],
                      dfs["nation"], dfs["region"])
    eu = n[n.n_regionkey.isin(r[r.r_name == "EUROPE"].r_regionkey)]
    es = s[s.s_nationkey.isin(eu.n_nationkey)]
    eps = ps.merge(es, left_on="ps_suppkey", right_on="s_suppkey")
    mi = eps.groupby("ps_partkey").ps_supplycost.min().rename("min_cost")
    pf = p[(p.p_size == 15) & p.p_type.str.endswith("BRASS")]
    j = (eps.merge(pf, left_on="ps_partkey", right_on="p_partkey")
         .merge(mi, left_on="ps_partkey", right_index=True))
    j = j[j.ps_supplycost == j.min_cost].merge(
        eu[["n_nationkey", "n_name"]], left_on="s_nationkey",
        right_on="n_nationkey")
    want = j.sort_values(
        ["s_acctbal", "n_name", "s_name", "p_partkey"],
        ascending=[False, True, True, True]).head(100)
    np.testing.assert_array_equal(res["p_partkey"], want.p_partkey)
    np.testing.assert_allclose(res["s_acctbal"], want.s_acctbal, rtol=1e-9)
    np.testing.assert_array_equal(res["n_name"], want.n_name)
    np.testing.assert_array_equal(res["s_name"], want.s_name)


def test_q7(cat, dfs):
    res = Q.q7(cat).run()
    li, o, c, s, n = (dfs["lineitem"], dfs["orders"], dfs["customer"],
                      dfs["supplier"], dfs["nation"])
    f = li[(li.l_shipdate >= tpch.d("1995-01-01"))
           & (li.l_shipdate <= tpch.d("1996-12-31"))]
    j = (f.merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(n.rename(columns={"n_nationkey": "k1", "n_name": "supp_nation"})
                [["k1", "supp_nation"]], left_on="s_nationkey", right_on="k1")
         .merge(n.rename(columns={"n_nationkey": "k2", "n_name": "cust_nation"})
                [["k2", "cust_nation"]], left_on="c_nationkey", right_on="k2"))
    j = j[((j.supp_nation == "FRANCE") & (j.cust_nation == "GERMANY"))
          | ((j.supp_nation == "GERMANY") & (j.cust_nation == "FRANCE"))]
    j = j.copy()
    j["l_year"] = pd.to_datetime(j.l_shipdate, unit="D").dt.year
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    want = (j.groupby(["supp_nation", "cust_nation", "l_year"])
            .agg(revenue=("volume", "sum")).reset_index()
            .sort_values(["supp_nation", "cust_nation", "l_year"]))
    assert len(res["revenue"]) == len(want)
    np.testing.assert_array_equal(res["supp_nation"], want.supp_nation)
    np.testing.assert_array_equal(res["cust_nation"], want.cust_nation)
    np.testing.assert_array_equal(res["l_year"], want.l_year)
    np.testing.assert_allclose(res["revenue"], want.revenue, rtol=1e-9)


def test_q8(cat, dfs):
    res = Q.q8(cat).run()
    li, o, c, s, n, r, p = (dfs["lineitem"], dfs["orders"], dfs["customer"],
                            dfs["supplier"], dfs["nation"], dfs["region"],
                            dfs["part"])
    pf = p[p.p_type == "ECONOMY ANODIZED STEEL"]
    of = o[(o.o_orderdate >= tpch.d("1995-01-01"))
           & (o.o_orderdate <= tpch.d("1996-12-31"))]
    am = n[n.n_regionkey.isin(r[r.r_name == "AMERICA"].r_regionkey)]
    j = (li[li.l_partkey.isin(pf.p_partkey)]
         .merge(of, left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey"))
    j = j[j.c_nationkey.isin(am.n_nationkey)]
    j = (j.merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(n.rename(columns={"n_nationkey": "k2", "n_name": "nation"})
                [["k2", "nation"]], left_on="s_nationkey", right_on="k2"))
    j = j.copy()
    j["o_year"] = pd.to_datetime(j.o_orderdate, unit="D").dt.year
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    j["nv"] = np.where(j.nation == "BRAZIL", j.volume, 0.0)
    want = (j.groupby("o_year")
            .agg(nat=("nv", "sum"), total=("volume", "sum")).reset_index()
            .sort_values("o_year"))
    want["mkt_share"] = want.nat / want.total
    assert len(res["o_year"]) == len(want)
    np.testing.assert_array_equal(res["o_year"], want.o_year)
    np.testing.assert_allclose(res["mkt_share"], want.mkt_share, rtol=1e-9)


def test_q11(cat, dfs):
    res = Q.q11(cat).run()
    ps, s, n = dfs["partsupp"], dfs["supplier"], dfs["nation"]
    sg = s[s.s_nationkey.isin(n[n.n_name == "GERMANY"].n_nationkey)]
    f = ps[ps.ps_suppkey.isin(sg.s_suppkey)].copy()
    f["value"] = f.ps_supplycost * f.ps_availqty
    per = f.groupby("ps_partkey").value.sum()
    thr = f.value.sum() * 0.0001
    want = per[per > thr].sort_values(ascending=False)
    assert len(res["ps_partkey"]) == len(want)
    np.testing.assert_array_equal(res["ps_partkey"], want.index.to_numpy())
    np.testing.assert_allclose(res["value"], want.to_numpy(), rtol=1e-9)


def test_q13(cat, dfs):
    res = Q.q13(cat).run()
    c, o = dfs["customer"], dfs["orders"]
    of = o[~o.o_comment.str.match(".*special.*requests.*", na=False)]
    j = c.merge(of, left_on="c_custkey", right_on="o_custkey", how="left")
    counts = j.groupby("c_custkey").o_orderkey.count()
    want = (counts.value_counts().rename("custdist").reset_index()
            .rename(columns={"o_orderkey": "c_count", "index": "c_count"})
            .sort_values(["custdist", "c_count"], ascending=[False, False]))
    assert len(res["c_count"]) == len(want)
    np.testing.assert_array_equal(res["c_count"], want.c_count)
    np.testing.assert_array_equal(res["custdist"], want.custdist)


def test_q15(cat, dfs):
    res = Q.q15(cat).run()
    li, s = dfs["lineitem"], dfs["supplier"]
    f = li[(li.l_shipdate >= tpch.d("1996-01-01"))
           & (li.l_shipdate < tpch.d("1996-01-01") + 90)].copy()
    f["rev"] = f.l_extendedprice * (1 - f.l_discount)
    rev = f.groupby("l_suppkey").rev.sum()
    # decimal-exact max: engine sums scaled ints; round to cents like it does
    revc = rev.round(4)
    mrev = revc.max()
    top = revc[revc == mrev]
    want = s[s.s_suppkey.isin(top.index)].sort_values("s_suppkey")
    assert len(res["s_suppkey"]) == len(want)
    np.testing.assert_array_equal(res["s_suppkey"], want.s_suppkey)
    np.testing.assert_array_equal(res["s_name"], want.s_name)
    np.testing.assert_allclose(
        res["total_revenue"],
        revc[want.s_suppkey].to_numpy(), rtol=1e-9)


def test_q16(cat, dfs):
    res = Q.q16(cat).run()
    p, ps, s = dfs["part"], dfs["partsupp"], dfs["supplier"]
    pf = p[(p.p_brand != "Brand#45")
           & ~p.p_type.str.startswith("MEDIUM POLISHED")
           & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    bad = s[s.s_comment.str.match(".*Customer.*Complaints.*", na=False)]
    j = ps[~ps.ps_suppkey.isin(bad.s_suppkey)].merge(
        pf, left_on="ps_partkey", right_on="p_partkey")
    want = (j.groupby(["p_brand", "p_type", "p_size"])
            .ps_suppkey.nunique().rename("supplier_cnt").reset_index()
            .sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                         ascending=[False, True, True, True]))
    assert len(res["p_brand"]) == len(want)
    np.testing.assert_array_equal(res["p_brand"], want.p_brand)
    np.testing.assert_array_equal(res["p_type"], want.p_type)
    np.testing.assert_array_equal(res["p_size"], want.p_size)
    np.testing.assert_array_equal(res["supplier_cnt"], want.supplier_cnt)


def test_q17(cat, dfs):
    res = Q.q17(cat).run()
    li, p = dfs["lineitem"], dfs["part"]
    pf = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
    f = li[li.l_partkey.isin(pf.p_partkey)]
    avg = f.groupby("l_partkey").l_quantity.mean()
    j = f.merge(avg.rename("avg_q"), left_on="l_partkey", right_index=True)
    j = j[j.l_quantity < 0.2 * j.avg_q]
    want = j.l_extendedprice.sum() / 7.0
    np.testing.assert_allclose(float(res["avg_yearly"][0]), want, rtol=1e-9)


def test_q19(cat, dfs):
    li, p = dfs["lineitem"], dfs["part"]
    f = li[li.l_shipmode.isin(["AIR", "AIR REG"])
           & (li.l_shipinstruct == "DELIVER IN PERSON")]
    j = f.merge(p, left_on="l_partkey", right_on="p_partkey")

    def br(b, conts, qlo, qhi, smax):
        return ((j.p_brand == b) & j.p_container.isin(conts)
                & (j.l_quantity >= qlo) & (j.l_quantity <= qhi)
                & (j.p_size >= 1) & (j.p_size <= smax))

    # spec params select zero rows at this tiny scale: SQL SUM over the
    # empty set is NULL (not 0)
    res0 = Q.q19(cat).run()
    assert res0["revenue"][0] is None
    # widened quantity windows + sizes exercise the real disjunction
    res = Q.q19(cat, qty1=0, qty2=0, qty3=0, width=50,
                sizes=(50, 50, 50)).run()
    k = j[br("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"],
             0, 50, 50)
          | br("Brand#23", ["MED BAG", "MED BOX", "MED PKG", "MED PACK"],
               0, 50, 50)
          | br("Brand#34", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"],
               0, 50, 50)]
    assert len(k) > 0
    want = (k.l_extendedprice * (1 - k.l_discount)).sum()
    np.testing.assert_allclose(float(res["revenue"][0]), want, rtol=1e-9)


def test_q20(cat, dfs):
    res = Q.q20(cat).run()
    p, li, ps, s, n = (dfs["part"], dfs["lineitem"], dfs["partsupp"],
                       dfs["supplier"], dfs["nation"])
    pf = p[p.p_name.str.startswith("forest")]
    f = li[(li.l_shipdate >= tpch.d("1994-01-01"))
           & (li.l_shipdate < tpch.d("1994-01-01") + 365)
           & li.l_partkey.isin(pf.p_partkey)]
    sums = f.groupby(["l_partkey", "l_suppkey"]).l_quantity.sum()
    psf = ps[ps.ps_partkey.isin(pf.p_partkey)].merge(
        sums.rename("q").reset_index(),
        left_on=["ps_partkey", "ps_suppkey"],
        right_on=["l_partkey", "l_suppkey"])
    good = psf[psf.ps_availqty > 0.5 * psf.q].ps_suppkey.unique()
    ca = n[n.n_name == "CANADA"].n_nationkey
    want = (s[s.s_nationkey.isin(ca) & s.s_suppkey.isin(good)]
            .sort_values("s_name"))
    assert len(res["s_name"]) == len(want)
    np.testing.assert_array_equal(res["s_name"], want.s_name)
    np.testing.assert_array_equal(res["s_address"], want.s_address)


def test_q21(cat, dfs):
    res = Q.q21(cat).run()
    li, o, s, n = (dfs["lineitem"], dfs["orders"], dfs["supplier"],
                   dfs["nation"])
    n_supp = li.groupby("l_orderkey").l_suppkey.nunique()
    late = li[li.l_receiptdate > li.l_commitdate]
    n_late = late.groupby("l_orderkey").l_suppkey.nunique()
    sa = s[s.s_nationkey.isin(n[n.n_name == "SAUDI ARABIA"].n_nationkey)]
    fo = o[o.o_orderstatus == "F"]
    l1 = late[late.l_orderkey.isin(fo.o_orderkey)
              & late.l_suppkey.isin(sa.s_suppkey)]
    l1 = l1.merge(n_supp.rename("ns"), left_on="l_orderkey",
                  right_index=True)
    l1 = l1.merge(n_late.rename("nl"), left_on="l_orderkey",
                  right_index=True)
    l1 = l1[(l1.ns >= 2) & (l1.nl == 1)]
    l1 = l1.merge(sa[["s_suppkey", "s_name"]], left_on="l_suppkey",
                  right_on="s_suppkey")
    want = (l1.groupby("s_name").size().rename("numwait").reset_index()
            .sort_values(["numwait", "s_name"], ascending=[False, True])
            .head(100))
    assert len(res["s_name"]) == len(want)
    np.testing.assert_array_equal(res["s_name"], want.s_name)
    np.testing.assert_array_equal(res["numwait"], want.numwait)


def test_q22(cat, dfs):
    res = Q.q22(cat).run()
    c, o = dfs["customer"], dfs["orders"]
    codes = ("13", "31", "23", "29", "30", "18", "17")
    f = c[c.c_phone.str[:2].isin(codes)].copy()
    f["cntrycode"] = f.c_phone.str[:2]
    avg = f[f.c_acctbal > 0].c_acctbal.mean()
    k = f[(f.c_acctbal > avg) & ~f.c_custkey.isin(o.o_custkey)]
    want = (k.groupby("cntrycode")
            .agg(numcust=("c_custkey", "size"),
                 totacctbal=("c_acctbal", "sum")).reset_index()
            .sort_values("cntrycode"))
    assert len(res["cntrycode"]) == len(want)
    np.testing.assert_array_equal(res["cntrycode"], want.cntrycode)
    np.testing.assert_array_equal(res["numcust"], want.numcust)
    np.testing.assert_allclose(res["totacctbal"], want.totacctbal,
                               rtol=1e-9)
