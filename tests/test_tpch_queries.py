"""End-to-end TPC-H query tests vs pandas oracles at tiny scale — the analog
of pkg/workload/tpch expected-row validation + the vec-vs-row oracle pattern
(pkg/sql/distsql/columnar_operators_test.go)."""

import numpy as np
import pandas as pd
import pytest

from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpch


@pytest.fixture(scope="module")
def cat():
    return tpch.gen_tpch(sf=0.005, seed=7)


@pytest.fixture(scope="module")
def dfs(cat):
    return {
        n: tpch.to_pandas(cat, n)
        for n in ("lineitem", "orders", "customer", "nation", "region",
                  "supplier", "part", "partsupp")
    }


def test_q1(cat, dfs):
    res = Q.q1(cat).run()
    li = dfs["lineitem"]
    cutoff = tpch.d("1998-12-01") - 90
    f = li[li.l_shipdate <= cutoff].copy()
    f["disc_price"] = (f.l_extendedprice * (1 - f.l_discount)).round(10)
    f["charge"] = (f.disc_price * (1 + f.l_tax)).round(10)
    want = (
        f.groupby(["l_returnflag", "l_linestatus"])
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size"),
        )
        .reset_index()
        .sort_values(["l_returnflag", "l_linestatus"])
    )
    assert len(res["l_returnflag"]) == len(want)
    np.testing.assert_array_equal(res["l_returnflag"], want.l_returnflag)
    np.testing.assert_array_equal(res["l_linestatus"], want.l_linestatus)
    np.testing.assert_allclose(res["sum_qty"], want.sum_qty, rtol=1e-12)
    np.testing.assert_allclose(res["sum_base_price"], want.sum_base_price, rtol=1e-12)
    np.testing.assert_allclose(res["sum_disc_price"], want.sum_disc_price, rtol=1e-9)
    np.testing.assert_allclose(res["sum_charge"], want.sum_charge, rtol=1e-9)
    np.testing.assert_allclose(res["avg_qty"], want.avg_qty, rtol=1e-12)
    np.testing.assert_allclose(res["avg_disc"], want.avg_disc, rtol=1e-12)
    np.testing.assert_array_equal(res["count_order"], want.count_order)


def test_q3(cat, dfs):
    res = Q.q3(cat).run()
    li, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    date = tpch.d("1995-03-15")
    cb = c[c.c_mktsegment == "BUILDING"]
    ob = o[o.o_orderdate < date].merge(cb, left_on="o_custkey", right_on="c_custkey")
    lb = li[li.l_shipdate > date]
    j = lb.merge(ob, left_on="l_orderkey", right_on="o_orderkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    want = (
        j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
        .agg(revenue=("revenue", "sum"))
        .reset_index()
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
        .head(10)
    )
    assert len(res["l_orderkey"]) == len(want)
    np.testing.assert_array_equal(res["l_orderkey"], want.l_orderkey)
    np.testing.assert_allclose(res["revenue"], want.revenue, rtol=1e-9)


def test_q6(cat, dfs):
    res = Q.q6(cat).run()
    li = dfs["lineitem"]
    date = tpch.d("1994-01-01")
    f = li[
        (li.l_shipdate >= date)
        & (li.l_shipdate < date + 365)
        & (li.l_discount >= 0.05 - 1e-9)
        & (li.l_discount <= 0.07 + 1e-9)
        & (li.l_quantity < 24)
    ]
    want = (f.l_extendedprice * f.l_discount).sum()
    np.testing.assert_allclose(res["revenue"][0], want, rtol=1e-9)


def test_q4(cat, dfs):
    res = Q.q4(cat).run()
    li, o = dfs["lineitem"], dfs["orders"]
    date = tpch.d("1993-07-01")
    of = o[(o.o_orderdate >= date) & (o.o_orderdate < date + 92)]
    late = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    j = of[of.o_orderkey.isin(late)]
    want = (
        j.groupby("o_orderpriority").size().reset_index(name="order_count")
        .sort_values("o_orderpriority")
    )
    np.testing.assert_array_equal(res["o_orderpriority"], want.o_orderpriority)
    np.testing.assert_array_equal(res["order_count"], want.order_count)


def test_q9(cat, dfs):
    res = Q.q9(cat).run()
    li, o, s = dfs["lineitem"], dfs["orders"], dfs["supplier"]
    n, p, ps = dfs["nation"], dfs["part"], dfs["partsupp"]
    pg = p[p.p_name.str.contains("green")]
    j = (
        li[li.l_partkey.isin(pg.p_partkey)]
        .merge(ps, left_on=["l_partkey", "l_suppkey"],
               right_on=["ps_partkey", "ps_suppkey"])
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
    )
    j["o_year"] = (
        pd.to_datetime(j.o_orderdate, unit="D", origin="unix").dt.year
    )
    j["amount"] = (
        (j.l_extendedprice * (1 - j.l_discount)).round(4)
        - (j.ps_supplycost * j.l_quantity).round(4)
    )
    want = (
        j.groupby(["n_name", "o_year"]).agg(sum_profit=("amount", "sum"))
        .reset_index().sort_values(["n_name", "o_year"],
                                   ascending=[True, False])
    )
    assert len(res["nation"]) == len(want)
    np.testing.assert_array_equal(res["nation"], want.n_name)
    np.testing.assert_array_equal(res["o_year"], want.o_year)
    np.testing.assert_allclose(res["sum_profit"], want.sum_profit, rtol=1e-9)


def test_q10(cat, dfs):
    res = Q.q10(cat).run()
    li, o, c, n = dfs["lineitem"], dfs["orders"], dfs["customer"], dfs["nation"]
    date = tpch.d("1993-10-01")
    of = o[(o.o_orderdate >= date) & (o.o_orderdate < date + 92)]
    j = (
        li[li.l_returnflag == "R"]
        .merge(of, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    )
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    want = (
        j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                   "c_address", "c_comment"])
        .agg(revenue=("rev", "sum")).reset_index()
        .sort_values(["revenue", "c_custkey"], ascending=[False, True])
        .head(20)
    )
    assert len(res["c_custkey"]) == len(want)
    np.testing.assert_array_equal(res["c_custkey"], want.c_custkey)
    np.testing.assert_allclose(res["revenue"], want.revenue, rtol=1e-9)


def test_q12(cat, dfs):
    res = Q.q12(cat).run()
    li, o = dfs["lineitem"], dfs["orders"]
    date = tpch.d("1994-01-01")
    f = li[
        li.l_shipmode.isin(["MAIL", "SHIP"])
        & (li.l_commitdate < li.l_receiptdate)
        & (li.l_shipdate < li.l_commitdate)
        & (li.l_receiptdate >= date)
        & (li.l_receiptdate < date + 365)
    ].merge(o, left_on="l_orderkey", right_on="o_orderkey")
    f["high"] = f.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    f["low"] = 1 - f.high
    want = (
        f.groupby("l_shipmode").agg(
            high_line_count=("high", "sum"), low_line_count=("low", "sum")
        ).reset_index().sort_values("l_shipmode")
    )
    np.testing.assert_array_equal(res["l_shipmode"], want.l_shipmode)
    np.testing.assert_array_equal(res["high_line_count"], want.high_line_count)
    np.testing.assert_array_equal(res["low_line_count"], want.low_line_count)


def test_q14(cat, dfs):
    res = Q.q14(cat).run()
    li, p = dfs["lineitem"], dfs["part"]
    date = tpch.d("1995-09-01")
    f = li[(li.l_shipdate >= date) & (li.l_shipdate < date + 30)].merge(
        p, left_on="l_partkey", right_on="p_partkey"
    )
    f["rev"] = f.l_extendedprice * (1 - f.l_discount)
    promo = f[f.p_type.str.startswith("PROMO")].rev.sum()
    want = 100.0 * promo / f.rev.sum()
    np.testing.assert_allclose(res["promo_revenue"][0], want, rtol=1e-9)


def test_q18(cat, dfs):
    res = Q.q18(cat).run()
    li, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    qty = li.groupby("l_orderkey").l_quantity.sum()
    big = qty[qty > 300].index
    j = (
        o[o.o_orderkey.isin(big)]
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
    )
    want = (
        j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"])
        .agg(sum_qty=("l_quantity", "sum")).reset_index()
        .sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True])
        .head(100)
    )
    assert len(res["o_orderkey"]) == len(want)
    np.testing.assert_array_equal(res["o_orderkey"], want.o_orderkey)
    np.testing.assert_allclose(res["sum_qty"], want.sum_qty, rtol=1e-12)


def test_q5(cat, dfs):
    res = Q.q5(cat).run()
    li, o, c = dfs["lineitem"], dfs["orders"], dfs["customer"]
    s, n, r = dfs["supplier"], dfs["nation"], dfs["region"]
    date = tpch.d("1994-01-01")
    nr = n.merge(r[r.r_name == "ASIA"], left_on="n_regionkey",
                 right_on="r_regionkey")
    of = o[(o.o_orderdate >= date) & (o.o_orderdate < date + 365)]
    j = (
        li.merge(of, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
    )
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(nr, left_on="s_nationkey", right_on="n_nationkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    want = (
        j.groupby("n_name").agg(revenue=("revenue", "sum")).reset_index()
        .sort_values("revenue", ascending=False)
    )
    assert len(res["n_name"]) == len(want)
    np.testing.assert_array_equal(res["n_name"], want.n_name)
    np.testing.assert_allclose(res["revenue"], want.revenue, rtol=1e-9)
