"""ParallelUnorderedSyncOp — the unordered fan-in with puller threads."""

import time

import numpy as np

from cockroach_tpu.coldata.batch import from_host
from cockroach_tpu.coldata.types import INT64, Schema
from cockroach_tpu.flow.operator import Operator
from cockroach_tpu.flow.operators import ParallelUnorderedSyncOp
from cockroach_tpu.flow.runtime import run_operator

SCHEMA = Schema.of(x=INT64)


class _Source(Operator):
    """Emits the given values one batch each, sleeping per batch."""

    def __init__(self, values, delay_s=0.0, fail_at=None):
        super().__init__()
        self.output_schema = SCHEMA
        self.dictionaries = {}
        self.col_stats = {}
        self.values = values
        self.delay_s = delay_s
        self.fail_at = fail_at
        self._i = 0

    def init(self):
        self._i = 0
        self._initialized = True

    def _next(self):
        if self.fail_at is not None and self._i == self.fail_at:
            raise RuntimeError("source exploded")
        if self._i >= len(self.values):
            return None
        v = self.values[self._i]
        self._i += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        return from_host(SCHEMA, {"x": np.array([v])})


def test_unordered_sync_collects_everything():
    srcs = (_Source([1, 2, 3]), _Source([10, 20]), _Source([]))
    out = run_operator(ParallelUnorderedSyncOp(srcs))
    assert sorted(out["x"]) == [1, 2, 3, 10, 20]


def test_inputs_overlap_instead_of_serializing():
    """Three sources sleeping 60ms per batch x 4 batches: serial draining
    would take >= 720ms; the parallel fan-in overlaps them."""
    srcs = tuple(
        _Source([i * 10 + j for j in range(4)], delay_s=0.06)
        for i in range(3)
    )
    op = ParallelUnorderedSyncOp(srcs)
    t0 = time.time()
    out = run_operator(op)
    el = time.time() - t0
    assert len(out["x"]) == 12
    assert el < 0.55, f"fan-in did not overlap its inputs ({el:.2f}s)"


def test_producer_error_surfaces_and_stops():
    srcs = (_Source(list(range(50)), delay_s=0.005),
            _Source([1, 2], fail_at=1))
    try:
        run_operator(ParallelUnorderedSyncOp(srcs))
        raise AssertionError("expected the source error to surface")
    except Exception as e:  # noqa: BLE001
        assert "source exploded" in str(e)


def test_reinit_restarts_cleanly():
    srcs = (_Source([1, 2, 3]), _Source([4, 5]))
    op = ParallelUnorderedSyncOp(srcs)
    a = run_operator(op)
    b = run_operator(op)
    assert sorted(a["x"]) == sorted(b["x"]) == [1, 2, 3, 4, 5]
