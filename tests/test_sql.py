"""SQL front-end tests: parse + bind TPC-H SQL and diff against the
hand-built Rel plans (the reference's logictest analog — behavior parity
between the SQL surface and the engine; pkg/sql/parser + optbuilder roles)."""

import numpy as np
import pytest

from cockroach_tpu.bench import queries as Q
from cockroach_tpu.bench import tpch
from cockroach_tpu.sql import sql
from cockroach_tpu.sql.parser import parse


@pytest.fixture(scope="module")
def cat():
    return tpch.gen_tpch(sf=0.005, seed=7)


# ---------------------------------------------------------------------------
# parser unit tests


def test_parse_simple():
    s = parse("select a, b as bb from t where a > 3 order by bb desc limit 5")
    assert len(s.items) == 2
    assert s.items[1].alias == "bb"
    assert s.limit == 5
    assert s.order_by[0].desc


def test_parse_join_group():
    s = parse("""
        select x, count(*) from t1 join t2 on t1.a = t2.b
        where c between 1 and 2 group by x having count(*) > 1
    """)
    assert s.group_by and s.having is not None


def test_parse_case_extract():
    s = parse("""
        select case when a = 1 then 2 else 3 end,
               extract(year from d) from t
    """)
    assert len(s.items) == 2


def test_parse_date_interval():
    s = parse("select a from t where d < date '1995-03-15' + interval '3' month")
    assert s.where is not None


def test_parse_errors():
    with pytest.raises(SyntaxError):
        parse("select from t")
    with pytest.raises(SyntaxError):
        parse("select a t where")


# ---------------------------------------------------------------------------
# end-to-end: TPC-H SQL == hand-built plans

TPCH_SQL = {
    "q1": """
        select l_returnflag, l_linestatus,
               sum(l_quantity) as sum_qty,
               sum(l_extendedprice) as sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
               avg(l_quantity) as avg_qty,
               avg(l_extendedprice) as avg_price,
               avg(l_discount) as avg_disc,
               count(*) as count_order
        from lineitem
        where l_shipdate <= date '1998-12-01' - 90
        group by l_returnflag, l_linestatus
        order by l_returnflag, l_linestatus
    """,
    "q3": """
        select l_orderkey,
               sum(l_extendedprice * (1 - l_discount)) as revenue,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING'
          and c_custkey = o_custkey and l_orderkey = o_orderkey
          and o_orderdate < date '1995-03-15'
          and l_shipdate > date '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate
        limit 10
    """,
    "q4": """
        select o_orderpriority, count(*) as order_count
        from orders
        where o_orderdate >= date '1993-07-01'
          and o_orderdate < date '1993-07-01' + interval '3' month
          and exists (
            select * from lineitem
            where l_orderkey = o_orderkey and l_commitdate < l_receiptdate
          )
        group by o_orderpriority
        order by o_orderpriority
    """,
    "q6": """
        select sum(l_extendedprice * l_discount) as revenue
        from lineitem
        where l_shipdate >= date '1994-01-01'
          and l_shipdate < date '1994-01-01' + interval '1' year
          and l_discount between 0.05 and 0.07
          and l_quantity < 24
    """,
    "q10": """
        select c_custkey, c_name,
               sum(l_extendedprice * (1 - l_discount)) as revenue,
               c_acctbal, n_name, c_address, c_phone, c_comment
        from customer, orders, lineitem, nation
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and o_orderdate >= date '1993-10-01'
          and o_orderdate < date '1993-10-01' + interval '3' month
          and l_returnflag = 'R' and c_nationkey = n_nationkey
        group by c_custkey, c_name, c_acctbal, c_phone, n_name, c_address,
                 c_comment
        order by revenue desc, c_custkey
        limit 20
    """,
    "q5": """
        select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
        from customer, orders, lineitem, supplier, nation, region
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and l_suppkey = s_suppkey and c_nationkey = s_nationkey
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'ASIA'
          and o_orderdate >= date '1994-01-01'
          and o_orderdate < date '1994-01-01' + interval '1' year
        group by n_name
        order by revenue desc
    """,
    "q9": """
        select n_name as nation,
               extract(year from o_orderdate) as o_year,
               sum(l_extendedprice * (1 - l_discount)
                   - ps_supplycost * l_quantity) as sum_profit
        from part, supplier, lineitem, partsupp, orders, nation
        where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
          and ps_partkey = l_partkey and p_partkey = l_partkey
          and o_orderkey = l_orderkey and s_nationkey = n_nationkey
          and p_name like '%green%'
        group by nation, o_year
        order by nation, o_year desc
    """,
    "q14": """
        select 100.00 * sum(case when p_type like 'PROMO%'
                                 then l_extendedprice * (1 - l_discount)
                                 else 0.0 end)
               / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
        from lineitem, part
        where l_partkey = p_partkey
          and l_shipdate >= date '1995-09-01'
          and l_shipdate < date '1995-10-01'
    """,
    "q18": """
        select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity) as sum_qty
        from customer, orders, lineitem
        where o_orderkey in (
            select l_orderkey from lineitem
            group by l_orderkey having sum(l_quantity) > 300
          )
          and c_custkey = o_custkey and o_orderkey = l_orderkey
        group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        order by o_totalprice desc, o_orderdate
        limit 100
    """,
    "q12": """
        select l_shipmode,
               sum(case when o_orderpriority = '1-URGENT'
                         or o_orderpriority = '2-HIGH'
                        then 1 else 0 end) as high_line_count,
               sum(case when o_orderpriority <> '1-URGENT'
                        and o_orderpriority <> '2-HIGH'
                        then 1 else 0 end) as low_line_count
        from orders, lineitem
        where o_orderkey = l_orderkey
          and l_shipmode in ('MAIL', 'SHIP')
          and l_commitdate < l_receiptdate
          and l_shipdate < l_commitdate
          and l_receiptdate >= date '1994-01-01'
          and l_receiptdate < date '1994-01-01' + interval '1' year
        group by l_shipmode
        order by l_shipmode
    """,
    "q19": """
        select sum(l_extendedprice * (1 - l_discount)) as revenue
        from lineitem, part
        where (p_partkey = l_partkey and p_brand = 'Brand#12'
               and p_container in ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
               and l_quantity >= 1 and l_quantity <= 11
               and p_size between 1 and 5
               and l_shipmode in ('AIR', 'AIR REG')
               and l_shipinstruct = 'DELIVER IN PERSON')
           or (p_partkey = l_partkey and p_brand = 'Brand#23'
               and p_container in ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
               and l_quantity >= 10 and l_quantity <= 20
               and p_size between 1 and 10
               and l_shipmode in ('AIR', 'AIR REG')
               and l_shipinstruct = 'DELIVER IN PERSON')
           or (p_partkey = l_partkey and p_brand = 'Brand#34'
               and p_container in ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
               and l_quantity >= 20 and l_quantity <= 30
               and p_size between 1 and 15
               and l_shipmode in ('AIR', 'AIR REG')
               and l_shipinstruct = 'DELIVER IN PERSON')
    """,
    "q7": """
        select supp_nation, cust_nation, l_year, sum(volume) as revenue
        from (
            select n1.n_name as supp_nation, n2.n_name as cust_nation,
                   extract(year from l_shipdate) as l_year,
                   l_extendedprice * (1 - l_discount) as volume
            from supplier, lineitem, orders, customer, nation as n1,
                 nation as n2
            where s_suppkey = l_suppkey and o_orderkey = l_orderkey
              and c_custkey = o_custkey and s_nationkey = n1.n_nationkey
              and c_nationkey = n2.n_nationkey
              and ((n1.n_name = 'FRANCE' and n2.n_name = 'GERMANY')
                or (n1.n_name = 'GERMANY' and n2.n_name = 'FRANCE'))
              and l_shipdate between date '1995-01-01' and date '1996-12-31'
        ) as shipping
        group by supp_nation, cust_nation, l_year
        order by supp_nation, cust_nation, l_year
    """,
    "q8": """
        select o_year,
               sum(case when nation = 'BRAZIL' then volume else 0.0 end)
               / sum(volume) as mkt_share
        from (
            select extract(year from o_orderdate) as o_year,
                   l_extendedprice * (1 - l_discount) as volume,
                   n2.n_name as nation
            from part, supplier, lineitem, orders, customer, nation as n1,
                 nation as n2, region
            where p_partkey = l_partkey and s_suppkey = l_suppkey
              and l_orderkey = o_orderkey and o_custkey = c_custkey
              and c_nationkey = n1.n_nationkey
              and n1.n_regionkey = r_regionkey and r_name = 'AMERICA'
              and s_nationkey = n2.n_nationkey
              and o_orderdate between date '1995-01-01' and date '1996-12-31'
              and p_type = 'ECONOMY ANODIZED STEEL'
        ) as all_nations
        group by o_year
        order by o_year
    """,
    "q13": """
        select c_count, count(*) as custdist
        from (
            select c_custkey, count(o_orderkey) as c_count
            from customer left outer join orders
                 on c_custkey = o_custkey
                 and o_comment not like '%special%requests%'
            group by c_custkey
        ) as c_orders
        group by c_count
        order by custdist desc, c_count desc
    """,
    "q17": """
        select sum(l_extendedprice) / 7.0 as avg_yearly
        from lineitem, part
        where p_partkey = l_partkey
          and p_brand = 'Brand#23' and p_container = 'MED BOX'
          and l_quantity < (
              select 0.2 * avg(l_quantity) from lineitem
              where l_partkey = p_partkey)
    """,
    "q16": """
        select p_brand, p_type, p_size,
               count(distinct ps_suppkey) as supplier_cnt
        from partsupp, part
        where p_partkey = ps_partkey
          and p_brand <> 'Brand#45'
          and p_type not like 'MEDIUM POLISHED%'
          and p_size in (49, 14, 23, 45, 19, 3, 36, 9)
          and ps_suppkey not in (
              select s_suppkey from supplier
              where s_comment like '%Customer%Complaints%')
        group by p_brand, p_type, p_size
        order by supplier_cnt desc, p_brand, p_type, p_size
    """,
    "q11": """
        select ps_partkey, sum(ps_supplycost * ps_availqty) as value
        from partsupp, supplier, nation
        where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
          and n_name = 'GERMANY'
        group by ps_partkey
        having sum(ps_supplycost * ps_availqty) > (
            select sum(ps_supplycost * ps_availqty) * 0.0001
            from partsupp, supplier, nation
            where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
              and n_name = 'GERMANY')
        order by value desc
    """,
    "q2": """
        select s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address,
               s_phone, s_comment
        from part, supplier, partsupp, nation, region
        where p_partkey = ps_partkey and s_suppkey = ps_suppkey
          and p_size = 15 and p_type like '%BRASS'
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'EUROPE'
          and ps_supplycost = (
              select min(ps_supplycost)
              from partsupp, supplier, nation, region
              where p_partkey = ps_partkey and s_suppkey = ps_suppkey
                and s_nationkey = n_nationkey and n_regionkey = r_regionkey
                and r_name = 'EUROPE')
        order by s_acctbal desc, n_name, s_name, p_partkey
        limit 100
    """,
    "q20": """
        select s_name, s_address
        from supplier, nation
        where s_suppkey in (
            select ps_suppkey from partsupp
            where ps_partkey in (
                select p_partkey from part where p_name like 'forest%')
              and ps_availqty > (
                  select 0.5 * sum(l_quantity) from lineitem
                  where l_partkey = ps_partkey and l_suppkey = ps_suppkey
                    and l_shipdate >= date '1994-01-01'
                    and l_shipdate < date '1994-01-01' + interval '1' year)
          )
          and s_nationkey = n_nationkey and n_name = 'CANADA'
        order by s_name
    """,
    "q21": """
        select s_name, count(*) as numwait
        from supplier, lineitem as l1, orders, nation
        where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey
          and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate
          and exists (
              select * from lineitem as l2
              where l2.l_orderkey = l1.l_orderkey
                and l2.l_suppkey <> l1.l_suppkey)
          and not exists (
              select * from lineitem as l3
              where l3.l_orderkey = l1.l_orderkey
                and l3.l_suppkey <> l1.l_suppkey
                and l3.l_receiptdate > l3.l_commitdate)
          and s_nationkey = n_nationkey and n_name = 'SAUDI ARABIA'
        group by s_name
        order by numwait desc, s_name
        limit 100
    """,
    "q22": """
        select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
        from (
            select substring(c_phone from 1 for 2) as cntrycode, c_acctbal
            from customer
            where substring(c_phone from 1 for 2)
                  in ('13', '31', '23', '29', '30', '18', '17')
              and c_acctbal > (
                  select avg(c_acctbal) from customer
                  where c_acctbal > 0.00
                    and substring(c_phone from 1 for 2)
                        in ('13', '31', '23', '29', '30', '18', '17'))
              and not exists (
                  select * from orders where o_custkey = c_custkey)
        ) as custsale
        group by cntrycode
        order by cntrycode
    """,
    "q15": """
        with revenue as (
            select l_suppkey as supplier_no,
                   sum(l_extendedprice * (1 - l_discount)) as total_revenue
            from lineitem
            where l_shipdate >= date '1996-01-01'
              and l_shipdate < date '1996-01-01' + 90
            group by l_suppkey
        )
        select s_suppkey, s_name, s_address, s_phone, total_revenue
        from supplier, revenue
        where s_suppkey = supplier_no
          and total_revenue = (select max(total_revenue) from revenue)
        order by s_suppkey
    """,
}


# the compile-heaviest sweeps (multi-join Q2/Q5/Q7/Q8... plans take
# 20-50s of XLA compile each on this host) run in the slow tier; tier-1
# keeps a representative spread of the parser/planner surface under its
# wall-clock cap, `-m slow` covers the full 22
_COMPILE_HEAVY = {"q2", "q3", "q5", "q7", "q8", "q9", "q10", "q11",
                  "q16", "q18", "q20", "q21"}


@pytest.mark.parametrize("qname", [
    pytest.param(q, marks=pytest.mark.slow) if q in _COMPILE_HEAVY else q
    for q in sorted(TPCH_SQL)
])
def test_tpch_sql_matches_handbuilt(cat, qname):
    got = sql(cat, TPCH_SQL[qname]).run()
    want = Q.QUERIES[qname](cat).run()
    assert set(got) >= set(want), f"missing columns: {set(want) - set(got)}"
    for col in want:
        w = want[col]
        g = got[col]
        assert len(g) == len(w), f"{col}: {len(g)} vs {len(w)} rows"
        if w.dtype.kind == "f" or g.dtype.kind == "f":
            np.testing.assert_allclose(
                g.astype(np.float64), w.astype(np.float64), rtol=1e-9,
                err_msg=col,
            )
        else:
            np.testing.assert_array_equal(g, w, err_msg=col)


def test_sql_scalar_subquery(cat):
    got = sql(cat, """
        select count(*) as n from lineitem
        where l_extendedprice > (select avg(l_extendedprice) from lineitem)
    """).run()
    df = tpch.to_pandas(cat, "lineitem")
    want = int((df.l_extendedprice > df.l_extendedprice.mean()).sum())
    assert int(got["n"][0]) == want


def test_sql_in_select_semi(cat):
    got = sql(cat, """
        select count(*) as n from orders
        where o_orderkey in (select l_orderkey from lineitem
                             where l_quantity > 49)
    """).run()
    li = tpch.to_pandas(cat, "lineitem")
    o = tpch.to_pandas(cat, "orders")
    big = li[li.l_quantity > 49].l_orderkey.unique()
    want = int(o.o_orderkey.isin(big).sum())
    assert int(got["n"][0]) == want


def test_sql_not_in_select_anti(cat):
    got = sql(cat, """
        select count(*) as n from customer
        where c_custkey not in (select o_custkey from orders)
    """).run()
    c = tpch.to_pandas(cat, "customer")
    o = tpch.to_pandas(cat, "orders")
    want = int((~c.c_custkey.isin(o.o_custkey)).sum())
    assert int(got["n"][0]) == want


def test_sql_distinct_and_like(cat):
    got = sql(cat, """
        select distinct p_mfgr from part where p_name like '%green%'
        order by p_mfgr
    """).run()
    p = tpch.to_pandas(cat, "part")
    want = np.sort(p[p.p_name.str.contains("green")].p_mfgr.unique())
    np.testing.assert_array_equal(got["p_mfgr"], want)


def test_sql_duplicate_agg_names_and_order(cat):
    got = sql(cat, """
        select l_returnflag, sum(l_quantity), sum(l_extendedprice)
        from lineitem group by l_returnflag
        order by sum(l_extendedprice) desc
    """).run()
    li = tpch.to_pandas(cat, "lineitem")
    w = (li.groupby("l_returnflag")
         .agg(q=("l_quantity", "sum"), e=("l_extendedprice", "sum"))
         .reset_index().sort_values("e", ascending=False))
    assert "sum" in got and "sum_1" in got  # both aggregates survive
    np.testing.assert_array_equal(got["l_returnflag"], w.l_returnflag)
    np.testing.assert_allclose(got["sum"].astype(np.float64), w.q, rtol=1e-9)
    np.testing.assert_allclose(got["sum_1"].astype(np.float64), w.e, rtol=1e-9)


def test_sql_double_negated_in(cat):
    got = sql(cat, """
        select count(*) as n from customer
        where not (c_custkey not in (select o_custkey from orders))
    """).run()
    c = tpch.to_pandas(cat, "customer")
    o = tpch.to_pandas(cat, "orders")
    want = int(c.c_custkey.isin(o.o_custkey).sum())
    assert int(got["n"][0]) == want


def test_sql_offset_without_limit(cat):
    got = sql(cat, """
        select n_nationkey from nation order by n_nationkey offset 5
    """).run()
    np.testing.assert_array_equal(got["n_nationkey"], np.arange(5, 25))


def test_sql_correlated_nonequality_exists(cat):
    """EXISTS with an extra <> correlation (TPC-H q21's shape) rewrites to a
    min/max-per-key grouped join; oracle is pandas."""
    got = sql(cat, """
        select count(*) as n from lineitem l1
        where exists (
          select * from lineitem l2
          where l2.l_orderkey = l1.l_orderkey
            and l2.l_suppkey <> l1.l_suppkey
        )
    """).run()
    li = tpch.to_pandas(cat, "lineitem")
    per = li.groupby("l_orderkey").l_suppkey.agg(["min", "max"])
    j = li.merge(per, left_on="l_orderkey", right_index=True)
    want = int(((j["min"] != j.l_suppkey) | (j["max"] != j.l_suppkey)).sum())
    assert int(got["n"][0]) == want


def test_sql_correlated_nonequality_not_exists(cat):
    got = sql(cat, """
        select count(*) as n from lineitem l1
        where not exists (
          select * from lineitem l2
          where l2.l_orderkey = l1.l_orderkey
            and l2.l_suppkey <> l1.l_suppkey
        )
    """).run()
    li = tpch.to_pandas(cat, "lineitem")
    per = li.groupby("l_orderkey").l_suppkey.agg(["min", "max"])
    j = li.merge(per, left_on="l_orderkey", right_index=True)
    want = int(((j["min"] == j.l_suppkey) & (j["max"] == j.l_suppkey)).sum())
    assert int(got["n"][0]) == want


def test_sql_subquery_in_from(cat):
    got = sql(cat, """
        select n_name, total from (
            select n_name, sum(s_acctbal) as total
            from supplier, nation
            where s_nationkey = n_nationkey
            group by n_name
        ) as t
        where total > 0
        order by total desc
    """).run()
    s = tpch.to_pandas(cat, "supplier")
    n = tpch.to_pandas(cat, "nation")
    j = s.merge(n, left_on="s_nationkey", right_on="n_nationkey")
    w = j.groupby("n_name").s_acctbal.sum().reset_index()
    w = w[w.s_acctbal > 0].sort_values("s_acctbal", ascending=False)
    np.testing.assert_array_equal(got["n_name"], w.n_name)
    np.testing.assert_allclose(
        got["total"].astype(np.float64), w.s_acctbal, rtol=1e-9
    )


def test_sql_not_in_three_valued():
    """NOT IN follows three-valued logic even over nullable columns: a NULL
    in the subquery empties the result; NULL probe keys are dropped; an
    empty subquery keeps every row (x NOT IN () is TRUE)."""
    import cockroach_tpu.catalog as catalog_mod
    from cockroach_tpu.coldata.types import INT64, Schema

    c2 = catalog_mod.Catalog()
    c2.add(catalog_mod.Table.from_strings(
        "t", Schema.of(a=INT64), {"a": np.arange(5)}))
    c2.add(catalog_mod.Table.from_strings(
        "u", Schema.of(b=INT64, c=INT64),
        {"b": np.arange(3), "c": np.arange(100, 103)},
        valids={"b": np.array([True, False, True])}))
    # NULL in the subquery result: NOT IN is never true -> empty
    got = sql(c2, "select count(*) as n from t "
                  "where a not in (select b from u)").run()
    assert int(got["n"][0]) == 0
    # nullable OUTER argument: NULL probe keys dropped, others anti-join
    got = sql(c2, "select count(*) as n from u "
                  "where b not in (select a from t)").run()
    assert int(got["n"][0]) == 0  # b values {0, 2} are all in t; NULL dropped
    got = sql(c2, "select count(*) as n from u "
                  "where b not in (select c from u)").run()
    assert int(got["n"][0]) == 2  # {0, 2} not in {100..102}; NULL dropped
    # empty subquery: every row passes, even the NULL-key one
    got = sql(c2, "select count(*) as n from u "
                  "where b not in (select a from t where a > 100)").run()
    assert int(got["n"][0]) == 3
    # IN (not negated) over the same nullable column is fine
    got = sql(c2, "select count(*) as n from t "
                  "where a in (select b from u)").run()
    assert int(got["n"][0]) >= 1
    # and NOT IN over provably non-null columns still binds (no execution
    # of the subquery at bind time on this fast path)
    got = sql(c2, "select count(*) as n from t "
                  "where a not in (select c from u)").run()
    assert int(got["n"][0]) == 5