"""Cross-cluster physical replication over the rangefeed plane."""

import time

from cockroach_tpu.kv import DB, Clock
from cockroach_tpu.kv.changefeed import RangefeedServer
from cockroach_tpu.kv.replication import ReplicationStream
from cockroach_tpu.storage.lsm import Engine


def _cluster():
    return DB(Engine(key_width=16, val_width=32, memtable_size=64), Clock())


def test_replicates_writes_updates_deletes_with_history():
    src = _cluster()
    dst = _cluster()
    ts1 = src.put(b"ra", b"v1")
    src.put(b"ra", b"v2")
    src.put(b"rb", b"\x00\xff bytes ok")  # non-utf8 value: byte-exact
    src.delete(b"rc_pre")  # tombstone
    srv = RangefeedServer(src, poll_interval_s=0.02)
    try:
        repl = ReplicationStream(srv.addr, dst, start=b"r",
                                 end=b"s").run_background()
        mark = src.put(b"rd", b"late")
        assert repl.wait_for_frontier(mark), (repl.frontier, mark)
        # byte-exact at now
        assert dst.get(b"ra") == b"v2"
        assert dst.get(b"rb") == b"\x00\xff bytes ok"
        assert dst.get(b"rd") == b"late"
        # TIME TRAVEL: the standby serves the same history as the source
        assert dst.get(b"ra", ts=ts1) == b"v1"
        assert src.get(b"ra", ts=ts1) == b"v1"

        # resolved frontier respects intents: an open txn holds it back
        t = src.new_txn()
        t.put(b"re", b"pending")
        f0 = repl.frontier
        src.put(b"rf", b"after-intent")
        time.sleep(0.2)
        assert repl.frontier <= src.clock.now()
        assert dst.get(b"re") is None  # intent never replicates
        t.commit()
        mark2 = src.put(b"rg", b"post-commit")
        assert repl.wait_for_frontier(mark2)
        assert dst.get(b"re") == b"pending"  # committed version arrived
        assert f0 >= 0

        # cutover: later source writes never arrive
        frontier = repl.cutover()
        src.put(b"rz", b"too-late")
        time.sleep(0.15)
        assert dst.get(b"rz") is None
        assert frontier >= mark2
    finally:
        srv.close()


def test_span_bounded_replication():
    src = _cluster()
    dst = _cluster()
    srv = RangefeedServer(src, poll_interval_s=0.02)
    try:
        repl = ReplicationStream(srv.addr, dst, start=b"m",
                                 end=b"n").run_background()
        src.put(b"a_out", b"x")
        mark = src.put(b"m_in", b"y")
        assert repl.wait_for_frontier(mark)
        assert dst.get(b"m_in") == b"y"
        assert dst.get(b"a_out") is None  # outside the replicated span
        repl.cutover()
    finally:
        srv.close()


def test_stream_reconnects_from_frontier_after_source_restart():
    """The source rangefeed server dies mid-stream and restarts on the
    same port (with an injected transient dial failure on top): the
    stream re-subscribes FROM THE FRONTIER with backoff, the standby
    converges, and the reconnect is visible in metrics — never a dead
    stream masquerading as healthy."""
    from cockroach_tpu.utils import faults, metric
    from cockroach_tpu.utils.faults import FaultSpec

    src = _cluster()
    dst = _cluster()
    srv = RangefeedServer(src, poll_interval_s=0.02)
    addr = srv.addr
    srv2 = None
    reconnects_before = metric.REPLICATION_RECONNECTS.value
    repl = ReplicationStream(srv.addr, dst, start=b"r",
                             end=b"s").run_background()
    try:
        mark = src.put(b"ra", b"pre-crash")
        assert repl.wait_for_frontier(mark)
        # crash the source server; the first re-dial also fails (injected)
        # so the reconnect path exercises its retry/backoff, not just a
        # lucky instant rebind
        faults.arm(61, {
            "kv.rangefeed.subscribe": FaultSpec(kind="error", p=1.0,
                                                max_fires=1),
        })
        srv.close()
        srv2 = RangefeedServer(src, poll_interval_s=0.02, port=addr[1])
        mark2 = src.put(b"rb", b"post-restart")
        assert repl.wait_for_frontier(mark2), (repl.frontier, mark2)
        faults.disarm()
        assert repl.reconnects >= 1
        assert metric.REPLICATION_RECONNECTS.value > reconnects_before
        assert dst.get(b"ra") == b"pre-crash"
        assert dst.get(b"rb") == b"post-restart"
        frontier = repl.cutover()
        assert frontier >= mark2
    finally:
        faults.disarm()
        try:
            repl.cutover()  # idempotent; stops the stream on any exit path
        except RuntimeError:
            pass  # a parked stream error already surfaced above
        if srv2 is not None:
            srv2.close()
        srv.close()


def test_external_storage_schemes(tmp_path):
    """pkg/cloud reduction: nodelocal:// BACKUP/RESTORE round-trips
    through the scheme registry; cloud schemes fail with guidance."""
    from cockroach_tpu.sql.session import Session
    from cockroach_tpu.utils import external_storage as es

    es.set_nodelocal_base(str(tmp_path / "extern"))
    try:
        sess = Session()
        sess.execute("create table bk (id int primary key, v int)")
        sess.execute("insert into bk values (1, 10), (2, 20)")
        res = sess.execute("backup to 'nodelocal://self/backups/b1'")
        assert res["state"] == "succeeded"
        # files landed under the nodelocal base
        import os

        assert os.path.isdir(tmp_path / "extern" / "backups" / "b1")
        sess.execute("insert into bk values (3, 30)")
        sess.execute("restore from 'nodelocal://self/backups/b1'")
        got = sess.execute("select count(*) as n from bk")
        assert int(got["n"][0]) == 2  # post-backup insert rolled away

        # cloud schemes: explicit configuration error, not a crash
        try:
            sess.execute("backup to 's3://bucket/b2'")
            raise AssertionError("expected s3 to be unconfigured")
        except Exception as e:  # noqa: BLE001
            assert "not configured" in str(e)

        # storage surface: write/read/list/delete + path-escape guard
        st, path = es.from_uri("nodelocal://self/files/a.txt")
        st.write_file(path, b"hello")
        assert st.read_file(path) == b"hello"
        assert "files/a.txt" in st.list("files/")
        st.delete(path)
        assert "files/a.txt" not in st.list("files/")
        try:
            es.resolve_dir_uri("nodelocal://self/../escape")
            raise AssertionError("expected path-escape rejection")
        except ValueError:
            pass
    finally:
        es.set_nodelocal_base(".extern")
