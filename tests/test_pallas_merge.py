"""Pallas bitonic-merge parity vs the concat+sort path (interpret mode on
CPU; the real-chip win is the compaction phase of bench.py's YCSB run)."""

import numpy as np
import pytest

import jax.numpy as jnp

from cockroach_tpu.storage import mvcc
from cockroach_tpu.storage import pallas_merge as pm


def _random_sorted_run(rng, n, cap=None, nkeys=25, val_width=8):
    """A sorted KVBlock run with random keys/versions, some dead rows and
    a dead pad tail (exactly what LSM flush produces)."""
    cap = cap or int(2 ** np.ceil(np.log2(max(n, 4))))
    keys = np.zeros((cap, 16), np.uint8)
    ts = np.zeros(cap, np.int64)
    seq = np.zeros(cap, np.int64)
    txn = np.zeros(cap, np.int64)
    tomb = np.zeros(cap, bool)
    value = np.zeros((cap, val_width), np.uint8)
    vlen = np.zeros(cap, np.int32)
    mask = np.zeros(cap, bool)
    for i in range(n):
        kb = b"user%07d" % rng.integers(0, nkeys)
        keys[i, : len(kb)] = np.frombuffer(kb, np.uint8)
        ts[i] = rng.integers(1, 1000)
        seq[i] = rng.integers(1, 1 << 40)  # globally unique w.h.p.
        txn[i] = rng.integers(0, 2)
        tomb[i] = rng.random() < 0.15
        value[i, : 4] = np.frombuffer(np.int32(i).tobytes(), np.uint8)
        vlen[i] = 4
        mask[i] = rng.random() < 0.95
    blk = mvcc.KVBlock(
        key=jnp.asarray(keys), ts=jnp.asarray(ts), seq=jnp.asarray(seq),
        txn=jnp.asarray(txn), tomb=jnp.asarray(tomb),
        value=jnp.asarray(value), vlen=jnp.asarray(vlen),
        mask=jnp.asarray(mask),
    )
    return mvcc.sort_block(blk)


def _live_tuples(blk):
    """Ordered (key, ts, seq, txn, tomb, value) tuples of live rows —
    the observable content, in sorted order."""
    m = np.asarray(blk.mask)
    rows = []
    for i in np.flatnonzero(m):
        rows.append((
            bytes(np.asarray(blk.key[i])),
            int(blk.ts[i]), int(blk.seq[i]), int(blk.txn[i]),
            bool(blk.tomb[i]),
            bytes(np.asarray(blk.value[i]))[: int(blk.vlen[i])],
        ))
    return rows


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("sizes", [
    # each size shape is its own 15-20s XLA compile on this host: tier-1
    # keeps the square case, the odd shapes ride the slow tier
    pytest.param((30, 50), marks=pytest.mark.slow),
    (64, 64),
    pytest.param((5, 120), marks=pytest.mark.slow),
    pytest.param((1, 1), marks=pytest.mark.slow),
])
def test_merge_pair_matches_sort(seed, sizes):
    rng = np.random.default_rng(seed)
    a = _random_sorted_run(rng, sizes[0])
    b = _random_sorted_run(rng, sizes[1])
    got = pm.merge_pair(a, b, interpret=True)
    total = a.capacity + b.capacity
    want = mvcc.merge_blocks((a, b), cap=total)
    assert _live_tuples(got) == _live_tuples(want)


# ~1-2 min of pallas-interpret tracing per k on the CI box; tier-1 keeps
# the pairwise kernels, `-m slow` covers the tournament tree
@pytest.mark.slow
@pytest.mark.parametrize("k", [3, 4, 5])
def test_merge_tournament_matches_sort(k):
    rng = np.random.default_rng(7 + k)
    runs = tuple(
        _random_sorted_run(rng, int(rng.integers(10, 90))) for _ in range(k)
    )
    assert pm.eligible(runs)
    got = pm.merge_runs(runs, interpret=True)
    want = mvcc.merge_blocks(runs, cap=sum(r.capacity for r in runs))
    assert _live_tuples(got) == _live_tuples(want)


def test_eligibility_bound():
    rng = np.random.default_rng(3)
    small = tuple(_random_sorted_run(rng, 8) for _ in range(2))
    assert pm.eligible(small)
    big = mvcc.empty_block(pm.MAX_MERGE_ROWS, 16, 8)
    assert not pm.eligible((big, big))
    assert not pm.eligible((small[0],))


@pytest.mark.slow
def test_engine_compaction_uses_kernel_result():
    """Engine.compact with the pallas merge enabled (interpret mode)
    produces the same live content as the sort path."""
    from cockroach_tpu.storage.lsm import Engine

    def build(pallas):
        eng = Engine(key_width=16, val_width=8, l0_trigger=64)
        eng._pallas_merge_interpret = True
        eng.pallas_merge = pallas
        rng = np.random.default_rng(11)
        for i in range(300):
            eng.put(b"k%05d" % rng.integers(0, 60), b"v%06d" % i, ts=i + 1)
            if i % 90 == 89:
                eng.flush_mem_only()
        eng.compact(bottom=False)
        eng.compact(bottom=True)
        return eng.scan(None, None, ts=1 << 40)

    assert build(True) == build(False)
