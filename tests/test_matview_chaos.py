"""Matview chaos: injected faults at the three maintenance sites
(utils/faults.py matview.*) must leave the standing state untouched —
the flush is all-or-nothing against the subscriber's un-acked buffer,
so a retry resumes from the resolved frontier with no delta lost and
none applied twice. Every scenario ends with the bit-identity oracle:
view state after fault + retry == fresh full rescan.

Fast seeds only (the test_chaos.py discipline): deterministic, runs in
tier-1, excluded with -m 'not chaos'."""

import numpy as np
import pytest

from cockroach_tpu.sql import Session, matview
from cockroach_tpu.utils import faults, locks, racesan, settings
from cockroach_tpu.utils.faults import FaultSpec, InjectedFault

pytestmark = pytest.mark.chaos

Q = ("SELECT flag, sum(qty) AS sq, avg(price) AS ap, count(*) AS n "
     "FROM t WHERE d <= DATE '1998-06-15' GROUP BY flag ORDER BY flag")


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


@pytest.fixture(autouse=True)
def _lock_order_detector():
    locks.reset()
    prev = settings.get("debug.lock_order.enabled")
    settings.set("debug.lock_order.enabled", True)
    yield
    settings.set("debug.lock_order.enabled", prev)
    locks.reset()


@pytest.fixture(autouse=True)
def _race_sanitizer():
    racesan.reset()
    prev = settings.get("debug.race_detector.enabled")
    settings.set("debug.race_detector.enabled", True)
    yield
    settings.set("debug.race_detector.enabled", prev)
    racesan.reset()


def _setup():
    s = Session(val_width=160)
    s.execute("CREATE TABLE t (k INT PRIMARY KEY, flag STRING, "
              "qty DECIMAL(12,2), price DECIMAL(12,2), d DATE)")
    for i in range(30):
        s.execute(
            f"INSERT INTO t VALUES ({i}, '{'AB'[i % 2]}', {i}.25, "
            f"{i * 2}.50, DATE '1998-0{1 + i % 8}-0{1 + i % 9}')")
    s.execute(f"CREATE MATERIALIZED VIEW mv AS {Q}")
    return s


def _oracle(s):
    prev = settings.get("sql.matview.rewrite.enabled")
    settings.set("sql.matview.rewrite.enabled", False)
    try:
        return s.execute(Q)
    finally:
        settings.set("sql.matview.rewrite.enabled", prev)


def _assert_same(a, b, ctx=""):
    assert list(a) == list(b), (ctx, list(a), list(b))
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), (
            ctx, k, a[k], b[k])


@pytest.mark.parametrize("site", [
    "matview.flush",
    "matview.delta.apply",
    "matview.frontier.checkpoint",
])
def test_faulted_flush_resumes_from_frontier(site):
    """Kill the flush at each stage: nothing commits (frontier, standing
    state and the un-acked event buffer are all unchanged), and the
    retried flush applies the SAME delta exactly once."""
    s = _setup()
    try:
        reg = matview.registry_for(s.catalog)
        view = reg.views["mv"]
        m = reg.maintainers["t"]
        f0 = view.frontier
        assert f0 > 0
        # mixed delta: insert + update (retraction) + delete (tombstone)
        s.execute("INSERT INTO t VALUES (100, 'A', 7.00, 3.00, "
                  "DATE '1998-02-02')")
        s.execute("UPDATE t SET qty = 99.75 WHERE k = 2")
        s.execute("DELETE FROM t WHERE k = 3")
        m.pump()
        assert m.pending()
        faults.arm(1234, {site: FaultSpec(kind="error", p=1.0, max_fires=1)})
        with pytest.raises(InjectedFault):
            m.flush()
        # all-or-nothing: no partial commit
        assert view.frontier == f0
        assert m.frontier == f0
        assert m.pending()  # events stay buffered until the ack
        # retry (the fault's max_fires is exhausted): exactly-once apply
        assert m.flush()
        assert view.frontier > f0
        reg.materialize(view)
        _assert_same(_oracle(s),
                     s.execute("SELECT * FROM mv ORDER BY flag"),
                     ctx=site)
    finally:
        matview.close_all(s.catalog)


def test_fault_storm_converges():
    """Faults across several flush attempts interleaved with more DML:
    whatever subset of flushes dies, the survivors plus the final clean
    flush must converge to the rescan oracle (no lost or doubled
    delta across the whole history)."""
    s = _setup()
    try:
        reg = matview.registry_for(s.catalog)
        view = reg.views["mv"]
        m = reg.maintainers["t"]
        faults.arm(99, {
            "matview.delta.apply": FaultSpec(kind="error", p=0.5,
                                             max_fires=3),
            "matview.frontier.checkpoint": FaultSpec(kind="error", p=0.3,
                                                     max_fires=2),
        })
        for i in range(8):
            s.execute(f"INSERT INTO t VALUES ({200 + i}, '{'AB'[i % 2]}', "
                      f"{i}.50, {i}.00, DATE '1998-03-0{1 + i}')")
            if i % 2 == 1:
                s.execute(f"DELETE FROM t WHERE k = {i}")
            m.pump()
            try:
                m.flush()
            except InjectedFault:
                pass
        faults.disarm()
        m.pump()
        m.flush()
        reg.materialize(view)
        _assert_same(_oracle(s),
                     s.execute("SELECT * FROM mv ORDER BY flag"),
                     ctx="storm")
    finally:
        matview.close_all(s.catalog)
