"""Cross-host flow streams (DCN skeleton): a table split across TWO
PROCESSES joins back together through Arrow-over-socket Outbox/Inbox —
the colrpc FlowStream parity point (outbox.go:44 / inbox.go:48 /
execinfrapb api.proto SetupFlow), with the second process standing in for
a remote node."""

import multiprocessing as mp

import numpy as np
import pytest

from cockroach_tpu.bench import tpch
from cockroach_tpu.coldata.types import Schema
from cockroach_tpu.flow import dcn
from cockroach_tpu.flow.operators import ScanOp, UnionOp
from cockroach_tpu.flow.runtime import run_operator


def _half_catalog(half: int):
    """Deterministic split: both processes regenerate the same catalog and
    take complementary halves of `orders` (the range/leaseholder split
    stand-in)."""
    cat = tpch.gen_tpch(sf=0.005, seed=23)
    t = cat.get("orders")
    n = t.num_rows
    sel = np.arange(n) % 2 == half
    t.columns = {k: v[sel] for k, v in t.columns.items()}
    t.valids = {k: v[sel] for k, v in t.valids.items()}
    t._device = None
    t._stats = None
    return cat


def _serve_half(q):
    """Child process: serve the scan of ITS half of orders as a flow."""
    from cockroach_tpu.utils.backend import force_cpu_backend

    force_cpu_backend()
    cat = _half_catalog(1)

    def make_op():
        return ScanOp(cat.get("orders"))

    srv = dcn.FlowServer({"orders_half": make_op}).serve_background()
    q.put(srv.addr)
    # serve until the parent says stop
    q.get()
    srv.close()


@pytest.fixture(scope="module")
def remote():
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_serve_half, args=(q,), daemon=True)
    p.start()
    addr = q.get(timeout=120)
    yield addr
    q.put("stop")
    p.join(timeout=10)
    if p.is_alive():
        p.terminate()


def test_two_process_scan_union(remote):
    """Local half UNION remote half == the whole table."""
    cat = _half_catalog(0)
    full = tpch.gen_tpch(sf=0.005, seed=23)
    orders = full.get("orders")

    local = ScanOp(cat.get("orders"))
    inbox = dcn.setup_remote_flow(remote, "orders_half",
                                  cat.get("orders").schema)
    union = UnionOp((local, inbox))
    got = run_operator(union)
    assert len(got["o_orderkey"]) == orders.num_rows
    np.testing.assert_array_equal(
        np.sort(np.asarray(got["o_orderkey"])),
        np.sort(np.asarray(orders.columns["o_orderkey"])),
    )
    # totalprice survives the Arrow round trip exactly (decimal codec)
    np.testing.assert_allclose(
        np.sort(np.asarray(got["o_totalprice"], dtype=np.float64)),
        np.sort(orders.columns["o_totalprice"] / 100.0), rtol=0,
    )


def test_two_process_join(remote):
    """A query whose orders input is split across processes: local half
    UNION remote inbox, joined + aggregated, equals the single-process
    result (the cross-host Exchange stage stand-in)."""
    from cockroach_tpu.ops import expr as ex
    from cockroach_tpu.sql.rel import Rel

    full = tpch.gen_tpch(sf=0.005, seed=23)
    want = (
        Rel.scan(full, "orders", ("o_orderkey", "o_custkey"))
        .join(Rel.scan(full, "customer", ("c_custkey", "c_nationkey")),
              on=[("o_custkey", "c_custkey")])
        .groupby(["c_nationkey"], [("n", "count_rows", None)])
        .sort([("c_nationkey", False)])
        .run()
    )

    cat = _half_catalog(0)
    local = ScanOp(cat.get("orders"), ("o_orderkey", "o_custkey"))
    inbox_schema = cat.get("orders").schema
    inbox = dcn.setup_remote_flow(remote, "orders_half", inbox_schema)

    # project the inbox stream to the two needed columns via plan surface:
    # simplest is to union full-schema halves, then go through Rel on a
    # synthetic catalog table built from the unioned host result
    union = UnionOp((ScanOp(cat.get("orders")), inbox))
    rows = run_operator(union)
    import cockroach_tpu.catalog as catalog_mod

    merged = catalog_mod.Catalog()
    t = full.get("orders")
    cols = {}
    for cname in t.schema.names:
        v = rows[cname]
        if cname in t.dictionaries:
            codes = np.array(
                [t.dictionaries[cname].code_of(str(x)) for x in v],
                dtype=np.int32,
            )
            cols[cname] = codes
        elif t.schema.type_of(cname).family.name == "DECIMAL":
            sc = t.schema.type_of(cname).scale
            cols[cname] = np.round(
                np.asarray(v, dtype=np.float64) * 10**sc
            ).astype(np.int64)
        else:
            cols[cname] = np.asarray(v)
    merged.add(catalog_mod.Table(
        name="orders", schema=t.schema, columns=cols,
        dictionaries=t.dictionaries,
    ))
    merged.add(full.get("customer"))
    got = (
        Rel.scan(merged, "orders", ("o_orderkey", "o_custkey"))
        .join(Rel.scan(merged, "customer", ("c_custkey", "c_nationkey")),
              on=[("o_custkey", "c_custkey")])
        .groupby(["c_nationkey"], [("n", "count_rows", None)])
        .sort([("c_nationkey", False)])
        .run()
    )
    np.testing.assert_array_equal(got["c_nationkey"], want["c_nationkey"])
    np.testing.assert_array_equal(got["n"], want["n"])


def _gossip_child(q):
    from cockroach_tpu.flow.gossip import Gossip

    g = Gossip(node_id=2)
    g.add_info("node:2:addr", "hostB:26257")
    g.add_info("setting:x", "from-node-2")
    addr = g.serve()
    q.put(addr)
    q.get()  # wait for stop
    g.close()


def test_gossip_two_process_convergence():
    """pkg/gossip reduction: push-pull exchange converges two PROCESSES'
    info stores; higher versions win on conflict."""
    from cockroach_tpu.flow.gossip import Gossip

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_gossip_child, args=(q,), daemon=True)
    p.start()
    addr = q.get(timeout=120)
    try:
        g1 = Gossip(node_id=1)
        g1.add_info("node:1:addr", "hostA:26257")
        learned = g1.exchange(addr)
        assert learned >= 2
        assert g1.get_info("node:2:addr") == "hostB:26257"
        assert g1.get_info("setting:x") == "from-node-2"

        # conflict: node 1 writes a NEWER version of setting:x; the second
        # round propagates it to node 2 and nothing regresses locally
        g1.add_info("setting:x", "from-node-1-newer")
        g1.exchange(addr)
        g1.exchange(addr)
        assert g1.get_info("setting:x") == "from-node-1-newer"
        # node 1 also carries its own info after the rounds
        assert g1.get_info("node:1:addr") == "hostA:26257"
    finally:
        q.put("stop")
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()


def test_flow_server_survives_bad_clients(remote):
    """A misbehaving client (empty handshake, unknown flow name, garbage
    frame) must not kill the accept loop: the next well-formed request
    still gets its stream (per-connection error isolation, the
    RangefeedServer handshake discipline)."""
    import socket

    from scripts.check_no_leaks import assert_no_leaks, snapshot

    before = snapshot()
    # 1: connect and immediately close (empty handshake)
    s = socket.create_connection(tuple(remote))
    s.close()
    # 2: unknown flow name
    s = socket.create_connection(tuple(remote))
    dcn._send_msg(s, b"no-such-flow")
    s.close()
    # 3: garbage bytes that are not a full frame
    s = socket.create_connection(tuple(remote))
    s.sendall(b"\xff\xff")
    s.close()

    # the server still answers a real request
    cat = _half_catalog(1)
    inbox = dcn.setup_remote_flow(remote, "orders_half",
                                  cat.get("orders").schema)
    got = run_operator(inbox)
    assert len(got["o_orderkey"]) == cat.get("orders").num_rows
    # all the churn above must leave no sockets behind in THIS process
    # (the drained inbox closes its own socket; bad clients closed theirs)
    assert_no_leaks(before)


# ---------------------------------------------------------------------------
# round 4: one query across processes — SetupFlow specs + flow registry


def _serve_host_flows(q):
    """Child process: a HostFlowServer over the full deterministic catalog;
    fragments arrive as serialized plan specs and build HERE."""
    from cockroach_tpu.utils.backend import force_cpu_backend

    force_cpu_backend()
    from cockroach_tpu.flow.disthost import HostFlowServer

    cat = tpch.gen_tpch(sf=0.005, seed=23)
    srv = HostFlowServer(cat).serve_background()
    q.put(srv.addr)
    q.get()
    srv.close()


@pytest.fixture(scope="module")
def host_servers():
    ctx = mp.get_context("spawn")
    qs, ps, addrs = [], [], []
    # sequential startup: two children importing jax simultaneously thrash
    # the single-core CI box past any reasonable timeout
    for _ in range(2):
        q = ctx.Queue()
        p = ctx.Process(target=_serve_host_flows, args=(q,), daemon=True)
        p.start()
        addrs.append(q.get(timeout=600))
        qs.append(q)
        ps.append(p)
    yield addrs
    for q in qs:
        q.put("stop")
    for p in ps:
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()


def test_query_across_two_processes(host_servers):
    """A grouped aggregation runs as remote partial fragments (scan shards
    behind each host's flow registry) + a local final stage, and equals the
    single-process result."""
    from cockroach_tpu.flow.disthost import (explain_hosts,
                                             run_distributed_hosts)
    from cockroach_tpu.ops import expr as ex
    from cockroach_tpu.ops.aggregation import AggSpec
    from cockroach_tpu.plan import builder as plan_builder
    from cockroach_tpu.plan import spec as S

    cat = tpch.gen_tpch(sf=0.005, seed=23)
    schema = cat.get("orders").schema
    pred = ex.Cmp("gt", ex.ColRef(schema.index("o_totalprice")),
                  ex.lit(1000.0))
    plan = S.Aggregate(
        S.Filter(S.TableScan("orders"), pred),
        group_cols=(schema.index("o_shippriority"),),
        aggs=(AggSpec("count_rows", None, "n"),
              AggSpec("sum", schema.index("o_totalprice"), "total"),
              AggSpec("max", schema.index("o_orderdate"), "latest")),
        mode="complete",
    )
    want = run_operator(plan_builder.build(plan, cat))
    got = run_distributed_hosts(plan, cat, host_servers)
    assert sorted(got.keys()) == sorted(want.keys())
    for k in want:
        np.testing.assert_allclose(
            np.asarray(got[k], dtype=np.float64),
            np.asarray(want[k], dtype=np.float64), rtol=1e-9,
        )
    # EXPLAIN (DISTSQL) renders the remote stages
    lines = explain_hosts(plan, 2)
    assert any("remote host 0" in ln for ln in lines)
    assert any("remote host 1" in ln for ln in lines)
    assert any("gateway: final aggregation" in ln for ln in lines)


def test_host_fragments_reject_unshardable_plans():
    from cockroach_tpu.flow.disthost import plan_host_fragments
    from cockroach_tpu.ops.aggregation import AggSpec
    from cockroach_tpu.plan import spec as S

    sortp = S.Sort(S.TableScan("orders"), ())
    with pytest.raises(TypeError):
        plan_host_fragments(
            S.Aggregate(sortp, (0,), (AggSpec("count_rows", None, "n"),)),
            2,
        )
    with pytest.raises(TypeError):
        plan_host_fragments(S.TableScan("orders"), 2)


def test_hash_repartitioned_join_across_two_processes(host_servers):
    """A q3-shaped join distributes by hash repartition: every host scans
    its shard of BOTH sides and scatters rows by key hash over DCN; each
    host joins one partition; the gateway unions the joined streams
    (HashRouter + colrpc shape, colflow/routers.go:420). Results equal
    the local join."""
    from cockroach_tpu.flow.disthost import (explain_host_join,
                                             run_distributed_join)
    from cockroach_tpu.ops import expr as ex
    from cockroach_tpu.plan import builder as plan_builder
    from cockroach_tpu.plan import spec as S

    cat = tpch.gen_tpch(sf=0.005, seed=23)
    oschema = cat.get("orders").schema
    pred = ex.Cmp("lt", ex.ColRef(1), ex.lit(10000.0))
    plan = S.HashJoin(
        probe=S.TableScan("lineitem", ("l_orderkey", "l_extendedprice")),
        build=S.Filter(
            S.TableScan("orders", ("o_orderkey", "o_totalprice")), pred),
        probe_keys=(0,),
        build_keys=(0,),
    )
    want = run_operator(plan_builder.build(plan, cat))
    got = run_distributed_join(plan, cat, host_servers)
    assert sorted(got.keys()) == sorted(want.keys())

    def canon(res):
        rows = np.stack([np.asarray(res[k], dtype=np.float64)
                         for k in sorted(res.keys())], axis=1)
        return rows[np.lexsort(rows.T[::-1])]

    np.testing.assert_allclose(canon(got), canon(want), rtol=1e-9)
    lines = explain_host_join(plan, 2)
    assert any("hash-repartition" in ln for ln in lines)
    assert any("join partition 1" in ln for ln in lines)


def test_join_fragment_wire_roundtrip():
    """The repartition fragments (HashBucket / RemoteStream / StreamUnion /
    HashJoin) survive the spec wire format."""
    from cockroach_tpu.coldata.types import FLOAT64, INT64, Schema
    from cockroach_tpu.flow import wire
    from cockroach_tpu.plan import spec as S

    sch = Schema(("k", "v"), (INT64, FLOAT64))
    frag = S.HashJoin(
        S.StreamUnion((
            S.RemoteStream(("127.0.0.1", 1234), "f1", 1001, sch),
            S.RemoteStream(("127.0.0.1", 1235), "f1", 1003, sch),
        )),
        S.HashBucket(S.TableScan("orders", ("o_orderkey",), shard=(0, 2)),
                     (0,), 2, 1),
        (0,), (0,),
    )
    back = wire.dec_plan(wire.enc_plan(frag))
    assert back == frag


# ---------------------------------------------------------------------------
# deadline discipline: no DCN wait is allowed to block forever


@pytest.fixture
def _tight_io_deadline():
    """Shrink flow.dcn.io_timeout_s so wedge scenarios fail in test time."""
    from cockroach_tpu.utils import settings

    prev = settings.get("flow.dcn.io_timeout_s")
    settings.set("flow.dcn.io_timeout_s", 0.3)
    yield 0.3
    settings.set("flow.dcn.io_timeout_s", prev)


def test_flow_dial_arms_stream_deadline(remote):
    """setup_remote_flow's connect timeout persists as the socket timeout,
    so every subsequent inbox stream read carries the same deadline — the
    untimed-wait regression (a wedged remote used to hang the puller
    thread forever)."""
    from cockroach_tpu.utils import settings

    cat = _half_catalog(1)
    inbox = dcn.setup_remote_flow(remote, "orders_half",
                                  cat.get("orders").schema)
    try:
        assert inbox.sock.gettimeout() == settings.get(
            "flow.dcn.io_timeout_s")
    finally:
        inbox.sock.close()


def test_inbox_read_times_out_on_silent_remote(_tight_io_deadline):
    """A server that accepts the flow handshake and then goes silent must
    surface as a timeout on the inbox read, not an eternal hang."""
    import socket
    import threading
    import time

    from cockroach_tpu.coldata.types import INT64, Schema

    srv = socket.create_server(("127.0.0.1", 0))
    conns = []

    def accept_and_stall():
        conn, _ = srv.accept()
        conns.append(conn)  # hold it open, never answer

    t = threading.Thread(target=accept_and_stall, daemon=True)
    t.start()
    inbox = dcn.setup_remote_flow(srv.getsockname(), "never",
                                  Schema(("k",), (INT64,)))
    t0 = time.monotonic()
    with pytest.raises(socket.timeout):
        inbox._next()
    assert time.monotonic() - t0 < 5.0
    inbox.sock.close()
    for c in conns:
        c.close()
    srv.close()


def test_flow_server_sheds_silent_handshake(_tight_io_deadline):
    """A client that dials and never sends its handshake must not wedge
    the single serve thread: after the io deadline the connection is
    dropped and the next well-formed request still gets its stream."""
    import socket
    import time

    cat = _half_catalog(0)

    def make_op():
        return ScanOp(cat.get("orders"))

    srv = dcn.FlowServer({"orders_half": make_op}).serve_background()
    try:
        silent = socket.create_connection(tuple(srv.addr))
        try:
            # let the server's handshake deadline fire and shed the
            # silent conn before dialing for real, so the real stream's
            # own (equally tight) read deadline starts from a free server
            time.sleep(_tight_io_deadline * 3)
            inbox = dcn.setup_remote_flow(srv.addr, "orders_half",
                                          cat.get("orders").schema)
            got = run_operator(inbox)
            assert len(got["o_orderkey"]) == cat.get("orders").num_rows
        finally:
            silent.close()
    finally:
        srv.close()


def test_gossip_exchange_times_out_on_silent_peer(_tight_io_deadline):
    """The push-pull dial carries the io deadline: a peer that accepts
    and never answers fails this round with a timeout (run_background's
    retry loop absorbs it) instead of freezing the gossip thread — the
    untimed-wait regression at gossip.exchange."""
    import socket
    import time

    from cockroach_tpu.flow.gossip import Gossip

    srv = socket.create_server(("127.0.0.1", 0))  # accepts, never reads
    g = Gossip(node_id=7)
    g.add_info("node:7:addr", "hostZ:26257")
    t0 = time.monotonic()
    with pytest.raises(OSError):
        g.exchange(srv.getsockname())
    assert time.monotonic() - t0 < 5.0
    g.close()
    srv.close()
