"""Tier-1 wiring for the crlint static-analysis suite (cockroach_tpu/lint/
+ scripts/check_lint.py) and the runtime lock-order detector
(cockroach_tpu/utils/locks.py).

Two halves:

- each lint pass is proven LIVE against a fixture tree that trips it
  (a gate that silently stopped finding anything is worse than no gate),
  plus pragma-suppression semantics;
- the real tree is held at zero findings and an acyclic static lock
  graph, and OrderedLock turns an A->B/B->A inversion into an immediate
  LockOrderError instead of a deadlock.
"""

import threading

import pytest

from cockroach_tpu.lint import run_lint
from cockroach_tpu.lint.core import load_files
from cockroach_tpu.lint import lockorder
from cockroach_tpu.utils import locks, settings
from scripts.check_lint import check


# ---------------------------------------------------------------- fixtures

def _tree(tmp_path, files: dict[str, str]):
    """Materialize {relpath: source} under tmp_path and return the root.
    Paths start with cockroach_tpu/... so the passes scope exactly like
    the real tree (core._canonical_rel anchors on that component)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return tmp_path / "cockroach_tpu"


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- real tree

def test_real_tree_is_clean():
    problems = check()
    assert not problems, "\n".join(problems)


def test_real_tree_lock_graph_acyclic():
    files = load_files(["cockroach_tpu"])
    lock_names, edges = lockorder.build_lock_graph(files)
    assert lock_names, "lock indexing broke: no locks found in the tree"
    assert not lockorder.check(files)


# ------------------------------------------------------------- host-sync

_HOT = "cockroach_tpu/flow/runtime.py"

def test_host_sync_flags_implicit_transfers(tmp_path):
    root = _tree(tmp_path, {_HOT: (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    n = int(jnp.sum(x))\n"          # int() on traced value
        "    v = x.item()\n"                 # .item()
        "    h = np.asarray(jnp.abs(x))\n"   # device -> host copy
        "    if jnp.any(x):\n"               # truth test forces sync
        "        pass\n"
        "    return n, v, h\n")})
    found = run_lint([root], rules=("host-sync",))
    assert len(found) == 4, [f.render() for f in found]
    assert _rules(found) == ["host-sync"]


def test_host_sync_scoped_to_hot_modules(tmp_path):
    # same code outside the hot path (and in the allowlisted wire module)
    # is not a finding
    src = "import jax.numpy as jnp\ndef f(x):\n    return int(jnp.sum(x))\n"
    root = _tree(tmp_path, {
        "cockroach_tpu/bench/baseline.py": src,
        "cockroach_tpu/flow/wire.py": src,
    })
    assert not run_lint([root], rules=("host-sync",))


def test_host_sync_pragma_and_host_literals(tmp_path):
    root = _tree(tmp_path, {_HOT: (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x, rows):\n"
        "    # crlint: allow-host-sync(one sync per query by design)\n"
        "    n = int(jnp.sum(x))\n"
        "    a = np.asarray([1, 2, 3])\n"     # host literal: no readback
        "    if jnp.issubdtype(x.dtype, jnp.integer):\n"  # host predicate
        "        pass\n"
        "    return n, a\n")})
    assert not run_lint([root], rules=("host-sync",))


# --------------------------------------------------------------- raw-jit

def test_raw_jit_flagged_outside_dispatch(tmp_path):
    root = _tree(tmp_path, {
        "cockroach_tpu/ops/thing.py": (
            "import jax\n"
            "import functools\n"
            "f = jax.jit(lambda x: x)\n"
            "g = functools.partial(jax.pmap, axis_name='d')\n"),
        "cockroach_tpu/flow/dispatch.py": (
            "import jax\n"
            "def jit(fn, **kw):\n"
            "    return jax.jit(fn, **kw)\n"),
    })
    found = run_lint([root], rules=("raw-jit",))
    # both sites in ops/thing.py (incl. the partial arg), none in dispatch
    assert len(found) == 2, [f.render() for f in found]
    assert all(f.path == "cockroach_tpu/ops/thing.py" for f in found)


# ----------------------------------------------------------- broad-except

def test_silent_swallow_is_unsuppressible(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/kv/thing.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    # crlint: allow-broad-except(pragma must NOT mute this)\n"
        "    except Exception:\n"
        "        pass\n")})
    found = run_lint([root], rules=("broad-except",))
    assert len(found) == 1
    assert not found[0].suppressible


def test_broad_except_pragma_and_reraise(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/flow/thing.py": (
        "def ok_reraise():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        raise\n"
        "def ok_pragma():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:  # crlint: allow-broad-except(logged)\n"
        "        log(e)\n"
        "def bad():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        log(e)\n")})
    found = run_lint([root], rules=("broad-except",))
    assert len(found) == 1
    assert found[0].line > 10  # the finding is in bad(), not the first two


def test_broad_except_scoped_outside_kv_flow_server(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/bench/thing.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n")})
    assert not run_lint([root], rules=("broad-except",))


# ------------------------------------------------------------ tracing-api

def test_tracing_api_flags_direct_span_construction(tmp_path):
    root = _tree(tmp_path, {
        "cockroach_tpu/flow/thing.py": (
            "from ..utils import tracing\n"
            "from ..utils.tracing import Span\n"
            "def f(tr):\n"
            "    a = Span('x')\n"             # imported-name construction
            "    b = tracing.Span('y')\n"     # attribute construction
            "    tr._current.set(a)\n"        # tracer internals
            "    return a, b\n"),
        "cockroach_tpu/utils/tracing.py": (
            "class Span:\n"
            "    pass\n"
            "def span(name):\n"
            "    return Span(name)\n"),       # the API itself is exempt
    })
    found = run_lint([root], rules=("tracing-api",))
    assert len(found) == 3, [f.render() for f in found]
    assert all(f.path == "cockroach_tpu/flow/thing.py" for f in found)


def test_tracing_api_pragma_suppresses(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/plan/thing.py": (
        "from ..utils import tracing\n"
        "def f():\n"
        "    # crlint: allow-tracing-api(test fixture builds a detached tree)\n"
        "    return tracing.Span('x')\n")})
    assert not run_lint([root], rules=("tracing-api",))


def test_tracing_api_ignores_entered_spans(tmp_path):
    # the sanctioned forms produce no findings
    root = _tree(tmp_path, {"cockroach_tpu/kv/thing.py": (
        "from ..utils import tracing\n"
        "def f():\n"
        "    with tracing.span('a') as sp:\n"
        "        with tracing.leaf_span('b'):\n"
        "            pass\n"
        "    return tracing.synthetic_span(sp, 'c', 0.1)\n")})
    assert not run_lint([root], rules=("tracing-api",))


# ---------------------------------------------------------- unused-import

def test_unused_import_flagged_and_pragma(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/util.py": (
        "import os\n"
        "import sys  # crlint: allow-unused-import(re-export shim)\n"
        "import json\n"
        "print(json.dumps({}))\n")})
    found = run_lint([root], rules=("unused-import",))
    assert len(found) == 1
    assert "'os'" in found[0].message


def test_empty_pragma_reason_does_not_suppress(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/util.py": (
        "import os  # crlint: allow-unused-import()\n")})
    assert len(run_lint([root], rules=("unused-import",))) == 1


# ------------------------------------------------------------- lock-order

def test_lock_order_cycle_through_call_graph(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/mod.py": (
        "import threading\n"
        "LOCK_A = threading.Lock()\n"
        "LOCK_B = threading.Lock()\n"
        "def path_one():\n"
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            pass\n"
        "def path_two():\n"
        "    with LOCK_B:\n"
        "        helper()\n"       # inversion is one call deep
        "def helper():\n"
        "    with LOCK_A:\n"
        "        pass\n")})
    found = run_lint([root], rules=("lock-order",))
    assert len(found) == 1
    assert "cycle" in found[0].message


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/mod.py": (
        "import threading\n"
        "LOCK_A = threading.Lock()\n"
        "LOCK_B = threading.Lock()\n"
        "def one():\n"
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            pass\n"
        "def two():\n"
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            pass\n")})
    assert not run_lint([root], rules=("lock-order",))


# ------------------------------------------------ runtime OrderedLock

@pytest.fixture
def lock_order_on():
    locks.reset()
    prev = settings.get("debug.lock_order.enabled")
    settings.set("debug.lock_order.enabled", True)
    yield
    settings.set("debug.lock_order.enabled", prev)
    locks.reset()


def test_ordered_lock_inversion_raises(lock_order_on):
    a, b = locks.lock("t.A"), locks.lock("t.B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locks.LockOrderError):
            a.acquire()


def test_ordered_lock_transitive_cycle(lock_order_on):
    x, y, z = locks.lock("t.X"), locks.lock("t.Y"), locks.lock("t.Z")
    with x:
        with y:
            pass
    with y:
        with z:
            pass
    with z:
        with pytest.raises(locks.LockOrderError):
            x.acquire()


def test_ordered_lock_cross_thread(lock_order_on):
    # the order graph is global: thread 1 records A->B, thread 2's B->A
    # trips even though the two never contend
    a, b = locks.lock("t2.A"), locks.lock("t2.B")
    def t1():
        with a:
            with b:
                pass
    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with b:
        with pytest.raises(locks.LockOrderError):
            a.acquire()


def test_ordered_lock_disabled_is_noop():
    locks.reset()
    assert settings.get("debug.lock_order.enabled") is False
    a, b = locks.lock("t3.A"), locks.lock("t3.B")
    with a:
        with b:
            pass
    with b:
        with a:  # inverted, but checking is off
            pass


def test_ordered_rlock_reentry_ok(lock_order_on):
    r = locks.rlock("t.R")
    with r:
        with r:
            pass
    assert not r.locked()


def test_ordered_condition_wait_notify(lock_order_on):
    c = locks.condition("t.C")
    hits = []
    def waiter():
        with c:
            c.wait_for(lambda: hits, timeout=5)
            hits.append("woke")
    th = threading.Thread(target=waiter)
    th.start()
    import time
    time.sleep(0.05)
    with c:
        hits.append("set")
        c.notify_all()
    th.join(timeout=5)
    assert hits == ["set", "woke"]


# ----------------------------------------------------------- shared-state

_RACY = (
    "import threading\n"
    "class W:\n"
    "    def __init__(self):\n"
    "        self.counter = 0\n"
    "        self._t = threading.Thread(target=self._loop, daemon=True)\n"
    "        self._t.start()\n"
    "    def _loop(self):\n"
    "        for _ in range(10):\n"
    "            self.counter += 1\n"
    "    def bump(self):\n"
    "        self.counter += 1\n"
)


def test_shared_state_flags_multi_entry_unlocked_rmw(tmp_path):
    """Live trip: a field RMW-mutated from both a spawned thread and the
    main entry with no lock anywhere is exactly the race the pass hunts."""
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": _RACY})
    found = run_lint([root], rules=("shared-state",))
    assert len(found) == 1, [f.render() for f in found]
    assert found[0].rule == "shared-state"
    assert "counter" in found[0].message
    assert "no common lock" in found[0].message


def test_shared_state_lock_guard_is_quiet(tmp_path):
    """The fix the finding demands, verified quiet: both sites under one
    OrderedLock."""
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": (
        "import threading\n"
        "from ..utils import locks\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._mu = locks.lock('kv.widget')\n"
        "        self.counter = 0\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        with self._mu:\n"
        "            self.counter += 1\n"
        "    def bump(self):\n"
        "        with self._mu:\n"
        "            self.counter += 1\n")})
    assert not run_lint([root], rules=("shared-state",))


def test_shared_state_inline_pragma_suppresses(tmp_path):
    src = _RACY.replace(
        "    def bump(self):\n",
        "    def bump(self):\n"
        "        # crlint: allow-shared-state(single writer by protocol)\n")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    assert not run_lint([root], rules=("shared-state",))


def test_shared_state_def_line_waiver_covers_body(tmp_path):
    src = _RACY.replace(
        "    def bump(self):\n",
        "    # crlint: allow-shared-state(test-only mutator, documented)\n"
        "    def bump(self):\n")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    assert not run_lint([root], rules=("shared-state",))


# --------------------------------------------------------- mem-accounting

_HOT_ALLOC = (
    "import numpy as np\n"
    "def f(n):\n"
    "    return np.zeros((n, 1024))\n"
)


def test_mem_accounting_flags_uncharged_hot_path_alloc(tmp_path):
    """Live trip: a data-sized materialization on a flow hot path with no
    accounting evidence anywhere in the function."""
    root = _tree(tmp_path, {_HOT: _HOT_ALLOC})
    found = run_lint([root], rules=("mem-accounting",))
    assert len(found) == 1, [f.render() for f in found]
    assert found[0].rule == "mem-accounting"
    assert "np.zeros" in found[0].message


def test_mem_accounting_evidence_and_scope(tmp_path):
    # reserve() in the function is evidence; the same alloc in a
    # non-hot-path module is out of scope entirely
    root = _tree(tmp_path, {
        _HOT: ("import numpy as np\n"
               "def g(mon, n):\n"
               "    mon.reserve(n * 8192)\n"
               "    return np.zeros((n, 1024))\n"),
        "cockroach_tpu/bench/gen.py": _HOT_ALLOC,
    })
    assert not run_lint([root], rules=("mem-accounting",))


def test_mem_accounting_small_literal_shape_is_quiet(tmp_path):
    root = _tree(tmp_path, {_HOT: (
        "import numpy as np\n"
        "def f():\n"
        "    return np.zeros((4, 8))\n")})
    assert not run_lint([root], rules=("mem-accounting",))


def test_mem_accounting_inline_pragma_suppresses(tmp_path):
    root = _tree(tmp_path, {_HOT: (
        "import numpy as np\n"
        "def f(n):\n"
        "    # crlint: allow-mem-accounting(bounded by tile count)\n"
        "    return np.zeros((n, 1024))\n")})
    assert not run_lint([root], rules=("mem-accounting",))


# --------------------------------------------------------- fault-coverage

_FAULTS_FIXTURE = {
    "cockroach_tpu/utils/faults.py": (
        "SITES: dict[str, str] = {\n"
        "    'a.b': 'site one',\n"
        "    'c.d': 'site two',\n"
        "}\n"
        "def fire(site):\n"
        "    pass\n"),
    "cockroach_tpu/kv/thing.py": (
        "from ..utils import faults\n"
        "def f(name):\n"
        "    faults.fire('a.b')\n"),
    "tests/test_foo.py": (
        "import pytest\n"
        "pytestmark = pytest.mark.chaos\n"
        "def test_x():\n"
        "    assert 'a.b'\n"),
}


def _fault_tree(tmp_path, files):
    _tree(tmp_path, files)
    return [tmp_path / "cockroach_tpu", tmp_path / "tests"]


def test_fault_coverage_flags_all_three_gaps(tmp_path):
    """Live trip of every finding class: a computed site name, a dead
    registration, and a registered site no chaos test exercises."""
    files = dict(_FAULTS_FIXTURE)
    files["cockroach_tpu/kv/thing.py"] = (
        "from ..utils import faults\n"
        "def f(name):\n"
        "    faults.fire('a.b')\n"
        "    faults.fire(name)\n")
    found = run_lint(_fault_tree(tmp_path, files),
                     rules=("fault-coverage",))
    msgs = [f.message for f in found]
    assert len(found) == 3, [f.render() for f in found]
    assert any("not a string literal" in m for m in msgs)
    assert any("no fire call in product code" in m for m in msgs)
    assert any("not exercised by any chaos-marked test" in m for m in msgs)


def test_fault_coverage_closed_loop_is_quiet(tmp_path):
    files = dict(_FAULTS_FIXTURE)
    files["cockroach_tpu/utils/faults.py"] = (
        "SITES: dict[str, str] = {\n"
        "    'a.b': 'site one',\n"
        "}\n"
        "def fire(site):\n"
        "    pass\n")
    assert not run_lint(_fault_tree(tmp_path, files),
                        rules=("fault-coverage",))


def test_fault_coverage_scoped_site_names_count(tmp_path):
    """A test naming the node-scoped '<site>.n<id>' variant covers the
    base registration (fire_scoped's contract)."""
    files = dict(_FAULTS_FIXTURE)
    files["cockroach_tpu/utils/faults.py"] = (
        "SITES: dict[str, str] = {\n"
        "    'a.b': 'site one',\n"
        "}\n"
        "def fire(site):\n"
        "    pass\n")
    files["tests/test_foo.py"] = (
        "import pytest\n"
        "pytestmark = pytest.mark.chaos\n"
        "def test_x():\n"
        "    assert 'a.b.n3'\n")
    assert not run_lint(_fault_tree(tmp_path, files),
                        rules=("fault-coverage",))


def test_fault_coverage_registry_pragma_suppresses(tmp_path):
    files = dict(_FAULTS_FIXTURE)
    files["cockroach_tpu/utils/faults.py"] = (
        "SITES: dict[str, str] = {\n"
        "    'a.b': 'site one',\n"
        "    # crlint: allow-fault-coverage(planned site, test in flight)\n"
        "    'c.d': 'site two',\n"
        "}\n"
        "def fire(site):\n"
        "    pass\n")
    files["cockroach_tpu/kv/thing.py"] = (
        "from ..utils import faults\n"
        "def f():\n"
        "    faults.fire('a.b')\n"
        "    faults.fire('c.d')\n")
    assert not run_lint(_fault_tree(tmp_path, files),
                        rules=("fault-coverage",))


# --------------------------------------------------------- unknown-pragma

def test_unknown_rule_pragma_is_a_finding(tmp_path):
    """A typo'd pragma suppresses nothing — and saying so is itself a
    finding, so the near-miss can't silently convince anyone a waiver is
    in force."""
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": (
        "def f():\n"
        "    # crlint: allow-mem-acounting(typo never suppresses)\n"
        "    return 1\n")})
    found = run_lint([root])
    assert [f.rule for f in found] == ["unknown-pragma"]
    assert "mem-acounting" in found[0].message


# ------------------------------------------------------------------- CLI

def test_cli_exit_codes_clean_findings_internal(tmp_path):
    from cockroach_tpu.lint.__main__ import main

    clean = tmp_path / "cockroach_tpu" / "ok.py"
    clean.parent.mkdir(parents=True, exist_ok=True)
    clean.write_text("X = 1\n")
    assert main([str(clean)]) == 0

    dirty = tmp_path / "cockroach_tpu" / "dirty.py"
    dirty.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    assert main([str(dirty)]) == 1

    broken = tmp_path / "cockroach_tpu" / "broken.py"
    broken.write_text("def f(:\n")
    assert main([str(broken)]) == 2  # linter failure, not a finding


def test_cli_changed_only_filters_report(tmp_path):
    from cockroach_tpu.lint.__main__ import main

    root = _tree(tmp_path, {
        "cockroach_tpu/kv/a.py": "import jax\nf = jax.jit(lambda x: x)\n",
        "cockroach_tpu/kv/b.py": "import jax\ng = jax.jit(lambda x: x)\n",
    })
    lst = tmp_path / "changed.txt"
    lst.write_text("cockroach_tpu/kv/a.py\n")
    # both files dirty, but only a.py is in the changed list
    assert main([str(root), "--changed-only", str(lst)]) == 1
    lst.write_text("cockroach_tpu/kv/other.py\n")
    assert main([str(root), "--changed-only", str(lst)]) == 0


def test_cli_json_is_stable_and_location_sorted(tmp_path):
    import json as _json

    from cockroach_tpu.lint.__main__ import main

    root = _tree(tmp_path, {
        "cockroach_tpu/kv/b.py": "import jax\ng = jax.jit(lambda x: x)\n",
        "cockroach_tpu/kv/a.py": "import jax\nf = jax.jit(lambda x: x)\n",
    })
    import io
    import contextlib

    bufs = []
    for _ in range(2):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert main([str(root), "--json"]) == 1
        bufs.append(buf.getvalue())
    assert bufs[0] == bufs[1]  # byte-stable across runs
    recs = _json.loads(bufs[0])
    locs = [(r["path"], r["line"]) for r in recs]
    assert locs == sorted(locs)
    assert locs[0][0].endswith("a.py")


# ----------------------------------------------------------- untimed-wait

_UNTIMED = (
    "import queue\n"
    "import threading\n"
    "class W:\n"
    "    def __init__(self):\n"
    "        self.ev = threading.Event()\n"
    "        self.q = queue.Queue()\n"
    "        self._t = threading.Thread(target=self._loop, daemon=True)\n"
    "        self._t.start()\n"
    "    def _loop(self):\n"
    "        self.ev.wait()\n"
    "        return self.q.get()\n"
)


def test_untimed_wait_flags_thread_reachable_waits(tmp_path):
    """Live trip: an Event.wait() and a Queue.get() with no timeout on a
    spawned thread's path are exactly the wedge the pass hunts."""
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": _UNTIMED})
    found = run_lint([root], rules=("untimed-wait",))
    assert len(found) == 2, [f.render() for f in found]
    assert all(f.rule == "untimed-wait" for f in found)
    msgs = " ".join(f.message for f in found)
    assert ".wait()" in msgs and ".get()" in msgs


def test_untimed_wait_bounded_is_quiet(tmp_path):
    """The fix the finding demands, verified quiet: explicit timeouts."""
    src = _UNTIMED.replace("self.ev.wait()", "self.ev.wait(1.0)") \
                  .replace("self.q.get()", "self.q.get(timeout=1.0)")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    assert not run_lint([root], rules=("untimed-wait",))


def test_untimed_wait_unreachable_helper_is_quiet(tmp_path):
    """The pass walks the thread-entry graph: a wait in a helper no
    thread entry reaches is not control-plane blocking."""
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": (
        "import threading\n"
        "def helper(ev):\n"
        "    ev.wait()\n")})
    assert not run_lint([root], rules=("untimed-wait",))


def test_untimed_wait_inline_pragma_suppresses(tmp_path):
    src = _UNTIMED.replace(
        "        self.ev.wait()\n",
        "        # crlint: allow-untimed-wait(shutdown path, reaped by "
        "close)\n"
        "        self.ev.wait()\n")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    found = run_lint([root], rules=("untimed-wait",))
    assert len(found) == 1  # the queue.get() is still a finding
    assert ".get()" in found[0].message


def test_untimed_wait_def_line_waiver_covers_body(tmp_path):
    src = _UNTIMED.replace(
        "    def _loop(self):\n",
        "    # crlint: allow-untimed-wait(owner arms deadlines before "
        "start)\n"
        "    def _loop(self):\n")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    assert not run_lint([root], rules=("untimed-wait",))


def test_untimed_wait_empty_reason_does_not_suppress(tmp_path):
    src = _UNTIMED.replace(
        "        self.ev.wait()\n",
        "        self.ev.wait()  # crlint: allow-untimed-wait()\n")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    assert len(run_lint([root], rules=("untimed-wait",))) == 2


# ------------------------------------------------------- recompile-hazard

_SHAPE_HOT_FIXTURE = "cockroach_tpu/flow/operators.py"


def test_recompile_hazard_flags_unbucketed_cap(tmp_path):
    """Live trip: a cap derived straight from len() in a shape-hot
    module mints one executable per cardinality."""
    root = _tree(tmp_path, {_SHAPE_HOT_FIXTURE: (
        "def plan(rows):\n"
        "    cap = len(rows)\n"
        "    return cap\n")})
    found = run_lint([root], rules=("recompile-hazard",))
    assert len(found) == 1, [f.render() for f in found]
    assert "canonical-bucketing" in found[0].message


def test_recompile_hazard_bucketed_cap_is_quiet(tmp_path):
    root = _tree(tmp_path, {_SHAPE_HOT_FIXTURE: (
        "from .fuse import _canonical_cap\n"
        "def plan(rows):\n"
        "    cap = _canonical_cap(len(rows))\n"
        "    return cap\n")})
    assert not run_lint([root], rules=("recompile-hazard",))


def test_recompile_hazard_flags_impure_kernel_key(tmp_path):
    """f-strings and repr() in a kernel key make two equal kernels key
    differently — a guaranteed cache miss and retrace."""
    root = _tree(tmp_path, {"cockroach_tpu/ops/thing.py": (
        "from ..flow import dispatch\n"
        "def f(schema, n):\n"
        "    return dispatch.kernel_key('agg', f'{schema}', repr(n))\n")})
    found = run_lint([root], rules=("recompile-hazard",))
    assert len(found) == 2, [f.render() for f in found]
    msgs = " ".join(f.message for f in found)
    assert "f-string" in msgs and "repr()" in msgs


def test_recompile_hazard_flags_keyless_closure_jit(tmp_path):
    """dispatch.jit on a fresh closure outside construction re-traces on
    every call; key= or construction-time hoisting is the fix."""
    root = _tree(tmp_path, {"cockroach_tpu/ops/thing.py": (
        "from ..flow import dispatch\n"
        "def f(x):\n"
        "    g = dispatch.jit(lambda v: v + 1)\n"
        "    return g(x)\n")})
    found = run_lint([root], rules=("recompile-hazard",))
    assert len(found) == 1
    assert "fresh wrapper" in found[0].message


def test_recompile_hazard_construction_and_keyed_are_quiet(tmp_path):
    """init() runs once per operator instance (instances are reused
    across queries), and key= rides the process-global kernel cache —
    neither is a per-call retrace."""
    root = _tree(tmp_path, {"cockroach_tpu/ops/thing.py": (
        "from ..flow import dispatch\n"
        "class Op:\n"
        "    def init(self):\n"
        "        self.g = dispatch.jit(lambda v: v + 1)\n"
        "def f(x):\n"
        "    h = dispatch.jit(lambda v: v - 1, key=('dec', 'i64'))\n"
        "    return h(x)\n")})
    assert not run_lint([root], rules=("recompile-hazard",))


def test_recompile_hazard_def_line_waiver_covers_body(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/ops/thing.py": (
        "from ..flow import dispatch\n"
        "# crlint: allow-recompile-hazard(cold path, traced once by "
        "contract)\n"
        "def f(x):\n"
        "    g = dispatch.jit(lambda v: v + 1)\n"
        "    return g(x)\n")})
    assert not run_lint([root], rules=("recompile-hazard",))


# --------------------------------------------------------- race-coverage

def test_race_coverage_flags_uninstrumented_shared_field(tmp_path):
    """Live trip: multi-entry unlocked writes the sanitizer never sees —
    the gap between the escape analysis and racesan's hand-placed
    instrumentation."""
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": _RACY})
    found = run_lint([root], rules=("race-coverage",))
    assert len(found) == 1, [f.render() for f in found]
    assert found[0].rule == "race-coverage"
    assert "note_read/note_write" in found[0].message


def test_race_coverage_instrumented_is_quiet(tmp_path):
    """racesan note_* calls naming the field in its module count as
    coverage: the runtime detector now sees every access."""
    src = _RACY.replace(
        "import threading\n",
        "import threading\n"
        "from ..utils import racesan\n"
    ).replace(
        "            self.counter += 1\n",
        "            racesan.note_write(self, 'counter')\n"
        "            self.counter += 1\n")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    assert not run_lint([root], rules=("race-coverage",))


def test_race_coverage_lock_guarded_is_quiet(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": (
        "import threading\n"
        "from ..utils import locks\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._mu = locks.lock('kv.widget')\n"
        "        self.counter = 0\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        with self._mu:\n"
        "            self.counter += 1\n"
        "    def bump(self):\n"
        "        with self._mu:\n"
        "            self.counter += 1\n")})
    assert not run_lint([root], rules=("race-coverage",))


def test_race_coverage_init_site_pragma_waives_state_wide(tmp_path):
    """A reasoned pragma on the __init__ assignment (the ergonomic spot)
    waives the whole state, like shared-state's state-wide waiver."""
    src = _RACY.replace(
        "        self.counter = 0\n",
        "        # crlint: allow-race-coverage(single-writer by "
        "protocol; instrumenting would false-positive under racesan)\n"
        "        self.counter = 0\n")
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    assert not run_lint([root], rules=("race-coverage",))


def test_race_coverage_map_statuses(tmp_path):
    """coverage_map labels every analyzed state; the waived row keeps
    its sites visible (the CLI's --race-map contract)."""
    from cockroach_tpu.lint.core import TreeCache
    from cockroach_tpu.lint.racecoverage import coverage_map, render_map

    src = _RACY.replace(
        "        self.counter = 0\n",
        "        # crlint: allow-race-coverage(documented lock-free "
        "single-writer)\n"
        "        self.counter = 0\n")
    _tree(tmp_path, {"cockroach_tpu/kv/widget.py": src})
    files = load_files([tmp_path / "cockroach_tpu"])
    rows = coverage_map(files, TreeCache(files))
    by_state = {r["state"].rsplit(".", 1)[-1]: r for r in rows}
    assert by_state["counter"]["status"] == "waived"
    assert by_state["counter"]["sites"]
    text = render_map(rows)
    assert "counter: waived" in text


def test_unknown_pragma_covers_new_rules(tmp_path):
    """Typo'd waivers of the three new passes are themselves findings."""
    root = _tree(tmp_path, {"cockroach_tpu/kv/widget.py": (
        "def f():\n"
        "    # crlint: allow-untimed-waits(typo)\n"
        "    # crlint: allow-recompile-hazzard(typo)\n"
        "    # crlint: allow-race-coverge(typo)\n"
        "    return 1\n")})
    found = run_lint([root])
    assert sorted(f.rule for f in found) == ["unknown-pragma"] * 3


# ------------------------------------------------- real tree: new passes

def test_real_tree_new_passes_are_clean_individually():
    """Each PR-20 pass holds zero findings at HEAD on its own (the tree
    gate runs them all; this pins the per-rule contract)."""
    found = run_lint(
        ["cockroach_tpu", "scripts", "tests", "bench.py",
         "__graft_entry__.py"],
        rules=("untimed-wait", "recompile-hazard", "race-coverage"))
    assert not found, [f.render() for f in found]


def test_run_lint_fills_per_pass_timings():
    """run_lint exposes per-pass wall seconds plus the shared load/parse
    cost — the budget the TreeCache defends."""
    from cockroach_tpu.lint.core import ALL_RULES

    timings = {}
    found = run_lint(["cockroach_tpu/lint"], timings=timings)
    assert "load/parse" in timings
    for rule in ALL_RULES:
        assert rule in timings, rule
        assert timings[rule] >= 0.0
    assert not found


def test_cli_changed_only_git_mode(tmp_path, monkeypatch):
    """--changed-only --git takes the changed set straight from git:
    untracked/modified files are reported, committed-clean ones are
    filtered out."""
    import subprocess

    from cockroach_tpu.lint.__main__ import main

    root = _tree(tmp_path, {
        "cockroach_tpu/kv/a.py": "import jax\nf = jax.jit(lambda x: x)\n",
    })
    monkeypatch.chdir(tmp_path)
    env = {"GIT_CONFIG_GLOBAL": "/dev/null", "GIT_CONFIG_SYSTEM": "/dev/null"}
    subprocess.run(["git", "init", "-q"], check=True, env={**__import__("os").environ, **env})
    # untracked: the dirty file is in the changed set
    assert main([str(root), "--changed-only", "--git"]) == 1
    subprocess.run(["git", "add", "-A"], check=True,
                   env={**__import__("os").environ, **env})
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "x"], check=True,
        env={**__import__("os").environ, **env})
    # committed and unmodified: filtered out of the report
    assert main([str(root), "--changed-only", "--git"]) == 0
