"""Tier-1 wiring for the crlint static-analysis suite (cockroach_tpu/lint/
+ scripts/check_lint.py) and the runtime lock-order detector
(cockroach_tpu/utils/locks.py).

Two halves:

- each lint pass is proven LIVE against a fixture tree that trips it
  (a gate that silently stopped finding anything is worse than no gate),
  plus pragma-suppression semantics;
- the real tree is held at zero findings and an acyclic static lock
  graph, and OrderedLock turns an A->B/B->A inversion into an immediate
  LockOrderError instead of a deadlock.
"""

import threading

import pytest

from cockroach_tpu.lint import run_lint
from cockroach_tpu.lint.core import load_files
from cockroach_tpu.lint import lockorder
from cockroach_tpu.utils import locks, settings
from scripts.check_lint import check


# ---------------------------------------------------------------- fixtures

def _tree(tmp_path, files: dict[str, str]):
    """Materialize {relpath: source} under tmp_path and return the root.
    Paths start with cockroach_tpu/... so the passes scope exactly like
    the real tree (core._canonical_rel anchors on that component)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return tmp_path / "cockroach_tpu"


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- real tree

def test_real_tree_is_clean():
    problems = check()
    assert not problems, "\n".join(problems)


def test_real_tree_lock_graph_acyclic():
    files = load_files(["cockroach_tpu"])
    lock_names, edges = lockorder.build_lock_graph(files)
    assert lock_names, "lock indexing broke: no locks found in the tree"
    assert not lockorder.check(files)


# ------------------------------------------------------------- host-sync

_HOT = "cockroach_tpu/flow/runtime.py"

def test_host_sync_flags_implicit_transfers(tmp_path):
    root = _tree(tmp_path, {_HOT: (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    n = int(jnp.sum(x))\n"          # int() on traced value
        "    v = x.item()\n"                 # .item()
        "    h = np.asarray(jnp.abs(x))\n"   # device -> host copy
        "    if jnp.any(x):\n"               # truth test forces sync
        "        pass\n"
        "    return n, v, h\n")})
    found = run_lint([root], rules=("host-sync",))
    assert len(found) == 4, [f.render() for f in found]
    assert _rules(found) == ["host-sync"]


def test_host_sync_scoped_to_hot_modules(tmp_path):
    # same code outside the hot path (and in the allowlisted wire module)
    # is not a finding
    src = "import jax.numpy as jnp\ndef f(x):\n    return int(jnp.sum(x))\n"
    root = _tree(tmp_path, {
        "cockroach_tpu/bench/baseline.py": src,
        "cockroach_tpu/flow/wire.py": src,
    })
    assert not run_lint([root], rules=("host-sync",))


def test_host_sync_pragma_and_host_literals(tmp_path):
    root = _tree(tmp_path, {_HOT: (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x, rows):\n"
        "    # crlint: allow-host-sync(one sync per query by design)\n"
        "    n = int(jnp.sum(x))\n"
        "    a = np.asarray([1, 2, 3])\n"     # host literal: no readback
        "    if jnp.issubdtype(x.dtype, jnp.integer):\n"  # host predicate
        "        pass\n"
        "    return n, a\n")})
    assert not run_lint([root], rules=("host-sync",))


# --------------------------------------------------------------- raw-jit

def test_raw_jit_flagged_outside_dispatch(tmp_path):
    root = _tree(tmp_path, {
        "cockroach_tpu/ops/thing.py": (
            "import jax\n"
            "import functools\n"
            "f = jax.jit(lambda x: x)\n"
            "g = functools.partial(jax.pmap, axis_name='d')\n"),
        "cockroach_tpu/flow/dispatch.py": (
            "import jax\n"
            "def jit(fn, **kw):\n"
            "    return jax.jit(fn, **kw)\n"),
    })
    found = run_lint([root], rules=("raw-jit",))
    # both sites in ops/thing.py (incl. the partial arg), none in dispatch
    assert len(found) == 2, [f.render() for f in found]
    assert all(f.path == "cockroach_tpu/ops/thing.py" for f in found)


# ----------------------------------------------------------- broad-except

def test_silent_swallow_is_unsuppressible(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/kv/thing.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    # crlint: allow-broad-except(pragma must NOT mute this)\n"
        "    except Exception:\n"
        "        pass\n")})
    found = run_lint([root], rules=("broad-except",))
    assert len(found) == 1
    assert not found[0].suppressible


def test_broad_except_pragma_and_reraise(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/flow/thing.py": (
        "def ok_reraise():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        raise\n"
        "def ok_pragma():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:  # crlint: allow-broad-except(logged)\n"
        "        log(e)\n"
        "def bad():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        log(e)\n")})
    found = run_lint([root], rules=("broad-except",))
    assert len(found) == 1
    assert found[0].line > 10  # the finding is in bad(), not the first two


def test_broad_except_scoped_outside_kv_flow_server(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/bench/thing.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n")})
    assert not run_lint([root], rules=("broad-except",))


# ------------------------------------------------------------ tracing-api

def test_tracing_api_flags_direct_span_construction(tmp_path):
    root = _tree(tmp_path, {
        "cockroach_tpu/flow/thing.py": (
            "from ..utils import tracing\n"
            "from ..utils.tracing import Span\n"
            "def f(tr):\n"
            "    a = Span('x')\n"             # imported-name construction
            "    b = tracing.Span('y')\n"     # attribute construction
            "    tr._current.set(a)\n"        # tracer internals
            "    return a, b\n"),
        "cockroach_tpu/utils/tracing.py": (
            "class Span:\n"
            "    pass\n"
            "def span(name):\n"
            "    return Span(name)\n"),       # the API itself is exempt
    })
    found = run_lint([root], rules=("tracing-api",))
    assert len(found) == 3, [f.render() for f in found]
    assert all(f.path == "cockroach_tpu/flow/thing.py" for f in found)


def test_tracing_api_pragma_suppresses(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/plan/thing.py": (
        "from ..utils import tracing\n"
        "def f():\n"
        "    # crlint: allow-tracing-api(test fixture builds a detached tree)\n"
        "    return tracing.Span('x')\n")})
    assert not run_lint([root], rules=("tracing-api",))


def test_tracing_api_ignores_entered_spans(tmp_path):
    # the sanctioned forms produce no findings
    root = _tree(tmp_path, {"cockroach_tpu/kv/thing.py": (
        "from ..utils import tracing\n"
        "def f():\n"
        "    with tracing.span('a') as sp:\n"
        "        with tracing.leaf_span('b'):\n"
        "            pass\n"
        "    return tracing.synthetic_span(sp, 'c', 0.1)\n")})
    assert not run_lint([root], rules=("tracing-api",))


# ---------------------------------------------------------- unused-import

def test_unused_import_flagged_and_pragma(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/util.py": (
        "import os\n"
        "import sys  # crlint: allow-unused-import(re-export shim)\n"
        "import json\n"
        "print(json.dumps({}))\n")})
    found = run_lint([root], rules=("unused-import",))
    assert len(found) == 1
    assert "'os'" in found[0].message


def test_empty_pragma_reason_does_not_suppress(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/util.py": (
        "import os  # crlint: allow-unused-import()\n")})
    assert len(run_lint([root], rules=("unused-import",))) == 1


# ------------------------------------------------------------- lock-order

def test_lock_order_cycle_through_call_graph(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/mod.py": (
        "import threading\n"
        "LOCK_A = threading.Lock()\n"
        "LOCK_B = threading.Lock()\n"
        "def path_one():\n"
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            pass\n"
        "def path_two():\n"
        "    with LOCK_B:\n"
        "        helper()\n"       # inversion is one call deep
        "def helper():\n"
        "    with LOCK_A:\n"
        "        pass\n")})
    found = run_lint([root], rules=("lock-order",))
    assert len(found) == 1
    assert "cycle" in found[0].message


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    root = _tree(tmp_path, {"cockroach_tpu/mod.py": (
        "import threading\n"
        "LOCK_A = threading.Lock()\n"
        "LOCK_B = threading.Lock()\n"
        "def one():\n"
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            pass\n"
        "def two():\n"
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            pass\n")})
    assert not run_lint([root], rules=("lock-order",))


# ------------------------------------------------ runtime OrderedLock

@pytest.fixture
def lock_order_on():
    locks.reset()
    prev = settings.get("debug.lock_order.enabled")
    settings.set("debug.lock_order.enabled", True)
    yield
    settings.set("debug.lock_order.enabled", prev)
    locks.reset()


def test_ordered_lock_inversion_raises(lock_order_on):
    a, b = locks.lock("t.A"), locks.lock("t.B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locks.LockOrderError):
            a.acquire()


def test_ordered_lock_transitive_cycle(lock_order_on):
    x, y, z = locks.lock("t.X"), locks.lock("t.Y"), locks.lock("t.Z")
    with x:
        with y:
            pass
    with y:
        with z:
            pass
    with z:
        with pytest.raises(locks.LockOrderError):
            x.acquire()


def test_ordered_lock_cross_thread(lock_order_on):
    # the order graph is global: thread 1 records A->B, thread 2's B->A
    # trips even though the two never contend
    a, b = locks.lock("t2.A"), locks.lock("t2.B")
    def t1():
        with a:
            with b:
                pass
    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with b:
        with pytest.raises(locks.LockOrderError):
            a.acquire()


def test_ordered_lock_disabled_is_noop():
    locks.reset()
    assert settings.get("debug.lock_order.enabled") is False
    a, b = locks.lock("t3.A"), locks.lock("t3.B")
    with a:
        with b:
            pass
    with b:
        with a:  # inverted, but checking is off
            pass


def test_ordered_rlock_reentry_ok(lock_order_on):
    r = locks.rlock("t.R")
    with r:
        with r:
            pass
    assert not r.locked()


def test_ordered_condition_wait_notify(lock_order_on):
    c = locks.condition("t.C")
    hits = []
    def waiter():
        with c:
            c.wait_for(lambda: hits, timeout=5)
            hits.append("woke")
    th = threading.Thread(target=waiter)
    th.start()
    import time
    time.sleep(0.05)
    with c:
        hits.append("set")
        c.notify_all()
    th.join(timeout=5)
    assert hits == ["set", "woke"]
