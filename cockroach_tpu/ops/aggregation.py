"""Grouped aggregation kernels — the hashAggregator / orderedAggregator analog.

Reference: pkg/sql/colexec/hash_aggregator.go:62 builds a vectorized hash table
(colexechash.HashTable, hashtable.go:215) and accumulates per-bucket; ordered
aggregation detects group boundaries in sorted input. The TPU redesign uses two
strategies, both static-shape:

1. ``sort_groupby`` — the general path. Sort the tile by the group key columns
   (XLA sort), detect segment boundaries, reduce with segmented associative
   scans (ops/segscan.py — log-depth fused passes; jax.ops.segment_* lowers
   to scatter, which serializes on the TPU vector unit at ~100ms/op/1M rows).
   Replaces pointer-chasing hash tables, which TPUs cannot do, with sorts and
   scans, which they do well.

2. ``smallgroup_partial_states`` — the MXU/VPU path for planner-known small group
   cardinality G (e.g. TPC-H Q1's returnflag x linestatus = 6): a one-hot
   [tile, G] membership matrix and masked reductions; exact in int64, no sort.

NULL semantics: NULLs form their own group (SQL GROUP BY); aggregates skip
NULL inputs; SUM/MIN/MAX over an empty (all-NULL) group is NULL; COUNT is 0.

Partial aggregation across devices/batches: every aggregate here has a
well-defined merge (sum+sum, count+count, min of mins...), used by the
distributed final-stage aggregator (reference analog: local+final aggregation
stages in distsql_physical_planner.go).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import FLOAT64, INT64, Family, Schema, SQLType
from . import segscan


@dataclass(frozen=True)
class AggSpec:
    # sum | count | count_rows | min | max | avg | any_not_null
    # | bool_and | bool_or | string_agg
    # | var | stddev | var_pop | stddev_pop | sum_sq (internal state)
    func: str
    col: int | None = None  # input column index (None for count_rows)
    name: str = ""
    sep: str = ","  # string_agg separator (ignored by every other func)


# statistical aggregates decompose into (sum, sum of squares, count) states
STAT_FUNCS = ("var", "stddev", "var_pop", "stddev_pop")


def agg_output_type(spec: AggSpec, schema: Schema) -> SQLType:
    from ..coldata.types import BOOL

    if spec.func in ("count", "count_rows"):
        return INT64
    if spec.func in ("bool_and", "bool_or"):
        return BOOL
    if spec.func == "string_agg":
        from ..coldata.types import STRING

        return STRING
    if spec.func in ("avg",) + STAT_FUNCS or spec.func == "sum_sq":
        return FLOAT64
    t = schema.types[spec.col]
    if spec.func == "sum":
        # CRDB promotes sum(int) to DECIMAL; we keep int64 and document the
        # divergence (overflow policy: TPC-H fits; see SURVEY.md §7 hard parts).
        # Float sums accumulate and return in float64.
        if t.family is Family.FLOAT:
            return FLOAT64
        return t
    return t  # min/max/any_not_null keep input type


def _minmax_sentinel(dtype, is_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(np.inf if is_min else -np.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.array(is_min, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if is_min else info.min, dtype)


def _segment_agg(spec: AggSpec, col: Column | None, live, seg, cap,
                 t: SQLType | None):
    """Per-segment reduction -> (data[cap], valid[cap]) given segment ids —
    the CPU path (XLA:CPU scatters are a cheap serial loop; see
    segscan.use_scans for the strategy split)."""
    if spec.func == "count_rows":
        data = jax.ops.segment_sum(live.astype(jnp.int64), seg, num_segments=cap)
        return data, jnp.ones((cap,), jnp.bool_)
    contributes = live & col.valid
    if spec.func == "count":
        data = jax.ops.segment_sum(contributes.astype(jnp.int64), seg, num_segments=cap)
        return data, jnp.ones((cap,), jnp.bool_)
    cnt = jax.ops.segment_sum(contributes.astype(jnp.int32), seg, num_segments=cap)
    nonempty = cnt > 0
    if spec.func in ("sum_f", "sum_sq"):
        d = col.data.astype(jnp.float64)
        if t is not None and t.family is Family.DECIMAL:
            d = d / (10.0 ** t.scale)
        if spec.func == "sum_sq":
            d = d * d
        vals = jnp.where(contributes, d, 0.0)
        return jax.ops.segment_sum(vals, seg, num_segments=cap), nonempty
    if spec.func in ("sum", "avg"):
        if t.family is Family.FLOAT or spec.func == "avg":
            vals = jnp.where(contributes, col.data.astype(jnp.float64), 0.0)
            s = jax.ops.segment_sum(vals, seg, num_segments=cap)
            if spec.func == "avg":
                denom = jnp.where(nonempty, cnt, 1).astype(jnp.float64)
                avg = s / denom
                if t.family is Family.DECIMAL:
                    avg = avg / (10.0**t.scale)
                return avg, nonempty
            return s, nonempty
        vals = jnp.where(contributes, col.data.astype(jnp.int64), 0)
        return jax.ops.segment_sum(vals, seg, num_segments=cap), nonempty
    if spec.func in ("min", "max"):
        is_min = spec.func == "min"
        sent = _minmax_sentinel(col.data.dtype, is_min)
        vals = jnp.where(contributes, col.data, sent)
        fn = jax.ops.segment_min if is_min else jax.ops.segment_max
        return fn(vals, seg, num_segments=cap), nonempty
    if spec.func == "any_not_null":
        sent = _minmax_sentinel(col.data.dtype, False)
        vals = jnp.where(contributes, col.data, sent)
        return jax.ops.segment_max(vals, seg, num_segments=cap), nonempty
    if spec.func in ("bool_and", "bool_or"):
        # AND = min over {0,1}, OR = max; non-contributing rows carry the
        # identity. int32 lanes: XLA segment reductions over pred are
        # unreliable on some backends
        is_and = spec.func == "bool_and"
        vals = jnp.where(contributes, col.data.astype(jnp.bool_),
                         jnp.bool_(is_and)).astype(jnp.int32)
        fn = jax.ops.segment_min if is_and else jax.ops.segment_max
        return (fn(vals, seg, num_segments=cap).astype(jnp.bool_), nonempty)
    raise ValueError(f"unknown aggregate {spec.func}")


def _scan_agg_entries(spec: AggSpec, col: Column | None, live,
                      t: SQLType | None):
    """Plan one aggregate as segmented-scan work: returns (entries, finish)
    where entries is a list of (op, row_vals) to scan and finish(*at_slots)
    maps the scans' per-segment totals (gathered at segment ends) to
    (data, valid).

    The scans replace jax.ops.segment_* (scatter-lowered on TPU, ~100ms per
    op per 1M-row tile) with log-depth fused passes (segscan.py)."""
    add = jnp.add

    if spec.func == "count_rows":
        return ([(add, live.astype(jnp.int64))],
                lambda c: (c, jnp.ones_like(c, dtype=jnp.bool_)))
    contributes = live & col.valid
    if spec.func == "count":
        return ([(add, contributes.astype(jnp.int64))],
                lambda c: (c, jnp.ones_like(c, dtype=jnp.bool_)))
    cnt_entry = (add, contributes.astype(jnp.int64))
    if spec.func in ("sum_f", "sum_sq"):
        d = col.data.astype(jnp.float64)
        if t is not None and t.family is Family.DECIMAL:
            d = d / (10.0 ** t.scale)
        if spec.func == "sum_sq":
            d = d * d
        vals = jnp.where(contributes, d, 0.0)
        return ([cnt_entry, (add, vals)],
                lambda c, s: (s, c > 0))
    if spec.func in ("sum", "avg"):
        if t.family is Family.FLOAT or spec.func == "avg":
            vals = jnp.where(contributes, col.data.astype(jnp.float64), 0.0)

            def finish_f(c, s):
                if spec.func != "avg":
                    return s, c > 0
                avg = s / jnp.where(c > 0, c, 1).astype(jnp.float64)
                if t.family is Family.DECIMAL:
                    avg = avg / (10.0 ** t.scale)
                return avg, c > 0

            return [cnt_entry, (add, vals)], finish_f
        vals = jnp.where(contributes, col.data.astype(jnp.int64), 0)
        return [cnt_entry, (add, vals)], lambda c, s: (s, c > 0)
    if spec.func in ("min", "max"):
        is_min = spec.func == "min"
        sent = _minmax_sentinel(col.data.dtype, is_min)
        vals = jnp.where(contributes, col.data, sent)
        op = jnp.minimum if is_min else jnp.maximum
        return [cnt_entry, (op, vals)], lambda c, s: (s, c > 0)
    if spec.func == "any_not_null":
        sent = _minmax_sentinel(col.data.dtype, False)
        vals = jnp.where(contributes, col.data, sent)
        return [cnt_entry, (jnp.maximum, vals)], lambda c, s: (s, c > 0)
    if spec.func in ("bool_and", "bool_or"):
        is_and = spec.func == "bool_and"
        vals = jnp.where(contributes, col.data.astype(jnp.bool_),
                         jnp.bool_(is_and))
        op = jnp.logical_and if is_and else jnp.logical_or
        return [cnt_entry, (op, vals)], lambda c, s: (s, c > 0)
    raise ValueError(f"unknown aggregate {spec.func}")


def sort_groupby(
    batch: Batch,
    schema: Schema,
    group_cols: tuple[int, ...],
    aggs: tuple[AggSpec, ...],
    out_capacity: int | None = None,
    col_stats: dict[int, tuple] | None = None,
    presorted: bool = False,
    compact: bool = True,
) -> tuple[Batch, jax.Array]:
    """General grouped aggregation over one tile. Output tile: one live row per
    group (group key columns first, then aggregates), padded to capacity.

    Returns (batch, num_groups). If num_groups > out_capacity the output is
    truncated and the caller must retry with a larger tile (same capacity-
    bucketing contract as hash_join_general).

    The group keys bit-pack into as few uint64 sort operands as possible
    (ops/keys.py; catalog stats shrink integer keys) — on TPU lax.sort
    compile time scales with operand count, so a 3-column TPC-H group-by
    sorts on ONE packed word instead of seven operands.

    presorted=True asserts equal group keys are already ADJACENT in the
    input (clustered storage, Table.ordering) and skips the key sort —
    the colexec orderedAggregator specialization (ordered sort-free
    grouping). compact=True still runs a single-operand stable sort that
    pushes dead rows last (needed when filters interleave dead rows);
    compact=False additionally asserts live rows form a prefix (pure
    scan tiles), making the whole grouping sort-free."""
    from . import keys as key_ops

    cap = batch.capacity
    cap_out = out_capacity or cap
    live = batch.mask
    col_stats = col_stats or {}

    # Sort live rows first, then by group keys (nulls are their own group;
    # NULL rows' garbage data is zeroed inside key_segments so the NULL
    # group is contiguous even with later key columns in play).
    segs: list = [key_ops.BitSeg(1, (~live).astype(jnp.uint64))]
    for gi in group_cols:
        c = batch.cols[gi]
        segs.extend(key_ops.key_segments(
            c.data, c.valid, schema.types[gi], desc=False, nulls_first=False,
            stats=col_stats.get(gi), order_semantics=False,
        ))
    operands = key_ops.pack_operands(segs)
    perm = jnp.arange(cap, dtype=jnp.int32)
    if not presorted:
        sorted_res = jax.lax.sort(
            operands + [perm], num_keys=len(operands) + 1
        )
        perm = sorted_res[-1]
        key_words = sorted_res[:-1]
    elif compact:
        # clustered keys: only push dead rows last (stable, so group
        # adjacency survives) — one u8 operand instead of the packed keys
        _, perm = jax.lax.sort(
            [(~live).astype(jnp.uint8), perm], num_keys=2
        )
        key_words = [w[perm] for w in operands]
    else:
        key_words = operands  # identity permutation, zero sorts

    live_s = live[perm] if (not presorted or compact) else live
    keys_s = [
        (batch.cols[gi].data[perm], batch.cols[gi].valid[perm])
        for gi in group_cols
    ] if (not presorted or compact) else [
        (batch.cols[gi].data, batch.cols[gi].valid) for gi in group_cols
    ]

    # Group boundaries: compare adjacent rows on the SORTED packed words
    # (word equality == full group-key equality, NULL==NULL included).
    idx = jnp.arange(cap)
    changed = jnp.zeros((cap,), jnp.bool_)
    for w in key_words:
        changed = changed | (w != jnp.roll(w, 1, axis=0))
    prev_live = jnp.roll(live_s, 1)
    boundary = live_s & ((idx == 0) | changed | ~prev_live)
    num_groups = jnp.sum(boundary, dtype=jnp.int32)

    out_cols: list[Column] = []
    out_mask = jnp.arange(cap_out, dtype=jnp.int32) < num_groups

    if not segscan.use_scans():
        # CPU: scatter the boundary row's key into its segment slot and
        # reduce with jax.ops.segment_* (XLA:CPU scatters are a cheap serial
        # loop; 20 log-depth scan passes are not — segscan.use_scans).
        seg = jnp.maximum(jnp.cumsum(boundary.astype(jnp.int32)) - 1, 0)
        dest = jnp.where(boundary, seg, cap_out)
        for kd, kv in keys_s:
            data = jnp.zeros(
                (cap_out,) + kd.shape[1:], kd.dtype
            ).at[dest].set(kd, mode="drop")
            valid = jnp.zeros((cap_out,), jnp.bool_).at[dest].set(
                kv, mode="drop"
            )
            out_cols.append(Column(data=data, valid=valid))
        for spec in aggs:
            col = None
            t = None
            if spec.col is not None:
                t = schema.types[spec.col]
                col = Column(
                    data=batch.cols[spec.col].data[perm],
                    valid=batch.cols[spec.col].valid[perm],
                )
            data, valid = _segment_agg(spec, col, live_s, seg, cap_out, t)
            out_cols.append(Column(data=data, valid=valid & out_mask))
        return Batch(cols=tuple(out_cols), mask=out_mask), num_groups

    # TPU: segment j's total lives at its END row after an inclusive
    # segmented scan; compacting the end rows to the front (one stable sort)
    # puts segment j's end at position j — scatter-free slot assignment.
    ends = segscan.seg_ends(boundary, live_s)
    slot_idx = segscan.compact_to_slots(ends, cap_out)

    # Group key columns: gather the end row's keys (same segment, same key).
    for kd, kv in keys_s:
        g = kd[slot_idx]
        m = out_mask if g.ndim == 1 else out_mask[:, None]  # BYTES: [cap, W]
        data = jnp.where(m, g, jnp.zeros_like(g))
        out_cols.append(Column(data=data, valid=kv[slot_idx] & out_mask))

    # One fused multi-scan covers every aggregate's per-segment reduction.
    entries: list = []
    finishers: list = []
    for spec in aggs:
        col = None
        t = None
        if spec.col is not None:
            t = schema.types[spec.col]
            col = Column(
                data=batch.cols[spec.col].data[perm],
                valid=batch.cols[spec.col].valid[perm],
            )
        es, finish = _scan_agg_entries(spec, col, live_s, t)
        finishers.append((len(entries), len(es), finish))
        entries.extend(es)
    if entries:
        scanned = segscan.seg_scan_multi(
            [op for op, _ in entries], [v for _, v in entries], boundary
        )
        at_slots = [s[slot_idx] for s in scanned]
    for start, n, finish in finishers:
        data, valid = finish(*at_slots[start:start + n])
        data = jnp.where(out_mask, data, jnp.zeros_like(data[:1]))
        out_cols.append(Column(data=data, valid=valid & out_mask))

    return Batch(cols=tuple(out_cols), mask=out_mask), num_groups


def groupby_output_schema(
    schema: Schema, group_cols: tuple[int, ...], aggs: tuple[AggSpec, ...]
) -> Schema:
    names = [schema.names[i] for i in group_cols]
    types = [schema.types[i] for i in group_cols]
    for spec in aggs:
        names.append(spec.name or f"{spec.func}_{spec.col}")
        types.append(agg_output_type(spec, schema))
    return Schema(tuple(names), tuple(types))


_MERGE_FUNC = {
    "sum": "sum",
    "sum_f": "sum",
    "sum_sq": "sum",
    "count": "sum",
    "count_rows": "sum",
    "min": "min",
    "max": "max",
    "any_not_null": "any_not_null",
    "bool_and": "bool_and",
    "bool_or": "bool_or",
}


def partial_layout(
    schema: Schema, group_cols: tuple[int, ...], aggs: tuple[AggSpec, ...]
):
    """The partial-aggregation state layout shared by partial and final
    stages: group keys first, then state columns (avg -> sum + count).

    Returns (partial_specs, state_schema, final_map) where final_map[j] gives,
    for output agg j, ('avg', sum_state_idx, count_state_idx) or
    (func, state_idx) with state indices relative to the first state column."""
    partial_specs: list[AggSpec] = []
    final_map = []
    for spec in aggs:
        if spec.func in STAT_FUNCS:
            si = len(partial_specs)
            partial_specs.append(AggSpec("sum_f", spec.col, f"_s{si}"))
            partial_specs.append(AggSpec("sum_sq", spec.col, f"_q{si}"))
            partial_specs.append(AggSpec("count", spec.col, f"_c{si}"))
            final_map.append((spec.func, si, si + 1, si + 2))
        elif spec.func == "avg":
            si = len(partial_specs)
            t = schema.types[spec.col]
            partial_specs.append(AggSpec("sum", spec.col, f"_s{si}"))
            partial_specs.append(AggSpec("count", spec.col, f"_c{si}"))
            final_map.append(("avg", si, si + 1, t))
        else:
            si = len(partial_specs)
            partial_specs.append(
                AggSpec(spec.func, spec.col, f"_st{si}")
            )
            final_map.append((spec.func, si))
    state_schema = groupby_output_schema(
        schema, group_cols, tuple(partial_specs)
    )
    return tuple(partial_specs), state_schema, final_map




def merge_specs_for(partial_specs: tuple[AggSpec, ...], num_keys: int):
    """Merge aggregation specs over the partial-state layout (group keys at
    0..num_keys-1, states after)."""
    return tuple(
        AggSpec(_MERGE_FUNC[s.func], num_keys + i, s.name)
        for i, s in enumerate(partial_specs)
    )


def finalize_states(state: Batch, final_map, num_keys: int) -> Batch:
    """Turn a merged partial-state batch into final SQL results (avg = sum /
    count, decimal scale restored). Shared by the single-node AggregateOp and
    the distributed final stage."""
    k = num_keys
    cols = list(state.cols[:k])
    for fm in final_map:
        if fm[0] in STAT_FUNCS:
            func, si, qi, ci = fm
            sm = state.cols[k + si].data.astype(jnp.float64)
            sq = state.cols[k + qi].data.astype(jnp.float64)
            n = state.cols[k + ci].data.astype(jnp.float64)
            safe_n = jnp.where(n > 0, n, 1.0)
            mean = sm / safe_n
            if func.endswith("_pop"):
                var = jnp.maximum(sq / safe_n - mean * mean, 0.0)
                valid = state.cols[k + ci].data > 0
            else:
                denom = jnp.where(n > 1, n - 1.0, 1.0)
                var = jnp.maximum((sq - n * mean * mean) / denom, 0.0)
                valid = state.cols[k + ci].data > 1
            d = jnp.sqrt(var) if func.startswith("stddev") else var
            cols.append(Column(data=d, valid=valid & state.mask))
            continue
        if fm[0] == "avg":
            _, si, ci, t = fm
            s = state.cols[k + si]
            c = state.cols[k + ci]
            denom = jnp.where(c.data > 0, c.data, 1).astype(jnp.float64)
            d = s.data.astype(jnp.float64) / denom
            if t.family is Family.DECIMAL:
                d = d / (10.0**t.scale)
            cols.append(Column(data=d, valid=s.valid & (c.data > 0)))
        else:
            cols.append(state.cols[k + fm[1]])
    return Batch(cols=tuple(cols), mask=state.mask)


def smallgroup_partial_states(
    batch: Batch,
    schema: Schema,
    codes,
    num_groups: int,
    specs: tuple[AggSpec, ...],
):
    """Dense-code partial aggregation: rows with group code g (precomputed,
    in [0, num_groups)) reduce into row g of [num_groups] state arrays.

    Unlike sort_groupby there is no sort and the output is POSITIONALLY
    aligned by code, so cross-tile / cross-device merging is elementwise
    (sum/min/max of equal-shaped arrays) — the TPU-ideal layout for
    planner-known small cardinalities (e.g. TPC-H Q1: 3x2 flag groups).

    Returns (state_cols, group_rows): state_cols is a list of (data[G],
    valid[G]) per spec; group_rows[G] counts rows per group."""
    G = num_groups
    live = batch.mask
    codes = jnp.clip(codes.astype(jnp.int32), 0, G - 1)
    onehot = (codes[:, None] == jnp.arange(G, dtype=jnp.int32)[None, :]) & live[:, None]
    group_rows = jnp.sum(onehot, axis=0, dtype=jnp.int64)
    out = []
    for spec in specs:
        if spec.func == "count_rows":
            out.append((group_rows, jnp.ones((G,), jnp.bool_)))
            continue
        col = batch.cols[spec.col]
        t = schema.types[spec.col]
        member = onehot & col.valid[:, None]
        cnt = jnp.sum(member, axis=0, dtype=jnp.int64)
        nonempty = cnt > 0
        if spec.func == "count":
            out.append((cnt, jnp.ones((G,), jnp.bool_)))
        elif spec.func == "sum":
            if t.family is Family.FLOAT:
                v = jnp.where(member, col.data.astype(jnp.float64)[:, None], 0.0)
            else:
                v = jnp.where(member, col.data.astype(jnp.int64)[:, None], 0)
            out.append((jnp.sum(v, axis=0), nonempty))
        elif spec.func in ("min", "max"):
            is_min = spec.func == "min"
            sent = _minmax_sentinel(col.data.dtype, is_min)
            v = jnp.where(member, col.data[:, None], sent)
            out.append((jnp.min(v, axis=0) if is_min else jnp.max(v, axis=0),
                        nonempty))
        elif spec.func == "any_not_null":
            sent = _minmax_sentinel(col.data.dtype, False)
            v = jnp.where(member, col.data[:, None], sent)
            out.append((jnp.max(v, axis=0), nonempty))
        else:
            raise ValueError(f"unsupported dense-state aggregate {spec.func}")
    return out, group_rows


def merge_dense_states(specs: tuple[AggSpec, ...], acc, new):
    """Elementwise merge of positionally-aligned dense states."""
    out = []
    for spec, (ad, av), (nd, nv) in zip(specs, acc, new):
        if spec.func in ("sum", "count", "count_rows"):
            out.append((ad + nd, av | nv))
        elif spec.func == "min":
            out.append((jnp.minimum(ad, nd), av | nv))
        elif spec.func in ("max", "any_not_null"):
            out.append((jnp.maximum(ad, nd), av | nv))
        else:
            raise ValueError(spec.func)
    return out


# ---------------------------------------------------------------------------
# mesh reduction of positionally-aligned states (sharded -> replicated)


def psum_dense_states(specs: tuple[AggSpec, ...], states, axis_name: str):
    """Reduce dense states across a mesh axis with XLA collectives — the
    all_to_all-free path for positionally-aligned layouts: sums/counts ride
    psum, min/max ride pmin/pmax, valid flags OR via psum>0. Must run inside
    shard_map over `axis_name`."""
    out = []
    for spec, (d, v) in zip(specs, states):
        if spec.func in ("sum", "count", "count_rows"):
            rd = jax.lax.psum(d, axis_name)
        elif spec.func == "min":
            rd = jax.lax.pmin(d, axis_name)
        elif spec.func in ("max", "any_not_null"):
            rd = jax.lax.pmax(d, axis_name)
        elif spec.func == "avg":
            rd = jax.tree_util.tree_map(
                lambda x: jax.lax.psum(x, axis_name), d
            )
        elif spec.func in ("bool_and", "bool_or"):
            # AND = min over {0,1} lanes, OR = max (pred collectives are
            # unreliable on some backends: ride int32)
            fn = jax.lax.pmin if spec.func == "bool_and" else jax.lax.pmax
            rd = fn(d.astype(jnp.int32), axis_name).astype(jnp.bool_)
        else:
            raise ValueError(spec.func)
        rv = jax.lax.psum(v.astype(jnp.int32), axis_name) > 0
        out.append((rd, rv))
    return out


def dense_layout(key_sizes: tuple[int, ...]) -> tuple[int, tuple[int, ...]]:
    """(G, strides) for the dense group-code space: one extra code per key
    column for NULL (every NULL combination is its own group, matching SQL
    GROUP BY semantics)."""
    eff = tuple(s + 1 for s in key_sizes)
    G = 1
    for s in eff:
        G *= s
    strides = []
    acc = 1
    for s in reversed(eff):
        strides.append(acc)
        acc *= s
    return G, tuple(reversed(strides))


def dense_group_codes(batch: Batch, group_cols, strides, key_sizes,
                      key_lows=None):
    """Per-row dense group code from bounded key columns (NULL maps to the
    extra per-column code). key_lows[i] offsets integer-family keys whose
    catalog stats put them in [lo, lo+size) — dictionary codes use lo=0."""
    code = jnp.zeros((batch.capacity,), jnp.int32)
    oob = jnp.zeros((batch.capacity,), jnp.bool_)
    lows = key_lows or (0,) * len(group_cols)
    for gi, st, size, lo in zip(group_cols, strides, key_sizes, lows):
        c = batch.cols[gi]
        v = c.data.astype(jnp.int32) - jnp.int32(lo)
        # rows outside the planned bounds (stale stats) are flagged, not
        # clipped into a neighboring group — callers route them to a
        # detectable overflow slot and fall back to the sort path
        oob = oob | (c.valid & ((v < 0) | (v >= size)))
        ci = jnp.where(c.valid, jnp.clip(v, 0, size - 1), size)
        code = code + ci * st
    return code, oob


def dense_scatter_states(
    batch: Batch,
    schema: Schema,
    codes,
    G: int,
    specs: tuple[AggSpec, ...],
):
    """Scatter-based dense-code partial aggregation: rows with group code g
    reduce into slot g of [G] state arrays via segment_* ops — O(rows)
    scatters plus O(G) state traffic, NO sort and NO one-hot (the
    smallgroup one-hot matmul is O(rows x G), viable only for tiny G).
    The missing middle this covers: bounded-but-large key spaces like
    TPC-H's GROUP BY l_orderkey (reference hash agg: hash_aggregator.go:62;
    here the dense code IS the hash table slot, collision-free).

    Returns (state_cols, group_rows) positionally aligned by code —
    cross-tile/device merge stays elementwise (merge_dense_states)."""
    live = batch.mask
    seg = jnp.where(live, codes.astype(jnp.int32), G)  # dead rows drop
    group_rows = jax.ops.segment_sum(
        live.astype(jnp.int64), seg, num_segments=G
    )
    out = []
    for spec in specs:
        col = None
        t = None
        if spec.col is not None:
            t = schema.types[spec.col]
            col = batch.cols[spec.col]
        data, valid = _segment_agg(spec, col, live, seg, G, t)
        out.append((data, valid))
    return out, group_rows


def dense_onehot_states(
    batch: Batch,
    schema: Schema,
    codes,
    G: int,
    specs: tuple[AggSpec, ...],
):
    """One-hot dense partial states (alias of smallgroup_partial_states) —
    O(rows x G), the right shape only for tiny G where the [rows, G]
    membership matrix rides the VPU in one fused pass."""
    return smallgroup_partial_states(batch, schema, codes, G, specs)


def dense_finalize(base: Schema, group_cols, strides, key_sizes, G,
                   final_map, states, rows, key_lows=None) -> Batch:
    """Decode dense group codes back into key columns and finalize the
    aggregate states — shared by SmallGroupAggregateOp and the SPMD path.
    key_lows restores integer-stat key offsets (see dense_group_codes)."""
    gid = jnp.arange(G, dtype=jnp.int32)
    lows = key_lows or (0,) * len(group_cols)
    cols = []
    for gi, st, size, lo in zip(group_cols, strides, key_sizes, lows):
        code_i = (gid // st) % (size + 1)
        t = base.types[gi]
        valid = code_i < size  # code==size means NULL key
        cols.append(Column(
            data=jnp.where(valid, code_i + jnp.int32(lo), 0).astype(t.dtype),
            valid=valid,
        ))
    mask = rows > 0
    for (d, v) in states:
        cols.append(Column(data=d, valid=v & mask))
    state_batch = Batch(cols=tuple(cols), mask=mask)
    return finalize_states(state_batch, final_map, len(group_cols))


# ---------------------------------------------------------------------------
# scalar (no GROUP BY) aggregation states — shared by ScalarAggregateOp and
# the SPMD planner's psum-merged scalar stage


def scalar_tile_states(batch: Batch, aggs: tuple[AggSpec, ...], base: Schema):
    """Per-tile scalar states: one (value, valid) pair per agg (avg carries
    (sum, count))."""
    out = []
    for spec in aggs:
        if spec.func == "count_rows":
            out.append((jnp.sum(batch.mask, dtype=jnp.int64), jnp.bool_(True)))
            continue
        c = batch.cols[spec.col]
        t = base.types[spec.col]
        m = batch.mask & c.valid
        cnt = jnp.sum(m, dtype=jnp.int64)
        if spec.func == "count":
            out.append((cnt, jnp.bool_(True)))
        elif spec.func in ("sum", "avg"):
            if t.family is Family.FLOAT or spec.func == "avg":
                s = jnp.sum(jnp.where(m, c.data.astype(jnp.float64), 0.0))
            else:
                s = jnp.sum(jnp.where(m, c.data.astype(jnp.int64), 0))
            if spec.func == "avg":
                out.append(((s, cnt), cnt > 0))
            else:
                out.append((s, cnt > 0))
        elif spec.func in ("min", "max"):
            is_min = spec.func == "min"
            sent = _minmax_sentinel(c.data.dtype, is_min)
            vals = jnp.where(m, c.data, sent)
            red = jnp.min(vals) if is_min else jnp.max(vals)
            out.append((red, cnt > 0))
        elif spec.func in STAT_FUNCS:
            d = c.data.astype(jnp.float64)
            if t.family is Family.DECIMAL:
                d = d / (10.0 ** t.scale)
            s_ = jnp.sum(jnp.where(m, d, 0.0))
            q_ = jnp.sum(jnp.where(m, d * d, 0.0))
            ok = cnt > 0 if spec.func.endswith("_pop") else cnt > 1
            out.append(((s_, q_, cnt), ok))
        elif spec.func in ("bool_and", "bool_or"):
            is_and = spec.func == "bool_and"
            vals = jnp.where(m, c.data.astype(jnp.bool_), jnp.bool_(is_and))
            red = jnp.all(vals) if is_and else jnp.any(vals)
            out.append((red, cnt > 0))
        else:
            raise ValueError(spec.func)
    return out


def scalar_merge_states(aggs: tuple[AggSpec, ...], acc, new):
    out = []
    for spec, (a, av), (n, nv) in zip(aggs, acc, new):
        if spec.func in ("count", "count_rows"):
            out.append((a + n, jnp.bool_(True)))
        elif spec.func == "sum":
            out.append((a + n, av | nv))
        elif spec.func == "avg":
            out.append(((a[0] + n[0], a[1] + n[1]), av | nv))
        elif spec.func in STAT_FUNCS:
            cnt = a[2] + n[2]
            ok = cnt > 0 if spec.func.endswith("_pop") else cnt > 1
            out.append(((a[0] + n[0], a[1] + n[1], cnt), ok))
        elif spec.func == "min":
            out.append((jnp.minimum(a, n), av | nv))
        elif spec.func == "max":
            out.append((jnp.maximum(a, n), av | nv))
        elif spec.func == "bool_and":
            out.append((a & n, av | nv))
        elif spec.func == "bool_or":
            out.append((a | n, av | nv))
        else:
            raise ValueError(spec.func)
    return out


def scalar_result_batch(aggs: tuple[AggSpec, ...], base: Schema,
                        out_schema: Schema, acc) -> Batch:
    """States -> one-row result Batch (acc=None means empty input: counts
    are 0, everything else NULL — SQL scalar aggregate semantics)."""
    acc = list(acc) if acc is not None else None
    cols = []
    for spec, t in zip(aggs, out_schema.types):
        if acc is None:
            if spec.func in ("count", "count_rows"):
                d, v = jnp.zeros((1,), jnp.int64), jnp.ones((1,), jnp.bool_)
            else:
                d = jnp.zeros((1,), t.dtype)
                v = jnp.zeros((1,), jnp.bool_)
        else:
            (val, valid) = acc.pop(0)  # states consumed in agg order
            if spec.func in STAT_FUNCS:
                sm, sq, c = val
                n = c.astype(jnp.float64)
                safe_n = jnp.where(n > 0, n, 1.0)
                mean = sm / safe_n
                if spec.func.endswith("_pop"):
                    var = jnp.maximum(sq / safe_n - mean * mean, 0.0)
                else:
                    denom = jnp.where(n > 1, n - 1.0, 1.0)
                    var = jnp.maximum((sq - n * mean * mean) / denom, 0.0)
                d = (jnp.sqrt(var) if spec.func.startswith("stddev")
                     else var)[None]
                cols.append(Column(data=d, valid=jnp.asarray(valid)[None]))
                continue
            if spec.func == "avg":
                s, c = val
                base_t = base.types[spec.col]
                d = s.astype(jnp.float64) / jnp.where(
                    c > 0, c, 1
                ).astype(jnp.float64)
                if base_t.family is Family.DECIMAL:
                    d = d / (10.0**base_t.scale)
                d = d[None]
            else:
                d = val.astype(t.dtype)[None]
            v = jnp.asarray(valid)[None]
        cols.append(Column(data=d, valid=v))
    return Batch(cols=tuple(cols), mask=jnp.ones((1,), jnp.bool_))


def agg_output_schema(
    base: Schema, group_cols: tuple[int, ...], aggs: tuple[AggSpec, ...],
    mode: str = "complete",
) -> Schema:
    """Output schema of an aggregation stage — the ONE place the group-key
    + per-agg naming/typing rule lives (avg -> FLOAT64, else
    agg_output_type), shared by the flow operators, the distribution
    rewrite, and the SPMD lowering."""
    _, state_schema, final_map = partial_layout(base, group_cols, aggs)
    if mode == "partial":
        return state_schema
    k = len(group_cols)
    if mode == "final":
        names = list(state_schema.names[:k])
        types = list(state_schema.types[:k])
    else:
        names = [base.names[i] for i in group_cols]
        types = [base.types[i] for i in group_cols]
    for spec, fm in zip(aggs, final_map):
        names.append(spec.name or spec.func)
        types.append(FLOAT64 if fm[0] in ("avg",) + STAT_FUNCS
                     else agg_output_type(spec, base))
    return Schema(tuple(names), tuple(types))
