"""Grouped aggregation kernels — the hashAggregator / orderedAggregator analog.

Reference: pkg/sql/colexec/hash_aggregator.go:62 builds a vectorized hash table
(colexechash.HashTable, hashtable.go:215) and accumulates per-bucket; ordered
aggregation detects group boundaries in sorted input. The TPU redesign uses two
strategies, both static-shape:

1. ``sort_groupby`` — the general path. Sort the tile by the group key columns
   (XLA sort), detect segment boundaries, reduce with jax.ops.segment_* into a
   padded output tile. Replaces pointer-chasing hash tables, which TPUs cannot
   do, with sorts, which they do well.

2. ``smallgroup_groupby`` — the MXU/VPU path for planner-known small group
   cardinality G (e.g. TPC-H Q1's returnflag x linestatus = 6): a one-hot
   [tile, G] membership matrix and masked reductions; exact in int64, no sort.

NULL semantics: NULLs form their own group (SQL GROUP BY); aggregates skip
NULL inputs; SUM/MIN/MAX over an empty (all-NULL) group is NULL; COUNT is 0.

Partial aggregation across devices/batches: every aggregate here has a
well-defined merge (sum+sum, count+count, min of mins...), used by the
distributed final-stage aggregator (reference analog: local+final aggregation
stages in distsql_physical_planner.go).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import FLOAT64, INT64, Family, Schema, SQLType


@dataclass(frozen=True)
class AggSpec:
    func: str  # sum | count | count_rows | min | max | avg | any_not_null
    col: int | None = None  # input column index (None for count_rows)
    name: str = ""


def agg_output_type(spec: AggSpec, schema: Schema) -> SQLType:
    if spec.func in ("count", "count_rows"):
        return INT64
    if spec.func == "avg":
        return FLOAT64
    t = schema.types[spec.col]
    if spec.func == "sum":
        # CRDB promotes sum(int) to DECIMAL; we keep int64 and document the
        # divergence (overflow policy: TPC-H fits; see SURVEY.md §7 hard parts).
        # Float sums accumulate and return in float64.
        if t.family is Family.FLOAT:
            return FLOAT64
        return t
    return t  # min/max/any_not_null keep input type


def _minmax_sentinel(dtype, is_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(np.inf if is_min else -np.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.array(is_min, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if is_min else info.min, dtype)


def _segment_agg(spec: AggSpec, col: Column | None, live, seg, cap, t: SQLType | None):
    """Per-segment reduction -> (data[cap], valid[cap]) given segment ids."""
    if spec.func == "count_rows":
        data = jax.ops.segment_sum(live.astype(jnp.int64), seg, num_segments=cap)
        return data, jnp.ones((cap,), jnp.bool_)
    contributes = live & col.valid
    if spec.func == "count":
        data = jax.ops.segment_sum(contributes.astype(jnp.int64), seg, num_segments=cap)
        return data, jnp.ones((cap,), jnp.bool_)
    cnt = jax.ops.segment_sum(contributes.astype(jnp.int32), seg, num_segments=cap)
    nonempty = cnt > 0
    if spec.func in ("sum", "avg"):
        if t.family is Family.FLOAT or spec.func == "avg":
            vals = jnp.where(contributes, col.data.astype(jnp.float64), 0.0)
            s = jax.ops.segment_sum(vals, seg, num_segments=cap)
            if spec.func == "avg":
                denom = jnp.where(nonempty, cnt, 1).astype(jnp.float64)
                avg = s / denom
                if t.family is Family.DECIMAL:
                    avg = avg / (10.0**t.scale)
                return avg, nonempty
            return s, nonempty
        vals = jnp.where(contributes, col.data.astype(jnp.int64), 0)
        return jax.ops.segment_sum(vals, seg, num_segments=cap), nonempty
    if spec.func in ("min", "max"):
        is_min = spec.func == "min"
        sent = _minmax_sentinel(col.data.dtype, is_min)
        vals = jnp.where(contributes, col.data, sent)
        fn = jax.ops.segment_min if is_min else jax.ops.segment_max
        return fn(vals, seg, num_segments=cap), nonempty
    if spec.func == "any_not_null":
        sent = _minmax_sentinel(col.data.dtype, False)
        vals = jnp.where(contributes, col.data, sent)
        return jax.ops.segment_max(vals, seg, num_segments=cap), nonempty
    raise ValueError(f"unknown aggregate {spec.func}")


def sort_groupby(
    batch: Batch,
    schema: Schema,
    group_cols: tuple[int, ...],
    aggs: tuple[AggSpec, ...],
    out_capacity: int | None = None,
) -> tuple[Batch, jax.Array]:
    """General grouped aggregation over one tile. Output tile: one live row per
    group (group key columns first, then aggregates), padded to capacity.

    Returns (batch, num_groups). If num_groups > out_capacity the output is
    truncated and the caller must retry with a larger tile (same capacity-
    bucketing contract as hash_join_general)."""
    cap = batch.capacity
    cap_out = out_capacity or cap
    live = batch.mask

    # Sort live rows first, then by group keys (nulls are their own group).
    operands = [~live]
    for gi in group_cols:
        c = batch.cols[gi]
        operands.append(~c.valid)
        operands.append(c.data)
    perm = jnp.arange(cap, dtype=jnp.int32)
    num_keys = len(operands)
    sorted_ops = jax.lax.sort(operands + [perm], num_keys=num_keys, is_stable=True)
    perm = sorted_ops[-1]

    live_s = live[perm]
    keys_s = [
        (batch.cols[gi].data[perm], batch.cols[gi].valid[perm]) for gi in group_cols
    ]

    #

    idx = jnp.arange(cap)
    changed = jnp.zeros((cap,), jnp.bool_)
    for kd, kv in keys_s:
        prev_d = jnp.roll(kd, 1, axis=0)
        prev_v = jnp.roll(kv, 1, axis=0)
        # two NULLs are the same group regardless of underlying data
        neq = (kv != prev_v) | (kv & prev_v & (kd != prev_d))
        changed = changed | neq
    prev_live = jnp.roll(live_s, 1)
    boundary = live_s & ((idx == 0) | changed | ~prev_live)
    num_groups = jnp.sum(boundary, dtype=jnp.int32)
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg = jnp.maximum(seg, 0)

    out_cols: list[Column] = []
    out_mask = jnp.arange(cap_out, dtype=jnp.int32) < num_groups

    # Group key columns: scatter the boundary row's key into its segment slot.
    dest = jnp.where(boundary, seg, cap_out)
    for kd, kv in keys_s:
        data = jnp.zeros((cap_out,), kd.dtype).at[dest].set(kd, mode="drop")
        valid = jnp.zeros((cap_out,), jnp.bool_).at[dest].set(kv, mode="drop")
        out_cols.append(Column(data=data, valid=valid))

    for spec in aggs:
        col = None
        t = None
        if spec.col is not None:
            t = schema.types[spec.col]
            col = Column(
                data=batch.cols[spec.col].data[perm],
                valid=batch.cols[spec.col].valid[perm],
            )
        data, valid = _segment_agg(spec, col, live_s, seg, cap_out, t)
        out_cols.append(Column(data=data, valid=valid & out_mask))

    return Batch(cols=tuple(out_cols), mask=out_mask), num_groups


def groupby_output_schema(
    schema: Schema, group_cols: tuple[int, ...], aggs: tuple[AggSpec, ...]
) -> Schema:
    names = [schema.names[i] for i in group_cols]
    types = [schema.types[i] for i in group_cols]
    for spec in aggs:
        names.append(spec.name or f"{spec.func}_{spec.col}")
        types.append(agg_output_type(spec, schema))
    return Schema(tuple(names), tuple(types))


def smallgroup_groupby(
    batch: Batch,
    schema: Schema,
    code_col: int,
    num_groups: int,
    aggs: tuple[AggSpec, ...],
) -> Batch:
    """Aggregation when the planner knows group ids are dense codes in
    [0, num_groups) (from dictionary codes or packed key codes). One-hot
    membership + masked reductions; exact for int64; no sort.

    Output tile capacity == num_groups (static); group id g lands in row g.
    The caller decodes row index -> key values via host-side tables."""
    G = num_groups
    live = batch.mask
    codes = jnp.clip(batch.cols[code_col].data.astype(jnp.int32), 0, G - 1)
    onehot = (codes[:, None] == jnp.arange(G, dtype=jnp.int32)[None, :]) & live[:, None]

    group_rows = jnp.sum(onehot, axis=0, dtype=jnp.int64)  # [G]
    out_mask = group_rows > 0

    out_cols: list[Column] = []
    # group id column (dense code) so callers can decode keys
    out_cols.append(
        Column(data=jnp.arange(G, dtype=jnp.int32), valid=jnp.ones((G,), jnp.bool_))
    )

    for spec in aggs:
        if spec.func == "count_rows":
            out_cols.append(Column(data=group_rows, valid=jnp.ones((G,), jnp.bool_)))
            continue
        col = batch.cols[spec.col]
        t = schema.types[spec.col]
        member = onehot & col.valid[:, None]  # [cap, G]
        cnt = jnp.sum(member, axis=0, dtype=jnp.int64)
        nonempty = cnt > 0
        if spec.func == "count":
            out_cols.append(Column(data=cnt, valid=jnp.ones((G,), jnp.bool_)))
        elif spec.func in ("sum", "avg"):
            if t.family is Family.FLOAT or spec.func == "avg":
                v = jnp.where(member, col.data.astype(jnp.float64)[:, None], 0.0)
                s = jnp.sum(v, axis=0)
                if spec.func == "avg":
                    avg = s / jnp.where(nonempty, cnt, 1).astype(jnp.float64)
                    if t.family is Family.DECIMAL:
                        avg = avg / (10.0**t.scale)
                    out_cols.append(Column(data=avg, valid=nonempty))
                else:
                    out_cols.append(Column(data=s, valid=nonempty))
            else:
                v = jnp.where(member, col.data.astype(jnp.int64)[:, None], 0)
                out_cols.append(Column(data=jnp.sum(v, axis=0), valid=nonempty))
        elif spec.func in ("min", "max"):
            is_min = spec.func == "min"
            sent = _minmax_sentinel(col.data.dtype, is_min)
            v = jnp.where(member, col.data[:, None], sent)
            red = jnp.min(v, axis=0) if is_min else jnp.max(v, axis=0)
            out_cols.append(Column(data=red, valid=nonempty))
        elif spec.func == "any_not_null":
            sent = _minmax_sentinel(col.data.dtype, False)
            v = jnp.where(member, col.data[:, None], sent)
            out_cols.append(Column(data=jnp.max(v, axis=0), valid=nonempty))
        else:
            raise ValueError(f"unknown aggregate {spec.func}")

    return Batch(cols=tuple(out_cols), mask=out_mask)
