"""Vectorized multi-column hashing — the colexechash analog.

Reference: pkg/sql/colexec/colexechash/hash_utils*.go computes bucket hashes by
a multiplicative hash folded across key columns. Here: each key column is
bit-cast to uint64, mixed with splitmix64, and combined with a rotate-xor fold
— one fused elementwise pass over the tile, no per-type codegen.

STRING columns hash via their dictionary's precomputed byte-hash table
(coldata.Dictionary.hashes) gathered by code, so equal strings hash equally
across tables regardless of dictionary layout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Column
from ..coldata.types import Family, SQLType

_NULL_SENTINEL = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: jax.Array) -> jax.Array:
    x = x + np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _to_u64(data: jax.Array, t: SQLType) -> jax.Array:
    if t.family is Family.FLOAT:
        from ..utils.backend import require_float_bitcast

        require_float_bitcast("float hash key")
        d = data.astype(jnp.float64)
        d = jnp.where(d == 0.0, 0.0, d)  # canonicalize -0.0
        parts = jax.lax.bitcast_convert_type(d, jnp.uint32)  # [..., 2]
        return (parts[..., 1].astype(jnp.uint64) << np.uint64(32)
                ) | parts[..., 0].astype(jnp.uint64)
    if t.family is Family.BOOL:
        return data.astype(jnp.uint64)
    return data.astype(jnp.int64).astype(jnp.uint64)


def hash_columns(
    cols: list[Column],
    types: list[SQLType],
    hash_tables: dict[int, np.ndarray] | None = None,
) -> jax.Array:
    """64-bit hash per row over the given key columns.

    hash_tables: optional per-position dictionary hash tables for STRING keys
    (code -> uint64); required for STRING columns.
    """
    hash_tables = hash_tables or {}
    h = jnp.full((cols[0].data.shape[0],), np.uint64(0x243F6A8885A308D3))
    for i, (c, t) in enumerate(zip(cols, types)):
        if t.family is Family.STRING:
            table = jnp.asarray(hash_tables[i])
            codes = jnp.clip(c.data, 0, table.shape[0] - 1)
            u = table[codes]
        else:
            u = _to_u64(c.data, t)
        u = jnp.where(c.valid, _splitmix64(u), _NULL_SENTINEL)
        h = _splitmix64(h ^ u)
    return h


def bucket(hashes: jax.Array, num_buckets: int) -> jax.Array:
    """Hash -> bucket id in [0, num_buckets). Used by the hash router
    (reference: colflow/routers.go HashRouter) and grace partitioning."""
    return (hashes % np.uint64(num_buckets)).astype(jnp.int32)
