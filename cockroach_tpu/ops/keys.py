"""Canonical sort-key encoding — bit-packed u64 operands for lax.sort.

The round-2 engine handed lax.sort one operand per null band / NaN band /
value column (a k-key sort cost 2k+1 operands). On the TPU backend each
lax.sort instantiation costs ~17-20s of XLA compile time REGARDLESS of shape,
scaling with operand count (measured on v5e: 2 operands 16s, 3 operands 43s at
1M rows) — so operand count, not row count, is the compile budget.

This module packs an ordered key list into the *minimum* number of sort
operands: every key contributes a bit-segment stream
``[null_flag(1), value(bits)]`` and the stream is packed MSB-first into
uint64 words. Comparing the word tuple lexicographically equals comparing the
concatenated bit string, so ANY split of segments across word boundaries
preserves order — values may straddle words freely. Typical TPC-H sorts and
group-bys land in ONE packed word (+ the permutation operand), so the whole
engine reuses a single compiled sort kernel per capacity.

Value encodings (order-preserving within the segment's bit width):
- INT/DECIMAL/DATE/TIMESTAMP/INTERVAL: ``x - lo`` when catalog stats give a
  [lo, hi] range (bits = ceil(log2(hi-lo+1))), else sign-flip at type width.
- STRING: dictionary rank gather (ORDER BY) or raw code (GROUP BY equality),
  bits from the dictionary size.
- BOOL: 1 bit.
- BYTES: big-endian packed 64-bit word lanes (coldata.pack_be_words).
- FLOAT: passes through as a NATIVE float64 sort operand — the x64 rewriter
  on this TPU backend miscompiles f64<->u32 bitcasts (verified: negative
  doubles collapse to f32-NaN bit patterns), so floats ride lax.sort's
  comparator directly, with their NaN band packed as a bit-segment.

DESC inverts value bits within the segment (floats: negation); NULL ordering
follows CockroachDB (NULLs first ascending — tree.Datum ordering).

Reference analog: pkg/sql/colexec/sort.go builds per-type comparators via
execgen; here the "comparator" is the packed key itself.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.types import Family, SQLType

_U64_ONE = np.uint64(1)


@dataclass(frozen=True)
class BitSeg:
    """`bits` wide unsigned values (< 2**bits) in a uint64 lane."""

    bits: int
    arr: jax.Array  # uint64


@dataclass(frozen=True)
class FloatSeg:
    """A native float64 sort operand (comparator-ordered by lax.sort)."""

    arr: jax.Array  # float64


def bits_for_count(n: int) -> int:
    """Bits to distinguish n values (>=1)."""
    return max(1, int(n - 1).bit_length()) if n > 1 else 1


def _u64(x) -> jax.Array:
    return x.astype(jnp.uint64)


def _int_segment(data, valid, t: SQLType, stats, desc: bool) -> BitSeg:
    """Order-preserving unsigned encoding of an integer-represented column."""
    d = data.astype(jnp.int64)
    if stats is not None:
        lo, hi = int(stats[0]), int(stats[1])
        bits = bits_for_count(hi - lo + 1)
        v = _u64(jnp.clip(d, lo, hi) - lo)
    else:
        w = 64
        if t.family is Family.INT:
            w = t.width
        elif t.family is Family.DATE:
            w = 32
        elif t.family is Family.STRING:
            w = 32
        bits = w
        # sign-flip maps the signed range onto [0, 2^w)
        v = _u64(d + (1 << (w - 1))) if w < 64 else (
            _u64(d) ^ (_U64_ONE << np.uint64(63))
        )
    v = jnp.where(valid, v, jnp.uint64(0))
    if desc and bits < 64:
        v = (jnp.uint64((1 << bits) - 1) - v)
    elif desc:
        v = ~v
    return BitSeg(bits, v)


def key_segments(
    data,
    valid,
    t: SQLType,
    desc: bool,
    nulls_first: bool,
    rank_table: np.ndarray | None = None,
    stats: tuple | None = None,
    order_semantics: bool = True,
) -> list:
    """Bit/float segments for one key column, null flag included.

    order_semantics=False (GROUP BY) only needs equality: STRING columns use
    raw dictionary codes instead of requiring a rank table.
    """
    segs: list = []
    # null flag: rows whose flag bit is 0 sort first
    nf = _u64(valid) if nulls_first else _u64(~valid)
    segs.append(BitSeg(1, nf))

    fam = t.family
    if fam is Family.FLOAT:
        d = data.astype(jnp.float64)
        # mask by valid: NULL rows carry garbage data, and a garbage NaN
        # would otherwise split the NULL group's packed key bits
        isnan = valid & jnp.isnan(d)
        # CRDB orders NaN before all other values ascending
        nan_flag = _u64(isnan) if desc else _u64(~isnan)
        segs.append(BitSeg(1, nan_flag))
        d = jnp.where(valid & ~isnan, d, 0.0)
        segs.append(FloatSeg(-d if desc else d))
        return segs
    if fam is Family.BYTES:
        from ..coldata.batch import pack_be_words

        words = pack_be_words(data)
        for i in range(words.shape[1]):
            w = jnp.where(valid, words[:, i], jnp.uint64(0))
            segs.append(BitSeg(64, ~w if desc else w))
        return segs
    if fam is Family.BOOL:
        v = _u64(data) & _U64_ONE
        v = jnp.where(valid, v, jnp.uint64(0))
        segs.append(BitSeg(1, (_U64_ONE - v) if desc else v))
        return segs
    if fam is Family.STRING:
        if order_semantics:
            assert rank_table is not None, \
                "STRING ORDER BY needs a dictionary rank table"
            table = jnp.asarray(rank_table)
            codes = jnp.clip(data, 0, table.shape[0] - 1)
            ranked = table[codes].astype(jnp.int64)
            bits = bits_for_count(int(rank_table.shape[0]) + 1)
            v = jnp.where(valid, _u64(ranked), jnp.uint64(0))
            if desc:
                v = jnp.uint64((1 << bits) - 1) - v
            segs.append(BitSeg(bits, v))
            return segs
        # equality only: raw codes; width from stats or dictionary size
        segs.append(_int_segment(data, valid, t, stats, desc))
        return segs
    # integer-represented families
    segs.append(_int_segment(data, valid, t, stats, desc))
    return segs


def pack_operands(segs: list) -> list[jax.Array]:
    """Pack a segment stream into sort operands: uint64 words (bit segments,
    MSB-first) interleaved with native float64 operands. Lexicographic order
    over the returned operand tuple equals order over the segment stream."""
    ops: list[jax.Array] = []
    cur = None
    pos = 0  # bits used in cur, from the MSB
    for s in segs:
        if isinstance(s, FloatSeg):
            if cur is not None:
                ops.append(cur)
                cur, pos = None, 0
            ops.append(s.arr)
            continue
        b = s.bits
        v = s.arr
        if b < 64:
            v = v & jnp.uint64((1 << b) - 1)
        while b > 0:
            if cur is None:
                cur = jnp.zeros_like(v)
                pos = 0
            avail = 64 - pos
            take = min(b, avail)
            chunk = v >> np.uint64(b - take)
            if take < 64:
                chunk = chunk & jnp.uint64((1 << take) - 1)
            cur = cur | (chunk << np.uint64(avail - take))
            pos += take
            b -= take
            if pos == 64:
                ops.append(cur)
                cur, pos = None, 0
    if cur is not None:
        ops.append(cur)
    return ops
