"""Segmented scans — scatter-free per-group reductions over sorted tiles.

The reference's per-group aggregation walks hash-table buckets row by row
(pkg/sql/colexec/colexecagg/hash_*_agg.eg.go); the first TPU design used
``jax.ops.segment_sum`` over sorted segment ids, which XLA lowers to a
scatter-add — measured ~100ms per op per 1M-row tile on v5e (scatter
serializes on the TPU's vector unit). This module replaces every hot-path
segment reduction with a *segmented associative scan*: log2(n) fused
elementwise passes (~1-2ms per 1M-row tile), which is also how the external
sort's merge and the window functions get their per-partition prefix sums.

Layout contract: rows are sorted so each segment is contiguous; ``boundary``
is True on the first row of every segment. Scans are inclusive. Per-segment
totals live at the segment's END row; `totals_everywhere` broadcasts them
back over the whole segment (for window functions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def use_scans() -> bool:
    """Strategy pick at trace time: segmented scans on accelerators (scatter
    serializes on the TPU VPU — ~100ms per 1M-row segment op, measured),
    jax.ops.segment_* on CPU (XLA:CPU scatters are a cheap serial loop while
    log-depth scans cost ~20 full passes over the tile)."""
    return jax.default_backend() != "cpu"


def seg_bcast(op, segop, vals, boundary, live):
    """Per-segment total of `vals`, broadcast to every row of its segment.
    op: elementwise combiner (jnp.minimum/maximum/add) for the scan path;
    segop: the matching jax.ops.segment_* for the CPU scatter path."""
    if not use_scans():
        seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        tot = segop(vals, seg, num_segments=vals.shape[0])
        return tot[seg]
    s = seg_scan(op, vals, boundary)
    return totals_everywhere(s, boundary, live)


def seg_scan(op, vals, boundary, reverse: bool = False):
    """Inclusive segmented scan of `vals` with associative `op`.

    boundary[i]=True starts a new segment at i (in scan direction: when
    reverse=True, boundaries must mark segment starts in the REVERSED order,
    i.e. segment ENDS of the forward order).
    """

    def combine(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, op(v1, v2))

    _, out = jax.lax.associative_scan(
        combine, (boundary, vals), reverse=reverse
    )
    return out


def seg_scan_multi(ops, vals_list, boundary):
    """One associative_scan over several value arrays sharing the same
    segment structure (cheaper than len(ops) separate scans: the flag lane
    and the fusion pass are shared)."""

    def combine(a, b):
        f1 = a[0]
        f2 = b[0]
        outs = tuple(
            jnp.where(f2, v2, op(v1, v2))
            for op, v1, v2 in zip(ops, a[1:], b[1:])
        )
        return (f1 | f2,) + outs

    res = jax.lax.associative_scan(combine, (boundary,) + tuple(vals_list))
    return res[1:]


def seg_ends(boundary, live):
    """True on the LAST live row of each segment. Dead rows must be sorted
    after live rows (the engine's canonical groupby sort order)."""
    nxt_boundary = jnp.concatenate(
        [boundary[1:], jnp.ones((1,), jnp.bool_)]
    )
    nxt_live = jnp.concatenate([live[1:], jnp.zeros((1,), jnp.bool_)])
    return live & (nxt_boundary | ~nxt_live)


def totals_everywhere(scanned, boundary, live):
    """Broadcast each segment's inclusive-scan END value over the whole
    segment (per-row segment totals, the window-frame ROWS UNBOUNDED case).

    Scatter-free: a reverse copy-scan seeded at segment ends."""
    ends = seg_ends(boundary, live)
    seeded = jnp.where(ends, scanned, jnp.zeros_like(scanned))

    # reverse scan: the seed (segment end, scan-direction start) must win —
    # seg_scan's combine keeps op(v1, v2) for non-boundary rows, so the op
    # propagates the accumulated (end-row) value v1 over the current row
    def keep_acc(v1, v2):
        return v1

    return seg_scan(keep_acc, seeded, ends, reverse=True)


def compact_to_slots(is_wanted, cap_out: int):
    """Positions of the wanted rows, compacted to the front in row order.

    Returns idx[cap_out] (int32 row positions; garbage past the wanted
    count — callers mask by their own num_groups). One lax.sort replaces a
    full-tile scatter: stable sort by (~is_wanted) moves wanted rows first
    while preserving order.
    """
    cap = is_wanted.shape[0]
    perm = jnp.arange(cap, dtype=jnp.int32)
    _, order = jax.lax.sort(
        [(~is_wanted).astype(jnp.uint8), perm], num_keys=2
    )
    if cap_out <= cap:
        return order[:cap_out]
    return jnp.concatenate(
        [order, jnp.zeros((cap_out - cap,), jnp.int32)]
    )
