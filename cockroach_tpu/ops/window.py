"""Window function kernels — the colexecwindow analog.

Reference: pkg/sql/colexec/colexecwindow implements rank/row_number/lead/lag
and aggregates-as-window over partitioned, ordered buffers (one generated
variant per frame/type). The TPU redesign runs every window function as a
segmented scan over ONE sorted tile:

- sort by (partition keys, order keys) — XLA lane-parallel sort;
- partition boundaries -> segment ids (same trick as the MVCC scan filter);
- row_number / rank / dense_rank = position arithmetic over boundaries;
- running (unbounded-preceding..current-row) aggregates = cumsum minus the
  segment's prefix; whole-partition aggregates = segment_sum gathered back;
- lead/lag = shifted gathers with partition-edge NULLs.

NULL ordering and peer semantics follow SQL: ORDER BY peers (ties) share
rank; rank counts peers, dense_rank doesn't skip.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import FLOAT64, INT64, Family, Schema, SQLType
from . import sort as sort_ops

WINDOW_FUNCS = (
    "row_number", "rank", "dense_rank", "lag", "lead",
    "sum", "count", "min", "max", "avg", "first_value", "last_value",
    "ntile", "percent_rank", "cume_dist",
)


@dataclass(frozen=True)
class WindowSpec:
    """One window function: func over column `col` (None for rank family),
    `offset` for lead/lag, `running` selects the cumulative frame
    (rows unbounded preceding..current row) vs whole-partition.

    `frame` is the general ROWS BETWEEN spec as (preceding, following)
    row counts, None in either slot meaning UNBOUNDED — e.g. (2, 0) is
    ROWS BETWEEN 2 PRECEDING AND CURRENT ROW, (None, 0) equals
    running=True, (1, 1) a centered 3-row window. Applies to
    sum/count/avg/min/max/first_value/last_value.

    frame_kind='range' reads the same (preceding, following) tuple as
    ORDER-BY-VALUE offsets (RANGE BETWEEN x PRECEDING AND y FOLLOWING):
    the frame holds every row whose single numeric order key lies within
    the offset window of the current row's value — offset 0 is exactly
    CURRENT ROW's peer-inclusive semantics.

    frame_kind='groups' counts whole PEER GROUPS instead (GROUPS BETWEEN
    n PRECEDING AND m FOLLOWING): the frame spans from the n-th peer
    group before the current row's group to the m-th after, any order-key
    shape (peer ids are integers, so the same binary-search machinery
    answers it exactly)."""

    func: str
    col: int | None = None
    name: str | None = None
    offset: int = 1
    running: bool = False
    frame: tuple | None = None
    frame_kind: str = "rows"
    # SQL EXCLUDE clause: "no_others" | "current" | "group" | "ties"
    exclude: str = "no_others"


def window_output_type(spec: WindowSpec, schema: Schema) -> SQLType:
    if spec.func in ("row_number", "rank", "dense_rank", "count", "ntile"):
        return INT64
    if spec.func in ("avg", "percent_rank", "cume_dist"):
        return FLOAT64
    return schema.types[spec.col]


def _partition_segments(batch: Batch, schema: Schema, part_cols, rank_tables):
    """Segment id per row from partition-key change boundaries; requires the
    batch sorted by partition keys (dead rows last)."""
    cap = batch.capacity
    if not part_cols:
        return jnp.zeros((cap,), jnp.int32)
    same = batch.mask[1:] & batch.mask[:-1]
    for c in part_cols:
        col = batch.cols[c]
        if col.data.ndim == 2:
            eqd = jnp.all(col.data[1:] == col.data[:-1], axis=-1)
        else:
            eqd = col.data[1:] == col.data[:-1]
        # equal non-NULLs, or both NULL (NULLs are peers in PARTITION BY)
        eq = (eqd & col.valid[1:] & col.valid[:-1]) | (
            ~col.valid[1:] & ~col.valid[:-1]
        )
        same = same & eq
    boundary = jnp.concatenate([jnp.ones((1,), jnp.bool_), ~same])
    return jnp.cumsum(boundary.astype(jnp.int32)) - 1


def _order_peers(batch: Batch, schema: Schema, order_keys, rank_tables, seg):
    """Boundary where (segment, order keys) change — peers share ranks."""
    cap = batch.capacity
    if not order_keys:
        return jnp.ones((cap,), jnp.bool_)
    same = seg[1:] == seg[:-1]
    for k in order_keys:
        col = batch.cols[k.col]
        if col.data.ndim == 2:  # BYTES: rows are equal iff all lanes equal
            eqd = jnp.all(col.data[1:] == col.data[:-1], axis=-1)
        else:
            eqd = col.data[1:] == col.data[:-1]
        eq = eqd | (~col.valid[1:] & ~col.valid[:-1])
        same = same & eq & (col.valid[1:] == col.valid[:-1])
    return jnp.concatenate([jnp.ones((1,), jnp.bool_), ~same])


def compute_windows(
    batch: Batch,
    schema: Schema,
    part_cols: tuple[int, ...],
    order_keys: tuple[sort_ops.SortKey, ...],
    specs: tuple[WindowSpec, ...],
    rank_tables=None,
) -> Batch:
    """Sort by (partition, order) and append one column per WindowSpec."""
    rank_tables = rank_tables or {}
    sort_keys = tuple(
        sort_ops.SortKey(c) for c in part_cols
    ) + tuple(order_keys)
    b = sort_ops.sort_batch(batch, schema, sort_keys, rank_tables)
    cap = b.capacity
    pos = jnp.arange(cap, dtype=jnp.int32)

    seg = _partition_segments(b, schema, part_cols, rank_tables)
    seg_start = jax.ops.segment_min(
        jnp.where(b.mask, pos, cap), seg, num_segments=cap
    )  # first row position of each segment
    start_of = seg_start[seg]  # per-row segment start position
    peer_boundary = _order_peers(b, schema, order_keys, rank_tables, seg)

    seg_end = jax.ops.segment_max(
        jnp.where(b.mask, pos, -1), seg, num_segments=cap
    )[seg]  # per-row last live position of the segment

    new_cols = list(b.cols)
    for spec in specs:
        out_t = window_output_type(spec, schema)
        if spec.frame is not None and spec.func in (
            "sum", "count", "avg", "min", "max", "first_value",
            "last_value",
        ):
            if (spec.frame_kind == "range"
                    and any(x not in (None, 0) for x in spec.frame)):
                # offset RANGE frames need one numeric key (Postgres
                # rule); peer-only frames (UNBOUNDED/CURRENT ROW) work
                # positionally for any order-key shape
                if len(order_keys) != 1:
                    raise ValueError(
                        "RANGE frames with offsets require exactly one "
                        "ORDER BY key (Postgres rule)"
                    )
                fam = schema.types[order_keys[0].col].family
                if fam not in (Family.INT, Family.FLOAT, Family.DECIMAL,
                               Family.DATE):
                    raise ValueError(
                        f"RANGE frame offsets need a numeric order key, "
                        f"got {fam}"
                    )
            d, v = _framed_window(b, schema, spec, seg, start_of, seg_end,
                                  pos, rank_tables, order_keys=order_keys,
                                  peer_boundary=peer_boundary)
            new_cols.append(Column(data=d, valid=v & b.mask))
            continue
        if spec.func == "row_number":
            d = (pos - start_of + 1).astype(jnp.int64)
            v = b.mask
        elif spec.func in ("rank", "dense_rank"):
            # rank: position of the peer-group head within the partition
            head_pos = jnp.where(peer_boundary, pos, 0)
            head = jax.lax.associative_scan(jnp.maximum, head_pos)
            if spec.func == "rank":
                d = (head - start_of + 1).astype(jnp.int64)
            else:
                # dense: count of peer boundaries in the partition so far
                pb = jnp.cumsum(peer_boundary.astype(jnp.int64))
                d = pb - pb[start_of] + 1
            v = b.mask
        elif spec.func in ("ntile", "percent_rank", "cume_dist"):
            n = jax.ops.segment_sum(
                b.mask.astype(jnp.int64), seg, num_segments=cap
            )[seg]  # partition row count, per row
            idx = (pos - start_of).astype(jnp.int64)  # 0-based in partition
            if spec.func == "ntile":
                # SQL ntile(k): first (n mod k) buckets get one extra row
                k = jnp.int64(max(1, spec.offset))
                q = n // k
                r = n % k
                big = r * (q + 1)  # rows covered by the larger buckets
                d = jnp.where(
                    q == 0,
                    idx + 1,
                    jnp.where(idx < big, idx // jnp.maximum(q + 1, 1) + 1,
                              r + (idx - big) // jnp.maximum(q, 1) + 1),
                )
                v = b.mask
            else:
                head_pos = jnp.where(peer_boundary, pos, 0)
                head = jax.lax.associative_scan(jnp.maximum, head_pos)
                rank = (head - start_of + 1).astype(jnp.float64)
                if spec.func == "percent_rank":
                    denom = jnp.maximum(n - 1, 1).astype(jnp.float64)
                    d = jnp.where(n > 1, (rank - 1.0) / denom, 0.0)
                else:  # cume_dist = rows <= my peer group / partition rows
                    peer_id = jnp.cumsum(peer_boundary.astype(jnp.int32)) - 1
                    peer_last = jax.ops.segment_max(
                        jnp.where(b.mask, pos, -1), peer_id,
                        num_segments=cap,
                    )[peer_id]
                    d = ((peer_last - start_of + 1).astype(jnp.float64)
                         / jnp.maximum(n, 1).astype(jnp.float64))
                v = b.mask
        elif spec.func in ("lag", "lead"):
            col = b.cols[spec.col]
            off = spec.offset if spec.func == "lag" else -spec.offset
            src = pos - off
            inb = (src >= 0) & (src < cap)
            srcc = jnp.clip(src, 0, cap - 1)
            same_seg = inb & (seg[srcc] == seg)
            d = jnp.where(same_seg, col.data[srcc], 0).astype(col.data.dtype)
            v = same_seg & col.valid[srcc] & b.mask
        elif spec.func == "count" and spec.col is None:
            # count(*) over the frame
            vals = b.mask.astype(jnp.int64)
            c = jnp.cumsum(vals)
            if spec.running:
                run = c - jnp.where(start_of > 0, c[start_of - 1], 0)
            else:
                run = jax.ops.segment_sum(vals, seg, num_segments=cap)[seg]
            d, v = run.astype(jnp.int64), b.mask
        elif spec.func in ("sum", "count", "min", "max", "avg",
                           "first_value", "last_value"):
            col = b.cols[spec.col]
            t = schema.types[spec.col]
            m = b.mask & col.valid
            if spec.func == "count":
                vals = m.astype(jnp.int64)
            elif spec.func == "avg" or t.family is Family.FLOAT:
                vals = jnp.where(m, col.data.astype(jnp.float64), 0.0)
            else:
                vals = jnp.where(m, col.data.astype(jnp.int64), 0)
            if spec.func in ("sum", "count", "avg"):
                c = jnp.cumsum(vals)
                if spec.running:
                    run = c - jnp.where(start_of > 0, c[start_of - 1], 0)
                else:
                    seg_tot = jax.ops.segment_sum(vals, seg, num_segments=cap)
                    run = seg_tot[seg]
                if spec.func == "count":
                    d, v = run.astype(jnp.int64), b.mask
                elif spec.func == "avg":
                    cm = jnp.cumsum(m.astype(jnp.int64))
                    if spec.running:
                        n = cm - jnp.where(start_of > 0, cm[start_of - 1], 0)
                    else:
                        n = jax.ops.segment_sum(
                            m.astype(jnp.int64), seg, num_segments=cap)[seg]
                    d = run.astype(jnp.float64) / jnp.where(n > 0, n, 1)
                    if t.family is Family.DECIMAL:
                        d = d / (10.0**t.scale)
                    v = b.mask & (n > 0)
                else:
                    d = run.astype(out_t.dtype)
                    if t.family is Family.FLOAT:
                        d = run
                    n = jax.ops.segment_sum(
                        m.astype(jnp.int64), seg, num_segments=cap)[seg]
                    v = b.mask & (n > 0)
            elif spec.func in ("min", "max"):
                from .aggregation import _minmax_sentinel

                is_min = spec.func == "min"
                data = col.data
                inv_rank = None
                if t.family is Family.STRING:
                    # reduce byte-order ranks, not insertion-order codes
                    table = jnp.asarray(rank_tables[spec.col])
                    data = table[jnp.clip(col.data, 0, table.shape[0] - 1)]
                    inv = np.empty(len(rank_tables[spec.col]), dtype=np.int32)
                    # crlint: allow-host-sync(rank tables are host numpy)
                    inv[np.asarray(rank_tables[spec.col])] = np.arange(
                        len(inv), dtype=np.int32
                    )
                    inv_rank = jnp.asarray(inv)
                sent = _minmax_sentinel(data.dtype, is_min)
                vv = jnp.where(m, data, sent)
                if spec.running:
                    # segmented cumulative min/max: boundary-resetting scan
                    op = jnp.minimum if is_min else jnp.maximum
                    boundary = jnp.concatenate(
                        [jnp.ones((1,), jnp.bool_), seg[1:] != seg[:-1]]
                    )

                    def comb(a, bb):
                        af, av = a
                        bf, bv = bb
                        return bf | af, jnp.where(bf, bv, op(av, bv))

                    _, red_run = jax.lax.associative_scan(
                        comb, (boundary, vv)
                    )
                    red_rows = red_run
                    n = jnp.cumsum(m.astype(jnp.int64))
                    nb = n - jnp.where(start_of > 0, n[start_of - 1], 0)
                else:
                    red = (jax.ops.segment_min if is_min
                           else jax.ops.segment_max)(vv, seg, num_segments=cap)
                    red_rows = red[seg]
                    nb = jax.ops.segment_sum(
                        m.astype(jnp.int64), seg, num_segments=cap)[seg]
                if inv_rank is not None:
                    red_rows = inv_rank[
                        jnp.clip(red_rows, 0, inv_rank.shape[0] - 1)
                    ]
                d = red_rows.astype(col.data.dtype)
                v = b.mask & (nb > 0)
            else:  # first_value / last_value over the partition or frame
                last = spec.func == "last_value"
                if spec.running and last:
                    # running last_value is the current row
                    d, v = col.data, b.mask & col.valid
                else:
                    # running first_value == partition first_value
                    cand = jnp.where(b.mask, pos, -1 if last else cap)
                    idx = (jax.ops.segment_max if last
                           else jax.ops.segment_min)(cand, seg,
                                                     num_segments=cap)
                    srcc = jnp.clip(idx[seg], 0, cap - 1)
                    d = col.data[srcc]
                    v = b.mask & col.valid[srcc]
        else:
            raise ValueError(f"unknown window function {spec.func}")
        new_cols.append(Column(data=d, valid=v & b.mask))
    return Batch(cols=tuple(new_cols), mask=b.mask)


def _rmq_levels(vals: jax.Array, op) -> jax.Array:
    """Sparse table for range min/max queries: T[k, i] = reduce over
    [i, i + 2^k) (out-of-range tail padded by repetition). log2(cap)
    levels, each one fused elementwise pass — the TPU-shaped answer to
    sliding-window min/max, where prefix sums don't apply."""
    cap = vals.shape[0]
    levels = [vals]
    k = 1
    while k < cap:
        prev = levels[-1]
        shifted = jnp.concatenate([prev[k:], prev[-1:].repeat(min(k, cap))])
        shifted = shifted[:cap]
        levels.append(op(prev, shifted))
        k *= 2
    return jnp.stack(levels)  # [K, cap]


def _rmq_query(table: jax.Array, op, lo: jax.Array, hi: jax.Array):
    """Per-row reduce over [lo, hi] (inclusive), widths data-dependent:
    pick level j = floor(log2(w)) via comparisons, then combine the two
    overlapping 2^j blocks."""
    K, cap = table.shape
    w = jnp.maximum(hi - lo + 1, 1)
    j = jnp.zeros(w.shape, jnp.int32)
    for k in range(1, K):
        j = jnp.where(w >= (1 << k), k, j)
    blk = (jnp.int32(1) << j)
    flat = table.reshape(-1)
    a = flat[j * cap + jnp.clip(lo, 0, cap - 1)]
    c = flat[j * cap + jnp.clip(hi - blk + 1, 0, cap - 1)]
    return op(a, c)


def _lower_bound(u, q, lo0, hi0, strict: bool = False):
    """Per-row binary search: smallest idx in [lo0, hi0] with u[idx] >= q
    (u[idx] > q when strict; hi0+1 when none) — vectorized, log2(cap)
    gather steps. The strict flag exists because the nextafter(q) trick
    dies on XLA:CPU's denormal flush (nextafter(0.0) -> 5e-324 -> 0.0)."""
    n = u.shape[0]
    lo = lo0.astype(jnp.int64)
    hi = hi0.astype(jnp.int64) + 1
    for _ in range(max(1, int(np.ceil(np.log2(max(n, 2)))) + 1)):
        active = lo < hi
        mid = (lo + hi) // 2
        um = u[jnp.clip(mid, 0, n - 1)]
        go_left = (um > q) if strict else (um >= q)
        hi = jnp.where(active & go_left, mid, hi)
        lo = jnp.where(active & ~go_left, mid + 1, lo)
    return lo


def _peer_run(peer_boundary, pos, mask, cap, order_keys,
              start_of, seg_end):
    """Per-row [first, last] position of the current row's PEER run.
    Without ORDER BY every partition row is a peer (SQL rule), so the
    run is the whole partition."""
    if not order_keys:
        return start_of, seg_end
    peer_id = jnp.cumsum(jnp.asarray(peer_boundary).astype(jnp.int32)) - 1
    ps = jax.ops.segment_min(
        jnp.where(mask, pos, cap), peer_id, num_segments=cap
    )[peer_id]
    pe = jax.ops.segment_max(
        jnp.where(mask, pos, -1), peer_id, num_segments=cap
    )[peer_id]
    return ps, pe


def _seg_run(pos, seg, member, cap, start_fallback, end_fallback):
    """Per-row [first, last] position of the rows where `member` holds,
    within the row's segment (fallbacks when the segment has none)."""
    first = jax.ops.segment_min(
        jnp.where(member, pos, cap), seg, num_segments=cap
    )[seg]
    last = jax.ops.segment_max(
        jnp.where(member, pos, -1), seg, num_segments=cap
    )[seg]
    return (jnp.where(first == cap, start_fallback, first),
            jnp.where(last == -1, end_fallback, last))


def _range_bounds(b: Batch, schema: Schema, spec: WindowSpec, order_keys,
                  seg, pos, start_of, seg_end):
    """Per-row RANGE frame bounds over the single numeric order key.

    Finite keys binary-search the SORT-transformed space u = sign*value
    (one monotone window [u_i - pre, u_i + fol] expresses ASC and DESC
    alike), searching only the segment's finite run. NULL rows — and,
    for floats, non-finite peer groups (NaN, +/-inf) — take their frames
    POSITIONALLY from their contiguous peer run instead, which keeps
    them exact with no sentinel arithmetic (a valid -inf key can never
    collide with a NULL encoding, and NaN frames are their peers, not
    empty). INT/DECIMAL/DATE keys search in exact int64; only FLOAT keys
    use float64 (documented: int keys are exact at any magnitude)."""
    cap = b.capacity
    k = order_keys[0]
    oc = b.cols[k.col]
    t = schema.types[k.col]
    valid = oc.valid & b.mask
    p, f = spec.frame

    if t.family is Family.FLOAT:
        data = oc.data.astype(jnp.float64)
        finite = valid & jnp.isfinite(data)
        sign = -1.0 if k.desc else 1.0
        u = sign * data
        pre = None if p is None else float(p)
        fol = None if f is None else float(f)
    else:
        scale = 10 ** t.scale if t.family is Family.DECIMAL else 1
        data = oc.data.astype(jnp.int64)
        finite = valid
        sign = -1 if k.desc else 1
        u = sign * data
        pre = None if p is None else int(round(float(p) * scale))
        fol = None if f is None else int(round(float(f) * scale))

    # masked-out positions must never satisfy a comparison: park them at
    # the far end of the search space (searches are bounded to the finite
    # run anyway; this only guards the clipped gathers)
    fin_start, fin_end = _seg_run(pos, seg, finite, cap, start_of, seg_end)
    u = jnp.where(finite, u, jnp.asarray(np.inf if u.dtype == jnp.float64
                                         else np.iinfo(np.int64).max,
                                         u.dtype))

    lo = start_of if pre is None else _lower_bound(
        u, u - pre, fin_start, fin_end
    )
    if fol is None:
        hi = seg_end
    else:
        # last idx with u <= q == (first idx with u > q) - 1
        first_gt = _lower_bound(u, u + fol, fin_start, fin_end,
                                strict=True)
        hi = first_gt - 1

    # non-finite peer groups (NULLs always; NaN/±inf for floats) frame to
    # their own contiguous run — unbounded ends still reach the partition
    # edge (Postgres: such rows are peers; offsets don't move their frame)
    def run_frame(member):
        r_start, r_end = _seg_run(pos, seg, member, cap, start_of, seg_end)
        rlo = start_of if p is None else r_start
        rhi = seg_end if f is None else r_end
        return rlo, rhi

    is_null = b.mask & ~oc.valid
    nlo, nhi = run_frame(is_null)
    lo = jnp.where(is_null, nlo, lo)
    hi = jnp.where(is_null, nhi, hi)
    if t.family is Family.FLOAT:
        fd = oc.data.astype(jnp.float64)
        for member in (valid & jnp.isnan(fd),
                       valid & jnp.isposinf(fd),
                       valid & jnp.isneginf(fd)):
            mlo, mhi = run_frame(member)
            lo = jnp.where(member, mlo, lo)
            hi = jnp.where(member, mhi, hi)
    return lo.astype(start_of.dtype), hi.astype(seg_end.dtype)


def _framed_window(b: Batch, schema: Schema, spec: WindowSpec, seg,
                   start_of, seg_end, pos, rank_tables, order_keys=(),
                   peer_boundary=None):
    """General ROWS/RANGE BETWEEN frame for the aggregate window
    functions: per-row frame bounds clamp to the partition; sums/counts/
    avgs answer by prefix-sum difference, min/max by RMQ sparse table,
    first/last by a gather at the frame edge."""
    p, f = spec.frame
    if spec.frame_kind == "groups":
        # GROUPS frames: the peer-id sequence is nondecreasing across the
        # whole sorted batch, so group-offset bounds are integer binary
        # searches over it, clamped to the segment
        peer_id = jnp.cumsum(
            jnp.asarray(peer_boundary).astype(jnp.int64)
        ) - 1
        lo = start_of if p is None else _lower_bound(
            peer_id, peer_id - int(p), start_of, seg_end
        )
        if f is None:
            hi = seg_end
        else:
            first_gt = _lower_bound(peer_id, peer_id + int(f),
                                    start_of, seg_end, strict=True)
            hi = first_gt - 1
    elif spec.frame_kind == "range":
        if all(x in (None, 0) for x in spec.frame):
            # peer-only frame (the SQL default shape): bounds are the
            # current row's peer run — positional, any order-key type
            ps, pe = _peer_run(peer_boundary, pos, b.mask, b.capacity,
                               order_keys, start_of, seg_end)
            lo = start_of if p is None else ps
            hi = seg_end if f is None else pe
        else:
            lo, hi = _range_bounds(b, schema, spec, order_keys, seg, pos,
                                   start_of, seg_end)
    else:
        lo = start_of if p is None else jnp.maximum(start_of, pos - int(p))
        hi = seg_end if f is None else jnp.minimum(seg_end, pos + int(f))
    cap = b.capacity
    empty = hi < lo  # e.g. 2 FOLLOWING AND 3 FOLLOWING past the edge

    # frame EXCLUSION (SQL's EXCLUDE clause): a contiguous sub-range of
    # the frame — CURRENT ROW is [pos, pos], GROUP/TIES the current peer
    # run; TIES adds the current row itself back. Aggregates subtract the
    # excluded span from the prefix-difference answers (min/max query the
    # two surviving sub-ranges)
    excl = getattr(spec, "exclude", "no_others")
    keep_cur = None
    if excl != "no_others":
        if excl == "current":
            ex_lo, ex_hi = pos, pos
        else:
            ex_lo, ex_hi = _peer_run(peer_boundary, pos, b.mask, cap,
                                     order_keys, start_of, seg_end)
        exc_lo = jnp.maximum(lo, ex_lo)
        exc_hi = jnp.minimum(hi, ex_hi)
        has_exc = (exc_lo <= exc_hi) & ~empty
        if excl == "ties":
            keep_cur = (lo <= pos) & (pos <= hi)  # current row survives
    else:
        has_exc = None

    def range_sum(c, lo_, hi_, present):
        l_ = jnp.clip(lo_, 0, cap - 1)
        h_ = jnp.clip(hi_, 0, cap - 1)
        s = c[h_] - jnp.where(l_ > 0, c[l_ - 1], 0)
        return jnp.where(present, s, 0)

    def framed_total(per_row_vals):
        """Sum of per_row_vals over the frame minus exclusions."""
        c = jnp.cumsum(per_row_vals)
        tot = range_sum(c, lo, hi, ~empty)
        if has_exc is not None:
            tot = tot - range_sum(c, exc_lo, exc_hi, has_exc)
            if keep_cur is not None:
                tot = tot + jnp.where(keep_cur, per_row_vals, 0)
        return tot

    if spec.func in ("first_value", "last_value"):
        if keep_cur is not None:
            raise ValueError(
                "EXCLUDE TIES with first_value/last_value is not "
                "supported (bind-time rule)"
            )
        lo_eff, hi_eff = lo, hi
        if has_exc is not None:
            # an edge inside the exclusion steps past it
            lo_eff = jnp.where(has_exc & (exc_lo == lo), exc_hi + 1, lo)
            hi_eff = jnp.where(has_exc & (exc_hi == hi), exc_lo - 1, hi)
        dead = empty | (hi_eff < lo_eff)
        col = b.cols[spec.col]
        edge = jnp.clip(
            lo_eff if spec.func == "first_value" else hi_eff, 0, cap - 1
        )
        return col.data[edge], col.valid[edge] & ~dead

    if spec.func == "count" and spec.col is None:
        d = framed_total(b.mask.astype(jnp.int64))
        return d, jnp.ones_like(b.mask)

    col = b.cols[spec.col]
    t = schema.types[spec.col]
    m = b.mask & col.valid
    wcnt = framed_total(m.astype(jnp.int64))
    if spec.func in ("sum", "count", "avg"):
        if spec.func == "count":
            return wcnt, jnp.ones_like(b.mask)
        if spec.func == "avg" or t.family is Family.FLOAT:
            vals = jnp.where(m, col.data.astype(jnp.float64), 0.0)
        else:
            vals = jnp.where(m, col.data.astype(jnp.int64), 0)
        wsum = framed_total(vals)
        if spec.func == "avg":
            d = wsum.astype(jnp.float64) / jnp.where(wcnt > 0, wcnt, 1)
            if t.family is Family.DECIMAL:
                d = d / (10.0**t.scale)
            return d, wcnt > 0
        out_t = window_output_type(spec, schema)
        return wsum.astype(out_t.dtype), wcnt > 0

    # min / max via RMQ
    from .aggregation import _minmax_sentinel

    is_min = spec.func == "min"
    data = col.data
    inv_rank = None
    if t.family is Family.STRING:
        table = jnp.asarray(rank_tables[spec.col])
        data = table[jnp.clip(col.data, 0, table.shape[0] - 1)]
        inv = np.empty(len(rank_tables[spec.col]), dtype=np.int32)
        # crlint: allow-host-sync(rank tables are host numpy)
        inv[np.asarray(rank_tables[spec.col])] = np.arange(
            len(inv), dtype=np.int32)
        inv_rank = jnp.asarray(inv)
    sent = _minmax_sentinel(data.dtype, is_min)
    vv = jnp.where(m, data, sent)
    op = jnp.minimum if is_min else jnp.maximum
    levels = _rmq_levels(vv, op)

    def rmq(lo_, hi_, present):
        r = _rmq_query(levels, op, jnp.clip(lo_, 0, cap - 1),
                       jnp.clip(hi_, 0, cap - 1))
        return jnp.where(present & (lo_ <= hi_), r, sent)

    if has_exc is None:
        red = rmq(lo, hi, ~empty)
    else:
        left = rmq(lo, exc_lo - 1, has_exc)
        right = rmq(exc_hi + 1, hi, has_exc)
        whole = rmq(lo, hi, ~empty & ~has_exc)
        red = op(op(left, right), whole)
        if keep_cur is not None:
            red = op(red, jnp.where(keep_cur & m, vv, sent))
    if inv_rank is not None:
        red = inv_rank[jnp.clip(red, 0, inv_rank.shape[0] - 1)]
    return red.astype(col.data.dtype), (wcnt > 0) & ~empty


def window_output_schema(
    schema: Schema, specs: tuple[WindowSpec, ...]
) -> Schema:
    names = list(schema.names)
    types = list(schema.types)
    for s in specs:
        names.append(s.name or s.func)
        types.append(window_output_type(s, schema))
    return Schema(tuple(names), tuple(types))
