"""Sort / top-k kernels — the colexec Sorter analog.

Reference: pkg/sql/colexec/sort.go:26 (NewSorter) spools all input then runs a
pdqsort per type (pdqsort.eg.go); sorttopk.go keeps a heap of K. On TPU both
become XLA's native sort over order-preserving uint64 key transforms:

- every key column maps to a uint64 whose unsigned order equals SQL order
  (ints: sign-flip bitcast; floats: IEEE total-order trick; strings: host-
  prepared dictionary rank gather — coldata.Dictionary.ranks);
- DESC inverts bits; NULL ordering is a leading bool key (CockroachDB sorts
  NULLs first ascending — tree.Datum ordering);
- dead rows sort last via a leading ~mask key, so output is also compacted.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import Family, Schema, SQLType


@dataclass(frozen=True)
class SortKey:
    col: int
    desc: bool = False
    # CockroachDB semantics: NULLs order first ascending, last descending.
    nulls_first: bool | None = None

    def effective_nulls_first(self) -> bool:
        return (not self.desc) if self.nulls_first is None else self.nulls_first


def order_keys(
    data: jax.Array,
    valid: jax.Array,
    k: "SortKey",
    t: SQLType,
    rank_table: np.ndarray | None = None,
) -> list[jax.Array]:
    """Sort-key operands whose ascending order equals SQL order for this key.

    TPU note: the X64 rewriter cannot bitcast f64<->u64, so floats sort as
    native float keys (with an explicit NaN flag — CockroachDB orders NaN
    before all other values) instead of the classic IEEE bit-trick. Integer
    families use sign-flipped uint64; DESC inverts bits / negates.
    """
    nf = k.effective_nulls_first()
    null_key = valid if nf else ~valid  # False sorts first
    if t.family is Family.STRING:
        assert rank_table is not None, "STRING sort needs a dictionary rank table"
        table = jnp.asarray(rank_table)
        codes = jnp.clip(data, 0, table.shape[0] - 1)
        u = table[codes].astype(jnp.int32)
        return [null_key, -u if k.desc else u]
    if t.family is Family.FLOAT:
        d = data.astype(jnp.float64)
        isnan = jnp.isnan(d)
        nan_key = isnan if k.desc else ~isnan  # NaN smallest in SQL order
        d = jnp.where(isnan, 0.0, d)
        return [null_key, nan_key, -d if k.desc else d]
    if t.family is Family.BOOL:
        key = data
        return [null_key, key != k.desc]
    if t.family is Family.BYTES:
        # lexicographic byte order == unsigned order of big-endian-packed
        # uint64 words (zero padding ranks shorter strings first, matching
        # the engine's zero-padded fixed-width representation)
        from ..coldata.batch import pack_be_words

        words = pack_be_words(data)
        return [null_key] + [
            ~words[:, i] if k.desc else words[:, i]
            for i in range(words.shape[1])
        ]
    u = data.astype(jnp.int64).astype(jnp.uint64) ^ np.uint64(0x8000000000000000)
    if k.desc:
        u = ~u
    return [null_key, u]


def pack_sort_operands(
    batch: Batch,
    schema: Schema,
    keys: tuple[SortKey, ...],
    rank_tables: dict[int, np.ndarray] | None = None,
    col_stats: dict[int, tuple] | None = None,
    include_mask: bool = True,
) -> list[jax.Array]:
    """Bit-packed sort operands for the key list (see ops/keys.py): dead rows
    last (leading ~mask bit), then per-key [null flag, value] segments packed
    into as few uint64 words as possible; float keys ride as native f64."""
    from . import keys as key_ops

    rank_tables = rank_tables or {}
    col_stats = col_stats or {}
    segs: list = []
    if include_mask:
        segs.append(key_ops.BitSeg(1, (~batch.mask).astype(jnp.uint64)))
    for k in keys:
        c = batch.cols[k.col]
        t = schema.types[k.col]
        segs.extend(key_ops.key_segments(
            c.data, c.valid, t, k.desc, k.effective_nulls_first(),
            rank_table=rank_tables.get(k.col),
            stats=col_stats.get(k.col),
        ))
    return key_ops.pack_operands(segs)


def sort_perm(  # crlint: allow-mem-accounting(traced kernel: permutation lanes shaped like the charged input tile)
    batch: Batch,
    schema: Schema,
    keys: tuple[SortKey, ...],
    rank_tables: dict[int, np.ndarray] | None = None,
    col_stats: dict[int, tuple] | None = None,
) -> jax.Array:
    """Stable permutation ordering live rows by keys, dead rows last.

    Stability comes from the row index participating as the FINAL sort key
    (equal-key rows order by original position) — measurably cheaper to
    compile on TPU than is_stable=True with the index as payload."""
    cap = batch.capacity
    operands = pack_sort_operands(batch, schema, keys, rank_tables, col_stats)
    perm = jnp.arange(cap, dtype=jnp.int32)
    res = jax.lax.sort(operands + [perm], num_keys=len(operands) + 1)
    return res[-1]


def apply_perm(batch: Batch, perm: jax.Array) -> Batch:
    cols = tuple(
        Column(data=c.data[perm], valid=c.valid[perm]) for c in batch.cols
    )
    return Batch(cols=cols, mask=batch.mask[perm])


def sort_batch(
    batch: Batch,
    schema: Schema,
    keys: tuple[SortKey, ...],
    rank_tables: dict[int, np.ndarray] | None = None,
    col_stats: dict[int, tuple] | None = None,
) -> Batch:
    return apply_perm(
        batch, sort_perm(batch, schema, keys, rank_tables, col_stats)
    )


def topk_batch(  # crlint: allow-mem-accounting(traced kernel: k-selection transients shaped like the charged input tile)
    batch: Batch,
    schema: Schema,
    keys: tuple[SortKey, ...],
    k: int,
    capacity: int,
    rank_tables: dict[int, np.ndarray] | None = None,
    col_stats: dict[int, tuple] | None = None,
) -> Batch:
    """Stable k-selection: the first ``k`` live rows of the stable sort
    order, re-materialized at static ``capacity`` (>= k). Equal keys at
    the k boundary resolve by original row position — exactly the rows a
    full sort + LIMIT k keeps — so folding per-tile selections through
    concat (earlier tiles first) stays bit-identical with the full-sort
    oracle. Output is sorted and compacted (dead rows masked off)."""
    perm = sort_perm(batch, schema, keys, rank_tables, col_stats)
    idx = jnp.arange(capacity, dtype=jnp.int32)
    take = perm[jnp.minimum(idx, batch.capacity - 1)]
    out = apply_perm(batch, take)
    keep = out.mask & (idx < batch.capacity) & (idx < k)
    return out.with_mask(keep)


def limit_mask(batch: Batch, limit: int, offset: int = 0) -> Batch:
    """LIMIT/OFFSET over live rows in tile order (apply after sort_batch,
    whose output is compacted). Reference: colexec limit/offset ops."""
    pos = jnp.cumsum(batch.mask.astype(jnp.int32)) - 1  # rank among live rows
    keep = batch.mask & (pos >= offset) & (pos < offset + limit)
    return batch.with_mask(keep)
