"""Merge join — the colexecjoin mergejoiner analog.

Reference: pkg/sql/colexec/colexecjoin/mergejoiner.go streams two inputs
sorted on the join key, advancing two cursors (per-join-type generated
variants). On TPU the cursor walk becomes vectorized binary search over
order-preserving uint64 key lanes (sort_ops.order_keys): with EXACT keys
(not hashes) there are no collisions, so each probe row's match run is just
[searchsorted left, searchsorted right) in the build tile — no advance loop
at all. Duplicate handling reuses the count+emit pattern of the hash join.

Single-key joins only (the composite-key case routes to the hash join; the
reference's merge joiner is likewise used when the plan's interesting order
covers the join key).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import Schema
from .join import JoinSpec

_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


def _u64_key(batch: Batch, key: int, schema: Schema, rank_table=None):
    """Order-preserving uint64 of one key column; NULL/dead -> sentinel
    (never matches, matching SQL NULL != NULL)."""
    from ..coldata.types import Family

    c = batch.cols[key]
    t = schema.types[key]
    if t.family is Family.STRING:
        assert rank_table is not None, "STRING merge join needs a rank table"
        table = jnp.asarray(rank_table)
        codes = jnp.clip(c.data, 0, table.shape[0] - 1)
        payload = table[codes].astype(jnp.int64).astype(jnp.uint64) ^ np.uint64(
            1 << 63
        )
    elif t.family is Family.FLOAT:
        # IEEE total-order trick composed from 32-bit lanes. Canonical
        # -0.0 == 0.0 and NaN == NaN (Postgres float equality semantics).
        # Guarded: the axon rewriter miscompiles these for negatives.
        from ..utils.backend import require_float_bitcast

        require_float_bitcast("float merge-join key")
        f = c.data.astype(jnp.float64)
        f = jnp.where(f == 0.0, 0.0, f)
        f = jnp.where(jnp.isnan(f), jnp.float64(jnp.nan), f)
        parts = jax.lax.bitcast_convert_type(f, jnp.uint32)  # [..., 2]
        u = (parts[..., 1].astype(jnp.uint64) << np.uint64(32)) | parts[
            ..., 0
        ].astype(jnp.uint64)
        neg = (u >> np.uint64(63)) != 0
        payload = jnp.where(neg, ~u, u | np.uint64(1 << 63))
    elif t.family is Family.BOOL:
        payload = c.data.astype(jnp.uint64)
    else:
        payload = c.data.astype(jnp.int64).astype(jnp.uint64) ^ np.uint64(
            1 << 63
        )
    active = batch.mask & c.valid
    return jnp.where(active, payload, _SENTINEL), active


def merge_join(
    probe: Batch,
    probe_schema: Schema,
    probe_key: int,
    build: Batch,
    build_schema: Schema,
    build_key: int,
    spec: JoinSpec,
    out_capacity: int,
    probe_rank_table=None,
    build_rank_table=None,
    build_index=None,
):
    """Returns (out_batch, total_rows); retry with a bigger tile if
    total_rows > out_capacity (same capacity-bucketing contract as
    hash_join_general). `build_index` caches the build-side sorted keys."""
    cap = probe.capacity
    bcap = build.capacity
    if build_index is None:
        build_index = build_merge_index(
            build, build_schema, build_key, build_rank_table
        )
    sk, order, prefix = build_index
    pk, p_active = _u64_key(probe, probe_key, probe_schema, probe_rank_table)

    lo = jnp.searchsorted(sk, pk, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sk, pk, side="right").astype(jnp.int32)
    # count only ACTIVE build rows in the run (dead/NULL rows share the key
    # lanes of inactive rows and sort to the run's tail)
    cnt = jnp.where(p_active, prefix[hi] - prefix[lo], 0)
    max_run = jnp.max(cnt)

    if spec.join_type == "semi":
        return probe.with_mask(probe.mask & (cnt > 0)), jnp.sum(cnt > 0)
    if spec.join_type == "anti":
        return probe.with_mask(probe.mask & (cnt == 0)), jnp.sum(cnt == 0)

    left = spec.join_type == "left"
    out_rows = jnp.where(left & probe.mask, jnp.maximum(cnt, 1), cnt)
    base = jnp.cumsum(out_rows) - out_rows
    total = jnp.sum(out_rows)

    OC = out_capacity
    out_pidx = jnp.zeros((OC,), jnp.int32)
    out_bidx = jnp.zeros((OC,), jnp.int32)
    out_found = jnp.zeros((OC,), jnp.bool_)
    out_live = jnp.zeros((OC,), jnp.bool_)
    if left:
        unmatched = probe.mask & (cnt == 0)
        dest0 = jnp.where(unmatched, base.astype(jnp.int32), OC)
        out_pidx = out_pidx.at[dest0].set(
            jnp.arange(cap, dtype=jnp.int32), mode="drop")
        out_live = out_live.at[dest0].set(True, mode="drop")

    def emit_body(state):
        k, op, ob, of, ol = state
        m = k < cnt
        posc = jnp.clip(lo + k, 0, bcap - 1)
        bidx = order[posc]
        dest = jnp.where(m, (base + k).astype(jnp.int32), OC)
        op = op.at[dest].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
        ob = ob.at[dest].set(bidx, mode="drop")
        of = of.at[dest].set(True, mode="drop")
        ol = ol.at[dest].set(True, mode="drop")
        return k + 1, op, ob, of, ol

    _, out_pidx, out_bidx, out_found, out_live = jax.lax.while_loop(
        lambda s: s[0] < max_run,
        emit_body,
        (jnp.int32(0), out_pidx, out_bidx, out_found, out_live),
    )

    pcols = tuple(
        Column(data=c.data[out_pidx], valid=c.valid[out_pidx] & out_live)
        for c in probe.cols
    )
    bcols = tuple(
        Column(data=c.data[out_bidx], valid=c.valid[out_bidx] & out_found)
        for c in build.cols
    )
    return Batch(cols=pcols + bcols, mask=out_live), total


def build_merge_index(build: Batch, schema: Schema, key: int, rank_table=None):
    """Sort build rows by exact key order -> (sorted_keys, orig_index,
    active_prefix). Inactive (dead/NULL-key) rows sort AFTER actives within
    an equal-key run, and active_prefix[i] counts active rows before sorted
    position i — so a probe run [lo, hi) has its active matches contiguous
    at [lo, lo + prefix[hi] - prefix[lo])."""
    bk, active = _u64_key(build, key, schema, rank_table)
    perm = jnp.arange(build.capacity, dtype=jnp.int32)
    sk, _, order = jax.lax.sort([bk, ~active, perm], num_keys=2)
    sorted_active = active[order]
    prefix = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(sorted_active.astype(jnp.int32)),
    ])
    return sk, order, prefix
