"""Merge join — the colexecjoin mergejoiner analog.

Reference: pkg/sql/colexec/colexecjoin/mergejoiner.go streams two inputs
sorted on the join key, advancing two cursors (per-join-type generated
variants, composite ordered keys included). On TPU the cursor walk becomes
vectorized binary search over order-preserving uint64 key lanes
(sort_ops.order_keys): with EXACT keys (not hashes) there are no
collisions, so each probe row's match run is just [searchsorted left,
searchsorted right) in the build tile — no advance loop at all. Duplicate
handling reuses the count+emit pattern of the hash join.

Composite keys compare lexicographically: the build side sorts on all key
lanes at once (multi-operand lax.sort), and the probe's binary search
composes per-lane compares into one tuple compare per step (log2(n) steps
x ncols gathers — the generated mergejoiner's multi-column cursor compare,
vectorized).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import Schema
from .join import JoinSpec

_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


def _u64_key(batch: Batch, key: int, schema: Schema, rank_table=None):
    """Order-preserving uint64 of one key column; NULL/dead -> sentinel
    (never matches, matching SQL NULL != NULL)."""
    from ..coldata.types import Family

    c = batch.cols[key]
    t = schema.types[key]
    if t.family is Family.STRING:
        assert rank_table is not None, "STRING merge join needs a rank table"
        table = jnp.asarray(rank_table)
        codes = jnp.clip(c.data, 0, table.shape[0] - 1)
        payload = table[codes].astype(jnp.int64).astype(jnp.uint64) ^ np.uint64(
            1 << 63
        )
    elif t.family is Family.FLOAT:
        # IEEE total-order trick composed from 32-bit lanes. Canonical
        # -0.0 == 0.0 and NaN == NaN (Postgres float equality semantics).
        # Guarded: the axon rewriter miscompiles these for negatives.
        from ..utils.backend import require_float_bitcast

        require_float_bitcast("float merge-join key")
        f = c.data.astype(jnp.float64)
        f = jnp.where(f == 0.0, 0.0, f)
        f = jnp.where(jnp.isnan(f), jnp.float64(jnp.nan), f)
        parts = jax.lax.bitcast_convert_type(f, jnp.uint32)  # [..., 2]
        u = (parts[..., 1].astype(jnp.uint64) << np.uint64(32)) | parts[
            ..., 0
        ].astype(jnp.uint64)
        neg = (u >> np.uint64(63)) != 0
        payload = jnp.where(neg, ~u, u | np.uint64(1 << 63))
    elif t.family is Family.BOOL:
        payload = c.data.astype(jnp.uint64)
    else:
        payload = c.data.astype(jnp.int64).astype(jnp.uint64) ^ np.uint64(
            1 << 63
        )
    active = batch.mask & c.valid
    return jnp.where(active, payload, _SENTINEL), active


def _norm_keys(key) -> tuple[int, ...]:
    return (key,) if isinstance(key, int) else tuple(key)


def rank_tables_for(probe_schema: Schema, probe_key, probe_dicts,
                    build_key, build_dicts):
    """Per-key-position STRING rank tables: the probe dictionary's rank
    space, with build codes remapped into it (absent build values rank past
    the probe's range so they compare unequal to everything). One shared
    helper so the flow (MergeJoinOp) and SPMD (_lower_mergejoin) paths can
    never diverge. Returns (probe_ranks, build_ranks) tuples aligned with
    the normalized key positions (None for non-STRING keys)."""
    from ..coldata.types import Family

    pkeys = _norm_keys(probe_key)
    bkeys = _norm_keys(build_key)
    probe_ranks: list = []
    build_ranks: list = []
    for pk, bk in zip(pkeys, bkeys):
        if probe_schema.types[pk].family is not Family.STRING:
            probe_ranks.append(None)
            build_ranks.append(None)
            continue
        pd = probe_dicts[pk]
        bd = build_dicts[bk]
        probe_ranks.append(pd.ranks)
        ranks = []
        for i, v in enumerate(bd.values):
            code = pd.code_of(str(v))
            ranks.append(pd.ranks[code] if code >= 0
                         else len(pd.values) + i)
        # crlint: allow-host-sync(ranks is a host python list, not a device array)
        build_ranks.append(np.array(ranks, dtype=np.int32))  # crlint: allow-mem-accounting(dictionary-sized rank table, metadata not query data)
    return tuple(probe_ranks), tuple(build_ranks)


def _norm_ranks(rank_tables, nkeys: int) -> tuple:
    """Accept the legacy single-table form (one table for a single key) or
    a tuple/dict keyed by key position."""
    if rank_tables is None:
        return (None,) * nkeys
    if isinstance(rank_tables, dict):
        return tuple(rank_tables.get(i) for i in range(nkeys))
    if isinstance(rank_tables, (list, tuple)):
        assert len(rank_tables) == nkeys
        return tuple(rank_tables)
    assert nkeys == 1
    return (rank_tables,)


def _u64_keys(batch: Batch, keys: tuple[int, ...], schema: Schema,
              rank_tables) -> tuple[tuple[jax.Array, ...], jax.Array]:
    """(per-column order lanes, combined active). A row is active only when
    EVERY key column is non-NULL (SQL: one NULL key kills the match)."""
    ranks = _norm_ranks(rank_tables, len(keys))
    lanes = []
    active = batch.mask
    for k, rt in zip(keys, ranks):
        lane, a = _u64_key(batch, k, schema, rt)
        lanes.append(lane)
        active = active & a
    return tuple(lanes), active


def lex_bsearch(sorted_lanes: tuple[jax.Array, ...],
                query_lanes: tuple[jax.Array, ...],
                side: str = "left") -> jax.Array:
    """Branchless unrolled binary search over LEXICOGRAPHIC tuples.
    Same step structure as join.bsearch (log2(n) static gather+select
    rounds), with the scalar compare replaced by a composed tuple compare
    — ncols gathers per step instead of one."""
    n = sorted_lanes[0].shape[0]
    bits = max(1, int(n).bit_length())
    pos = jnp.zeros(query_lanes[0].shape, jnp.int32)
    for sb in range(bits - 1, -1, -1):
        cand = pos + (1 << sb)
        at = jnp.clip(cand - 1, 0, n - 1)
        lt = jnp.zeros(pos.shape, jnp.bool_)
        eq = jnp.ones(pos.shape, jnp.bool_)
        for sl, ql in zip(sorted_lanes, query_lanes):
            v = sl[at]
            lt = lt | (eq & (v < ql))
            eq = eq & (v == ql)
        ok = lt if side == "left" else (lt | eq)
        pos = jnp.where((cand <= n) & ok, cand, pos)
    return pos


def merge_join(  # crlint: allow-mem-accounting(traced kernel: buffers are XLA transients sized by out_capacity, which the dispatching operator reserves)
    probe: Batch,
    probe_schema: Schema,
    probe_key,
    build: Batch,
    build_schema: Schema,
    build_key,
    spec: JoinSpec,
    out_capacity: int,
    probe_rank_table=None,
    build_rank_table=None,
    build_index=None,
):
    """Returns (out_batch, total_rows); retry with a bigger tile if
    total_rows > out_capacity (same capacity-bucketing contract as
    hash_join_general). `build_index` caches the build-side sorted keys.
    probe_key/build_key: one column index or a tuple of them (composite
    ordered keys, compared lexicographically)."""
    pkeys = _norm_keys(probe_key)
    bkeys = _norm_keys(build_key)
    cap = probe.capacity
    bcap = build.capacity
    if build_index is None:
        build_index = build_merge_index(
            build, build_schema, bkeys, build_rank_table
        )
    sks, order, prefix = build_index
    pks, p_active = _u64_keys(probe, pkeys, probe_schema, probe_rank_table)

    lo = lex_bsearch(sks, pks, side="left")
    hi = lex_bsearch(sks, pks, side="right")
    # count only ACTIVE build rows in the run (dead/NULL rows share the key
    # lanes of inactive rows and sort to the run's tail)
    cnt = jnp.where(p_active, prefix[hi] - prefix[lo], 0)
    max_run = jnp.max(cnt)

    if spec.join_type == "semi":
        return probe.with_mask(probe.mask & (cnt > 0)), jnp.sum(cnt > 0)
    if spec.join_type == "anti":
        return probe.with_mask(probe.mask & (cnt == 0)), jnp.sum(cnt == 0)

    left = spec.join_type == "left"
    out_rows = jnp.where(left & probe.mask, jnp.maximum(cnt, 1), cnt)
    base = jnp.cumsum(out_rows) - out_rows
    total = jnp.sum(out_rows)

    OC = out_capacity
    out_pidx = jnp.zeros((OC,), jnp.int32)
    out_bidx = jnp.zeros((OC,), jnp.int32)
    out_found = jnp.zeros((OC,), jnp.bool_)
    out_live = jnp.zeros((OC,), jnp.bool_)
    if left:
        unmatched = probe.mask & (cnt == 0)
        dest0 = jnp.where(unmatched, base.astype(jnp.int32), OC)
        out_pidx = out_pidx.at[dest0].set(
            jnp.arange(cap, dtype=jnp.int32), mode="drop")
        out_live = out_live.at[dest0].set(True, mode="drop")

    def emit_body(state):
        k, op, ob, of, ol = state
        m = k < cnt
        posc = jnp.clip(lo + k, 0, bcap - 1)
        bidx = order[posc]
        dest = jnp.where(m, (base + k).astype(jnp.int32), OC)
        op = op.at[dest].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
        ob = ob.at[dest].set(bidx, mode="drop")
        of = of.at[dest].set(True, mode="drop")
        ol = ol.at[dest].set(True, mode="drop")
        return k + 1, op, ob, of, ol

    _, out_pidx, out_bidx, out_found, out_live = jax.lax.while_loop(
        lambda s: s[0] < max_run,
        emit_body,
        (jnp.int32(0), out_pidx, out_bidx, out_found, out_live),
    )

    pcols = tuple(
        Column(data=c.data[out_pidx], valid=c.valid[out_pidx] & out_live)
        for c in probe.cols
    )
    bcols = tuple(
        Column(data=c.data[out_bidx], valid=c.valid[out_bidx] & out_found)
        for c in build.cols
    )
    return Batch(cols=pcols + bcols, mask=out_live), total


def build_merge_index(build: Batch, schema: Schema, key, rank_table=None):  # crlint: allow-mem-accounting(traced kernel: index lanes are shaped like the build batch the operator already charged)
    """Sort build rows by exact (composite) key order -> (sorted_key_lanes,
    orig_index, active_prefix). Inactive (dead/NULL-key) rows sort AFTER
    actives within an equal-key run, and active_prefix[i] counts active rows
    before sorted position i — so a probe run [lo, hi) has its active
    matches contiguous at [lo, lo + prefix[hi] - prefix[lo])."""
    keys = _norm_keys(key)
    lanes, active = _u64_keys(build, keys, schema, rank_table)
    perm = jnp.arange(build.capacity, dtype=jnp.int32)
    out = jax.lax.sort([*lanes, ~active, perm], num_keys=len(lanes) + 1)
    sks, order = tuple(out[:len(lanes)]), out[-1]
    sorted_active = active[order]
    prefix = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(sorted_active.astype(jnp.int32)),
    ])
    return sks, order, prefix
